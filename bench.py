"""Benchmark: the five BASELINE.md target configs, device engine vs a CPU
columnar engine (pandas/pyarrow) on the same machine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} —
ALWAYS, even when the time budget expires mid-run: a watchdog thread
emits the JSON for whatever completed before the deadline and exits
(the r4 lesson: a benchmark that times out silently is worse than a slow
number; BenchUtils.scala:39-300 writes its report unconditionally).

Workloads (executed THROUGH the engine: parquet scan with pruned columns,
host->device upload, TPU kernels, collect — nothing pre-resident in HBM):
- TPC-H q1/q6 (scan+filter+agg) and q3/q5 (joins) — benchmarks/tpch.py
- TPC-DS q67-like (rollup + rank window + top-k)   — benchmarks/suites.py
- TPCxBB q5-like (conditional-sum pivot + joins)   — benchmarks/suites.py
- repartition-heavy (full hash shuffle + counts)   — benchmarks/suites.py

Per query, in budget order (cheap scans first, joins, then suites):
pandas oracle (result + wall time cached on disk keyed by the datagen
manifest + oracle source hash, so repeated runs skip the CPU rerun), one
first device run (compile + cold scan + correctness check), then
BENCH_ITERS hot runs against the device scan cache. q1/q6 additionally
get one post-compile cold run (scan cache cleared) for the scan-bandwidth
headline, comparable to earlier rounds' cold medians.

- ``value`` is the suite wall-clock (sum of per-query hot medians) over
  ``completed``; ``partial`` is true when not every selected query ran.
- ``vs_baseline`` is the speedup of this engine over the pandas/pyarrow
  implementation of the same queries at the same scale — the stand-in for
  the reference's GPU-vs-CPU-Spark headline (docs/FAQ.md:60-66 claims 3-4x
  typical; the repo publishes no absolute numbers, BASELINE.md).
- ``first_run_s`` holds the compile+cold time of the first device run;
  ``cold_s`` the post-compile cold runs (q1/q6).
- Every device result is checked against the pandas result before timing;
  a mismatch fails the benchmark (BenchUtils.compareResults analog).

Env knobs: TPCH_SF (default 1.0), TPCH_DIR, SUITES_DIR, BENCH_ITERS
(default 2), BENCH_QUERIES (comma list to subset), BENCH_BUDGET_S
(default 420 — hard deadline for the whole run including datagen).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import statistics
import sys
import threading
import time

if os.environ.get("BENCH_PLATFORM") == "cpu":
    # Hermetic CPU run (validation/dev): drop the remote-TPU plugin the
    # environment pins before any backend materializes (conftest recipe).
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

# v5e HBM bandwidth ~819 GB/s (public spec); used only for the
# utilization ratio, overridable for other chips.
HBM_GB_PER_SEC = float(os.environ.get("BENCH_HBM_GBPS", "819"))

_START = time.perf_counter()
# _LOCK guards every read AND write of _STATE["out"] and its nested dicts:
# the watchdog json.dumps()es the same objects the main thread mutates.
_LOCK = threading.Lock()
_STATE = {"out": None, "done": False, "ok": {}}


def _emit(out):
    sys.stdout.write(json.dumps(out) + "\n")
    sys.stdout.flush()


def _watchdog(budget_s: float):
    """Print the partial report and hard-exit at the deadline. A thread
    (not SIGALRM): a signal handler can't preempt a blocked device
    round-trip, os._exit from a thread can. Exit code still reflects any
    correctness failure seen before the deadline."""
    deadline = _START + budget_s
    while True:
        now = time.perf_counter()
        if _STATE["done"]:
            return
        if now >= deadline:
            with _LOCK:
                if _STATE["done"]:      # main finished while we waited
                    return
                out = _STATE["out"] or {
                    "metric": "tpc_suite_wall_clock", "value": None,
                    "unit": "s", "vs_baseline": None, "completed": []}
                out["timed_out"] = True
                out["partial"] = True
                out["budget_s"] = budget_s
                _emit(out)
                ok = _STATE["ok"]
                code = 0 if ok and all(ok.values()) else 1
                # Exit while still holding the lock: main's own emit needs
                # it, so exactly one JSON line ever reaches stdout.
                os._exit(code)
        time.sleep(min(1.0, deadline - now))


def _remaining(budget_s):
    return budget_s - (time.perf_counter() - _START)


# The benchmark queries standing in for BASELINE.md's five target
# configurations (the headline shapes vs_baseline covers).
_TARGETS = {"q1", "q6", "q3", "q5", "q67", "xbb_q5", "repart"}


# The 11-query forced-host sweep (tests/test_host_engine.py runs the
# same set as a parity suite): numpy host-engine wall vs the pandas
# oracle on the same data.
_HOST_SWEEP = ("q1", "q6", "q3", "q5", "q12", "q14", "q22",
               "q67", "xbb_q5", "ds_q89", "ds_q98")


def _host_engine_probe(packs, pandas_s, budget):
    """Forced-host run per sweep query. ``vs_pandas`` > 1 means the
    vectorized numpy engine beat the pandas implementation of the same
    query; the perf gate asserts no query falls below 0.5 (2x slower
    than pandas)."""
    res = {}
    for qn in _HOST_SWEEP:
        if qn not in packs or qn not in pandas_s:
            continue
        if _remaining(budget) < 30:
            break
        mod, ddir = packs[qn]
        try:
            df = mod.QUERIES[qn](_session(), ddir)
            t0 = time.perf_counter()
            df.collect_host()
            hs = time.perf_counter() - t0
            entry = {"host_s": round(hs, 4), "pandas_s": pandas_s[qn]}
            if hs > 0:
                entry["vs_pandas"] = round(pandas_s[qn] / hs, 3)
            res[qn] = entry
        except Exception as e:  # the headline must survive a probe bug
            res[qn] = {"error": f"{type(e).__name__}: {e}"}
    return res


def _session(scan_cache: bool = True):
    from spark_rapids_tpu.api.dataframe import TpuSession
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    # TPC data is finite; the reference's benchmark setups make the same
    # assertion (spark.rapids.sql.hasNans=false) to unlock float fast paths.
    s.set("spark.rapids.sql.hasNans", False)
    # Persistent kernel cache: compiled XLA executables survive across
    # bench invocations, so a re-run's first collect deserializes (~ms)
    # instead of recompiling (~s) — the q67-lands-in-budget warmup
    # (VERDICT r5 weak #1). BENCH_KERNEL_CACHE_DIR= disables.
    kc_dir = os.environ.get("BENCH_KERNEL_CACHE_DIR",
                            "/tmp/srt_bench_kernel_cache")
    if kc_dir:
        s.set("spark.rapids.sql.kernelCache.persistentDir", kc_dir)
    if not scan_cache:
        s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    return s


def _oracle_cached(mod, qn, ddir, manifest):
    """Pandas oracle result + wall time, cached on disk. The key folds in
    the benchmark module's source hash so editing an oracle invalidates
    its cache. Cache hit skips the CPU rerun entirely (the budget saver);
    miss runs pandas once and stores both result and time."""
    src = hashlib.sha256()
    src.update(open(mod.__file__, "rb").read())
    key = f"{qn}:{manifest}:{src.hexdigest()[:16]}"
    # The cache lives inside the datagen dir: anyone who can write there
    # can already poison the parquet inputs (and thus the oracle result),
    # so the pickle adds no trust boundary beyond the data itself. Timing
    # is a single cached sample by design — the driver budget can't afford
    # fresh pandas medians every run (VERDICT r4 item 1).
    path = os.path.join(ddir, f"_oracle_{qn}.pkl")
    try:
        with open(path, "rb") as f:
            cached = pickle.load(f)
        if cached.get("key") == key:
            return cached["want"], cached["secs"]
    except Exception:       # stale pickle: unpickling can raise anything
        pass
    t0 = time.perf_counter()
    want = mod.pandas_query(qn, ddir)
    secs = time.perf_counter() - t0
    try:
        with open(path, "wb") as f:
            pickle.dump({"key": key, "want": want, "secs": secs}, f)
    except (OSError, pickle.PickleError):
        pass
    return want, secs


def _scan_probe(tpch_dir: str) -> dict:
    """Scan-bandwidth microbench measured from the INGEST FAST PATH:
    post-compile cold q1+q6 runs (scan cache cleared, pipeline +
    codec v2 + coalesced uploads all active) with the wire-counter
    deltas for exactly those runs. The gb_per_sec here is the
    scan_gb_per_sec headline (bytes = uncompressed pruned columns the
    queries read, the same denominator prior rounds used)."""
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.columnar import wire
    from spark_rapids_tpu.io.scan import DEVICE_SCAN_CACHE

    s = _session()
    dfs = [tpch.QUERIES[qn](s, tpch_dir) for qn in ("q1", "q6")]
    for df in dfs:
        df.collect()                # warm: compile + plan cache
    DEVICE_SCAN_CACHE.clear()
    w0 = wire.counters()
    t0 = time.perf_counter()
    for df in dfs:
        df.collect()
    secs = time.perf_counter() - t0
    w1 = wire.counters()
    nbytes = tpch.bytes_scanned("q1", tpch_dir) + \
        tpch.bytes_scanned("q6", tpch_dir)
    wd = {k: round(w1.get(k, 0) - w0.get(k, 0), 4)
          for k in ("rawBytes", "encodedBytes", "stagingBytes",
                    "uploadTransfers", "uploadedBatches",
                    "groupedUploads")}
    if wd.get("rawBytes", 0) > 0:
        wd["wireCompressionRatio"] = round(
            wd["rawBytes"] / max(wd["encodedBytes"], 1), 4)
    if wd.get("uploadedBatches", 0) > 0:
        wd["stagingHitRate"] = round(
            1.0 - wd["uploadTransfers"] / wd["uploadedBatches"], 4)
    return {
        "queries": ["q1", "q6"],
        "seconds": round(secs, 4),
        "bytes": nbytes,
        "gb_per_sec": round(nbytes / secs / 1e9, 3) if secs > 0 else None,
        "wire": wd,
    }


def _trace_probe(tpch_dir: str, trace_path: str) -> dict:
    """One traced q3 run through the flight recorder: where the
    wall-clock went by span category, plus the Chrome trace JSON written
    as the benchmark's artifact (tier1.yml uploads it)."""
    from spark_rapids_tpu import monitoring
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.io.scan import DEVICE_SCAN_CACHE

    s = _session()
    s.set("spark.rapids.sql.trace.enabled", True)
    df = tpch.QUERIES["q3"](s, tpch_dir)
    monitoring.reset()
    DEVICE_SCAN_CACHE.clear()   # the upload funnel must actually run
    t0 = time.perf_counter()
    df.collect()
    secs = time.perf_counter() - t0
    df.trace_export(trace_path)
    snap = monitoring.snapshot()
    breakdown = {cat: agg["ms"]
                 for cat, agg in snap["categories"].items()}
    monitoring.configure(False)
    monitoring.reset()
    return {
        "query": "q3",
        "seconds": round(secs, 4),
        "category_ms": breakdown,
        "instants": snap["instants"],
        "dropped_events": snap["droppedEvents"],
        "artifact": trace_path,
    }


def _sustained_probe(tpch_dir: str, total: int, clients: int) -> dict:
    """Sustained serving load (ROADMAP item 2): ``total`` parameterized
    queries — mixed q6-class/aggregate/limit shapes with NEW literals
    every call — submitted from ``clients`` worker threads through the
    admission scheduler at maxConcurrentQueries=4. Every call would
    re-plan AND re-trace without the plan cache (literal values key the
    kernel fingerprints); with it, steady state is bind-only dispatch.
    Reports p50/p99 latency, queries/sec, the plan-cache hit rate, the
    mean plan+bind wall, and the q6-class bind-only speedup vs a
    planCache.enabled=false control (the ISSUE 10 acceptance ratio)."""
    import statistics as _st

    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.plan import plan_cache as _pc
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col

    def sess(cache=True):
        s = _session()
        s.set("spark.rapids.sql.scheduler.maxConcurrentQueries", 4)
        s.set("spark.rapids.sql.planCache.enabled", bool(cache))
        # The sustained block doubles as the live-telemetry acceptance
        # probe: metrics on, and the block's own JSON is reconciled
        # against an HTTP scrape taken right after the load drains.
        s.set("spark.rapids.sql.metrics.enabled", True)
        return s

    day0 = tpch.days("1994-01-01")

    def shape_q6(s, i):
        li = tpch._read(s, tpch_dir, "lineitem")
        lo = day0 + (i % 330)
        f = li.filter(
            (col("l_shipdate") >= lit_col(lo))
            & (col("l_shipdate") < lit_col(lo + 30))
            & (col("l_discount") >= 0.05) & (col("l_quantity") < 24.0))
        return f.agg(agg_sum(col("l_extendedprice") * col("l_discount"))
                     .alias("rev"))

    def shape_sum(s, i):
        li = tpch._read(s, tpch_dir, "lineitem")
        return li.filter(col("l_quantity") < float(5 + i % 40)) \
            .agg(agg_sum(col("l_extendedprice")).alias("s"))

    def shape_limit(s, i):
        li = tpch._read(s, tpch_dir, "lineitem")
        return li.select("l_orderkey", "l_extendedprice") \
            .limit(10 + i % 50)

    shapes = [shape_q6, shape_sum, shape_limit]
    s = sess()
    t0 = time.perf_counter()
    for i, sh in enumerate(shapes):         # cold: template + compile
        sh(s, i).collect()
    warmup_s = time.perf_counter() - t0

    from spark_rapids_tpu.monitoring import telemetry as _tm

    def _queries_total(text: str) -> float:
        return sum(float(ln.rsplit(" ", 1)[1])
                   for ln in text.splitlines()
                   if ln.startswith("srt_queries_total"))

    tm_base = _queries_total(_tm.render_text()) if _tm.enabled() else None
    c0 = _pc.counters()
    from spark_rapids_tpu.parallel import qos as _qos
    from spark_rapids_tpu.parallel import scheduler as _sc
    q0c = _qos.counters()
    s0c = _sc.counters()
    lock = threading.Lock()
    lat: list = []
    idx = {"i": 0}
    errors = [0]

    def client(k):
        # Each client is a distinct serving tenant: the per-tenant
        # plan-cache counters (parallel/qos/) attribute every hit/miss
        # even with the QoS scheduler off. Obedient-client contract
        # (ISSUE 18): rejections with a retry_after_ms hint back off
        # and resubmit through collect_with_retry (deterministic
        # per-client jitter, seed=k) instead of counting as errors.
        tenant = f"client{k}"
        while True:
            with lock:
                i = idx["i"]
                if i >= total:
                    return
                idx["i"] = i + 1
            q0 = time.perf_counter()
            try:
                shapes[i % len(shapes)](s, i).collect_with_retry(
                    tenant=tenant, seed=k)
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            took = time.perf_counter() - q0
            with lock:
                lat.append(took)

    t0 = time.perf_counter()
    workers = [threading.Thread(target=client, args=(k,), daemon=True,
                                name=f"srt-sustained-{k}")
               for k in range(clients)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    wall = time.perf_counter() - t0
    # Scrape reconciliation: a REAL OpenMetrics HTTP scrape, taken the
    # instant the load drains, must agree (±1 for an in-flight
    # straggler) with this block's own completion count — the proof the
    # exposition path reports the same world the bench JSON does.
    telemetry_js = None
    if _tm.enabled() and tm_base is not None:
        try:
            import urllib.request
            from spark_rapids_tpu.monitoring import exporter as _exp
            port = _exp.ensure_started(0)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                scraped = r.read().decode()
            delta = _queries_total(scraped) - tm_base
            expect = len(lat) + errors[0]
            telemetry_js = {
                "scrape_port": port,
                "scraped_queries_total_delta": delta,
                "bench_completions": expect,
                "reconciles": abs(delta - expect) <= 1,
            }
        except Exception as e:
            telemetry_js = {"error": f"{type(e).__name__}: {e}"}
    c1 = _pc.counters()
    q1c = _qos.counters()
    hits = c1.get("planCacheHits", 0) - c0.get("planCacheHits", 0)
    misses = c1.get("planCacheMisses", 0) - c0.get("planCacheMisses", 0)
    bind_ns = c1.get("planBindNs", 0) - c0.get("planBindNs", 0)
    lat.sort()

    def pct(q):
        return round(lat[min(int(q * len(lat)), len(lat) - 1)] * 1000, 2) \
            if lat else None

    # q6-class cold-vs-warm acceptance ratio: fresh literals every call,
    # plan cache on vs off (off re-plans AND re-traces per call).
    def serial(cache, n, off):
        ss = sess(cache)
        shape_q6(ss, off - 1).collect()     # conf-specific warm
        t = time.perf_counter()
        for i in range(n):
            shape_q6(ss, off + i).collect()
        return (time.perf_counter() - t) / n
    on_s = serial(True, 6, 500)
    off_s = serial(False, 6, 600)
    s1c = _sc.counters()
    return {
        "queries": total, "clients": clients, "errors": errors[0],
        "client_retries": int(s1c.get("clientRetries", 0)
                              - s0c.get("clientRetries", 0)),
        "max_concurrent": 4,
        "warmup_s": round(warmup_s, 3),
        "wall_s": round(wall, 3),
        "qps": round(len(lat) / wall, 2) if wall > 0 else None,
        "p50_ms": pct(0.50), "p99_ms": pct(0.99),
        "mean_ms": round(_st.mean(lat) * 1000, 2) if lat else None,
        "plan_cache_hits": hits, "plan_cache_misses": misses,
        "plan_cache_hit_rate": round(hits / max(hits + misses, 1), 4),
        "plan_bind_ms_mean": round(
            bind_ns / 1e6 / max(hits + misses, 1), 3),
        "q6_bind_only_s": round(on_s, 4),
        "q6_replan_retrace_s": round(off_s, 4),
        "q6_speedup_vs_plan_cache_off": round(off_s / on_s, 2)
        if on_s > 0 else None,
        "telemetry": telemetry_js,
        "tenants": {
            f"client{k}": {
                "plan_cache_hits": int(
                    q1c.get(f"planCacheHit.client{k}", 0)
                    - q0c.get(f"planCacheHit.client{k}", 0)),
                "plan_cache_misses": int(
                    q1c.get(f"planCacheMiss.client{k}", 0)
                    - q0c.get(f"planCacheMiss.client{k}", 0)),
            } for k in range(clients)
        },
    }


def _qos_probe(tpch_dir: str, total: int) -> dict:
    """Serving QoS block (ISSUE 14; parallel/qos/): mixed-class
    parameterized load through the WFQ scheduler at a deliberately
    tight maxConcurrentQueries=2 with a lopsided weight vector and a
    small starvation bound, plus a 2-client tenant capped at ONE
    in-flight query. Reports per-class p50/p99 latency, rejections by
    kind (the capped tenant produces real tenant-quota rejections),
    starvation-bound engagements, and kernel-quota evictions."""
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.parallel import qos as _qos
    from spark_rapids_tpu.parallel import scheduler as _sched
    from spark_rapids_tpu.plan.logical import agg_sum, col

    weights = "8,3,1"

    def sess():
        s = _session()
        s.set("spark.rapids.sql.scheduler.maxConcurrentQueries", 2)
        s.set("spark.rapids.sql.scheduler.qos.enabled", True)
        s.set("spark.rapids.sql.scheduler.qos.weights", weights)
        s.set("spark.rapids.sql.scheduler.qos.starvationBound", 2)
        return s

    def shape(s, i):
        li = tpch._read(s, tpch_dir, "lineitem")
        return li.filter(col("l_quantity") < float(5 + i % 8)) \
            .agg(agg_sum(col("l_extendedprice")).alias("s"))

    s = sess()
    shape(s, 0).collect()                   # warm: template + kernels
    c0 = _qos.counters()
    lock = threading.Lock()
    lat = {cls: [] for cls in _qos.CLASSES}
    rejected = [0]
    errors = [0]
    classes = [("interactive", None), ("batch", None),
               ("background", None), ("batch", "capped"),
               ("batch", "capped")]
    per_client = max(total // len(classes), 1)
    capped = sess()
    capped.set("spark.rapids.sql.scheduler.qos.tenantMaxInFlight", 1)

    def client(k, cls, tenant):
        cs = capped if tenant else s
        for j in range(per_client):
            i = k * per_client + j
            q0 = time.perf_counter()
            try:
                shape(cs, i).collect(priority=cls,
                                     tenant=tenant or f"t{k}")
            except _sched.QueryRejectedError:
                with lock:
                    rejected[0] += 1
                continue
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            took = time.perf_counter() - q0
            with lock:
                lat[cls].append(took)

    t0 = time.perf_counter()
    workers = [threading.Thread(target=client, args=(k, cls, tenant),
                                daemon=True, name=f"srt-qos-{k}")
               for k, (cls, tenant) in enumerate(classes)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    wall = time.perf_counter() - t0
    c1 = _qos.counters()

    def diff(name):
        return int(c1.get(name, 0) - c0.get(name, 0))

    def pct(xs, q):
        if not xs:
            return None
        xs = sorted(xs)
        return round(xs[min(int(q * len(xs)), len(xs) - 1)] * 1000, 2)

    return {
        "queries": per_client * len(classes), "clients": len(classes),
        "max_concurrent": 2, "weights": weights,
        "starvation_bound": 2,
        "wall_s": round(wall, 3), "errors": errors[0],
        "per_class": {
            cls: {"count": len(lat[cls]), "p50_ms": pct(lat[cls], 0.50),
                  "p99_ms": pct(lat[cls], 0.99)}
            for cls in _qos.CLASSES
        },
        "rejections": {
            kind: diff(f"rejected.{kind}")
            for kind in ("queue-full", "admission-timeout",
                         "tenant-quota", "deadline-unmeetable")
        },
        "rejected_total": rejected[0],
        "starvation_bound_engagements": diff(
            "starvationBoundEngagements"),
        "quota_evictions": diff("quotaEvictions"),
    }


def _concurrency_probe(tpch_dir: str, n: int) -> dict:
    """N-query throughput: N fresh sessions run hot q6 serially, then
    the same N concurrently through the scheduler (each on its own
    thread). Kernels are already compiled (the main loop ran q6), so
    this measures admission + isolation overhead and device sharing,
    not compilation."""
    from spark_rapids_tpu.benchmarks import tpch

    dfs = [tpch.QUERIES["q6"](_session(), tpch_dir) for _ in range(n)]
    for df in dfs:
        df.collect()            # warm: plan cache + device scan cache
    t0 = time.perf_counter()
    for df in dfs:
        df.collect()
    serial_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    handles = [df.submit() for df in dfs]
    errors = 0
    for h in handles:
        try:
            h.result(300)
        except Exception:
            errors += 1
    concurrent_s = time.perf_counter() - t0
    return {
        "query": "q6", "queries": n, "errors": errors,
        "serial_s": round(serial_s, 4),
        "concurrent_s": round(concurrent_s, 4),
        "speedup": round(serial_s / concurrent_s, 3)
        if concurrent_s > 0 else None,
    }


def _distributed_probe(tpch_dir: str) -> dict:
    """Distributed worker runtime (parallel/cluster/): shuffle-forced q3
    dispatched through the stage-task coordinator at 1 vs 2 vs 3 worker
    processes, checked bit-identical against the same-conf local run.

    Interpreting the numbers requires ``host_cpus``: extra co-located
    worker processes can only overlap stage compute when there are
    spare cores to run them on. With host_cpus >= workers the leaf
    scans overlap and speedup_3v1 should exceed 1; on a single-core
    host the block instead measures the *overhead* of distribution
    (cross-process shard hops, poll gaps, steal-delay waits), and
    multi-worker parity with workers_1 is the best possible result.
    The multi-host speedup story is measured on real TPU pods, not
    here (ROADMAP item 2).

    Each configuration warms to steady state first: the cold run's
    multi-second kernel traces outlive the steal-delay reservation, so
    placement only settles once every worker has compiled its stages —
    up to one compile wave per worker, hence n+1 warm runs."""
    import subprocess
    import tempfile

    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.parallel import cluster as CL
    from spark_rapids_tpu.parallel import transport as _tp

    jdir = tempfile.mkdtemp(prefix="srt_bench_cluster_")

    def q3_session(n=None):
        s = _session()
        s.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
        if n is not None:
            s.set("spark.rapids.sql.cluster.enabled", True)
            s.set("spark.rapids.sql.cluster.minWorkers", n)
            # Journal the 3-worker round so the replay path below
            # measures a real WAL, not an empty file.
            if n == 3:
                s.set("spark.rapids.sql.cluster.dir", jdir)
                s.set("spark.rapids.sql.cluster.journal.enabled", True)
        return s

    want = tpch.QUERIES["q3"](q3_session(), tpch_dir).collect()
    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.pop("SRT_FAULTS", None)
    bc0 = dict(_tp.counters())
    res: dict = {"query": "q3", "shuffle_forced": True,
                 "host_cpus": os.cpu_count(),
                 # Which data plane stage outputs publish through
                 # (ISSUE 17: hostfile spool vs object store).
                 "store_kind": CL.cluster_store_kind(
                     q3_session(1).conf)}
    for n in (1, 2, 3):
        sc = q3_session(n)
        co = CL.get_coordinator(sc.conf)
        addr = f"{co.addr[0]}:{co.addr[1]}"
        procs = [subprocess.Popen(
            [sys.executable, "-m",
             "spark_rapids_tpu.parallel.cluster.worker",
             "--coordinator", addr, "--worker-id", f"b{n}w{i}"],
            env=env, cwd=root) for i in range(n)]
        try:
            df = tpch.QUERIES["q3"](sc, tpch_dir)
            for _ in range(n + 1):
                df.collect()
            secs, got = None, None
            for _ in range(2):
                t0 = time.perf_counter()
                got = df.collect()
                dt = time.perf_counter() - t0
                secs = dt if secs is None else min(secs, dt)
            res[f"workers_{n}"] = {"seconds": round(secs, 4),
                                   "correct": got == want}
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=15)
                except Exception:
                    p.kill()
            CL.shutdown_coordinator()
    w1 = res.get("workers_1", {}).get("seconds")
    w3 = res.get("workers_3", {}).get("seconds")
    if w1 and w3:
        res["speedup_3v1"] = round(w1 / w3, 3)
        # The scaling expectation is conditional on cores: three
        # co-located workers can only overlap stage compute with >= 3
        # host CPUs. There, speedup_3v1 > 1 is asserted (speedup_ok);
        # on smaller hosts (1-core CI) the honest reading is
        # distribution OVERHEAD, so speedup_ok stays null and parity
        # with workers_1 is the best possible result.
        cpus = res.get("host_cpus") or 1
        res["speedup_ok"] = \
            bool(res["speedup_3v1"] > 1.0) if cpus >= 3 else None
    # Coordinator failover cost: replay the 3-worker round's journal
    # into a fresh coordinator, exactly what a SIGKILL + restart pays
    # before it starts listening (parallel/cluster/journal.py).
    try:
        from spark_rapids_tpu import config as _C
        co2 = CL.ClusterCoordinator(_C.TpuConf({
            "spark.rapids.sql.cluster.dir": jdir,
            "spark.rapids.sql.cluster.journal.enabled": True}))
        res["journal_replay_ms"] = round(co2.journal_replay_ms, 3)
        co2.close()
    except Exception as e:      # pragma: no cover - probe must not die
        res["journal_replay_error"] = f"{type(e).__name__}: {e}"
    # Broadcast artifact cache traffic across the probe (zero under the
    # shuffle-forced q3 — broadcast-join queries populate it).
    bc1 = _tp.counters()
    res["broadcast_cache"] = {
        k: bc1.get(k, 0) - bc0.get(k, 0)
        for k in ("broadcastCacheHits", "broadcastCacheMisses",
                  "broadcastCachePublishes")}
    return res


def _autoscale_probe(tpch_dir: str) -> dict:
    """Self-healing fleet (ISSUE 20): shuffle-forced q3 bursts against
    a supervised, SLO-autoscaled pool. Records the worker-count
    timeline (sampled while the burst runs and through the idle
    scale-down window), one healed SIGKILL, and the supervisor /
    autoscaler action counters — the bench-side mirror of the
    tests/test_autoscale.py soak."""
    import subprocess  # noqa: F401  (worker spawns via the supervisor)

    from spark_rapids_tpu import config as _C
    from spark_rapids_tpu import faults as _faults
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.parallel import cluster as CL
    from spark_rapids_tpu.parallel.cluster.autoscaler import Autoscaler
    from spark_rapids_tpu.parallel.cluster.supervisor import (
        RUNNING, Supervisor)

    sc = _session()
    sc.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
    sc.set("spark.rapids.sql.cluster.enabled", True)
    sc.set("spark.rapids.sql.cluster.heartbeatTimeoutMs", 1500)
    co = CL.get_coordinator(sc.conf)
    addr = f"{co.addr[0]}:{co.addr[1]}"
    aconf = _C.TpuConf({
        "spark.rapids.sql.cluster.autoscale.minWorkers": 1,
        "spark.rapids.sql.cluster.autoscale.maxWorkers": 2,
        "spark.rapids.sql.cluster.autoscale.targetQueuedMs": 50,
        "spark.rapids.sql.cluster.autoscale.scaleDownIdleS": 2,
        "spark.rapids.sql.cluster.autoscale.cooldownMs": 500,
        "spark.rapids.sql.cluster.supervisor.pollMs": 100,
        "spark.rapids.sql.cluster.supervisor.restartBackoffBaseMs":
            100})
    sup = Supervisor(addr, conf=aconf, prefix="bs", heartbeat_ms=500)
    scaler = Autoscaler(sup, conf=aconf)
    sup.add_worker()
    c0 = dict(_faults.counters())
    timeline: list = []
    stop_sampler = threading.Event()

    def sample():
        t0 = time.perf_counter()
        while not stop_sampler.wait(0.2):
            timeline.append({"t_s": round(time.perf_counter() - t0, 1),
                             "workers": sup.active_count()})

    df = tpch.QUERIES["q3"](sc, tpch_dir)
    res: dict = {}
    sup.start()
    scaler.start()
    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()
    try:
        df.collect()                      # warm the first worker's JIT
        killed = False
        errors = 0

        def burst(n):
            nonlocal errors
            for _ in range(n):
                try:
                    df.collect()
                except Exception:
                    errors += 1

        threads = [threading.Thread(target=burst, args=(3,))
                   for _ in range(2)]
        for t in threads:
            t.start()
        # One SIGKILL mid-burst: the supervisor heals it.
        time.sleep(0.5)
        with sup._lock:
            running = [w for w in sup.workers.values()
                       if w.state == RUNNING and w.proc.poll() is None]
        if running:
            running[0].proc.kill()
            killed = True
        for t in threads:
            t.join(120)
        # Quiet window: the idle clock drains the pool back down.
        deadline = time.monotonic() + 15
        while sup.active_count() > scaler.min_workers and \
                time.monotonic() < deadline:
            time.sleep(0.25)
        c1 = _faults.counters()
        res = {
            "errors": errors,
            "sigkill_injected": killed,
            "worker_timeline": timeline[-60:],
            "peak_workers": max((p["workers"] for p in timeline),
                                default=1),
            "final_workers": sup.active_count(),
            "worker_deaths": c1.get("clusterWorkerDeaths", 0)
            - c0.get("clusterWorkerDeaths", 0),
            "stage_recomputes": c1.get("stageRecomputes", 0)
            - c0.get("stageRecomputes", 0),
            "restarts": sup.counters["restarts"],
            "quarantines": sup.counters["quarantines"],
            "drains": sup.counters["drains"],
            "retirements": sup.counters["retirements"],
            "scale_decisions": dict(scaler.decisions),
        }
    finally:
        stop_sampler.set()
        scaler.stop()
        sup.close()
        CL.shutdown_coordinator()
    return res


def main():
    budget = float(os.environ.get("BENCH_BUDGET_S", "420"))
    threading.Thread(target=_watchdog, args=(budget,), daemon=True).start()

    from spark_rapids_tpu import faults as _faults
    from spark_rapids_tpu.benchmarks import suites, tpch
    from spark_rapids_tpu.io.scan import DEVICE_SCAN_CACHE
    from spark_rapids_tpu.ops import kernel_cache as _kc
    from spark_rapids_tpu.parallel import pipeline as _pl

    sf = float(os.environ.get("TPCH_SF", "1.0"))
    iters = int(os.environ.get("BENCH_ITERS", "2"))
    tpch_dir = os.environ.get("TPCH_DIR", f"/tmp/srt_tpch_sf{sf:g}")
    suites_dir = os.environ.get("SUITES_DIR", f"/tmp/srt_suites_sf{sf:g}")
    t0 = time.perf_counter()
    rows = tpch.generate(tpch_dir, scale=sf)
    rows.update(suites.generate(suites_dir, scale=sf))
    gen_s = time.perf_counter() - t0
    manifest = f"sf{sf:g}:" + ",".join(
        f"{k}={v}" for k, v in sorted(rows.items()))

    # Budget order: ALL the BASELINE.md target configs first — q67
    # included — so the 420s budget can only truncate the NON-target
    # tail; a partial JSON always contains every target the budget
    # could possibly fit (the r5 lesson: a headline that ships without
    # a q67 number is a hole, not a speedup). q67 runs THIRD, right
    # after the cheap q1/q6 scans: r5 ran it last among the targets and
    # the budget cut it (timed_out with q67 absent — VERDICT weak #1);
    # its rollup+window compile cost is also the biggest winner of the
    # persistent kernel cache the session now warms. The remaining
    # TPC-H/TPC-DS coverage queries run cheapest-first.
    packs = {
        "q1": (tpch, tpch_dir), "q6": (tpch, tpch_dir),
        "q67": (suites, suites_dir),
        "q3": (tpch, tpch_dir), "q5": (tpch, tpch_dir),
        "xbb_q5": (suites, suites_dir), "repart": (suites, suites_dir),
    }
    for qn in ("q14", "q19", "q12", "q22", "q11", "q15", "q16", "q2",
               "q4", "q17", "q20", "q10", "q13", "q7", "q8", "q9",
               "q18", "q21"):
        packs[qn] = (tpch, tpch_dir)
    for qn in ("ds_q3", "ds_q42", "ds_q89", "ds_q55", "ds_q98",
               "xbb_q12"):
        packs[qn] = (suites, suites_dir)
    sel = os.environ.get("BENCH_QUERIES", ",".join(packs)).split(",")
    qnames = [q for q in packs if q in sel]

    device_s = {}       # hot / steady-state (post-warmup medians)
    first_s = {}        # first device run: compile + cold scan + check
    cold_s = {}         # post-compile cold runs (q1/q6 scan headline)
    compile_s = {}      # first-minus-steady: the compile-ish overhead
    cache_q = {}        # per-query kernel-cache hit/miss deltas
    pandas_s = {}
    ok = _STATE["ok"]
    out = {
        "metric": f"tpc_sf{sf:g}_suite{len(qnames)}_wall_clock",
        "value": None, "unit": "s", "vs_baseline": None,
        "baseline": "pandas/pyarrow CPU engine, same queries+data+machine",
        "correct": ok, "device_s": device_s, "first_run_s": first_s,
        "cold_s": cold_s, "compile_s": compile_s, "pandas_s": pandas_s,
        "kernel_cache": {}, "kernel_cache_per_query": cache_q,
        "completed": [], "timed_out": False, "partial": True,
        "rows": rows, "datagen_s": round(gen_s, 2),
        # Recovery machinery counters (memory/oom.py ladder, planner
        # transient retry, host degradation, fault injection): all zero
        # on a healthy run — nonzero values say the run survived real
        # pressure (or an SRT_FAULTS chaos schedule).
        "recovery": {},
        # Pipelined-executor counters (parallel/pipeline.py): overlap of
        # host decode/encode with device dispatch. overlapRatio > 0 says
        # the overlap is actually happening; 0/absent says the pipeline
        # degenerated (or SRT_PIPELINE=0).
        "pipeline": {},
        # Multi-query scheduler (parallel/scheduler.py): admission
        # counters for the whole run plus the N-query-vs-serial
        # throughput measurement (filled after the per-query loop when
        # the budget allows).
        "scheduler": {},
        "concurrency": {},
        # Parameterized plan cache (plan/plan_cache.py): template
        # hits/misses + bind-only executions for the whole run, and the
        # sustained-load serving block (N clients x mixed parameterized
        # shapes at maxConcurrentQueries=4 — p50/p99, qps, hit rate,
        # and the q6-class bind-only-vs-replan speedup).
        "plan_cache": {},
        "sustained": {},
        # Serving QoS subsystem (parallel/qos/): per-class latency
        # under weighted fair queueing, rejections by kind, starvation
        # -bound engagements, and per-tenant quota evictions.
        "qos": {},
        # Shuffle transport SPI (parallel/transport/): which transport
        # served the run plus its byte/shard counters — nonzero
        # remoteShardRefetches/remoteShardsLost say the run recovered
        # from data-at-rest damage.
        "transport": {},
        # Cost-based placement + runtime adaptive re-planning
        # (plan/cost.py, parallel/replan.py): how many queries were
        # host-placed by the static model and how many shuffled joins
        # demoted to broadcast from observed shuffle sizes.
        "cost": {},
        # Ingest fast path (columnar/wire.py): raw vs encoded wire
        # bytes, per-codec column counts, transfer counts and the
        # staging-buffer grouping rate; `scan` is the fast-path
        # microbench that produces the scan_gb_per_sec headline.
        "wire": {},
        "scan_bench": {},
        # Vectorized host engine (numpy fallback path): per-query
        # forced-host wall vs the pandas oracle — vs_pandas > 1 means
        # the host engine wins; the perf gate holds the floor at 0.5.
        "host_engine": {},
        # Native Pallas kernel layer (ops/native.py): the enabled
        # kernel set (empty on CPU — the layer no-ops to the jax.numpy
        # fallback there), per-kernel trace counts, and the cost
        # model's self-calibrated effective constants.
        "native": {},
        # Query flight recorder (spark_rapids_tpu/monitoring/): one
        # TRACED q3 run after the timing loop — the span-category wall
        # breakdown (queued/host-prefetch/device-compute/upload/
        # shuffle/recovery) plus the Chrome trace JSON artifact path
        # (loads in Perfetto / chrome://tracing).
        "trace": {},
        # Distributed worker runtime (parallel/cluster/): shuffle-forced
        # q3 wall-clock at 1 vs 2 vs 3 subprocess workers through the
        # stage-task coordinator, plus the 3-vs-1 speedup and per-config
        # correctness against the same-conf local run.
        "distributed": {},
    }
    with _LOCK:
        _STATE["out"] = out

    for qn in qnames:
        # Skip a query we clearly can't finish: leave headroom for the
        # report instead of letting the watchdog cut mid-query.
        if _remaining(budget) < 20:
            break
        mod, ddir = packs[qn]
        want, psecs = _oracle_cached(mod, qn, ddir, manifest)
        df = mod.QUERIES[qn](_session(), ddir)
        kc0 = _kc.cache().stats()
        t0 = time.perf_counter()
        got = df.collect()          # compile + cold scan + cache populate
        fsecs = time.perf_counter() - t0
        qok = bool(mod.check_result(qn, got, want))
        with _LOCK:
            # Record the verdict BEFORE the timing runs: a deadline hit
            # during them must still surface this query's failure.
            ok[qn] = qok
        times = []
        for _ in range(iters):
            if times and _remaining(budget) < times[-1] + 10:
                break               # keep what we have; report it
            t0 = time.perf_counter()
            df.collect()
            times.append(time.perf_counter() - t0)
        csecs = None
        if qn in ("q1", "q6") and \
                _remaining(budget) > fsecs + 10:
            # Post-compile cold run: decode + upload + kernels, no
            # compile — the scan-bandwidth denominator prior rounds used.
            DEVICE_SCAN_CACHE.clear()
            t0 = time.perf_counter()
            df.collect()
            csecs = time.perf_counter() - t0
        kc1 = _kc.cache().stats()
        with _LOCK:
            pandas_s[qn] = round(psecs, 4)
            first_s[qn] = round(fsecs, 4)
            if csecs is not None:
                cold_s[qn] = round(csecs, 4)
            device_s[qn] = round(statistics.median(times) if times
                                 else fsecs, 4)
            # Compile-inclusive first run minus the steady-state median:
            # the retrace cost a warm process (serving, later iterations)
            # no longer pays thanks to the process-global kernel cache.
            compile_s[qn] = round(max(fsecs - device_s[qn], 0.0), 4)
            cache_q[qn] = {
                "hits": kc1["hits"] - kc0["hits"],
                "misses": kc1["misses"] - kc0["misses"]}
            out["kernel_cache"] = kc1
            out["recovery"] = _faults.counters()
            out["pipeline"] = _pl.counters()
            out["completed"].append(qn)
            done = out["completed"]
            out["metric"] = f"tpc_sf{sf:g}_suite{len(done)}_wall_clock"
            out["partial"] = len(done) < len(qnames)
            dev_total = sum(device_s[q] for q in done)
            cpu_total = sum(pandas_s[q] for q in done)
            out["value"] = round(dev_total, 4)
            # Headline ratio covers the five BASELINE.md target configs;
            # the full completed set reports separately (the extra TPC-H
            # coverage queries are correctness surface first).
            tgt = [q for q in done if q in _TARGETS]
            tdev = sum(device_s[q] for q in tgt)
            tcpu = sum(pandas_s[q] for q in tgt)
            if tdev > 0:
                out["vs_baseline"] = round(tcpu / tdev, 3)
            if dev_total > 0:
                out["vs_baseline_all"] = round(cpu_total / dev_total, 3)
            if "q1" in cold_s and "q6" in cold_s:
                scan_bytes = tpch.bytes_scanned("q1", tpch_dir) + \
                    tpch.bytes_scanned("q6", tpch_dir)
                denom = cold_s["q1"] + cold_s["q6"]
                out["scan_gb_per_sec"] = round(scan_bytes / denom / 1e9, 3)
                out["scan_frac_of_hbm_bw"] = round(
                    out["scan_gb_per_sec"] / HBM_GB_PER_SEC, 5)
        DEVICE_SCAN_CACHE.clear()

    # Scan-bandwidth microbench from the ingest fast path: the
    # scan_gb_per_sec headline is measured HERE (post-compile cold runs
    # through codec v2 + coalesced uploads); the q1/q6 cold_s derivation
    # above remains as scan_gb_per_sec_q1q6 for cross-round comparison.
    if "q1" in _STATE["ok"] and "q6" in _STATE["ok"] and \
            _remaining(budget) > 30:
        probe = _scan_probe(packs["q1"][1])
        with _LOCK:
            out["scan_bench"] = probe
            if "scan_gb_per_sec" in out:
                out["scan_gb_per_sec_q1q6"] = out["scan_gb_per_sec"]
            if probe.get("gb_per_sec"):
                out["scan_gb_per_sec"] = probe["gb_per_sec"]
                out["scan_frac_of_hbm_bw"] = round(
                    probe["gb_per_sec"] / HBM_GB_PER_SEC, 5)

    # One TRACED q3 run (outside the timing loop — tracing costs ~µs per
    # span but the timed medians stay untouched): exports the Chrome
    # trace artifact and the span-category wall breakdown.
    if "q3" in _STATE["ok"] and _remaining(budget) > 30:
        trace_path = os.environ.get("BENCH_TRACE_PATH",
                                    "/tmp/srt_bench_q3_trace.json")
        try:
            probe = _trace_probe(packs["q3"][1], trace_path)
            with _LOCK:
                out["trace"] = probe
        except Exception as e:     # the headline must survive a probe bug
            with _LOCK:
                out["trace"] = {"error": f"{type(e).__name__}: {e}"}

    # Forced-host engine sweep: the host-path headline (the 30x gap vs
    # pandas this round closed). No compile step, so it is cheap next to
    # the device loop; still budget-gated.
    if _remaining(budget) > 60:
        he = _host_engine_probe(packs, pandas_s, budget)
        with _LOCK:
            out["host_engine"] = he

    # N-query concurrent throughput vs serial (the scheduler's reason to
    # exist): N fresh sessions run the same hot query back-to-back and
    # then simultaneously — speedup > 1 says admission + isolation let
    # concurrent queries share the device productively.
    if "q6" in _STATE["ok"] and _remaining(budget) > 30:
        conc = _concurrency_probe(packs["q6"][1],
                                  int(os.environ.get(
                                      "BENCH_CONCURRENCY", "2")))
        with _LOCK:
            out["concurrency"] = conc

    # Distributed worker runtime: 1 vs 2 vs 3 worker processes executing
    # q3's stage DAG through the coordinator. The heaviest probe (each
    # configuration boots fresh workers that pay their own JIT warm-up),
    # so it needs the most headroom; BENCH_DISTRIBUTED=0 skips it.
    if "q3" in _STATE["ok"] and _remaining(budget) > 150 and \
            os.environ.get("BENCH_DISTRIBUTED", "1") != "0":
        try:
            dist = _distributed_probe(packs["q3"][1])
        except Exception as e:  # the headline must survive a probe bug
            dist = {"error": f"{type(e).__name__}: {e}"}
        # Self-healing fleet sub-block (ISSUE 20): supervised +
        # autoscaled pool under a q3 burst with one healed SIGKILL.
        if "error" not in dist and _remaining(budget) > 60 and \
                os.environ.get("BENCH_DISTRIBUTED", "1") != "0":
            try:
                dist["autoscale"] = _autoscale_probe(packs["q3"][1])
            except Exception as e:
                dist["autoscale"] = {
                    "error": f"{type(e).__name__}: {e}"}
        with _LOCK:
            out["distributed"] = dist

    # Sustained serving load through the plan cache: the "millions of
    # users" block — mixed parameterized shapes, new literals per call.
    if "q6" in _STATE["ok"] and _remaining(budget) > 60:
        try:
            sus = _sustained_probe(
                packs["q6"][1],
                int(os.environ.get("BENCH_SUSTAINED_QUERIES", "200")),
                int(os.environ.get("BENCH_SUSTAINED_CLIENTS", "4")))
        except Exception as e:  # the headline must survive a probe bug
            sus = {"error": f"{type(e).__name__}: {e}"}
        with _LOCK:
            out["sustained"] = sus

    # Serving QoS: mixed-class WFQ load with a capped tenant (the
    # tenant-quota rejections and starvation-bound engagements the
    # subsystem exists to produce under pressure).
    if "q6" in _STATE["ok"] and _remaining(budget) > 45:
        try:
            qjs = _qos_probe(packs["q6"][1],
                             int(os.environ.get("BENCH_QOS_QUERIES",
                                                "100")))
        except Exception as e:  # the headline must survive a probe bug
            qjs = {"error": f"{type(e).__name__}: {e}"}
        with _LOCK:
            out["qos"] = qjs

    from spark_rapids_tpu.parallel import scheduler as _sched
    with _LOCK:
        sch = _sched.counters()
        for name in ("admitted", "rejected", "cancelled", "deadlineKills",
                     "crossQueryEvictions", "queuedMs"):
            sch.setdefault(name, 0)
        out["scheduler"] = sch
        rec = _faults.counters()
        # Headline recovery counters always present (zero on a healthy
        # run); the per-stage detail (stageRecomputes.stage<N>) and
        # per-site injection detail ride along from the counter map.
        for name in ("faultsInjected", "retriesAttempted",
                     "spillEscalations", "hostFallbacks",
                     "corruptionsDetected", "stageRecomputes",
                     "partitionRetries", "watchdogKills", "meshDegrades",
                     "meshCollectiveSkipped", "crossQueryEvictions",
                     "graceJoinPartitions", "graceJoinEngaged"):
            rec.setdefault(name, 0)
        out["recovery"] = rec
        from spark_rapids_tpu.columnar import wire as _wire
        w = _wire.counters()
        for name in ("rawBytes", "encodedBytes", "stagingBytes",
                     "uploadTransfers", "uploadedBatches",
                     "groupedUploads", "wireCompressionRatio",
                     "stagingHitRate"):
            w.setdefault(name, 0)
        w["codec"] = _wire.codec_mode()
        out["wire"] = w
        pl = _pl.counters()
        for name in ("hostPrefetchMs", "consumerWaitMs", "pipelineStalls",
                     "prefetchedPartitions", "concurrentStages",
                     "overlapRatio"):
            pl.setdefault(name, 0)
        out["pipeline"] = pl
        from spark_rapids_tpu import config as _C
        from spark_rapids_tpu.parallel import transport as _tp
        tp = _tp.counters()
        for name in ("transportBytesWritten", "transportBytesFetched",
                     "transportShardsWritten", "transportShardsFetched",
                     "remoteShardRefetches", "remoteShardsLost"):
            tp.setdefault(name, 0)
        tp["selected"] = _tp.transport_name(_C.TpuConf())
        out["transport"] = tp
        from spark_rapids_tpu.plan import cost as _cost
        cs = _cost.counters()
        for name in ("costPlanningRuns", "costHostPlacements",
                     "costHostPlacedNodes", "replanChecks",
                     "joinDemotions"):
            cs.setdefault(name, 0)
        cs["enabled"] = _cost.cost_enabled(_C.TpuConf())
        out["cost"] = cs
        from spark_rapids_tpu.plan import plan_cache as _plc
        plc = _plc.counters()
        for name in ("planCacheHits", "planCacheMisses",
                     "bindOnlyExecutions", "planCacheBypasses",
                     "planCacheUncacheable", "planBindNs"):
            plc.setdefault(name, 0)
        plc["entries"] = _plc.cache().stats()["entries"]
        plc["enabled"] = _plc.plan_cache_enabled(_C.TpuConf())
        out["plan_cache"] = plc
        from spark_rapids_tpu.ops import native as _native
        nt = _native.counters()
        for name in ("nativeRadixSortTraces", "nativeJoinProbeTraces",
                     "nativeRleDecodeTraces",
                     "nativeSegmentReduceTraces"):
            nt.setdefault(name, 0)
        nt["calibration"] = _cost.calibration_state()
        out["native"] = nt
        from spark_rapids_tpu.monitoring import telemetry as _tm
        if _tm.enabled():
            # Compact registry rollup (the sustained block flips metrics
            # on, so a full bench run always carries this): the query
            # counter series plus how many series/metrics exist at exit.
            snap = _tm.snapshot()["metrics"]
            out["telemetry"] = {
                "enabled": True,
                "metrics": len(snap),
                "series": sum(len(m["series"]) for m in snap.values()),
                "queries_by_series": {
                    ",".join(f"{k}={v}" for k, v in
                             sorted(s["labels"].items())) or "-":
                    s["value"]
                    for s in snap.get("srt_queries",
                                      {}).get("series", [])},
            }
        else:
            out["telemetry"] = {"enabled": False}
        _STATE["done"] = True
        _emit(out)
    # No completed query = nothing measured: that is a failure signal even
    # though no individual check failed (vacuous all() must not pass).
    sys.exit(0 if out["completed"] and all(ok.values()) else 1)


if __name__ == "__main__":
    main()
