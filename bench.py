"""Benchmark: TPC-H q1-shaped columnar aggregate on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The workload mirrors BASELINE.md's first target config (scan+filter+agg,
the TPC-H q1/q6 shape): filter -> groupby(2 keys) -> sum/sum/avg/count over
a synthetic 4-column table. ``value`` is device rows/sec through the full
jitted pipeline (including the iterative partial/merge aggregation);
``vs_baseline`` is the speedup over this repo's host (numpy) engine on the
same machine — the stand-in for the reference's GPU-vs-CPU-Spark headline
(docs/FAQ.md:60-66 claims >=3x typical; published numbers are absent, see
BASELINE.md).
"""

from __future__ import annotations

import json
import time

import numpy as np

DEVICE_ROWS = 1 << 20       # 1M rows through the device pipeline
HOST_ROWS = 1 << 17         # host oracle is python-loop based; sample+scale
ITERS = 5


def make_host_batch(n_rows: int, seed: int = 0):
    # Shared with the driver entry so both measure the same workload.
    import __graft_entry__ as g
    return g.make_host_batch(n_rows, seed)


def device_pipeline():
    import jax
    import jax.numpy as jnp
    import __graft_entry__ as g
    fn, _ = g.entry()
    return jax.jit(fn)


def bench_device() -> float:
    import jax
    from spark_rapids_tpu.columnar.host import host_to_device
    hb = make_host_batch(DEVICE_ROWS)
    batch = host_to_device(hb, capacity=DEVICE_ROWS)
    fn = device_pipeline()
    out = fn(batch)
    jax.block_until_ready(out.num_rows)   # compile + warmup
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(batch)
    jax.block_until_ready(out.num_rows)
    dt_s = (time.perf_counter() - t0) / ITERS
    return DEVICE_ROWS / dt_s


def bench_host() -> float:
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.exprs.base import BoundReference as Ref, lit
    from spark_rapids_tpu import exprs as E
    from spark_rapids_tpu.ops import (
        AggSpec, Average, CountStar, FilterExec, HashAggregateExec,
        InMemorySourceExec, Sum)
    hb = make_host_batch(HOST_ROWS)
    schema = (("flag", dt.INT32), ("status", dt.INT32),
              ("qty", dt.INT64), ("price", dt.FLOAT64))
    src = InMemorySourceExec(schema, [[hb]])
    plan = HashAggregateExec(
        FilterExec(src, E.LessThanOrEqual(Ref(2, dt.INT64), lit(45))),
        [("flag", Ref(0, dt.INT32)), ("status", Ref(1, dt.INT32))],
        [AggSpec("sum_qty", Sum(Ref(2, dt.INT64))),
         AggSpec("sum_price", Sum(Ref(3, dt.FLOAT64))),
         AggSpec("avg_qty", Average(Ref(2, dt.INT64))),
         AggSpec("count", CountStar(None))])
    t0 = time.perf_counter()
    plan.collect(device=False)
    dt_s = time.perf_counter() - t0
    return HOST_ROWS / dt_s


def main():
    device_rps = bench_device()
    host_rps = bench_host()
    print(json.dumps({
        "metric": "tpch_q1like_device_rows_per_sec",
        "value": round(device_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(device_rps / host_rps, 3),
    }))


if __name__ == "__main__":
    main()
