"""Benchmark: TPC-H q1/q6/q3/q5 over parquet files, device engine vs a CPU
columnar engine (pandas/pyarrow) on the same machine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

- Workloads are BASELINE.md's target configs (TPC-H q1/q6 scan+filter+agg,
  q3/q5 joins), executed THROUGH the engine: parquet scan (pruned columns,
  multithreaded host decode), host->device upload, TPU kernels, collect.
  Nothing is pre-resident in HBM.
- ``value`` is the suite wall-clock (sum of per-query medians, seconds).
- ``vs_baseline`` is the speedup of this engine over the pandas/pyarrow
  implementation of the same queries at the same scale factor — the
  stand-in for the reference's GPU-vs-CPU-Spark headline (docs/FAQ.md:60-66
  claims 3-4x typical; the repo publishes no absolute numbers, BASELINE.md).
- ``scan_gb_per_sec`` reports q1+q6 achieved scan bandwidth (uncompressed
  pruned bytes / wall time) and ``scan_frac_of_hbm_bw`` normalizes it by
  the chip's HBM bandwidth — the MFU-style utilization accounting.
- Every device result is checked against the pandas result before timing;
  a mismatch fails the benchmark (BenchUtils.compareResults analog).

Env knobs: TPCH_SF (default 1.0), TPCH_DIR, BENCH_ITERS (default 3).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

if os.environ.get("BENCH_PLATFORM") == "cpu":
    # Hermetic CPU run (validation/dev): drop the remote-TPU plugin the
    # environment pins before any backend materializes (conftest recipe).
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

# v5e HBM bandwidth ~819 GB/s (public spec); used only for the
# utilization ratio, overridable for other chips.
HBM_GB_PER_SEC = float(os.environ.get("BENCH_HBM_GBPS", "819"))


def _session(scan_cache: bool = True):
    from spark_rapids_tpu.api.dataframe import TpuSession
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    if not scan_cache:
        s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    return s


def _timed_runs(df, iters):
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        df.collect()
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def main():
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.io.scan import DEVICE_SCAN_CACHE

    sf = float(os.environ.get("TPCH_SF", "1.0"))
    iters = int(os.environ.get("BENCH_ITERS", "3"))
    data_dir = os.environ.get(
        "TPCH_DIR", f"/tmp/srt_tpch_sf{sf:g}")
    t0 = time.perf_counter()
    rows = tpch.generate(data_dir, scale=sf)
    gen_s = time.perf_counter() - t0
    qnames = ["q1", "q6", "q3", "q5"]

    # Two configurations per query:
    # - cold: scan cache off — every run pays decode + host->device, the
    #   reference's cold-storage headline shape.
    # - hot (default config): the transparent device scan cache serves
    #   repeated scans from HBM, Spark columnar-cache style.
    device_s = {}       # default config (hot)
    cold_s = {}
    ok = {}
    for qn in qnames:
        DEVICE_SCAN_CACHE.clear()
        session = _session(scan_cache=False)
        df = tpch.QUERIES[qn](session, data_dir)
        # Warmup: compile + correctness check vs the pandas result.
        got = df.collect()
        want = tpch.pandas_query(qn, data_dir)
        ok[qn] = tpch.check_result(qn, got, want)
        cold_s[qn] = _timed_runs(df, iters)
        hot = tpch.QUERIES[qn](_session(), data_dir)
        hot.collect()               # populates the device cache
        device_s[qn] = _timed_runs(hot, iters)
        DEVICE_SCAN_CACHE.clear()

    pandas_s = {}
    for qn in qnames:
        times = []
        for _ in range(max(iters - 1, 2)):
            t0 = time.perf_counter()
            tpch.pandas_query(qn, data_dir)
            times.append(time.perf_counter() - t0)
        pandas_s[qn] = statistics.median(times)

    dev_total = sum(device_s.values())
    cold_total = sum(cold_s.values())
    cpu_total = sum(pandas_s.values())
    scan_bytes = tpch.bytes_scanned("q1", data_dir) + \
        tpch.bytes_scanned("q6", data_dir)
    scan_gbps = scan_bytes / (cold_s["q1"] + cold_s["q6"]) / 1e9

    print(json.dumps({
        "metric": f"tpch_sf{sf:g}_q1q6q3q5_wall_clock",
        "value": round(dev_total, 4),
        "unit": "s",
        "vs_baseline": round(cpu_total / dev_total, 3),
        "baseline": "pandas/pyarrow CPU engine, same queries+data+machine",
        "correct": ok,
        "device_s": {k: round(v, 4) for k, v in device_s.items()},
        "cold_device_s": {k: round(v, 4) for k, v in cold_s.items()},
        "vs_baseline_cold": round(cpu_total / cold_total, 3),
        "pandas_s": {k: round(v, 4) for k, v in pandas_s.items()},
        "scan_gb_per_sec": round(scan_gbps, 3),
        "scan_frac_of_hbm_bw": round(scan_gbps / HBM_GB_PER_SEC, 5),
        "rows": rows,
        "datagen_s": round(gen_s, 2),
    }))
    if not all(ok.values()):
        sys.exit(1)


if __name__ == "__main__":
    main()
