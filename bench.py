"""Benchmark: the five BASELINE.md target configs, device engine vs a CPU
columnar engine (pandas/pyarrow) on the same machine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Workloads (executed THROUGH the engine: parquet scan with pruned columns,
host->device upload, TPU kernels, collect — nothing pre-resident in HBM):
- TPC-H q1/q6 (scan+filter+agg) and q3/q5 (joins) — benchmarks/tpch.py
- TPC-DS q67-like (rollup + rank window + top-k)   — benchmarks/suites.py
- TPCxBB q5-like (conditional-sum pivot + joins)   — benchmarks/suites.py
- repartition-heavy (full hash shuffle + counts)   — benchmarks/suites.py

- ``value`` is the suite wall-clock (sum of per-query medians, seconds,
  hot config: transparent device scan cache on).
- ``vs_baseline`` is the speedup of this engine over the pandas/pyarrow
  implementation of the same queries at the same scale — the stand-in for
  the reference's GPU-vs-CPU-Spark headline (docs/FAQ.md:60-66 claims 3-4x
  typical; the repo publishes no absolute numbers, BASELINE.md).
- ``scan_gb_per_sec`` reports q1+q6 achieved scan bandwidth and
  ``scan_frac_of_hbm_bw`` normalizes by the chip's HBM bandwidth.
- Every device result is checked against the pandas result before timing;
  a mismatch fails the benchmark (BenchUtils.compareResults analog).

Env knobs: TPCH_SF (default 1.0), TPCH_DIR, SUITES_DIR, BENCH_ITERS
(default 3), BENCH_QUERIES (comma list to subset).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

if os.environ.get("BENCH_PLATFORM") == "cpu":
    # Hermetic CPU run (validation/dev): drop the remote-TPU plugin the
    # environment pins before any backend materializes (conftest recipe).
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")

# v5e HBM bandwidth ~819 GB/s (public spec); used only for the
# utilization ratio, overridable for other chips.
HBM_GB_PER_SEC = float(os.environ.get("BENCH_HBM_GBPS", "819"))


def _session(scan_cache: bool = True):
    from spark_rapids_tpu.api.dataframe import TpuSession
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    # TPC data is finite; the reference's benchmark setups make the same
    # assertion (spark.rapids.sql.hasNans=false) to unlock float fast paths.
    s.set("spark.rapids.sql.hasNans", False)
    if not scan_cache:
        s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    return s


def _timed_runs(df, iters):
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        df.collect()
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def main():
    from spark_rapids_tpu.benchmarks import suites, tpch
    from spark_rapids_tpu.io.scan import DEVICE_SCAN_CACHE

    sf = float(os.environ.get("TPCH_SF", "1.0"))
    iters = int(os.environ.get("BENCH_ITERS", "3"))
    tpch_dir = os.environ.get("TPCH_DIR", f"/tmp/srt_tpch_sf{sf:g}")
    suites_dir = os.environ.get("SUITES_DIR", f"/tmp/srt_suites_sf{sf:g}")
    t0 = time.perf_counter()
    rows = tpch.generate(tpch_dir, scale=sf)
    rows.update(suites.generate(suites_dir, scale=sf))
    gen_s = time.perf_counter() - t0

    packs = {
        "q1": (tpch, tpch_dir), "q6": (tpch, tpch_dir),
        "q3": (tpch, tpch_dir), "q5": (tpch, tpch_dir),
        "q67": (suites, suites_dir), "xbb_q5": (suites, suites_dir),
        "repart": (suites, suites_dir),
    }
    qnames = [q for q in packs
              if q in os.environ.get("BENCH_QUERIES",
                                     ",".join(packs)).split(",")]

    # Two configurations per query:
    # - cold: scan cache off — every run pays decode + host->device, the
    #   reference's cold-storage headline shape.
    # - hot (default config): the transparent device scan cache serves
    #   repeated scans from HBM, Spark columnar-cache style.
    device_s = {}       # default config (hot)
    cold_s = {}
    ok = {}
    for qn in qnames:
        mod, ddir = packs[qn]
        DEVICE_SCAN_CACHE.clear()
        session = _session(scan_cache=False)
        df = mod.QUERIES[qn](session, ddir)
        # Warmup: compile + correctness check vs the pandas result.
        got = df.collect()
        want = mod.pandas_query(qn, ddir)
        ok[qn] = mod.check_result(qn, got, want)
        cold_s[qn] = _timed_runs(df, iters)
        hot = mod.QUERIES[qn](_session(), ddir)
        hot.collect()               # populates the device cache
        device_s[qn] = _timed_runs(hot, iters)
        DEVICE_SCAN_CACHE.clear()

    pandas_s = {}
    for qn in qnames:
        mod, ddir = packs[qn]
        times = []
        for _ in range(max(iters - 1, 2)):
            t0 = time.perf_counter()
            mod.pandas_query(qn, ddir)
            times.append(time.perf_counter() - t0)
        pandas_s[qn] = statistics.median(times)

    dev_total = sum(device_s.values())
    cold_total = sum(cold_s.values())
    cpu_total = sum(pandas_s.values())
    out = {
        "metric": f"tpc_sf{sf:g}_suite7_wall_clock",
        "value": round(dev_total, 4),
        "unit": "s",
        "vs_baseline": round(cpu_total / dev_total, 3),
        "baseline": "pandas/pyarrow CPU engine, same queries+data+machine",
        "correct": ok,
        "device_s": {k: round(v, 4) for k, v in device_s.items()},
        "cold_device_s": {k: round(v, 4) for k, v in cold_s.items()},
        "vs_baseline_cold": round(cpu_total / cold_total, 3),
        "pandas_s": {k: round(v, 4) for k, v in pandas_s.items()},
        "rows": rows,
        "datagen_s": round(gen_s, 2),
    }
    if "q1" in qnames and "q6" in qnames:
        scan_bytes = tpch.bytes_scanned("q1", tpch_dir) + \
            tpch.bytes_scanned("q6", tpch_dir)
        scan_gbps = scan_bytes / (cold_s["q1"] + cold_s["q6"]) / 1e9
        out["scan_gb_per_sec"] = round(scan_gbps, 3)
        out["scan_frac_of_hbm_bw"] = round(scan_gbps / HBM_GB_PER_SEC, 5)
    print(json.dumps(out))
    if not all(ok.values()):
        sys.exit(1)


if __name__ == "__main__":
    main()
