// Host-side compression codecs for spilled blobs and shuffle payloads.
//
// TPU-native analog of the reference's TableCompressionCodec SPI
// (sql-plugin/.../TableCompressionCodec.scala:41) whose GPU implementation
// is nvcomp LZ4 (NvcompLZ4CompressionCodec.scala). There is no accelerator
// decompressor on the TPU side (XLA has no byte-oriented kernels), so the
// codec runs where the spilled bytes live: on the host, in native code, on
// the spill/shuffle write+read paths.
//
// Self-contained LZ4 *block format* implementation (the image ships no
// lz4.h): greedy hash-chain-less matcher with a 2^16-entry hash table,
// standard token/literal/match encoding, 64KB window. Decompression is
// format-exact, so blocks interoperate with any LZ4 block decoder.
//
// C ABI (ctypes-friendly):
//   int64 lz4_compress_bound(int64 n)
//   int64 lz4_compress(src, n, dst, dst_cap)   -> compressed size or -1
//   int64 lz4_decompress(src, n, dst, dst_cap) -> decompressed size or -1

#include <cstdint>
#include <cstring>

namespace {

constexpr int kMinMatch = 4;
constexpr int kHashBits = 16;
constexpr uint32_t kHashMul = 2654435761u;

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash4(const uint8_t* p) {
  return (read32(p) * kHashMul) >> (32 - kHashBits);
}

}  // namespace

extern "C" {

int64_t lz4_compress_bound(int64_t n) {
  // LZ4 worst case: n + n/255 + 16.
  return n + n / 255 + 16;
}

int64_t lz4_compress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                     int64_t dst_cap) {
  if (src_len < 0 || dst_cap < lz4_compress_bound(src_len)) return -1;
  uint32_t table[1 << kHashBits];
  std::memset(table, 0, sizeof(table));

  const uint8_t* ip = src;
  const uint8_t* const iend = src + src_len;
  // Matches must end >= 12 bytes before the end (format requirement:
  // last 5 bytes are literals, and a match can't start in the last 12).
  const uint8_t* const mflimit = src + src_len - 12;
  const uint8_t* anchor = src;
  uint8_t* op = dst;

  if (src_len >= 13) {
    ip++;  // first byte can't match (offset 0 is invalid)
    while (ip <= mflimit) {
      uint32_t h = hash4(ip);
      const uint8_t* match = src + table[h];
      table[h] = static_cast<uint32_t>(ip - src);
      if (match < ip && ip - match <= 0xFFFF && match >= src &&
          read32(match) == read32(ip)) {
        // Extend the match forward.
        const uint8_t* mp = match + kMinMatch;
        const uint8_t* cp = ip + kMinMatch;
        const uint8_t* climit = src + src_len - 5;
        while (cp < climit && *cp == *mp) { cp++; mp++; }
        int64_t match_len = cp - ip - kMinMatch;
        int64_t lit_len = ip - anchor;
        // Token.
        uint8_t* token = op++;
        if (lit_len >= 15) {
          *token = 15 << 4;
          int64_t l = lit_len - 15;
          while (l >= 255) { *op++ = 255; l -= 255; }
          *op++ = static_cast<uint8_t>(l);
        } else {
          *token = static_cast<uint8_t>(lit_len) << 4;
        }
        std::memcpy(op, anchor, lit_len);
        op += lit_len;
        // Offset (little endian).
        uint16_t off = static_cast<uint16_t>(ip - match);
        *op++ = off & 0xFF;
        *op++ = off >> 8;
        // Match length.
        if (match_len >= 15) {
          *token |= 15;
          int64_t l = match_len - 15;
          while (l >= 255) { *op++ = 255; l -= 255; }
          *op++ = static_cast<uint8_t>(l);
        } else {
          *token |= static_cast<uint8_t>(match_len);
        }
        ip = cp;
        anchor = ip;
      } else {
        ip++;
      }
    }
  }
  // Final literal run.
  int64_t lit_len = iend - anchor;
  uint8_t* token = op++;
  if (lit_len >= 15) {
    *token = 15 << 4;
    int64_t l = lit_len - 15;
    while (l >= 255) { *op++ = 255; l -= 255; }
    *op++ = static_cast<uint8_t>(l);
  } else {
    *token = static_cast<uint8_t>(lit_len) << 4;
  }
  std::memcpy(op, anchor, lit_len);
  op += lit_len;
  return op - dst;
}

int64_t lz4_decompress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                       int64_t dst_cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + src_len;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;

  while (ip < iend) {
    uint8_t token = *ip++;
    // Literals.
    int64_t lit_len = token >> 4;
    if (lit_len == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        lit_len += b;
      } while (b == 255);
    }
    if (ip + lit_len > iend || op + lit_len > oend) return -1;
    std::memcpy(op, ip, lit_len);
    ip += lit_len;
    op += lit_len;
    if (ip >= iend) break;  // last block: literals only
    // Match.
    if (ip + 2 > iend) return -1;
    uint16_t offset = ip[0] | (ip[1] << 8);
    ip += 2;
    if (offset == 0 || op - dst < offset) return -1;
    int64_t match_len = (token & 15) + kMinMatch;
    if ((token & 15) == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        match_len += b;
      } while (b == 255);
    }
    if (op + match_len > oend) return -1;
    const uint8_t* match = op - offset;
    // Byte-by-byte: overlapping copies are the RLE case.
    for (int64_t i = 0; i < match_len; i++) op[i] = match[i];
    op += match_len;
  }
  return op - dst;
}

}  // extern "C"
