// Native disk spill store (ref: RapidsDiskStore.scala +
// AddressSpaceAllocator.scala + RapidsDiskBlockManager.scala — the
// reference's disk tier writes spilled device buffers into per-executor
// files; this is the same design as a C component: one large spill file
// per store, a first-fit address-space allocator handing out file ranges,
// and pread/pwrite data movement that bypasses Python entirely for the
// byte shuffling).
//
// C ABI (used from Python via ctypes — no pybind11 in this environment):
//   spill_store_create(dir)            -> handle (opaque ptr)
//   spill_store_write(h, buf, len)     -> block id (>=0) or -errno
//   spill_store_read(h, id, buf, len)  -> bytes read or -errno
//   spill_store_block_size(h, id)      -> size or -1
//   spill_store_free(h, id)            -> 0/-1 (range returns to allocator)
//   spill_store_allocated_bytes(h)     -> live bytes
//   spill_store_file_bytes(h)          -> current spill file size
//   spill_store_destroy(h)
//
// Thread safety: a single mutex per store (matches the reference's
// synchronized stores; spills are IO-bound, not lock-bound).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

// First-fit allocator over the spill file's address space
// (AddressSpaceAllocator.scala). Free ranges are kept sorted by offset and
// coalesced on free.
class AddressSpaceAllocator {
 public:
  uint64_t Allocate(uint64_t size) {
    for (auto it = free_.begin(); it != free_.end(); ++it) {
      if (it->second >= size) {
        uint64_t offset = it->first;
        uint64_t remaining = it->second - size;
        free_.erase(it);
        if (remaining > 0) {
          free_[offset + size] = remaining;
        }
        return offset;
      }
    }
    // Extend the address space.
    uint64_t offset = end_;
    end_ += size;
    return offset;
  }

  void Free(uint64_t offset, uint64_t size) {
    auto it = free_.insert({offset, size}).first;
    // Coalesce with next.
    auto next = std::next(it);
    if (next != free_.end() && it->first + it->second == next->first) {
      it->second += next->second;
      free_.erase(next);
    }
    // Coalesce with prev.
    if (it != free_.begin()) {
      auto prev = std::prev(it);
      if (prev->first + prev->second == it->first) {
        prev->second += it->second;
        free_.erase(it);
      }
    }
  }

  uint64_t end() const { return end_; }

 private:
  std::map<uint64_t, uint64_t> free_;  // offset -> size
  uint64_t end_ = 0;
};

struct Block {
  uint64_t offset;
  uint64_t size;
};

struct Store {
  int fd = -1;
  std::string path;
  AddressSpaceAllocator alloc;
  std::map<int64_t, Block> blocks;
  int64_t next_id = 0;
  uint64_t live_bytes = 0;
  std::mutex mu;
};

}  // namespace

extern "C" {

void* spill_store_create(const char* dir) {
  std::string path = std::string(dir) + "/spill-XXXXXX";
  std::vector<char> tmpl(path.begin(), path.end());
  tmpl.push_back('\0');
  int fd = mkstemp(tmpl.data());
  if (fd < 0) return nullptr;
  // Unlink immediately: the file lives until the store closes, and the OS
  // reclaims it even on crash (RapidsDiskBlockManager's temp-file habit).
  unlink(tmpl.data());
  Store* s = new Store();
  s->fd = fd;
  s->path.assign(tmpl.data());
  return s;
}

int64_t spill_store_write(void* h, const uint8_t* buf, uint64_t len) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  uint64_t offset = s->alloc.Allocate(len);
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = pwrite(s->fd, buf + done, len - done,
                       static_cast<off_t>(offset + done));
    if (n < 0) {
      if (errno == EINTR) continue;
      s->alloc.Free(offset, len);
      return -static_cast<int64_t>(errno);
    }
    done += static_cast<uint64_t>(n);
  }
  int64_t id = s->next_id++;
  s->blocks[id] = Block{offset, len};
  s->live_bytes += len;
  return id;
}

int64_t spill_store_read(void* h, int64_t id, uint8_t* buf, uint64_t len) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->blocks.find(id);
  if (it == s->blocks.end()) return -ENOENT;
  uint64_t to_read = it->second.size < len ? it->second.size : len;
  uint64_t done = 0;
  while (done < to_read) {
    ssize_t n = pread(s->fd, buf + done, to_read - done,
                      static_cast<off_t>(it->second.offset + done));
    if (n < 0) {
      if (errno == EINTR) continue;
      return -static_cast<int64_t>(errno);
    }
    if (n == 0) break;
    done += static_cast<uint64_t>(n);
  }
  return static_cast<int64_t>(done);
}

int64_t spill_store_block_size(void* h, int64_t id) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->blocks.find(id);
  if (it == s->blocks.end()) return -1;
  return static_cast<int64_t>(it->second.size);
}

int spill_store_free(void* h, int64_t id) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto it = s->blocks.find(id);
  if (it == s->blocks.end()) return -1;
  s->alloc.Free(it->second.offset, it->second.size);
  s->live_bytes -= it->second.size;
  s->blocks.erase(it);
  return 0;
}

uint64_t spill_store_allocated_bytes(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->live_bytes;
}

uint64_t spill_store_file_bytes(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->alloc.end();
}

void spill_store_destroy(void* h) {
  Store* s = static_cast<Store*>(h);
  if (s->fd >= 0) close(s->fd);
  delete s;
}

}  // extern "C"
