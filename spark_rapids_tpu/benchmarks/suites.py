"""TPC-DS- and TPCxBB-like benchmark workloads (BASELINE.md targets 1/4/5).

The reference ships these as SQL over registered temp views
(integration_tests/.../tests/tpcds/TpcdsLikeSpark.scala Query("q67"),
tpcxbb/TpcxbbLikeSpark.scala object Q5Like); this module is the TPU
build's analog: numpy datagen writing multi-file parquet, the queries
expressed through the DataFrame API, and pandas implementations used as
the CPU baseline and the result oracle.

- ``q67`` (TPC-DS q67-like): store_sales x date_dim x store x item,
  ROLLUP over the 8 grouping columns, sum(coalesce(price*qty, 0)),
  rank() over (partition by i_category order by sumsales desc), rk <= 100,
  order + limit — the sort+window target config.
- ``xbb_q5`` (TPCxBB q5-like): clickstream x item join, per-user
  conditional-sum pivot (CASE WHEN), joins to customer/demographics with
  CASE projections — the filter+project+hash-aggregate target config.
- ``repart`` (repartition-heavy): full hash repartition of the
  clickstream fact table followed by a per-bucket count — the shuffle
  exchange target config (single-chip stand-in for the SF10K ICI case).
- ``ds_q3`` / ``ds_q42`` (TPC-DS q3/q42-like): fact x date x item joins
  with grouped revenue and deterministic ordered top-100s.
- ``ds_q89`` (TPC-DS q89-like): monthly class sales vs the class's
  windowed monthly average with a deviation filter (join + agg +
  window-avg shape).
- ``ds_q55`` (TPC-DS q55-like): one month's brand revenue top-100.
- ``ds_q98`` (TPC-DS q98-like): class revenue share of its category via
  a whole-partition window SUM ratio.
- ``xbb_q12`` (TPCxBB q12-like): distinct browsing users per category
  (COUNT DISTINCT through the partial/merge distinct pipeline).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq

CATEGORIES = ["Books", "Home", "Electronics", "Music", "Sports",
              "Toys", "Jewelry", "Shoes", "Men", "Women"]
EDU = ["Advanced Degree", "College", "4 yr Degree", "2 yr Degree",
       "Secondary", "Primary", "Unknown"]


def _write_parts(table: pa.Table, out_dir: str, n_files: int):
    os.makedirs(out_dir, exist_ok=True)
    n = table.num_rows
    per = max(1, -(-n // n_files))
    for i in range(n_files):
        part = table.slice(i * per, per)
        if part.num_rows == 0 and i > 0:
            break
        papq.write_table(part, os.path.join(out_dir, f"part-{i:03d}.parquet"),
                         compression="snappy")


def generate(data_dir: str, scale: float = 1.0, files_per_table: int = 8,
             seed: int = 0) -> Dict[str, int]:
    """TPC-DS/xBB-like tables (idempotent via a manifest)."""
    manifest_path = os.path.join(data_dir, "manifest.json")
    want = {"scale": scale, "files": files_per_table, "seed": seed,
            "version": 2}
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            have = json.load(f)
        if all(have.get(k) == v for k, v in want.items()):
            return have["rows"]
    rng = np.random.default_rng(seed)

    # -- TPC-DS-like ---------------------------------------------------------
    n_item = max(int(18_000 * scale), 100)
    n_store = max(int(12 * scale), 4)
    n_dates = 731                          # two years of days
    n_ss = max(int(2_880_000 * scale), 1000)

    item = pa.table({
        "i_item_sk": np.arange(1, n_item + 1, dtype=np.int64),
        "i_category": pa.array(
            [CATEGORIES[i] for i in rng.integers(0, 10, n_item)]),
        "i_category_id": rng.integers(1, 11, n_item, dtype=np.int64),
        "i_class": pa.array([f"class{i:02d}" for i in
                             rng.integers(0, 40, n_item)]),
        "i_brand": pa.array([f"brand{i:03d}" for i in
                             rng.integers(0, 200, n_item)]),
        "i_product_name": pa.array([f"prod{i:05d}" for i in
                                    rng.integers(0, 5000, n_item)]),
    })
    store = pa.table({
        "s_store_sk": np.arange(1, n_store + 1, dtype=np.int64),
        "s_store_id": pa.array([f"S{i:04d}" for i in range(n_store)]),
    })
    date_dim = pa.table({
        "d_date_sk": np.arange(1, n_dates + 1, dtype=np.int64),
        "d_year": (1998 + np.arange(n_dates) // 366).astype(np.int32),
        "d_qoy": ((np.arange(n_dates) % 366) // 92 + 1).astype(np.int32),
        "d_moy": ((np.arange(n_dates) % 366) // 31 + 1).astype(np.int32),
        "d_month_seq": (1176 + np.arange(n_dates) // 30).astype(np.int32),
    })
    store_sales = pa.table({
        "ss_sold_date_sk": rng.integers(1, n_dates + 1, n_ss,
                                        dtype=np.int64),
        "ss_item_sk": rng.integers(1, n_item + 1, n_ss, dtype=np.int64),
        "ss_store_sk": rng.integers(1, n_store + 1, n_ss, dtype=np.int64),
        "ss_quantity": rng.integers(1, 100, n_ss).astype(np.float64),
        # Whole-dollar prices: rank() partitions on sumsales, and integral
        # sums are exact in f64, so CPU and TPU rank ties identically
        # (2-decimal prices would make near-ties order-dependent — the
        # float-variance class the reference gates behind flags).
        "ss_sales_price": rng.integers(1, 200, n_ss).astype(np.float64),
    })

    # -- TPCxBB-like ---------------------------------------------------------
    n_cust = max(int(100_000 * scale), 50)
    n_demo = max(int(20_000 * scale), 20)
    n_wcs = max(int(4_000_000 * scale), 1000)
    user = rng.integers(1, n_cust + 1, n_wcs, dtype=np.int64)
    user_null = rng.random(n_wcs) < 0.05   # query filters IS NOT NULL
    web_clickstreams = pa.table({
        "wcs_user_sk": pa.array(user, pa.int64(), mask=user_null),
        "wcs_item_sk": rng.integers(1, n_item + 1, n_wcs, dtype=np.int64),
    })
    customer = pa.table({
        "c_customer_sk": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_current_cdemo_sk": rng.integers(1, n_demo + 1, n_cust,
                                           dtype=np.int64),
    })
    customer_demographics = pa.table({
        "cd_demo_sk": np.arange(1, n_demo + 1, dtype=np.int64),
        "cd_gender": pa.array(
            ["M" if g else "F" for g in rng.integers(0, 2, n_demo)]),
        "cd_education_status": pa.array(
            [EDU[i] for i in rng.integers(0, len(EDU), n_demo)]),
    })

    tables = {
        "item": item, "store": store, "date_dim": date_dim,
        "store_sales": store_sales, "web_clickstreams": web_clickstreams,
        "customer": customer, "customer_demographics": customer_demographics,
    }
    for name, tbl in tables.items():
        files = files_per_table if tbl.num_rows > 100_000 else 1
        _write_parts(tbl, os.path.join(data_dir, name), files)
    rows = {k: t.num_rows for k, t in tables.items()}
    with open(manifest_path, "w") as f:
        json.dump({**want, "rows": rows}, f)
    return rows


def _paths(data_dir: str, table: str) -> List[str]:
    d = os.path.join(data_dir, table)
    return sorted(os.path.join(d, f) for f in os.listdir(d)
                  if f.endswith(".parquet"))


def _read(session, data_dir: str, table: str):
    return session.read.parquet(*_paths(data_dir, table))


# ---------------------------------------------------------------------------
# Queries
# ---------------------------------------------------------------------------

Q67_KEYS = ["i_category", "i_class", "i_brand", "i_product_name",
            "d_year", "d_qoy", "d_moy", "s_store_id"]


def q67(session, data_dir: str):
    """TPC-DS q67-like: joins + ROLLUP + rank() window + top-100."""
    from spark_rapids_tpu.plan.logical import (
        Window, agg_sum, coalesce_cols, col, lit_col, rank)
    ss = _read(session, data_dir, "store_sales")
    dd = _read(session, data_dir, "date_dim") \
        .filter((col("d_month_seq") >= 1178)
                & (col("d_month_seq") <= 1189))
    st = _read(session, data_dir, "store")
    it = _read(session, data_dir, "item")
    j = ss.join_on(dd, ["ss_sold_date_sk"], ["d_date_sk"]) \
        .join_on(st, ["ss_store_sk"], ["s_store_sk"]) \
        .join_on(it, ["ss_item_sk"], ["i_item_sk"]) \
        .with_column("sales",
                     coalesce_cols(col("ss_sales_price")
                                   * col("ss_quantity"), lit_col(0.0)))
    dw1 = j.rollup(*Q67_KEYS).agg(agg_sum(col("sales")).alias("sumsales"))
    w = Window.partition_by("i_category").order_by(col("sumsales").desc())
    dw2 = dw1.with_column("rk", rank().over(w)).filter(col("rk") <= 100)
    return dw2.order_by(*[col(k).asc() for k in Q67_KEYS],
                        col("sumsales").asc(), col("rk").asc()) \
        .limit(100)


def xbb_q5(session, data_dir: str):
    """TPCxBB q5-like: per-user conditional-sum pivot + demo joins."""
    from spark_rapids_tpu.plan.logical import (
        agg_sum, col, lit_col, when)
    wcs = _read(session, data_dir, "web_clickstreams") \
        .filter(col("wcs_user_sk").isNotNull())
    it = _read(session, data_dir, "item")
    j = wcs.join_on(it, ["wcs_item_sk"], ["i_item_sk"])
    aggs = [agg_sum(when(col("i_category") == lit_col("Books"), 1)
                    .otherwise(0)).alias("clicks_in_category")]
    for i in range(1, 8):
        aggs.append(agg_sum(
            when(col("i_category_id") == lit_col(i), 1).otherwise(0))
            .alias(f"clicks_in_{i}"))
    per_user = j.group_by("wcs_user_sk").agg(*aggs)
    cust = _read(session, data_dir, "customer")
    demo = _read(session, data_dir, "customer_demographics")
    out = per_user.join_on(cust, ["wcs_user_sk"], ["c_customer_sk"]) \
        .join_on(demo, ["c_current_cdemo_sk"], ["cd_demo_sk"])
    return out.select(
        col("wcs_user_sk"),
        col("clicks_in_category"),
        when(col("cd_education_status").isin(
            "Advanced Degree", "College", "4 yr Degree", "2 yr Degree"), 1)
        .otherwise(0).alias("college_education"),
        when(col("cd_gender") == lit_col("M"), 1).otherwise(0).alias("male"),
        *[col(f"clicks_in_{i}") for i in range(1, 8)])


REPART_N = 16


def repart(session, data_dir: str):
    """Repartition-heavy: full hash shuffle of the clickstream fact table,
    then per-bucket row counts (validates every row moved exactly once).
    The bucket expression is exactly the exchange's partition id
    (pmod(murmur3(key), n) — GpuHashPartitioning parity)."""
    from spark_rapids_tpu.plan.logical import (
        agg_count, col, lit_col, murmur3_hash)
    wcs = _read(session, data_dir, "web_clickstreams")
    shuffled = wcs.repartition(REPART_N, col("wcs_item_sk"))
    n = lit_col(REPART_N)
    bucket = ((murmur3_hash(col("wcs_item_sk")) % n) + n) % n
    return shuffled.group_by(bucket.alias("bucket")) \
        .agg(agg_count().alias("n")).order_by("bucket")


def ds_q3(session, data_dir: str):
    """TPC-DS q3-like: fact x date x item, November sales by year and
    brand, revenue-ordered."""
    from spark_rapids_tpu.plan.logical import agg_sum, col
    ss = _read(session, data_dir, "store_sales")
    dd = _read(session, data_dir, "date_dim").filter(col("d_moy") == 11)
    it = _read(session, data_dir, "item") \
        .filter(col("i_category_id") == 1)
    return ss.join_on(dd, ["ss_sold_date_sk"], ["d_date_sk"]) \
        .join_on(it, ["ss_item_sk"], ["i_item_sk"]) \
        .group_by("d_year", "i_brand") \
        .agg(agg_sum(col("ss_sales_price")).alias("sum_agg")) \
        .order_by(col("d_year").asc(), col("sum_agg").desc(),
                  col("i_brand").asc()) \
        .limit(100)


def ds_q42(session, data_dir: str):
    """TPC-DS q42-like: category revenue for one year by quarter."""
    from spark_rapids_tpu.plan.logical import agg_sum, col
    ss = _read(session, data_dir, "store_sales")
    dd = _read(session, data_dir, "date_dim") \
        .filter(col("d_year") == 1999)
    it = _read(session, data_dir, "item")
    return ss.join_on(dd, ["ss_sold_date_sk"], ["d_date_sk"]) \
        .join_on(it, ["ss_item_sk"], ["i_item_sk"]) \
        .group_by("d_year", "d_qoy", "i_category") \
        .agg(agg_sum(col("ss_sales_price")).alias("revenue")) \
        .order_by(col("revenue").desc(), col("d_year").asc(),
                  col("d_qoy").asc(), col("i_category").asc()) \
        .limit(100)


def ds_q89(session, data_dir: str):
    """TPC-DS q89-like: monthly class sales vs the class's yearly monthly
    average (windowed avg + deviation filter)."""
    from spark_rapids_tpu.plan.logical import (
        Window, agg_avg, agg_sum, col)
    ss = _read(session, data_dir, "store_sales")
    dd = _read(session, data_dir, "date_dim") \
        .filter(col("d_year") == 1999)
    it = _read(session, data_dir, "item")
    monthly = ss.join_on(dd, ["ss_sold_date_sk"], ["d_date_sk"]) \
        .join_on(it, ["ss_item_sk"], ["i_item_sk"]) \
        .group_by("i_category", "i_class", "d_moy") \
        .agg(agg_sum(col("ss_sales_price")).alias("sum_sales"))
    w = Window.partition_by("i_category", "i_class")
    out = monthly.with_column("avg_monthly_sales",
                              agg_avg(col("sum_sales")).over(w))
    return out.filter(
        (col("sum_sales") - col("avg_monthly_sales"))
        / col("avg_monthly_sales") > 0.1) \
        .order_by(col("i_category").asc(), col("i_class").asc(),
                  col("d_moy").asc())


def ds_q55(session, data_dir: str):
    """TPC-DS q55-like: one month's brand revenue, top-100."""
    from spark_rapids_tpu.plan.logical import agg_sum, col
    ss = _read(session, data_dir, "store_sales")
    dd = _read(session, data_dir, "date_dim") \
        .filter((col("d_moy") == 12) & (col("d_year") == 1998))
    it = _read(session, data_dir, "item")
    return ss.join_on(dd, ["ss_sold_date_sk"], ["d_date_sk"]) \
        .join_on(it, ["ss_item_sk"], ["i_item_sk"]) \
        .group_by("i_brand") \
        .agg(agg_sum(col("ss_sales_price")).alias("ext_price")) \
        .order_by(col("ext_price").desc(), col("i_brand").asc()) \
        .limit(100)


def ds_q98(session, data_dir: str):
    """TPC-DS q98-like: class revenue with its share of the category
    total (window SUM ratio)."""
    from spark_rapids_tpu.plan.logical import Window, agg_sum, col
    ss = _read(session, data_dir, "store_sales")
    dd = _read(session, data_dir, "date_dim") \
        .filter(col("d_year") == 1999)
    it = _read(session, data_dir, "item") \
        .filter(col("i_category").isin("Books", "Home", "Sports"))
    per_class = ss.join_on(dd, ["ss_sold_date_sk"], ["d_date_sk"]) \
        .join_on(it, ["ss_item_sk"], ["i_item_sk"]) \
        .group_by("i_category", "i_class") \
        .agg(agg_sum(col("ss_sales_price")).alias("itemrevenue"))
    w = Window.partition_by("i_category")
    return per_class \
        .with_column("cat_total", agg_sum(col("itemrevenue")).over(w)) \
        .select(col("i_category"), col("i_class"), col("itemrevenue"),
                (col("itemrevenue") * 100.0 / col("cat_total"))
                .alias("revenueratio")) \
        .order_by(col("i_category").asc(), col("i_class").asc())


def xbb_q12(session, data_dir: str):
    """TPCxBB q12-like: distinct browsing users per category (COUNT
    DISTINCT through the partial/merge distinct pipeline)."""
    from spark_rapids_tpu.plan.logical import agg_count_distinct, col
    wcs = _read(session, data_dir, "web_clickstreams") \
        .filter(col("wcs_user_sk").isNotNull())
    it = _read(session, data_dir, "item")
    return wcs.join_on(it, ["wcs_item_sk"], ["i_item_sk"]) \
        .group_by("i_category") \
        .agg(agg_count_distinct(col("wcs_user_sk")).alias("users")) \
        .order_by(col("i_category").asc())


QUERIES = {"q67": q67, "xbb_q5": xbb_q5, "repart": repart,
           "ds_q3": ds_q3, "ds_q42": ds_q42, "ds_q89": ds_q89,
           "ds_q55": ds_q55, "ds_q98": ds_q98, "xbb_q12": xbb_q12}


# ---------------------------------------------------------------------------
# Pandas baselines / oracles
# ---------------------------------------------------------------------------

def pandas_query(name: str, data_dir: str):
    import pandas as pd

    def read(table, columns=None):
        return pa.concat_tables(
            [papq.read_table(p, columns=columns)
             for p in _paths(data_dir, table)]).to_pandas()

    if name == "q67":
        ss = read("store_sales")
        dd = read("date_dim")
        dd = dd[(dd.d_month_seq >= 1178) & (dd.d_month_seq <= 1189)]
        st = read("store")
        it = read("item")
        j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
            .merge(st, left_on="ss_store_sk", right_on="s_store_sk") \
            .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        j["sales"] = (j.ss_sales_price * j.ss_quantity).fillna(0.0)
        levels = []
        for lvl in range(len(Q67_KEYS), -1, -1):
            keys = Q67_KEYS[:lvl]
            if keys:
                g = j.groupby(keys, dropna=False)["sales"].sum() \
                    .reset_index()
            else:
                g = pd.DataFrame({"sales": [j.sales.sum()]})
            for k in Q67_KEYS[lvl:]:
                g[k] = None
            g = g[Q67_KEYS + ["sales"]]
            levels.append(g)
        dw1 = pd.concat(levels, ignore_index=True) \
            .rename(columns={"sales": "sumsales"})
        dw1["rk"] = dw1.groupby("i_category", dropna=False)["sumsales"] \
            .rank(method="min", ascending=False)
        # Partition NULL (from rollup levels dropping i_category) ranks as
        # its own partition, same as the engine's window partitioning.
        dw2 = dw1[dw1.rk <= 100].copy()
        dw2["rk"] = dw2.rk.astype("int32")
        dw2 = dw2.sort_values(
            Q67_KEYS + ["sumsales", "rk"],
            ascending=True, na_position="first").head(100)
        out = dw2[Q67_KEYS + ["sumsales", "rk"]]
        return [tuple(None if pd.isna(v) else v for v in r)
                for r in out.itertuples(index=False)]
    if name == "xbb_q5":
        wcs = read("web_clickstreams")
        wcs = wcs[wcs.wcs_user_sk.notna()]
        it = read("item")
        j = wcs.merge(it, left_on="wcs_item_sk", right_on="i_item_sk")
        j["clicks_in_category"] = (j.i_category == "Books").astype("int64")
        for i in range(1, 8):
            j[f"clicks_in_{i}"] = (j.i_category_id == i).astype("int64")
        cols = ["clicks_in_category"] + [f"clicks_in_{i}"
                                         for i in range(1, 8)]
        per_user = j.groupby("wcs_user_sk")[cols].sum().reset_index()
        cust = read("customer")
        demo = read("customer_demographics")
        out = per_user.merge(cust, left_on="wcs_user_sk",
                             right_on="c_customer_sk") \
            .merge(demo, left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
        out["college_education"] = out.cd_education_status.isin(
            ["Advanced Degree", "College", "4 yr Degree", "2 yr Degree"]
        ).astype("int64")
        out["male"] = (out.cd_gender == "M").astype("int64")
        final = out[["wcs_user_sk", "clicks_in_category",
                     "college_education", "male"]
                    + [f"clicks_in_{i}" for i in range(1, 8)]]
        return [tuple(int(v) for v in r)
                for r in final.itertuples(index=False)]
    if name == "repart":
        # Honest CPU equivalent of a hash repartition + per-bucket count:
        # the same vectorized murmur3 bucket per row, then group counts.
        from spark_rapids_tpu.exprs import hash as mh
        wcs = read("web_clickstreams", ["wcs_item_sk"])
        vals = wcs.wcs_item_sk.to_numpy(np.int64)
        h = mh.hash_long(np, vals, np.uint32(mh.DEFAULT_SEED)) \
            .astype(np.int32)
        bucket = ((h.astype(np.int64) % REPART_N) + REPART_N) % REPART_N
        counts = pd.Series(bucket).value_counts().sort_index()
        return [(int(b), int(n)) for b, n in counts.items()]
    if name == "ds_q3":
        ss = read("store_sales", ["ss_sold_date_sk", "ss_item_sk",
                                  "ss_sales_price"])
        dd = read("date_dim", ["d_date_sk", "d_year", "d_moy"])
        dd = dd[dd.d_moy == 11]
        it = read("item", ["i_item_sk", "i_brand", "i_category_id"])
        it = it[it.i_category_id == 1]
        j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
            .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        g = j.groupby(["d_year", "i_brand"], as_index=False) \
            .agg(sum_agg=("ss_sales_price", "sum"))
        g = g.sort_values(["d_year", "sum_agg", "i_brand"],
                          ascending=[True, False, True]).head(100)
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "ds_q42":
        ss = read("store_sales", ["ss_sold_date_sk", "ss_item_sk",
                                  "ss_sales_price"])
        dd = read("date_dim", ["d_date_sk", "d_year", "d_qoy"])
        dd = dd[dd.d_year == 1999]
        it = read("item", ["i_item_sk", "i_category"])
        j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
            .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        g = j.groupby(["d_year", "d_qoy", "i_category"], as_index=False) \
            .agg(revenue=("ss_sales_price", "sum"))
        g = g.sort_values(["revenue", "d_year", "d_qoy", "i_category"],
                          ascending=[False, True, True, True]).head(100)
        out = g[["d_year", "d_qoy", "i_category", "revenue"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "ds_q89":
        ss = read("store_sales", ["ss_sold_date_sk", "ss_item_sk",
                                  "ss_sales_price"])
        dd = read("date_dim", ["d_date_sk", "d_year", "d_moy"])
        dd = dd[dd.d_year == 1999]
        it = read("item", ["i_item_sk", "i_category", "i_class"])
        j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
            .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        g = j.groupby(["i_category", "i_class", "d_moy"],
                      as_index=False).agg(sum_sales=("ss_sales_price",
                                                     "sum"))
        g["avg_monthly_sales"] = g.groupby(
            ["i_category", "i_class"]).sum_sales.transform("mean")
        g = g[(g.sum_sales - g.avg_monthly_sales)
              / g.avg_monthly_sales > 0.1]
        g = g.sort_values(["i_category", "i_class", "d_moy"])
        out = g[["i_category", "i_class", "d_moy", "sum_sales",
                 "avg_monthly_sales"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "ds_q55":
        ss = read("store_sales", ["ss_sold_date_sk", "ss_item_sk",
                                  "ss_sales_price"])
        dd = read("date_dim", ["d_date_sk", "d_year", "d_moy"])
        dd = dd[(dd.d_moy == 12) & (dd.d_year == 1998)]
        it = read("item", ["i_item_sk", "i_brand"])
        j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
            .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        g = j.groupby("i_brand", as_index=False) \
            .agg(ext_price=("ss_sales_price", "sum"))
        g = g.sort_values(["ext_price", "i_brand"],
                          ascending=[False, True]).head(100)
        out = g[["i_brand", "ext_price"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "ds_q98":
        ss = read("store_sales", ["ss_sold_date_sk", "ss_item_sk",
                                  "ss_sales_price"])
        dd = read("date_dim", ["d_date_sk", "d_year"])
        dd = dd[dd.d_year == 1999]
        it = read("item", ["i_item_sk", "i_category", "i_class"])
        it = it[it.i_category.isin(["Books", "Home", "Sports"])]
        j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
            .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        g = j.groupby(["i_category", "i_class"], as_index=False) \
            .agg(itemrevenue=("ss_sales_price", "sum"))
        tot = g.groupby("i_category").itemrevenue.transform("sum")
        g["revenueratio"] = g.itemrevenue * 100.0 / tot
        g = g.sort_values(["i_category", "i_class"])
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "xbb_q12":
        wcs = read("web_clickstreams", ["wcs_user_sk", "wcs_item_sk"])
        wcs = wcs[wcs.wcs_user_sk.notna()]
        it = read("item", ["i_item_sk", "i_category"])
        j = wcs.merge(it, left_on="wcs_item_sk", right_on="i_item_sk")
        g = j.groupby("i_category", sort=True, as_index=False) \
            .agg(users=("wcs_user_sk", "nunique"))
        return [tuple(r) for r in g.itertuples(index=False)]
    raise KeyError(name)


# xbb_q5's ORDER BY is a computed float pivot — compare the row SET
# under the type-aware sort (compare.sort_key), like tpch._SET_COMPARE.
_SET_COMPARE = {"xbb_q5"}


def check_result(name: str, got, want) -> bool:
    """Oracle compare through the generalized helper
    (benchmarks/compare.py; BenchUtils.compareResults analog)."""
    from spark_rapids_tpu.benchmarks.compare import compare_results
    return compare_results(got, want, sort=name in _SET_COMPARE)
