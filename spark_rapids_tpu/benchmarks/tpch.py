"""TPC-H-like workload: parquet data generation + q1/q6/q3/q5 DataFrames.

The reference ships TPC-H query definitions (integration_tests/.../tests/
tpch/TpchLikeSpark.scala) and a bench harness (common/BenchUtils.scala:
39-300). This module is the TPU build's analog: a numpy-vectorized dbgen
stand-in writing multi-file parquet tables (so scans parallelize), the four
BASELINE.md target queries expressed through the DataFrame API, and a
pandas implementation of each query used both as the CPU baseline and as an
independent result check.

Distributions approximate dbgen (uniform where dbgen is uniform; the exact
text columns the queries never touch are omitted) — benchmark-faithful, not
audit-grade TPC-H.
"""

from __future__ import annotations

import datetime
import json
import os
from typing import Dict, List

import numpy as np

import pyarrow as pa
import pyarrow.parquet as papq

_EPOCH = datetime.date(1970, 1, 1)


def days(date_str: str) -> int:
    """'YYYY-MM-DD' -> days since epoch (Spark DateType physical value)."""
    y, m, d = map(int, date_str.split("-"))
    return (datetime.date(y, m, d) - _EPOCH).days


SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
SHIPINSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                "TAKE BACK RETURN"]
# p_name words (dbgen's color list, truncated): q9 greps '%green%'.
P_WORDS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
    "chartreuse", "chiffon", "chocolate", "coral", "cornflower",
    "cornsilk", "cream", "cyan", "dark", "deep", "dim", "dodger", "drab",
    "firebrick", "floral", "forest", "frosted", "gainsboro", "ghost",
    "goldenrod", "green", "grey", "honeydew", "hot", "indian", "ivory",
    "khaki", "lace", "lavender", "lawn", "lemon", "light", "lime", "linen",
    "magenta", "maroon", "medium", "metallic", "midnight", "mint", "misty",
    "moccasin", "navajo", "navy", "olive", "orange", "orchid", "pale",
    "papaya", "peach", "peru", "pink", "plum", "powder", "puff", "purple",
    "red", "rose", "rosy", "royal", "saddle", "salmon", "sandy", "seashell",
    "sienna", "sky", "slate", "smoke", "snow", "spring", "steel", "tan",
    "thistle", "tomato", "turquoise", "violet", "wheat", "white", "yellow"]
P_TYPE_1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
P_TYPE_2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
P_TYPE_3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
P_CONTAINER_1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
P_CONTAINER_2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
# Comment pools: a small fraction match q13's not-like '%special%requests%'
# and q16's '%Customer%Complaints%' — the distributions the queries probe.
O_COMMENTS = (
    ["carefully final deposits haggle", "quickly ironic packages wake",
     "furiously regular accounts sleep", "pending theodolites nag idly",
     "slyly even instructions boost", "blithely bold pinto beans detect",
     "ironic foxes above the accounts", "express waters cajole carefully",
     "silent requests along the pains", "unusual deposits engage daringly",
     "regular ideas use furiously", "enticing platelets among the ideas"]
    + ["special packages wake slyly requests",
       "special pinto beans use quickly regular requests"])
S_COMMENTS = (
    ["blithely regular packages boost", "carefully silent foxes detect",
     "quickly final deposits about the ideas", "furiously even pearls wake",
     "pending pains sleep slyly", "express dolphins above the packages",
     "regular warhorses cajole daringly", "ironic courts haggle quietly"]
    + ["Customer recounts wake Complaints",
       "Customer accounts nag slyly Complaints"])
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]


def _write_parts(table: pa.Table, out_dir: str, n_files: int):
    os.makedirs(out_dir, exist_ok=True)
    n = table.num_rows
    per = max(1, -(-n // n_files))
    for i in range(n_files):
        part = table.slice(i * per, per)
        if part.num_rows == 0 and i > 0:
            break
        papq.write_table(part, os.path.join(out_dir, f"part-{i:03d}.parquet"),
                         compression="snappy")


def generate(data_dir: str, scale: float = 1.0, files_per_table: int = 8,
             seed: int = 0, force: bool = False) -> Dict[str, int]:
    """Generate the TPC-H-like dataset (idempotent via a manifest)."""
    manifest_path = os.path.join(data_dir, "manifest.json")
    want = {"scale": scale, "files": files_per_table, "seed": seed,
            "version": 5}
    if not force and os.path.exists(manifest_path):
        with open(manifest_path) as f:
            have = json.load(f)
        if all(have.get(k) == v for k, v in want.items()):
            return have["rows"]
    rng = np.random.default_rng(seed)
    n_ord = max(int(1_500_000 * scale), 10)
    n_cust = max(int(150_000 * scale), 5)
    n_supp = max(int(10_000 * scale), 3)
    n_part = max(int(200_000 * scale), 8)

    def pick(pool, n):
        return np.array(pool, dtype=object)[rng.integers(0, len(pool), n)]

    # -- orders -------------------------------------------------------------
    o_orderkey = np.arange(1, n_ord + 1, dtype=np.int64)
    # dbgen leaves a third of customers orderless (q13's zero bucket,
    # q22's NOT EXISTS population).
    o_custkey = rng.integers(1, max(n_cust * 2 // 3, 2), n_ord,
                             dtype=np.int64)
    lo, hi = days("1992-01-01"), days("1998-08-02")
    o_orderdate = rng.integers(lo, hi, n_ord, dtype=np.int64).astype(np.int32)
    o_shippriority = np.zeros(n_ord, dtype=np.int32)
    # Status follows the date like dbgen: old orders are fulfilled.
    o_orderstatus = np.where(o_orderdate < days("1995-06-17"), "F",
                             np.where(rng.integers(0, 2, n_ord) == 0,
                                      "O", "P"))
    orders = pa.table({
        "o_orderkey": o_orderkey,
        "o_custkey": o_custkey,
        "o_orderdate": pa.array(o_orderdate, pa.int32()).cast(pa.date32()),
        "o_shippriority": o_shippriority,
        "o_totalprice": np.round(rng.uniform(900.0, 500_000.0, n_ord), 2),
        "o_orderstatus": pa.array(o_orderstatus.tolist(), pa.string()),
        "o_orderpriority": pa.array(pick(PRIORITIES, n_ord).tolist(),
                                    pa.string()),
        "o_comment": pa.array(pick(O_COMMENTS, n_ord).tolist(),
                              pa.string()),
    })

    # -- lineitem: 1..7 lines per order (dbgen's cardinality shape) ---------
    per_order = rng.integers(1, 8, n_ord)
    l_orderkey = np.repeat(o_orderkey, per_order)
    l_orderdate = np.repeat(o_orderdate, per_order)
    n_li = len(l_orderkey)
    linenumber = (np.arange(n_li, dtype=np.int64)
                  - np.repeat(np.cumsum(per_order) - per_order, per_order)
                  + 1).astype(np.int32)
    l_quantity = rng.integers(1, 51, n_li).astype(np.float64)
    l_extendedprice = np.round(rng.uniform(900.0, 105_000.0, n_li), 2)
    l_discount = rng.integers(0, 11, n_li).astype(np.float64) / 100.0
    l_tax = rng.integers(0, 9, n_li).astype(np.float64) / 100.0
    l_shipdate = (l_orderdate.astype(np.int64)
                  + rng.integers(1, 122, n_li)).astype(np.int32)
    l_commitdate = (l_orderdate.astype(np.int64)
                    + rng.integers(30, 91, n_li)).astype(np.int32)
    l_receiptdate = (l_shipdate.astype(np.int64)
                     + rng.integers(1, 31, n_li)).astype(np.int32)
    # returnflag: R/A for delivered-long-ago, N otherwise (dbgen's rule is
    # receiptdate-based; keep that correlation so q1 groups are realistic).
    cutoff = days("1995-06-17")
    ra = rng.integers(0, 2, n_li)
    l_returnflag = np.where(l_receiptdate <= cutoff,
                            np.where(ra == 0, "A", "R"), "N")
    l_linestatus = np.where(l_shipdate > days("1995-06-17"), "O", "F")
    # Each part is stocked by 4 suppliers (partsupp below); a line's
    # (partkey, suppkey) pair references one of them so q9/q20's
    # lineitem<->partsupp joins hit.
    l_partkey = rng.integers(1, n_part + 1, n_li, dtype=np.int64)
    l_suppkey = ((l_partkey + rng.integers(0, 4, n_li)
                  * (n_supp // 4 + 1)) % n_supp) + 1
    lineitem = pa.table({
        "l_orderkey": l_orderkey,
        "l_linenumber": linenumber,
        "l_partkey": l_partkey,
        "l_suppkey": l_suppkey,
        "l_quantity": l_quantity,
        "l_extendedprice": l_extendedprice,
        "l_discount": l_discount,
        "l_tax": l_tax,
        "l_returnflag": pa.array(l_returnflag.tolist(), pa.string()),
        "l_linestatus": pa.array(l_linestatus.tolist(), pa.string()),
        "l_shipdate": pa.array(l_shipdate, pa.int32()).cast(pa.date32()),
        "l_commitdate": pa.array(l_commitdate, pa.int32()).cast(pa.date32()),
        "l_receiptdate": pa.array(l_receiptdate,
                                  pa.int32()).cast(pa.date32()),
        "l_shipmode": pa.array(pick(SHIPMODES, n_li).tolist(), pa.string()),
        "l_shipinstruct": pa.array(pick(SHIPINSTRUCT, n_li).tolist(),
                                   pa.string()),
    })

    # -- part / partsupp ----------------------------------------------------
    p_partkey = np.arange(1, n_part + 1, dtype=np.int64)
    w1, w2, w3 = (pick(P_WORDS, n_part) for _ in range(3))
    p_name = [f"{a} {b} {c}" for a, b, c in zip(w1, w2, w3)]
    p_type = [f"{a} {b} {c}" for a, b, c in zip(
        pick(P_TYPE_1, n_part), pick(P_TYPE_2, n_part),
        pick(P_TYPE_3, n_part))]
    p_container = [f"{a} {b}" for a, b in zip(
        pick(P_CONTAINER_1, n_part), pick(P_CONTAINER_2, n_part))]
    brand_m = rng.integers(1, 6, n_part)
    brand_n = rng.integers(1, 6, n_part)
    part = pa.table({
        "p_partkey": p_partkey,
        "p_name": pa.array(p_name, pa.string()),
        "p_mfgr": pa.array([f"Manufacturer#{m}" for m in brand_m],
                           pa.string()),
        "p_brand": pa.array([f"Brand#{m}{n}" for m, n in
                             zip(brand_m, brand_n)], pa.string()),
        "p_type": pa.array(p_type, pa.string()),
        "p_size": rng.integers(1, 51, n_part).astype(np.int32),
        "p_container": pa.array(p_container, pa.string()),
        "p_retailprice": np.round(rng.uniform(900.0, 2000.0, n_part), 2),
    })
    # 4 suppliers per part, same formula the lineitem generator uses.
    ps_partkey = np.repeat(p_partkey, 4)
    ps_i = np.tile(np.arange(4), n_part)
    ps_suppkey = ((ps_partkey + ps_i * (n_supp // 4 + 1)) % n_supp) + 1
    n_ps = len(ps_partkey)
    partsupp = pa.table({
        "ps_partkey": ps_partkey,
        "ps_suppkey": ps_suppkey,
        "ps_availqty": rng.integers(1, 10_000, n_ps).astype(np.int32),
        "ps_supplycost": np.round(rng.uniform(1.0, 1000.0, n_ps), 2),
    })

    # -- customer / supplier / nation / region ------------------------------
    c_nationkey = rng.integers(0, 25, n_cust, dtype=np.int64)
    # Phone country code = 10 + nationkey (dbgen's rule; q22 slices it).
    c_phone = [f"{10 + nk}-{a}-{b}-{c}" for nk, a, b, c in zip(
        c_nationkey, rng.integers(100, 1000, n_cust),
        rng.integers(100, 1000, n_cust), rng.integers(1000, 10000, n_cust))]
    customer = pa.table({
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": pa.array([f"Customer#{i:09d}" for i in
                            range(1, n_cust + 1)], pa.string()),
        "c_nationkey": c_nationkey,
        "c_mktsegment": pa.array(
            [SEGMENTS[i] for i in rng.integers(0, 5, n_cust)], pa.string()),
        "c_phone": pa.array(c_phone, pa.string()),
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust), 2),
        "c_address": pa.array(pick(O_COMMENTS, n_cust).tolist(),
                              pa.string()),
        "c_comment": pa.array(pick(O_COMMENTS, n_cust).tolist(),
                              pa.string()),
    })
    s_nationkey = rng.integers(0, 25, n_supp, dtype=np.int64)
    s_phone = [f"{10 + nk}-{a}-{b}-{c}" for nk, a, b, c in zip(
        s_nationkey, rng.integers(100, 1000, n_supp),
        rng.integers(100, 1000, n_supp), rng.integers(1000, 10000, n_supp))]
    supplier = pa.table({
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_name": pa.array([f"Supplier#{i:09d}" for i in
                            range(1, n_supp + 1)], pa.string()),
        "s_nationkey": s_nationkey,
        "s_phone": pa.array(s_phone, pa.string()),
        "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_supp), 2),
        "s_address": pa.array(pick(S_COMMENTS, n_supp).tolist(),
                              pa.string()),
        "s_comment": pa.array(pick(S_COMMENTS, n_supp).tolist(),
                              pa.string()),
    })
    nation = pa.table({
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": pa.array([n for n, _ in NATIONS], pa.string()),
        "n_regionkey": np.array([r for _, r in NATIONS], dtype=np.int64),
    })
    region = pa.table({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": pa.array(REGIONS, pa.string()),
    })

    _write_parts(lineitem, os.path.join(data_dir, "lineitem"),
                 files_per_table)
    _write_parts(orders, os.path.join(data_dir, "orders"), files_per_table)
    _write_parts(customer, os.path.join(data_dir, "customer"),
                 max(files_per_table // 2, 1))
    _write_parts(part, os.path.join(data_dir, "part"),
                 max(files_per_table // 2, 1))
    _write_parts(partsupp, os.path.join(data_dir, "partsupp"),
                 max(files_per_table // 2, 1))
    _write_parts(supplier, os.path.join(data_dir, "supplier"), 1)
    _write_parts(nation, os.path.join(data_dir, "nation"), 1)
    _write_parts(region, os.path.join(data_dir, "region"), 1)
    rows = {"lineitem": n_li, "orders": n_ord, "customer": n_cust,
            "supplier": n_supp, "part": n_part, "partsupp": n_ps,
            "nation": 25, "region": 5}
    with open(manifest_path, "w") as f:
        json.dump({**want, "rows": rows}, f)
    return rows


def _paths(data_dir: str, table: str) -> List[str]:
    d = os.path.join(data_dir, table)
    return sorted(os.path.join(d, f) for f in os.listdir(d)
                  if f.endswith(".parquet"))


def _read(session, data_dir: str, table: str):
    return session.read.parquet(*_paths(data_dir, table))


# ---------------------------------------------------------------------------
# Queries (TpchLikeSpark.scala Q1/Q6/Q3/Q5 analogs)
# ---------------------------------------------------------------------------

def q1(session, data_dir: str):
    """Pricing summary report: scan+filter+wide hash aggregate."""
    from spark_rapids_tpu.plan.logical import (
        agg_avg, agg_count, agg_sum, col, lit_col)
    li = _read(session, data_dir, "lineitem")
    disc = li.filter(col("l_shipdate") <= lit_col(days("1998-09-02"))) \
        .with_column("disc_price",
                     col("l_extendedprice") * (1.0 - col("l_discount"))) \
        .with_column("charge",
                     col("l_extendedprice") * (1.0 - col("l_discount"))
                     * (1.0 + col("l_tax")))
    return disc.group_by("l_returnflag", "l_linestatus").agg(
        agg_sum(col("l_quantity")).alias("sum_qty"),
        agg_sum(col("l_extendedprice")).alias("sum_base_price"),
        agg_sum(col("disc_price")).alias("sum_disc_price"),
        agg_sum(col("charge")).alias("sum_charge"),
        agg_avg(col("l_quantity")).alias("avg_qty"),
        agg_avg(col("l_extendedprice")).alias("avg_price"),
        agg_avg(col("l_discount")).alias("avg_disc"),
        agg_count().alias("count_order"),
    ).order_by("l_returnflag", "l_linestatus")


def q6(session, data_dir: str):
    """Forecasting revenue change: selective filter + global agg."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    li = _read(session, data_dir, "lineitem")
    f = li.filter(
        (col("l_shipdate") >= lit_col(days("1994-01-01")))
        & (col("l_shipdate") < lit_col(days("1995-01-01")))
        & (col("l_discount") >= 0.05) & (col("l_discount") <= 0.07)
        & (col("l_quantity") < 24.0))
    return f.agg(agg_sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue"))


def q3(session, data_dir: str):
    """Shipping priority: two joins + agg + top-10 by revenue."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    cust = _read(session, data_dir, "customer") \
        .filter(col("c_mktsegment") == lit_col("BUILDING")) \
        .select("c_custkey")
    orders = _read(session, data_dir, "orders") \
        .filter(col("o_orderdate") < lit_col(days("1995-03-15"))) \
        .select("o_orderkey", "o_custkey", "o_orderdate", "o_shippriority")
    li = _read(session, data_dir, "lineitem") \
        .filter(col("l_shipdate") > lit_col(days("1995-03-15"))) \
        .select("l_orderkey", "l_extendedprice", "l_discount")
    co = orders.join_on(cust, ["o_custkey"], ["c_custkey"])
    j = li.join_on(co, ["l_orderkey"], ["o_orderkey"])
    return j.group_by("l_orderkey", "o_orderdate", "o_shippriority").agg(
        agg_sum(col("l_extendedprice") * (1.0 - col("l_discount")))
        .alias("revenue")
    ).order_by(col("revenue").desc(), col("o_orderdate").asc()) \
        .limit(10)


def q5(session, data_dir: str):
    """Local supplier volume: 5-way join + agg ordered by revenue."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    region = _read(session, data_dir, "region") \
        .filter(col("r_name") == lit_col("ASIA"))
    nation = _read(session, data_dir, "nation")
    nat = nation.join_on(region, ["n_regionkey"], ["r_regionkey"]) \
        .select("n_nationkey", "n_name")
    cust = _read(session, data_dir, "customer") \
        .join_on(nat, ["c_nationkey"], ["n_nationkey"]) \
        .select("c_custkey", "c_nationkey", "n_name")
    orders = _read(session, data_dir, "orders") \
        .filter((col("o_orderdate") >= lit_col(days("1994-01-01")))
                & (col("o_orderdate") < lit_col(days("1995-01-01")))) \
        .select("o_orderkey", "o_custkey")
    co = orders.join_on(cust, ["o_custkey"], ["c_custkey"]) \
        .select("o_orderkey", "c_nationkey", "n_name")
    li = _read(session, data_dir, "lineitem") \
        .select("l_orderkey", "l_suppkey", "l_extendedprice", "l_discount")
    j = li.join_on(co, ["l_orderkey"], ["o_orderkey"])
    supp = _read(session, data_dir, "supplier")
    j2 = j.join_on(supp, ["l_suppkey", "c_nationkey"],
                   ["s_suppkey", "s_nationkey"])
    return j2.group_by("n_name").agg(
        agg_sum(col("l_extendedprice") * (1.0 - col("l_discount")))
        .alias("revenue")
    ).order_by(col("revenue").desc())


def q2(session, data_dir: str):
    """Minimum-cost supplier: correlated min subquery as a re-join
    (TpchLikeSpark.scala's Q2 DataFrame shape)."""
    from spark_rapids_tpu.plan.logical import agg_min, col, lit_col
    region = _read(session, data_dir, "region") \
        .filter(col("r_name") == lit_col("EUROPE"))
    nat = _read(session, data_dir, "nation") \
        .join_on(region, ["n_regionkey"], ["r_regionkey"]) \
        .select("n_nationkey", "n_name")
    supp = _read(session, data_dir, "supplier") \
        .join_on(nat, ["s_nationkey"], ["n_nationkey"]) \
        .select("s_suppkey", "s_name", "s_address", "s_phone", "s_acctbal",
                "s_comment", "n_name")
    ps = _read(session, data_dir, "partsupp") \
        .join_on(supp, ["ps_suppkey"], ["s_suppkey"])
    minc = ps.group_by("ps_partkey").agg(
        agg_min(col("ps_supplycost")).alias("min_cost")) \
        .select(col("ps_partkey").alias("m_partkey"), col("min_cost"))
    part = _read(session, data_dir, "part") \
        .filter((col("p_size") == 15)
                & col("p_type").endswith("BRASS")) \
        .select("p_partkey", "p_mfgr")
    j = part.join_on(ps, ["p_partkey"], ["ps_partkey"]) \
        .join_on(minc, ["p_partkey"], ["m_partkey"]) \
        .filter(col("ps_supplycost") == col("min_cost"))
    return j.select("s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
                    "s_address", "s_phone", "s_comment") \
        .order_by(col("s_acctbal").desc(), col("n_name").asc(),
                  col("s_name").asc(), col("p_partkey").asc()) \
        .limit(100)


def q4(session, data_dir: str):
    """Order priority checking: EXISTS subquery as a left-semi join."""
    from spark_rapids_tpu.plan.logical import agg_count, col, lit_col
    li = _read(session, data_dir, "lineitem") \
        .filter(col("l_commitdate") < col("l_receiptdate")) \
        .select("l_orderkey")
    o = _read(session, data_dir, "orders") \
        .filter((col("o_orderdate") >= lit_col(days("1993-07-01")))
                & (col("o_orderdate") < lit_col(days("1993-10-01"))))
    return o.join_on(li, ["o_orderkey"], ["l_orderkey"], how="semi") \
        .group_by("o_orderpriority") \
        .agg(agg_count().alias("order_count")) \
        .order_by("o_orderpriority")


def q7(session, data_dir: str):
    """Volume shipping between FRANCE and GERMANY by year."""
    from spark_rapids_tpu.plan.logical import col, lit_col, agg_sum, year
    n1 = _read(session, data_dir, "nation") \
        .select(col("n_nationkey").alias("s_nkey"),
                col("n_name").alias("supp_nation"))
    n2 = _read(session, data_dir, "nation") \
        .select(col("n_nationkey").alias("c_nkey"),
                col("n_name").alias("cust_nation"))
    supp = _read(session, data_dir, "supplier") \
        .join_on(n1, ["s_nationkey"], ["s_nkey"]) \
        .select("s_suppkey", "supp_nation")
    cust = _read(session, data_dir, "customer") \
        .join_on(n2, ["c_nationkey"], ["c_nkey"]) \
        .select("c_custkey", "cust_nation")
    orders = _read(session, data_dir, "orders") \
        .select("o_orderkey", "o_custkey") \
        .join_on(cust, ["o_custkey"], ["c_custkey"])
    li = _read(session, data_dir, "lineitem") \
        .filter((col("l_shipdate") >= lit_col(days("1995-01-01")))
                & (col("l_shipdate") <= lit_col(days("1996-12-31")))) \
        .select("l_orderkey", "l_suppkey", "l_extendedprice", "l_discount",
                "l_shipdate")
    j = li.join_on(supp, ["l_suppkey"], ["s_suppkey"]) \
        .join_on(orders, ["l_orderkey"], ["o_orderkey"]) \
        .filter(((col("supp_nation") == lit_col("FRANCE"))
                 & (col("cust_nation") == lit_col("GERMANY")))
                | ((col("supp_nation") == lit_col("GERMANY"))
                   & (col("cust_nation") == lit_col("FRANCE"))))
    return j.with_column("l_year", year(col("l_shipdate"))) \
        .with_column("volume",
                     col("l_extendedprice") * (1.0 - col("l_discount"))) \
        .group_by("supp_nation", "cust_nation", "l_year") \
        .agg(agg_sum(col("volume")).alias("revenue")) \
        .order_by("supp_nation", "cust_nation", "l_year")


def q8(session, data_dir: str):
    """National market share of BRAZIL in AMERICA for a part type."""
    from spark_rapids_tpu.plan.logical import (
        agg_sum, col, lit_col, when, year)
    region = _read(session, data_dir, "region") \
        .filter(col("r_name") == lit_col("AMERICA"))
    n1 = _read(session, data_dir, "nation") \
        .join_on(region, ["n_regionkey"], ["r_regionkey"]) \
        .select(col("n_nationkey").alias("c_nkey"))
    n2 = _read(session, data_dir, "nation") \
        .select(col("n_nationkey").alias("s_nkey"),
                col("n_name").alias("nation"))
    cust = _read(session, data_dir, "customer") \
        .join_on(n1, ["c_nationkey"], ["c_nkey"]).select("c_custkey")
    supp = _read(session, data_dir, "supplier") \
        .join_on(n2, ["s_nationkey"], ["s_nkey"]) \
        .select("s_suppkey", "nation")
    part = _read(session, data_dir, "part") \
        .filter(col("p_type") == lit_col("ECONOMY ANODIZED STEEL")) \
        .select("p_partkey")
    orders = _read(session, data_dir, "orders") \
        .filter((col("o_orderdate") >= lit_col(days("1995-01-01")))
                & (col("o_orderdate") <= lit_col(days("1996-12-31")))) \
        .join_on(cust, ["o_custkey"], ["c_custkey"]) \
        .select("o_orderkey", "o_orderdate")
    li = _read(session, data_dir, "lineitem") \
        .select("l_orderkey", "l_partkey", "l_suppkey", "l_extendedprice",
                "l_discount")
    j = li.join_on(part, ["l_partkey"], ["p_partkey"]) \
        .join_on(supp, ["l_suppkey"], ["s_suppkey"]) \
        .join_on(orders, ["l_orderkey"], ["o_orderkey"]) \
        .with_column("o_year", year(col("o_orderdate"))) \
        .with_column("volume",
                     col("l_extendedprice") * (1.0 - col("l_discount")))
    return j.group_by("o_year").agg(
        (agg_sum(when(col("nation") == lit_col("BRAZIL"),
                      col("volume")).otherwise(0.0))).alias("brazil"),
        agg_sum(col("volume")).alias("total"),
    ).with_column("mkt_share", col("brazil") / col("total")) \
        .select("o_year", "mkt_share").order_by("o_year")


def q9(session, data_dir: str):
    """Product-type profit by nation and year (p_name like '%green%')."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, year
    part = _read(session, data_dir, "part") \
        .filter(col("p_name").contains("green")).select("p_partkey")
    supp = _read(session, data_dir, "supplier") \
        .select("s_suppkey", "s_nationkey")
    nat = _read(session, data_dir, "nation") \
        .select(col("n_nationkey"), col("n_name").alias("nation"))
    ps = _read(session, data_dir, "partsupp") \
        .select(col("ps_partkey"), col("ps_suppkey"), col("ps_supplycost"))
    orders = _read(session, data_dir, "orders") \
        .select("o_orderkey", "o_orderdate")
    li = _read(session, data_dir, "lineitem") \
        .select("l_orderkey", "l_partkey", "l_suppkey", "l_quantity",
                "l_extendedprice", "l_discount")
    j = li.join_on(part, ["l_partkey"], ["p_partkey"]) \
        .join_on(supp, ["l_suppkey"], ["s_suppkey"]) \
        .join_on(ps, ["l_partkey", "l_suppkey"],
                 ["ps_partkey", "ps_suppkey"]) \
        .join_on(orders, ["l_orderkey"], ["o_orderkey"]) \
        .join_on(nat, ["s_nationkey"], ["n_nationkey"]) \
        .with_column("o_year", year(col("o_orderdate"))) \
        .with_column("amount",
                     col("l_extendedprice") * (1.0 - col("l_discount"))
                     - col("ps_supplycost") * col("l_quantity"))
    return j.group_by("nation", "o_year") \
        .agg(agg_sum(col("amount")).alias("sum_profit")) \
        .order_by(col("nation").asc(), col("o_year").desc())


def q10(session, data_dir: str):
    """Returned-item reporting: top 20 customers by lost revenue."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    orders = _read(session, data_dir, "orders") \
        .filter((col("o_orderdate") >= lit_col(days("1993-10-01")))
                & (col("o_orderdate") < lit_col(days("1994-01-01")))) \
        .select("o_orderkey", "o_custkey")
    li = _read(session, data_dir, "lineitem") \
        .filter(col("l_returnflag") == lit_col("R")) \
        .select("l_orderkey", "l_extendedprice", "l_discount")
    nat = _read(session, data_dir, "nation") \
        .select("n_nationkey", "n_name")
    cust = _read(session, data_dir, "customer") \
        .join_on(nat, ["c_nationkey"], ["n_nationkey"]) \
        .select("c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                "c_address", "c_comment")
    j = li.join_on(orders, ["l_orderkey"], ["o_orderkey"]) \
        .join_on(cust, ["o_custkey"], ["c_custkey"]) \
        .with_column("revenue",
                     col("l_extendedprice") * (1.0 - col("l_discount")))
    return j.group_by("c_custkey", "c_name", "c_acctbal", "c_phone",
                      "n_name", "c_address", "c_comment") \
        .agg(agg_sum(col("revenue")).alias("revenue")) \
        .select("c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
                "c_address", "c_phone", "c_comment") \
        .order_by(col("revenue").desc()).limit(20)


def q11(session, data_dir: str):
    """Important stock identification: HAVING over a scalar subquery as a
    cross join against the global total."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    nat = _read(session, data_dir, "nation") \
        .filter(col("n_name") == lit_col("GERMANY")).select("n_nationkey")
    supp = _read(session, data_dir, "supplier") \
        .join_on(nat, ["s_nationkey"], ["n_nationkey"]).select("s_suppkey")
    ps = _read(session, data_dir, "partsupp") \
        .join_on(supp, ["ps_suppkey"], ["s_suppkey"]) \
        .with_column("value", col("ps_supplycost") * col("ps_availqty"))
    total = ps.agg(agg_sum(col("value")).alias("total"))
    g = ps.group_by("ps_partkey").agg(agg_sum(col("value")).alias("value"))
    return g.cross_join(total) \
        .filter(col("value") > col("total") * 0.0001) \
        .select("ps_partkey", "value") \
        .order_by(col("value").desc())


def q12(session, data_dir: str):
    """Shipping modes and order priority (two conditional sums)."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col, when
    li = _read(session, data_dir, "lineitem") \
        .filter(col("l_shipmode").isin("MAIL", "SHIP")
                & (col("l_commitdate") < col("l_receiptdate"))
                & (col("l_shipdate") < col("l_commitdate"))
                & (col("l_receiptdate") >= lit_col(days("1994-01-01")))
                & (col("l_receiptdate") < lit_col(days("1995-01-01")))) \
        .select("l_orderkey", "l_shipmode")
    o = _read(session, data_dir, "orders") \
        .select("o_orderkey", "o_orderpriority")
    j = li.join_on(o, ["l_orderkey"], ["o_orderkey"])
    high = col("o_orderpriority").isin("1-URGENT", "2-HIGH")
    return j.group_by("l_shipmode").agg(
        agg_sum(when(high, 1).otherwise(0)).alias("high_line_count"),
        agg_sum(when(high, 0).otherwise(1)).alias("low_line_count"),
    ).order_by("l_shipmode")


def q13(session, data_dir: str):
    """Customer order-count distribution: filtered LEFT join + count(col)
    (the filter only touches the right side, so it pre-applies)."""
    from spark_rapids_tpu.plan.logical import agg_count, col
    o = _read(session, data_dir, "orders") \
        .filter(~col("o_comment").like("%special%requests%")) \
        .select("o_orderkey", "o_custkey")
    c = _read(session, data_dir, "customer").select("c_custkey")
    j = c.join_on(o, ["c_custkey"], ["o_custkey"], how="left")
    counts = j.group_by("c_custkey").agg(
        agg_count(col("o_orderkey")).alias("c_count"))
    return counts.group_by("c_count").agg(
        agg_count().alias("custdist")) \
        .order_by(col("custdist").desc(), col("c_count").desc())


def q14(session, data_dir: str):
    """Promotion effect: conditional revenue share of PROMO parts."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col, when
    li = _read(session, data_dir, "lineitem") \
        .filter((col("l_shipdate") >= lit_col(days("1995-09-01")))
                & (col("l_shipdate") < lit_col(days("1995-10-01")))) \
        .select("l_partkey", "l_extendedprice", "l_discount")
    p = _read(session, data_dir, "part").select("p_partkey", "p_type")
    j = li.join_on(p, ["l_partkey"], ["p_partkey"]) \
        .with_column("revenue",
                     col("l_extendedprice") * (1.0 - col("l_discount")))
    promo = when(col("p_type").startswith("PROMO"),
                 col("revenue")).otherwise(0.0)
    return j.agg(agg_sum(promo).alias("promo"),
                 agg_sum(col("revenue")).alias("total")) \
        .select((col("promo") * 100.0 / col("total"))
                .alias("promo_revenue"))


def q15(session, data_dir: str):
    """Top supplier: scalar MAX subquery as a cross join + filter."""
    from spark_rapids_tpu.plan.logical import agg_max, agg_sum, col, lit_col
    li = _read(session, data_dir, "lineitem") \
        .filter((col("l_shipdate") >= lit_col(days("1996-01-01")))
                & (col("l_shipdate") < lit_col(days("1996-04-01"))))
    rev = li.with_column(
        "r", col("l_extendedprice") * (1.0 - col("l_discount"))) \
        .group_by("l_suppkey").agg(agg_sum(col("r")).alias("total_revenue"))
    mx = rev.agg(agg_max(col("total_revenue")).alias("mx"))
    top = rev.cross_join(mx).filter(col("total_revenue") == col("mx"))
    supp = _read(session, data_dir, "supplier") \
        .select("s_suppkey", "s_name", "s_address", "s_phone")
    return supp.join_on(top, ["s_suppkey"], ["l_suppkey"]) \
        .select("s_suppkey", "s_name", "s_address", "s_phone",
                "total_revenue") \
        .order_by("s_suppkey")


def q16(session, data_dir: str):
    """Parts/supplier relationship: anti join on complaint suppliers +
    count distinct."""
    from spark_rapids_tpu.plan.logical import (
        agg_count_distinct, col, lit_col)
    bad = _read(session, data_dir, "supplier") \
        .filter(col("s_comment").like("%Customer%Complaints%")) \
        .select("s_suppkey")
    p = _read(session, data_dir, "part") \
        .filter((col("p_brand") != lit_col("Brand#45"))
                & ~col("p_type").startswith("MEDIUM POLISHED")
                & col("p_size").isin(49, 14, 23, 45, 19, 3, 36, 9)) \
        .select("p_partkey", "p_brand", "p_type", "p_size")
    ps = _read(session, data_dir, "partsupp") \
        .select("ps_partkey", "ps_suppkey") \
        .join_on(bad, ["ps_suppkey"], ["s_suppkey"], how="anti")
    j = ps.join_on(p, ["ps_partkey"], ["p_partkey"])
    return j.group_by("p_brand", "p_type", "p_size").agg(
        agg_count_distinct(col("ps_suppkey")).alias("supplier_cnt")) \
        .order_by(col("supplier_cnt").desc(), col("p_brand").asc(),
                  col("p_type").asc(), col("p_size").asc())


def q17(session, data_dir: str):
    """Small-quantity-order revenue: correlated AVG as a grouped re-join."""
    from spark_rapids_tpu.plan.logical import agg_avg, agg_sum, col, lit_col
    p = _read(session, data_dir, "part") \
        .filter((col("p_brand") == lit_col("Brand#23"))
                & (col("p_container") == lit_col("MED BOX"))) \
        .select("p_partkey")
    li = _read(session, data_dir, "lineitem") \
        .select("l_partkey", "l_quantity", "l_extendedprice")
    lp = li.join_on(p, ["l_partkey"], ["p_partkey"])
    lim = lp.group_by("l_partkey").agg(
        agg_avg(col("l_quantity")).alias("avg_qty")) \
        .select(col("l_partkey").alias("a_partkey"),
                (col("avg_qty") * 0.2).alias("qty_limit"))
    j = lp.join_on(lim, ["l_partkey"], ["a_partkey"]) \
        .filter(col("l_quantity") < col("qty_limit"))
    return j.agg(agg_sum(col("l_extendedprice")).alias("s")) \
        .select((col("s") / 7.0).alias("avg_yearly"))


def q18(session, data_dir: str):
    """Large-volume customers: HAVING sum(qty) > 300 as a semi join."""
    from spark_rapids_tpu.plan.logical import agg_sum, col
    li = _read(session, data_dir, "lineitem") \
        .select("l_orderkey", "l_quantity")
    big = li.group_by("l_orderkey").agg(
        agg_sum(col("l_quantity")).alias("sum_qty")) \
        .filter(col("sum_qty") > 300.0) \
        .select(col("l_orderkey").alias("b_orderkey"))
    o = _read(session, data_dir, "orders") \
        .select("o_orderkey", "o_custkey", "o_orderdate", "o_totalprice") \
        .join_on(big, ["o_orderkey"], ["b_orderkey"], how="semi")
    c = _read(session, data_dir, "customer").select("c_custkey", "c_name")
    j = li.join_on(o, ["l_orderkey"], ["o_orderkey"]) \
        .join_on(c, ["o_custkey"], ["c_custkey"])
    return j.group_by("c_name", "c_custkey", "o_orderkey", "o_orderdate",
                      "o_totalprice") \
        .agg(agg_sum(col("l_quantity")).alias("sum_qty")) \
        .order_by(col("o_totalprice").desc(), col("o_orderdate").asc()) \
        .limit(100)


def q19(session, data_dir: str):
    """Discounted revenue: three-way disjunctive predicate over li x part."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    li = _read(session, data_dir, "lineitem") \
        .filter(col("l_shipmode").isin("AIR", "REG AIR")
                & (col("l_shipinstruct") == lit_col("DELIVER IN PERSON"))) \
        .select("l_partkey", "l_quantity", "l_extendedprice", "l_discount")
    p = _read(session, data_dir, "part") \
        .select("p_partkey", "p_brand", "p_container", "p_size")
    j = li.join_on(p, ["l_partkey"], ["p_partkey"])
    c1 = ((col("p_brand") == lit_col("Brand#12"))
          & col("p_container").isin("SM CASE", "SM BOX", "SM PACK",
                                    "SM PKG")
          & (col("l_quantity") >= 1.0) & (col("l_quantity") <= 11.0)
          & (col("p_size") >= 1) & (col("p_size") <= 5))
    c2 = ((col("p_brand") == lit_col("Brand#23"))
          & col("p_container").isin("MED BAG", "MED BOX", "MED PKG",
                                    "MED PACK")
          & (col("l_quantity") >= 10.0) & (col("l_quantity") <= 20.0)
          & (col("p_size") >= 1) & (col("p_size") <= 10))
    c3 = ((col("p_brand") == lit_col("Brand#34"))
          & col("p_container").isin("LG CASE", "LG BOX", "LG PACK",
                                    "LG PKG")
          & (col("l_quantity") >= 20.0) & (col("l_quantity") <= 30.0)
          & (col("p_size") >= 1) & (col("p_size") <= 15))
    return j.filter(c1 | c2 | c3).agg(
        agg_sum(col("l_extendedprice") * (1.0 - col("l_discount")))
        .alias("revenue"))


def q20(session, data_dir: str):
    """Potential part promotion: nested IN subqueries as semi joins +
    a grouped sum re-join with a non-equi filter."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    pf = _read(session, data_dir, "part") \
        .filter(col("p_name").startswith("forest")).select("p_partkey")
    liq = _read(session, data_dir, "lineitem") \
        .filter((col("l_shipdate") >= lit_col(days("1994-01-01")))
                & (col("l_shipdate") < lit_col(days("1995-01-01")))) \
        .group_by("l_partkey", "l_suppkey") \
        .agg(agg_sum(col("l_quantity")).alias("sum_qty"))
    ps = _read(session, data_dir, "partsupp") \
        .join_on(pf, ["ps_partkey"], ["p_partkey"], how="semi") \
        .join_on(liq, ["ps_partkey", "ps_suppkey"],
                 ["l_partkey", "l_suppkey"]) \
        .filter(col("ps_availqty").cast("double")
                > col("sum_qty") * 0.5) \
        .select("ps_suppkey")
    nat = _read(session, data_dir, "nation") \
        .filter(col("n_name") == lit_col("CANADA")).select("n_nationkey")
    supp = _read(session, data_dir, "supplier") \
        .join_on(nat, ["s_nationkey"], ["n_nationkey"]) \
        .join_on(ps, ["s_suppkey"], ["ps_suppkey"], how="semi")
    return supp.select("s_name", "s_address").order_by("s_name")


def q21(session, data_dir: str):
    """Suppliers who kept orders waiting: EXISTS/NOT-EXISTS self joins
    with a different-supplier condition."""
    from spark_rapids_tpu.plan.logical import agg_count, col, lit_col
    nat = _read(session, data_dir, "nation") \
        .filter(col("n_name") == lit_col("SAUDI ARABIA")) \
        .select("n_nationkey")
    supp = _read(session, data_dir, "supplier") \
        .join_on(nat, ["s_nationkey"], ["n_nationkey"]) \
        .select("s_suppkey", "s_name")
    o = _read(session, data_dir, "orders") \
        .filter(col("o_orderstatus") == lit_col("F")).select("o_orderkey")
    l1 = _read(session, data_dir, "lineitem") \
        .filter(col("l_receiptdate") > col("l_commitdate")) \
        .select("l_orderkey", "l_suppkey") \
        .join_on(o, ["l_orderkey"], ["o_orderkey"], how="semi")
    l2 = _read(session, data_dir, "lineitem") \
        .select(col("l_orderkey").alias("l2_orderkey"),
                col("l_suppkey").alias("l2_suppkey"))
    l3 = _read(session, data_dir, "lineitem") \
        .filter(col("l_receiptdate") > col("l_commitdate")) \
        .select(col("l_orderkey").alias("l3_orderkey"),
                col("l_suppkey").alias("l3_suppkey"))
    j = l1.join_on(l2, ["l_orderkey"], ["l2_orderkey"], how="semi",
                   condition=col("l2_suppkey") != col("l_suppkey")) \
        .join_on(l3, ["l_orderkey"], ["l3_orderkey"], how="anti",
                 condition=col("l3_suppkey") != col("l_suppkey")) \
        .join_on(supp, ["l_suppkey"], ["s_suppkey"])
    return j.group_by("s_name").agg(agg_count().alias("numwait")) \
        .order_by(col("numwait").desc(), col("s_name").asc()).limit(100)


def q22(session, data_dir: str):
    """Global sales opportunity: phone-prefix slice, scalar AVG subquery,
    NOT EXISTS as an anti join."""
    from spark_rapids_tpu.plan.logical import (
        agg_avg, agg_count, agg_sum, col)
    codes = ("13", "31", "23", "29", "30", "18", "17")
    cust = _read(session, data_dir, "customer") \
        .with_column("cntrycode", col("c_phone").substr(1, 2)) \
        .filter(col("cntrycode").isin(*codes)) \
        .select("c_custkey", "c_acctbal", "cntrycode")
    avg_bal = cust.filter(col("c_acctbal") > 0.0) \
        .agg(agg_avg(col("c_acctbal")).alias("avg_bal"))
    o = _read(session, data_dir, "orders").select("o_custkey")
    j = cust.cross_join(avg_bal) \
        .filter(col("c_acctbal") > col("avg_bal")) \
        .join_on(o, ["c_custkey"], ["o_custkey"], how="anti")
    return j.group_by("cntrycode").agg(
        agg_count().alias("numcust"),
        agg_sum(col("c_acctbal")).alias("totacctbal")) \
        .order_by("cntrycode")


QUERIES = {"q1": q1, "q2": q2, "q3": q3, "q4": q4, "q5": q5, "q6": q6,
           "q7": q7, "q8": q8, "q9": q9, "q10": q10, "q11": q11,
           "q12": q12, "q13": q13, "q14": q14, "q15": q15, "q16": q16,
           "q17": q17, "q18": q18, "q19": q19, "q20": q20, "q21": q21,
           "q22": q22}


# ---------------------------------------------------------------------------
# Pandas baseline (the CPU engine the bench compares against)
# ---------------------------------------------------------------------------

def pandas_query(name: str, data_dir: str):
    """Run query ``name`` with pandas/pyarrow — a genuine multi-threaded
    CPU columnar engine, standing in for BASELINE.md's 'CPU Spark' side
    (docs/FAQ.md:60-66 speedup claims). Returns a list of row tuples in
    the same column order as the DataFrame version."""
    import pandas as pd

    def read(table, columns):
        return pa.concat_tables(
            [papq.read_table(p, columns=columns)
             for p in _paths(data_dir, table)]).to_pandas()

    if name == "q1":
        li = read("lineitem", ["l_quantity", "l_extendedprice",
                               "l_discount", "l_tax", "l_returnflag",
                               "l_linestatus", "l_shipdate"])
        li = li[li.l_shipdate <= datetime.date(1998, 9, 2)]
        li["disc_price"] = li.l_extendedprice * (1.0 - li.l_discount)
        li["charge"] = li.disc_price * (1.0 + li.l_tax)
        g = li.groupby(["l_returnflag", "l_linestatus"], sort=True).agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_quantity", "size"),
        ).reset_index()
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q6":
        li = read("lineitem", ["l_shipdate", "l_discount", "l_quantity",
                               "l_extendedprice"])
        m = ((li.l_shipdate >= datetime.date(1994, 1, 1))
             & (li.l_shipdate < datetime.date(1995, 1, 1))
             & (li.l_discount >= 0.05) & (li.l_discount <= 0.07)
             & (li.l_quantity < 24.0))
        return [(float((li.l_extendedprice[m] * li.l_discount[m]).sum()),)]
    if name == "q3":
        cust = read("customer", ["c_custkey", "c_mktsegment"])
        cust = cust[cust.c_mktsegment == "BUILDING"][["c_custkey"]]
        orders = read("orders", ["o_orderkey", "o_custkey", "o_orderdate",
                                 "o_shippriority"])
        orders = orders[orders.o_orderdate < datetime.date(1995, 3, 15)]
        li = read("lineitem", ["l_orderkey", "l_extendedprice",
                               "l_discount", "l_shipdate"])
        li = li[li.l_shipdate > datetime.date(1995, 3, 15)]
        co = orders.merge(cust, left_on="o_custkey", right_on="c_custkey")
        j = li.merge(co, left_on="l_orderkey", right_on="o_orderkey")
        j["revenue"] = j.l_extendedprice * (1.0 - j.l_discount)
        g = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"]) \
            .agg(revenue=("revenue", "sum")).reset_index()
        g = g.sort_values(["revenue", "o_orderdate"],
                          ascending=[False, True]).head(10)
        out = g[["l_orderkey", "o_orderdate", "o_shippriority", "revenue"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "q5":
        region = read("region", ["r_regionkey", "r_name"])
        region = region[region.r_name == "ASIA"]
        nation = read("nation", ["n_nationkey", "n_name", "n_regionkey"])
        nat = nation.merge(region, left_on="n_regionkey",
                           right_on="r_regionkey")
        cust = read("customer", ["c_custkey", "c_nationkey"])
        cust = cust.merge(nat, left_on="c_nationkey",
                          right_on="n_nationkey")
        orders = read("orders", ["o_orderkey", "o_custkey", "o_orderdate"])
        orders = orders[(orders.o_orderdate >= datetime.date(1994, 1, 1))
                        & (orders.o_orderdate < datetime.date(1995, 1, 1))]
        co = orders.merge(cust, left_on="o_custkey", right_on="c_custkey")
        li = read("lineitem", ["l_orderkey", "l_suppkey",
                               "l_extendedprice", "l_discount"])
        j = li.merge(co[["o_orderkey", "c_nationkey", "n_name"]],
                     left_on="l_orderkey", right_on="o_orderkey")
        supp = read("supplier", ["s_suppkey", "s_nationkey"])
        j = j.merge(supp, left_on=["l_suppkey", "c_nationkey"],
                    right_on=["s_suppkey", "s_nationkey"])
        j["revenue"] = j.l_extendedprice * (1.0 - j.l_discount)
        g = j.groupby("n_name").agg(revenue=("revenue", "sum")) \
            .reset_index().sort_values("revenue", ascending=False)
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q2":
        region = read("region", None)
        nation = read("nation", None)
        nat = nation.merge(region[region.r_name == "EUROPE"],
                           left_on="n_regionkey", right_on="r_regionkey")
        supp = read("supplier", None).merge(
            nat[["n_nationkey", "n_name"]],
            left_on="s_nationkey", right_on="n_nationkey")
        ps = read("partsupp", None).merge(supp, left_on="ps_suppkey",
                                          right_on="s_suppkey")
        minc = ps.groupby("ps_partkey", as_index=False) \
            .agg(min_cost=("ps_supplycost", "min"))
        part = read("part", None)
        part = part[(part.p_size == 15)
                    & part.p_type.str.endswith("BRASS")]
        j = part.merge(ps, left_on="p_partkey", right_on="ps_partkey") \
            .merge(minc, on="ps_partkey")
        j = j[j.ps_supplycost == j.min_cost]
        j = j.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                          ascending=[False, True, True, True]).head(100)
        out = j[["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
                 "s_address", "s_phone", "s_comment"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "q4":
        li = read("lineitem", ["l_orderkey", "l_commitdate",
                               "l_receiptdate"])
        li = li[li.l_commitdate < li.l_receiptdate]
        o = read("orders", ["o_orderkey", "o_orderdate", "o_orderpriority"])
        o = o[(o.o_orderdate >= datetime.date(1993, 7, 1))
              & (o.o_orderdate < datetime.date(1993, 10, 1))]
        o = o[o.o_orderkey.isin(li.l_orderkey)]
        g = o.groupby("o_orderpriority", sort=True, as_index=False) \
            .agg(order_count=("o_orderkey", "size"))
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q7":
        nation = read("nation", ["n_nationkey", "n_name"])
        supp = read("supplier", ["s_suppkey", "s_nationkey"]).merge(
            nation.rename(columns={"n_name": "supp_nation"}),
            left_on="s_nationkey", right_on="n_nationkey")
        cust = read("customer", ["c_custkey", "c_nationkey"]).merge(
            nation.rename(columns={"n_name": "cust_nation"}),
            left_on="c_nationkey", right_on="n_nationkey")
        orders = read("orders", ["o_orderkey", "o_custkey"]).merge(
            cust[["c_custkey", "cust_nation"]],
            left_on="o_custkey", right_on="c_custkey")
        li = read("lineitem", ["l_orderkey", "l_suppkey", "l_shipdate",
                               "l_extendedprice", "l_discount"])
        li = li[(li.l_shipdate >= datetime.date(1995, 1, 1))
                & (li.l_shipdate <= datetime.date(1996, 12, 31))]
        j = li.merge(supp[["s_suppkey", "supp_nation"]],
                     left_on="l_suppkey", right_on="s_suppkey") \
            .merge(orders[["o_orderkey", "cust_nation"]],
                   left_on="l_orderkey", right_on="o_orderkey")
        j = j[((j.supp_nation == "FRANCE") & (j.cust_nation == "GERMANY"))
              | ((j.supp_nation == "GERMANY")
                 & (j.cust_nation == "FRANCE"))]
        j["l_year"] = pd.to_datetime(j.l_shipdate).dt.year
        j["volume"] = j.l_extendedprice * (1.0 - j.l_discount)
        g = j.groupby(["supp_nation", "cust_nation", "l_year"], sort=True,
                      as_index=False).agg(revenue=("volume", "sum"))
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q8":
        region = read("region", None)
        nation = read("nation", None)
        n1 = nation.merge(region[region.r_name == "AMERICA"],
                          left_on="n_regionkey", right_on="r_regionkey")
        cust = read("customer", ["c_custkey", "c_nationkey"])
        cust = cust[cust.c_nationkey.isin(n1.n_nationkey)]
        supp = read("supplier", ["s_suppkey", "s_nationkey"]).merge(
            nation.rename(columns={"n_name": "nation"}),
            left_on="s_nationkey", right_on="n_nationkey")
        part = read("part", ["p_partkey", "p_type"])
        part = part[part.p_type == "ECONOMY ANODIZED STEEL"]
        orders = read("orders", ["o_orderkey", "o_custkey", "o_orderdate"])
        orders = orders[(orders.o_orderdate >= datetime.date(1995, 1, 1))
                        & (orders.o_orderdate
                           <= datetime.date(1996, 12, 31))]
        orders = orders[orders.o_custkey.isin(cust.c_custkey)]
        li = read("lineitem", ["l_orderkey", "l_partkey", "l_suppkey",
                               "l_extendedprice", "l_discount"])
        j = li.merge(part[["p_partkey"]], left_on="l_partkey",
                     right_on="p_partkey") \
            .merge(supp[["s_suppkey", "nation"]], left_on="l_suppkey",
                   right_on="s_suppkey") \
            .merge(orders[["o_orderkey", "o_orderdate"]],
                   left_on="l_orderkey", right_on="o_orderkey")
        j["o_year"] = pd.to_datetime(j.o_orderdate).dt.year
        j["volume"] = j.l_extendedprice * (1.0 - j.l_discount)
        j["brazil"] = np.where(j.nation == "BRAZIL", j.volume, 0.0)
        g = j.groupby("o_year", sort=True, as_index=False) \
            .agg(brazil=("brazil", "sum"), total=("volume", "sum"))
        g["mkt_share"] = g.brazil / g.total
        out = g[["o_year", "mkt_share"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "q9":
        part = read("part", ["p_partkey", "p_name"])
        part = part[part.p_name.str.contains("green")]
        supp = read("supplier", ["s_suppkey", "s_nationkey"])
        nat = read("nation", ["n_nationkey", "n_name"]) \
            .rename(columns={"n_name": "nation"})
        ps = read("partsupp", ["ps_partkey", "ps_suppkey", "ps_supplycost"])
        orders = read("orders", ["o_orderkey", "o_orderdate"])
        li = read("lineitem", ["l_orderkey", "l_partkey", "l_suppkey",
                               "l_quantity", "l_extendedprice",
                               "l_discount"])
        j = li.merge(part[["p_partkey"]], left_on="l_partkey",
                     right_on="p_partkey") \
            .merge(supp, left_on="l_suppkey", right_on="s_suppkey") \
            .merge(ps, left_on=["l_partkey", "l_suppkey"],
                   right_on=["ps_partkey", "ps_suppkey"]) \
            .merge(orders, left_on="l_orderkey", right_on="o_orderkey") \
            .merge(nat, left_on="s_nationkey", right_on="n_nationkey")
        j["o_year"] = pd.to_datetime(j.o_orderdate).dt.year
        j["amount"] = j.l_extendedprice * (1.0 - j.l_discount) \
            - j.ps_supplycost * j.l_quantity
        g = j.groupby(["nation", "o_year"], as_index=False) \
            .agg(sum_profit=("amount", "sum"))
        g = g.sort_values(["nation", "o_year"], ascending=[True, False])
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q10":
        orders = read("orders", ["o_orderkey", "o_custkey", "o_orderdate"])
        orders = orders[(orders.o_orderdate >= datetime.date(1993, 10, 1))
                        & (orders.o_orderdate < datetime.date(1994, 1, 1))]
        li = read("lineitem", ["l_orderkey", "l_extendedprice",
                               "l_discount", "l_returnflag"])
        li = li[li.l_returnflag == "R"]
        nat = read("nation", ["n_nationkey", "n_name"])
        cust = read("customer", ["c_custkey", "c_name", "c_acctbal",
                                 "c_phone", "c_nationkey", "c_address",
                                 "c_comment"]).merge(
            nat, left_on="c_nationkey", right_on="n_nationkey")
        j = li.merge(orders[["o_orderkey", "o_custkey"]],
                     left_on="l_orderkey", right_on="o_orderkey") \
            .merge(cust, left_on="o_custkey", right_on="c_custkey")
        j["revenue"] = j.l_extendedprice * (1.0 - j.l_discount)
        g = j.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone",
                       "n_name", "c_address", "c_comment"],
                      as_index=False).agg(revenue=("revenue", "sum"))
        g = g.sort_values("revenue", ascending=False).head(20)
        out = g[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
                 "c_address", "c_phone", "c_comment"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "q11":
        nat = read("nation", ["n_nationkey", "n_name"])
        nat = nat[nat.n_name == "GERMANY"]
        supp = read("supplier", ["s_suppkey", "s_nationkey"])
        supp = supp[supp.s_nationkey.isin(nat.n_nationkey)]
        ps = read("partsupp", ["ps_partkey", "ps_suppkey", "ps_availqty",
                               "ps_supplycost"])
        ps = ps[ps.ps_suppkey.isin(supp.s_suppkey)]
        ps["value"] = ps.ps_supplycost * ps.ps_availqty
        total = ps.value.sum()
        g = ps.groupby("ps_partkey", as_index=False) \
            .agg(value=("value", "sum"))
        g = g[g.value > total * 0.0001] \
            .sort_values("value", ascending=False)
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q12":
        li = read("lineitem", ["l_orderkey", "l_shipmode", "l_shipdate",
                               "l_commitdate", "l_receiptdate"])
        li = li[li.l_shipmode.isin(["MAIL", "SHIP"])
                & (li.l_commitdate < li.l_receiptdate)
                & (li.l_shipdate < li.l_commitdate)
                & (li.l_receiptdate >= datetime.date(1994, 1, 1))
                & (li.l_receiptdate < datetime.date(1995, 1, 1))]
        o = read("orders", ["o_orderkey", "o_orderpriority"])
        j = li.merge(o, left_on="l_orderkey", right_on="o_orderkey")
        high = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
        j["high_line"] = np.where(high, 1, 0)
        j["low_line"] = np.where(high, 0, 1)
        g = j.groupby("l_shipmode", sort=True, as_index=False) \
            .agg(high_line_count=("high_line", "sum"),
                 low_line_count=("low_line", "sum"))
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q13":
        o = read("orders", ["o_orderkey", "o_custkey", "o_comment"])
        o = o[~o.o_comment.str.contains("special.*requests")]
        c = read("customer", ["c_custkey"])
        j = c.merge(o, left_on="c_custkey", right_on="o_custkey",
                    how="left")
        counts = j.groupby("c_custkey", as_index=False) \
            .agg(c_count=("o_orderkey", "count"))
        g = counts.groupby("c_count", as_index=False) \
            .agg(custdist=("c_count", "size"))
        g = g.sort_values(["custdist", "c_count"], ascending=[False, False])
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q14":
        li = read("lineitem", ["l_partkey", "l_shipdate", "l_extendedprice",
                               "l_discount"])
        li = li[(li.l_shipdate >= datetime.date(1995, 9, 1))
                & (li.l_shipdate < datetime.date(1995, 10, 1))]
        p = read("part", ["p_partkey", "p_type"])
        j = li.merge(p, left_on="l_partkey", right_on="p_partkey")
        j["revenue"] = j.l_extendedprice * (1.0 - j.l_discount)
        promo = np.where(j.p_type.str.startswith("PROMO"), j.revenue, 0.0)
        return [(float(100.0 * promo.sum() / j.revenue.sum()),)]
    if name == "q15":
        li = read("lineitem", ["l_suppkey", "l_shipdate", "l_extendedprice",
                               "l_discount"])
        li = li[(li.l_shipdate >= datetime.date(1996, 1, 1))
                & (li.l_shipdate < datetime.date(1996, 4, 1))]
        li["r"] = li.l_extendedprice * (1.0 - li.l_discount)
        rev = li.groupby("l_suppkey", as_index=False) \
            .agg(total_revenue=("r", "sum"))
        top = rev[rev.total_revenue == rev.total_revenue.max()]
        supp = read("supplier", ["s_suppkey", "s_name", "s_address",
                                 "s_phone"])
        j = supp.merge(top, left_on="s_suppkey", right_on="l_suppkey") \
            .sort_values("s_suppkey")
        out = j[["s_suppkey", "s_name", "s_address", "s_phone",
                 "total_revenue"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "q16":
        bad = read("supplier", ["s_suppkey", "s_comment"])
        bad = bad[bad.s_comment.str.contains("Customer.*Complaints")]
        p = read("part", ["p_partkey", "p_brand", "p_type", "p_size"])
        p = p[(p.p_brand != "Brand#45")
              & ~p.p_type.str.startswith("MEDIUM POLISHED")
              & p.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
        ps = read("partsupp", ["ps_partkey", "ps_suppkey"])
        ps = ps[~ps.ps_suppkey.isin(bad.s_suppkey)]
        j = ps.merge(p, left_on="ps_partkey", right_on="p_partkey")
        g = j.groupby(["p_brand", "p_type", "p_size"], as_index=False) \
            .agg(supplier_cnt=("ps_suppkey", "nunique"))
        g = g.sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                          ascending=[False, True, True, True])
        out = g[["p_brand", "p_type", "p_size", "supplier_cnt"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "q17":
        p = read("part", ["p_partkey", "p_brand", "p_container"])
        p = p[(p.p_brand == "Brand#23") & (p.p_container == "MED BOX")]
        li = read("lineitem", ["l_partkey", "l_quantity",
                               "l_extendedprice"])
        lp = li.merge(p[["p_partkey"]], left_on="l_partkey",
                      right_on="p_partkey")
        lim = lp.groupby("l_partkey", as_index=False) \
            .agg(avg_qty=("l_quantity", "mean"))
        lim["qty_limit"] = lim.avg_qty * 0.2
        j = lp.merge(lim[["l_partkey", "qty_limit"]], on="l_partkey")
        j = j[j.l_quantity < j.qty_limit]
        return [(float(j.l_extendedprice.sum() / 7.0),)]
    if name == "q18":
        li = read("lineitem", ["l_orderkey", "l_quantity"])
        sums = li.groupby("l_orderkey", as_index=False) \
            .agg(sum_qty=("l_quantity", "sum"))
        big = sums[sums.sum_qty > 300.0].l_orderkey
        o = read("orders", ["o_orderkey", "o_custkey", "o_orderdate",
                            "o_totalprice"])
        o = o[o.o_orderkey.isin(big)]
        c = read("customer", ["c_custkey", "c_name"])
        j = li.merge(o, left_on="l_orderkey", right_on="o_orderkey") \
            .merge(c, left_on="o_custkey", right_on="c_custkey")
        g = j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                       "o_totalprice"], as_index=False) \
            .agg(sum_qty=("l_quantity", "sum"))
        g = g.sort_values(["o_totalprice", "o_orderdate"],
                          ascending=[False, True]).head(100)
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q19":
        li = read("lineitem", ["l_partkey", "l_quantity", "l_extendedprice",
                               "l_discount", "l_shipmode",
                               "l_shipinstruct"])
        li = li[li.l_shipmode.isin(["AIR", "REG AIR"])
                & (li.l_shipinstruct == "DELIVER IN PERSON")]
        p = read("part", ["p_partkey", "p_brand", "p_container", "p_size"])
        j = li.merge(p, left_on="l_partkey", right_on="p_partkey")
        c1 = ((j.p_brand == "Brand#12")
              & j.p_container.isin(["SM CASE", "SM BOX", "SM PACK",
                                    "SM PKG"])
              & (j.l_quantity >= 1.0) & (j.l_quantity <= 11.0)
              & (j.p_size >= 1) & (j.p_size <= 5))
        c2 = ((j.p_brand == "Brand#23")
              & j.p_container.isin(["MED BAG", "MED BOX", "MED PKG",
                                    "MED PACK"])
              & (j.l_quantity >= 10.0) & (j.l_quantity <= 20.0)
              & (j.p_size >= 1) & (j.p_size <= 10))
        c3 = ((j.p_brand == "Brand#34")
              & j.p_container.isin(["LG CASE", "LG BOX", "LG PACK",
                                    "LG PKG"])
              & (j.l_quantity >= 20.0) & (j.l_quantity <= 30.0)
              & (j.p_size >= 1) & (j.p_size <= 15))
        j = j[c1 | c2 | c3]
        if len(j) == 0:
            # Spark SUM over zero rows is NULL, not 0.0 — tiny scale
            # factors legitimately filter q19 down to nothing.
            return [(None,)]
        return [(float((j.l_extendedprice * (1.0 - j.l_discount)).sum()),)]
    if name == "q20":
        pf = read("part", ["p_partkey", "p_name"])
        pf = pf[pf.p_name.str.startswith("forest")]
        li = read("lineitem", ["l_partkey", "l_suppkey", "l_shipdate",
                               "l_quantity"])
        li = li[(li.l_shipdate >= datetime.date(1994, 1, 1))
                & (li.l_shipdate < datetime.date(1995, 1, 1))]
        liq = li.groupby(["l_partkey", "l_suppkey"], as_index=False) \
            .agg(sum_qty=("l_quantity", "sum"))
        ps = read("partsupp", ["ps_partkey", "ps_suppkey", "ps_availqty"])
        ps = ps[ps.ps_partkey.isin(pf.p_partkey)]
        ps = ps.merge(liq, left_on=["ps_partkey", "ps_suppkey"],
                      right_on=["l_partkey", "l_suppkey"])
        ps = ps[ps.ps_availqty > ps.sum_qty * 0.5]
        nat = read("nation", ["n_nationkey", "n_name"])
        nat = nat[nat.n_name == "CANADA"]
        supp = read("supplier", ["s_suppkey", "s_name", "s_address",
                                 "s_nationkey"])
        supp = supp[supp.s_nationkey.isin(nat.n_nationkey)
                    & supp.s_suppkey.isin(ps.ps_suppkey)]
        supp = supp.sort_values("s_name")
        out = supp[["s_name", "s_address"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "q21":
        nat = read("nation", ["n_nationkey", "n_name"])
        nat = nat[nat.n_name == "SAUDI ARABIA"]
        supp = read("supplier", ["s_suppkey", "s_name", "s_nationkey"])
        supp = supp[supp.s_nationkey.isin(nat.n_nationkey)]
        o = read("orders", ["o_orderkey", "o_orderstatus"])
        o = o[o.o_orderstatus == "F"]
        li = read("lineitem", ["l_orderkey", "l_suppkey", "l_receiptdate",
                               "l_commitdate"])
        late = li[li.l_receiptdate > li.l_commitdate]
        l1 = late[late.l_orderkey.isin(o.o_orderkey)]
        # exists l2: same order, different supplier (any line)
        nsupp_all = li.groupby("l_orderkey").l_suppkey.nunique()
        multi = nsupp_all[nsupp_all > 1].index
        l1 = l1[l1.l_orderkey.isin(multi)]
        # not exists l3: same order, different supplier, also late
        nsupp_late = late.groupby("l_orderkey").l_suppkey.nunique()
        sole_late = nsupp_late[nsupp_late == 1].index
        l1 = l1[l1.l_orderkey.isin(sole_late)]
        j = l1.merge(supp, left_on="l_suppkey", right_on="s_suppkey")
        g = j.groupby("s_name", as_index=False) \
            .agg(numwait=("s_name", "size"))
        g = g.sort_values(["numwait", "s_name"],
                          ascending=[False, True]).head(100)
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q22":
        cust = read("customer", ["c_custkey", "c_phone", "c_acctbal"])
        cust["cntrycode"] = cust.c_phone.str[:2]
        codes = ["13", "31", "23", "29", "30", "18", "17"]
        cust = cust[cust.cntrycode.isin(codes)]
        avg_bal = cust[cust.c_acctbal > 0.0].c_acctbal.mean()
        o = read("orders", ["o_custkey"])
        sel = cust[(cust.c_acctbal > avg_bal)
                   & ~cust.c_custkey.isin(o.o_custkey)]
        g = sel.groupby("cntrycode", sort=True, as_index=False) \
            .agg(numcust=("c_custkey", "size"),
                 totacctbal=("c_acctbal", "sum"))
        return [tuple(r) for r in g.itertuples(index=False)]
    raise KeyError(name)


def rows_close(a, b, rel: float = 1e-6) -> bool:
    """Shared row-list comparator — the generalized helper in
    benchmarks/compare.py (BenchUtils.compareResults analog), kept under
    its historical name for the test suites that import it here."""
    from spark_rapids_tpu.benchmarks.compare import compare_results
    return compare_results(a, b, rel_tol=rel)


# Queries ordered by a COMPUTED float (summed revenue/value): the two
# engines legitimately order epsilon-different sums differently, so only
# the row SET is checked. Everything else orders by raw data or unique
# int/string keys and must match exactly, ORDER BY included.
_SET_COMPARE = {"q5", "q10", "q11"}


def check_result(name: str, got, want) -> bool:
    """Compare a device result against the pandas result for query
    ``name`` (BenchUtils.compareResults analog)."""
    from spark_rapids_tpu.benchmarks.compare import compare_results
    return compare_results(got, want, sort=name in _SET_COMPARE)


def bytes_scanned(name: str, data_dir: str) -> int:
    """Uncompressed bytes of the pruned columns each query reads — the
    numerator of the bytes/s (bandwidth-utilization) bench metric."""
    cols = {
        "q1": {"lineitem": ["l_quantity", "l_extendedprice", "l_discount",
                            "l_tax", "l_returnflag", "l_linestatus",
                            "l_shipdate"]},
        "q6": {"lineitem": ["l_shipdate", "l_discount", "l_quantity",
                            "l_extendedprice"]},
        "q3": {"customer": ["c_custkey", "c_mktsegment"],
               "orders": ["o_orderkey", "o_custkey", "o_orderdate",
                          "o_shippriority"],
               "lineitem": ["l_orderkey", "l_extendedprice", "l_discount",
                            "l_shipdate"]},
        "q5": {"region": ["r_regionkey", "r_name"],
               "nation": ["n_nationkey", "n_name", "n_regionkey"],
               "customer": ["c_custkey", "c_nationkey"],
               "orders": ["o_orderkey", "o_custkey", "o_orderdate"],
               "lineitem": ["l_orderkey", "l_suppkey", "l_extendedprice",
                            "l_discount"],
               "supplier": ["s_suppkey", "s_nationkey"]},
    }[name]
    total = 0
    for table, names in cols.items():
        for p in _paths(data_dir, table):
            md = papq.ParquetFile(p).metadata
            for rg in range(md.num_row_groups):
                g = md.row_group(rg)
                for ci in range(g.num_columns):
                    c = g.column(ci)
                    leaf = c.path_in_schema.split(".")[0]
                    if leaf in names:
                        total += c.total_uncompressed_size
    return total
