"""TPC-H-like workload: parquet data generation + q1/q6/q3/q5 DataFrames.

The reference ships TPC-H query definitions (integration_tests/.../tests/
tpch/TpchLikeSpark.scala) and a bench harness (common/BenchUtils.scala:
39-300). This module is the TPU build's analog: a numpy-vectorized dbgen
stand-in writing multi-file parquet tables (so scans parallelize), the four
BASELINE.md target queries expressed through the DataFrame API, and a
pandas implementation of each query used both as the CPU baseline and as an
independent result check.

Distributions approximate dbgen (uniform where dbgen is uniform; the exact
text columns the queries never touch are omitted) — benchmark-faithful, not
audit-grade TPC-H.
"""

from __future__ import annotations

import datetime
import json
import os
from typing import Dict, List

import numpy as np

import pyarrow as pa
import pyarrow.parquet as papq

_EPOCH = datetime.date(1970, 1, 1)


def days(date_str: str) -> int:
    """'YYYY-MM-DD' -> days since epoch (Spark DateType physical value)."""
    y, m, d = map(int, date_str.split("-"))
    return (datetime.date(y, m, d) - _EPOCH).days


SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]


def _write_parts(table: pa.Table, out_dir: str, n_files: int):
    os.makedirs(out_dir, exist_ok=True)
    n = table.num_rows
    per = max(1, -(-n // n_files))
    for i in range(n_files):
        part = table.slice(i * per, per)
        if part.num_rows == 0 and i > 0:
            break
        papq.write_table(part, os.path.join(out_dir, f"part-{i:03d}.parquet"),
                         compression="snappy")


def generate(data_dir: str, scale: float = 1.0, files_per_table: int = 8,
             seed: int = 0, force: bool = False) -> Dict[str, int]:
    """Generate the TPC-H-like dataset (idempotent via a manifest)."""
    manifest_path = os.path.join(data_dir, "manifest.json")
    want = {"scale": scale, "files": files_per_table, "seed": seed,
            "version": 3}
    if not force and os.path.exists(manifest_path):
        with open(manifest_path) as f:
            have = json.load(f)
        if all(have.get(k) == v for k, v in want.items()):
            return have["rows"]
    rng = np.random.default_rng(seed)
    n_ord = max(int(1_500_000 * scale), 10)
    n_cust = max(int(150_000 * scale), 5)
    n_supp = max(int(10_000 * scale), 3)

    # -- orders -------------------------------------------------------------
    o_orderkey = np.arange(1, n_ord + 1, dtype=np.int64)
    o_custkey = rng.integers(1, n_cust + 1, n_ord, dtype=np.int64)
    lo, hi = days("1992-01-01"), days("1998-08-02")
    o_orderdate = rng.integers(lo, hi, n_ord, dtype=np.int64).astype(np.int32)
    o_shippriority = np.zeros(n_ord, dtype=np.int32)
    orders = pa.table({
        "o_orderkey": o_orderkey,
        "o_custkey": o_custkey,
        "o_orderdate": pa.array(o_orderdate, pa.int32()).cast(pa.date32()),
        "o_shippriority": o_shippriority,
        "o_totalprice": np.round(rng.uniform(900.0, 500_000.0, n_ord), 2),
    })

    # -- lineitem: 1..7 lines per order (dbgen's cardinality shape) ---------
    per_order = rng.integers(1, 8, n_ord)
    l_orderkey = np.repeat(o_orderkey, per_order)
    l_orderdate = np.repeat(o_orderdate, per_order)
    n_li = len(l_orderkey)
    linenumber = (np.arange(n_li, dtype=np.int64)
                  - np.repeat(np.cumsum(per_order) - per_order, per_order)
                  + 1).astype(np.int32)
    l_quantity = rng.integers(1, 51, n_li).astype(np.float64)
    l_extendedprice = np.round(rng.uniform(900.0, 105_000.0, n_li), 2)
    l_discount = rng.integers(0, 11, n_li).astype(np.float64) / 100.0
    l_tax = rng.integers(0, 9, n_li).astype(np.float64) / 100.0
    l_shipdate = (l_orderdate.astype(np.int64)
                  + rng.integers(1, 122, n_li)).astype(np.int32)
    l_receiptdate = (l_shipdate.astype(np.int64)
                     + rng.integers(1, 31, n_li)).astype(np.int32)
    # returnflag: R/A for delivered-long-ago, N otherwise (dbgen's rule is
    # receiptdate-based; keep that correlation so q1 groups are realistic).
    cutoff = days("1995-06-17")
    ra = rng.integers(0, 2, n_li)
    l_returnflag = np.where(l_receiptdate <= cutoff,
                            np.where(ra == 0, "A", "R"), "N")
    l_linestatus = np.where(l_shipdate > days("1995-06-17"), "O", "F")
    l_suppkey = rng.integers(1, n_supp + 1, n_li, dtype=np.int64)
    lineitem = pa.table({
        "l_orderkey": l_orderkey,
        "l_linenumber": linenumber,
        "l_suppkey": l_suppkey,
        "l_quantity": l_quantity,
        "l_extendedprice": l_extendedprice,
        "l_discount": l_discount,
        "l_tax": l_tax,
        "l_returnflag": pa.array(l_returnflag.tolist(), pa.string()),
        "l_linestatus": pa.array(l_linestatus.tolist(), pa.string()),
        "l_shipdate": pa.array(l_shipdate, pa.int32()).cast(pa.date32()),
    })

    # -- customer / supplier / nation / region ------------------------------
    customer = pa.table({
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_nationkey": rng.integers(0, 25, n_cust, dtype=np.int64),
        "c_mktsegment": pa.array(
            [SEGMENTS[i] for i in rng.integers(0, 5, n_cust)], pa.string()),
    })
    supplier = pa.table({
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_nationkey": rng.integers(0, 25, n_supp, dtype=np.int64),
    })
    nation = pa.table({
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": pa.array([n for n, _ in NATIONS], pa.string()),
        "n_regionkey": np.array([r for _, r in NATIONS], dtype=np.int64),
    })
    region = pa.table({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": pa.array(REGIONS, pa.string()),
    })

    _write_parts(lineitem, os.path.join(data_dir, "lineitem"),
                 files_per_table)
    _write_parts(orders, os.path.join(data_dir, "orders"), files_per_table)
    _write_parts(customer, os.path.join(data_dir, "customer"),
                 max(files_per_table // 2, 1))
    _write_parts(supplier, os.path.join(data_dir, "supplier"), 1)
    _write_parts(nation, os.path.join(data_dir, "nation"), 1)
    _write_parts(region, os.path.join(data_dir, "region"), 1)
    rows = {"lineitem": n_li, "orders": n_ord, "customer": n_cust,
            "supplier": n_supp, "nation": 25, "region": 5}
    with open(manifest_path, "w") as f:
        json.dump({**want, "rows": rows}, f)
    return rows


def _paths(data_dir: str, table: str) -> List[str]:
    d = os.path.join(data_dir, table)
    return sorted(os.path.join(d, f) for f in os.listdir(d)
                  if f.endswith(".parquet"))


def _read(session, data_dir: str, table: str):
    return session.read.parquet(*_paths(data_dir, table))


# ---------------------------------------------------------------------------
# Queries (TpchLikeSpark.scala Q1/Q6/Q3/Q5 analogs)
# ---------------------------------------------------------------------------

def q1(session, data_dir: str):
    """Pricing summary report: scan+filter+wide hash aggregate."""
    from spark_rapids_tpu.plan.logical import (
        agg_avg, agg_count, agg_sum, col, lit_col)
    li = _read(session, data_dir, "lineitem")
    disc = li.filter(col("l_shipdate") <= lit_col(days("1998-09-02"))) \
        .with_column("disc_price",
                     col("l_extendedprice") * (1.0 - col("l_discount"))) \
        .with_column("charge",
                     col("l_extendedprice") * (1.0 - col("l_discount"))
                     * (1.0 + col("l_tax")))
    return disc.group_by("l_returnflag", "l_linestatus").agg(
        agg_sum(col("l_quantity")).alias("sum_qty"),
        agg_sum(col("l_extendedprice")).alias("sum_base_price"),
        agg_sum(col("disc_price")).alias("sum_disc_price"),
        agg_sum(col("charge")).alias("sum_charge"),
        agg_avg(col("l_quantity")).alias("avg_qty"),
        agg_avg(col("l_extendedprice")).alias("avg_price"),
        agg_avg(col("l_discount")).alias("avg_disc"),
        agg_count().alias("count_order"),
    ).order_by("l_returnflag", "l_linestatus")


def q6(session, data_dir: str):
    """Forecasting revenue change: selective filter + global agg."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    li = _read(session, data_dir, "lineitem")
    f = li.filter(
        (col("l_shipdate") >= lit_col(days("1994-01-01")))
        & (col("l_shipdate") < lit_col(days("1995-01-01")))
        & (col("l_discount") >= 0.05) & (col("l_discount") <= 0.07)
        & (col("l_quantity") < 24.0))
    return f.agg(agg_sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue"))


def q3(session, data_dir: str):
    """Shipping priority: two joins + agg + top-10 by revenue."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    cust = _read(session, data_dir, "customer") \
        .filter(col("c_mktsegment") == lit_col("BUILDING")) \
        .select("c_custkey")
    orders = _read(session, data_dir, "orders") \
        .filter(col("o_orderdate") < lit_col(days("1995-03-15"))) \
        .select("o_orderkey", "o_custkey", "o_orderdate", "o_shippriority")
    li = _read(session, data_dir, "lineitem") \
        .filter(col("l_shipdate") > lit_col(days("1995-03-15"))) \
        .select("l_orderkey", "l_extendedprice", "l_discount")
    co = orders.join_on(cust, ["o_custkey"], ["c_custkey"])
    j = li.join_on(co, ["l_orderkey"], ["o_orderkey"])
    return j.group_by("l_orderkey", "o_orderdate", "o_shippriority").agg(
        agg_sum(col("l_extendedprice") * (1.0 - col("l_discount")))
        .alias("revenue")
    ).order_by(col("revenue").desc(), col("o_orderdate").asc()) \
        .limit(10)


def q5(session, data_dir: str):
    """Local supplier volume: 5-way join + agg ordered by revenue."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    region = _read(session, data_dir, "region") \
        .filter(col("r_name") == lit_col("ASIA"))
    nation = _read(session, data_dir, "nation")
    nat = nation.join_on(region, ["n_regionkey"], ["r_regionkey"]) \
        .select("n_nationkey", "n_name")
    cust = _read(session, data_dir, "customer") \
        .join_on(nat, ["c_nationkey"], ["n_nationkey"]) \
        .select("c_custkey", "c_nationkey", "n_name")
    orders = _read(session, data_dir, "orders") \
        .filter((col("o_orderdate") >= lit_col(days("1994-01-01")))
                & (col("o_orderdate") < lit_col(days("1995-01-01")))) \
        .select("o_orderkey", "o_custkey")
    co = orders.join_on(cust, ["o_custkey"], ["c_custkey"]) \
        .select("o_orderkey", "c_nationkey", "n_name")
    li = _read(session, data_dir, "lineitem") \
        .select("l_orderkey", "l_suppkey", "l_extendedprice", "l_discount")
    j = li.join_on(co, ["l_orderkey"], ["o_orderkey"])
    supp = _read(session, data_dir, "supplier")
    j2 = j.join_on(supp, ["l_suppkey", "c_nationkey"],
                   ["s_suppkey", "s_nationkey"])
    return j2.group_by("n_name").agg(
        agg_sum(col("l_extendedprice") * (1.0 - col("l_discount")))
        .alias("revenue")
    ).order_by(col("revenue").desc())


QUERIES = {"q1": q1, "q6": q6, "q3": q3, "q5": q5}


# ---------------------------------------------------------------------------
# Pandas baseline (the CPU engine the bench compares against)
# ---------------------------------------------------------------------------

def pandas_query(name: str, data_dir: str):
    """Run query ``name`` with pandas/pyarrow — a genuine multi-threaded
    CPU columnar engine, standing in for BASELINE.md's 'CPU Spark' side
    (docs/FAQ.md:60-66 speedup claims). Returns a list of row tuples in
    the same column order as the DataFrame version."""
    import pandas as pd

    def read(table, columns):
        return pa.concat_tables(
            [papq.read_table(p, columns=columns)
             for p in _paths(data_dir, table)]).to_pandas()

    if name == "q1":
        li = read("lineitem", ["l_quantity", "l_extendedprice",
                               "l_discount", "l_tax", "l_returnflag",
                               "l_linestatus", "l_shipdate"])
        li = li[li.l_shipdate <= datetime.date(1998, 9, 2)]
        li["disc_price"] = li.l_extendedprice * (1.0 - li.l_discount)
        li["charge"] = li.disc_price * (1.0 + li.l_tax)
        g = li.groupby(["l_returnflag", "l_linestatus"], sort=True).agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_quantity", "size"),
        ).reset_index()
        return [tuple(r) for r in g.itertuples(index=False)]
    if name == "q6":
        li = read("lineitem", ["l_shipdate", "l_discount", "l_quantity",
                               "l_extendedprice"])
        m = ((li.l_shipdate >= datetime.date(1994, 1, 1))
             & (li.l_shipdate < datetime.date(1995, 1, 1))
             & (li.l_discount >= 0.05) & (li.l_discount <= 0.07)
             & (li.l_quantity < 24.0))
        return [(float((li.l_extendedprice[m] * li.l_discount[m]).sum()),)]
    if name == "q3":
        cust = read("customer", ["c_custkey", "c_mktsegment"])
        cust = cust[cust.c_mktsegment == "BUILDING"][["c_custkey"]]
        orders = read("orders", ["o_orderkey", "o_custkey", "o_orderdate",
                                 "o_shippriority"])
        orders = orders[orders.o_orderdate < datetime.date(1995, 3, 15)]
        li = read("lineitem", ["l_orderkey", "l_extendedprice",
                               "l_discount", "l_shipdate"])
        li = li[li.l_shipdate > datetime.date(1995, 3, 15)]
        co = orders.merge(cust, left_on="o_custkey", right_on="c_custkey")
        j = li.merge(co, left_on="l_orderkey", right_on="o_orderkey")
        j["revenue"] = j.l_extendedprice * (1.0 - j.l_discount)
        g = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"]) \
            .agg(revenue=("revenue", "sum")).reset_index()
        g = g.sort_values(["revenue", "o_orderdate"],
                          ascending=[False, True]).head(10)
        out = g[["l_orderkey", "o_orderdate", "o_shippriority", "revenue"]]
        return [tuple(r) for r in out.itertuples(index=False)]
    if name == "q5":
        region = read("region", ["r_regionkey", "r_name"])
        region = region[region.r_name == "ASIA"]
        nation = read("nation", ["n_nationkey", "n_name", "n_regionkey"])
        nat = nation.merge(region, left_on="n_regionkey",
                           right_on="r_regionkey")
        cust = read("customer", ["c_custkey", "c_nationkey"])
        cust = cust.merge(nat, left_on="c_nationkey",
                          right_on="n_nationkey")
        orders = read("orders", ["o_orderkey", "o_custkey", "o_orderdate"])
        orders = orders[(orders.o_orderdate >= datetime.date(1994, 1, 1))
                        & (orders.o_orderdate < datetime.date(1995, 1, 1))]
        co = orders.merge(cust, left_on="o_custkey", right_on="c_custkey")
        li = read("lineitem", ["l_orderkey", "l_suppkey",
                               "l_extendedprice", "l_discount"])
        j = li.merge(co[["o_orderkey", "c_nationkey", "n_name"]],
                     left_on="l_orderkey", right_on="o_orderkey")
        supp = read("supplier", ["s_suppkey", "s_nationkey"])
        j = j.merge(supp, left_on=["l_suppkey", "c_nationkey"],
                    right_on=["s_suppkey", "s_nationkey"])
        j["revenue"] = j.l_extendedprice * (1.0 - j.l_discount)
        g = j.groupby("n_name").agg(revenue=("revenue", "sum")) \
            .reset_index().sort_values("revenue", ascending=False)
        return [tuple(r) for r in g.itertuples(index=False)]
    raise KeyError(name)


def rows_close(a, b, rel: float = 1e-6) -> bool:
    """Shared row-list comparator (BenchUtils.compareResults analog):
    float epsilon compare, pandas dates normalized to days-since-epoch."""
    import math
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, datetime.date):
                va = (va - _EPOCH).days
            if isinstance(vb, datetime.date):
                vb = (vb - _EPOCH).days
            if isinstance(va, float) or isinstance(vb, float):
                if not math.isclose(float(va), float(vb), rel_tol=rel,
                                    abs_tol=1e-9):
                    return False
            elif va != vb:
                return False
    return True


def check_result(name: str, got, want) -> bool:
    """Compare a device result against the pandas result for query
    ``name`` (q5's revenue-desc output has unordered ties)."""
    if name == "q5":
        return rows_close(sorted(got), sorted(want))
    return rows_close(got, want)


def bytes_scanned(name: str, data_dir: str) -> int:
    """Uncompressed bytes of the pruned columns each query reads — the
    numerator of the bytes/s (bandwidth-utilization) bench metric."""
    cols = {
        "q1": {"lineitem": ["l_quantity", "l_extendedprice", "l_discount",
                            "l_tax", "l_returnflag", "l_linestatus",
                            "l_shipdate"]},
        "q6": {"lineitem": ["l_shipdate", "l_discount", "l_quantity",
                            "l_extendedprice"]},
        "q3": {"customer": ["c_custkey", "c_mktsegment"],
               "orders": ["o_orderkey", "o_custkey", "o_orderdate",
                          "o_shippriority"],
               "lineitem": ["l_orderkey", "l_extendedprice", "l_discount",
                            "l_shipdate"]},
        "q5": {"region": ["r_regionkey", "r_name"],
               "nation": ["n_nationkey", "n_name", "n_regionkey"],
               "customer": ["c_custkey", "c_nationkey"],
               "orders": ["o_orderkey", "o_custkey", "o_orderdate"],
               "lineitem": ["l_orderkey", "l_suppkey", "l_extendedprice",
                            "l_discount"],
               "supplier": ["s_suppkey", "s_nationkey"]},
    }[name]
    total = 0
    for table, names in cols.items():
        for p in _paths(data_dir, table):
            md = papq.ParquetFile(p).metadata
            for rg in range(md.num_row_groups):
                g = md.row_group(rg)
                for ci in range(g.num_columns):
                    c = g.column(ci)
                    leaf = c.path_in_schema.split(".")[0]
                    if leaf in names:
                        total += c.total_uncompressed_size
    return total
