"""Generalized oracle result comparison (BenchUtils.compareResults
analog — BenchUtils.scala's sorted/epsilon compare, ISSUE 5 satellite).

One comparator for every harness that checks engine output against an
oracle (bench.py, tests/test_suites.py, tests/test_tpch*.py, the
scheduler's bit-identity tests): dtype-aware epsilon on floats, date
normalization, None-aware exact compare on everything else, and an
optional type-aware row sort for queries whose ORDER BY is computed from
epsilon-different floats (the two engines may legitimately order such
rows differently, so only the row SET is comparable).

Replaces the hand-rolled per-query ``check_result`` comparisons that
used bare ``sorted(...)`` (which throws on None and mixed types) —
``tests/harness.py`` re-exports these helpers for test use.
"""

from __future__ import annotations

import datetime
import math
from typing import Sequence

_EPOCH = datetime.date(1970, 1, 1)


def sort_key(row: Sequence) -> tuple:
    """Total order over heterogeneous rows: None sorts first within a
    column, then by type name (so int/str mixes never raise), then by
    value — deterministic for any oracle row set."""
    return tuple((v is None, str(type(v)), v if v is not None else 0)
                 for v in row)


def values_close(va, vb, rel_tol: float = 1e-6,
                 abs_tol: float = 1e-9) -> bool:
    """Dtype-aware scalar compare: dates normalize to days-since-epoch
    (pandas oracles yield datetime.date, the engine yields ints), floats
    compare with relative+absolute epsilon (NaN == NaN — an oracle
    emitting NaN means the engine must too), everything else exactly."""
    if va is None or vb is None:
        return va is None and vb is None
    if isinstance(va, datetime.date):
        va = (va - _EPOCH).days
    if isinstance(vb, datetime.date):
        vb = (vb - _EPOCH).days
    if isinstance(va, float) or isinstance(vb, float):
        fa, fb = float(va), float(vb)
        if math.isnan(fa) or math.isnan(fb):
            return math.isnan(fa) and math.isnan(fb)
        return math.isclose(fa, fb, rel_tol=rel_tol, abs_tol=abs_tol)
    return va == vb


def compare_results(got, want, sort: bool = False,
                    rel_tol: float = 1e-6,
                    abs_tol: float = 1e-9) -> bool:
    """Row-list compare. ``sort=True`` compares the row SETS under the
    type-aware total order (for computed-float ORDER BY); default keeps
    order significant (ORDER BY included in the contract)."""
    if len(got) != len(want):
        return False
    if sort:
        got = sorted(got, key=sort_key)
        want = sorted(want, key=sort_key)
    for ra, rb in zip(got, want):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if not values_close(va, vb, rel_tol, abs_tol):
                return False
    return True


def first_mismatch(got, want, sort: bool = False,
                   rel_tol: float = 1e-6, abs_tol: float = 1e-9):
    """(row, col, got_value, want_value) of the first divergence, or a
    (row-count) tuple when lengths differ, or None when equal — the
    assertion-message half of the harness."""
    if len(got) != len(want):
        return ("rows", len(got), len(want))
    if sort:
        got = sorted(got, key=sort_key)
        want = sorted(want, key=sort_key)
    for r, (ra, rb) in enumerate(zip(got, want)):
        if len(ra) != len(rb):
            return (r, "width", len(ra), len(rb))
        for c, (va, vb) in enumerate(zip(ra, rb)):
            if not values_close(va, vb, rel_tol, abs_tol):
                return (r, c, va, vb)
    return None
