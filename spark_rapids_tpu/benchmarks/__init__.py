from spark_rapids_tpu.benchmarks import tpch  # noqa: F401
