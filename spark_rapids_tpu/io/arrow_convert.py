"""Arrow <-> HostBatch bridge.

The reference decodes file formats on the GPU through libcudf
(Table.readParquet etc.). On TPU there is no device decoder (SURVEY.md §7
hard-part #7 calls an on-device parquet decoder a stretch goal), so the
design follows the reference's CPU-footer/accelerator-decode split as far
as the platform allows: pyarrow does the host decode (columnar, vectorized
C++), the columns convert zero-ish-copy into HostBatch numpy arrays, and
one contiguous H2D upload per buffer puts them in HBM
(GpuParquetScan.scala's HostMemoryBuffer -> Table.readParquet hand-off).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn

import pyarrow as pa


_ARROW_TO_DT = {
    pa.bool_(): dt.BOOL,
    pa.int8(): dt.INT8,
    pa.int16(): dt.INT16,
    pa.int32(): dt.INT32,
    pa.int64(): dt.INT64,
    pa.float32(): dt.FLOAT32,
    pa.float64(): dt.FLOAT64,
    pa.date32(): dt.DATE,
    pa.string(): dt.STRING,
    pa.large_string(): dt.STRING,
    pa.binary(): dt.STRING,
}


def arrow_type_to_dt(t: pa.DataType) -> DataType:
    if t in _ARROW_TO_DT:
        return _ARROW_TO_DT[t]
    if pa.types.is_timestamp(t):
        return dt.TIMESTAMP
    if pa.types.is_dictionary(t):
        return arrow_type_to_dt(t.value_type)
    raise TypeError(f"unsupported arrow type {t} "
                    "(supported: bool/int/float/date/timestamp/string)")


def dt_to_arrow_type(t: DataType) -> pa.DataType:
    if t.is_string:
        return pa.string()
    if t.name == "date":
        return pa.date32()
    if t.name == "timestamp":
        return pa.timestamp("us", tz="UTC")
    return pa.from_numpy_dtype(t.np_dtype)


def schema_from_arrow(sch: pa.Schema) -> Tuple[Tuple[str, DataType], ...]:
    return tuple((f.name, arrow_type_to_dt(f.type)) for f in sch)


def arrow_to_host_batch(table: "pa.Table",
                        schema: Optional[Sequence] = None) -> HostBatch:
    """One arrow table/record-batch -> HostBatch."""
    if isinstance(table, pa.RecordBatch):
        table = pa.Table.from_batches([table])
    table = table.combine_chunks()
    names = []
    cols: List[HostColumn] = []
    for ci, field in enumerate(table.schema):
        t = arrow_type_to_dt(field.type)
        arr = table.column(ci)
        chunk = arr.chunk(0) if arr.num_chunks else pa.array(
            [], type=field.type)
        if pa.types.is_dictionary(chunk.type):
            chunk = chunk.dictionary_decode()
        n = len(chunk)
        validity = np.asarray(chunk.is_valid())
        if t.is_string:
            m, lens = _arrow_strings_to_matrix(chunk, validity)
            names.append(field.name)
            cols.append(HostColumn(t, None, validity,
                                   str_matrix=m, str_lengths=lens))
            continue
        elif t.name == "timestamp":
            # Arrow timestamps may be s/ms/us/ns; normalize to us.
            c = chunk.cast(pa.timestamp("us"))
            data = np.asarray(c.cast(pa.int64()).fill_null(0),
                              dtype=np.int64)
        elif t.name == "date":
            data = np.asarray(chunk.cast(pa.int32()).fill_null(0),
                              dtype=np.int32)
        else:
            data = np.asarray(chunk.fill_null(0)).astype(t.np_dtype)
        names.append(field.name)
        cols.append(HostColumn(t, data, validity))
    return HostBatch(tuple(names), cols)


def _arrow_strings_to_matrix(chunk, validity: np.ndarray):
    """Vectorized arrow string array -> ((n, w) uint8 matrix, int32 lens):
    index math over the offsets+data buffers, no per-row python loop (the
    host-decode half of GpuParquetScan's string path, numpy-vectorized)."""
    n = len(chunk)
    if n == 0:
        return np.zeros((0, 1), np.uint8), np.zeros(0, np.int32)
    if pa.types.is_large_string(chunk.type) or \
            pa.types.is_large_binary(chunk.type):
        off_dt = np.int64
    else:
        off_dt = np.int32
    bufs = chunk.buffers()
    isz = np.dtype(off_dt).itemsize
    offs = np.frombuffer(bufs[1], dtype=off_dt, count=n + 1,
                         offset=chunk.offset * isz).astype(np.int64)
    blob = (np.frombuffer(bufs[2], dtype=np.uint8)
            if bufs[2] is not None else np.zeros(0, np.uint8))
    starts = offs[:-1]
    lens = (offs[1:] - starts).astype(np.int32)
    lens = np.where(validity, lens, 0).astype(np.int32)
    w = max(int(lens.max()), 1)
    pos = np.arange(w, dtype=np.int64)[None, :]
    mask = pos < lens[:, None]
    idx = np.where(mask, starts[:, None] + pos, 0)
    m = (blob[idx] if blob.size else
         np.zeros((n, w), np.uint8)) * mask.astype(np.uint8)
    return np.ascontiguousarray(m, dtype=np.uint8), lens


def host_batch_to_arrow(hb: HostBatch) -> "pa.Table":
    arrays = []
    fields = []
    for name, c in zip(hb.names, hb.columns):
        at = dt_to_arrow_type(c.dtype)
        vals = c.to_list()
        if c.dtype.name == "timestamp":
            arr = pa.array(
                [None if v is None else int(v) for v in
                 _raw_vals(c)], type=pa.int64()).cast(at)
        elif c.dtype.name == "date":
            arr = pa.array(
                [None if v is None else int(v) for v in _raw_vals(c)],
                type=pa.int32()).cast(at)
        else:
            arr = pa.array(vals, type=at)
        arrays.append(arr)
        fields.append(pa.field(name, at))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def _raw_vals(c: HostColumn):
    out = []
    for i in range(c.num_rows):
        out.append(None if not c.validity[i] else c.data[i])
    return out
