"""File scans: parquet / ORC / CSV (ref: GpuParquetScan.scala:84,
GpuOrcScan.scala, GpuBatchScanExec.scala CSV path).

Reader strategies mirror RapidsConf's
``spark.rapids.sql.format.parquet.reader.type`` (RapidsConf.scala:510):
- PERFILE: open and decode one file at a time, upload per batch.
- MULTITHREADED: a host thread pool prefetches+decodes files in the
  background while the device consumes earlier ones — the
  MultiFileCloudParquetPartitionReader overlap (GpuParquetScan.scala:1144).
- COALESCING: decode several files and concatenate their rows into fewer,
  larger device batches (MultiFileParquetPartitionReader:823's
  stitch-row-groups idea at the arrow level).
- AUTO: MULTITHREADED (the cloud default heuristic).

Partitioning: files are distributed round-robin over N partitions
(one Spark task per file-chunk analog). Row-group-level splits are handled
inside pyarrow's batch iteration.
"""

from __future__ import annotations

import concurrent.futures
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch, host_to_device
from spark_rapids_tpu.ops.base import Exec, ExecContext, LeafExec, Schema, \
    timed
from spark_rapids_tpu.io.arrow_convert import (
    arrow_to_host_batch, schema_from_arrow)

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq


def infer_schema(fmt: str, paths: Sequence[str], options: Dict) -> Schema:
    """Footer/header-only schema inference (CPU-side footer parse, the
    GpuParquetScan footer-on-CPU half)."""
    path = paths[0]
    if fmt == "parquet":
        return schema_from_arrow(papq.ParquetFile(path).schema_arrow)
    if fmt == "orc":
        return schema_from_arrow(paorc.ORCFile(path).schema)
    if fmt == "csv":
        # Stream only the first block to infer types (no full-file parse).
        read_opts = _csv_read_options(options, sample=True)
        with pacsv.open_csv(path, **read_opts) as reader:
            return schema_from_arrow(reader.schema)
    raise ValueError(f"unknown format {fmt}")


def _csv_read_options(options: Dict, sample: bool = False):
    kwargs = {}
    parse = pacsv.ParseOptions(
        delimiter=options.get("sep", options.get("delimiter", ",")))
    has_header = str(options.get("header", "true")).lower() in (
        "true", "1", "yes")
    read_kwargs = {"autogenerate_column_names": not has_header}
    if sample:
        read_kwargs["block_size"] = 1 << 20   # schema from first 1MB only
    kwargs["parse_options"] = parse
    kwargs["read_options"] = pacsv.ReadOptions(**read_kwargs)
    return kwargs


def _read_file_batches(fmt: str, path: str, options: Dict,
                       batch_rows: int,
                       columns: Optional[List[str]] = None
                       ) -> Iterator[HostBatch]:
    """Decode one file; ``columns`` restricts the read to a pruned schema
    (GpuParquetScan readDataSchema analog — unread columns are never
    decoded)."""
    if fmt == "parquet":
        pf = papq.ParquetFile(path)
        for rb in pf.iter_batches(batch_size=batch_rows, columns=columns):
            yield arrow_to_host_batch(rb)
    elif fmt == "orc":
        f = paorc.ORCFile(path)
        for si in range(f.nstripes):
            yield arrow_to_host_batch(f.read_stripe(si, columns=columns))
    elif fmt == "csv":
        kwargs = _csv_read_options(options)
        if columns:
            kwargs["convert_options"] = pacsv.ConvertOptions(
                include_columns=list(columns))
        tbl = pacsv.read_csv(path, **kwargs)
        for rb in tbl.to_batches(max_chunksize=batch_rows):
            yield arrow_to_host_batch(rb)
    else:
        raise ValueError(fmt)


class FileScanExec(LeafExec):
    """Leaf scan over N files in a format, with reader strategies."""

    def __init__(self, fmt: str, paths: Sequence[str], schema: Schema,
                 options: Optional[Dict] = None,
                 num_partitions: Optional[int] = None,
                 force_perfile: bool = False):
        super().__init__()
        self.fmt = fmt
        self.paths = list(paths)
        self._schema = tuple(schema)
        self.options = dict(options or {})
        self._columns = [n for n, _ in self._schema]
        self._parts = num_partitions or min(len(self.paths), 8) or 1
        # input_file_name() in the plan: batches must not span files.
        self.force_perfile = force_perfile

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def name(self) -> str:
        return f"{type(self).__name__}[{self.fmt}]"

    def num_partitions(self, ctx) -> int:
        return self._parts

    def _files_of(self, partition: int) -> List[str]:
        return [p for i, p in enumerate(self.paths)
                if i % self._parts == partition]

    def _reader_type(self, ctx) -> str:
        if self.force_perfile:
            return "PERFILE"
        rt = str(ctx.conf.get(C.PARQUET_READER_TYPE)).upper()
        if rt == "AUTO":
            return "MULTITHREADED"
        return rt

    def _batch_rows(self, ctx) -> int:
        return int(ctx.conf.get(C.MAX_READER_BATCH_SIZE_ROWS))

    # -- host engine ---------------------------------------------------------
    def execute_host(self, ctx, partition):
        rows = self._batch_rows(ctx)
        for path in self._files_of(partition):
            ctx.cache[f"input_file_host:{partition}"] = path
            yield from _read_file_batches(self.fmt, path, self.options,
                                          rows, self._columns)

    # -- device engine -------------------------------------------------------
    def execute_device(self, ctx, partition):
        m = ctx.metrics_for(self)
        rt = self._reader_type(ctx)
        rows = self._batch_rows(ctx)
        files = self._files_of(partition)
        if rt == "MULTITHREADED":
            yield from self._device_multithreaded(ctx, m, files, rows,
                                                  partition)
            return
        if rt == "COALESCING":
            yield from self._device_coalescing(ctx, m, files, rows)
            return
        for path in files:   # PERFILE
            # Publish the current file for input_file_name() downstream
            # (GpuInputFileBlock analog; per-batch, pre-yield).
            ctx.cache[f"input_file:{partition}"] = path
            for hb in _read_file_batches(self.fmt, path, self.options,
                                         rows, self._columns):
                with timed(m, "bufferTime"):
                    batch = host_to_device(hb)
                m.add("numOutputBatches", 1)
                yield batch

    def _device_multithreaded(self, ctx, m, files, rows, partition):
        """Background host decode overlapped with device consumption
        (MultiFileCloudParquetPartitionReader's thread-pool overlap)."""
        nthreads = int(ctx.conf.get(
            C.PARQUET_MULTITHREADED_READ_NUM_THREADS))
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(nthreads, max(len(files), 1))) as pool:
            futures = [
                pool.submit(lambda p=p: list(_read_file_batches(
                    self.fmt, p, self.options, rows, self._columns)))
                for p in files]
            for path, fut in zip(files, futures):
                ctx.cache[f"input_file:{partition}"] = path
                for hb in fut.result():
                    with timed(m, "bufferTime"):
                        batch = host_to_device(hb)
                    m.add("numOutputBatches", 1)
                    yield batch

    def _device_coalescing(self, ctx, m, files, rows):
        """Concatenate small files' rows into fewer, larger uploads."""
        pending: List[HostBatch] = []
        pending_rows = 0
        for path in files:
            for hb in _read_file_batches(self.fmt, path, self.options,
                                         rows, self._columns):
                pending.append(hb)
                pending_rows += hb.num_rows
                if pending_rows >= rows:
                    yield self._upload_merged(m, pending)
                    pending, pending_rows = [], 0
        if pending:
            yield self._upload_merged(m, pending)

    def _upload_merged(self, m, hbs: List[HostBatch]):
        from spark_rapids_tpu.columnar.host import concat_host_batches
        merged = concat_host_batches(hbs)
        with timed(m, "bufferTime"):
            batch = host_to_device(merged)
        m.add("numOutputBatches", 1)
        return batch


def make_scan_exec(file_scan, conf, force_perfile: bool = False
                   ) -> FileScanExec:
    """Planner hook for L.FileScan nodes."""
    return FileScanExec(file_scan.fmt, file_scan.paths,
                        file_scan.source_schema, file_scan.options,
                        force_perfile=force_perfile)
