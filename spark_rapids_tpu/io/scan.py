"""File scans: parquet / ORC / CSV (ref: GpuParquetScan.scala:84,
GpuOrcScan.scala, GpuBatchScanExec.scala CSV path).

Reader strategies mirror RapidsConf's
``spark.rapids.sql.format.parquet.reader.type`` (RapidsConf.scala:510):
- PERFILE: open and decode one file at a time, upload per batch.
- MULTITHREADED: a host thread pool prefetches+decodes files in the
  background while the device consumes earlier ones — the
  MultiFileCloudParquetPartitionReader overlap (GpuParquetScan.scala:1144).
- COALESCING: decode several units and concatenate their rows into fewer,
  larger device batches (MultiFileParquetPartitionReader:823's
  stitch-row-groups idea at the arrow level — small row groups from MANY
  files merge into one upload).
- AUTO: MULTITHREADED (the cloud default heuristic).

Partitioning is at **scan-unit** granularity: a unit is one parquet row
group / one ORC stripe / one CSV file (the footer parse that enumerates
them is CPU-side, exactly the reference's split — GpuParquetScan.scala:823
``populateCurrentBlockChunk``). Units are dealt round-robin over N
partitions, so one big parquet file parallelizes across partitions
instead of becoming a single giant host decode.

Predicate pushdown: pushed conjuncts (plan/pruning.pushdown_filters) are
checked against per-row-group min/max/null statistics; units whose stats
prove no row can match are skipped without reading data bytes
(GpuParquetScan filter pushdown / OrcFilters.scala analog).
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import config as C
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch, host_to_device
from spark_rapids_tpu.ops.base import Exec, ExecContext, LeafExec, Schema, \
    record_batch, timed
from spark_rapids_tpu.io.arrow_convert import (
    arrow_to_host_batch, schema_from_arrow)

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq


def infer_schema(fmt: str, paths: Sequence[str], options: Dict) -> Schema:
    """Footer/header-only schema inference (CPU-side footer parse, the
    GpuParquetScan footer-on-CPU half)."""
    path = paths[0]
    if fmt == "parquet":
        return schema_from_arrow(papq.ParquetFile(path).schema_arrow)
    if fmt == "orc":
        return schema_from_arrow(paorc.ORCFile(path).schema)
    if fmt == "csv":
        # Stream only the first block to infer types (no full-file parse).
        read_opts = _csv_read_options(options, sample=True)
        with pacsv.open_csv(path, **read_opts) as reader:
            return schema_from_arrow(reader.schema)
    raise ValueError(f"unknown format {fmt}")


def _csv_read_options(options: Dict, sample: bool = False):
    kwargs = {}
    parse = pacsv.ParseOptions(
        delimiter=options.get("sep", options.get("delimiter", ",")))
    has_header = str(options.get("header", "true")).lower() in (
        "true", "1", "yes")
    read_kwargs = {"autogenerate_column_names": not has_header}
    if sample:
        read_kwargs["block_size"] = 1 << 20   # schema from first 1MB only
    kwargs["parse_options"] = parse
    kwargs["read_options"] = pacsv.ReadOptions(**read_kwargs)
    return kwargs


@dataclasses.dataclass(frozen=True)
class ScanUnit:
    """One independently-readable slice of a file: a parquet row group,
    an ORC stripe, or a whole CSV file (``index is None``)."""

    path: str
    index: Optional[int]        # row group / stripe ordinal
    rows: int                   # 0 = unknown (csv)


# (path, mtime, size) -> parquet FileMetaData; footer parses are cheap but
# repeated across planning + N partitions, so memoize. Bounded: inserting a
# new entry evicts stale entries for the same path (rewritten files), and
# the whole cache is FIFO-capped so long sessions don't leak FileMetaData.
# Locked: pipeline prefetch threads probe partitions concurrently.
_PQ_META_CACHE: Dict[Tuple[str, float, int], Any] = {}
_PQ_META_CACHE_MAX = 1024
_PQ_META_LOCK = threading.Lock()


def _parquet_metadata(path: str):
    st = os.stat(path)
    key = (path, st.st_mtime, st.st_size)
    with _PQ_META_LOCK:
        md = _PQ_META_CACHE.get(key)
    if md is None:
        md = papq.ParquetFile(path).metadata
        with _PQ_META_LOCK:
            for stale in [k for k in _PQ_META_CACHE if k[0] == path]:
                del _PQ_META_CACHE[stale]
            while len(_PQ_META_CACHE) >= _PQ_META_CACHE_MAX:
                _PQ_META_CACHE.pop(next(iter(_PQ_META_CACHE)))
            _PQ_META_CACHE[key] = md
    return md


def enumerate_units(fmt: str, paths: Sequence[str]) -> List[ScanUnit]:
    """CPU-side footer/tail parse producing the scan's split units
    (GpuParquetScan.scala:823 block enumeration analog)."""
    units: List[ScanUnit] = []
    for path in paths:
        if fmt == "parquet":
            md = _parquet_metadata(path)
            for rg in range(md.num_row_groups):
                units.append(ScanUnit(path, rg, md.row_group(rg).num_rows))
        elif fmt == "orc":
            f = paorc.ORCFile(path)
            for si in range(f.nstripes):
                units.append(ScanUnit(path, si, 0))
        else:
            units.append(ScanUnit(path, None, 0))
    return units


# ORC stripe stats index (OrcFilters.scala:206 pushdown analog): pyarrow
# exposes no ORC column statistics, so the engine builds its own per-
# stripe min/max/null index on FIRST contact with a stripe (one decode of
# the predicate columns) and prunes every later scan from the cache.
# (stripe_key) -> {col: (min, max, null_count, rows)}. A true LRU:
# hits move-to-end, and eviction happens only when a genuinely NEW key
# is inserted at capacity — warm stripes survive a full cache, instead
# of FIFO-evicting the entries the workload keeps probing.
_ORC_STATS_CACHE: "OrderedDict[Tuple, Dict[str, tuple]]" = OrderedDict()
_ORC_STATS_CACHE_MAX = 4096
_ORC_STATS_LOCK = threading.Lock()


class _Stat:
    """Duck-typed stand-in for a parquet ColumnChunk statistics object."""

    def __init__(self, mn, mx, null_count):
        self.min, self.max = mn, mx
        self.null_count = null_count
        self.has_min_max = mn is not None


def _orc_stripe_stats(unit: ScanUnit, names: Sequence[str]
                      ) -> Tuple[Dict[str, "_Stat"], int]:
    """(per-column stats, stripe row count). Columns missing from the
    file cache a no-stats sentinel so they are never re-probed.
    Serialized by a lock: pipeline prefetch threads prune partitions
    concurrently and an OrderedDict must never interleave mutations."""
    st = os.stat(unit.path)
    key = (unit.path, st.st_mtime, st.st_size, unit.index)
    with _ORC_STATS_LOCK:
        cached = _ORC_STATS_CACHE.get(key)
        if cached is not None:
            _ORC_STATS_CACHE.move_to_end(key)
            cached = dict(cached)
    need = [n for n in names
            if cached is None or n not in cached]
    if need:
        f = paorc.ORCFile(unit.path)
        have = set(f.schema.names)
        cols = [n for n in need if n in have]
        entry = dict(cached or {})
        if cols:
            tab = f.read_stripe(unit.index, columns=cols)
            for n in cols:
                c = tab.column(n)
                nulls = c.null_count
                if nulls == len(c):
                    entry[n] = (None, None, nulls, len(c))
                else:
                    import pyarrow.compute as pc
                    mm = pc.min_max(c).as_py()
                    entry[n] = (mm["min"], mm["max"], nulls, len(c))
        for n in need:
            if n not in entry:      # absent column: unknown-stats marker
                entry[n] = (None, None, None, -1)
        with _ORC_STATS_LOCK:
            resident = _ORC_STATS_CACHE.get(key)
            if resident is not None:
                # A concurrent prober filled other columns meanwhile:
                # merge instead of clobbering its work.
                entry = {**resident, **entry}
            elif key not in _ORC_STATS_CACHE:
                # Evict only for a genuinely new key (an update of a
                # resident key must never push out a warm neighbor),
                # oldest first.
                while len(_ORC_STATS_CACHE) >= _ORC_STATS_CACHE_MAX:
                    _ORC_STATS_CACHE.popitem(last=False)
            _ORC_STATS_CACHE[key] = entry
            _ORC_STATS_CACHE.move_to_end(key)
        cached = entry
    num_rows = max((rows for (_, _, _, rows) in cached.values()
                    if rows >= 0), default=0)
    return ({n: _Stat(mn, mx, nulls)
             for n, (mn, mx, nulls, rows) in cached.items()
             if rows >= 0}, num_rows)


def _unit_survives(fmt: str, unit: ScanUnit,
                   predicates: Sequence[Tuple[str, str, Any]]) -> bool:
    """False when unit statistics prove no row can satisfy ALL pushed
    conjuncts (conservative: missing/odd stats keep the unit). SQL null
    semantics make this safe — a comparison is never true for NULL, so
    bounds over non-null values suffice. Parquet reads footer stats; ORC
    uses the engine's own first-contact stripe index."""
    if not predicates or fmt == "csv":
        return True
    if fmt == "orc":
        stats_by_name, num_rows = _orc_stripe_stats(
            unit, [name for name, _, _ in predicates])
        return _stats_survive(stats_by_name, num_rows, predicates)
    rg = _parquet_metadata(unit.path).row_group(unit.index)
    stats_by_name = {}
    for ci in range(rg.num_columns):
        col = rg.column(ci)
        stats_by_name[col.path_in_schema] = col.statistics
    return _stats_survive(stats_by_name, rg.num_rows, predicates)


def _stats_survive(stats_by_name, num_rows,
                   predicates: Sequence[Tuple[str, str, Any]]) -> bool:
    for name, op, value in predicates:
        st = stats_by_name.get(name)
        if st is None:
            continue
        try:
            if op == "isnotnull":
                if st.null_count is not None and \
                        st.null_count == num_rows:
                    return False
                continue
            if not st.has_min_max:
                # All-null pages carry no min/max: a comparison predicate
                # can never be true then.
                if st.null_count is not None and \
                        st.null_count == num_rows:
                    return False
                continue
            mn, mx = st.min, st.max
            v = value.decode() if isinstance(value, bytes) else value
            mn = mn.decode() if isinstance(mn, bytes) else mn
            mx = mx.decode() if isinstance(mx, bytes) else mx
            if op == "eq" and (v < mn or v > mx):
                return False
            if op == "lt" and mn >= v:
                return False
            if op == "le" and mn > v:
                return False
            if op == "gt" and mx <= v:
                return False
            if op == "ge" and mx < v:
                return False
        except TypeError:
            continue    # incomparable stat/value types: keep the unit
    return True


def _read_unit_batches(fmt: str, unit: ScanUnit, options: Dict,
                       batch_rows: int,
                       columns: Optional[List[str]] = None
                       ) -> Iterator[HostBatch]:
    """Decode one scan unit; ``columns`` restricts the read to a pruned
    schema (GpuParquetScan readDataSchema analog — unread columns are
    never decoded)."""
    if fmt == "parquet":
        pf = papq.ParquetFile(unit.path)
        for rb in pf.iter_batches(batch_size=batch_rows,
                                  row_groups=[unit.index],
                                  columns=columns):
            yield arrow_to_host_batch(rb)
    elif fmt == "orc":
        f = paorc.ORCFile(unit.path)
        yield arrow_to_host_batch(
            f.read_stripe(unit.index, columns=columns))
    elif fmt == "csv":
        kwargs = _csv_read_options(options)
        if columns:
            kwargs["convert_options"] = pacsv.ConvertOptions(
                include_columns=list(columns))
        tbl = pacsv.read_csv(unit.path, **kwargs)
        for rb in tbl.to_batches(max_chunksize=batch_rows):
            yield arrow_to_host_batch(rb)
    else:
        raise ValueError(fmt)


class DeviceScanCache:
    """Transparent device-resident cache of decoded scan units.

    The TPU analog of keeping Spark's columnar cache on the accelerator
    (InMemoryTableScanExec handling, GpuTransitionOverrides.scala:339) at
    scan-unit granularity: a unit's decoded DeviceBatches stay in HBM,
    keyed by file identity (path, mtime, size), unit ordinal and the
    pruned column set, so a repeated query serves them without touching
    the host->device link (which, on a tunneled device, costs ~100ms per
    transfer call). LRU-evicted down to the configured byte budget;
    rewritten files miss naturally via the mtime/size key."""

    def __init__(self):
        self._entries: "dict" = {}     # key -> [DeviceBatch]
        self._bytes: Dict[Any, int] = {}
        self._total = 0
        # Probed/filled from pipeline prefetch threads and concurrent
        # consumers: LRU reorder + eviction accounting must be atomic.
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self._entries[key] = e     # move to MRU position
            return e

    def put(self, key, batches, budget: int):
        size = sum(b.device_size_bytes() for b in batches)
        if size > budget:
            return
        with self._lock:
            if key in self._entries:
                return                     # concurrent filler won
            while self._total + size > budget and self._entries:
                old_key = next(iter(self._entries))
                self._entries.pop(old_key)
                self._total -= self._bytes.pop(old_key)
            self._entries[key] = list(batches)
            self._bytes[key] = size
            self._total += size

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._bytes.clear()
            self._total = 0


DEVICE_SCAN_CACHE = DeviceScanCache()


class FileScanExec(LeafExec):
    """Leaf scan over N files in a format, with reader strategies.
    Splits at scan-unit (row-group/stripe) granularity and applies pushed
    predicates as row-group stats skips."""

    def __init__(self, fmt: str, paths: Sequence[str], schema: Schema,
                 options: Optional[Dict] = None,
                 num_partitions: Optional[int] = None,
                 force_perfile: bool = False,
                 predicates: Sequence[Tuple[str, str, Any]] = ()):
        super().__init__()
        self.fmt = fmt
        self.paths = list(paths)
        self._schema = tuple(schema)
        self.options = dict(options or {})
        self._columns = [n for n, _ in self._schema]
        self.predicates = tuple(predicates)
        self._opts_key = tuple(sorted((str(k), str(v))
                                      for k, v in self.options.items()))
        self._units = enumerate_units(fmt, self.paths)
        self._parts = num_partitions or min(len(self._units), 8) or 1
        # input_file_name() in the plan: batches must not span files.
        self.force_perfile = force_perfile

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def name(self) -> str:
        return f"{type(self).__name__}[{self.fmt}]"

    def num_partitions(self, ctx) -> int:
        return self._parts

    def _resolved_predicates(self, ctx) -> Tuple:
        """Pushed conjuncts with plan-cache bind slots resolved against
        THIS execution's binding vector (``ctx.cache['plan_binds']``).
        A slot predicate with no bindings in scope is dropped — stats
        skipping is an optimization; the filter above still runs."""
        from spark_rapids_tpu.exprs.bindslots import BindValue
        if not any(isinstance(v, BindValue)
                   for _, _, v in self.predicates):
            return self.predicates
        binds = None if ctx is None else ctx.cache.get("plan_binds")
        out = []
        for name, op, value in self.predicates:
            if isinstance(value, BindValue):
                if binds is None or value.slot >= len(binds):
                    continue
                value = binds[value.slot]
            out.append((name, op, value))
        return tuple(out)

    def _units_of(self, partition: int, m=None, ctx=None) -> List[ScanUnit]:
        """This partition's units, minus stats-skipped ones."""
        mine = [u for i, u in enumerate(self._units)
                if i % self._parts == partition]
        if not self.predicates:
            return mine
        predicates = self._resolved_predicates(ctx)
        if not predicates:
            return mine
        kept = [u for u in mine
                if _unit_survives(self.fmt, u, predicates)]
        if m is not None and len(kept) < len(mine):
            m.add("numSkippedRowGroups", len(mine) - len(kept))
        return kept

    def _reader_type(self, ctx) -> str:
        if self.force_perfile:
            return "PERFILE"
        entry = {"parquet": C.PARQUET_READER_TYPE,
                 "orc": C.ORC_READER_TYPE,
                 "csv": C.CSV_READER_TYPE}[self.fmt]
        rt = str(ctx.conf.get(entry)).upper()
        if rt == "AUTO":
            return "MULTITHREADED"
        return rt

    def _batch_rows(self, ctx) -> int:
        return int(ctx.conf.get(C.MAX_READER_BATCH_SIZE_ROWS))

    def _publish_input_file(self, ctx, partition: int, path: str,
                            host: bool = False) -> None:
        """Publish the current file for input_file_name() downstream
        (GpuInputFileBlock analog; per-unit, pre-yield). Keys are scoped to
        this scan instance so two scans sharing a partition (join of two
        reads) never clobber each other; the consumer resolves the key via
        its unique descendant scan (ops/basic.py)."""
        prefix = "input_file_host" if host else "input_file"
        ctx.cache[f"{prefix}:{id(self)}:{partition}"] = path

    # -- host engine ---------------------------------------------------------
    def execute_host(self, ctx, partition):
        rows = self._batch_rows(ctx)
        for unit in self._units_of(partition, ctx=ctx):
            self._publish_input_file(ctx, partition, unit.path, host=True)
            yield from _read_unit_batches(self.fmt, unit, self.options,
                                          rows, self._columns)

    # -- pipelined prefetch (parallel/pipeline.py) ---------------------------
    def host_prefetchable(self) -> bool:
        return True

    def _prefetch_key(self, partition: int) -> str:
        return f"scan-prefetch:{id(self):x}:{partition}"

    def prefetch_host(self, ctx, partition) -> None:
        """The separable host half of one partition: stats pruning, unit
        decode, wire encode AND staging-buffer pack — everything before
        ``device_put``. Runs on a pipeline prefetch thread; the payload
        lands in ``ctx.cache`` and the ordered consumer's
        :meth:`execute_device` pops it and only dispatches transfers.
        Payload entries are ``(unit, [EncodedBatch...])`` /
        ``(unit, "cached")`` for device-cache hits / ``(None, encs)``
        for COALESCING merges (which have no per-unit identity)."""
        from spark_rapids_tpu import faults
        from spark_rapids_tpu.columnar import wire
        from spark_rapids_tpu.columnar.host import concat_host_batches
        from spark_rapids_tpu.parallel import pipeline as PL
        m = ctx.metrics_for(self)
        rt = self._reader_type(ctx)
        rows = self._batch_rows(ctx)
        units = self._units_of(partition, m, ctx=ctx)
        budget = int(ctx.conf.get(C.SCAN_CACHE_BYTES))
        use_cache = budget > 0 and rt != "COALESCING"
        payload: List[tuple] = []
        if rt == "COALESCING":
            pending: List[HostBatch] = []
            pending_rows = 0
            for unit in units:
                faults.fault_point("scan")
                for hb in _read_unit_batches(self.fmt, unit, self.options,
                                             rows, self._columns):
                    pending.append(hb)
                    pending_rows += hb.num_rows
                    if pending_rows >= rows:
                        payload.append((None, [wire.pack_batch(
                            concat_host_batches(pending))]))
                        pending, pending_rows = [], 0
            if pending:
                payload.append((None, [wire.pack_batch(
                    concat_host_batches(pending))]))
        else:
            for unit in units:
                if use_cache and DEVICE_SCAN_CACHE.get(
                        self._unit_cache_key(unit, rows)) is not None:
                    payload.append((unit, "cached"))
                    continue
                faults.fault_point("scan")
                payload.append((unit, [
                    wire.pack_batch(hb)
                    for hb in _read_unit_batches(self.fmt, unit,
                                                 self.options, rows,
                                                 self._columns)]))
        staged = sum(e.nbytes for _, item in payload
                     if item != "cached" for e in item)
        PL.record(ctx, "stagingBytesPrefetched", staged)
        ctx.cache[self._prefetch_key(partition)] = payload

    def _upload_group_plan(self, ctx, encs):
        """Deterministic transfer grouping for a run of encoded batches:
        members below wire.minUploadBytes coalesce into one device_put
        (columnar/wire.py plan_upload_groups)."""
        from spark_rapids_tpu.columnar import wire
        min_bytes = int(ctx.conf.get(C.WIRE_MIN_UPLOAD_BYTES))
        if min_bytes <= 0:
            return [[i] for i in range(len(encs))]
        return wire.plan_upload_groups([e.nbytes for e in encs],
                                       min_bytes)

    def _upload_run(self, ctx, m, run, rows, partition, budget):
        """Upload a run of consecutive non-cached payload entries
        ``(unit_or_None, [EncodedBatch...])`` with tiny members grouped
        into shared transfers. Yield order (and therefore every
        downstream bit) is identical to per-batch uploads — grouping
        changes only the transfer count."""
        from spark_rapids_tpu.columnar import wire
        flat = []                      # (entry_idx, EncodedBatch)
        for ei, (_unit, encs) in enumerate(run):
            for enc in encs:
                flat.append((ei, enc))
        # Groups are consecutive flat-index runs, so streaming them in
        # order preserves the serial yield order exactly.
        groups = self._upload_group_plan(ctx, [e for _, e in flat])
        entry_batches: List[List] = [[] for _ in run]
        started = set()
        for g in groups:
            with timed(m, "bufferTime"):
                outs = wire.upload_packed_group([flat[i][1] for i in g])
            for i, b in zip(g, outs):
                ei = flat[i][0]
                unit = run[ei][0]
                if ei not in started:
                    started.add(ei)
                    if unit is not None:
                        self._publish_input_file(ctx, partition,
                                                 unit.path)
                entry_batches[ei].append(b)
                record_batch(m, b)
                yield b
                last_of_entry = i + 1 >= len(flat) or \
                    flat[i + 1][0] != ei
                if last_of_entry and unit is not None and budget > 0:
                    key = self._unit_cache_key(unit, rows)
                    if key is not None:
                        DEVICE_SCAN_CACHE.put(key, entry_batches[ei],
                                              budget)

    def _device_prefetched(self, ctx, m, payload, rows, partition,
                           budget):
        """Consume a prefetched partition: dispatch-only, in payload
        order (identical to the serial decode order, so results match
        the serial path bit-for-bit). Consecutive tiny units share one
        transfer (wire.minUploadBytes)."""
        run: List[tuple] = []
        for unit, item in payload:
            if unit is not None and item == "cached":
                if run:
                    yield from self._upload_run(ctx, m, run, rows,
                                                partition, budget)
                    run = []
                hit = DEVICE_SCAN_CACHE.get(
                    self._unit_cache_key(unit, rows)) \
                    if budget > 0 else None
                if hit is not None:
                    m.add("scanCacheHits", 1)
                    self._publish_input_file(ctx, partition, unit.path)
                    for b in hit:
                        record_batch(m, b)
                        yield b
                else:
                    # Evicted between prefetch and consume: decode inline.
                    yield from self._device_perfile(ctx, m, [unit], rows,
                                                    partition, budget)
                continue
            run.append((unit, item))
        if run:
            yield from self._upload_run(ctx, m, run, rows, partition,
                                        budget)

    # -- device engine -------------------------------------------------------
    def _unit_cache_key(self, unit: ScanUnit, rows: int):
        try:
            st = os.stat(unit.path)
        except OSError:
            return None
        # Reader options and the user schema change how the same bytes
        # decode (CSV delimiter/header, imposed types): they must key the
        # cache or two differently-configured scans would share entries.
        return (self.fmt, unit.path, st.st_mtime_ns, st.st_size, unit.index,
                self._schema, self._opts_key, rows)

    def execute_device(self, ctx, partition):
        m = ctx.metrics_for(self)
        rt = self._reader_type(ctx)
        rows = self._batch_rows(ctx)
        pre = ctx.cache.pop(self._prefetch_key(partition), None)
        if pre is not None:
            # Pipeline prefetch already decoded+encoded this partition on
            # a host thread; this (ordered, single-consumer) call only
            # uploads. A watchdog-killed attempt popped the payload with
            # it, so a re-dispatch falls through to the inline path.
            yield from self._device_prefetched(
                ctx, m, pre, rows, partition,
                int(ctx.conf.get(C.SCAN_CACHE_BYTES)))
            return
        units = self._units_of(partition, m, ctx=ctx)
        budget = int(ctx.conf.get(C.SCAN_CACHE_BYTES))
        # COALESCING merges units into one upload, so its outputs have no
        # per-unit identity to cache under; the per-unit strategies cache.
        use_cache = budget > 0 and rt != "COALESCING"
        if not use_cache:
            if rt == "MULTITHREADED":
                yield from self._device_multithreaded(ctx, m, units, rows,
                                                      partition, 0)
            elif rt == "COALESCING":
                yield from self._device_coalescing(ctx, m, units, rows)
            else:
                yield from self._device_perfile(ctx, m, units, rows,
                                                partition, 0)
            return
        # Serve cache hits inline; read contiguous miss runs through the
        # configured reader strategy (which inserts them into the cache).
        read = self._device_multithreaded if rt == "MULTITHREADED" \
            else self._device_perfile
        run: List[ScanUnit] = []
        for unit in units:
            hit = DEVICE_SCAN_CACHE.get(self._unit_cache_key(unit, rows))
            if hit is None:
                run.append(unit)
                continue
            if run:
                yield from read(ctx, m, run, rows, partition, budget)
                run = []
            m.add("scanCacheHits", 1)
            self._publish_input_file(ctx, partition, unit.path)
            for b in hit:
                record_batch(m, b)
                yield b
        if run:
            yield from read(ctx, m, run, rows, partition, budget)

    def _device_perfile(self, ctx, m, units, rows, partition, budget):
        from spark_rapids_tpu import faults
        for unit in units:
            faults.fault_point("scan")
            self._publish_input_file(ctx, partition, unit.path)
            ubatches = []
            for hb in _read_unit_batches(self.fmt, unit, self.options,
                                         rows, self._columns):
                with timed(m, "bufferTime"):
                    batch = host_to_device(hb)
                record_batch(m, batch)
                ubatches.append(batch)
                yield batch
            if budget > 0:
                key = self._unit_cache_key(unit, rows)
                if key is not None:
                    DEVICE_SCAN_CACHE.put(key, ubatches, budget)

    def _device_multithreaded(self, ctx, m, units, rows, partition,
                              budget=0):
        """Background host decode overlapped with device consumption
        (MultiFileCloudParquetPartitionReader's thread-pool overlap,
        GpuParquetScan.scala:1144). Streaming: at most ``nthreads`` units
        are in flight at once and each finished unit's batches are yielded
        (uploaded) while later units keep decoding in the background —
        never the old whole-partition ``list(...)`` buffering."""
        from spark_rapids_tpu import faults
        nthreads = int(ctx.conf.get(
            C.PARQUET_MULTITHREADED_READ_NUM_THREADS))
        if not units:
            return
        window = min(nthreads, len(units))
        # Worker threads inherit this (consuming) thread's recovery sink
        # and watchdog cancel event, so injected faults on the pool count
        # into the query's Recovery metrics and a stalled decode unwinds
        # the moment the watchdog kills the consuming attempt.
        sink = faults.get_recovery_sink()
        cancel = faults.get_cancel_event()

        def read_unit(u):
            # Decode, wire-encode AND pack in the worker: the upload's
            # entire host half (narrowing analysis, padding, bit-packing,
            # staging-buffer assembly) is CPU work that overlaps with
            # device consumption of earlier units.
            from spark_rapids_tpu.columnar import wire
            faults.set_recovery_sink(sink)
            faults.set_cancel_event(cancel)
            try:
                faults.fault_point("scan")
                return [wire.pack_batch(hb)
                        for hb in _read_unit_batches(self.fmt, u,
                                                     self.options, rows,
                                                     self._columns)]
            finally:
                faults.set_cancel_event(None)
                faults.set_recovery_sink(None)

        with concurrent.futures.ThreadPoolExecutor(
                max_workers=window) as pool:
            inflight = []          # [(unit, future)] bounded by `window`
            it = iter(units)
            for u in it:
                inflight.append((u, pool.submit(read_unit, u)))
                if len(inflight) >= window:
                    break
            while inflight:
                unit, fut = inflight.pop(0)
                encoded = fut.result()
                nxt = next(it, None)
                if nxt is not None:
                    inflight.append((nxt, pool.submit(read_unit, nxt)))
                yield from self._upload_run(ctx, m, [(unit, encoded)],
                                            rows, partition, budget)

    def _device_coalescing(self, ctx, m, units, rows):
        """Concatenate small units' rows into fewer, larger uploads
        (MultiFileParquetPartitionReader:823 stitch idea)."""
        from spark_rapids_tpu import faults
        pending: List[HostBatch] = []
        pending_rows = 0
        for unit in units:
            faults.fault_point("scan")
            for hb in _read_unit_batches(self.fmt, unit, self.options,
                                         rows, self._columns):
                pending.append(hb)
                pending_rows += hb.num_rows
                if pending_rows >= rows:
                    yield self._upload_merged(m, pending)
                    pending, pending_rows = [], 0
        if pending:
            yield self._upload_merged(m, pending)

    def _upload_merged(self, m, hbs: List[HostBatch]):
        from spark_rapids_tpu.columnar.host import concat_host_batches
        merged = concat_host_batches(hbs)
        with timed(m, "bufferTime"):
            batch = host_to_device(merged)
        record_batch(m, batch)
        return batch


def make_scan_exec(file_scan, conf, force_perfile: bool = False
                   ) -> FileScanExec:
    """Planner hook for L.FileScan nodes."""
    return FileScanExec(file_scan.fmt, file_scan.paths,
                        file_scan.source_schema, file_scan.options,
                        force_perfile=force_perfile,
                        predicates=getattr(file_scan, "predicates", ()))
