"""Columnar writers: parquet / ORC / CSV output (ref:
GpuParquetFileFormat.scala + ColumnarOutputWriter.scala +
GpuFileFormatWriter.scala's per-partition files).

Each engine partition writes one ``part-NNNNN`` file inside the output
directory (Spark's directory-of-parts layout), chunked through arrow
writers (Table.writeParquetChunked analog). ``partition_by`` switches to
dynamic partitioning: rows split by their partition-column values into
``col=value/`` subdirectories, partition columns dropped from the file
contents (GpuFileFormatWriter.scala:338's dynamic write — the reference
sorts by partition columns to bound open writers; this host-side writer
groups each batch instead, holding one open writer per seen partition).

Every write records stats (BasicColumnarWriteStatsTracker.scala:180
analog) in ``last_stats``: numFiles, numOutputRows, numOutputBytes,
numParts (dynamic partition directories).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from spark_rapids_tpu.columnar.host import HostBatch, HostColumn, \
    device_to_host
from spark_rapids_tpu.io.arrow_convert import host_batch_to_arrow

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq


# Characters Hive escapes in partition paths (ExternalCatalogUtils
# escapePathName): anything that could change the directory structure.
_ESCAPE = set('"#%\'*/:=?\\\x7f{[]^') | {chr(c) for c in range(0x20)}


def _part_value(v) -> str:
    """Hive-style partition directory value, path-safely escaped."""
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    if isinstance(v, bytes):
        v = v.decode("utf-8", errors="replace")
    elif isinstance(v, float):
        import math
        if math.isfinite(v) and v == int(v):
            v = int(v)
    s = str(v)
    return "".join(f"%{ord(ch):02X}" if ch in _ESCAPE else ch
                   for ch in s)


def _take_rows(hb: HostBatch, idx: np.ndarray,
               keep_cols: List[int]) -> HostBatch:
    cols = []
    names = []
    for ci in keep_cols:
        c = hb.columns[ci]
        if c.dtype.is_string and c.str_matrix is not None:
            # Slice the dense byte matrix; never materialize the lazy
            # per-row object array.
            cols.append(HostColumn(c.dtype, None, c.validity[idx],
                                   str_matrix=c.str_matrix[idx],
                                   str_lengths=c.str_lengths[idx]))
        else:
            cols.append(HostColumn(c.dtype, c.data[idx],
                                   c.validity[idx]))
        names.append(hb.names[ci])
    return HostBatch(tuple(names), cols)


class _Stats:
    def __init__(self):
        self.values = {"numFiles": 0, "numOutputRows": 0,
                       "numOutputBytes": 0, "numParts": 0}

    def file_closed(self, path: str):
        self.values["numFiles"] += 1
        try:
            self.values["numOutputBytes"] += os.path.getsize(path)
        except OSError:
            pass


class DataFrameWriter:
    def __init__(self, df):
        self._df = df
        self._options: Dict = {}
        self._mode = "error"
        self._partition_by: List[str] = []
        self.last_stats: Optional[Dict] = None

    def option(self, key: str, value) -> "DataFrameWriter":
        self._options[key] = value
        return self

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    partitionBy = partition_by

    def _prepare_dir(self, path: str):
        if os.path.exists(path):
            if self._mode == "overwrite":
                import shutil
                shutil.rmtree(path)
            elif self._mode == "error":
                raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)

    def _open(self, fmt: str, out: str, table):
        if fmt == "parquet":
            return papq.ParquetWriter(out, table.schema)
        if fmt == "orc":
            return paorc.ORCWriter(out)
        return pacsv.CSVWriter(out, table.schema)

    @staticmethod
    def _append(fmt: str, writer, table):
        if fmt == "parquet":
            writer.write_table(table)
        else:
            writer.write(table)

    def _write(self, path: str, fmt: str):
        import uuid
        import spark_rapids_tpu.config as C
        from spark_rapids_tpu.ops.base import ExecContext
        self._prepare_dir(path)
        conf = self._df._session.conf
        write_gate = {"parquet": C.ENABLE_PARQUET_WRITE,
                      "orc": C.ENABLE_ORC_WRITE}.get(fmt)
        if write_gate is not None and not bool(conf.get(write_gate)):
            # Write gate off: the job runs through the host fallback
            # engine (the reference's CPU FileFormatWriter fallback).
            phys = self._df._host_physical()
        else:
            phys = self._df._physical()
        ctx = ExecContext(phys.conf)
        ctx.cache["engine"] = "device" if phys.root_on_device else "host"
        root = phys.root
        names = tuple(n for n, _ in root.schema)
        stats = _Stats()
        n_parts = root.num_partitions(ctx)
        # Unique job id in file names so append mode never clobbers a
        # previous write's parts (Spark's write-uuid naming).
        job = uuid.uuid4().hex[:8]
        part_ords = []
        for k in self._partition_by:
            if k not in names:
                raise ValueError(f"unknown partitionBy column {k!r}")
            part_ords.append(names.index(k))
        data_ords = [i for i in range(len(names)) if i not in part_ords]
        part_dirs = set()
        for p in range(n_parts):
            out = os.path.join(path, f"part-{p:05d}-{job}.{fmt}")
            writers: Dict = {}      # key -> (writer, path); None key=plain
            wrote = False
            for b in (root.execute_device(ctx, p) if phys.root_on_device
                      else root.execute_host(ctx, p)):
                hb = device_to_host(b, names) if phys.root_on_device else b
                if hb.num_rows == 0 and wrote:
                    continue
                if not self._partition_by:
                    table = host_batch_to_arrow(hb)
                    if None not in writers:
                        writers[None] = (self._open(fmt, out, table), out)
                    self._append(fmt, writers[None][0], table)
                    stats.values["numOutputRows"] += hb.num_rows
                    wrote = True
                    continue
                # Dynamic partitioning: group this batch's rows by their
                # partition-column value tuple (vectorized factorize per
                # key column), one open writer per seen directory.
                import pandas as pd
                code_cols = []
                uniq_cols = []
                for o in part_ords:
                    c = hb.columns[o]
                    # Factorize the native array (no per-row boxing);
                    # nulls become code -1 afterwards.
                    codes, uniques = pd.factorize(c.data, sort=False)
                    codes = np.asarray(codes).copy()
                    codes[~c.validity] = -1
                    code_cols.append(codes)          # -1 = None
                    uniq_cols.append(list(uniques))
                gid = np.zeros(hb.num_rows, np.int64)
                for codes, uniques in zip(code_cols, uniq_cols):
                    gid = gid * (len(uniques) + 1) + (codes + 1)
                order = np.argsort(gid, kind="stable")
                bounds = np.flatnonzero(np.diff(gid[order])) + 1
                groups = np.split(order, bounds)
                def key_of(row_i):
                    return tuple(
                        None if codes[row_i] < 0 else uniques[codes[row_i]]
                        for codes, uniques in zip(code_cols, uniq_cols))
                keyed = sorted(
                    ((key_of(int(rows[0])), rows) for rows in groups
                     if len(rows)),
                    key=lambda kv: tuple(map(_part_value, kv[0])))
                for k, rows in keyed:
                    sub = _take_rows(hb, np.asarray(rows, np.int64),
                                     data_ords)
                    table = host_batch_to_arrow(sub)
                    if k not in writers:
                        sub_dir = os.path.join(path, *[
                            f"{name}={_part_value(v)}"
                            for name, v in zip(self._partition_by, k)])
                        os.makedirs(sub_dir, exist_ok=True)
                        part_dirs.add(sub_dir)
                        f = os.path.join(sub_dir,
                                         f"part-{p:05d}-{job}.{fmt}")
                        writers[k] = (self._open(fmt, f, table), f)
                    self._append(fmt, writers[k][0], table)
                    stats.values["numOutputRows"] += sub.num_rows
                wrote = True
            for w, fpath in writers.values():
                w.close()
                stats.file_closed(fpath)
            if not writers and not wrote and not self._partition_by:
                # Empty partition still writes schema-only file (parquet).
                if fmt == "parquet":
                    empty = host_batch_to_arrow(
                        _empty_host_batch(root.schema))
                    papq.write_table(empty, out)
                    stats.file_closed(out)
        stats.values["numParts"] = len(part_dirs)
        self.last_stats = dict(stats.values)
        return self.last_stats

    def parquet(self, path: str):
        return self._write(path, "parquet")

    def orc(self, path: str):
        return self._write(path, "orc")

    def csv(self, path: str):
        return self._write(path, "csv")


def _empty_host_batch(schema) -> HostBatch:
    from spark_rapids_tpu.columnar.host import HostColumn
    return HostBatch(tuple(n for n, _ in schema),
                     [HostColumn.from_values(t, []) for _, t in schema])
