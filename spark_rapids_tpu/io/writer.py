"""Columnar writers: parquet / ORC / CSV output (ref:
GpuParquetFileFormat.scala + ColumnarOutputWriter.scala +
GpuFileFormatWriter.scala's per-partition files).

Each engine partition writes one ``part-NNNNN`` file inside the output
directory (Spark's directory-of-parts layout), chunked through arrow
writers (Table.writeParquetChunked analog).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from spark_rapids_tpu.columnar.host import HostBatch, device_to_host
from spark_rapids_tpu.io.arrow_convert import host_batch_to_arrow

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq


class DataFrameWriter:
    def __init__(self, df):
        self._df = df
        self._options: Dict = {}
        self._mode = "error"

    def option(self, key: str, value) -> "DataFrameWriter":
        self._options[key] = value
        return self

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def _prepare_dir(self, path: str):
        if os.path.exists(path):
            if self._mode == "overwrite":
                import shutil
                shutil.rmtree(path)
            elif self._mode == "error":
                raise FileExistsError(path)
        os.makedirs(path, exist_ok=True)

    def _write(self, path: str, fmt: str):
        import uuid
        from spark_rapids_tpu.ops.base import ExecContext
        self._prepare_dir(path)
        phys = self._df._physical()
        ctx = ExecContext(self._df._session.conf)
        ctx.cache["engine"] = "device" if phys.root_on_device else "host"
        root = phys.root
        names = tuple(n for n, _ in root.schema)
        n_parts = root.num_partitions(ctx)
        # Unique job id in file names so append mode never clobbers a
        # previous write's parts (Spark's write-uuid naming).
        job = uuid.uuid4().hex[:8]
        for p in range(n_parts):
            out = os.path.join(path, f"part-{p:05d}-{job}.{fmt}")
            writer = None
            wrote = False
            for b in (root.execute_device(ctx, p) if phys.root_on_device
                      else root.execute_host(ctx, p)):
                hb = device_to_host(b, names) if phys.root_on_device else b
                if hb.num_rows == 0 and wrote:
                    continue
                table = host_batch_to_arrow(hb)
                if fmt == "parquet":
                    if writer is None:
                        writer = papq.ParquetWriter(out, table.schema)
                    writer.write_table(table)
                elif fmt == "orc":
                    if writer is None:
                        writer = paorc.ORCWriter(out)
                    writer.write(table)
                elif fmt == "csv":
                    if writer is None:
                        writer = pacsv.CSVWriter(out, table.schema)
                    writer.write(table)
                wrote = True
            if writer is not None:
                writer.close()
            elif not wrote:
                # Empty partition still writes schema-only file (parquet).
                if fmt == "parquet":
                    empty = host_batch_to_arrow(
                        _empty_host_batch(root.schema))
                    papq.write_table(empty, out)

    def parquet(self, path: str):
        self._write(path, "parquet")

    def orc(self, path: str):
        self._write(path, "orc")

    def csv(self, path: str):
        self._write(path, "csv")


def _empty_host_batch(schema) -> HostBatch:
    from spark_rapids_tpu.columnar.host import HostColumn
    return HostBatch(tuple(n for n, _ in schema),
                     [HostColumn.from_values(t, []) for _, t in schema])
