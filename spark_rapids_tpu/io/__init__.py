"""IO layer: file scans & writers over the arrow host-decode bridge
(SURVEY.md §2.4 scan rows; §7 step 3)."""

from spark_rapids_tpu.io.scan import (      # noqa: F401
    FileScanExec, infer_schema, make_scan_exec)
