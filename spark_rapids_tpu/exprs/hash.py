"""Spark-compatible Murmur3 hash (ref: HashFunctions.scala:39 + cudf's
Spark-flavored murmur3, used by GpuHashPartitioning for shuffle parity).

Implements org.apache.spark.unsafe.hash.Murmur3_x86_32 exactly, vectorized in
int32 lane arithmetic (uint32 on device to sidestep signed-overflow):
- bool/byte/short/int/date -> hashInt
- long/timestamp -> hashLong (two 4-byte blocks, low then high)
- float -> hashInt(floatToIntBits) with -0.0 kept as is (Spark hashes raw
  bits; NaN canonicalized to the single Java NaN bit pattern)
- double -> hashLong(doubleToLongBits), same NaN canonicalization
- string -> hashUnsafeBytes: 4-byte little-endian blocks then per-byte tail
  (bytes are SIGNED in the tail, matching the JVM)
- NULL columns pass the running seed through unchanged
- multi-column: seed chains left to right starting at 42

Bit-for-bit parity with Spark here is what makes TPU shuffle partitions line
up with CPU Spark's (SURVEY.md §7 step 2 "murmur3-compatible hash").
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np
from spark_rapids_tpu.columnar.host import all_valid as _all_valid

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exprs.base import (
    Expression, as_device_column, as_host_column, make_column,
    make_host_column)

DEFAULT_SEED = 42

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _u32(xp, x):
    return x.astype(np.uint32)


def _rotl(xp, x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _mix_k1(xp, k1):
    k1 = k1 * _C1
    k1 = _rotl(xp, k1, 15)
    return k1 * _C2


def _mix_h1(xp, h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl(xp, h1, 13)
    return h1 * np.uint32(5) + np.uint32(0xE6546B64)


def _fmix(xp, h1, length):
    # length may be a per-row array (string hashing) or a python int.
    length = np.uint32(length) if isinstance(length, int) \
        else length.astype(np.uint32)
    h1 = h1 ^ length
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = h1 * np.uint32(0xC2B2AE35)
    h1 = h1 ^ (h1 >> np.uint32(16))
    return h1


def hash_int(xp, value_i32, seed_u32):
    """Murmur3 of one 4-byte value (already int32 lanes)."""
    k1 = _mix_k1(xp, _u32(xp, value_i32))
    h1 = _mix_h1(xp, seed_u32, k1)
    return _fmix(xp, h1, 4)


def hash_long(xp, value_i64, seed_u32):
    v = value_i64.astype(np.uint64)
    low = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    high = (v >> np.uint64(32)).astype(np.uint32)
    h1 = _mix_h1(xp, seed_u32, _mix_k1(xp, low))
    h1 = _mix_h1(xp, h1, _mix_k1(xp, high))
    return _fmix(xp, h1, 8)


def _float_bits(xp, data):
    """Java floatToIntBits: canonicalize NaN to 0x7FC00000."""
    bits = data.astype(np.float32).view(np.int32) if xp is np else \
        jnp.asarray(data, np.float32).view(jnp.int32)
    nan = xp.isnan(data)
    return xp.where(nan, np.int32(0x7FC00000), bits)


def _double_bits(xp, data):
    if xp is np:
        bits = data.astype(np.float64).view(np.int64)
        nan = np.isnan(data)
        return np.where(nan, np.int64(0x7FF8000000000000), bits)
    return _double_bits_device(data)


def _double_bits_device(x):
    """Java doubleToLongBits WITHOUT a 64-bit bitcast (TPU's x64 emulation
    cannot bitcast f64): decompose sign/exponent/mantissa arithmetically.

    Exponent by binary-search normalization (all multiplies are exact
    powers of two), mantissa as (m-1)*2^52 which is an exact 52-bit
    integer. ~40 emulated f64 ops per element; the CPU test suite
    validates it bit-for-bit against numpy's view() oracle.

    Known deviation: XLA flushes f64 subnormals to zero (FTZ), so
    subnormal inputs hash as +/-0.0 — the same class of documented float
    incompatibility the reference gates (GpuOverrides incompat flags)."""
    x = jnp.asarray(x, jnp.float64)
    nan = jnp.isnan(x)
    inf = jnp.isinf(x)
    zero = (x == 0.0) | (jnp.abs(x) < 2.0 ** -1022)   # FTZ: subnormal -> 0
    neg = (x < 0) | (1.0 / x < 0)                     # sign incl. -0.0
    ax = jnp.abs(x)
    m = ax
    e = jnp.zeros(x.shape, jnp.int32)
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        c = m >= 2.0 ** k
        m = jnp.where(c, m * (2.0 ** -k), m)
        e = e + jnp.where(c, jnp.int32(k), jnp.int32(0))
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        c = m < 2.0 ** (1 - k)
        m = jnp.where(c, m * (2.0 ** k), m)
        e = e - jnp.where(c, jnp.int32(k), jnp.int32(0))
    frac = ((m - 1.0) * (2.0 ** 52)).astype(jnp.uint64)
    bexp = (e + 1023).astype(jnp.uint64)
    bits = (bexp << jnp.uint64(52)) | frac
    bits = jnp.where(zero, jnp.uint64(0), bits)
    bits = jnp.where(inf, jnp.uint64(0x7FF0000000000000), bits)
    bits = jnp.where(neg, bits | (jnp.uint64(1) << jnp.uint64(63)), bits)
    bits = jnp.where(nan, jnp.uint64(0x7FF8000000000000), bits)
    return bits.astype(jnp.int64)


def hash_string_matrix(xp, data, lengths, seed_u32):
    """hashUnsafeBytes over a (N, W) byte matrix with per-row lengths.

    Block loop runs W//4 iterations of dense lane ops; tail bytes are folded
    with a masked per-byte pass. All trace-time loops are over the static
    width, so XLA unrolls and fuses them.
    """
    n, w = data.shape
    h1 = seed_u32
    nblocks_row = lengths // 4
    # 4-byte little-endian words.
    nwords = w // 4
    for bi in range(nwords):
        b0 = data[:, bi * 4].astype(np.uint32)
        b1 = data[:, bi * 4 + 1].astype(np.uint32)
        b2 = data[:, bi * 4 + 2].astype(np.uint32)
        b3 = data[:, bi * 4 + 3].astype(np.uint32)
        word = b0 | (b1 << np.uint32(8)) | (b2 << np.uint32(16)) | \
            (b3 << np.uint32(24))
        mixed = _mix_h1(xp, h1, _mix_k1(xp, word))
        h1 = xp.where(bi < nblocks_row, mixed, h1)
    # Tail: signed bytes hashed one at a time as ints.
    aligned = nblocks_row * 4
    for off in range(w):
        byte = data[:, off].astype(np.int8).astype(np.int32)
        k1 = _mix_k1(xp, _u32(xp, byte))
        mixed = _mix_h1(xp, h1, k1)
        in_tail = (off >= aligned) & (off < lengths)
        h1 = xp.where(in_tail, mixed, h1)
    return _fmix(xp, h1, lengths.astype(np.uint32))


def hash_column(xp, col, dtype: DataType, seed_u32):
    """Hash one column, passing the seed through for NULL rows."""
    if dtype.is_string:
        h = hash_string_matrix(xp, col.data, col.lengths, seed_u32)
    elif dtype.name in ("int64", "timestamp"):
        h = hash_long(xp, col.data, seed_u32)
    elif dtype.name == "float64":
        h = hash_long(xp, _double_bits(xp, col.data), seed_u32)
    elif dtype.name == "float32":
        h = hash_int(xp, _float_bits(xp, col.data), seed_u32)
    elif dtype.is_boolean:
        h = hash_int(xp, col.data.astype(np.int32), seed_u32)
    else:  # int8/16/32/date widen to int
        h = hash_int(xp, col.data.astype(np.int32), seed_u32)
    return xp.where(col.validity, h, seed_u32)


# ---------------------------------------------------------------------------
# MD5 (Spark's Md5 expression: md5(binary) -> 32-char lowercase hex string)
# ---------------------------------------------------------------------------
#
# RFC 1321 vectorized in uint32 lane arithmetic over the (N, W) byte
# matrix, same xp polymorphism as murmur3 above so the device (jnp) and
# host (np) paths share one implementation. Per-row message lengths vary,
# so padding (0x80 terminator + little-endian bit length) is injected
# positionally with where-selects, and chunks beyond a row's padded
# length leave its state untouched. All loops are over the STATIC width,
# so XLA unrolls and fuses them.

import math as _math

_MD5_K = tuple(int(abs(_math.sin(i + 1)) * (1 << 32)) & 0xFFFFFFFF
               for i in range(64))
_MD5_S = (7, 12, 17, 22) * 4 + (5, 9, 14, 20) * 4 + \
    (4, 11, 16, 23) * 4 + (6, 10, 15, 21) * 4
_MD5_INIT = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476)


def md5_hex_matrix(xp, data, lengths):
    """MD5 of each row of a (N, W) byte matrix (first ``lengths[i]``
    bytes), as an (N, 32) lowercase-hex byte matrix."""
    n, w = data.shape
    lengths = lengths.astype(np.int32)
    # Padded byte stream: message | 0x80 | zeros | 8-byte LE bit length.
    max_chunks = (w + 8) // 64 + 1
    total = max_chunks * 64
    padded_len = ((lengths + 8) // 64 + 1) * 64
    bitlen = lengths.astype(np.uint32) * np.uint32(8)
    row_chunks = padded_len // 64

    def byte_at(o: int):
        """(N,) uint32 byte o of each row's padded stream."""
        msg = data[:, o].astype(np.uint32) if o < w else np.uint32(0)
        b = xp.where(o < lengths, msg, np.uint32(0))
        b = xp.where(o == lengths, np.uint32(0x80), b)
        # Little-endian 64-bit bit count in the trailing 8 bytes; the
        # high 4 bytes are always zero (lengths are far below 2^29).
        k = o - (padded_len - 8)
        in_len = (k >= 0) & (k < 4)
        k_safe = xp.where(in_len, k, 0).astype(np.uint32)
        lb = xp.where(in_len,
                      (bitlen >> (k_safe * np.uint32(8))) & np.uint32(0xFF),
                      np.uint32(0))
        return b | lb

    a, b, c, d = (xp.full((n,), np.uint32(v), dtype=np.uint32)
                  for v in _MD5_INIT)
    for chunk in range(max_chunks):
        m = []
        for j in range(16):
            o = chunk * 64 + j * 4
            word = byte_at(o) | (byte_at(o + 1) << np.uint32(8)) | \
                (byte_at(o + 2) << np.uint32(16)) | \
                (byte_at(o + 3) << np.uint32(24))
            m.append(word)
        A, B, C, D = a, b, c, d
        for i in range(64):
            if i < 16:
                f = (B & C) | (~B & D)
                g = i
            elif i < 32:
                f = (D & B) | (~D & C)
                g = (5 * i + 1) % 16
            elif i < 48:
                f = B ^ C ^ D
                g = (3 * i + 5) % 16
            else:
                f = C ^ (B | ~D)
                g = (7 * i) % 16
            f = f + A + np.uint32(_MD5_K[i]) + m[g]
            A = D
            D = C
            C = B
            B = B + _rotl(xp, f, _MD5_S[i])
        live = chunk < row_chunks
        a = xp.where(live, a + A, a)
        b = xp.where(live, b + B, b)
        c = xp.where(live, c + C, c)
        d = xp.where(live, d + D, d)
    # Digest = a|b|c|d little-endian -> 32 lowercase hex chars.
    out = []
    for word in (a, b, c, d):
        for byte_i in range(4):
            byte = (word >> np.uint32(8 * byte_i)) & np.uint32(0xFF)
            for nib_shift in (4, 0):
                nib = (byte >> np.uint32(nib_shift)) & np.uint32(0xF)
                out.append(xp.where(nib < 10, nib + np.uint32(48),
                                    nib + np.uint32(87)).astype(np.uint8))
    return xp.stack(out, axis=1)


class Md5(Expression):
    """md5(string) -> 32-char lowercase hex string (Spark Md5 over the
    UTF-8 bytes; NULL in, NULL out)."""

    def __init__(self, child: Expression):
        self._children = (child,)

    @property
    def children(self):
        return self._children

    def data_type(self) -> DataType:
        return dt.STRING

    def eval(self, batch):
        child = self._children[0]
        assert child.data_type().is_string, "md5 expects a string column"
        col = as_device_column(child.eval(batch), batch)
        hexm = md5_hex_matrix(jnp, col.data, col.lengths)
        validity = col.validity & batch.row_mask()
        lengths = jnp.where(validity, jnp.int32(32), jnp.int32(0))
        return make_column(dt.STRING, hexm, validity, lengths)

    def eval_host(self, batch):
        from spark_rapids_tpu.columnar.host import (
            HostColumn, strings_to_matrix)
        child = self._children[0]
        hc = as_host_column(child.eval_host(batch), batch)
        m, lens = strings_to_matrix(hc)
        hexm = np.asarray(md5_hex_matrix(np, m, lens), np.uint8)
        validity = np.asarray(hc.validity, np.bool_)
        hexm = hexm * validity[:, None].astype(np.uint8)
        lengths = np.where(validity, 32, 0).astype(np.int32)
        return HostColumn(dt.STRING, None, validity,
                          str_matrix=hexm, str_lengths=lengths)


class Murmur3Hash(Expression):
    """hash(c1, c2, ...) -> int32, seed chained across columns."""

    def __init__(self, children: Sequence[Expression],
                 seed: int = DEFAULT_SEED):
        self._children = tuple(children)
        self.seed = seed

    @property
    def children(self):
        return self._children

    def data_type(self) -> DataType:
        return dt.INT32

    def _run(self, xp, cols, n):
        h = xp.full((n,), np.uint32(np.uint32(self.seed)), dtype=np.uint32)
        for col, dtype in cols:
            h = hash_column(xp, col, dtype, h)
        return h.astype(np.int32)

    def eval(self, batch):
        cols = [(as_device_column(c.eval(batch), batch), c.data_type())
                for c in self._children]
        data = self._run(jnp, cols, batch.capacity)
        return make_column(dt.INT32, data, batch.row_mask())

    def eval_host(self, batch):
        from spark_rapids_tpu.columnar.host import StringMatrixView
        cols = []
        for c in self._children:
            hc = as_host_column(c.eval_host(batch), batch)
            if c.data_type().is_string:
                cols.append((StringMatrixView.of(hc), c.data_type()))
            else:
                cols.append((hc, c.data_type()))
        data = self._run(np, cols, batch.num_rows)
        return make_host_column(dt.INT32, data,
                                _all_valid(batch.num_rows))
