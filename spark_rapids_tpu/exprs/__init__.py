"""Expression engine: dual device(jnp)/host(numpy) columnar expressions.

The analog of the reference's GpuExpression library (~150 expressions
registered in GpuOverrides.scala:537-1667). See base.py for the evaluation
contract.
"""

from spark_rapids_tpu.exprs.base import (        # noqa: F401
    BoundReference, Expression, Literal, Scalar, eval_exprs, eval_exprs_host,
    lit)
from spark_rapids_tpu.exprs.arithmetic import (  # noqa: F401
    Abs, Add, BitwiseAnd, BitwiseNot, BitwiseOr, BitwiseXor, Divide,
    Greatest, IntegralDivide, Least, Multiply, Pmod, Remainder, ShiftLeft,
    ShiftRight, ShiftRightUnsigned, Subtract, UnaryMinus, UnaryPositive)
from spark_rapids_tpu.exprs.predicates import (  # noqa: F401
    And, AtLeastNNonNulls, EqualNullSafe, EqualTo, GreaterThan,
    GreaterThanOrEqual, InSet, IsNan, IsNotNull, IsNull, LessThan,
    LessThanOrEqual, Not, Or)
from spark_rapids_tpu.exprs.math import (        # noqa: F401
    Acos, Acosh, Asin, Asinh, Atan, Atan2, Atanh, BRound, Cbrt, Ceil,
    Cos, Cosh, Exp, Expm1, Floor, Log, Log1p, Log2, Log10, Logarithm,
    Pow, Rint, Round, Signum, Sin, Sinh, Sqrt, Tan, Tanh, ToDegrees,
    ToRadians)
from spark_rapids_tpu.exprs.conditional import (  # noqa: F401
    CaseWhen, Coalesce, If, KnownFloatingPointNormalized, NaNvl,
    NormalizeNaNAndZero, Nvl)
from spark_rapids_tpu.exprs.cast import Cast      # noqa: F401
from spark_rapids_tpu.exprs.datetime import (     # noqa: F401
    AddMonths, DateAdd, DateDiff, DateSub, DayOfMonth, DayOfWeek, DayOfYear,
    FromUnixTime, Hour, LastDay, Minute, Month, Quarter, Second, TimeAdd,
    TimeSub, ToUnixTimestamp, TruncDate, UnixTimestamp, WeekDay, Year)
from spark_rapids_tpu.exprs.strings import (      # noqa: F401
    ConcatStrings, ConcatWs, Contains, EndsWith, InitCap, Length, Like,
    Lower, RegExpExtract, RegExpReplace, StartsWith, StringLocate,
    StringLPad, StringRepeat, StringReplace, StringReverse, StringRPad,
    StringSplit, StringTrim, StringTrimLeft, StringTrimRight, Substring,
    SubstringIndex, Translate, Upper)
from spark_rapids_tpu.exprs.hash import Md5, Murmur3Hash  # noqa: F401
from spark_rapids_tpu.exprs.nondeterministic import (  # noqa: F401
    EvalContext, InputFileName, MonotonicallyIncreasingID, Rand,
    SparkPartitionID, eval_context, needs_eval_context)
