"""Nondeterministic / task-context expressions.

Ref: GpuSparkPartitionID.scala:58, GpuMonotonicallyIncreasingID.scala:75,
GpuRandomExpressions.scala:75, GpuInputFileBlock.scala — expressions whose
value depends on the task context (partition index, row position within the
partition, current input file) rather than only on column inputs.

The engine threads that context through an ``EvalContext`` (a contextvar set
by the evaluating operator around each batch). Under jit the partition id and
row base are *traced* scalars, so one compiled program serves every
partition/batch — the TPU analog of the reference reading
``TaskContext.getPartitionId()`` per task.

``Rand`` matches Spark's distribution (uniform [0,1), seeded per
(seed, partition)) but not Spark's bit-exact XORShift sequence — the same
deviation the reference takes (GpuRandomExpressions uses cuDF's RNG, not
Spark's). Device and host paths here produce *identical* values (shared
counter-based mixer), so the dual-engine compare harness still applies.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Any, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from spark_rapids_tpu.columnar.host import all_valid as _all_valid

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exprs.base import (
    Expression, Scalar, expand_scalar, expand_scalar_host, make_column,
    make_host_column)


@dataclasses.dataclass
class EvalContext:
    """Per-batch task context visible to contextual expressions.

    ``partition_id``/``row_base`` may be python ints (host path) or traced
    jnp scalars (device path under jit). ``row_base`` counts rows of the
    partition that came before this batch.
    """

    partition_id: Any = 0
    row_base: Any = 0
    input_file: Optional[str] = None


_EVAL_CTX: contextvars.ContextVar[Optional[EvalContext]] = \
    contextvars.ContextVar("spark_rapids_tpu_eval_ctx", default=None)


@contextlib.contextmanager
def eval_context(ctx: EvalContext):
    token = _EVAL_CTX.set(ctx)
    try:
        yield ctx
    finally:
        _EVAL_CTX.reset(token)


def current_eval_context() -> EvalContext:
    ctx = _EVAL_CTX.get()
    return ctx if ctx is not None else EvalContext()


class ContextualExpression(Expression):
    """Marker base: evaluation reads the EvalContext."""


def needs_eval_context(exprs) -> bool:
    """True when any expression tree contains a contextual node."""
    def rec(e: Expression) -> bool:
        if isinstance(e, ContextualExpression):
            return True
        return any(rec(c) for c in e.children)
    return any(rec(e) for e in exprs)


class SparkPartitionID(ContextualExpression):
    """spark_partition_id() — ref GpuSparkPartitionID.scala:58."""

    def data_type(self) -> DataType:
        return dt.INT32

    def eval(self, batch):
        ctx = current_eval_context()
        mask = batch.row_mask()
        pid = jnp.asarray(ctx.partition_id, jnp.int32)
        return make_column(dt.INT32, jnp.where(mask, pid, 0), mask)

    def eval_host(self, batch):
        ctx = current_eval_context()
        n = batch.num_rows
        return make_host_column(
            dt.INT32, np.full(n, int(ctx.partition_id), np.int32),
            _all_valid(n))

    def pretty(self) -> str:
        return "spark_partition_id()"


class MonotonicallyIncreasingID(ContextualExpression):
    """monotonically_increasing_id(): (partition_id << 33) + row index
    within the partition — ref GpuMonotonicallyIncreasingID.scala:75
    (Spark's exact layout: upper 31 bits partition, lower 33 row)."""

    def data_type(self) -> DataType:
        return dt.INT64

    def eval(self, batch):
        ctx = current_eval_context()
        mask = batch.row_mask()
        pid = jnp.asarray(ctx.partition_id, jnp.int64)
        base = jnp.asarray(ctx.row_base, jnp.int64)
        idx = base + jnp.cumsum(mask.astype(jnp.int64)) - 1
        val = (pid * (1 << 33)) + jnp.maximum(idx, 0)
        return make_column(dt.INT64, jnp.where(mask, val, 0), mask)

    def eval_host(self, batch):
        ctx = current_eval_context()
        n = batch.num_rows
        idx = int(ctx.row_base) + np.arange(n, dtype=np.int64)
        val = (np.int64(int(ctx.partition_id)) << np.int64(33)) + idx
        return make_host_column(dt.INT64, val, _all_valid(n))

    def pretty(self) -> str:
        return "monotonically_increasing_id()"


# -- counter-based uniform RNG (identical jnp/numpy results) ----------------

_GOLDEN = 0x9E3779B97F4A7C15
_MIX1 = 0xBF58476D1CE4E5B9
_MIX2 = 0x94D049BB133111EB


def _splitmix64(xp, x):
    """SplitMix64 finalizer over uint64 (wrapping arithmetic; no bitcasts,
    TPU x64-emulation safe)."""
    x = (x ^ (x >> np.uint64(30))) * np.uint64(_MIX1)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(_MIX2)
    return x ^ (x >> np.uint64(31))


_U64 = 0xFFFFFFFFFFFFFFFF


def _premix_seed(seed: int) -> int:
    """SplitMix64 over python ints: decorrelates seeds BEFORE they are
    combined with the row counter (a raw ``seed*GOLDEN + idx*GOLDEN``
    counter would make seed s+1's stream a one-row shift of seed s's)."""
    x = (seed * _GOLDEN) & _U64
    x = ((x ^ (x >> 30)) * _MIX1) & _U64
    x = ((x ^ (x >> 27)) * _MIX2) & _U64
    return x ^ (x >> 31)


def _uniform(xp, seed: int, pid, idx):
    """uint64 counter -> float64 in [0, 1). idx is the absolute row index
    within the partition; identical streams on device and host (uint64
    wraparound is the point — numpy overflow warnings suppressed)."""
    def impl():
        ctr = (xp.asarray(np.uint64(_premix_seed(seed)))
               + pid.astype(np.uint64) * np.uint64(_MIX1)
               + idx.astype(np.uint64) * np.uint64(_GOLDEN))
        bits = _splitmix64(xp, ctr) >> np.uint64(11)   # top 53 bits
        return bits.astype(np.float64) * np.float64(2.0 ** -53)
    if xp is np:
        with np.errstate(over="ignore"):
            return impl()
    return impl()


class Rand(ContextualExpression):
    """rand(seed) — uniform [0,1) double, seeded per (seed, partition),
    stable per absolute row index. Ref GpuRandomExpressions.scala:75 (same
    incompat stance: distribution-equal, not sequence-equal, to Spark)."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)

    def data_type(self) -> DataType:
        return dt.FLOAT64

    def eval(self, batch):
        ctx = current_eval_context()
        mask = batch.row_mask()
        pid = jnp.asarray(ctx.partition_id, jnp.int64)
        base = jnp.asarray(ctx.row_base, jnp.int64)
        idx = base + jnp.arange(batch.capacity, dtype=jnp.int64)
        u = _uniform(jnp, self.seed, pid, idx)
        return make_column(dt.FLOAT64, jnp.where(mask, u, 0.0), mask)

    def eval_host(self, batch):
        ctx = current_eval_context()
        n = batch.num_rows
        pid = np.int64(int(ctx.partition_id))
        idx = np.int64(int(ctx.row_base)) + np.arange(n, dtype=np.int64)
        u = _uniform(np, self.seed, pid, idx)
        return make_host_column(dt.FLOAT64, u, _all_valid(n))

    def pretty(self) -> str:
        return f"rand({self.seed})"


class InputFileName(ContextualExpression):
    """input_file_name() — ref GpuInputFileBlock.scala. The scan publishes
    the current file path into the ExecContext as it yields batches; the
    value is a per-batch host string, so this node is not jittable (the
    evaluating operator runs the projection eagerly — an expression-level
    CPU-decision island, like the reference's disableCoalesceUntilInput
    fence, GpuExpressions.scala:64-74)."""

    def data_type(self) -> DataType:
        return dt.STRING

    @property
    def self_jittable(self) -> bool:
        return False

    def _scalar(self) -> Scalar:
        ctx = current_eval_context()
        return Scalar(dt.STRING, ctx.input_file or "")

    def eval(self, batch):
        return self._scalar()

    def eval_host(self, batch):
        return self._scalar()

    def pretty(self) -> str:
        return "input_file_name()"
