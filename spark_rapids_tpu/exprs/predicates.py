"""Predicates & comparisons (ref: .../sql/rapids/predicates.scala 631 LoC).

Spark comparison semantics reproduced exactly:
- NaN is equal to NaN and greater than every other double/float value
  (Spark diverges from IEEE here; see Spark's ``NaN semantics`` docs).
- And/Or use Kleene three-valued logic (false && null = false,
  true || null = true).
- EqualNullSafe (``<=>``) never returns NULL.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.columnar.host import all_valid as _all_valid
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, Expression, Scalar, UnaryExpression,
    as_device_column, as_host_column, make_column, make_host_column)


def _string_cmp(xp, l_data, l_len, r_data, r_len):
    """Lexicographic byte compare of two (N, W) padded matrices.

    Returns (lt, eq) bool arrays. Zero padding is safe because comparison is
    on unsigned bytes and real lengths break ties.
    """
    wl, wr = l_data.shape[1], r_data.shape[1]
    w = max(wl, wr)
    if wl < w:
        l_data = xp.concatenate(
            [l_data, xp.zeros((l_data.shape[0], w - wl), np.uint8)], axis=1)
    if wr < w:
        r_data = xp.concatenate(
            [r_data, xp.zeros((r_data.shape[0], w - wr), np.uint8)], axis=1)
    li = l_data.astype(np.int16)
    ri = r_data.astype(np.int16)
    diff = li - ri                       # (N, W); first nonzero decides
    nz = diff != 0
    # Index of first nonzero byte; W if none differ.
    first = xp.where(nz.any(axis=1), xp.argmax(nz, axis=1), w)
    idx = xp.minimum(first, w - 1)
    d = xp.take_along_axis(diff, idx[:, None], axis=1)[:, 0]
    bytes_eq = first == w
    eq = bytes_eq & (l_len == r_len)
    lt = xp.where(bytes_eq, l_len < r_len, d < 0)
    return lt, eq


class _Comparison(BinaryExpression):
    def data_type(self) -> DataType:
        return dt.BOOL

    def _lt_eq(self, xp, l_col, r_col):
        """Compute (lt, eq) with Spark NaN ordering for floats."""
        t = self.left.data_type()
        if t.is_string:
            return _string_cmp(xp, l_col.data, l_col.lengths,
                               r_col.data, r_col.lengths)
        a, b = l_col.data, r_col.data
        if t.is_floating:
            na, nb = xp.isnan(a), xp.isnan(b)
            eq = (a == b) | (na & nb)
            lt = (~na & nb) | ((a < b) & ~na & ~nb)
            return lt, eq
        return a < b, a == b

    def _cmp_eval(self, xp, l_col, r_col, pick):
        lt, eq = self._lt_eq(xp, l_col, r_col)
        return pick(lt, eq), l_col.validity & r_col.validity

    def _pick(self, lt, eq):
        raise NotImplementedError

    def eval(self, batch):
        import jax.numpy as jnp
        lc = as_device_column(self.left.eval(batch), batch)
        rc = as_device_column(self.right.eval(batch), batch)
        data, validity = self._cmp_eval(jnp, lc, rc, self._pick)
        return make_column(dt.BOOL, data, validity)

    def eval_host(self, batch):
        lc = as_host_column(self.left.eval_host(batch), batch)
        rc = as_host_column(self.right.eval_host(batch), batch)
        if self.left.data_type().is_string:
            lc = _host_strings_to_matrix(lc)
            rc = _host_strings_to_matrix(rc)
        data, validity = self._cmp_eval(np, lc, rc, self._pick)
        return make_host_column(dt.BOOL, data, validity)


def _host_strings_to_matrix(col):
    from spark_rapids_tpu.columnar.host import StringMatrixView
    return StringMatrixView.of(col)


class EqualTo(_Comparison):
    def _pick(self, lt, eq):
        return eq


class LessThan(_Comparison):
    def _pick(self, lt, eq):
        return lt


class LessThanOrEqual(_Comparison):
    def _pick(self, lt, eq):
        return lt | eq


class GreaterThan(_Comparison):
    def _pick(self, lt, eq):
        return ~(lt | eq)


class GreaterThanOrEqual(_Comparison):
    def _pick(self, lt, eq):
        return ~lt


class EqualNullSafe(_Comparison):
    """``<=>``: NULL <=> NULL is true; never returns NULL."""

    def _cmp_eval(self, xp, l_col, r_col, pick):
        lt, eq = self._lt_eq(xp, l_col, r_col)
        lv, rv = l_col.validity, r_col.validity
        data = (lv & rv & eq) | (~lv & ~rv)
        return data, xp.ones_like(data, dtype=np.bool_)

    def _pick(self, lt, eq):  # pragma: no cover - unused
        return eq

    def eval(self, batch):
        import jax.numpy as jnp
        lc = as_device_column(self.left.eval(batch), batch)
        rc = as_device_column(self.right.eval(batch), batch)
        data, _ = self._cmp_eval(jnp, lc, rc, None)
        # Padding rows must still be invalid.
        return make_column(dt.BOOL, data, batch.row_mask())

    def eval_host(self, batch):
        lc = as_host_column(self.left.eval_host(batch), batch)
        rc = as_host_column(self.right.eval_host(batch), batch)
        if self.left.data_type().is_string:
            lc = _host_strings_to_matrix(lc)
            rc = _host_strings_to_matrix(rc)
        data, _ = self._cmp_eval(np, lc, rc, None)
        return make_host_column(dt.BOOL, data,
                                _all_valid(batch.num_rows))


class Not(UnaryExpression):
    def data_type(self) -> DataType:
        return dt.BOOL

    def do_columnar(self, xp, data, validity, col):
        return ~data, validity


class And(BinaryExpression):
    """Kleene: F & x = F even when x is NULL."""

    def data_type(self) -> DataType:
        return dt.BOOL

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        l_false = l_valid & ~l_data
        r_false = r_valid & ~r_data
        data = l_data & r_data
        validity = (l_valid & r_valid) | l_false | r_false
        return data & l_valid & r_valid, validity


class Or(BinaryExpression):
    """Kleene: T | x = T even when x is NULL."""

    def data_type(self) -> DataType:
        return dt.BOOL

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        l_true = l_valid & l_data
        r_true = r_valid & r_data
        data = l_true | r_true
        validity = (l_valid & r_valid) | l_true | r_true
        return data, validity


class IsNull(UnaryExpression):
    def data_type(self) -> DataType:
        return dt.BOOL

    def eval(self, batch):
        col = as_device_column(self.child.eval(batch), batch)
        return make_column(dt.BOOL, ~col.validity, batch.row_mask())

    def eval_host(self, batch):
        col = as_host_column(self.child.eval_host(batch), batch)
        return make_host_column(dt.BOOL, ~col.validity,
                                _all_valid(batch.num_rows))

    def do_columnar(self, xp, data, validity, col):  # pragma: no cover
        raise AssertionError


class IsNotNull(UnaryExpression):
    def data_type(self) -> DataType:
        return dt.BOOL

    def eval(self, batch):
        col = as_device_column(self.child.eval(batch), batch)
        return make_column(dt.BOOL, col.validity, batch.row_mask())

    def eval_host(self, batch):
        col = as_host_column(self.child.eval_host(batch), batch)
        return make_host_column(dt.BOOL, col.validity,
                                _all_valid(batch.num_rows))

    def do_columnar(self, xp, data, validity, col):  # pragma: no cover
        raise AssertionError


class IsNan(UnaryExpression):
    def data_type(self) -> DataType:
        return dt.BOOL

    def do_columnar(self, xp, data, validity, col):
        return xp.isnan(data), validity


class AtLeastNNonNulls(Expression):
    """True when at least ``n`` of the children are non-null — Spark's
    AtLeastNNonNulls, the predicate behind ``df.na.drop(thresh=n)``.
    Matches Spark exactly: NaN in a float/double child counts as NULL,
    and the result itself is never null."""

    def __init__(self, n: int, *children: Expression):
        self.n = int(n)
        self._children = tuple(children)

    @property
    def children(self):
        return self._children

    def data_type(self) -> DataType:
        return dt.BOOL

    def _count(self, xp, cols):
        acc = None
        for e, c in zip(self._children, cols):
            ok = c.validity
            if e.data_type().is_floating:
                ok = ok & ~xp.isnan(c.data)
            v = ok.astype(np.int32)
            acc = v if acc is None else acc + v
        return acc

    def eval(self, batch):
        import jax.numpy as jnp
        cols = [as_device_column(e.eval(batch), batch)
                for e in self._children]
        acc = self._count(jnp, cols)
        if acc is None:
            acc = jnp.zeros(batch.capacity, jnp.int32)
        return make_column(dt.BOOL, acc >= self.n, batch.row_mask())

    def eval_host(self, batch):
        cols = [as_host_column(e.eval_host(batch), batch)
                for e in self._children]
        acc = self._count(np, cols)
        if acc is None:
            acc = np.zeros(batch.num_rows, np.int32)
        return make_host_column(dt.BOOL, acc >= self.n,
                                _all_valid(batch.num_rows))


class InSet(Expression):
    """value IN (literals) — ref GpuInSet.scala. NULL semantics: if the value
    is NULL, the result is NULL; if no match and the list has a NULL, NULL."""

    def __init__(self, child: Expression, values: Sequence):
        self.child = child
        self.values = tuple(values)

    @property
    def children(self):
        return (self.child,)

    def data_type(self) -> DataType:
        return dt.BOOL

    def _run(self, xp, col, batch_cls):
        t = self.child.data_type()
        has_null = any(v is None for v in self.values)
        present = [v for v in self.values if v is not None]
        if t.is_string:
            lens = col.lengths
            acc = xp.zeros(col.data.shape[0], dtype=np.bool_)
            for v in present:
                b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                w = col.data.shape[1]
                target = np.zeros(w, dtype=np.uint8)
                target[:min(len(b), w)] = np.frombuffer(
                    b[:w], dtype=np.uint8)
                hit = ((col.data == xp.asarray(target)[None, :]).all(axis=1)
                       & (lens == len(b)))
                acc = acc | hit
        else:
            acc = xp.zeros(col.data.shape[0], dtype=np.bool_)
            for v in present:
                if t.is_floating and isinstance(v, float) and np.isnan(v):
                    acc = acc | xp.isnan(col.data)
                else:
                    acc = acc | (col.data == t.np_dtype.type(v))
        validity = col.validity & (acc | (not has_null))
        return acc, validity

    def eval(self, batch):
        import jax.numpy as jnp
        col = as_device_column(self.child.eval(batch), batch)
        data, validity = self._run(jnp, col, None)
        return make_column(dt.BOOL, data, validity)

    def eval_host(self, batch):
        col = as_host_column(self.child.eval_host(batch), batch)
        if self.child.data_type().is_string:
            col = _host_strings_to_matrix(col)
        data, validity = self._run(np, col, None)
        return make_host_column(dt.BOOL, data, validity)
