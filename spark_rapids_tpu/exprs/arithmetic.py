"""Arithmetic expressions (ref: sql-plugin .../sql/rapids/arithmetic.scala).

Spark semantics reproduced:
- ``+ - *`` on numerics use widened common type, overflow wraps (ANSI off).
- ``Divide`` is always floating (Spark casts operands to double); divide by
  zero yields NULL, not Inf.
- ``IntegralDivide`` (``div``) returns long; by-zero -> NULL.
- ``Remainder`` / ``Pmod``: by-zero -> NULL; sign follows Spark (remainder
  takes dividend's sign, pmod is non-negative for positive modulus).
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, Expression, UnaryExpression)


class _Arith(BinaryExpression):
    """Common-type widening binary arithmetic."""

    def data_type(self) -> DataType:
        return dt.common_numeric_type(self.left.data_type(),
                                      self.right.data_type())

    def _prep(self, xp, l_data, r_data):
        t = self.data_type().np_dtype
        return l_data.astype(t), r_data.astype(t)


class Add(_Arith):
    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a, b = self._prep(xp, l_data, r_data)
        return a + b, l_valid & r_valid


class Subtract(_Arith):
    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a, b = self._prep(xp, l_data, r_data)
        return a - b, l_valid & r_valid


class Multiply(_Arith):
    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a, b = self._prep(xp, l_data, r_data)
        return a * b, l_valid & r_valid


class Divide(BinaryExpression):
    """Spark Divide: operands cast to double; x/0 -> NULL."""

    def data_type(self) -> DataType:
        return dt.FLOAT64

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a = l_data.astype(np.float64)
        b = r_data.astype(np.float64)
        zero = b == 0.0
        safe = xp.where(zero, xp.asarray(1.0, dtype=np.float64), b)
        return a / safe, l_valid & r_valid & ~zero


class IntegralDivide(BinaryExpression):
    """Spark ``div``: long integral quotient, truncated toward zero."""

    def data_type(self) -> DataType:
        return dt.INT64

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a = l_data.astype(np.int64)
        b = r_data.astype(np.int64)
        zero = b == 0
        safe = xp.where(zero, xp.asarray(1, dtype=np.int64), b)
        # Java integer division truncates toward zero; xp floor_divide floors.
        q = xp.floor_divide(a, safe)
        rem = a - q * safe
        trunc_fix = (rem != 0) & ((a < 0) != (safe < 0))
        q = xp.where(trunc_fix, q + 1, q)
        return q, l_valid & r_valid & ~zero


class Remainder(_Arith):
    """Spark ``%``: result takes the dividend's sign (Java semantics)."""

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a, b = self._prep(xp, l_data, r_data)
        t = self.data_type()
        zero = b == (0.0 if t.is_floating else 0)
        one = xp.asarray(1, dtype=t.np_dtype)
        safe = xp.where(zero, one, b)
        if t.is_floating:
            r = xp.fmod(a, safe)
        else:
            # xp.remainder floors; convert to truncated (Java) semantics.
            r = xp.remainder(a, safe)
            fix = (r != 0) & ((r < 0) != (a < 0))
            r = xp.where(fix, r - safe, r)
        return r, l_valid & r_valid & ~zero


class Pmod(_Arith):
    """Spark pmod(a, b): ((a % b) + b) % b."""

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a, b = self._prep(xp, l_data, r_data)
        t = self.data_type()
        zero = b == (0.0 if t.is_floating else 0)
        one = xp.asarray(1, dtype=t.np_dtype)
        safe = xp.where(zero, one, b)
        if t.is_floating:
            r = xp.fmod(xp.fmod(a, safe) + safe, safe)
        else:
            r = xp.remainder(xp.remainder(a, safe) + safe, safe)
            fix = (r != 0) & ((r < 0) != (safe < 0))
            r = xp.where(fix, r - safe, r)
        return r, l_valid & r_valid & ~zero


class UnaryMinus(UnaryExpression):
    def data_type(self) -> DataType:
        return self.child.data_type()

    def do_columnar(self, xp, data, validity, col):
        return -data, validity


class UnaryPositive(UnaryExpression):
    def data_type(self) -> DataType:
        return self.child.data_type()

    def do_columnar(self, xp, data, validity, col):
        return data, validity


class Abs(UnaryExpression):
    def data_type(self) -> DataType:
        return self.child.data_type()

    def do_columnar(self, xp, data, validity, col):
        return xp.abs(data), validity


class Least(Expression):
    """least(...) — NULLs skipped; NULL only if all inputs NULL."""

    def __init__(self, *children: Expression):
        self._children = tuple(children)

    @property
    def children(self):
        return self._children

    def data_type(self) -> DataType:
        t = self._children[0].data_type()
        for c in self._children[1:]:
            t = dt.common_numeric_type(t, c.data_type())
        return t

    _want_smaller = True

    def _lt(self, xp, a, b):
        """Spark ordering: NaN equal to NaN and greater than everything."""
        if self.data_type().is_floating:
            na, nb = xp.isnan(a), xp.isnan(b)
            return (~na & nb) | ((a < b) & ~na & ~nb)
        return a < b

    def _fold(self, xp, cols):
        t = self.data_type()
        data = None
        validity = None
        for d, v in cols:
            d = d.astype(t.np_dtype)
            if data is None:
                data, validity = d, v
                continue
            if self._want_smaller:
                better = self._lt(xp, d, data)
            else:
                better = self._lt(xp, data, d)
            # NULLs are skipped: an invalid accumulator always loses to a
            # valid operand and vice versa.
            take_new = v & (~validity | better)
            data = xp.where(take_new, d, data)
            validity = validity | v
        return data, validity

    def eval(self, batch):
        import jax.numpy as jnp
        from spark_rapids_tpu.exprs.base import as_device_column, make_column
        cols = [as_device_column(c.eval(batch), batch) for c in self._children]
        data, validity = self._fold(jnp, [(c.data, c.validity) for c in cols])
        return make_column(self.data_type(), data, validity)

    def eval_host(self, batch):
        from spark_rapids_tpu.exprs.base import as_host_column, make_host_column
        cols = [as_host_column(c.eval_host(batch), batch)
                for c in self._children]
        data, validity = self._fold(np, [(c.data, c.validity) for c in cols])
        return make_host_column(self.data_type(), data, validity)


class Greatest(Least):
    _want_smaller = False


# -- bitwise (ref: .../sql/rapids/bitwise.scala) -----------------------------

class BitwiseAnd(_Arith):
    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a, b = self._prep(xp, l_data, r_data)
        return a & b, l_valid & r_valid


class BitwiseOr(_Arith):
    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a, b = self._prep(xp, l_data, r_data)
        return a | b, l_valid & r_valid


class BitwiseXor(_Arith):
    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a, b = self._prep(xp, l_data, r_data)
        return a ^ b, l_valid & r_valid


class BitwiseNot(UnaryExpression):
    def data_type(self) -> DataType:
        return self.child.data_type()

    def do_columnar(self, xp, data, validity, col):
        return ~data, validity


class ShiftLeft(BinaryExpression):
    """Java ``<<``: shift count masked to the width of the left operand."""

    def data_type(self) -> DataType:
        return self.left.data_type()

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        bits = self.data_type().itemsize * 8
        sh = (r_data.astype(np.int32) & (bits - 1)).astype(l_data.dtype)
        return l_data << sh, l_valid & r_valid


class ShiftRight(ShiftLeft):
    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        bits = self.data_type().itemsize * 8
        sh = (r_data.astype(np.int32) & (bits - 1)).astype(l_data.dtype)
        return l_data >> sh, l_valid & r_valid


class ShiftRightUnsigned(ShiftLeft):
    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        bits = self.data_type().itemsize * 8
        sh = (r_data.astype(np.int32) & (bits - 1))
        ut = np.dtype(f"uint{bits}")
        u = l_data.astype(ut) >> sh.astype(ut)
        return u.astype(l_data.dtype), l_valid & r_valid
