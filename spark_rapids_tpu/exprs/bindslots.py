"""Literal bind slots: runtime-bound literals for the parameterized plan
cache (plan/plan_cache.py).

A ``Literal`` is a trace-time CONSTANT: jax bakes its value into the
compiled program, so the kernel cache must fold literal values into its
structural fingerprints and a repeated query with a new filter constant
re-traces every kernel it touches. A :class:`BindSlotExpr` is the
value-free replacement the plan cache hoists bindable literals into: the
expression carries only ``(slot, dtype)`` — the VALUE arrives at
execution time through :func:`bound_literals`, as a traced jnp scalar on
the device path (a runtime kernel input, so one compiled executable
serves every binding of the same dtype) and as a plain python value on
the host path.

Plumbing contract (mirrors exprs/nondeterministic.EvalContext):

- The execution's binding vector lives in ``ctx.cache["plan_binds"]``
  (python values) + ``ctx.cache["plan_bind_dtypes"]`` — installed by
  ``PhysicalPlan.collect`` from the bound plan, so it reaches pipeline
  prefetch threads, stage workers and watchdog attempts for free.
- Kernel CALL SITES (Project/Filter/FusedStage and the contextual loop,
  ops/) fetch :func:`device_bind_args` and pass the tuple as an extra
  jitted argument; inside the traced function the body runs under
  ``with bound_literals(binds)`` so :meth:`BindSlotExpr.eval` reads its
  slot as a tracer. Host paths wrap their eval in
  ``bound_literals(host_bind_args(ctx))`` with raw python values.
- Plan attributes that stay host-side python ints (limit budgets, scan
  pushdown predicate values) use :class:`BindValue` markers resolved via
  :func:`resolve_bound`.

This module deliberately imports only exprs.base + columnar leaves so
every layer above (ops, plan) can use it without cycles.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exprs.base import Expression, Scalar

_BOUND: contextvars.ContextVar[Optional[Tuple]] = \
    contextvars.ContextVar("srt_bound_literals", default=None)


@contextlib.contextmanager
def bound_literals(values: Sequence[Any]):
    """Install the execution's binding vector for the enclosed eval.
    Under jit this runs at TRACE time, so slot reads become traced
    inputs of the compiled program."""
    token = _BOUND.set(tuple(values))
    try:
        yield
    finally:
        _BOUND.reset(token)


def current_bound_literals() -> Optional[Tuple]:
    return _BOUND.get()


@dataclasses.dataclass
class BindSlotExpr(Expression):
    """A hoisted literal: dtype-typed, VALUE-FREE leaf. Two bindings of
    the same dtype share one kernel-cache fingerprint — the cache
    correctness contract is preserved because the value is a runtime
    input, never a trace constant."""

    slot: int
    dtype: DataType

    def data_type(self) -> DataType:
        return self.dtype

    def _value(self):
        vals = _BOUND.get()
        if vals is None or self.slot >= len(vals):
            raise RuntimeError(
                f"bind slot {self.slot} evaluated without bound literals "
                "(plan-cache template executed outside a bound "
                "collect?)")
        return vals[self.slot]

    def eval(self, batch) -> DeviceColumn:
        val = self._value()
        mask = batch.row_mask()
        # Same expansion expand_scalar does for a non-null scalar, but
        # tracer-safe: the value may be a traced jnp scalar.
        data = jnp.where(mask, jnp.asarray(val).astype(self.dtype.np_dtype),
                         jnp.zeros((), self.dtype.np_dtype))
        return DeviceColumn(self.dtype, data, mask)

    def eval_host(self, batch) -> Scalar:
        v = self._value()
        if hasattr(v, "item"):      # device scalar leaked to host path
            v = v.item()
        if self.dtype is dt.BOOL:
            v = bool(v)
        elif self.dtype.is_integral or self.dtype.is_datetime:
            v = int(v)
        elif self.dtype.is_floating:
            v = float(v)
        return Scalar(self.dtype, v)

    def pretty(self) -> str:
        return f"?{self.slot}:{self.dtype.name}"


@dataclasses.dataclass(frozen=True)
class BindValue:
    """Slot marker for host-side python plan attributes (limit budgets,
    scan pushdown predicate values): resolved at execute time via
    :func:`resolve_bound`, never traced."""

    slot: int


def resolve_bound(v: Any, ctx) -> Any:
    """Resolve a possibly-slot-bound plan attribute to its value for
    THIS execution (``ctx.cache['plan_binds']``)."""
    if not isinstance(v, BindValue):
        return v
    binds = None if ctx is None else ctx.cache.get("plan_binds")
    if binds is None:
        binds = current_bound_literals()
    if binds is None or v.slot >= len(binds):
        raise RuntimeError(
            f"bind value slot {v.slot} resolved without bound literals")
    return binds[v.slot]


def has_bind_slots(exprs: Sequence[Expression]) -> bool:
    """True when any expression tree contains a bind slot (the call-site
    gate for passing the binding vector into the jitted kernel)."""
    def rec(e: Expression) -> bool:
        if isinstance(e, BindSlotExpr):
            return True
        return any(rec(c) for c in e.children)
    return any(rec(e) for e in exprs)


def device_bind_args(ctx) -> Tuple:
    """This execution's binding vector as dtype-committed jnp scalars,
    built once per context (the tuple is what call sites pass as the
    extra jitted argument — stable dtypes mean a stable jit signature
    across bindings)."""
    cached = ctx.cache.get("plan_binds_dev")
    if cached is None:
        vals = ctx.cache.get("plan_binds") or ()
        dts = ctx.cache.get("plan_bind_dtypes") or ()
        cached = tuple(jnp.asarray(v, t.np_dtype)
                       for v, t in zip(vals, dts))
        ctx.cache["plan_binds_dev"] = cached
    return cached


def host_bind_args(ctx) -> Tuple:
    """The raw python binding vector for host-engine eval."""
    return tuple(ctx.cache.get("plan_binds") or ())
