"""Conditionals & null expressions (ref: conditionalExpressions.scala 251 LoC,
nullExpressions.scala 297 LoC).

If / CaseWhen / Coalesce / Nvl / NaNvl / NormalizeNaNAndZero. Whole-batch
evaluation: every branch is evaluated over all rows, results blended with
``where`` — the columnar trade-off the reference makes too (and exactly what
XLA ``select`` wants; no divergent control flow).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, Expression, UnaryExpression,
    as_device_column, as_host_column, make_column, make_host_column)


def _repad_matrix(xp, data, w: int):
    """Widen (zero-pad) or narrow a raw (n, cur) byte matrix to width w."""
    cur = data.shape[1]
    if cur == w:
        return data
    if cur < w:
        return xp.concatenate(
            [data, xp.zeros((data.shape[0], w - cur), np.uint8)], axis=1)
    return data[:, :w]


def _blend(xp, cond, a_col, b_col, dtype):
    """Select a_col where cond else b_col; returns (data, validity, lengths).

    Operates on anything with (data, validity[, lengths]) attributes —
    including the dtype-less accumulator wrappers CaseWhen/Coalesce fold
    through — so string widening happens on the raw matrices."""
    if dtype.is_string:
        w = max(a_col.data.shape[1], b_col.data.shape[1])
        a_data = _repad_matrix(xp, a_col.data, w)
        b_data = _repad_matrix(xp, b_col.data, w)
        data = xp.where(cond[:, None], a_data, b_data)
        lengths = xp.where(cond, a_col.lengths, b_col.lengths)
        validity = xp.where(cond, a_col.validity, b_col.validity)
        return data, validity, lengths
    data = xp.where(cond, a_col.data, b_col.data)
    validity = xp.where(cond, a_col.validity, b_col.validity)
    return data, validity, None


def _host_adapt(col, dtype):
    from spark_rapids_tpu.columnar.host import StringMatrixView
    return StringMatrixView.of(col) if dtype.is_string else col


def _host_blend(cond, a_col, b_col, dtype):
    return _blend(np, cond, a_col, b_col, dtype)


@dataclasses.dataclass
class _Acc:
    """The (data, validity, lengths) accumulator CaseWhen/Coalesce fold
    through — shaped like a column for _blend but dtype-agnostic."""

    data: object
    validity: object
    lengths: object = None


def _matrix_to_host_strings(data, lengths, validity, dtype):
    from spark_rapids_tpu.columnar.host import matrix_to_strings
    return matrix_to_strings(data, lengths, validity)


class If(Expression):
    """if(cond, a, b): Spark's If takes the false branch whenever the
    predicate is not true — including when it is NULL."""

    def __init__(self, predicate: Expression, true_value: Expression,
                 false_value: Expression):
        self.predicate = predicate
        self.true_value = true_value
        self.false_value = false_value

    @property
    def children(self):
        return (self.predicate, self.true_value, self.false_value)

    def data_type(self) -> DataType:
        return self.true_value.data_type()

    def eval(self, batch):
        t = self.data_type()
        p = as_device_column(self.predicate.eval(batch), batch)
        a = as_device_column(self.true_value.eval(batch), batch)
        b = as_device_column(self.false_value.eval(batch), batch)
        cond = p.data & p.validity
        data, validity, lengths = _blend(jnp, cond, a, b, t)
        return make_column(t, data, validity & batch.row_mask(), lengths)

    def eval_host(self, batch):
        t = self.data_type()
        p = as_host_column(self.predicate.eval_host(batch), batch)
        a = _host_adapt(as_host_column(self.true_value.eval_host(batch),
                                       batch), t)
        b = _host_adapt(as_host_column(self.false_value.eval_host(batch),
                                       batch), t)
        cond = p.data & p.validity
        data, validity, lengths = _host_blend(cond, a, b, t)
        if t.is_string:
            return _matrix_to_host_strings(data, lengths, validity, t)
        return make_host_column(t, data, validity)


class CaseWhen(Expression):
    """CASE WHEN c1 THEN v1 ... ELSE e END. Branch predicates are evaluated
    over the whole batch; first-true-wins blending right-to-left."""

    def __init__(self, branches: Sequence[Tuple[Expression, Expression]],
                 else_value: Optional[Expression] = None):
        self.branches = list(branches)
        self.else_value = else_value

    @property
    def children(self):
        out: List[Expression] = []
        for c, v in self.branches:
            out.extend((c, v))
        if self.else_value is not None:
            out.append(self.else_value)
        return tuple(out)

    def data_type(self) -> DataType:
        return self.branches[0][1].data_type()

    def _run(self, batch, device: bool):
        t = self.data_type()
        if device:
            getcol = lambda e: as_device_column(e.eval(batch), batch)
            blend = lambda cond, a, b: _blend(jnp, cond, a, b, t)
        else:
            getcol = lambda e: _host_adapt(
                as_host_column(e.eval_host(batch), batch), t)
            blend = lambda cond, a, b: _host_blend(cond, a, b, t)

        # Start from the ELSE value (typed NULLs when absent).
        from spark_rapids_tpu.exprs.base import Literal
        acc = getcol(self.else_value or Literal(t, None))
        for cond_e, val_e in reversed(self.branches):
            c = getcol(cond_e) if device else \
                as_host_column(cond_e.eval_host(batch), batch)
            cond = c.data & c.validity
            acc = _Acc(*blend(cond, getcol(val_e), acc))
        return acc

    def eval(self, batch):
        t = self.data_type()
        acc = self._run(batch, device=True)
        return make_column(t, acc.data, acc.validity & batch.row_mask(),
                           acc.lengths)

    def eval_host(self, batch):
        t = self.data_type()
        acc = self._run(batch, device=False)
        if t.is_string:
            return _matrix_to_host_strings(acc.data, acc.lengths,
                                           acc.validity, t)
        return make_host_column(t, acc.data, acc.validity)


class Coalesce(Expression):
    """First non-null argument (nullExpressions.scala)."""

    def __init__(self, *children: Expression):
        self._children = tuple(children)

    @property
    def children(self):
        return self._children

    def data_type(self) -> DataType:
        return self._children[0].data_type()

    def eval(self, batch):
        t = self.data_type()
        acc = as_device_column(self._children[-1].eval(batch), batch)
        for e in reversed(self._children[:-1]):
            c = as_device_column(e.eval(batch), batch)
            acc = _Acc(*_blend(jnp, c.validity, c, acc, t))
        return make_column(t, acc.data, acc.validity & batch.row_mask(),
                           getattr(acc, "lengths", None))

    def eval_host(self, batch):
        t = self.data_type()
        acc = _host_adapt(as_host_column(self._children[-1].eval_host(batch),
                                         batch), t)
        for e in reversed(self._children[:-1]):
            c = _host_adapt(as_host_column(e.eval_host(batch), batch), t)
            acc = _Acc(*_host_blend(c.validity, c, acc, t))
        if t.is_string:
            return _matrix_to_host_strings(acc.data, acc.lengths,
                                           acc.validity, t)
        return make_host_column(t, acc.data, acc.validity)


def Nvl(a: Expression, b: Expression) -> Coalesce:
    return Coalesce(a, b)


class NaNvl(BinaryExpression):
    """nanvl(a, b): b where a is NaN."""

    def data_type(self) -> DataType:
        return self.left.data_type()

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        nan = xp.isnan(l_data)
        data = xp.where(nan, r_data.astype(l_data.dtype), l_data)
        validity = xp.where(nan, r_valid, l_valid)
        return data, validity


class NormalizeNaNAndZero(UnaryExpression):
    """Canonicalize NaN bit patterns and -0.0 -> 0.0 (Spark's
    NormalizeFloatingNumbers, used before grouping/joining on floats)."""

    def data_type(self) -> DataType:
        return self.child.data_type()

    def do_columnar(self, xp, data, validity, col):
        data = xp.where(xp.isnan(data),
                        xp.asarray(np.nan, dtype=data.dtype), data)
        data = xp.where(data == 0, xp.abs(data), data)
        return data, validity


class KnownFloatingPointNormalized(UnaryExpression):
    """Marker pass-through (constraintExpressions.scala)."""

    def data_type(self) -> DataType:
        return self.child.data_type()

    def do_columnar(self, xp, data, validity, col):
        return data, validity
