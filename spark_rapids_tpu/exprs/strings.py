"""String expressions (ref: .../sql/rapids/stringFunctions.scala 862 LoC).

TPU-first string layout: each column is a dense ``(N, W) uint8`` matrix plus
int32 lengths (columnar/batch.py). Every op below is expressed as dense
vector ops over that matrix (VPU-friendly), not gathers over a ragged heap:

- upper/lower: branchless ASCII case flip (locale-sensitive Unicode casing is
  the same incompat the reference flags on GpuUpper/GpuLower).
- length/substring: UTF-8 aware via the char-start mask
  ``(b & 0xC0) != 0x80`` and cumulative sums.
- contains/startswith/endswith/locate/like: sliding-window equality over the
  width axis — O(W * |needle|) fused elementwise work instead of per-row
  loops.
- byte packing (left-compaction after substring/trim) via a stable argsort on
  the keep mask — XLA lowers this to a bitonic sort over W lanes.

replace / regexp_replace route through the host engine (python re), same
boundary the reference draws at GpuRegExpReplace's cudf limitations.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np
from spark_rapids_tpu.columnar.host import all_valid as _host_all_valid

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.exprs.base import (
    BinaryExpression, Expression, Scalar, UnaryExpression,
    as_device_column, as_host_column, make_column, make_host_column)


# ---------------------------------------------------------------------------
# Dense byte-matrix primitives
# ---------------------------------------------------------------------------

def byte_mask(xp, width: int, lengths) -> "np.ndarray":
    """(N, W) bool — True for bytes inside the string."""
    return xp.arange(width, dtype=np.int32)[None, :] < lengths[:, None]


def char_starts(xp, data, lengths):
    """(N, W) bool — True at the first byte of each UTF-8 codepoint."""
    return ((data & 0xC0) != 0x80) & byte_mask(xp, data.shape[1], lengths)


def pack_left(xp, data, keep) -> Tuple["np.ndarray", "np.ndarray"]:
    """Compact kept bytes to the left of each row; returns (data, lengths)."""
    w = data.shape[1]
    key = (~keep).astype(np.int8)
    if xp is np:
        order = np.argsort(key, axis=1, kind="stable")
    else:
        order = xp.argsort(key, axis=1, stable=True)
    packed = xp.take_along_axis(data, order, axis=1)
    counts = keep.sum(axis=1).astype(np.int32)
    live = xp.arange(w, dtype=np.int32)[None, :] < counts[:, None]
    return xp.where(live, packed, 0), counts


def _char_count(xp, data, lengths):
    return char_starts(xp, data, lengths).sum(axis=1).astype(np.int32)


from spark_rapids_tpu.columnar.host import (
    matrix_to_strings as _matrix_to_host, strings_to_matrix as
    _host_to_matrix)


class StringUnary(Expression):
    """Template for string->string ops defined on the byte matrix."""

    def __init__(self, child: Expression):
        self.child = child

    @property
    def children(self):
        return (self.child,)

    def data_type(self) -> DataType:
        return dt.STRING

    def kernel(self, xp, data, lengths, validity):
        """Return (data, lengths, validity)."""
        raise NotImplementedError

    def eval(self, batch: DeviceBatch):
        col = as_device_column(self.child.eval(batch), batch)
        data, lengths, validity = self.kernel(jnp, col.data, col.lengths,
                                              col.validity)
        return make_column(dt.STRING, data, validity, lengths)

    def eval_host(self, batch: HostBatch):
        col = as_host_column(self.child.eval_host(batch), batch)
        m, lens = _host_to_matrix(col)
        data, lengths, validity = self.kernel(np, m, lens, col.validity)
        return _matrix_to_host(data, lengths, validity)


class Upper(StringUnary):
    def kernel(self, xp, data, lengths, validity):
        lower = (data >= ord("a")) & (data <= ord("z"))
        return xp.where(lower, data - 32, data), lengths, validity


class Lower(StringUnary):
    def kernel(self, xp, data, lengths, validity):
        upper = (data >= ord("A")) & (data <= ord("Z"))
        return xp.where(upper, data + 32, data), lengths, validity


class Length(Expression):
    """Character (codepoint) length, like Spark's length()."""

    def __init__(self, child: Expression):
        self.child = child

    @property
    def children(self):
        return (self.child,)

    def data_type(self) -> DataType:
        return dt.INT32

    def eval(self, batch):
        col = as_device_column(self.child.eval(batch), batch)
        n = _char_count(jnp, col.data, col.lengths)
        return make_column(dt.INT32, n, col.validity)

    def eval_host(self, batch):
        col = as_host_column(self.child.eval_host(batch), batch)
        m, lens = _host_to_matrix(col)
        n = _char_count(np, m, lens)
        return make_host_column(dt.INT32, n, col.validity)


class Substring(Expression):
    """substring(str, pos, len) — 1-based, character-addressed, negative pos
    counts from the end (Spark semantics; ref GpuSubstring)."""

    def __init__(self, child: Expression, pos: Expression, length: Expression):
        self.child = child
        self.pos = pos
        self.length = length

    @property
    def children(self):
        return (self.child, self.pos, self.length)

    def data_type(self) -> DataType:
        return dt.STRING

    def _kernel(self, xp, data, lengths, validity, pos, slen):
        w = data.shape[1]
        starts = char_starts(xp, data, lengths)
        nchars = starts.sum(axis=1).astype(np.int64)
        # char index of each byte (0-based); bytes of char k get k.
        cidx = (xp.cumsum(starts.astype(np.int32), axis=1) - 1) \
            .astype(np.int64)
        # int64 throughout: substr(s, pos) desugars to len = Int.MaxValue,
        # and start + len must not wrap.
        pos = pos.astype(np.int64)
        slen = xp.maximum(slen.astype(np.int64), 0)
        # Spark: pos>0 -> 1-based from start; pos<0 -> from end; pos==0 -> 1.
        start = xp.where(pos > 0, pos - 1,
                         xp.where(pos < 0, nchars + pos, 0))
        start0 = xp.maximum(start, 0)
        end = start0 + xp.where(start < 0,
                                xp.maximum(slen + start, 0), slen)
        inside = byte_mask(xp, w, lengths)
        keep = inside & (cidx >= start0[:, None]) & (cidx < end[:, None])
        out, out_len = pack_left(xp, data, keep)
        return out, out_len, validity

    def eval(self, batch):
        col = as_device_column(self.child.eval(batch), batch)
        p = as_device_column(self.pos.eval(batch), batch)
        l = as_device_column(self.length.eval(batch), batch)
        data, lengths, validity = self._kernel(
            jnp, col.data, col.lengths,
            col.validity & p.validity & l.validity, p.data, l.data)
        return make_column(dt.STRING, data, validity, lengths)

    def eval_host(self, batch):
        col = as_host_column(self.child.eval_host(batch), batch)
        p = as_host_column(self.pos.eval_host(batch), batch)
        l = as_host_column(self.length.eval_host(batch), batch)
        m, lens = _host_to_matrix(col)
        data, lengths, validity = self._kernel(
            np, m, lens, col.validity & p.validity & l.validity,
            p.data, l.data)
        return _matrix_to_host(data, lengths, validity)


def _sliding_match(xp, data, lengths, needle: bytes):
    """(N, W) bool — True at byte offset i iff needle matches starting at i
    and fits inside the string."""
    n, w = data.shape
    m = len(needle)
    if m == 0:
        return byte_mask(xp, w, lengths + 1)  # empty matches everywhere
    if m > w:
        return xp.zeros((n, w), dtype=np.bool_)
    acc = xp.ones((n, w), dtype=np.bool_)
    for j, byte in enumerate(needle):
        # data shifted left by j: data[:, i+j] compared to needle[j]
        shifted = xp.concatenate(
            [data[:, j:], xp.zeros((n, j), np.uint8)], axis=1)
        acc = acc & (shifted == byte)
    fits = (xp.arange(w, dtype=np.int32)[None, :]
            <= (lengths - m)[:, None])
    return acc & fits


class _NeedleOp(Expression):
    """Binary string op whose right side must be a literal (same restriction
    the reference places on Like/StartsWith/EndsWith needles)."""

    def __init__(self, child: Expression, needle: Expression):
        self.child = child
        self.needle = needle

    @property
    def children(self):
        return (self.child, self.needle)

    def data_type(self) -> DataType:
        return dt.BOOL

    def _needle_bytes(self, batch, device: bool) -> Tuple[bytes, bool]:
        v = self.needle.eval(batch) if device else \
            self.needle.eval_host(batch)
        assert isinstance(v, Scalar), \
            f"{type(self).__name__} needle must be a literal"
        if v.is_null:
            return b"", True
        return v.as_bytes(), False

    def _match(self, xp, data, lengths, needle: bytes):
        raise NotImplementedError

    def eval(self, batch):
        col = as_device_column(self.child.eval(batch), batch)
        needle, null = self._needle_bytes(batch, True)
        if null:
            return make_column(dt.BOOL,
                               jnp.zeros(batch.capacity, np.bool_),
                               jnp.zeros(batch.capacity, np.bool_))
        data = self._match(jnp, col.data, col.lengths, needle)
        return make_column(dt.BOOL, data, col.validity)

    def eval_host(self, batch):
        col = as_host_column(self.child.eval_host(batch), batch)
        needle, null = self._needle_bytes(batch, False)
        if null:
            z = np.zeros(batch.num_rows, np.bool_)
            return make_host_column(dt.BOOL, z, z.copy())
        m, lens = _host_to_matrix(col)
        data = self._match(np, m, lens, needle)
        return make_host_column(dt.BOOL, data, col.validity)


class Contains(_NeedleOp):
    def _match(self, xp, data, lengths, needle):
        return _sliding_match(xp, data, lengths, needle).any(axis=1)


class StartsWith(_NeedleOp):
    def _match(self, xp, data, lengths, needle):
        hits = _sliding_match(xp, data, lengths, needle)
        return hits[:, 0] if hits.shape[1] > 0 else \
            xp.zeros(data.shape[0], np.bool_)


class EndsWith(_NeedleOp):
    def _match(self, xp, data, lengths, needle):
        hits = _sliding_match(xp, data, lengths, needle)
        m = len(needle)
        w = data.shape[1]
        pos = xp.clip(lengths - m, 0, max(w - 1, 0))
        at_end = xp.take_along_axis(hits, pos[:, None].astype(np.int32),
                                    axis=1)[:, 0]
        return at_end & (lengths >= m)


def _greedy_matches(xp, hits, m: int):
    """Greedy left-to-right non-overlapping occurrence selection over the
    sliding-window hits (N, W): a hit is REAL iff no real hit covers it —
    the scan Java's indexOf loop performs, vectorized over rows. The
    device path uses ``lax.scan`` over the width axis (constant compile
    cost in W); the host path loops columns in numpy."""
    n, w = hits.shape
    if m <= 1 or w == 0:
        return hits
    if xp is np:
        cols = []
        next_free = np.zeros((n,), np.int32)
        for j in range(w):
            real_j = hits[:, j] & (next_free <= j)
            next_free = np.where(real_j, j + m, next_free)
            cols.append(real_j)
        return np.stack(cols, axis=1)
    import jax

    def step(next_free, xs):
        hits_j, j = xs
        real_j = hits_j & (next_free <= j)
        return xp.where(real_j, j + m, next_free), real_j

    _, reals = jax.lax.scan(
        step, xp.zeros((n,), jnp.int32),
        (hits.T, xp.arange(w, dtype=jnp.int32)))
    return reals.T


def _delim_scan(xp, data, lengths, delim: bytes):
    """(occ_incl, completed, total) for the greedy occurrences of
    ``delim``: occ_incl[j] = occurrences STARTED at or before byte j,
    completed[j] = occurrences fully before byte j, total = count."""
    m = len(delim)
    real = _greedy_matches(xp, _sliding_match(xp, data, lengths, delim), m)
    occ_incl = xp.cumsum(real.astype(np.int32), axis=1)
    n, w = data.shape
    if w > m:
        completed = xp.concatenate(
            [xp.zeros((n, m), np.int32), occ_incl[:, :-m]], axis=1)
    else:
        completed = xp.zeros((n, w), np.int32)
    total = occ_incl[:, -1] if w else xp.zeros((n,), np.int32)
    return occ_incl, completed, total


class SubstringIndex(StringUnary):
    """substring_index(str, delim, count) — Spark/Hive semantics over a
    LITERAL delimiter (the same restriction as GpuSubstringIndex):
    count>0 keeps everything before the count-th occurrence, count<0
    everything after the |count|-th occurrence from the end, count==0 is
    empty; fewer occurrences than |count| keeps the whole string."""

    def __init__(self, child: Expression, delim: str, count: int):
        super().__init__(child)
        if not delim:
            raise ValueError(
                "substring_index delimiter must be a non-empty literal")
        self.delim = delim
        self.count = int(count)

    def kernel(self, xp, data, lengths, validity):
        delim = self.delim.encode("utf-8")
        occ_incl, completed, total = _delim_scan(xp, data, lengths, delim)
        inside = byte_mask(xp, data.shape[1], lengths)
        if self.count > 0:
            keep = inside & (occ_incl < self.count)
        elif self.count < 0:
            k = -self.count
            keep = inside & (completed >= (total - k + 1)[:, None])
        else:
            keep = xp.zeros_like(inside)
        out, out_len = pack_left(xp, data, keep)
        return out, out_len, validity


class StringSplit(Expression):
    """split(str, delim)[index] — the element-access form of Spark's
    StringSplit (array columns are not a device type here; the
    ubiquitous split(...).getItem(i) pattern lowers to this). The
    delimiter is a LITERAL matched verbatim (no regex — the reference's
    GpuStringSplit carries the same pattern restriction); out-of-range
    or negative indices yield NULL, and Spark's limit=-1 semantics keep
    trailing empty elements."""

    def __init__(self, child: Expression, delim: str, index: int):
        if not delim:
            raise ValueError("split delimiter must be a non-empty literal")
        self.child = child
        self.delim = delim
        self.index = int(index)

    @property
    def children(self):
        return (self.child,)

    def data_type(self) -> DataType:
        return dt.STRING

    def _kernel(self, xp, data, lengths, validity):
        delim = self.delim.encode("utf-8")
        occ_incl, completed, total = _delim_scan(xp, data, lengths, delim)
        inside = byte_mask(xp, data.shape[1], lengths)
        in_delim = (occ_incl - completed) > 0
        if self.index < 0:
            keep = xp.zeros_like(inside)
            valid = xp.zeros_like(validity)
        else:
            keep = inside & ~in_delim & (completed == self.index)
            valid = validity & (self.index < total + 1)
        out, out_len = pack_left(xp, data, keep)
        return out, out_len, valid

    def eval(self, batch: DeviceBatch):
        col = as_device_column(self.child.eval(batch), batch)
        data, lengths, validity = self._kernel(
            jnp, col.data, col.lengths, col.validity)
        return make_column(dt.STRING, data, validity, lengths)

    def eval_host(self, batch: HostBatch):
        col = as_host_column(self.child.eval_host(batch), batch)
        m, lens = _host_to_matrix(col)
        data, lengths, validity = self._kernel(np, m, lens, col.validity)
        return _matrix_to_host(data, lengths, validity)


class StringLocate(Expression):
    """locate(needle, str, start=1): 1-based char position of first match at
    or after ``start``; 0 if absent (ref GpuStringLocate)."""

    def __init__(self, needle: Expression, child: Expression,
                 start: Expression):
        self.needle = needle
        self.child = child
        self.start = start

    @property
    def children(self):
        return (self.needle, self.child, self.start)

    def data_type(self) -> DataType:
        return dt.INT32

    def _kernel(self, xp, data, lengths, needle: bytes, start):
        w = data.shape[1]
        hits = _sliding_match(xp, data, lengths, needle)
        starts = char_starts(xp, data, lengths)
        cidx = xp.cumsum(starts.astype(np.int32), axis=1) - 1  # char of byte
        # Only hits at char starts count; char position must be >= start-1.
        ok = hits & starts & (cidx >= (start - 1)[:, None])
        any_hit = ok.any(axis=1)
        first_byte = xp.argmax(ok, axis=1)
        charpos = xp.take_along_axis(
            cidx, first_byte[:, None].astype(np.int32), axis=1)[:, 0] + 1
        res = xp.where(any_hit, charpos, 0)
        # Empty needle: Spark returns start if start <= len+1, else 0.
        if len(needle) == 0:
            res = xp.where(start <= _char_count(xp, data, lengths) + 1,
                           start, 0)
        # Spark short-circuits any start < 1 to 0.
        return xp.where(start >= 1, res, 0).astype(np.int32)

    def eval(self, batch):
        col = as_device_column(self.child.eval(batch), batch)
        nv = self.needle.eval(batch)
        sv = as_device_column(self.start.eval(batch), batch)
        assert isinstance(nv, Scalar), "locate needle must be a literal"
        if nv.is_null:
            z = jnp.zeros(batch.capacity, np.bool_)
            return make_column(dt.INT32,
                               jnp.zeros(batch.capacity, np.int32), z)
        data = self._kernel(jnp, col.data, col.lengths, nv.as_bytes(),
                            sv.data.astype(np.int32))
        return make_column(dt.INT32, data, col.validity & sv.validity)

    def eval_host(self, batch):
        col = as_host_column(self.child.eval_host(batch), batch)
        nv = self.needle.eval_host(batch)
        sv = as_host_column(self.start.eval_host(batch), batch)
        if nv.is_null:
            z = np.zeros(batch.num_rows, np.bool_)
            return make_host_column(dt.INT32,
                                    np.zeros(batch.num_rows, np.int32), z)
        m, lens = _host_to_matrix(col)
        data = self._kernel(np, m, lens, nv.as_bytes(),
                            sv.data.astype(np.int32))
        return make_host_column(dt.INT32, data, col.validity & sv.validity)


class ConcatStrings(Expression):
    """concat(s1, s2, ...): NULL if any input NULL (Spark concat).

    Device kernel: output byte j of row r comes from whichever input the
    running length prefix places there — computed with shifted gathers, no
    per-row loops.
    """

    def __init__(self, *children: Expression):
        self._children = tuple(children)

    @property
    def children(self):
        return self._children

    def data_type(self) -> DataType:
        return dt.STRING

    @staticmethod
    def _concat2(xp, a_data, a_len, b_data, b_len):
        n = a_data.shape[0]
        wa, wb = a_data.shape[1], b_data.shape[1]
        w = wa + wb
        j = xp.arange(w, dtype=np.int32)[None, :]            # (1, W)
        from_a = j < a_len[:, None]
        # byte index into b for output position j
        bj = xp.clip(j - a_len[:, None], 0, max(wb - 1, 0))
        a_pad = xp.concatenate(
            [a_data, xp.zeros((n, w - wa), np.uint8)], axis=1)
        b_g = xp.take_along_axis(
            xp.concatenate([b_data, xp.zeros((n, w - wb), np.uint8)], axis=1),
            bj, axis=1)
        out = xp.where(from_a, a_pad, b_g)
        out_len = a_len + b_len
        live = xp.arange(w, dtype=np.int32)[None, :] < out_len[:, None]
        return xp.where(live, out, 0), out_len

    def _run(self, xp, cols):
        data, lengths, validity = cols[0]
        for d, l, v in cols[1:]:
            data, lengths = self._concat2(xp, data, lengths, d, l)
            validity = validity & v
        return data, lengths, validity

    def eval(self, batch):
        cols = []
        for c in self._children:
            col = as_device_column(c.eval(batch), batch)
            cols.append((col.data, col.lengths, col.validity))
        data, lengths, validity = self._run(
            jnp, [(d, l, v) for d, l, v in cols])
        return make_column(dt.STRING, data, validity, lengths)

    def eval_host(self, batch):
        cols = []
        for c in self._children:
            col = as_host_column(c.eval_host(batch), batch)
            m, lens = _host_to_matrix(col)
            cols.append((m, lens, col.validity))
        data, lengths, validity = self._run(np, cols)
        return _matrix_to_host(data, lengths, validity)


class StringTrim(StringUnary):
    """trim(): strip leading+trailing spaces (0x20), like Spark default.

    All-space strings trim to empty (``keep &= has``)."""

    def kernel(self, xp, data, lengths, validity):
        w = data.shape[1]
        inside = byte_mask(xp, w, lengths)
        nonspace = inside & (data != 0x20)
        idx = xp.arange(w, dtype=np.int32)[None, :]
        has = nonspace.any(axis=1)
        big = xp.where(nonspace, idx, w)
        first = xp.where(has, big.min(axis=1), 0)
        small = xp.where(nonspace, idx, -1)
        last = xp.where(has, small.max(axis=1), -1)
        keep = inside & (idx >= first[:, None]) & (idx < (last + 1)[:, None])
        keep = keep & has[:, None]
        out, out_len = pack_left(xp, data, keep)
        return out, out_len, validity


class StringTrimLeft(StringTrim):
    def kernel(self, xp, data, lengths, validity):
        w = data.shape[1]
        inside = byte_mask(xp, w, lengths)
        nonspace = inside & (data != 0x20)
        idx = xp.arange(w, dtype=np.int32)[None, :]
        has = nonspace.any(axis=1)
        big = xp.where(nonspace, idx, w)
        first = xp.where(has, big.min(axis=1), lengths)
        keep = inside & (idx >= first[:, None])
        out, out_len = pack_left(xp, data, keep)
        return out, out_len, validity


class StringTrimRight(StringTrim):
    def kernel(self, xp, data, lengths, validity):
        w = data.shape[1]
        inside = byte_mask(xp, w, lengths)
        nonspace = inside & (data != 0x20)
        idx = xp.arange(w, dtype=np.int32)[None, :]
        has = nonspace.any(axis=1)
        small = xp.where(nonspace, idx, -1)
        last = xp.where(has, small.max(axis=1) + 1, 0)
        keep = inside & (idx < last[:, None])
        out, out_len = pack_left(xp, data, keep)
        return out, out_len, validity


class _HostStringOp(Expression):
    """Template for ops that run on host even in the device plan (regex and
    friends — the boundary the reference draws at cudf's regex support)."""

    def data_type(self) -> DataType:
        return dt.STRING

    @property
    def self_jittable(self) -> bool:
        return False

    def _host_kernel(self, values, validity):
        raise NotImplementedError

    def _strings_of(self, col: HostColumn):
        return [bytes(b) for b in col.data]

    def eval(self, batch):
        from spark_rapids_tpu.columnar.host import device_to_host, host_to_device
        col = as_device_column(self.children[0].eval(batch), batch)
        tmp = DeviceBatch((col,), batch.num_rows)
        hb = device_to_host(tmp)
        out = self._host_kernel(self._strings_of(hb.columns[0]),
                                hb.columns[0].validity)
        dev = host_to_device(HostBatch(("c",), [out]),
                             capacity=batch.capacity)
        return dev.columns[0]

    def eval_host(self, batch):
        col = as_host_column(self.children[0].eval_host(batch), batch)
        return self._host_kernel(self._strings_of(col), col.validity)


class StringReplace(_HostStringOp):
    """replace(str, search, replace) with literal search (GpuStringReplace)."""

    def __init__(self, child: Expression, search: str, replace: str):
        self.child = child
        self.search = search.encode() if isinstance(search, str) else search
        self.replace = replace.encode() if isinstance(replace, str) \
            else replace

    @property
    def children(self):
        return (self.child,)

    def _host_kernel(self, values, validity):
        n = len(values)
        out = np.empty(n, dtype=object)
        for i in range(n):
            if validity[i] and len(self.search):
                out[i] = values[i].replace(self.search, self.replace)
            else:
                out[i] = values[i]
        return HostColumn(dt.STRING, out, np.asarray(validity, np.bool_))


class RegExpReplace(_HostStringOp):
    """regexp_replace with literal pattern (host engine, python re)."""

    def __init__(self, child: Expression, pattern: str, replacement: str):
        import re
        self.child = child
        self.pattern = re.compile(pattern.encode()
                                  if isinstance(pattern, str) else pattern)
        self.replacement = replacement.encode() \
            if isinstance(replacement, str) else replacement

    @property
    def children(self):
        return (self.child,)

    def _host_kernel(self, values, validity):
        n = len(values)
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = self.pattern.sub(self.replacement, values[i]) \
                if validity[i] else b""
        return HostColumn(dt.STRING, out, np.asarray(validity, np.bool_))


class ConcatWs(Expression):
    """concat_ws(sep, s1, s2, ...): joins NON-null inputs with the literal
    separator; null inputs are skipped and the result is never null
    (Spark concat_ws; ref GpuConcatWs). Device kernel reuses the running
    two-way concat with per-row conditional lengths — null pieces and
    their separators contribute zero bytes."""

    def __init__(self, sep: str, *children: Expression):
        self.sep = sep.encode() if isinstance(sep, str) else bytes(sep)
        self._children = tuple(children)

    @property
    def children(self):
        return self._children

    def data_type(self) -> DataType:
        return dt.STRING

    def _run(self, xp, cols):
        """cols: [(data, lengths, validity)]; returns (data, lengths)."""
        n = cols[0][0].shape[0]
        sep = np.frombuffer(self.sep, np.uint8)
        ws = len(sep)
        cc = ConcatStrings
        acc_data = xp.zeros((n, 1), np.uint8)
        acc_len = xp.zeros((n,), np.int32)
        has_prev = xp.zeros((n,), np.bool_)
        for d, l, v in cols:
            eff_len = xp.where(v, l, 0).astype(np.int32)
            if ws:
                sep_len = xp.where(has_prev & v, ws, 0).astype(np.int32)
                sep_data = xp.broadcast_to(
                    xp.asarray(sep)[None, :], (n, ws)).astype(np.uint8)
                acc_data, acc_len = cc._concat2(xp, acc_data, acc_len,
                                                sep_data, sep_len)
            acc_data, acc_len = cc._concat2(xp, acc_data, acc_len,
                                            d, eff_len)
            has_prev = has_prev | v
        return acc_data, acc_len

    def eval(self, batch):
        if not self._children:
            n = batch.capacity
            return make_column(dt.STRING, jnp.zeros((n, 1), np.uint8),
                               batch.row_mask(),
                               jnp.zeros((n,), jnp.int32))
        cols = []
        for c in self._children:
            col = as_device_column(c.eval(batch), batch)
            cols.append((col.data, col.lengths, col.validity))
        data, lengths = self._run(jnp, cols)
        # concat_ws is never NULL itself, but padding rows must stay
        # invalid (batch.py engine invariant: padding validity is False).
        valid = batch.row_mask()
        return make_column(dt.STRING, data, valid, lengths)

    def eval_host(self, batch):
        if not self._children:
            n = batch.num_rows
            out = np.full(n, b"", dtype=object)
            return HostColumn(dt.STRING, out, _host_all_valid(n))
        cols = []
        for c in self._children:
            col = as_host_column(c.eval_host(batch), batch)
            m, lens = _host_to_matrix(col)
            cols.append((m, lens, col.validity))
        data, lengths = self._run(np, cols)
        valid = np.asarray(_host_all_valid(len(lengths)))
        return _matrix_to_host(data, lengths, valid)


class StringRepeat(Expression):
    """repeat(str, n) with literal n (ref GpuStringRepeat)."""

    def __init__(self, child: Expression, n: int):
        from spark_rapids_tpu.exprs.base import Literal
        if isinstance(n, Literal):
            n = n.value
        self.child = child
        self.n = max(int(n), 0)

    @property
    def children(self):
        return (self.child,)

    def data_type(self) -> DataType:
        return dt.STRING

    def _kernel(self, xp, data, lengths, validity):
        w = data.shape[1]
        k = self.n
        if k == 0 or w == 0:
            n = data.shape[0]
            return xp.zeros((n, 1), np.uint8), xp.zeros((n,), np.int32)
        out_w = w * k
        j = xp.arange(out_w, dtype=np.int32)[None, :]
        src = xp.remainder(j, xp.maximum(lengths[:, None], 1))
        out = xp.take_along_axis(data, src, axis=1)
        out_len = (lengths * k).astype(np.int32)
        live = j < out_len[:, None]
        return xp.where(live, out, 0), out_len

    def eval(self, batch):
        col = as_device_column(self.child.eval(batch), batch)
        data, lengths = self._kernel(jnp, col.data, col.lengths,
                                     col.validity)
        return make_column(dt.STRING, data, col.validity, lengths)

    def eval_host(self, batch):
        col = as_host_column(self.child.eval_host(batch), batch)
        m, lens = _host_to_matrix(col)
        data, lengths = self._kernel(np, m, lens, col.validity)
        return _matrix_to_host(data, lengths, col.validity)


class StringReverse(StringUnary):
    """reverse(str): character-level (UTF-8 aware) reversal via a per-row
    argsort on (reversed char ordinal, byte offset within char)."""

    def kernel(self, xp, data, lengths, validity):
        w = data.shape[1]
        if w == 0:
            return data, lengths, validity
        idx = xp.arange(w, dtype=np.int32)[None, :]
        inside = byte_mask(xp, w, lengths)
        starts = char_starts(xp, data, lengths)
        char_ord = xp.cumsum(starts.astype(np.int32), axis=1) - 1
        # byte offset within its codepoint: distance from last start <= j.
        start_pos = xp.where(starts, idx, -1)
        if xp is np:
            last_start = np.maximum.accumulate(start_pos, axis=1)
        else:
            import jax
            last_start = jax.lax.associative_scan(jnp.maximum, start_pos,
                                                  axis=1)
        within = idx - last_start
        nchars = starts.sum(axis=1).astype(np.int32)
        key = xp.where(inside,
                       (nchars[:, None] - 1 - char_ord) * (w + 1) + within,
                       np.int32(2) * w * (w + 1))
        if xp is np:
            order = np.argsort(key, axis=1, kind="stable")
        else:
            order = xp.argsort(key, axis=1, stable=True)
        out = xp.take_along_axis(data, order.astype(np.int32), axis=1)
        live = idx < lengths[:, None]
        return xp.where(live, out, 0), lengths, validity


class InitCap(StringUnary):
    """initcap(): first letter of each space-separated word uppercased,
    the rest lowercased (ASCII; same locale incompat as upper/lower)."""

    def kernel(self, xp, data, lengths, validity):
        w = data.shape[1]
        prev = xp.concatenate(
            [xp.full((data.shape[0], 1), 0x20, data.dtype),
             data[:, :-1]], axis=1)
        word_start = prev == 0x20
        is_lower = (data >= ord("a")) & (data <= ord("z"))
        is_upper = (data >= ord("A")) & (data <= ord("Z"))
        up = xp.where(word_start & is_lower, data - 32, data)
        out = xp.where(~word_start & is_upper, up + 32, up)
        # Only the cased transform differs; bytes outside length are 0.
        out = xp.where(byte_mask(xp, w, lengths), out, 0)
        return out, lengths, validity


class RegExpExtract(_HostStringOp):
    """regexp_extract(str, pattern, idx): group idx of the first match,
    '' when no match (Spark semantics; host engine, python re — the
    reference draws the same host boundary for unsupported cudf regex)."""

    def __init__(self, child: Expression, pattern: str, idx: int = 1):
        import re
        self.child = child
        self.pattern = re.compile(pattern)
        self.idx = int(idx)

    @property
    def children(self):
        return (self.child,)

    def _host_kernel(self, values, validity):
        n = len(values)
        out = np.empty(n, dtype=object)
        for i in range(n):
            if not validity[i]:
                out[i] = b""
                continue
            m = self.pattern.search(values[i].decode("utf-8", "replace"))
            if m is None:
                out[i] = b""
            else:
                g = m.group(self.idx)
                out[i] = (g or "").encode()
        return HostColumn(dt.STRING, out, np.asarray(validity, np.bool_))


class Translate(_HostStringOp):
    """translate(str, from, to): per-character mapping; chars beyond
    len(to) are deleted (Spark semantics)."""

    def __init__(self, child: Expression, src: str, to: str):
        self.child = child
        self.table = {}
        for i, ch in enumerate(src):
            if ch not in self.table:
                self.table[ch] = to[i] if i < len(to) else None

    @property
    def children(self):
        return (self.child,)

    def _host_kernel(self, values, validity):
        n = len(values)
        out = np.empty(n, dtype=object)
        for i in range(n):
            if not validity[i]:
                out[i] = b""
                continue
            s = values[i].decode("utf-8", "replace")
            buf = []
            for ch in s:
                r = self.table.get(ch, ch)
                if r is not None:
                    buf.append(r)
            out[i] = "".join(buf).encode()
        return HostColumn(dt.STRING, out, np.asarray(validity, np.bool_))


class _StringPad(_HostStringOp):
    """lpad/rpad(str, len, pad): character-addressed pad/truncate
    (GpuStringLPad/RPad). Host kernel (exact char semantics)."""

    left = True

    def __init__(self, child: Expression, length: int, pad: str = " "):
        from spark_rapids_tpu.exprs.base import Literal
        if isinstance(length, Literal):
            length = length.value
        self.child = child
        self.length = int(length)
        self.pad = pad

    @property
    def children(self):
        return (self.child,)

    def _host_kernel(self, values, validity):
        n = len(values)
        out = np.empty(n, dtype=object)
        for i in range(n):
            if not validity[i]:
                out[i] = b""
                continue
            s = values[i].decode("utf-8", "replace")
            want = self.length
            if want <= 0:
                out[i] = b""
            elif len(s) >= want:
                out[i] = s[:want].encode()
            elif not self.pad:
                out[i] = s.encode()
            else:
                fill = (self.pad * want)[:want - len(s)]
                out[i] = (fill + s if self.left else s + fill).encode()
        return HostColumn(dt.STRING, out, np.asarray(validity, np.bool_))


class StringLPad(_StringPad):
    left = True


class StringRPad(_StringPad):
    left = False


class Like(Expression):
    """SQL LIKE. The pattern must be a literal. Patterns made only of literal
    segments and ``%`` compile to fused device contains/prefix/suffix matches;
    anything with ``_`` falls back to the host matcher (same split the
    reference makes for GpuLike's cudf `matchesRe`)."""

    def __init__(self, child: Expression, pattern: str, escape: str = "\\"):
        self.child = child
        self.pattern = pattern
        self.escape = escape

    @property
    def children(self):
        return (self.child,)

    def data_type(self) -> DataType:
        return dt.BOOL

    @property
    def self_jittable(self) -> bool:
        return self._segments() is not None

    def _segments(self):
        """Split the pattern on unescaped %; returns None if '_' present."""
        segs = []
        cur = []
        i = 0
        p = self.pattern
        while i < len(p):
            ch = p[i]
            if ch == self.escape and i + 1 < len(p):
                cur.append(p[i + 1])
                i += 2
                continue
            if ch == "_":
                return None
            if ch == "%":
                segs.append("".join(cur))
                cur = []
            else:
                cur.append(ch)
            i += 1
        segs.append("".join(cur))
        return segs

    def _device_match(self, xp, data, lengths):
        segs = self._segments()
        assert segs is not None
        n, w = data.shape
        bsegs = [s.encode() for s in segs]
        total = sum(len(b) for b in bsegs)
        ok = lengths >= total
        if len(bsegs) == 1:
            # exact match
            b = bsegs[0]
            target = np.zeros(w, dtype=np.uint8)
            target[:min(len(b), w)] = np.frombuffer(b[:w], np.uint8)
            return ((data == xp.asarray(target)[None, :]).all(axis=1)
                    & (lengths == len(b)))
        # prefix
        if bsegs[0]:
            hits = _sliding_match(xp, data, lengths, bsegs[0])
            ok = ok & (hits[:, 0] if w else False)
        # suffix
        if bsegs[-1]:
            b = bsegs[-1]
            hits = _sliding_match(xp, data, lengths, b)
            pos = xp.clip(lengths - len(b), 0, max(w - 1, 0))
            ok = ok & (xp.take_along_axis(
                hits, pos[:, None].astype(np.int32), axis=1)[:, 0]
                & (lengths >= len(b)))
        # middles: ordered, non-overlapping containment. Track the earliest
        # position each segment can start from.
        min_start = xp.full((n,), len(bsegs[0]), dtype=np.int32)
        for b in bsegs[1:-1]:
            if not b:
                continue
            hits = _sliding_match(xp, data, lengths, b)
            idx = xp.arange(w, dtype=np.int32)[None, :]
            usable = hits & (idx >= min_start[:, None])
            any_hit = usable.any(axis=1)
            first = xp.argmax(usable, axis=1).astype(np.int32)
            ok = ok & any_hit
            min_start = first + len(b)
        if bsegs[-1]:
            ok = ok & ((lengths - len(bsegs[-1])) >= min_start) \
                if len(bsegs) > 1 else ok
        return ok

    def _host_match(self, values, validity):
        import re
        # Translate LIKE to an anchored regex.
        out = []
        p = self.pattern
        rx = []
        i = 0
        while i < len(p):
            ch = p[i]
            if ch == self.escape and i + 1 < len(p):
                rx.append(re.escape(p[i + 1]))
                i += 2
                continue
            if ch == "%":
                rx.append(".*")
            elif ch == "_":
                rx.append(".")
            else:
                rx.append(re.escape(ch))
            i += 1
        pat = re.compile("(?s)^" + "".join(rx) + "$")
        for i, b in enumerate(values):
            out.append(bool(validity[i])
                       and pat.match(b.decode("utf-8", "replace")) is not None)
        return np.asarray(out, dtype=np.bool_)

    def eval(self, batch):
        col = as_device_column(self.child.eval(batch), batch)
        if self._segments() is not None:
            data = self._device_match(jnp, col.data, col.lengths)
            return make_column(dt.BOOL, data, col.validity)
        # '_' patterns: host roundtrip.
        from spark_rapids_tpu.columnar.host import device_to_host, host_to_device
        hb = device_to_host(DeviceBatch((col,), batch.num_rows))
        vals = [bytes(b) for b in hb.columns[0].data]
        res = self._host_match(vals, hb.columns[0].validity)
        hc = HostColumn(dt.BOOL, res, hb.columns[0].validity.copy())
        dev = host_to_device(HostBatch(("c",), [hc]), capacity=batch.capacity)
        return dev.columns[0]

    def eval_host(self, batch):
        col = as_host_column(self.child.eval_host(batch), batch)
        vals = [bytes(b) for b in col.data]
        res = self._host_match(vals, col.validity)
        return make_host_column(dt.BOOL, res, col.validity)
