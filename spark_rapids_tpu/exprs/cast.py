"""Cast (ref: GpuCast.scala 891 LoC).

The Spark cast matrix over the supported types: numeric<->numeric (JVM
narrowing wrap-around), numeric<->bool, numeric<->string, date/timestamp
conversions, string->date/timestamp (ISO formats), bool<->string.

Corner cases matched to Spark (ANSI off):
- float->integral: NaN -> 0? No: Spark casts NaN to 0 and clamps to the
  target range via ``(long) x`` style truncation toward zero; values outside
  long range clamp to Long.MIN/MAX then narrow-wrap for smaller types.
- string->numeric: invalid strings -> NULL (trimmed first).
- float->string uses the shortest round-trip Java format; gated behind
  ``spark.rapids.sql.castFloatToString.enabled`` in the plan layer because the
  formatting differs in corner cases (we produce repr-style).
- timestamp->date floors to days; date->timestamp at midnight UTC.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exprs.base import (
    Expression, UnaryExpression, as_device_column, as_host_column,
    make_column, make_host_column)

_LONG_MIN = -(2 ** 63)
_LONG_MAX = 2 ** 63 - 1
MICROS_PER_DAY = 86400 * 1000 * 1000


class Cast(UnaryExpression):
    def __init__(self, child: Expression, to: DataType):
        super().__init__(child)
        self.to = to

    def data_type(self) -> DataType:
        return self.to

    def pretty(self) -> str:
        return f"cast({self.child.pretty()} as {self.to.name})"

    @property
    def self_jittable(self) -> bool:
        # String parse/format runs on host (CPU island).
        return not (self.child.data_type().is_string or self.to.is_string) \
            or self.child.data_type() == self.to

    # -- device path ---------------------------------------------------------
    def eval(self, batch):
        import jax.numpy as jnp
        col = as_device_column(self.child.eval(batch), batch)
        src = self.child.data_type()
        if src == self.to:
            return col
        if src.is_string or self.to.is_string:
            return self._eval_string_side_device(jnp, col, batch)
        data, validity = _cast_fixed(jnp, col.data, col.validity, src, self.to)
        return make_column(self.to, data, validity)

    def eval_host(self, batch):
        col = as_host_column(self.child.eval_host(batch), batch)
        src = self.child.data_type()
        if src == self.to:
            return col
        if src.is_string or self.to.is_string:
            return self._eval_string_side_host(col, batch)
        data, validity = _cast_fixed(np, col.data, col.validity, src, self.to)
        return make_host_column(self.to, data, validity)

    # -- string-involved casts -----------------------------------------------
    # TPU-side string parse/format of numerics is byte-loop heavy; the plan
    # layer routes these through the host fallback column-wise (the same
    # boundary the reference draws with castStringToFloat etc. disabled by
    # default). Device path downloads, computes, re-uploads.
    def _eval_string_side_device(self, jnp, col, batch):
        from spark_rapids_tpu.columnar.host import device_to_host, host_to_device
        from spark_rapids_tpu.columnar.batch import DeviceBatch
        tmp = DeviceBatch((col,), batch.num_rows)
        hb = device_to_host(tmp)
        out = _cast_string_host(hb.columns[0], self.child.data_type(), self.to)
        from spark_rapids_tpu.columnar.host import HostBatch
        dev = host_to_device(HostBatch(("c",), [out]), capacity=batch.capacity)
        return dev.columns[0]

    def _eval_string_side_host(self, col, batch):
        return _cast_string_host(col, self.child.data_type(), self.to)


def _cast_fixed(xp, data, validity, src: DataType, to: DataType):
    """Fixed-width -> fixed-width cast on raw arrays."""
    if src == to:
        return data, validity
    if to.is_boolean:
        return data != 0, validity
    if src.is_boolean:
        return data.astype(to.np_dtype), validity
    if src.name == "timestamp" and to.name == "date":
        days = xp.floor_divide(data, MICROS_PER_DAY)
        return days.astype(np.int32), validity
    if src.name == "date" and to.name == "timestamp":
        return data.astype(np.int64) * MICROS_PER_DAY, validity
    if src.is_datetime and to.is_numeric:
        if src.name == "timestamp":
            # timestamp->long = seconds; ->int/short/byte narrows from that.
            secs = xp.floor_divide(data, 1000 * 1000)
            if to.is_floating:
                return (data.astype(np.float64) / 1e6).astype(to.np_dtype), \
                    validity
            return secs.astype(to.np_dtype), validity
        return data.astype(to.np_dtype), validity
    if src.is_numeric and to.name == "timestamp":
        if src.is_floating:
            x = data.astype(np.float64)
            finite = xp.isfinite(x)
            safe = xp.where(finite, x, xp.asarray(0.0))
            # Spark returns NULL for NaN/Infinity -> timestamp.
            return (safe * 1e6).astype(np.int64), validity & finite
        return data.astype(np.int64) * 1000 * 1000, validity
    if src.is_numeric and to.name == "date":
        return data.astype(np.int32), validity
    if src.is_floating and to.is_integral:
        # JVM d2i/d2l semantics: truncate toward zero, NaN -> 0, SATURATE at
        # the intermediate type's range. Spark's double->int goes through
        # d2i (saturating at Int range); double->byte/short saturate at Int
        # then wrap-narrow (Scala's x.toInt.toByte).
        x = data.astype(np.float64)
        x = xp.where(xp.isnan(x), xp.asarray(0.0), x)
        if to.name == "int64":
            lo, hi = float(_LONG_MIN), float(_LONG_MAX)
            lo_i, hi_i = np.int64(_LONG_MIN), np.int64(_LONG_MAX)
        else:
            info = np.iinfo(np.int32)
            lo, hi = float(info.min), float(info.max)
            lo_i, hi_i = np.int64(info.min), np.int64(info.max)
        too_big = x >= hi
        too_small = x <= lo
        safe = xp.where(too_big | too_small, xp.asarray(0.0), x)
        longs = xp.trunc(safe).astype(np.int64)
        longs = xp.where(too_big, hi_i, longs)
        longs = xp.where(too_small, lo_i, longs)
        return longs.astype(to.np_dtype), validity
    # numeric widening/narrowing (wrap-around like the JVM) & int<->float.
    return data.astype(to.np_dtype), validity


# ---------------------------------------------------------------------------
# Host-side string cast kernels (also the oracle for tests)
# ---------------------------------------------------------------------------

def _format_value(v, src: DataType) -> bytes:
    if src.is_boolean:
        return b"true" if v else b"false"
    if src.is_integral:
        return str(int(v)).encode()
    if src.is_floating:
        f = float(v)
        if np.isnan(f):
            return b"NaN"
        if np.isinf(f):
            return b"Infinity" if f > 0 else b"-Infinity"
        # Java Double.toString-style: always includes a decimal point or E.
        if src.name == "float32":
            s = repr(np.float32(f).item())
        else:
            s = repr(f)
        if "e" in s or "E" in s:
            mant, ex = s.split("e") if "e" in s else s.split("E")
            exi = int(ex)
            if "." not in mant:
                mant += ".0"
            s = f"{mant}E{exi}"
        elif "." not in s and "inf" not in s and "nan" not in s:
            s += ".0"
        return s.encode()
    if src.name == "date":
        days = int(v)
        return (np.datetime64(0, "D") + np.timedelta64(days, "D")) \
            .astype("datetime64[D]").astype(str).encode()
    if src.name == "timestamp":
        us = int(v)
        ts = np.datetime64(us, "us")
        s = str(ts)
        # Spark formats as 'YYYY-MM-DD HH:MM:SS[.ffffff]'
        s = s.replace("T", " ")
        if "." in s:
            s = s.rstrip("0").rstrip(".")
        return s.encode()
    raise TypeError(f"cannot format {src}")


def _parse_value(b: bytes, to: DataType):
    """Parse one trimmed string; return (value, ok)."""
    s = b.decode("utf-8", "replace").strip()
    if s == "":
        return None, False
    try:
        if to.is_boolean:
            low = s.lower()
            if low in ("t", "true", "y", "yes", "1"):
                return True, True
            if low in ("f", "false", "n", "no", "0"):
                return False, True
            return None, False
        if to.is_integral:
            # Spark allows trailing .0 forms? No: int('1.5') invalid for
            # string->int; Spark trims and parses with Long.parseLong-like
            # logic allowing a decimal part that is truncated for ansi=false
            # via cast to decimal... v0.3 cudf path rejects decimals; match
            # plain integer parse.
            v = int(s)
            info = np.iinfo(to.np_dtype)
            # Out-of-range longs -> NULL like Spark's parse failure.
            if to.name == "int64":
                if not (_LONG_MIN <= v <= _LONG_MAX):
                    return None, False
            elif not (info.min <= v <= info.max):
                return None, False
            return v, True
        if to.is_floating:
            low = s.lower()
            if low in ("nan",):
                return float("nan"), True
            if low in ("inf", "+inf", "infinity", "+infinity"):
                return float("inf"), True
            if low in ("-inf", "-infinity"):
                return float("-inf"), True
            return float(s), True
        if to.name == "date":
            # ISO yyyy[-mm[-dd]] only; trailing garbage -> NULL like Spark.
            import re as _re
            m = _re.fullmatch(r"(\d{4,5})(?:-(\d{1,2})(?:-(\d{1,2}))?)?", s)
            if not m:
                return None, False
            y = int(m.group(1))
            mo = int(m.group(2) or 1)
            dd = int(m.group(3) or 1)
            if not (1 <= mo <= 12 and 1 <= dd <= 31):
                return None, False
            d = np.datetime64(f"{y:04d}-{mo:02d}-{dd:02d}", "D")
            return int(d.astype("datetime64[D]").astype(np.int64)), True
        if to.name == "timestamp":
            t = s.replace(" ", "T")
            v = np.datetime64(t)
            return int(v.astype("datetime64[us]").astype(np.int64)), True
    except (ValueError, OverflowError):
        return None, False
    raise TypeError(f"cannot parse to {to}")


def _format_column(col, src: DataType):
    """Vectorized bool/int/date formatting straight into the string
    byte-matrix (numpy S-dtype arrays are already fixed-width NUL-padded
    row buffers). Floats and timestamps keep the exact per-row
    Double.toString mimicry. Returns None when not vectorizable."""
    n = col.num_rows
    if n == 0:
        return None
    arr = np.asarray(col.data)
    if src.is_boolean:
        s = np.where(arr.astype(np.bool_), np.asarray(b"true", "S5"),
                     np.asarray(b"false", "S5"))
    elif src.is_integral:
        s = np.char.mod(b"%d", arr.astype(np.int64))
    elif src.name == "date":
        days = arr.astype(np.int64).astype("datetime64[D]")
        s = np.char.encode(np.datetime_as_string(days, unit="D"))
    else:
        return None
    return np.ascontiguousarray(s)


def _cast_string_host(col, src: DataType, to: DataType):
    """HostColumn cast where either side is a string."""
    from spark_rapids_tpu.columnar.host import HostColumn
    n = col.num_rows
    if to.is_string:
        validity = np.asarray(col.validity, np.bool_)
        s = _format_column(col, src)
        if s is not None:
            w = max(s.dtype.itemsize, 1)
            m = np.frombuffer(s.tobytes(), np.uint8).reshape(n, w)
            m = m * validity[:, None].astype(np.uint8)
            lens = np.char.str_len(s).astype(np.int32)
            lens = np.where(validity, lens, 0).astype(np.int32)
            return HostColumn(to, None, validity.copy(),
                              str_matrix=m, str_lengths=lens)
        data = np.empty(n, dtype=object)
        validity = col.validity.copy()
        cdata = col.data
        for i in range(n):
            data[i] = _format_value(cdata[i], src) if validity[i] else b""
        return HostColumn(to, data, validity)
    # string -> typed
    data = np.zeros(n, dtype=to.np_dtype)
    validity = np.zeros(n, dtype=np.bool_)
    for i in range(n):
        if not col.validity[i]:
            continue
        v, ok = _parse_value(bytes(col.data[i]), to)
        if ok:
            validity[i] = True
            data[i] = to.np_dtype.type(v) if not to.is_boolean else bool(v)
    return HostColumn(to, data, validity)
