"""Date/time expressions (ref: .../sql/rapids/datetimeExpressions.scala 560).

DATE = days since epoch (int32), TIMESTAMP = UTC micros (int64) — Catalyst's
internal encodings, so all calendar math is pure integer arithmetic and runs
on the VPU. Civil-date decomposition uses the days-from-civil algorithm
(Gregorian, proleptic) in integer ops only — no table lookups, XLA friendly.
Timezone is UTC-only, same restriction the reference enforces
(GpuOverrides timezone checks).
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exprs.base import BinaryExpression, UnaryExpression

MICROS_PER_SEC = 1000 * 1000
MICROS_PER_DAY = 86400 * MICROS_PER_SEC


def _fdiv(xp, a, b):
    return xp.floor_divide(a, b)


def civil_from_days(xp, z):
    """days-since-epoch -> (year, month, day), vectorized integer math."""
    z = z.astype(np.int64) + 719468
    era = _fdiv(xp, z, 146097)
    doe = z - era * 146097
    yoe = _fdiv(xp, doe - _fdiv(xp, doe, 1460) + _fdiv(xp, doe, 36524)
                - _fdiv(xp, doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + _fdiv(xp, yoe, 4) - _fdiv(xp, yoe, 100))
    mp = _fdiv(xp, 5 * doy + 2, 153)
    d = doy - _fdiv(xp, 153 * mp + 2, 5) + 1
    m = mp + xp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y.astype(np.int32), m.astype(np.int32), d.astype(np.int32)


def days_from_civil(xp, y, m, d):
    """(year, month, day) -> days since epoch."""
    y = y.astype(np.int64) - (m <= 2)
    era = _fdiv(xp, y, 400)
    yoe = y - era * 400
    mp = xp.where(m > 2, m - 3, m + 9).astype(np.int64)
    doy = _fdiv(xp, 153 * mp + 2, 5) + d.astype(np.int64) - 1
    doe = yoe * 365 + _fdiv(xp, yoe, 4) - _fdiv(xp, yoe, 100) + doy
    return (era * 146097 + doe - 719468).astype(np.int32)


def _days_of(xp, data, src: DataType):
    if src.name == "timestamp":
        return _fdiv(xp, data, MICROS_PER_DAY)
    return data


class _DatePart(UnaryExpression):
    def data_type(self) -> DataType:
        return dt.INT32

    def _part(self, xp, y, m, d, days):
        raise NotImplementedError

    def do_columnar(self, xp, data, validity, col):
        days = _days_of(xp, data, self.child.data_type())
        y, m, d = civil_from_days(xp, days)
        return self._part(xp, y, m, d, days), validity


class Year(_DatePart):
    def _part(self, xp, y, m, d, days):
        return y


class Month(_DatePart):
    def _part(self, xp, y, m, d, days):
        return m


class DayOfMonth(_DatePart):
    def _part(self, xp, y, m, d, days):
        return d


class Quarter(_DatePart):
    def _part(self, xp, y, m, d, days):
        return _fdiv(xp, m - 1, 3).astype(np.int32) + 1


class DayOfWeek(_DatePart):
    """Spark: Sunday=1 ... Saturday=7. Epoch day 0 was a Thursday."""

    def _part(self, xp, y, m, d, days):
        return (xp.remainder(days.astype(np.int64) + 4, 7) + 1) \
            .astype(np.int32)


class WeekDay(_DatePart):
    """Spark weekday(): Monday=0 ... Sunday=6."""

    def _part(self, xp, y, m, d, days):
        return xp.remainder(days.astype(np.int64) + 3, 7).astype(np.int32)


class DayOfYear(_DatePart):
    def _part(self, xp, y, m, d, days):
        jan1 = days_from_civil(xp, y, xp.ones_like(m), xp.ones_like(d))
        return (days - jan1 + 1).astype(np.int32)


class LastDay(UnaryExpression):
    """Last day of the month of the given date."""

    def data_type(self) -> DataType:
        return dt.DATE

    def do_columnar(self, xp, data, validity, col):
        days = _days_of(xp, data, self.child.data_type())
        y, m, d = civil_from_days(xp, days)
        ny = xp.where(m == 12, y + 1, y)
        nm = xp.where(m == 12, 1, m + 1)
        first_next = days_from_civil(xp, ny, nm, xp.ones_like(d))
        return first_next - 1, validity


class _TimePart(UnaryExpression):
    def data_type(self) -> DataType:
        return dt.INT32

    def do_columnar(self, xp, data, validity, col):
        secs_in_day = _fdiv(xp, xp.remainder(data, MICROS_PER_DAY),
                            MICROS_PER_SEC)
        return self._part(xp, secs_in_day), validity

    def _part(self, xp, secs):
        raise NotImplementedError


class Hour(_TimePart):
    def _part(self, xp, secs):
        return _fdiv(xp, secs, 3600).astype(np.int32)


class Minute(_TimePart):
    def _part(self, xp, secs):
        return xp.remainder(_fdiv(xp, secs, 60), 60).astype(np.int32)


class Second(_TimePart):
    def _part(self, xp, secs):
        return xp.remainder(secs, 60).astype(np.int32)


class TruncDate(UnaryExpression):
    """trunc(date, fmt) for fmt in year/yyyy/yy, quarter, month/mon/mm,
    week (Monday start, Spark semantics); ref GpuTruncDate."""

    _FMT = {"year": "year", "yyyy": "year", "yy": "year",
            "quarter": "quarter",
            "month": "month", "mon": "month", "mm": "month",
            "week": "week"}

    def __init__(self, child, fmt: str):
        super().__init__(child)
        from spark_rapids_tpu.exprs.base import Literal
        if isinstance(fmt, Literal):
            fmt = fmt.value
        if isinstance(fmt, bytes):
            fmt = fmt.decode()
        self.fmt = self._FMT.get(str(fmt).lower())

    def data_type(self) -> DataType:
        return dt.DATE

    def do_columnar(self, xp, data, validity, col):
        days = _days_of(xp, data, self.child.data_type())
        if self.fmt is None:
            # Unknown format -> NULL (Spark behavior).
            return days.astype(np.int32), validity & False
        if self.fmt == "week":
            # Monday of the current week; epoch day 0 was a Thursday.
            dow = xp.remainder(days.astype(np.int64) + 3, 7)
            return (days - dow).astype(np.int32), validity
        y, m, d = civil_from_days(xp, days)
        if self.fmt == "year":
            m = xp.ones_like(m)
        elif self.fmt == "quarter":
            m = (_fdiv(xp, m - 1, 3) * 3 + 1).astype(m.dtype)
        out = days_from_civil(xp, y, m, xp.ones_like(d))
        return out.astype(np.int32), validity


class DateAdd(BinaryExpression):
    """date_add(date, n days)."""

    def data_type(self) -> DataType:
        return dt.DATE

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        return (l_data.astype(np.int32) + r_data.astype(np.int32),
                l_valid & r_valid)


class DateSub(BinaryExpression):
    def data_type(self) -> DataType:
        return dt.DATE

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        return (l_data.astype(np.int32) - r_data.astype(np.int32),
                l_valid & r_valid)


class DateDiff(BinaryExpression):
    """datediff(end, start) in days."""

    def data_type(self) -> DataType:
        return dt.INT32

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        return (l_data.astype(np.int32) - r_data.astype(np.int32),
                l_valid & r_valid)


class AddMonths(BinaryExpression):
    """add_months(date, n): clamps the day to the target month's end."""

    def data_type(self) -> DataType:
        return dt.DATE

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        days = l_data.astype(np.int64)
        y, m, d = civil_from_days(xp, days)
        months = y.astype(np.int64) * 12 + (m - 1) + r_data.astype(np.int64)
        ny = _fdiv(xp, months, 12).astype(np.int32)
        nm = xp.remainder(months, 12).astype(np.int32) + 1
        # clamp day to last day of target month
        nny = xp.where(nm == 12, ny + 1, ny)
        nnm = xp.where(nm == 12, 1, nm + 1)
        last = days_from_civil(xp, nny, nnm, xp.ones_like(nm)) - \
            days_from_civil(xp, ny, nm, xp.ones_like(nm))
        nd = xp.minimum(d, last.astype(np.int32))
        return days_from_civil(xp, ny, nm, nd), l_valid & r_valid


class TimeAdd(BinaryExpression):
    """timestamp + interval-micros (ref: GpuTimeSub shim rule, inverted).

    The right child must evaluate to int64 micros (CalendarInterval with only
    the microseconds field set, the same restriction the reference enforces at
    Spark300Shims TimeSub)."""

    def data_type(self) -> DataType:
        return dt.TIMESTAMP

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        return (l_data.astype(np.int64) + r_data.astype(np.int64),
                l_valid & r_valid)


class TimeSub(TimeAdd):
    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        return (l_data.astype(np.int64) - r_data.astype(np.int64),
                l_valid & r_valid)


class ToUnixTimestamp(UnaryExpression):
    """Seconds since epoch from timestamp/date (default format path)."""

    def data_type(self) -> DataType:
        return dt.INT64

    def do_columnar(self, xp, data, validity, col):
        if self.child.data_type().name == "date":
            return data.astype(np.int64) * 86400, validity
        return _fdiv(xp, data, MICROS_PER_SEC), validity


UnixTimestamp = ToUnixTimestamp


class FromUnixTime(UnaryExpression):
    """Seconds -> timestamp (the string-format variant goes through cast)."""

    def data_type(self) -> DataType:
        return dt.TIMESTAMP

    def do_columnar(self, xp, data, validity, col):
        return data.astype(np.int64) * MICROS_PER_SEC, validity
