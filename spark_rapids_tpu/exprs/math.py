"""Math expressions (ref: .../sql/rapids/mathExpressions.scala 378 LoC).

Unary math functions follow Spark: inputs are cast to double, domain errors
produce NaN (not NULL), log of non-positive is NULL in Spark? No — Spark's
``log`` returns NULL for non-positive input. We match Spark: ``ln/log10/log2/
log1p`` return NULL for out-of-domain, others produce NaN like java.lang.Math.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.exprs.base import BinaryExpression, UnaryExpression


class _UnaryMathD(UnaryExpression):
    """double -> double math fn."""

    def data_type(self) -> DataType:
        return dt.FLOAT64

    def _fn(self, xp, x):
        raise NotImplementedError

    def do_columnar(self, xp, data, validity, col):
        return self._fn(xp, data.astype(np.float64)), validity


class Sqrt(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.sqrt(x)


class Cbrt(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.cbrt(x)


class Exp(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.exp(x)


class Expm1(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.expm1(x)


class _LogBase(UnaryExpression):
    """Spark logs return NULL outside the domain."""

    def data_type(self) -> DataType:
        return dt.FLOAT64

    def _fn(self, xp, x):
        raise NotImplementedError

    def _domain_ok(self, xp, x):
        return x > 0

    def do_columnar(self, xp, data, validity, col):
        x = data.astype(np.float64)
        ok = self._domain_ok(xp, x)
        safe = xp.where(ok, x, xp.asarray(1.0))
        return self._fn(xp, safe), validity & ok


class Log(_LogBase):
    def _fn(self, xp, x):
        return xp.log(x)


class Log10(_LogBase):
    def _fn(self, xp, x):
        return xp.log10(x)


class Log2(_LogBase):
    def _fn(self, xp, x):
        return xp.log2(x)


class Log1p(_LogBase):
    def _domain_ok(self, xp, x):
        return x > -1

    def _fn(self, xp, x):
        return xp.log1p(x)


class Sin(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.sin(x)


class Cos(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.cos(x)


class Tan(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.tan(x)


class Asin(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.arcsin(x)


class Acos(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.arccos(x)


class Atan(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.arctan(x)


class Sinh(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.sinh(x)


class Cosh(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.cosh(x)


class Tanh(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.tanh(x)


class Asinh(_UnaryMathD):
    """Total on the reals — no domain handling needed."""

    def _fn(self, xp, x):
        return xp.arcsinh(x)


class Acosh(_UnaryMathD):
    """Domain x >= 1; outside it java.lang.Math (and Spark) produce NaN.
    Evaluated on a clamped-safe input so the host engine never emits
    numpy invalid-value warnings."""

    def do_columnar(self, xp, data, validity, col):
        x = data.astype(np.float64)
        ok = x >= 1
        res = xp.arccosh(xp.where(ok, x, xp.asarray(1.0)))
        return xp.where(ok, res, xp.asarray(np.nan)), validity


class Atanh(_UnaryMathD):
    """Domain |x| < 1 -> finite, x == ±1 -> ±Infinity (log-of-zero, as
    java.lang.Math computes it), |x| > 1 -> NaN. Piecewise on safe
    inputs so neither engine trips divide/invalid warnings."""

    def do_columnar(self, xp, data, validity, col):
        x = data.astype(np.float64)
        inside = xp.abs(x) < 1
        res = xp.arctanh(xp.where(inside, x, xp.asarray(0.0)))
        edge = xp.where(x == 1, xp.asarray(np.inf),
                        xp.where(x == -1, xp.asarray(-np.inf),
                                 xp.asarray(np.nan)))
        return xp.where(inside, res, edge), validity


class Logarithm(BinaryExpression):
    """log(base, x) — Spark's two-argument Logarithm. NULL outside the
    domain (base <= 0, base == 1, or x <= 0 — the shapes where
    ln(x)/ln(base) is undefined or a division by zero), matching the
    unary log family's NULL-on-domain-error convention above. NaN
    inputs fall through the comparisons to NULL as well."""

    def data_type(self) -> DataType:
        return dt.FLOAT64

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        b = l_data.astype(np.float64)
        x = r_data.astype(np.float64)
        ok = (b > 0) & (b != 1) & (x > 0)
        sb = xp.where(ok, b, xp.asarray(2.0))
        sx = xp.where(ok, x, xp.asarray(1.0))
        return xp.log(sx) / xp.log(sb), l_valid & r_valid & ok


class ToDegrees(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.degrees(x)


class ToRadians(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.radians(x)


class Signum(_UnaryMathD):
    def _fn(self, xp, x):
        return xp.sign(x)


class Rint(_UnaryMathD):
    """Math.rint: round half to even."""

    def _fn(self, xp, x):
        return xp.round(x)


class Floor(UnaryExpression):
    """Spark floor returns LONG for numeric input."""

    def data_type(self) -> DataType:
        return dt.INT64

    def do_columnar(self, xp, data, validity, col):
        return xp.floor(data.astype(np.float64)).astype(np.int64), validity


class Ceil(UnaryExpression):
    def data_type(self) -> DataType:
        return dt.INT64

    def do_columnar(self, xp, data, validity, col):
        return xp.ceil(data.astype(np.float64)).astype(np.int64), validity


class Pow(BinaryExpression):
    def data_type(self) -> DataType:
        return dt.FLOAT64

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        a = l_data.astype(np.float64)
        b = r_data.astype(np.float64)
        return xp.power(a, b), l_valid & r_valid


class Atan2(BinaryExpression):
    def data_type(self) -> DataType:
        return dt.FLOAT64

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        return (xp.arctan2(l_data.astype(np.float64),
                           r_data.astype(np.float64)),
                l_valid & r_valid)


class Round(UnaryExpression):
    """round(x, d): HALF_UP like Spark (not banker's rounding).

    The scale must be a literal (same restriction as the reference's
    GpuRound) — it is a static python int so jit sees a constant.
    """

    def __init__(self, child, scale=0):
        super().__init__(child)
        from spark_rapids_tpu.exprs.base import Literal
        if isinstance(scale, Literal):
            scale = scale.value
        self.scale = int(scale)

    def data_type(self) -> DataType:
        return self.child.data_type()

    def do_columnar(self, xp, data, validity, col):
        t = self.data_type()
        if t.is_integral:
            # Exact integer path: float64 would corrupt |x| > 2^53.
            if self.scale >= 0:
                return data, validity
            factor = np.int64(10) ** np.int64(-self.scale)
            x = data.astype(np.int64)
            mag = xp.abs(x) + factor // 2
            r = xp.floor_divide(mag, factor) * factor
            r = xp.where(x < 0, -r, r)
            return r.astype(t.np_dtype), validity
        factor = 10.0 ** self.scale
        x = data.astype(np.float64)
        # HALF_UP: away from zero on ties.
        scaled = x * factor
        r = xp.where(scaled >= 0, xp.floor(scaled + 0.5),
                     xp.ceil(scaled - 0.5)) / factor
        return r, validity


class BRound(Round):
    """bround(x, d): HALF_EVEN (banker's rounding), ref GpuBRound."""

    def do_columnar(self, xp, data, validity, col):
        t = self.data_type()
        if t.is_integral:
            if self.scale >= 0:
                return data, validity
            factor = np.int64(10) ** np.int64(-self.scale)
            x = data.astype(np.int64)
            q = xp.floor_divide(x, factor)        # floor: rem in [0, factor)
            rem = x - q * factor
            up = (2 * rem > factor) | ((2 * rem == factor) &
                                       (xp.remainder(q, 2) != 0))
            r = (q + up.astype(np.int64)) * factor
            return r.astype(t.np_dtype), validity
        factor = 10.0 ** self.scale
        x = data.astype(np.float64)
        # numpy/jax round() is half-to-even natively.
        return xp.round(x * factor) / factor, validity
