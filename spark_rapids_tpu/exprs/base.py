"""Expression engine core: the GpuExpression analog.

Ref: sql-plugin GpuExpressions.scala:63 — ``GpuExpression.columnarEval(batch)``
returns either a device column or a scalar. Here every expression has TWO
evaluation paths:

- ``eval(DeviceBatch) -> DeviceColumn | Scalar`` — the TPU path, pure jnp on
  fixed-capacity columns so it is jit-traceable end to end.
- ``eval_host(HostBatch) -> HostColumn | Scalar`` — the numpy CPU-fallback
  path (the stand-in for rows staying on CPU Spark), which doubles as the
  comparison oracle for the CPU-vs-TPU equality tests (SURVEY.md §4).

Null semantics are SQL three-valued: a row's output validity is the AND of the
input validities unless an expression overrides it (IsNull, Coalesce, And/Or
Kleene logic...). Data under dead rows is zeroed so padding stays
deterministic under jit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn, _zero_dead
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn


@dataclasses.dataclass(frozen=True)
class Scalar:
    """A typed scalar value; ``value is None`` means the SQL NULL literal."""

    dtype: DataType
    value: Any

    @property
    def is_null(self) -> bool:
        return self.value is None

    def as_bytes(self) -> bytes:
        assert self.dtype.is_string and self.value is not None
        v = self.value
        return v.encode("utf-8") if isinstance(v, str) else bytes(v)


ColumnLike = Union[DeviceColumn, Scalar]
HostColumnLike = Union[HostColumn, Scalar]


class Expression:
    """Base expression node (GpuExpressions.scala:63 analog)."""

    def data_type(self) -> DataType:
        raise NotImplementedError

    @property
    def children(self) -> Tuple["Expression", ...]:
        return ()

    def eval(self, batch: DeviceBatch) -> ColumnLike:
        raise NotImplementedError

    def eval_host(self, batch: HostBatch) -> HostColumnLike:
        raise NotImplementedError

    @property
    def self_jittable(self) -> bool:
        """False when this node's device eval does a host roundtrip."""
        return True

    @property
    def jittable(self) -> bool:
        """True when the whole subtree can run under jax.jit. Non-jittable
        trees are the expression-level CPU islands: the plan layer keeps them
        out of compiled programs, mirroring the reference's CPU fallback
        boundary (RapidsMeta.willNotWorkOnGpu)."""
        return self.self_jittable and all(c.jittable for c in self.children)

    # Pretty name used by the plan layer's explain output.
    def pretty(self) -> str:
        name = type(self).__name__
        if self.children:
            return f"{name}({', '.join(c.pretty() for c in self.children)})"
        return name

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return self.pretty()


# ---------------------------------------------------------------------------
# Materialization helpers: Scalar <-> column broadcasting
# ---------------------------------------------------------------------------

def expand_scalar(s: Scalar, capacity: int, row_mask: jnp.ndarray,
                  string_width: Optional[int] = None) -> DeviceColumn:
    """Broadcast a scalar into a full device column (live rows only)."""
    if s.dtype.is_string:
        b = b"" if s.is_null else s.as_bytes()
        width = string_width or dt.string_width_bucket(len(b))
        width = max(width, len(b), 1)
        row = np.zeros(width, dtype=np.uint8)
        row[:len(b)] = np.frombuffer(b, dtype=np.uint8)
        validity = row_mask & (not s.is_null)
        data = jnp.where(validity[:, None], jnp.asarray(row)[None, :],
                         jnp.zeros((1, width), jnp.uint8))
        lengths = jnp.where(validity, jnp.int32(len(b)), 0)
        return DeviceColumn(s.dtype, data, validity, lengths)
    validity = row_mask & (not s.is_null)
    fill = s.dtype.np_dtype.type(0 if s.is_null else s.value)
    data = jnp.where(validity, jnp.asarray(fill), jnp.zeros((), s.dtype.np_dtype))
    return DeviceColumn(s.dtype, data.astype(s.dtype.np_dtype), validity)


def expand_scalar_host(s: Scalar, n: int) -> HostColumn:
    from spark_rapids_tpu.columnar.host import all_valid
    validity = all_valid(n) if not s.is_null \
        else np.zeros(n, dtype=np.bool_)
    if s.dtype.is_string:
        b = b"" if s.is_null else s.as_bytes()
        data = np.empty(n, dtype=object)
        data[:] = b
        lens = np.zeros(n, np.int32) if s.is_null else \
            np.full(n, len(b), np.int32)
        m = np.zeros((n, max(len(b), 1)), np.uint8)
        if b and not s.is_null:
            m[:] = np.frombuffer(b, dtype=np.uint8)[None, :]
        return HostColumn(s.dtype, data, validity,
                          str_matrix=m, str_lengths=lens)
    data = np.full(n, 0 if s.is_null else s.value, dtype=s.dtype.np_dtype)
    return HostColumn(s.dtype, data, validity)


def as_device_column(v: ColumnLike, batch: DeviceBatch,
                     string_width: Optional[int] = None) -> DeviceColumn:
    if isinstance(v, Scalar):
        return expand_scalar(v, batch.capacity, batch.row_mask(), string_width)
    return v


def as_host_column(v: HostColumnLike, batch: HostBatch) -> HostColumn:
    if isinstance(v, Scalar):
        return expand_scalar_host(v, batch.num_rows)
    return v


def make_column(dtype: DataType, data, validity,
                lengths=None) -> DeviceColumn:
    """Build a device column, zeroing data under dead rows."""
    data = _zero_dead(data.astype(dtype.np_dtype) if dtype is not dt.STRING
                      else data, validity)
    if dtype.is_string:
        lengths = jnp.where(validity, lengths, 0)
        return DeviceColumn(dtype, data, validity, lengths)
    return DeviceColumn(dtype, data, validity)


def make_host_column(dtype: DataType, data, validity) -> HostColumn:
    validity = np.asarray(validity, dtype=np.bool_)
    if not dtype.is_string:
        data = np.asarray(data).astype(dtype.np_dtype, copy=True)
        data[~validity] = np.zeros(1, dtype.np_dtype)
    else:
        out = np.empty(len(data), dtype=object)
        out[:] = data
        if not validity.all():
            out[~validity] = b""
        data = out
    return HostColumn(dtype, data, validity)


# ---------------------------------------------------------------------------
# Leaf expressions
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class BoundReference(Expression):
    """Column by ordinal (GpuBoundAttribute.scala analog)."""

    ordinal: int
    dtype: DataType
    name: str = ""

    def data_type(self) -> DataType:
        return self.dtype

    def eval(self, batch: DeviceBatch) -> DeviceColumn:
        return batch.columns[self.ordinal]

    def eval_host(self, batch: HostBatch) -> HostColumn:
        return batch.columns[self.ordinal]

    def pretty(self) -> str:
        return self.name or f"#{self.ordinal}"


@dataclasses.dataclass
class Literal(Expression):
    """Constant (literals.scala analog). ``value is None`` -> typed NULL."""

    dtype: DataType
    value: Any

    def data_type(self) -> DataType:
        return self.dtype

    def eval(self, batch: DeviceBatch) -> Scalar:
        return Scalar(self.dtype, self.value)

    def eval_host(self, batch: HostBatch) -> Scalar:
        return Scalar(self.dtype, self.value)

    def pretty(self) -> str:
        return f"lit({self.value!r})"


def lit(value: Any, dtype: Optional[DataType] = None) -> Literal:
    """Convenience literal builder with python-type inference."""
    if dtype is None:
        if isinstance(value, bool):
            dtype = dt.BOOL
        elif isinstance(value, int):
            dtype = dt.INT32 if -2**31 <= value < 2**31 else dt.INT64
        elif isinstance(value, float):
            dtype = dt.FLOAT64
        elif isinstance(value, (str, bytes)):
            dtype = dt.STRING
        else:
            raise TypeError(f"cannot infer literal type for {value!r}")
    return Literal(dtype, value)


# ---------------------------------------------------------------------------
# Unary / binary templates (GpuUnaryExpression / GpuBinaryExpression analogs)
# ---------------------------------------------------------------------------

class UnaryExpression(Expression):
    """Template: null in -> null out; subclass provides the kernel."""

    def __init__(self, child: Expression):
        self.child = child

    @property
    def children(self) -> Tuple[Expression, ...]:
        return (self.child,)

    def do_columnar(self, xp, data, validity, col: DeviceColumn):
        """Return (data, validity) given raw arrays. ``xp`` is jnp or np."""
        raise NotImplementedError

    def eval(self, batch: DeviceBatch) -> ColumnLike:
        v = self.child.eval(batch)
        col = as_device_column(v, batch)
        data, validity = self.do_columnar(jnp, col.data, col.validity, col)
        return make_column(self.data_type(), data, validity)

    def eval_host(self, batch: HostBatch) -> HostColumnLike:
        v = self.child.eval_host(batch)
        col = as_host_column(v, batch)
        data, validity = self.do_columnar(np, col.data, col.validity, col)
        return make_host_column(self.data_type(), data, validity)


class BinaryExpression(Expression):
    """Template handling scalar/column operand combinations."""

    def __init__(self, left: Expression, right: Expression):
        self.left = left
        self.right = right

    @property
    def children(self) -> Tuple[Expression, ...]:
        return (self.left, self.right)

    def do_columnar(self, xp, l_data, l_valid, r_data, r_valid):
        """Return (data, validity) from raw operand arrays."""
        raise NotImplementedError

    def eval(self, batch: DeviceBatch) -> ColumnLike:
        lv = self.left.eval(batch)
        rv = self.right.eval(batch)
        lc = as_device_column(lv, batch)
        rc = as_device_column(rv, batch)
        data, validity = self.do_columnar(jnp, lc.data, lc.validity,
                                          rc.data, rc.validity)
        return make_column(self.data_type(), data, validity)

    def eval_host(self, batch: HostBatch) -> HostColumnLike:
        lc = as_host_column(self.left.eval_host(batch), batch)
        rc = as_host_column(self.right.eval_host(batch), batch)
        data, validity = self.do_columnar(np, lc.data, lc.validity,
                                          rc.data, rc.validity)
        return make_host_column(self.data_type(), data, validity)


def eval_exprs(exprs: Sequence[Expression],
               batch: DeviceBatch) -> DeviceBatch:
    """Project: evaluate expressions into a new device batch
    (GpuProjectExec's core, basicPhysicalOperators.scala:66)."""
    return project_batch(
        tuple(as_device_column(e.eval(batch), batch) for e in exprs),
        batch)


def project_batch(cols, batch: DeviceBatch) -> DeviceBatch:
    """New batch of ``cols`` sharing ``batch``'s liveness. A ZERO-column
    projection (count(*) pruning) must keep liveness in the selection
    vector, or the batch's capacity/row count is unrecoverable from its
    (empty) column shapes."""
    sel = batch.sel
    if not cols and sel is None:
        sel = batch.row_mask()
    return DeviceBatch(tuple(cols), batch.num_rows, sel=sel)


def eval_exprs_host(exprs: Sequence[Expression], batch: HostBatch,
                    names: Optional[Sequence[str]] = None) -> HostBatch:
    cols = [as_host_column(e.eval_host(batch), batch) for e in exprs]
    if names is None:
        names = tuple(f"c{i}" for i in range(len(cols)))
    return HostBatch(tuple(names), cols)
