"""Host-evaluated Python UDF expression — the fallback half of the UDF
tier (GpuArrowEvalPythonExec.scala:494 analog: the reference ships columns
to Python workers over Arrow and reads results back; in-process, the
device path downloads the argument columns, applies the function over
python values, and uploads the result column)."""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch
from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.exprs.base import (
    Expression, as_device_column, as_host_column)


class PythonUDF(Expression):
    """f(*args) applied row-wise with SQL-null passthrough of Nones."""

    def __init__(self, func, return_type: DataType, children,
                 reason: str = ""):
        self.func = func
        self._rt = return_type
        self._children = tuple(children)
        self.reason = reason        # why compilation failed (explain)

    @property
    def children(self) -> Tuple[Expression, ...]:
        return self._children

    def data_type(self) -> DataType:
        return self._rt

    @property
    def self_jittable(self) -> bool:
        return False

    def _apply(self, arg_lists: List[list], n: int) -> HostColumn:
        out = []
        for i in range(n):
            try:
                out.append(self.func(*[a[i] for a in arg_lists]))
            except Exception as e:
                raise RuntimeError(
                    f"python UDF "
                    f"{getattr(self.func, '__name__', 'udf')!r} failed "
                    f"on row {i}: {e}") from e
        return HostColumn.from_values(self._rt, out)

    def eval_host(self, batch: HostBatch) -> HostColumn:
        cols = [as_host_column(c.eval_host(batch), batch)
                for c in self._children]
        return self._apply([c.to_list() for c in cols], batch.num_rows)

    def eval(self, batch: DeviceBatch):
        from spark_rapids_tpu.columnar.host import (
            device_to_host, host_to_device)
        cols = [as_device_column(c.eval(batch), batch)
                for c in self._children]
        hb = device_to_host(DeviceBatch(tuple(cols), batch.num_rows,
                                        sel=batch.sel))
        # The download compacts selection vectors; re-expand results to
        # the batch's live positions so the column lines up row-for-row.
        live = np.asarray(batch.row_mask()) if batch.sel is not None \
            else None
        out = self._apply([c.to_list() for c in hb.columns], hb.num_rows)
        if live is not None:
            data = np.zeros(batch.capacity, object) \
                if self._rt.is_string else \
                np.zeros(batch.capacity, self._rt.np_dtype)
            validity = np.zeros(batch.capacity, np.bool_)
            idx = np.nonzero(live)[0]
            if self._rt.is_string:
                data[:] = b""
            data[idx] = out.data
            validity[idx] = out.validity
            out = HostColumn(self._rt, data, validity)
        dev = host_to_device(HostBatch(("c",), [out]),
                             capacity=batch.capacity)
        return dev.columns[0]

    def pretty(self) -> str:
        return f"pyudf:{getattr(self.func, '__name__', 'udf')}"
