"""Live telemetry plane: a process-global typed metric registry.

The flight recorder (recorder.py) answers "where did query N's
wall-clock go" — a bounded per-query timeline that dies with the
process. This module is the *serving* counterpart: monotonic counters,
gauges and sliding-window log-bucket histograms (p50/p95/p99) with
labeled series (tenant / class / kind / tier / worker), continuously
scrapeable while queries are in flight. The reference stack's analog is
the Spark metrics system the RAPIDS plugin feeds per-operator GPU
metrics into; here the sources are the engine's existing counter
funnels (scheduler + QoS admission/rejection, plan- and kernel-cache
hit rates, recovery ladder rungs, transport bytes/refetches, pipeline
overlap, the spill ladder's device watermark) plus direct
instrumentation on the query lifecycle.

Same always-cheap discipline as the recorder: the DISABLED path of
:func:`inc` / :func:`observe` / :func:`set_gauge` is one module-global
load and a return — the tier-1 suite runs byte-identical with metrics
off, and scripts/microbench.py's ``telemetry_overhead`` probe bounds
the disabled-call cost next to the trace no-op.

Config (process-global, last collect's conf wins — the wire-codec
regime): ``spark.rapids.sql.metrics.enabled`` (``SRT_METRICS`` env
override), ``spark.rapids.sql.metrics.port`` (the OpenMetrics exporter
in exporter.py; 0 = registry only, no socket).

Consumers: :func:`snapshot` (structured dict — bench.py's ``telemetry``
block), :func:`render_text` (OpenMetrics/Prometheus text exposition —
the exporter's ``/metrics`` body, zero-dependency so tests never need
the socket), and the cluster runtime: workers flatten their registry
into :func:`export_cluster_blob` piggybacked on CBEAT heartbeats, the
driver's coordinator feeds :func:`fleet_update`, and every fleet series
re-renders with a ``worker=<wid>`` label.

Stdlib-only at module level, like the recorder: this is imported from
the dispatch funnel.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# -- process-global state -----------------------------------------------------

# THE fast-path gate: the disabled inc()/observe()/set_gauge() path
# reads this one global and returns.
_ENABLED = False

_LOCK = threading.Lock()
_METRICS: "collections.OrderedDict[str, _Metric]" = collections.OrderedDict()
# Fleet view (driver only): wid -> flat {series_key: value} ingested
# from CBEAT heartbeat piggybacks.
_FLEET: Dict[str, dict] = {}

# Histogram window geometry: log buckets growing by 2**(1/4) (~19% per
# bucket, so a reconstructed quantile is within ~9% of the true value),
# over a sliding window of epochs rotated by time or explicitly.
_BUCKET_BASE = 2.0 ** 0.25
_LOG_BASE = math.log(_BUCKET_BASE)
_WINDOW_EPOCHS = 8
_ROTATE_S = 30.0

_QUANTILES = (0.5, 0.95, 0.99)


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Hist:
    """One labeled histogram series: sparse log-bucket counts over a
    sliding window (current epoch + up to window-1 rotated epochs), with
    LIFETIME count/sum (the OpenMetrics summary ``_count``/``_sum``
    monotonic pair) and window-scoped quantiles."""

    __slots__ = ("cur", "past", "count", "sum", "epoch_t0")

    def __init__(self):
        self.cur: Dict[int, int] = {}
        self.past: collections.deque = collections.deque(
            maxlen=_WINDOW_EPOCHS - 1)
        self.count = 0
        self.sum = 0.0
        self.epoch_t0 = time.monotonic()

    def observe(self, value: float, now: Optional[float] = None) -> None:
        if now is None:
            now = time.monotonic()
        if now - self.epoch_t0 >= _ROTATE_S:
            self.rotate(now)
        v = float(value)
        idx = int(math.floor(math.log(v) / _LOG_BASE)) if v > 0 else -(10**9)
        self.cur[idx] = self.cur.get(idx, 0) + 1
        self.count += 1
        self.sum += v

    def rotate(self, now: Optional[float] = None) -> None:
        """Start a new epoch; observations older than the window leave
        the quantile view (count/sum stay monotonic)."""
        self.past.append(self.cur)
        self.cur = {}
        self.epoch_t0 = time.monotonic() if now is None else now

    def _window_buckets(self) -> Dict[int, int]:
        merged = dict(self.cur)
        for epoch in self.past:
            for idx, n in epoch.items():
                merged[idx] = merged.get(idx, 0) + n
        return merged

    def quantiles(self) -> Dict[float, float]:
        buckets = self._window_buckets()
        total = sum(buckets.values())
        if total == 0:
            return {q: float("nan") for q in _QUANTILES}
        order = sorted(buckets)
        out = {}
        for q in _QUANTILES:
            target = q * total
            cum = 0
            val = 0.0
            for idx in order:
                n = buckets[idx]
                cum += n
                if cum >= target:
                    if idx <= -(10**9):
                        val = 0.0
                    else:
                        lo = _BUCKET_BASE ** idx
                        hi = _BUCKET_BASE ** (idx + 1)
                        frac = (target - (cum - n)) / n
                        val = lo + (hi - lo) * frac
                    break
            out[q] = val
        return out


class _Metric:
    __slots__ = ("name", "kind", "help", "series")

    def __init__(self, name: str, kind: str, help_: str):
        self.name = name
        self.kind = kind
        self.help = help_
        # label tuple -> float (counter/gauge) or _Hist
        self.series: Dict[tuple, object] = {}


def _metric(name: str, kind: str, help_: str = "") -> _Metric:
    """Register-on-first-use; a name keeps the kind it was born with."""
    m = _METRICS.get(name)
    if m is None:
        m = _METRICS.setdefault(name, _Metric(name, kind, help_))
    if m.kind != kind:
        raise ValueError(
            f"metric {name!r} is a {m.kind}, not a {kind}")
    if help_ and not m.help:
        m.help = help_
    return m


def describe(name: str, kind: str, help_: str) -> None:
    """Pre-register a metric's kind + help text (optional — first use
    registers too)."""
    with _LOCK:
        _metric(name, kind, help_)


# -- the recording API (hot path) ---------------------------------------------

def inc(name: str, amount: float = 1.0, **labels) -> None:
    """Increment a monotonic counter series. Disabled: one global load."""
    if not _ENABLED:
        return
    with _LOCK:
        m = _metric(name, COUNTER)
        key = _label_key(labels)
        m.series[key] = m.series.get(key, 0.0) + amount


def set_gauge(name: str, value: float, **labels) -> None:
    """Set a gauge series to ``value``. Disabled: one global load."""
    if not _ENABLED:
        return
    with _LOCK:
        m = _metric(name, GAUGE)
        m.series[_label_key(labels)] = float(value)


def max_gauge(name: str, value: float, **labels) -> None:
    """High-watermark gauge: keeps the max ever set (the spill ladder's
    device watermark). Disabled: one global load."""
    if not _ENABLED:
        return
    with _LOCK:
        m = _metric(name, GAUGE)
        key = _label_key(labels)
        prev = m.series.get(key)
        if prev is None or float(value) > prev:
            m.series[key] = float(value)


def observe(name: str, value: float, **labels) -> None:
    """Record one observation into a sliding-window log-bucket histogram
    series. Disabled: one global load."""
    if not _ENABLED:
        return
    with _LOCK:
        m = _metric(name, HISTOGRAM)
        key = _label_key(labels)
        h = m.series.get(key)
        if h is None:
            h = m.series[key] = _Hist()
        h.observe(value)


def rotate_windows() -> None:
    """Force every histogram series into a new epoch (tests drive window
    rotation deterministically through this instead of the 30 s timer)."""
    with _LOCK:
        for m in _METRICS.values():
            if m.kind == HISTOGRAM:
                for h in m.series.values():
                    h.rotate()


def enabled() -> bool:
    return _ENABLED


# -- configuration ------------------------------------------------------------

def metrics_enabled(conf=None) -> bool:
    """Conf key wins; else the SRT_METRICS env (the CI matrix hook);
    else the registered default (off)."""
    from spark_rapids_tpu import config as C
    if conf is not None and conf.raw.get(C.METRICS_ENABLED.key) is not None:
        return bool(conf.get(C.METRICS_ENABLED))
    env = os.environ.get("SRT_METRICS")
    if env is not None:
        return env.strip() not in ("", "0", "false", "no")
    return bool(C.METRICS_ENABLED.default)


def maybe_configure(conf) -> None:
    """Adopt this query's telemetry configuration (process-global, last
    writer wins — the wire-codec regime). Called from the dispatch
    funnel before any instrumented site runs. Starting the exporter
    socket and the event log are side effects of turning metrics on;
    neither ever stops a running exporter (mixed-conf processes would
    flap it)."""
    global _ENABLED
    from spark_rapids_tpu import config as C
    want = metrics_enabled(conf)
    if want != _ENABLED:
        _ENABLED = want
    from spark_rapids_tpu.monitoring import history
    history.maybe_configure(conf)
    if not want:
        return
    port = int(conf.get(C.METRICS_PORT))
    if port > 0:
        from spark_rapids_tpu.monitoring import exporter
        exporter.ensure_started(port)


def configure(enabled_: bool, port: int = 0) -> None:
    """Direct (test/bench) configuration, bypassing the conf plumbing."""
    global _ENABLED
    _ENABLED = bool(enabled_)
    if enabled_ and port > 0:
        from spark_rapids_tpu.monitoring import exporter
        exporter.ensure_started(port)


def reset() -> None:
    """Drop every series and the fleet view (test isolation; keeps the
    enabled flag)."""
    with _LOCK:
        _METRICS.clear()
        _FLEET.clear()


# -- funnel bridge ------------------------------------------------------------

# Dotted funnel counter names carry a dimension in their tail
# (``rejected.queue-full``, ``admitted.interactive``,
# ``planCacheHit.tenantA``): the base picks the label name.
_SUB_LABEL = {
    "admitted": "class", "rejected": "kind", "class": "class",
    "tenant": "tenant", "planCacheHit": "tenant", "planCacheMiss": "tenant",
}


def _snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        elif ch.isalnum() or ch == "_":
            out.append(ch)
        else:
            out.append("_")
    s = "".join(out).strip("_")
    while "__" in s:
        s = s.replace("__", "_")
    return s


def _publish_funnel(sub: str, counters: Dict[str, float]) -> None:
    for name, value in counters.items():
        if isinstance(value, bool) or \
                not isinstance(value, (int, float)):
            continue    # funnels may expose structured diagnostics too
        base, _, tail = name.partition(".")
        metric = f"srt_{sub}_{_snake(base)}"
        m = _metric(metric, COUNTER)
        labels = {}
        if tail:
            labels[_SUB_LABEL.get(base, "sub")] = tail
        # Funnel counters are cumulative at the source: publish the
        # absolute value (set, not add) so a re-sync is idempotent.
        m.series[_label_key(labels)] = float(value)


def sync_funnels() -> None:
    """Pull every existing counter funnel into the registry (absolute
    values, idempotent). Runs at query teardown and on every
    snapshot/render/scrape — the funnels stay the single source of
    truth; this is the exposition bridge."""
    if not _ENABLED:
        return
    sources = []
    try:
        from spark_rapids_tpu.parallel import scheduler as _sc
        sources.append(("scheduler", _sc.counters()))
    except Exception:
        pass
    try:
        from spark_rapids_tpu.parallel import qos as _q
        sources.append(("qos", _q.counters()))
    except Exception:
        pass
    try:
        from spark_rapids_tpu import faults as _f
        sources.append(("recovery", _f.counters()))
    except Exception:
        pass
    try:
        from spark_rapids_tpu.parallel import transport as _t
        sources.append(("transport", _t.counters()))
    except Exception:
        pass
    try:
        from spark_rapids_tpu.parallel import pipeline as _p
        sources.append(("pipeline", _p.counters()))
    except Exception:
        pass
    try:
        from spark_rapids_tpu.columnar import wire as _w
        sources.append(("wire", _w.counters()))
    except Exception:
        pass
    try:
        from spark_rapids_tpu.ops import native as _n
        sources.append(("native", _n.counters()))
    except Exception:
        pass
    try:
        from spark_rapids_tpu.plan import cost as _c
        sources.append(("cost", _c.counters()))
    except Exception:
        pass
    try:
        from spark_rapids_tpu.plan import plan_cache as _pc
        sources.append(("plan_cache", _pc.counters()))
        sources.append(("plan_cache", {
            k: v for k, v in _pc.cache().stats().items()
            if isinstance(v, (int, float))}))
    except Exception:
        pass
    try:
        from spark_rapids_tpu.ops import kernel_cache as _kc
        sources.append(("kernel_cache", {
            k: v for k, v in _kc.cache().stats().items()
            if isinstance(v, (int, float))}))
    except Exception:
        pass
    with _LOCK:
        for sub, counters in sources:
            _publish_funnel(sub, counters)


# -- consumers ----------------------------------------------------------------

def snapshot() -> dict:
    """Structured registry view (bench.py's ``telemetry`` block and the
    zero-socket test path). Funnels are synced first so the view
    reconciles with the subsystem counters at the instant of the call."""
    sync_funnels()
    out: Dict[str, dict] = {}
    with _LOCK:
        for name, m in _METRICS.items():
            series = []
            for key in sorted(m.series):
                labels = dict(key)
                if m.kind == HISTOGRAM:
                    h = m.series[key]
                    qs = h.quantiles()
                    series.append({
                        "labels": labels, "count": h.count,
                        "sum": round(h.sum, 6),
                        "p50": qs[0.5], "p95": qs[0.95], "p99": qs[0.99]})
                else:
                    series.append({"labels": labels,
                                   "value": m.series[key]})
            out[name] = {"kind": m.kind, "help": m.help, "series": series}
        fleet = {wid: dict(payload) for wid, payload in _FLEET.items()}
    return {"enabled": _ENABLED, "metrics": out, "fleet": fleet}


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels, extra=()) -> str:
    items = list(labels) + list(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in items)
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_text() -> str:
    """OpenMetrics/Prometheus text exposition: ``# TYPE`` lines,
    escaped labels, counters with a ``_total`` sample suffix,
    histograms as summaries (window quantiles + lifetime count/sum).
    Fleet series ingested from worker heartbeats render after the local
    series of the same metric with a ``worker`` label."""
    sync_funnels()
    lines: List[str] = []
    with _LOCK:
        fleet_by_metric: Dict[str, list] = {}
        for wid in sorted(_FLEET):
            for skey, value in sorted(_FLEET[wid].get("series", {}).items()):
                name, _, labelpart = skey.partition("|")
                kind = _FLEET[wid].get("kinds", {}).get(name, GAUGE)
                labels = []
                if labelpart:
                    labels = [tuple(p.split("=", 1))
                              for p in labelpart.split(",")]
                fleet_by_metric.setdefault(name, []).append(
                    (kind, labels, wid, value))
        names = sorted(set(_METRICS) | set(fleet_by_metric))
        for name in names:
            m = _METRICS.get(name)
            kind = m.kind if m is not None else \
                fleet_by_metric[name][0][0]
            lines.append(f"# TYPE {name} {kind}")
            if m is not None and m.help:
                lines.append(f"# HELP {name} {_escape_label(m.help)}")
            if m is not None:
                for key in sorted(m.series):
                    if m.kind == COUNTER:
                        lines.append(
                            f"{name}_total{_fmt_labels(key)} "
                            f"{_fmt_value(m.series[key])}")
                    elif m.kind == GAUGE:
                        lines.append(
                            f"{name}{_fmt_labels(key)} "
                            f"{_fmt_value(m.series[key])}")
                    else:
                        h = m.series[key]
                        qs = h.quantiles()
                        for q in _QUANTILES:
                            lines.append(
                                f"{name}{_fmt_labels(key, [('quantile', repr(q))])} "
                                f"{_fmt_value(qs[q])}")
                        lines.append(
                            f"{name}_sum{_fmt_labels(key)} "
                            f"{_fmt_value(h.sum)}")
                        lines.append(
                            f"{name}_count{_fmt_labels(key)} "
                            f"{_fmt_value(h.count)}")
            for kind_, labels, wid, value in fleet_by_metric.get(name, []):
                suffix = "_total" if kind_ == COUNTER else ""
                lines.append(
                    f"{name}{suffix}"
                    f"{_fmt_labels(labels, [('worker', wid)])} "
                    f"{_fmt_value(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# -- cluster fleet view -------------------------------------------------------

def export_cluster_blob() -> dict:
    """Flatten the local registry (counters + gauges; histograms ship
    their lifetime count/sum as gauges) for a CBEAT heartbeat piggyback.
    Values are cumulative absolutes, so a lost heartbeat costs nothing:
    the next one supersedes it."""
    sync_funnels()
    series: Dict[str, float] = {}
    kinds: Dict[str, str] = {}
    with _LOCK:
        for name, m in _METRICS.items():
            kinds[name] = m.kind if m.kind != HISTOGRAM else GAUGE
            for key, v in m.series.items():
                labelpart = ",".join(f"{k}={val}" for k, val in key)
                if m.kind == HISTOGRAM:
                    series[f"{name}_count|{labelpart}"] = float(v.count)
                    series[f"{name}_sum|{labelpart}"] = float(v.sum)
                    kinds[f"{name}_count"] = GAUGE
                    kinds[f"{name}_sum"] = GAUGE
                else:
                    series[f"{name}|{labelpart}"] = float(v)
    return {"series": series, "kinds": kinds}


def fleet_update(wid: str, payload: dict) -> None:
    """Ingest one worker's flattened registry (driver side, fed by the
    coordinator's CBEAT handler). Last heartbeat wins."""
    if not isinstance(payload, dict):
        return
    with _LOCK:
        _FLEET[str(wid)] = payload


def fleet() -> Dict[str, dict]:
    with _LOCK:
        return {wid: dict(p) for wid, p in _FLEET.items()}
