"""``DataFrame.explain_analyze``: the plan tree annotated with OBSERVED
per-operator numbers next to the cost model's ESTIMATES.

``explain`` answers "what will run where"; this answers "what actually
happened, and how wrong was the model" — the estimate-vs-actual feedback
loop the cost calibration constants (``spark.rapids.sql.cost.*``) need.
Per physical node:

- observed: rows / bytes (recorded where a host-known row count exists —
  scans, projections, exchange serves; ``?`` where counting would cost a
  device sync), wall-ms (the operator's ``totalTime``), batches;
- estimated: the cost model's subtree device estimate (ms / sync count /
  bytes) for the logical node this physical node was converted from,
  with the subtree observed wall and the signed error percentage.

The query footer aggregates the audit entries (Recovery/Scheduler/...)
and, when the flight recorder is on, the span-category time breakdown —
so one artifact answers "where did query N's wall-clock go".
"""

from __future__ import annotations

from typing import Dict, List, Optional


def _fmt_ms(ns: float) -> str:
    return f"{ns / 1e6:.1f}ms"


def _fmt_bytes(n) -> str:
    return "?" if n is None else f"{int(n):,}B"


def _node_metrics(ctx, op) -> dict:
    if ctx is None:
        return {}
    m = ctx.metrics.get(f"{op.name}@{id(op):x}")
    return dict(m.values) if m is not None else {}


def _wall_ns(vals: dict) -> float:
    # Scans meter their host decode+upload as bufferTime, operators
    # their dispatch as totalTime; a node's wall is whichever it pays.
    return vals.get("totalTime", 0.0) + vals.get("bufferTime", 0.0)


def _subtree_wall_ns(ctx, op) -> float:
    total = _wall_ns(_node_metrics(ctx, op))
    return total + sum(_subtree_wall_ns(ctx, c) for c in op.children)


def render(phys, ctx) -> str:
    """Render the analyzed plan tree for one executed PhysicalPlan."""
    from spark_rapids_tpu.plan import cost as COST
    ests: Dict[int, object] = {}
    try:
        ests = COST.estimate_plan(phys.meta.plan, phys.conf)
    except Exception:
        pass        # no footer stats / exotic plan: observed-only render

    lines: List[str] = []

    def walk(op, depth: int):
        vals = _node_metrics(ctx, op)
        rows = vals.get("numOutputRows")
        nbytes = vals.get("numOutputBytes")
        wall = _wall_ns(vals)
        parts = [
            f"rows={int(rows):,}" if rows is not None else "rows=?",
            f"bytes={_fmt_bytes(nbytes)}",
            f"wall={_fmt_ms(wall)}",
        ]
        batches = vals.get("numOutputBatches")
        if batches:
            parts.append(f"batches={int(batches)}")
        est = ests.get(getattr(op, "_logical_id", -1))
        if est is not None:
            obs_ms = _subtree_wall_ns(ctx, op) / 1e6
            est_ms = est.device_ms
            err = ""
            if est_ms > 0:
                err = f" err={100.0 * (obs_ms - est_ms) / est_ms:+.0f}%"
            parts.append(
                f"| est {est_ms:.0f}ms/{est.syncs} syncs "
                f"~{_fmt_bytes(est.bytes_out)} obs {obs_ms:.1f}ms{err}")
        lines.append("  " * depth + f"{op.name}  " + " ".join(parts))
        for c in op.children:
            walk(c, depth + 1)

    walk(phys.root, 0)

    # Footer: the per-query audit entries + the trace's category
    # breakdown ("where did the wall-clock go", one line per category).
    if ctx is not None:
        from spark_rapids_tpu.ops.base import audit_metric_groups
        audits = {k: m for k, m in ctx.metrics.items()
                  if m.owner in audit_metric_groups() and m.values}
        for key in sorted(audits):
            vals = audits[key].values
            body = ", ".join(
                f"{n}={v:.0f}" if float(v).is_integer() else f"{n}={v:.2f}"
                for n, v in sorted(vals.items()))
            lines.append(f"{key}: {body}")
        qid = ctx.cache.get("trace_query")
        if qid is not None:
            from spark_rapids_tpu.monitoring import recorder
            cats: Dict[str, float] = {}
            syncs = 0
            for e in recorder.events(qid):
                if e[0] == "X":
                    cats[e[2]] = cats.get(e[2], 0.0) + e[4] / 1e6
                    if e[2] == "sync":
                        syncs += 1
            if cats:
                body = ", ".join(f"{c}={ms:.1f}ms"
                                 for c, ms in sorted(cats.items()))
                lines.append(f"Trace@query {qid}: {body}"
                             + (f", syncs={syncs}" if syncs else ""))
    return "\n".join(lines)
