"""OpenMetrics/Prometheus HTTP exporter for the telemetry registry.

A stdlib-only ``http.server`` on a localhost daemon thread (behind
``spark.rapids.sql.metrics.port``; 0 = never started — tests and bench
read :func:`telemetry.render_text` directly). Endpoints:

- ``/metrics`` — the OpenMetrics text exposition (local series + the
  fleet series ingested from worker heartbeats);
- ``/healthz`` — liveness ("ok").

Bound to 127.0.0.1 only: the scrape surface carries tenant names and
query shapes, so exposure beyond the host is a deliberate operator
decision (a real deployment fronts it with its own relay), not a
default.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_LOCK = threading.Lock()
_SERVER: Optional[ThreadingHTTPServer] = None
_THREAD: Optional[threading.Thread] = None
_PORT = 0

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):

    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path.split("?", 1)[0] == "/metrics":
            from spark_rapids_tpu.monitoring import telemetry
            try:
                body = telemetry.render_text().encode("utf-8")
            except Exception as e:     # a scrape must never wedge a query
                self.send_response(500)
                self.end_headers()
                self.wfile.write(f"render failed: {e}".encode())
                return
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/healthz":
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"ok")
        else:
            self.send_response(404)
            self.end_headers()

    def log_message(self, fmt, *args):  # silence per-request stderr lines
        pass


def ensure_started(port: int) -> int:
    """Start the exporter on 127.0.0.1:``port`` if not already running
    (idempotent; a running exporter keeps its original port). ``port``
    0 binds an ephemeral port (tests). Returns the bound port."""
    global _SERVER, _THREAD, _PORT
    with _LOCK:
        if _SERVER is not None:
            return _PORT
        server = ThreadingHTTPServer(("127.0.0.1", int(port)), _Handler)
        server.daemon_threads = True
        thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.25},
            name="srt-metrics-exporter", daemon=True)
        thread.start()
        _SERVER, _THREAD = server, thread
        _PORT = server.server_address[1]
        return _PORT


def stop() -> None:
    """Shut the exporter down (tests; production lets the daemon thread
    die with the process)."""
    global _SERVER, _THREAD, _PORT
    with _LOCK:
        server, thread = _SERVER, _THREAD
        _SERVER, _THREAD, _PORT = None, None, 0
    if server is not None:
        server.shutdown()
        server.server_close()
    if thread is not None:
        thread.join(timeout=5.0)


def running() -> bool:
    with _LOCK:
        return _SERVER is not None


def port() -> int:
    with _LOCK:
        return _PORT
