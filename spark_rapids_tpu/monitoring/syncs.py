"""Host-sync attribution on the span stream (scripts/syncprof.py's
engine, promoted into the monitoring subsystem).

On a tunneled chip a device->host read costs a ~70ms round trip, so
query wall time ~= device compute + 70ms * syncs. This wraps every sync
funnel (``jax.device_get``, ``ArrayImpl.__array__`` / ``__int__`` /
``__float__`` / ``__bool__`` / ``__index__``) and records each blocking
read as a ``sync`` span (LEVEL_KERNEL) whose args carry the innermost
engine call sites — the "where do the round trips come from" view that
jax.profiler traces don't give on a remote backend. The spans interleave
with the operator/upload/shuffle spans on the same timeline, so a
Perfetto export shows each round trip *inside* the operator that paid
for it.

Install once per process (:func:`install`); the wrappers stay resident
but record nothing while the recorder is disabled or below
LEVEL_KERNEL, so installation is safe outside profiling runs too.
"""

from __future__ import annotations

import traceback
from typing import Dict, List, Tuple

from spark_rapids_tpu.monitoring import recorder

_INSTALLED = False


def _site() -> str:
    """Innermost TWO spark_rapids_tpu frames (helper + its caller)."""
    frames = []
    for f in reversed(traceback.extract_stack()):
        if "spark_rapids_tpu" in f.filename and \
                "/monitoring/" not in f.filename:
            short = f.filename.split("spark_rapids_tpu/")[-1]
            frames.append(f"{short}:{f.lineno} {f.name}")
            if len(frames) == 2:
                break
    return " <- ".join(frames) if frames else "<outside engine>"


def _wrap(fn, label: str):
    def wrapper(*a, **k):
        if not recorder.enabled() or \
                recorder.level() < recorder.LEVEL_KERNEL:
            return fn(*a, **k)
        with recorder.span(label, "sync", level=recorder.LEVEL_KERNEL,
                           args={"site": _site()}):
            return fn(*a, **k)
    wrapper.__wrapped__ = fn
    return wrapper


def install() -> None:
    """Wrap the jax sync funnels (idempotent)."""
    global _INSTALLED
    if _INSTALLED:
        return
    import jax
    from jax._src import array as _arr
    jax.device_get = _wrap(jax.device_get, "device_get")
    for m in ("__array__", "__int__", "__float__", "__bool__",
              "__index__"):
        if hasattr(_arr.ArrayImpl, m):
            setattr(_arr.ArrayImpl, m,
                    _wrap(getattr(_arr.ArrayImpl, m), m))
    _INSTALLED = True


def sync_stats(query_id=None) -> Dict[str, Tuple[int, float]]:
    """Aggregate recorded sync spans: ``label @ site`` -> (count, secs)
    — the exact shape scripts/syncprof.py reports."""
    stats: Dict[str, List[float]] = {}
    for e in recorder.events(query_id):
        ph, name, cat, ts, dur, tid, qid, args = e
        if ph != "X" or cat != "sync":
            continue
        a = args or {}
        # timed(m, "sizesPullTime") spans are syncs too — their "site"
        # is the metric name on the owning operator.
        site = a.get("site") or a.get("metric") or "<unknown>"
        s = stats.setdefault(f"{name} @ {site}", [0, 0.0])
        s[0] += 1
        s[1] += dur / 1e9
    return {k: (int(v[0]), v[1]) for k, v in stats.items()}
