"""Query flight recorder: structured trace spans + instant events.

The reference wraps every GPU operator in an NVTX range
(NvtxWithMetrics.scala:21-44) so an Nsight capture shows exactly where a
query's time went. This engine's analog must work WITHOUT an external
profiler attached — the backend is a tunneled chip and the interesting
time is host-side orchestration (scheduler queue, host prefetch, wire
pack, upload, device dispatch, shuffle spool, recovery rework) — so the
recorder lives in-process: a bounded per-query ring buffer of

- **spans** — named intervals with a category, monotonic start/duration
  (``time.perf_counter_ns``), the recording thread, and the owning query
  id (the scheduler admission ordinal, resolved from the thread's
  ``faults.QueryToken``); and
- **instants** — point events for the things that are *decisions*, not
  durations: fault injected, OOM rung taken, stage recompute, join
  demotion, watchdog kill, cancellation, cross-query eviction.

Always cheap enough to leave on: the DISABLED path of :func:`span` /
:func:`instant` is one module-global load + a truthiness test returning
a shared no-op (no allocation, no lock, no clock read) — the tier-1
suite runs bit-identical with tracing off, and scripts/microbench.py
bounds the disabled-call cost. Enabled, every ring is a
``collections.deque(maxlen=trace.maxEvents)``, so a runaway query can
never hold more than a bounded window of its own history (the flight
recorder discipline: you keep the tail, not the flight).

Config (process-global, last collect's conf wins — the same regime as
the wire codec): ``spark.rapids.sql.trace.enabled`` (``SRT_TRACE`` env
override), ``spark.rapids.sql.trace.maxEvents``,
``spark.rapids.sql.trace.level`` (``query`` < ``operator`` <
``kernel``).

Consumers: ``DataFrame.trace_export`` renders Chrome trace-event JSON
(chrome.py — loads in Perfetto / chrome://tracing, one track per query
and per worker thread), ``DataFrame.explain_analyze`` joins the span
stream with per-operator metrics and the cost model's estimates
(analyze.py), and :func:`snapshot` aggregates the span-category time
breakdown bench.py publishes as its ``trace`` JSON block.

Deliberately imports nothing beyond stdlib at module level: faults.py
(itself stdlib-only) emits instants from injection sites, and the
query-id resolve lazily imports faults at first *enabled* record.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

# Verbosity levels: a span/instant records only when its level is at or
# below the configured one.
LEVEL_QUERY = 1      # query/stage lifecycle + every instant event
LEVEL_OPERATOR = 2   # + per-partition, per-operator, upload, shuffle
LEVEL_KERNEL = 3     # + per-batch wire encode/pack, sync attribution

_LEVEL_NAMES = {"query": LEVEL_QUERY, "operator": LEVEL_OPERATOR,
                "kernel": LEVEL_KERNEL}

# -- process-global state -----------------------------------------------------

# THE fast-path gate: the disabled span()/instant() path reads this one
# global and returns. Everything else hides behind it.
_ENABLED = False
_LEVEL = LEVEL_OPERATOR
_MAX_EVENTS = 65536
_MAX_QUERIES = 64           # oldest query rings evicted past this

_LOCK = threading.Lock()
# query id -> deque of event tuples, insertion-ordered so the oldest
# query is evicted first. Event tuples (kept flat for append cost):
#   ("X", name, cat, ts_ns, dur_ns, tid, qid, args_or_None)   span
#   ("i", name, cat, ts_ns, None,   tid, qid, args_or_None)   instant
_RINGS: "collections.OrderedDict[int, collections.deque]" = \
    collections.OrderedDict()
_THREAD_NAMES: Dict[int, str] = {}
_DROPPED: Dict[int, int] = {}       # per-query ring overflow count
_OPEN = itertools.count()           # spans entered
_CLOSED = itertools.count()         # spans exited (well-formedness probe)

# Epoch all timestamps are relative to (perf_counter_ns at import), so
# exported traces start near 0 instead of at an arbitrary boot offset.
_EPOCH_NS = time.perf_counter_ns()

_faults = None                      # lazily-bound spark_rapids_tpu.faults

# Process identity for exported traces. Empty in the driver; cluster
# worker processes set "worker <wid>" so a worker-side trace export
# names its tracks "worker w0 query N" and a merged multi-process view
# stays attributable.
_PROCESS_TAG = ""


def _now_ns() -> int:
    return time.perf_counter_ns() - _EPOCH_NS


def _current_query_id() -> int:
    """The recording thread's query id (scheduler admission ordinal), or
    0 outside a managed query — unmanaged collects share ring 0."""
    global _faults
    f = _faults
    if f is None:
        from spark_rapids_tpu import faults as f
        globals()["_faults"] = f
    qid = f.current_query_id()
    return 0 if qid is None else qid


def _ring(qid: int) -> collections.deque:
    ring = _RINGS.get(qid)
    if ring is None:
        with _LOCK:
            ring = _RINGS.get(qid)
            if ring is None:
                ring = _RINGS[qid] = collections.deque(maxlen=_MAX_EVENTS)
                while len(_RINGS) > _MAX_QUERIES:
                    old, _ = _RINGS.popitem(last=False)
                    _DROPPED.pop(old, None)
    return ring


def _record(event: tuple, qid: int) -> None:
    ring = _ring(qid)
    if len(ring) == ring.maxlen:
        _DROPPED[qid] = _DROPPED.get(qid, 0) + 1
    ring.append(event)      # deque.append is atomic under the GIL
    tid = event[5]
    if tid not in _THREAD_NAMES:
        _THREAD_NAMES[tid] = threading.current_thread().name


# -- the recording API --------------------------------------------------------

class _NoopSpan:
    """Shared disabled span: __enter__/__exit__ do nothing. One instance
    for the whole process — the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "qid", "_t0")

    def __init__(self, name: str, cat: str, args, qid):
        self.name = name
        self.cat = cat
        self.args = args
        self.qid = qid

    def __enter__(self):
        next(_OPEN)
        self._t0 = _now_ns()
        return self

    def __exit__(self, *exc):
        t0 = self._t0
        dur = _now_ns() - t0
        qid = self.qid if self.qid is not None else _current_query_id()
        _record(("X", self.name, self.cat, t0, dur,
                 threading.get_ident(), qid, self.args), qid)
        next(_CLOSED)
        return False


def span(name: str, cat: str, level: int = LEVEL_OPERATOR,
         args: Optional[dict] = None, qid: Optional[int] = None):
    """A context manager recording one trace span. Disabled (or above
    the configured level) it returns the shared no-op — the caller's
    ``with`` costs two empty method calls and nothing else."""
    if not _ENABLED or level > _LEVEL:
        return _NOOP
    return _Span(name, cat, args, qid)


def now_ns() -> int:
    """Recorder-epoch-relative monotonic timestamp (for retro-recorded
    spans)."""
    return _now_ns()


def record_span(name: str, cat: str, t0_ns: int, dur_ns: int,
                qid: Optional[int] = None, args: Optional[dict] = None,
                level: int = LEVEL_OPERATOR) -> None:
    """Retro-record one completed span — for intervals whose owning
    query id only exists once they END (scheduler admission issues the
    id the admission wait was FOR)."""
    if not _ENABLED or level > _LEVEL:
        return
    q = qid if qid is not None else _current_query_id()
    _record(("X", name, cat, t0_ns, max(int(dur_ns), 0),
             threading.get_ident(), q, args), q)


def instant(name: str, cat: str, args: Optional[dict] = None,
            qid: Optional[int] = None, level: int = LEVEL_QUERY) -> None:
    """Record one instant event (fault injected, OOM rung, recompute,
    demotion, cancellation...). Instants default to LEVEL_QUERY: they
    are rare and they are the events the trace exists to explain."""
    if not _ENABLED or level > _LEVEL:
        return
    q = qid if qid is not None else _current_query_id()
    _record(("i", name, cat, _now_ns(), None,
             threading.get_ident(), q, args), q)


def set_process_tag(tag: str) -> None:
    """Name this process in exported traces (cluster workers pass
    ``worker <wid>``). Affects rendering only, never recording."""
    global _PROCESS_TAG
    _PROCESS_TAG = str(tag)


def process_tag() -> str:
    return _PROCESS_TAG


def enabled() -> bool:
    return _ENABLED


def level() -> int:
    return _LEVEL


# -- configuration ------------------------------------------------------------

def trace_enabled(conf) -> bool:
    """Conf key wins; else the SRT_TRACE env (the CI matrix hook); else
    the registered default (off)."""
    from spark_rapids_tpu import config as C
    if conf.raw.get(C.TRACE_ENABLED.key) is not None:
        return bool(conf.get(C.TRACE_ENABLED))
    env = os.environ.get("SRT_TRACE")
    if env is not None:
        return env.strip() not in ("", "0", "false", "no")
    return bool(C.TRACE_ENABLED.default)


def maybe_configure(conf) -> None:
    """Adopt this query's trace configuration (process-global, last
    writer wins — the wire-codec regime). Called once per collect from
    the dispatch funnel, BEFORE any span site runs."""
    global _ENABLED, _LEVEL, _MAX_EVENTS
    from spark_rapids_tpu import config as C
    want = trace_enabled(conf)
    lvl = _LEVEL_NAMES.get(
        str(conf.get(C.TRACE_LEVEL)).strip().lower(), LEVEL_OPERATOR)
    max_events = max(int(conf.get(C.TRACE_MAX_EVENTS)), 256)
    if want == _ENABLED and lvl == _LEVEL and max_events == _MAX_EVENTS:
        return
    with _LOCK:
        _LEVEL = lvl
        if max_events != _MAX_EVENTS:
            _MAX_EVENTS = max_events    # existing rings keep their bound
        _ENABLED = want


def configure(enabled_: bool, level_: int = LEVEL_OPERATOR,
              max_events: int = 65536) -> None:
    """Direct (test/bench) configuration, bypassing the conf plumbing."""
    global _ENABLED, _LEVEL, _MAX_EVENTS
    with _LOCK:
        _LEVEL = int(level_)
        _MAX_EVENTS = max(int(max_events), 256)
        _ENABLED = bool(enabled_)


def reset() -> None:
    """Drop every recorded event (test isolation; keeps configuration)."""
    with _LOCK:
        _RINGS.clear()
        _THREAD_NAMES.clear()
        _DROPPED.clear()


# -- consumers ----------------------------------------------------------------

def events(query_id: Optional[int] = None) -> List[tuple]:
    """Recorded events — one query's ring, or every ring interleaved in
    timestamp order."""
    with _LOCK:
        if query_id is not None:
            ring = _RINGS.get(query_id)
            return list(ring) if ring is not None else []
        out: List[tuple] = []
        for ring in _RINGS.values():
            out.extend(ring)
    out.sort(key=lambda e: e[3])
    return out


def query_ids() -> List[int]:
    with _LOCK:
        return list(_RINGS.keys())


def thread_names() -> Dict[int, str]:
    with _LOCK:
        return dict(_THREAD_NAMES)


def open_span_count() -> int:
    """Spans entered minus spans exited — 0 when every begin got its
    end (the well-formedness probe the trace tests assert)."""
    # itertools.count has no read API; peek by advancing paired clones is
    # racy — instead derive from the repr ("count(N)").
    opened = int(repr(_OPEN)[6:-1])
    closed = int(repr(_CLOSED)[6:-1])
    return opened - closed


def snapshot() -> dict:
    """Aggregated process-wide view: per-category span time and counts,
    instant counts by name, per-query event totals — the ``trace`` block
    bench.py publishes, and the at-a-glance answer to "where did the
    wall-clock go" without exporting a full timeline."""
    cats: Dict[str, Dict[str, float]] = {}
    instants: Dict[str, int] = {}
    queries: Dict[str, Dict[str, float]] = {}
    for e in events():
        ph, name, cat, ts, dur, tid, qid, args = e
        q = queries.setdefault(str(qid), {"events": 0, "spanMs": 0.0})
        q["events"] += 1
        if ph == "X":
            c = cats.setdefault(cat, {"spans": 0, "ms": 0.0})
            c["spans"] += 1
            c["ms"] += dur / 1e6
            q["spanMs"] += dur / 1e6
        else:
            instants[name] = instants.get(name, 0) + 1
    for c in cats.values():
        c["ms"] = round(c["ms"], 3)
    for q in queries.values():
        q["spanMs"] = round(q["spanMs"], 3)
    with _LOCK:
        dropped = sum(_DROPPED.values())
    return {
        "enabled": _ENABLED,
        "level": {v: k for k, v in _LEVEL_NAMES.items()}[_LEVEL],
        "maxEvents": _MAX_EVENTS,
        "categories": cats,
        "instants": instants,
        "queries": queries,
        "droppedEvents": dropped,
        "openSpans": open_span_count(),
    }


def category_breakdown() -> Dict[str, float]:
    """Span-category -> total ms, flat (the p50/p99 attribution story's
    denominator: queued / host-prefetch / device-compute / upload /
    shuffle / recovery ...)."""
    return {cat: agg["ms"]
            for cat, agg in snapshot()["categories"].items()}


def export_chrome(path: Optional[str] = None,
                  query_id: Optional[int] = None) -> dict:
    """Chrome trace-event JSON (loads in Perfetto / chrome://tracing):
    one process track per query, one thread track per worker thread.
    Writes ``path`` when given; returns the document either way."""
    from spark_rapids_tpu.monitoring.chrome import to_chrome
    doc = to_chrome(events(query_id), thread_names(), _PROCESS_TAG)
    if path:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc
