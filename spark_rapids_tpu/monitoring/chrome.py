"""Chrome trace-event rendering of the flight-recorder stream.

The output is the Trace Event Format's JSON object flavor
(``{"traceEvents": [...]}``) that chrome://tracing and Perfetto's legacy
importer load directly. Mapping:

- ``pid`` = query id, with a ``process_name`` metadata event naming the
  track ``query <N>`` — so concurrent queries render as separate
  process groups and "what did query 7 do to query 8" is one screen.
- ``tid`` = recording thread, named from the live thread names
  (``srt-prefetch-*``, ``srt-stage-*``, ``srt-watchdog-*``, the collect
  thread) — scheduler queueing, host prefetch, device dispatch, shuffle
  spool and recovery rework land on distinct tracks.
- spans are ``"X"`` complete events (ts/dur in microseconds, as the
  format requires), instants are ``"i"`` thread-scoped events.
"""

from __future__ import annotations

from typing import Dict, List


def to_chrome(events: List[tuple], thread_names: Dict[int, str],
              process_tag: str = "") -> dict:
    """Render recorder event tuples into one Chrome trace document.
    ``process_tag`` prefixes every process track name — cluster worker
    processes pass ``worker <wid>`` so their exports stay attributable
    when several per-process traces are viewed side by side."""
    prefix = f"{process_tag} " if process_tag else ""
    trace: List[dict] = []
    seen_pids = set()
    seen_tids = set()
    for e in events:
        ph, name, cat, ts, dur, tid, qid, args = e
        if qid not in seen_pids:
            seen_pids.add(qid)
            trace.append({"ph": "M", "name": "process_name", "pid": qid,
                          "args": {"name": f"{prefix}query {qid}"}})
            trace.append({"ph": "M", "name": "process_sort_index",
                          "pid": qid, "args": {"sort_index": qid}})
        if (qid, tid) not in seen_tids:
            seen_tids.add((qid, tid))
            trace.append({"ph": "M", "name": "thread_name", "pid": qid,
                          "tid": tid,
                          "args": {"name": thread_names.get(
                              tid, f"thread-{tid}")}})
        ev = {"ph": ph, "name": name, "cat": cat, "pid": qid, "tid": tid,
              "ts": ts / 1e3}
        if ph == "X":
            ev["dur"] = (dur or 0) / 1e3
        else:
            ev["s"] = "t"
        if args:
            ev["args"] = dict(args)
        trace.append(ev)
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


_WORKER_PID_STRIDE = 1_000_000


def to_chrome_cluster(driver_events: List[tuple],
                      driver_threads: Dict[int, str],
                      worker_groups: Dict[str, tuple],
                      process_tag: str = "") -> dict:
    """ONE merged Perfetto document for a distributed query: the
    driver's rings render as usual, then each worker's shipped ring
    (the CDONE piggyback) is appended under its own process tracks.
    Worker ``k``'s pids are offset by ``(k+1) * 1_000_000`` so a
    worker's ring-0 events never collide with the driver's query
    tracks, while its ``process_name`` metadata keeps the worker tag
    ("worker w0 query 3"). ``worker_groups`` maps wid ->
    ``(events, thread_names, tag)`` — the shape the coordinator
    stashes in ``ctx.cache["cluster_worker_events"]``."""
    doc = to_chrome(driver_events, driver_threads, process_tag)
    trace = doc["traceEvents"]
    for k, wid in enumerate(sorted(worker_groups)):
        events, threads, tag = worker_groups[wid]
        base = (k + 1) * _WORKER_PID_STRIDE
        for ev in to_chrome(events, threads, tag)["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = base + ev["pid"]
            if ev.get("name") == "process_sort_index":
                ev["args"] = {"sort_index": ev["pid"]}
            trace.append(ev)
    return doc
