"""Persistent per-query event log — the history-server analog.

The registry (telemetry.py) answers "what is the process doing NOW";
this module answers "what did query N do LAST TUESDAY". At query
teardown (PhysicalPlan.collect's finally, before the context closes)
one JSONL record per query is appended under
``spark.rapids.sql.eventLog.dir`` (``SRT_EVENT_LOG`` env override;
empty = off, the default):

- identity: wall-clock ts, query id, status, QoS class, tenant,
  duration;
- plan: structural fingerprint, provenance (plan-cache hit / fresh),
  bind slot values+dtypes;
- per-node observed rows/bytes/batches/wall in deterministic DFS
  preorder (the same node indexing the cluster runtime uses to ship
  worker stage stats back on CDONE — so a distributed query's record
  matches a single-process one);
- the flight recorder's span-category breakdown and instant events
  (fault injected, OOM rung, recompute, demotion, kill) for this
  query's ring, verbatim — recovery forensics survive the process;
- the final per-query metrics entries (operator + audit groups).

``scripts/history.py`` reconstructs ``explain_analyze``-style node
reports and a fleet summary from these records alone, after every
process that ran the queries has exited.

Stdlib-only, append-only, one file per process
(``events-<pid>.jsonl``) so concurrent drivers sharing a directory
never interleave partial lines.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

SCHEMA_VERSION = 1

_LOCK = threading.Lock()
_DIR = ""


# -- configuration ------------------------------------------------------------

def event_log_dir(conf=None) -> str:
    """Conf key wins; else the SRT_EVENT_LOG env (the CI matrix hook);
    else the registered default (empty = off)."""
    from spark_rapids_tpu import config as C
    if conf is not None and conf.raw.get(C.EVENT_LOG_DIR.key) is not None:
        return str(conf.get(C.EVENT_LOG_DIR)).strip()
    env = os.environ.get("SRT_EVENT_LOG")
    if env is not None:
        return env.strip()
    return str(C.EVENT_LOG_DIR.default or "").strip()


def maybe_configure(conf) -> None:
    """Adopt this query's event-log directory (process-global, last
    writer wins — the wire-codec regime)."""
    global _DIR
    d = event_log_dir(conf)
    if d != _DIR:
        _DIR = d


def set_dir(d: str) -> None:
    """Direct (test/bench) configuration, bypassing the conf plumbing."""
    global _DIR
    _DIR = str(d or "").strip()


def log_dir() -> str:
    return _DIR


# -- record construction ------------------------------------------------------

def plan_fingerprint(phys) -> str:
    """Stable structural fingerprint of the physical tree (matches
    across processes executing the same pickled plan)."""
    import hashlib
    try:
        shape = phys.root.pretty_tree()
    except Exception:
        shape = repr(type(phys.root))
    return hashlib.sha256(shape.encode()).hexdigest()[:16]


def node_stats(root, ctx) -> List[dict]:
    """Per-node observed metrics in deterministic DFS preorder — THE
    node indexing shared by the event log, the cluster CDONE stat
    shipping, and the post-hoc report renderer. ``idx`` is the preorder
    ordinal, so two processes walking the same plan agree on it."""
    out: List[dict] = []

    def walk(op, depth):
        idx = len(out)
        m = ctx.metrics.get(f"{op.name}@{id(op):x}") if ctx is not None \
            else None
        vals = dict(m.values) if m is not None else {}
        rows = vals.get("numOutputRows")
        nbytes = vals.get("numOutputBytes")
        wall_ns = vals.get("totalTime", 0.0) + vals.get("bufferTime", 0.0)
        out.append({
            "idx": idx, "depth": depth, "name": op.name,
            "rows": int(rows) if rows is not None else None,
            "bytes": int(nbytes) if nbytes is not None else None,
            "batches": int(vals.get("numOutputBatches", 0)),
            "wall_ms": round(wall_ns / 1e6, 3),
        })
        for c in op.children:
            walk(c, depth + 1)

    walk(root, 0)
    return out


def _json_safe(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


def build_record(phys, ctx, *, query_id: int, status: str,
                 qos_class: Optional[str], tenant: Optional[str],
                 duration_ms: float, error: Optional[str] = None) -> dict:
    """One query's event-log record (also the in-memory shape tests
    assert against before the JSONL round trip)."""
    import time
    from spark_rapids_tpu.monitoring import recorder

    binds = []
    if ctx is not None and "plan_binds" in ctx.cache:
        values = ctx.cache.get("plan_binds") or ()
        dtypes = ctx.cache.get("plan_bind_dtypes") or ()
        for i, v in enumerate(values):
            dt = dtypes[i] if i < len(dtypes) else None
            binds.append({"slot": i, "value": _json_safe(v),
                          "dtype": str(dt) if dt is not None else None})

    categories: Dict[str, float] = {}
    instants: List[list] = []
    if recorder.enabled():
        for e in recorder.events(query_id):
            ph, name, cat, ts, dur, tid, qid, args = e
            if ph == "X":
                categories[cat] = categories.get(cat, 0.0) + dur / 1e6
            else:
                instants.append([name, cat, ts, _json_safe(args)])
        categories = {c: round(ms, 3) for c, ms in categories.items()}

    metrics = {}
    if ctx is not None:
        for key, m in ctx.metrics.items():
            if m.values:
                metrics[key] = {k: float(v) for k, v in m.values.items()}

    return {
        "v": SCHEMA_VERSION,
        "ts": time.time(),
        "query_id": int(query_id),
        "status": status,
        "class": qos_class,
        "tenant": tenant,
        "duration_ms": round(float(duration_ms), 3),
        "plan_fingerprint": plan_fingerprint(phys),
        "provenance": getattr(phys, "provenance", None),
        "bind_slots": binds,
        "nodes": node_stats(phys.root, ctx),
        "categories": categories,
        "instants": instants,
        "metrics": metrics,
        "error": error,
    }


def log_query(phys, ctx, *, query_id: int, status: str,
              qos_class: Optional[str], tenant: Optional[str],
              duration_ms: float, error: Optional[str] = None) -> None:
    """Append one query record to the event log (no-op when the dir is
    unset; never fails a query)."""
    d = _DIR
    if not d:
        return
    try:
        rec = build_record(phys, ctx, query_id=query_id, status=status,
                           qos_class=qos_class, tenant=tenant,
                           duration_ms=duration_ms, error=error)
        line = json.dumps(rec, sort_keys=True)
        path = os.path.join(d, f"events-{os.getpid()}.jsonl")
        with _LOCK:
            os.makedirs(d, exist_ok=True)
            with open(path, "a") as f:
                f.write(line + "\n")
    except Exception:
        import logging
        logging.getLogger("spark_rapids_tpu").warning(
            "event-log write failed", exc_info=True)


def log_fleet(event: str, **fields) -> None:
    """Append one FLEET record (type='fleet') to the event log: the
    supervisor/autoscaler control plane's observable trail (ISSUE 20).
    Records land in ``fleet-<pid>.jsonl`` next to the per-query files,
    so the soak can replay worker count vs load off the same directory
    a history server already reads. Shape:

        {"v": 1, "ts": ..., "type": "fleet", "event": "scale-up",
         "workers": 3, "target": 4, ...}

    No-op when the event-log dir is unset; never fails the caller."""
    d = _DIR
    if not d:
        return
    try:
        import time
        rec = {"v": SCHEMA_VERSION, "ts": time.time(), "type": "fleet",
               "event": str(event)}
        rec.update({k: _json_safe(v) for k, v in fields.items()})
        line = json.dumps(rec, sort_keys=True)
        path = os.path.join(d, f"fleet-{os.getpid()}.jsonl")
        with _LOCK:
            os.makedirs(d, exist_ok=True)
            with open(path, "a") as f:
                f.write(line + "\n")
    except Exception:
        import logging
        logging.getLogger("spark_rapids_tpu").warning(
            "fleet event-log write failed", exc_info=True)


# -- readers (the history-server side) ----------------------------------------

def read_fleet_events(path: str) -> List[dict]:
    """The fleet-control subset of :func:`read_events` (type='fleet'),
    oldest first: scale decisions, restarts, quarantines, drains and
    periodic worker-count samples."""
    return [r for r in read_events(path) if r.get("type") == "fleet"]


def read_events(path: str) -> List[dict]:
    """Load records from one ``.jsonl`` file or every ``events-*.jsonl``
    under a directory, oldest first; torn trailing lines are skipped."""
    files: List[str] = []
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.endswith(".jsonl"):
                files.append(os.path.join(path, name))
    elif os.path.exists(path):
        files.append(path)
    out: List[dict] = []
    for fp in files:
        with open(fp) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out


def render_report(rec: dict) -> str:
    """``explain_analyze``-style node report reconstructed from one
    event-log record alone — no live context, no live process."""
    lines = [
        f"query {rec.get('query_id')} [{rec.get('status')}] "
        f"class={rec.get('class') or '-'} tenant={rec.get('tenant') or '-'} "
        f"wall={rec.get('duration_ms', 0.0):.1f}ms "
        f"plan={rec.get('plan_fingerprint')}"
    ]
    prov = rec.get("provenance")
    if prov:
        lines.append(f"provenance: {prov}")
    if rec.get("bind_slots"):
        body = ", ".join(f"${b['slot']}={b['value']!r}"
                         for b in rec["bind_slots"])
        lines.append(f"bind slots: {body}")
    for n in rec.get("nodes", []):
        rows = f"{n['rows']:,}" if n.get("rows") is not None else "?"
        nbytes = f"{n['bytes']:,}B" if n.get("bytes") is not None else "?"
        parts = [f"rows={rows}", f"bytes={nbytes}",
                 f"wall={n.get('wall_ms', 0.0):.1f}ms"]
        if n.get("batches"):
            parts.append(f"batches={n['batches']}")
        lines.append("  " * n.get("depth", 0) + f"{n['name']}  "
                     + " ".join(parts))
    cats = rec.get("categories") or {}
    if cats:
        body = ", ".join(f"{c}={ms:.1f}ms" for c, ms in sorted(cats.items()))
        lines.append(f"trace: {body}")
    for name, cat, ts, args in rec.get("instants") or []:
        suffix = f" {args}" if args else ""
        lines.append(f"instant [{cat}] {name}{suffix}")
    if rec.get("error"):
        lines.append(f"error: {rec['error']}")
    return "\n".join(lines)


def fleet_summary(records: List[dict]) -> dict:
    """Aggregate view across every record (the history server's front
    page): totals by status/class/tenant, latency percentiles, plan
    reuse."""
    by_status: Dict[str, int] = {}
    by_class: Dict[str, int] = {}
    by_tenant: Dict[str, int] = {}
    by_plan: Dict[str, int] = {}
    durs: List[float] = []
    cache_hits = 0
    for r in records:
        if r.get("type") == "fleet":
            continue            # control-plane records, not queries
        by_status[r.get("status", "?")] = \
            by_status.get(r.get("status", "?"), 0) + 1
        c = r.get("class") or "-"
        by_class[c] = by_class.get(c, 0) + 1
        t = r.get("tenant") or "-"
        by_tenant[t] = by_tenant.get(t, 0) + 1
        fp = r.get("plan_fingerprint") or "?"
        by_plan[fp] = by_plan.get(fp, 0) + 1
        durs.append(float(r.get("duration_ms", 0.0)))
        if "hit" in str(r.get("provenance") or ""):
            cache_hits += 1
    durs.sort()

    def pct(p: float) -> float:
        if not durs:
            return 0.0
        return durs[min(int(p * len(durs)), len(durs) - 1)]

    return {
        "queries": len(records),
        "byStatus": by_status,
        "byClass": by_class,
        "byTenant": by_tenant,
        "distinctPlans": len(by_plan),
        "planCacheHits": cache_hits,
        "p50Ms": round(pct(0.50), 3),
        "p99Ms": round(pct(0.99), 3),
    }
