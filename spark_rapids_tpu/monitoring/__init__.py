"""Query flight recorder + live telemetry plane (the observability
substrate).

- :mod:`recorder` — bounded per-query ring buffers of spans/instants,
  with a near-zero disabled path (``spark.rapids.sql.trace.*``).
- :mod:`chrome` — Chrome trace-event JSON (Perfetto / chrome://tracing).
- :mod:`analyze` — the ``explain_analyze`` renderer (observed metrics
  next to cost-model estimates).
- :mod:`syncs` — host-sync funnel attribution on the same span stream.
- :mod:`telemetry` — process-global typed metric registry (counters /
  gauges / sliding-window histograms, ``spark.rapids.sql.metrics.*``),
  with cluster fleet aggregation.
- :mod:`exporter` — OpenMetrics HTTP scrape surface on localhost.
- :mod:`history` — persistent per-query JSONL event log
  (``spark.rapids.sql.eventLog.dir``) + post-hoc report readers.

Import cost matters: this package (like faults.py) is imported from
deep dispatch code, so the recorder and telemetry stay stdlib-only and
everything engine-shaped is lazy.
"""

from spark_rapids_tpu.monitoring import history, telemetry  # noqa: F401
from spark_rapids_tpu.monitoring.recorder import (     # noqa: F401
    LEVEL_KERNEL, LEVEL_OPERATOR, LEVEL_QUERY, category_breakdown,
    configure, enabled, events, export_chrome, instant, level,
    maybe_configure, now_ns, open_span_count, process_tag, query_ids,
    record_span, reset, set_process_tag, snapshot, span, thread_names,
    trace_enabled)
