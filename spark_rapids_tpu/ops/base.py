"""Physical operator base (ref: GpuExec.scala:65).

Execution model: a plan is a tree of ``Exec`` nodes; each node, per
partition, produces an iterator of batches. Two engines exist, mirroring the
reference's CPU-Spark vs GPU split:

- device: iterators of ``DeviceBatch``; per-batch kernels are pure jnp
  functions (jittable). The Python generator layer is only orchestration —
  the same division the reference has between JVM iterators and cuDF kernels.
- host: iterators of ``HostBatch`` (numpy) — the CPU fallback engine and the
  comparison oracle.

Metrics mirror GpuMetricNames (GpuExec.scala:27-56): numOutputRows,
numOutputBatches, totalTime (ns).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from spark_rapids_tpu.columnar.dtypes import DataType
from spark_rapids_tpu.columnar.batch import DeviceBatch
from spark_rapids_tpu.columnar.host import (
    HostBatch, device_to_host, host_to_device)
from spark_rapids_tpu.config import TpuConf

Schema = Tuple[Tuple[str, DataType], ...]


class Metrics:
    """Per-operator metric registry (NvtxWithMetrics analog — ``timed``
    additionally opens a named ``jax.profiler.TraceAnnotation`` so a
    profile of a query shows per-operator ranges, NvtxWithMetrics.scala:
    21-44)."""

    def __init__(self, owner: str = ""):
        self.owner = owner
        self.values: Dict[str, float] = {}
        # add() is a read-modify-write reached from prefetch/stage
        # threads under the pipelined executor — lock it so two
        # concurrent collects can never lose counter increments.
        self._lock = threading.Lock()

    def add(self, name: str, amount: float):
        with self._lock:
            self.values[name] = self.values.get(name, 0) + amount

    def __repr__(self):  # pragma: no cover - cosmetic
        return f"Metrics({self.values})"


# -- audit metric groups ------------------------------------------------------
# THE registry of per-query audit entries (<Owner>@query) that the
# metrics verbosity filter (spark.rapids.sql.metrics.level) must never
# drop: they are recovery/scheduling audit trails, not operator
# telemetry. Every subsystem creates its entry through
# query_metrics_entry(), which registers the owner here — replacing the
# ad-hoc per-call-site exemptions DataFrame.metrics() used to hardcode.
_AUDIT_METRIC_GROUPS = {"Recovery", "Pipeline", "Scheduler", "Transport",
                        "Cost", "Cluster"}
_AUDIT_LOCK = threading.Lock()


def register_audit_metric_group(owner: str) -> None:
    """Mark ``owner`` as a level-filter-exempt audit group (idempotent).
    Third-party subsystems get the same never-filtered treatment as the
    built-in Recovery/Pipeline/Scheduler/Transport/Cost entries."""
    with _AUDIT_LOCK:
        _AUDIT_METRIC_GROUPS.add(owner)


def audit_metric_groups() -> frozenset:
    with _AUDIT_LOCK:
        return frozenset(_AUDIT_METRIC_GROUPS)


def query_metrics_entry(ctx: "ExecContext", owner: str) -> Metrics:
    """The per-query ``<owner>@query`` audit Metrics entry, created on
    first use and registered as level-filter exempt. All subsystems
    (scheduler, pipeline, transport, cost/replan, recovery) route
    through here so the exemption set has exactly one source."""
    register_audit_metric_group(owner)
    return ctx.metrics.setdefault(f"{owner}@query", Metrics(owner=owner))


def record_batch(m: Metrics, batch) -> None:
    """Record one output batch's observable size: always
    ``numOutputBatches``; ``numOutputRows``/``numOutputBytes`` when a
    HOST-KNOWN row count exists (``rows_hint`` on device batches, exact
    ``num_rows`` on host batches). Never forces a device sync — an
    unknown count stays unknown (explain_analyze renders ``?``) rather
    than costing a ~70ms round trip per batch."""
    m.add("numOutputBatches", 1)
    rows = getattr(batch, "rows_hint", None)
    if rows is None:
        nr = getattr(batch, "num_rows", None)
        if type(nr) is int:
            rows = nr
    if rows is None:
        return
    m.add("numOutputRows", int(rows))
    try:
        width = 0
        for c in batch.columns:
            if c.dtype.is_string:
                width += int(c.data.shape[1]) + 5
            else:
                width += int(c.dtype.np_dtype.itemsize) + 1
        if width:
            m.add("numOutputBytes", int(rows) * width)
    except Exception:
        pass        # exotic column layout: rows recorded, bytes skipped


@dataclasses.dataclass
class ExecContext:
    """Per-query execution context: conf + metrics sink + materialization
    cache (shuffle buckets, broadcast batches, built join sides — the role
    the reference's RapidsBufferCatalog/device store plays for shuffle
    data, SURVEY.md §2.6)."""

    conf: TpuConf = dataclasses.field(default_factory=TpuConf)
    metrics: Dict[str, Metrics] = dataclasses.field(default_factory=dict)
    cache: Dict[str, object] = dataclasses.field(default_factory=dict)
    # The admitting QueryManager ticket (parallel/scheduler.py): carries
    # the query id (catalog owner tag), the fair-share memory fraction,
    # and the cancellation token. None = unmanaged context (unit tests,
    # host oracle runs) — full budget, no owner, today's behavior.
    query: Optional[object] = None
    # Catalog leak report captured at close() AFTER owned handles were
    # released: [] proves query teardown freed everything it owned.
    last_leak_report: Optional[list] = None
    _catalog: Optional[object] = None
    _lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False)

    def metrics_for(self, op: "Exec") -> Metrics:
        # Keyed/owned by op.name (not the bare class name) so fused
        # stages report as FusedStageExec[Project->Filter->...] and the
        # per-node metrics owner stays readable after fusion. Locked:
        # concurrent stage/prefetch threads registering the same op must
        # share ONE Metrics object (a lost entry loses its counts).
        key = f"{op.name}@{id(op):x}"
        m = self.metrics.get(key)
        if m is None:
            with self._lock:
                m = self.metrics.get(key)
                if m is None:
                    m = self.metrics[key] = Metrics(owner=op.name)
        return m

    @property
    def catalog(self):
        """Lazily-built spill catalog: every held batch (shuffle buckets,
        broadcast tables, buffered build sides) registers here so HBM
        pressure spills device->host->disk instead of OOMing
        (RapidsBufferCatalog.init wiring, RapidsBufferCatalog.scala:128).
        Built under the context lock: concurrent stage threads must
        never race two catalogs into existence (one would leak)."""
        if self._catalog is None:
            with self._lock:
                if self._catalog is not None:
                    return self._catalog
                from spark_rapids_tpu import config as C
                from spark_rapids_tpu.memory.stores import BufferCatalog
                budget = int(self.conf.get(C.DEVICE_BUDGET_BYTES))
                if budget <= 0:
                    visible = _visible_device_bytes()
                    budget = int(visible * float(
                        self.conf.get(C.HBM_POOL_FRACTION)))
                    # Ceiling + runtime reserve (maxAllocFraction /
                    # reserve, RapidsConf's RMM pool bounds).
                    ceiling = int(visible * float(
                        self.conf.get(C.MAX_ALLOC_FRACTION))) \
                        - int(self.conf.get(C.RESERVE_BYTES))
                    budget = max(min(budget, ceiling), 1 << 20)
                owner = None
                if self.query is not None:
                    # Managed query: fair-share budget + owner tagging
                    # (scheduler.queryMemoryFraction; GpuSemaphore +
                    # owner-tagged RapidsBufferCatalog analog).
                    from spark_rapids_tpu.parallel import scheduler as SC
                    frac = SC.query_memory_fraction(
                        self.conf, SC.get_query_manager(self.conf))
                    budget = max(int(budget * frac), 1 << 20)
                    owner = self.query.query_id
                self._catalog = BufferCatalog(
                    device_budget_bytes=budget,
                    host_budget_bytes=int(
                        self.conf.get(C.HOST_SPILL_STORAGE_SIZE)),
                    spill_dir=str(self.conf.get(C.SPILL_DIR)),
                    compression_codec=str(
                        self.conf.get(C.SHUFFLE_COMPRESSION_CODEC)),
                    debug=bool(self.conf.get(C.MEMORY_DEBUG)),
                    owner=owner)
        return self._catalog

    def release_owned(self):
        """Close every durable handle this context still holds (shuffle
        buckets, broadcast singles, mesh shards — SpillableBatch handles
        parked in ``cache``): query teardown must free everything the
        query owned whether it succeeded, failed, or was cancelled."""
        from spark_rapids_tpu.memory.stores import SpillableBatch
        from spark_rapids_tpu.parallel.transport.base import \
            ShuffleSession

        def close_in(obj, depth: int = 0):
            if isinstance(obj, SpillableBatch):
                obj.close()
            elif isinstance(obj, ShuffleSession):
                # Transport sessions (parallel/transport/) own their
                # shards — catalog handles or spool files; teardown
                # releases both.
                obj.close()
            elif depth < 3 and isinstance(obj, (list, tuple)):
                for x in obj:
                    close_in(x, depth + 1)
            elif depth < 3 and isinstance(obj, dict):
                for x in obj.values():
                    close_in(x, depth + 1)

        for v in list(self.cache.values()):
            close_in(v)

    def close(self):
        if self._catalog is not None:
            self.release_owned()
            # The leak report AFTER releasing owned handles: non-empty
            # means a buffer escaped its owner's teardown — the
            # scheduler's isolation tests assert this is [].
            self.last_leak_report = self._catalog.leak_report()
            self._catalog.close()
            self._catalog = None


def _visible_device_bytes() -> int:
    """Best-effort HBM size of device 0 (fallback 8 GiB)."""
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
        if stats:
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit")
            if limit:
                return int(limit)
    except Exception:
        pass
    return 8 << 30


class WatchdogTimeoutError(RuntimeError):
    """Every watchdog attempt at a partition exceeded its deadline. The
    message carries the DEADLINE_EXCEEDED marker so the planner's
    transient retry is the next demotion rung (partition retry -> stage
    recompute -> whole-query retry)."""

    def __init__(self, op: str, label: str, timeout_ms: int,
                 attempts: int):
        super().__init__(
            f"DEADLINE_EXCEEDED: watchdog killed {op} {label} on all "
            f"{attempts} attempt(s) of {timeout_ms}ms "
            "(spark.rapids.sql.watchdog.*)")
        self.label = label


@dataclasses.dataclass
class _WatchdogParams:
    timeout_ms: int
    max_attempts: int


def _watchdog_params(conf: TpuConf) -> Optional[_WatchdogParams]:
    from spark_rapids_tpu import config as C
    if not bool(conf.get(C.WATCHDOG_ENABLED)):
        return None
    return _WatchdogParams(
        timeout_ms=max(int(conf.get(C.WATCHDOG_TASK_TIMEOUT_MS)), 1),
        max_attempts=max(int(conf.get(C.WATCHDOG_MAX_ATTEMPTS)), 1))


class Exec:
    """A physical operator. Subclasses implement the per-partition device
    and host paths. ``schema`` is the output schema."""

    def __init__(self, *children: "Exec"):
        self.children: Tuple["Exec", ...] = tuple(children)

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    # Number of output partitions (defaults to the first child's).
    def num_partitions(self, ctx: ExecContext) -> int:
        return self.children[0].num_partitions(ctx)

    # -- device engine -------------------------------------------------------
    def execute_device(self, ctx: ExecContext,
                       partition: int) -> Iterator[DeviceBatch]:
        raise NotImplementedError

    # -- host engine ---------------------------------------------------------
    def execute_host(self, ctx: ExecContext,
                     partition: int) -> Iterator[HostBatch]:
        raise NotImplementedError

    # -- pipelined execution (parallel/pipeline.py) --------------------------
    def host_prefetchable(self) -> bool:
        """True when this subtree exposes a separable host half worth
        prefetching (a scan below, without crossing a stage boundary —
        a boundary exchange pipelines its own materialization loop)."""
        from spark_rapids_tpu.parallel.stages import is_stage_boundary
        return any(c.host_prefetchable() for c in self.children
                   if not is_stage_boundary(c))

    def prefetch_host(self, ctx: ExecContext, partition: int) -> None:
        """Run the host half of ``partition`` ahead of device dispatch
        (decode, stats pruning, wire encode — everything before
        ``device_put``). Called on pipeline prefetch threads; the
        results land in ``ctx.cache`` keyed by (node, partition) and the
        ordered consumer's ``execute_device`` pops them, so a mistimed
        or never-consumed prefetch costs only wasted CPU, never wrong
        rows. Recursion stops at stage boundaries: partition numbering
        changes there, and the boundary pipelines its own loop."""
        from spark_rapids_tpu.parallel.stages import is_stage_boundary
        for c in self.children:
            if not is_stage_boundary(c):
                c.prefetch_host(ctx, partition)

    def _grace_retry(self, ctx: ExecContext, partition: int):
        """Operator-specific on-device OOM rung ABOVE host fallback:
        return a replacement device iterator (e.g. the hash join's
        grace-partitioned path, ops/join.py) or None. Only consulted
        when the spill/shrink ladder is exhausted before the first
        output batch."""
        return None

    # -- recovery ------------------------------------------------------------
    def execute_device_recovering(self, ctx: ExecContext,
                                  partition: int) -> Iterator[DeviceBatch]:
        """Device stream with the FINAL OOM escalation rungs: when the
        device path dies on an exhausted spill/shrink ladder
        (memory/oom.py OomRetryExhausted) BEFORE producing its first
        batch, first offer the operator its on-device degraded mode
        (``_grace_retry`` — the hash join's spill-partitioned grace
        path), and only if that is unavailable or also OOMs re-run this
        operator subtree on the host engine and upload the results —
        the reference's operator-by-operator CPU fallback, applied at
        the dispatch funnels that pull child streams (collect,
        exchanges, broadcasts). After the first batch is out, consumers
        have already observed device output, so a mid-stream failure
        propagates instead of duplicating rows."""
        from spark_rapids_tpu import config as C, faults
        from spark_rapids_tpu.memory.oom import OomRetryExhausted
        it = self.execute_device(ctx, partition)
        try:
            first = next(it)
        except StopIteration:
            return
        except OomRetryExhausted as e:
            from spark_rapids_tpu import monitoring
            grace_it = self._grace_retry(ctx, partition)
            if grace_it is not None:
                import logging
                logging.getLogger("spark_rapids_tpu").warning(
                    "OOM ladder exhausted in %s partition %d; retrying "
                    "on-device via the grace-partitioned path: %s",
                    self.name, partition, e)
                monitoring.instant(
                    "grace-join-engaged", "recovery",
                    args={"op": self.name, "partition": partition})
                try:
                    first = next(grace_it)
                except StopIteration:
                    return
                except OomRetryExhausted as e2:
                    e = e2      # grace also OOMed: host fallback next
                else:
                    yield first
                    yield from grace_it
                    return
            if not bool(ctx.conf.get(C.OOM_HOST_FALLBACK)):
                raise e
            try:
                host_iter = self.execute_host(ctx, partition)
            except (NotImplementedError, AssertionError):
                raise e     # no host path (bridge nodes): nothing to do
            import logging
            logging.getLogger("spark_rapids_tpu").warning(
                "OOM ladder exhausted in %s partition %d; degrading the "
                "operator subtree to the host engine: %s",
                self.name, partition, e)
            faults.record("hostFallbacks")
            ctx.metrics_for(self).add("hostFallbacks", 1)
            monitoring.instant(
                "host-fallback", "recovery",
                args={"op": self.name, "partition": partition})
            for hb in host_iter:
                yield host_to_device(hb)
            return
        yield first
        yield from it

    def _watchdog_run(self, ctx: ExecContext, wd: "_WatchdogParams",
                      label: str, fn):
        """Execution watchdog (spark.rapids.sql.watchdog.*): run one unit
        of device work (a partition's stream, or the partition-count /
        AQE materialization step) under a deadline with bounded
        re-dispatch — the speculative-re-execution half of the fault
        story (Dean & Ghemawat, MapReduce, OSDI 2004), scoped to a
        partition instead of the query.

        Deterministic first-winner semantics: attempts run strictly
        serially, the first attempt to COMPLETE within its deadline wins,
        and a killed attempt's partial output is discarded whole — the
        computation is pure batch->batch, so whichever attempt wins, the
        result is bit-identical. Kills are cooperative: the attempt
        thread gets a cancel event that injected stalls (and any future
        cancellation-aware dispatch) unwind on; a truly wedged device
        call is abandoned to its daemon thread."""
        import threading

        from spark_rapids_tpu import faults
        from spark_rapids_tpu.memory.oom import (get_active_catalog,
                                                 set_active_catalog)
        timeout_s = wd.timeout_ms / 1000.0
        catalog = get_active_catalog()
        sink = faults.get_recovery_sink()
        token = faults.get_query_token()
        for attempt in range(wd.max_attempts):
            cancel = threading.Event()
            box: Dict[str, object] = {}

            def work():
                # Thread-locals don't inherit: the worker needs the
                # query's spill catalog (OOM ladder), recovery sink,
                # query token (cancellation/owner/fault tag), and its
                # attempt's cancel event.
                set_active_catalog(catalog)
                faults.set_recovery_sink(sink)
                faults.set_query_token(token)
                faults.set_cancel_event(cancel)
                try:
                    box["out"] = fn()
                except BaseException as e:
                    box["err"] = e

            t = threading.Thread(
                target=work, daemon=True,
                name=f"srt-watchdog-{label}-a{attempt}")
            t.start()
            t.join(timeout_s)
            if not t.is_alive():
                err = box.get("err")
                if err is not None:
                    raise err
                return box["out"]
            cancel.set()
            faults.record("watchdogKills")
            ctx.metrics_for(self).add("watchdogKills", 1)
            from spark_rapids_tpu import monitoring
            monitoring.instant(
                "watchdog-kill", "recovery",
                args={"op": self.name, "label": label,
                      "attempt": attempt + 1})
            import logging
            logging.getLogger("spark_rapids_tpu").warning(
                "watchdog: %s %s exceeded %dms (attempt %d/%d)"
                "; killing and %s", self.name, label, wd.timeout_ms,
                attempt + 1, wd.max_attempts,
                "re-dispatching" if attempt + 1 < wd.max_attempts
                else "giving up")
            # Grace join: a cooperatively-cancelled attempt (injected
            # stall) unwinds immediately, so the re-dispatch rarely
            # overlaps the old thread.
            t.join(0.2)
            if attempt + 1 < wd.max_attempts:
                faults.record("partitionRetries")
        raise WatchdogTimeoutError(self.name, label, wd.timeout_ms,
                                   wd.max_attempts)

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def _recovery_metrics(ctx: ExecContext) -> Metrics:
        """The per-query Recovery metrics entry (retriesAttempted /
        spillEscalations / hostFallbacks / faultsInjected...), surfaced
        by DataFrame.metrics() next to the per-operator entries."""
        return query_metrics_entry(ctx, "Recovery")

    def collect(self, ctx: Optional[ExecContext] = None,
                device: bool = True) -> List[tuple]:
        """Run all partitions and collect rows (driver collect analog).

        The device path dispatches EVERY partition before downloading
        anything, then fetches all result batches in one two-phase
        ``download_batches`` call — on a tunneled device that is two
        round trips for the whole query instead of O(batches)."""
        ctx = ctx or ExecContext()
        # Engine marker: runtime-adaptive pieces (AQE partition coalescing)
        # must only trigger device materialization on the device engine.
        ctx.cache.setdefault("engine", "device" if device else "host")
        rows: List[tuple] = []
        names = tuple(n for n, _ in self.schema)
        if device:
            from spark_rapids_tpu import config as C, monitoring
            from spark_rapids_tpu.columnar import wire
            from spark_rapids_tpu.columnar.host import download_batches
            from spark_rapids_tpu.memory import stores
            from spark_rapids_tpu.memory.stores import get_tpu_semaphore
            # Adopt this query's wire codec selection (process-global,
            # spark.rapids.sql.wire.codec) before any upload happens —
            # its flight-recorder configuration, before any span
            # site runs (spark.rapids.sql.trace.*) — and its native
            # Pallas kernel gates, before any kernel traces
            # (spark.rapids.sql.native.*).
            from spark_rapids_tpu.monitoring import telemetry
            from spark_rapids_tpu.ops import native
            wire.maybe_configure(ctx.conf)
            monitoring.maybe_configure(ctx.conf)
            telemetry.maybe_configure(ctx.conf)
            native.maybe_configure(ctx.conf)
            stores.preemption_configure(ctx.conf)
            # Task admission (GpuSemaphore.scala:74-87): at most
            # concurrentTpuTasks collects issue device work at once, so
            # concurrent queries can't oversubscribe HBM.
            sem = get_tpu_semaphore(
                max(int(ctx.conf.get(C.CONCURRENT_TPU_TASKS)), 1))
            # The query-level span covers EVERYTHING the device path
            # pays for: semaphore wait, adaptive re-planning, stage
            # prematerialization, the partition loop, and the download.
            collect_span = monitoring.span(
                "collect", "query", level=monitoring.LEVEL_QUERY,
                args={"op": self.name})
            t0_collect = time.perf_counter()
            collect_span.__enter__()
            try:
                with sem:
                    # OOM->spill->retry needs the catalog reachable from
                    # dispatch sites deep in the kernel layer (memory/oom.py);
                    # the recovery sink mirrors ladder/fallback/injection
                    # counters into this query's Metrics.
                    from spark_rapids_tpu import faults
                    from spark_rapids_tpu.memory.oom import set_active_catalog
                    set_active_catalog(ctx.catalog)
                    faults.set_recovery_sink(self._recovery_metrics(ctx))
                    try:
                        from spark_rapids_tpu.parallel import pipeline as PL
                        from spark_rapids_tpu.parallel import replan as RP
                        # Runtime adaptive re-planning BEFORE stage
                        # prematerialization: build-side exchanges
                        # materialize now, observed sizes demote shuffled
                        # joins to broadcast, and the skipped probe
                        # exchanges are flagged so the stage pass does not
                        # shuffle them anyway (parallel/replan.py).
                        RP.plan_adaptive(ctx, self)
                        # Independent stages (join build/probe sides...)
                        # materialize their exchange outputs concurrently
                        # before the ordered partition loop; a no-op when
                        # the pipeline is off or the plan is single-stage.
                        PL.prematerialize_stages(ctx, self)
                        wd = _watchdog_params(ctx.conf)
                        batches: List[DeviceBatch] = []
                        if wd is None:
                            nparts = self.num_partitions(ctx)
                            pipe = PL.open_pipeline(ctx, self, nparts)
                            try:
                                for p in range(nparts):
                                    # Per-partition cancellation +
                                    # preemption checkpoint (the deep
                                    # funnels check cancellation too,
                                    # via fault_point; preemption only
                                    # ever fires at this boundary).
                                    faults.check_cancelled()
                                    faults.check_preempted()
                                    # consume() waits for p's host half
                                    # then returns the device stream
                                    # verbatim, so the serial path keeps
                                    # streaming exactly as before.
                                    with monitoring.span(
                                            "partition", "device-compute",
                                            args={"partition": p,
                                                  "op": self.name}):
                                        batches.extend(pipe.consume(
                                            p, lambda p=p:
                                            self.execute_device_recovering(
                                                ctx, p)))
                            finally:
                                pipe.close()
                        else:
                            # The partition count itself can trigger
                            # device work (AQE coalescing materializes
                            # the exchange to learn exact bucket sizes),
                            # so it runs under the watchdog too; the
                            # pipeline's per-partition wait then happens
                            # INSIDE the watchdog deadline (a stalled
                            # prefetch is killed with the attempt).
                            nparts = self._watchdog_run(
                                ctx, wd, "partition-count",
                                lambda: self.num_partitions(ctx))
                            pipe = PL.open_pipeline(ctx, self, nparts)
                            try:
                                for p in range(nparts):
                                    # Same partition-boundary preemption
                                    # checkpoint as the serial loop (the
                                    # watchdog handles cancellation).
                                    faults.check_preempted()
                                    with monitoring.span(
                                            "partition", "device-compute",
                                            args={"partition": p,
                                                  "op": self.name}):
                                        batches.extend(self._watchdog_run(
                                            ctx, wd, f"partition {p}",
                                            lambda p=p: pipe.consume(
                                                p, lambda: list(
                                                    self
                                                    .execute_device_recovering(
                                                        ctx, p)))))
                            finally:
                                pipe.close()
                        with monitoring.span(
                                "download", "device-compute",
                                args={"batches": len(batches)}):
                            host_batches = download_batches(batches, names)
                    finally:
                        set_active_catalog(None)
                        faults.set_recovery_sink(None)
                # Row materialization is pure host CPU — outside the permit,
                # like the reference releasing GpuSemaphore once the task
                # leaves the device.
                for hb in host_batches:
                    rows.extend(hb.to_pylist())
            finally:
                collect_span.__exit__(None, None, None)
                # Live telemetry (the hot-collect instrumentation the
                # microbench overhead probe models): one counter inc +
                # one histogram observe per collect, plus the spill
                # ladder's tier occupancy and device high watermark —
                # read off the catalog only if this query built one.
                telemetry.inc("srt_collects")
                telemetry.observe(
                    "srt_collect_ms",
                    (time.perf_counter() - t0_collect) * 1e3)
                cat = ctx._catalog
                if cat is not None:
                    # Memory-pressure plane: one scalar score per
                    # collect teardown feeds the admission brownout
                    # state machine and (via the worker heartbeat) the
                    # coordinator's shed-aware placement.
                    score = stores.pressure_score(cat)
                    if telemetry.enabled():
                        telemetry.set_gauge("srt_pressure_score", score)
                    from spark_rapids_tpu.parallel import scheduler as SC
                    SC.note_pressure(score, ctx.conf)
                if cat is not None and telemetry.enabled():
                    telemetry.set_gauge("srt_memory_bytes",
                                        cat.device_bytes, tier="device")
                    telemetry.set_gauge("srt_memory_bytes",
                                        cat.host_bytes, tier="host")
                    telemetry.set_gauge("srt_memory_bytes",
                                        cat.disk_bytes, tier="disk")
                    telemetry.set_gauge("srt_device_budget_bytes",
                                        cat.device_budget)
                    telemetry.max_gauge("srt_device_watermark_bytes",
                                        cat.device_bytes)
            # Cost-model self-calibration: feed this query's observed
            # sync-span mean and upload throughput (plus the Cost@query
            # estimateErrorPct as a trust dampener) back into the
            # placement model's effective constants (plan/cost.py). A
            # no-op when tracing is off or calibration is disabled.
            try:
                from spark_rapids_tpu.plan import cost as COST
                COST.observe_query(ctx)
            except Exception:   # calibration must never fail a query
                pass
        else:
            from spark_rapids_tpu import monitoring
            from spark_rapids_tpu.monitoring import telemetry
            monitoring.maybe_configure(ctx.conf)
            telemetry.maybe_configure(ctx.conf)
            with monitoring.span("collect", "query",
                                 level=monitoring.LEVEL_QUERY,
                                 args={"op": self.name,
                                       "engine": "host"}):
                for p in range(self.num_partitions(ctx)):
                    with monitoring.span("partition", "host-compute",
                                         args={"partition": p,
                                               "op": self.name}):
                        for b in self.execute_host(ctx, p):
                            rows.extend(b.to_pylist())
        return rows

    def pretty_tree(self, indent: int = 0) -> str:
        out = "  " * indent + self.name + "\n"
        for c in self.children:
            out += c.pretty_tree(indent + 1)
        return out


class LeafExec(Exec):
    """Base for source nodes (scans, in-memory sources)."""

    def num_partitions(self, ctx: ExecContext) -> int:
        raise NotImplementedError


class InMemorySourceExec(LeafExec):
    """In-memory host-batch source, pre-partitioned (test/bench currency;
    the DataFrame frontend's createDataFrame lands here)."""

    def __init__(self, schema: Schema,
                 partitions: Sequence[Sequence[HostBatch]]):
        super().__init__()
        self._schema = tuple(schema)
        self._partitions = [list(p) for p in partitions]

    @property
    def schema(self) -> Schema:
        return self._schema

    def num_partitions(self, ctx: ExecContext) -> int:
        return len(self._partitions)

    def execute_device(self, ctx, partition):
        for hb in self._partitions[partition]:
            yield host_to_device(hb)

    def execute_host(self, ctx, partition):
        yield from iter(self._partitions[partition])


class DeviceToHostExec(Exec):
    """Explicit device->host transition (GpuColumnarToRowExec analog): runs
    the child on the device engine, downloads each batch."""

    def __init__(self, child: Exec):
        super().__init__(child)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute_host(self, ctx, partition):
        names = tuple(n for n, _ in self.schema)
        for b in self.children[0].execute_device(ctx, partition):
            yield device_to_host(b, names)

    def execute_device(self, ctx, partition):  # pragma: no cover
        raise AssertionError("DeviceToHostExec is a host-side node")


class HostToDeviceExec(Exec):
    """Explicit host->device transition (GpuRowToColumnarExec analog)."""

    def __init__(self, child: Exec):
        super().__init__(child)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute_device(self, ctx, partition):
        for hb in self.children[0].execute_host(ctx, partition):
            yield host_to_device(hb)

    def execute_host(self, ctx, partition):  # pragma: no cover
        raise AssertionError("HostToDeviceExec is a device-side node")


# Flight-recorder category per timed() metric: operator dispatch is
# device-compute; scan decode/buffer work is host-side; shuffle and
# sizes-pull syncs label themselves.
_TIMED_CATS = {"bufferTime": "host-prefetch", "shuffleTime": "shuffle",
               "sizesPullTime": "sync"}


def timed(metrics: Metrics, name: str = "totalTime"):
    """Context manager adding elapsed ns to a metric AND opening a
    ``jax.profiler.TraceAnnotation`` named ``<Op>:<metric>`` — a captured
    profile (jax.profiler.trace) shows every operator's dispatch ranges
    (NvtxWithMetrics.scala:21-44 analog). The same interval records as a
    flight-recorder span (monitoring/recorder.py), so every operator
    that meters itself lands on the trace timeline for free."""
    import jax.profiler as _prof
    from spark_rapids_tpu.monitoring import recorder as _rec

    class _Timer:
        def __enter__(self):
            self._ann = _prof.TraceAnnotation(
                f"{metrics.owner or 'op'}:{name}")
            self._ann.__enter__()
            self._span = _rec.span(
                metrics.owner or "op", _TIMED_CATS.get(
                    name, "device-compute"), _rec.LEVEL_OPERATOR,
                args=None if name == "totalTime" else {"metric": name})
            self._span.__enter__()
            self.t0 = time.perf_counter_ns()

        def __exit__(self, *exc):
            metrics.add(name, time.perf_counter_ns() - self.t0)
            self._span.__exit__(None, None, None)
            self._ann.__exit__(None, None, None)
            return False
    return _Timer()
