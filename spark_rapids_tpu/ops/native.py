"""Native Pallas kernel layer for the hot device loops.

The reference accelerator routes *every* kernel through hand-tuned native
libcudf code reached over JNI (PAPER.md L0); until this module, our device
compute was pure jax.numpy composition lowered by XLA. The flight recorder
(PR 9) put numbers on where device time goes, and the top sinks are exactly
the loops XLA lowers worst on TPU:

- ``_radix_perm``'s per-digit LSD passes (ops/kernels.py) — every stable
  ``jnp.argsort`` is an O(n log^2 n) bitonic sort network on TPU. The
  native kernel is a *linear* stable counting-sort rank per 8-bit digit:
  per-block histograms, scanned digit/block bases, and a stable
  within-block prefix, all dense VPU work.
- the hash-join probe (ops/join.py ``probe_ranges``) — two separate
  ``jnp.searchsorted`` dispatches over the sorted build fingerprints
  become ONE branchless lower+upper binary search over two u32 planes.
- wire v2's RLE decode (columnar/wire.py) — ``searchsorted`` over the run
  ends plus a gather becomes one interval-membership select over the run
  table (bit patterns only, so -0.0/NaN payloads survive exactly).
- the sorted-segment groupby reduction (ops/kernels.py
  ``segment_reduce``) — scatter-based ``jax.ops.segment_*`` becomes a
  single-sweep segmented scan: Hillis-Steele within a block, a
  sequential-grid carry across blocks (TPU grid steps run in order on a
  core, which Pallas guarantees and the interpreter emulates).

Contracts (mirroring every other gate in this engine):

- **Bit identity.** Each kernel's output is bit-identical to its
  jax.numpy twin; tests/test_native.py pins the whole dtype ladder
  including -0.0/NaN float edge cases. Where bit identity cannot be
  guaranteed (float SUM reduction order, the unstable-first sort
  relaxation), the native path simply does not engage.
- **Kill switches.** ``spark.rapids.sql.native.enabled`` is the master
  gate; per-kernel ``native.<kernel>.enabled`` keys disable one kernel.
  ``SRT_NATIVE=0`` disables for a whole process. Off restores today's
  code paths byte-for-byte.
- **Backend.** Mosaic only compiles on TPU. On CPU the layer no-ops to
  the fallback; ``SRT_NATIVE_INTERPRET=1`` (or :func:`forced`) runs the
  kernels through the Pallas interpreter so the CPU CI can prove parity.
- **Cache coherence.** :func:`fingerprint` folds the enabled-kernel set
  into every kernel-cache key (ops/kernel_cache.py ``lookup``) and the
  wire decode-jit cache, so toggling a gate never serves a stale
  compiled program.

Config is adopted process-globally per collect (``maybe_configure``),
like the wire codec — these kernels run deep inside traced code with no
conf in scope.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

KERNELS = ("radixSort", "joinProbe", "rleDecode", "segmentReduce")

_LOCK = threading.Lock()
# Conf-adopted overrides: None = fall through to env/default.
_OVERRIDE: Dict[str, Optional[bool]] = {"master": None}
_OVERRIDE.update({k: None for k in KERNELS})
_MAX_RUNS_OVERRIDE: Optional[int] = None
_FORCED: Optional[Dict[str, bool]] = None     # tests: forced() context
# Trace-time dispatch counters (a kernel inside a jitted program traces
# once and executes many times; these count traces, which is what the
# bench `native` block and the gating tests need).
_COUNTERS: Dict[str, float] = {}


def _env_true(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip() not in ("0", "false", "no", "")


def interpret_forced() -> bool:
    """Pallas interpreter forced (the CPU parity-suite hook)."""
    return _env_true("SRT_NATIVE_INTERPRET", False)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def available() -> bool:
    """Native kernels can run at all: a real TPU backend compiles them
    through Mosaic; anything else needs the interpreter forced."""
    if jax.default_backend() == "tpu":
        return True
    return interpret_forced()


def maybe_configure(conf) -> None:
    """Adopt explicitly-set ``spark.rapids.sql.native.*`` keys for the
    process (unset keys clear back to env/default), mirroring the wire
    codec's process-global adoption."""
    global _MAX_RUNS_OVERRIDE
    from spark_rapids_tpu import config as C
    entries = {"master": C.NATIVE_ENABLED, "radixSort": C.NATIVE_RADIX_SORT,
               "joinProbe": C.NATIVE_JOIN_PROBE,
               "rleDecode": C.NATIVE_RLE_DECODE,
               "segmentReduce": C.NATIVE_SEGMENT_REDUCE}
    with _LOCK:
        for name, entry in entries.items():
            raw = conf.raw.get(entry.key)
            _OVERRIDE[name] = None if raw is None else bool(entry.get(conf))
        raw = conf.raw.get(C.NATIVE_RLE_MAX_RUNS.key)
        _MAX_RUNS_OVERRIDE = None if raw is None \
            else int(conf.get(C.NATIVE_RLE_MAX_RUNS))


def master_enabled() -> bool:
    if _FORCED is not None:
        return bool(_FORCED.get("master", True))
    with _LOCK:
        ov = _OVERRIDE["master"]
    if ov is not None:
        return ov
    return _env_true("SRT_NATIVE", True)


def kernel_enabled(name: str) -> bool:
    """Is one native kernel live right now (master gate + per-kernel
    gate + backend availability)?"""
    assert name in KERNELS, name
    if _FORCED is not None:
        return bool(_FORCED.get("master", True)) and \
            bool(_FORCED.get(name, True)) and available()
    if not master_enabled() or not available():
        return False
    with _LOCK:
        ov = _OVERRIDE[name]
    if ov is not None:
        return ov
    return _env_true(f"SRT_NATIVE_{name.upper()}", True)


def rle_max_runs() -> int:
    with _LOCK:
        if _MAX_RUNS_OVERRIDE is not None:
            return _MAX_RUNS_OVERRIDE
    from spark_rapids_tpu import config as C
    return int(C.NATIVE_RLE_MAX_RUNS.default)


def fingerprint() -> Tuple:
    """Folded into every kernel-cache key: the set of live native
    kernels (+ interpret mode, which changes the lowering). Toggling a
    gate therefore never serves a compiled program traced under the
    other setting."""
    live = tuple(k for k in KERNELS if kernel_enabled(k))
    if not live:
        return ()
    return ("native", live, "interp" if _interpret() else "mosaic")


class forced:
    """Test hook: force the native gate state (and the interpreter on
    non-TPU backends) for a ``with`` scope.

    ``forced(radixSort=False)`` keeps the master gate on with one kernel
    off; ``forced(master=False)`` disables everything."""

    def __init__(self, **kw: bool):
        self._kw = dict(kw)
        self._prev_forced = None
        self._prev_env = None

    def __enter__(self):
        global _FORCED
        self._prev_forced = _FORCED
        _FORCED = self._kw
        self._prev_env = os.environ.get("SRT_NATIVE_INTERPRET")
        if jax.default_backend() != "tpu":
            os.environ["SRT_NATIVE_INTERPRET"] = "1"
        return self

    def __exit__(self, *exc):
        global _FORCED
        _FORCED = self._prev_forced
        if self._prev_env is None:
            os.environ.pop("SRT_NATIVE_INTERPRET", None)
        else:
            os.environ["SRT_NATIVE_INTERPRET"] = self._prev_env
        return False


def _count(name: str) -> None:
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + 1


def counters() -> Dict[str, float]:
    with _LOCK:
        out = dict(_COUNTERS)
    out["nativeEnabled"] = bool(master_enabled() and available())
    out["nativeKernels"] = [k for k in KERNELS if kernel_enabled(k)]
    return out


def reset_counters() -> None:
    with _LOCK:
        _COUNTERS.clear()


# ---------------------------------------------------------------------------
# Block geometry. Capacity buckets are 2^k or 3*2^(k-1) (columnar/batch.py),
# so a 512/384 block always divides the capacity exactly — no remainder
# masking inside the kernels.
# ---------------------------------------------------------------------------

def _block(cap: int, limit: int = 512) -> int:
    if cap <= limit:
        return cap
    if cap % limit == 0:
        return limit
    b = limit * 3 // 4                     # 384 divides every 3*2^(k-1) rung
    assert cap % b == 0, f"capacity {cap} not divisible by {limit}/{b}"
    return b


def _pallas(kernel, **kw):
    from jax.experimental import pallas as pl
    return pl.pallas_call(kernel, interpret=_interpret(), **kw)


# ---------------------------------------------------------------------------
# Kernel 1: stable u32 radix rank (the LSD sort passes)
# ---------------------------------------------------------------------------
#
# One stable argsort of a (cap,) uint32 array = 4 stable counting-sort
# passes over 8-bit digits. Per digit pass:
#   hist kernel : per-block 256-bucket histogram (one-hot sum, dense VPU)
#   (jnp glue)  : digit bases = exclusive scan of totals; block bases =
#                 digit base + exclusive scan of block histograms
#   rank kernel : rank[i] = base[block, digit] + stable within-block
#                 prefix (exclusive one-hot column cumsum)
#   (jnp glue)  : permutation scatter (linear)
#
# Stability is by construction (block-major, row order), and a stable sort
# permutation is unique — hence bit-identical to jnp.argsort(stable=True).

_RADIX_BUCKETS = 256


def _hist_kernel(dig_ref, hist_ref):
    d = dig_ref[:].reshape(-1, 1)
    buckets = jax.lax.broadcasted_iota(
        jnp.int32, (d.shape[0], _RADIX_BUCKETS), 1)
    hist_ref[0, :] = jnp.sum((d == buckets).astype(jnp.int32),
                             axis=0).astype(jnp.int32)


def _rank_kernel(dig_ref, base_ref, rank_ref):
    d = dig_ref[:].reshape(-1, 1)
    buckets = jax.lax.broadcasted_iota(
        jnp.int32, (d.shape[0], _RADIX_BUCKETS), 1)
    onehot = (d == buckets).astype(jnp.int32)
    # Exclusive within-block stable prefix per bucket.
    prefix = jnp.cumsum(onehot, axis=0).astype(jnp.int32) - onehot
    rank_ref[:] = jnp.sum(onehot * (base_ref[0, :][None, :] + prefix),
                          axis=1).astype(jnp.int32)


def _digit_rank(dig: jnp.ndarray, cap: int) -> jnp.ndarray:
    """Stable counting-sort rank of one 8-bit digit array."""
    from jax.experimental import pallas as pl
    blk = _block(cap)
    nblocks = cap // blk
    hist = _pallas(
        _hist_kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((blk,), lambda b: (b,))],
        out_specs=pl.BlockSpec((1, _RADIX_BUCKETS), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((nblocks, _RADIX_BUCKETS),
                                       jnp.int32),
    )(dig)
    totals = jnp.sum(hist, axis=0).astype(jnp.int32)
    digit_base = jnp.cumsum(totals).astype(jnp.int32) - totals
    block_excl = jnp.cumsum(hist, axis=0).astype(jnp.int32) - hist
    block_base = (digit_base[None, :] + block_excl).astype(jnp.int32)
    return _pallas(
        _rank_kernel,
        grid=(nblocks,),
        in_specs=[pl.BlockSpec((blk,), lambda b: (b,)),
                  pl.BlockSpec((1, _RADIX_BUCKETS), lambda b: (b, 0))],
        out_specs=pl.BlockSpec((blk,), lambda b: (b,)),
        out_shape=jax.ShapeDtypeStruct((cap,), jnp.int32),
    )(dig, block_base)


def stable_argsort_u32(keyed: jnp.ndarray) -> jnp.ndarray:
    """Native twin of ``jnp.argsort(keyed, stable=True)`` for (cap,)
    uint32 keys: 4 LSD counting-sort digit passes."""
    _count("nativeRadixSortTraces")
    cap = keyed.shape[0]
    cur = jnp.arange(cap, dtype=jnp.int32)
    for shift in (0, 8, 16, 24):
        k = jnp.take(keyed, cur, axis=0)
        dig = ((k >> jnp.uint32(shift)) & jnp.uint32(0xFF)).astype(jnp.int32)
        rank = _digit_rank(dig, cap)
        cur = jnp.zeros((cap,), jnp.int32).at[rank].set(cur)
    return cur


# ---------------------------------------------------------------------------
# Kernel 2: fused hash-join probe (lower+upper bound over u64 fingerprints)
# ---------------------------------------------------------------------------

def _probe_kernel_factory(cap_b: int):
    # Descending power-of-two steps covering any capacity rung.
    steps = []
    s = 1
    while s * 2 <= cap_b:
        s *= 2
    while s >= 1:
        steps.append(s)
        s //= 2

    def kernel(bh_ref, bl_ref, qh_ref, ql_ref, lo_ref, hi_ref):
        bh = bh_ref[:]
        bl = bl_ref[:]
        qh = qh_ref[:]
        ql = ql_ref[:]
        lo = jnp.zeros(qh.shape, jnp.int32)
        hi = jnp.zeros(qh.shape, jnp.int32)
        n = jnp.int32(cap_b)
        for s in steps:
            for is_hi in (False, True):
                pos = hi if is_hi else lo
                nxt = pos + jnp.int32(s)
                idx = nxt - 1
                ah = jnp.take(bh, idx, axis=0)
                al = jnp.take(bl, idx, axis=0)
                if is_hi:       # count of build <= key (searchsorted right)
                    cmp = (ah < qh) | ((ah == qh) & (al <= ql))
                else:           # count of build <  key (searchsorted left)
                    cmp = (ah < qh) | ((ah == qh) & (al < ql))
                ok = (nxt <= n) & cmp
                if is_hi:
                    hi = jnp.where(ok, nxt, hi)
                else:
                    lo = jnp.where(ok, nxt, lo)
        lo_ref[:] = lo
        hi_ref[:] = hi
    return kernel


def searchsorted_u64_pair(built_fp: jnp.ndarray, probe_fp: jnp.ndarray):
    """Native twin of the probe's two ``jnp.searchsorted`` calls:
    ``(left, right)`` insertion points of every probe fingerprint in the
    sorted build fingerprints, as int32."""
    from jax.experimental import pallas as pl
    _count("nativeJoinProbeTraces")
    cap_b = built_fp.shape[0]
    cap_p = probe_fp.shape[0]
    bh = (built_fp >> jnp.uint64(32)).astype(jnp.uint32)
    bl = (built_fp & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    qh = (probe_fp >> jnp.uint64(32)).astype(jnp.uint32)
    ql = (probe_fp & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    blk = _block(cap_p)
    lo, hi = _pallas(
        _probe_kernel_factory(cap_b),
        grid=(cap_p // blk,),
        in_specs=[pl.BlockSpec((cap_b,), lambda b: (0,)),
                  pl.BlockSpec((cap_b,), lambda b: (0,)),
                  pl.BlockSpec((blk,), lambda b: (b,)),
                  pl.BlockSpec((blk,), lambda b: (b,))],
        out_specs=(pl.BlockSpec((blk,), lambda b: (b,)),
                   pl.BlockSpec((blk,), lambda b: (b,))),
        out_shape=(jax.ShapeDtypeStruct((cap_p,), jnp.int32),
                   jax.ShapeDtypeStruct((cap_p,), jnp.int32)),
    )(bh, bl, qh, ql)
    return lo, hi


# ---------------------------------------------------------------------------
# Kernel 3: wire v2 RLE decode (interval-membership select)
# ---------------------------------------------------------------------------

def _rle_kernel_factory(blk: int, run_cap: int, planes: int):
    def kernel(prev_ref, ends_ref, vals_ref, nrows_ref, out_ref):
        from jax.experimental import pallas as pl
        r0 = pl.program_id(0) * blk
        rows = r0 + jax.lax.broadcasted_iota(jnp.int32, (blk, 1), 0)
        prev = prev_ref[:].reshape(1, run_cap)
        ends = ends_ref[:].reshape(1, run_cap)
        mask = (prev <= rows) & (rows < ends)          # (blk, run_cap)
        live = rows < nrows_ref[0]                     # (blk, 1)
        vals = vals_ref[:]                             # (run_cap, planes)
        for p in range(planes):
            sel = jnp.sum(jnp.where(mask, vals[:, p][None, :], 0),
                          axis=1).astype(jnp.int32)
            out_ref[:, p] = jnp.where(live[:, 0], sel, jnp.int32(0))
    return kernel


def rle_decode(run_vals: jnp.ndarray, run_ends: jnp.ndarray, cap: int,
               num_rows) -> jnp.ndarray:
    """Native twin of the RLE decode's searchsorted+gather chain: expand
    the run table to (cap,) values in the wire dtype, padding rows
    zeroed. Bit patterns move through int32 planes, so float payloads
    (-0.0, NaN) reconstruct exactly."""
    from jax.experimental import pallas as pl
    _count("nativeRleDecodeTraces")
    run_cap = run_vals.shape[0]
    dt_ = run_vals.dtype
    itemsize = np.dtype(dt_).itemsize
    if itemsize == 8:
        planes = jax.lax.bitcast_convert_type(
            run_vals.reshape(run_cap, 1), jnp.int32).reshape(run_cap, 2)
    elif itemsize == 4:
        planes = jax.lax.bitcast_convert_type(
            run_vals, jnp.int32).reshape(run_cap, 1)
    else:                       # int8/int16 sign-extend (exact round trip)
        planes = run_vals.astype(jnp.int32).reshape(run_cap, 1)
    npl = planes.shape[1]
    prev = jnp.concatenate([jnp.zeros((1,), run_ends.dtype), run_ends[:-1]])
    blk = _block(cap)
    nrows = jnp.asarray(num_rows, jnp.int32).reshape(1)
    out = _pallas(
        _rle_kernel_factory(blk, run_cap, npl),
        grid=(cap // blk,),
        in_specs=[pl.BlockSpec((run_cap,), lambda b: (0,)),
                  pl.BlockSpec((run_cap,), lambda b: (0,)),
                  pl.BlockSpec((run_cap, npl), lambda b: (0, 0)),
                  pl.BlockSpec((1,), lambda b: (0,))],
        out_specs=pl.BlockSpec((blk, npl), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((cap, npl), jnp.int32),
    )(prev.astype(jnp.int32), run_ends.astype(jnp.int32), planes, nrows)
    if itemsize == 8:
        return jax.lax.bitcast_convert_type(out, dt_).reshape(cap)
    if itemsize == 4:
        return jax.lax.bitcast_convert_type(out[:, 0], dt_)
    return out[:, 0].astype(dt_)        # wrap-narrow, exact for the widen


# ---------------------------------------------------------------------------
# Kernel 4: sorted-segment reduction (segmented scan + boundary pick)
# ---------------------------------------------------------------------------
#
# ``segment_reduce``'s gid is group-sorted (nondecreasing), so the
# scatter-based jax.ops.segment_* is overkill: one segmented scan sweep
# produces per-row running reductions; the value at each segment's last
# row IS the segment result (scattered to its slot with unique indices).
#
# Everything runs in an exact encoded domain of 1-2 uint32 planes:
#   - integer sums: two's-complement add (wrap-exact, associative),
#     int64 as (hi, lo) with explicit carry;
#   - min/max: the total-order bit transform (floats: sign-flip trick,
#     so -0.0 < 0.0 exactly like XLA's minimum; ints: sign-bias flip),
#     identities chosen to decode to the twin's identities.
# Float SUMS never come here: reduction order changes rounding, and bit
# identity is the contract.

def _shift_down(x, d, fill):
    pad = jnp.full((d,) + x.shape[1:], fill, x.dtype)
    return jnp.concatenate([pad, x[:-d]], axis=0)


def _combine(kind: str, a_planes, b_planes):
    """combine(a, b) where a precedes b; returns planes of the result."""
    if kind == "sum32":
        return (a_planes[0] + b_planes[0],)
    if kind == "sum64":
        ah, al = a_planes
        bh, bl = b_planes
        lo = al + bl
        carry = (lo < al).astype(jnp.uint32)
        return (ah + bh + carry, lo)
    # min/max over 1 or 2 unsigned planes, lexicographic.
    if len(a_planes) == 1:
        a, b = a_planes[0], b_planes[0]
        pick_a = a < b if kind == "min" else a > b
        return (jnp.where(pick_a, a, b),)
    ah, al = a_planes
    bh, bl = b_planes
    a_lt = (ah < bh) | ((ah == bh) & (al < bl))
    pick_a = a_lt if kind == "min" else \
        (ah > bh) | ((ah == bh) & (al > bl))
    return (jnp.where(pick_a, ah, bh), jnp.where(pick_a, al, bl))


def _segscan_kernel_factory(blk: int, planes: int, kind: str,
                            identity: Tuple[int, ...]):
    steps = []
    d = 1
    while d < blk:
        steps.append(d)
        d *= 2

    def kernel(flag_ref, pl_refs, out_ref, carry_ref):
        from jax.experimental import pallas as pl
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _():
            for p in range(planes):
                carry_ref[0, p] = jnp.uint32(identity[p])

        # Hillis-Steele over the segmented-scan monoid (g, v):
        #   (g1,v1) + (g2,v2) = (g1|g2, g2 ? v2 : combine(v1,v2))
        # with (g=0, v=identity) as the neutral fill beyond block start.
        g = flag_ref[:]                              # (blk,) int32 0/1
        v = tuple(pl_refs[:, p] for p in range(planes))
        for d in steps:
            g_sh = _shift_down(g, d, jnp.int32(0))
            v_sh = tuple(_shift_down(v[p], d, jnp.uint32(identity[p]))
                         for p in range(planes))
            comb = _combine(kind, v_sh, v)
            keep = g == 1
            v = tuple(jnp.where(keep, v[p], comb[p])
                      for p in range(planes))
            g = g | g_sh
        # Rows with no segment start inside this block continue the
        # carried segment from the previous block.
        open_ = g == 0
        carry = tuple(jnp.broadcast_to(carry_ref[0, p], (blk,))
                      for p in range(planes))
        fixed = _combine(kind, carry, v)
        v = tuple(jnp.where(open_, fixed[p], v[p]) for p in range(planes))
        for p in range(planes):
            out_ref[:, p] = v[p]
            carry_ref[0, p] = v[p][blk - 1]
    return kernel


def _segscan(flags: jnp.ndarray, planes: jnp.ndarray, kind: str,
             identity: Tuple[int, ...]) -> jnp.ndarray:
    """Per-row running segmented reduction over (cap, P) uint32 planes.
    ``flags[i]`` = 1 iff row i starts a segment (row 0 included)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    cap, npl = planes.shape
    blk = _block(cap)
    return _pallas(
        _segscan_kernel_factory(blk, npl, kind, identity),
        grid=(cap // blk,),
        in_specs=[pl.BlockSpec((blk,), lambda b: (b,)),
                  pl.BlockSpec((blk, npl), lambda b: (b, 0))],
        out_specs=pl.BlockSpec((blk, npl), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((cap, npl), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((1, npl), jnp.uint32)],
    )(flags.astype(jnp.int32), planes)


def _bitcast(x, dt_):
    return jax.lax.bitcast_convert_type(x, dt_)


def _minmax_encode(values: jnp.ndarray):
    """Exact total-order encode to uint32 planes; returns (planes list,
    decode fn) or None when the dtype has no exact encode here."""
    dt_ = values.dtype
    if dt_ == jnp.bool_:
        enc = _bitcast(values.astype(jnp.int32), jnp.uint32) \
            ^ jnp.uint32(0x80000000)

        def dec(planes):
            return _bitcast(planes[0] ^ jnp.uint32(0x80000000),
                            jnp.int32) != 0
        return [enc], dec
    if jnp.issubdtype(dt_, jnp.integer) and np.dtype(dt_).itemsize <= 4:
        enc = _bitcast(values.astype(jnp.int32), jnp.uint32) \
            ^ jnp.uint32(0x80000000)

        def dec(planes):
            return _bitcast(planes[0] ^ jnp.uint32(0x80000000),
                            jnp.int32).astype(dt_)
        return [enc], dec
    if jnp.issubdtype(dt_, jnp.integer):          # int64 / timestamp
        u = _bitcast(values, jnp.uint64) ^ jnp.uint64(0x8000000000000000)
        hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
        lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)

        def dec(planes):
            u_ = (planes[0].astype(jnp.uint64) << jnp.uint64(32)) | \
                planes[1].astype(jnp.uint64)
            return _bitcast(u_ ^ jnp.uint64(0x8000000000000000), dt_)
        return [hi, lo], dec
    if dt_ == jnp.float32:
        bits = _bitcast(values, jnp.uint32)
        neg = (bits >> jnp.uint32(31)) == 1
        enc = jnp.where(neg, ~bits, bits | jnp.uint32(0x80000000))

        def dec(planes):
            e = planes[0]
            pos = (e & jnp.uint32(0x80000000)) != 0
            bits_ = jnp.where(pos, e ^ jnp.uint32(0x80000000), ~e)
            return _bitcast(bits_, jnp.float32)
        return [enc], dec
    if dt_ == jnp.float64:
        if jax.default_backend() == "tpu":
            return None         # emulated f64 cannot bitcast on TPU
        bits = _bitcast(values, jnp.uint64)
        neg = (bits >> jnp.uint64(63)) == 1
        enc = jnp.where(neg, ~bits, bits | jnp.uint64(0x8000000000000000))
        hi = (enc >> jnp.uint64(32)).astype(jnp.uint32)
        lo = (enc & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)

        def dec(planes):
            e = (planes[0].astype(jnp.uint64) << jnp.uint64(32)) | \
                planes[1].astype(jnp.uint64)
            pos = (e & jnp.uint64(0x8000000000000000)) != 0
            bits_ = jnp.where(pos, e ^ jnp.uint64(0x8000000000000000), ~e)
            return _bitcast(bits_, jnp.float64)
        return [hi, lo], dec
    return None


def _encoded_identity(np_dtype, kind: str) -> Tuple[int, ...]:
    """Encoded identity planes computed in NUMPY (this runs at trace
    time). The identity must DECODE to exactly the twin's
    ``jax.ops.segment_min``/``segment_max`` empty-segment fill (dtype
    max/min, +/-inf for floats), and no encoded value may beat it in
    the total order — true by construction since it encodes the
    dtype's extreme (the twin masks NaN before reducing, so the float
    extremes are the infinities)."""
    if np.issubdtype(np_dtype, np.floating):
        ext = np.asarray(np.inf if kind == "min" else -np.inf, np_dtype)
        if np_dtype == np.dtype(np.float32):
            bits = int(ext.view(np.uint32))
            enc = (~bits & 0xFFFFFFFF) if bits >> 31 else bits | 0x80000000
            return (enc,)
        bits = int(ext.view(np.uint64))
        enc = (~bits & (2 ** 64 - 1)) if bits >> 63 else \
            bits | 0x8000000000000000
        return (enc >> 32, enc & 0xFFFFFFFF)
    if np_dtype == np.dtype(np.bool_):
        v = 1 if kind == "min" else 0
        return ((v ^ 0x80000000),)
    info = np.iinfo(np_dtype)
    v = info.max if kind == "min" else info.min
    if np_dtype.itemsize <= 4:
        return (((v & 0xFFFFFFFF) ^ 0x80000000),)
    u = (v & (2 ** 64 - 1)) ^ (1 << 63)
    return (u >> 32, u & 0xFFFFFFFF)


def _segment_finish(running: jnp.ndarray, gid: jnp.ndarray, capacity: int,
                    identity: Tuple[int, ...]) -> jnp.ndarray:
    """Scatter each segment's last running value to its slot; empty
    slots keep the (encoded) identity. Indices are unique (gid is
    nondecreasing), so .set is race-free."""
    cap = gid.shape[0]
    is_last = jnp.concatenate([gid[1:] != gid[:-1],
                               jnp.ones((1,), jnp.bool_)])
    slots = jnp.where(is_last, gid, capacity)
    npl = running.shape[1]
    init = jnp.tile(jnp.asarray(identity, jnp.uint32)[None, :],
                    (capacity, 1))
    return init.at[slots].set(running, mode="drop")


def _flags_of(gid: jnp.ndarray) -> jnp.ndarray:
    return jnp.concatenate([jnp.ones((1,), jnp.bool_),
                            gid[1:] != gid[:-1]]).astype(jnp.int32)


def segment_sum_sorted(values: jnp.ndarray, gid: jnp.ndarray,
                       capacity: int) -> Optional[jnp.ndarray]:
    """Native twin of ``jax.ops.segment_sum`` for nondecreasing ids.
    Returns None when the dtype is not exactly summable here (floats:
    reduction order changes rounding)."""
    dt_ = values.dtype
    if jnp.issubdtype(dt_, jnp.floating) or dt_ == jnp.bool_:
        return None
    _count("nativeSegmentReduceTraces")
    flags = _flags_of(gid)
    if np.dtype(dt_).itemsize <= 4:
        planes = jnp.stack(
            [_bitcast(values.astype(jnp.int32), jnp.uint32)], axis=1)
        running = _segscan(flags, planes, "sum32", (0,))
        out = _segment_finish(running, gid, capacity, (0,))
        return _bitcast(out[:, 0], jnp.int32).astype(dt_)
    u = _bitcast(values, jnp.uint64)
    planes = jnp.stack([(u >> jnp.uint64(32)).astype(jnp.uint32),
                        (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)],
                       axis=1)
    running = _segscan(flags, planes, "sum64", (0, 0))
    out = _segment_finish(running, gid, capacity, (0, 0))
    u_ = (out[:, 0].astype(jnp.uint64) << jnp.uint64(32)) | \
        out[:, 1].astype(jnp.uint64)
    return _bitcast(u_, dt_)


def segment_minmax_sorted(values: jnp.ndarray, gid: jnp.ndarray,
                          capacity: int, kind: str
                          ) -> Optional[jnp.ndarray]:
    """Native twin of ``jax.ops.segment_min``/``segment_max`` for
    nondecreasing ids, in the total-order bit domain. Returns None when
    the dtype has no exact encode (f64 on a real TPU)."""
    assert kind in ("min", "max")
    enc = _minmax_encode(values)
    if enc is None:
        return None
    _count("nativeSegmentReduceTraces")
    planes_list, dec = enc
    identity = _encoded_identity(np.dtype(values.dtype), kind)
    flags = _flags_of(gid)
    planes = jnp.stack(planes_list, axis=1)
    running = _segscan(flags, planes, kind, identity)
    out = _segment_finish(running, gid, capacity, identity)
    return dec([out[:, p] for p in range(out.shape[1])])
