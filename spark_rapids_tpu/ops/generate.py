"""GenerateExec: explode / posexplode (ref: GpuGenerateExec.scala, 194
LoC — per-row repeat of companion columns + flattened array elements).

The engine's type system is scalar-only (same envelope as the reference's
GpuOverrides.isSupportedType gate), so the supported generator is
``explode(array(e1, .., ek))`` — an inline array of K element expressions
per row, Spark's array-literal explode. Row i expands to up to K output
rows (NULL elements dropped unless ``outer``); companion columns repeat.

TPU shape story: K is static, so the expansion is a fixed gather — output
capacity = K * input capacity, no size sync at all (unlike joins). A
compaction pass drops null elements when not outer.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, DeviceColumn, bucket_capacity, string_repad)
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.exprs.base import Expression, as_device_column, \
    as_host_column
from spark_rapids_tpu.ops.base import (Exec, ExecContext, Schema,
    record_batch, timed)


class GenerateExec(Exec):
    """explode/posexplode of an inline array over each input row."""

    def __init__(self, child: Exec, elements: Sequence[Expression],
                 position: bool = False, outer: bool = False,
                 element_name: str = "col", skip_nulls: bool = False):
        """``skip_nulls`` drops NULL elements (emulating variable-length
        arrays via null padding); ``outer`` then still emits one all-NULL
        row for rows whose every element is NULL (explode_outer). With
        skip_nulls=False (Spark's semantics for inline arrays, which are
        never null) every row emits exactly K output rows."""
        super().__init__(child)
        assert elements, "explode of empty array"
        self.elements = list(elements)
        self.position = position
        self.outer = outer
        self.skip_nulls = skip_nulls
        self.element_name = element_name
        t0 = self.elements[0].data_type()
        for e in self.elements[1:]:
            assert e.data_type() == t0, "array elements must share a type"
        self._elem_type = t0

    @property
    def schema(self) -> Schema:
        base = list(self.children[0].schema)
        if self.position:
            base.append(("pos", dt.INT32))
        base.append((self.element_name, self._elem_type))
        return tuple(base)

    def _kernel(self, batch: DeviceBatch) -> DeviceBatch:
        cap = batch.capacity
        k = len(self.elements)
        out_cap = bucket_capacity(cap * k)
        # Element columns evaluated on the input batch.
        elems = [as_device_column(e.eval(batch), batch)
                 for e in self.elements]
        if self._elem_type.is_string:
            w = max(c.string_width for c in elems)
            elems = [string_repad(c, w) for c in elems]
        # Output slot s (< cap*k) maps to (row = s // k, element = s % k):
        # each input row's K elements are adjacent, Spark's explode order.
        slots = jnp.arange(out_cap, dtype=jnp.int32)
        row = slots // k
        ei = slots % k
        live = jnp.take(batch.row_mask(), jnp.clip(row, 0, cap - 1),
                        axis=0) & (slots < cap * k)
        # Element value/validity per slot: select among the K columns.
        edata = jnp.stack([c.data for c in elems])        # (k, cap, [w])
        evalid = jnp.stack([c.validity for c in elems])   # (k, cap)
        rr = jnp.clip(row, 0, cap - 1)
        if self._elem_type.is_string:
            val = edata[ei, rr]                           # (out_cap, w)
            elens = jnp.stack([c.lengths for c in elems])
            lens = elens[ei, rr]
        else:
            val = edata[ei, rr]
            lens = None
        vvalid = evalid[ei, rr] & live
        if not self.skip_nulls:
            keep = live
        else:
            keep = live & vvalid
            if self.outer:
                # explode_outer: a row with zero surviving elements still
                # emits one all-NULL element row (at slot ei == 0).
                any_valid = jnp.any(jnp.stack(
                    [c.validity for c in elems]), axis=0)
                none_valid = ~jnp.take(any_valid, rr, axis=0)
                keep = keep | (live & none_valid & (ei == 0))
        # Companion columns gathered by source row.
        out_cols: List[DeviceColumn] = []
        for c in batch.columns:
            out_cols.append(c.gather(rr, live))
        if self.position:
            out_cols.append(DeviceColumn(
                dt.INT32, jnp.where(live, ei, 0).astype(jnp.int32), live))
        if self._elem_type.is_string:
            out_cols.append(DeviceColumn(
                self._elem_type, jnp.where(vvalid[:, None], val, 0),
                vvalid, jnp.where(vvalid, lens, 0)))
        else:
            out_cols.append(DeviceColumn(
                self._elem_type,
                jnp.where(vvalid, val, jnp.zeros((), val.dtype)), vvalid))
        expanded = DeviceBatch(tuple(out_cols),
                               jnp.asarray(cap * k, jnp.int32))
        # Dense rows first: compact away dropped slots (padding + non-outer
        # nulls). keep already excludes dead input rows.
        return expanded.compact(keep)

    def execute_device(self, ctx, partition):
        from spark_rapids_tpu.ops import kernel_cache as kc
        m = ctx.metrics_for(self)
        fp = (kc.fingerprint(tuple(self.elements)), self.position,
              self.outer, self.skip_nulls)
        schema_fp = kc.schema_fingerprint(self.children[0].schema)
        for batch in self.children[0].execute_device(ctx, partition):
            # The kernel is a bound method: jit a child-severed clone so
            # the cache entry never pins the plan subtree.
            entry = kc.lookup(
                "generate", (fp, schema_fp, batch.capacity),
                lambda: jax.jit(kc.detached_clone(self)._kernel), m)
            with timed(m):
                out = kc.call(entry, m, batch)
            record_batch(m, out)
            yield out

    # -- host oracle ---------------------------------------------------------
    def execute_host(self, ctx, partition):
        for hb in self.children[0].execute_host(ctx, partition):
            elem_lists = [as_host_column(e.eval_host(hb), hb).to_list()
                          for e in self.elements]
            comp = [c.to_list() for c in hb.columns]
            rows = []
            for i in range(hb.num_rows):
                emitted = False
                for j, el in enumerate(elem_lists):
                    v = el[i]
                    if v is None and self.skip_nulls:
                        continue
                    r = [cl[i] for cl in comp]
                    if self.position:
                        r.append(j)
                    r.append(v)
                    rows.append(tuple(r))
                    emitted = True
                if self.skip_nulls and self.outer and not emitted:
                    r = [cl[i] for cl in comp]
                    if self.position:
                        r.append(0)
                    r.append(None)
                    rows.append(tuple(r))
            names = tuple(n for n, _ in self.schema)
            cols = []
            for ci, (_, t) in enumerate(self.schema):
                cols.append(HostColumn.from_values(
                    t, [r[ci] for r in rows]))
            yield HostBatch(names, cols)
