"""Physical operators (the GpuExec layer, SURVEY.md §2.4)."""

from spark_rapids_tpu.ops.base import (         # noqa: F401
    DeviceToHostExec, Exec, ExecContext, HostToDeviceExec,
    InMemorySourceExec, Metrics, Schema)
from spark_rapids_tpu.ops.basic import (        # noqa: F401
    CoalescePartitionsExec, ExpandExec, FilterExec, GlobalLimitExec,
    LocalLimitExec, ProjectExec, RangeExec, UnionExec)
from spark_rapids_tpu.ops.fused import FusedStageExec  # noqa: F401
from spark_rapids_tpu.ops.sort import SortExec, SortOrder  # noqa: F401
from spark_rapids_tpu.ops.aggregate import (    # noqa: F401
    AggSpec, Average, Count, CountStar, First, HashAggregateExec, Last, Max,
    Min, Sum)
