"""Pandas-UDF operator family — host islands inside device plans
(ref: GpuArrowEvalPythonExec.scala:494 and its grouped flavors
GpuFlatMapGroupsInPandasExec, GpuCoGroupedMapInPandasExec,
GpuMapInPandasExec, GpuAggregateInPandasExec, plus the bounded
PythonWorkerSemaphore and python/rapids/worker.py:22-67 daemon pool).

The reference ships columnar batches to out-of-process Python workers
over Arrow. Here the engine and the UDFs share one interpreter, so the
"worker" is a bounded thread pool (the PythonWorkerSemaphore analog:
at most ``spark.rapids.python.concurrentPythonWorkers`` group functions
in flight) and the Arrow hop is a direct HostBatch<->pandas conversion.
Each exec's device path is: download the child's device batches, run the
user's pandas function on the host, upload the results — exactly the
shape of the reference's GPU->JVM->Python round trip, minus a process
boundary that buys nothing in-process.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import (
    HostBatch, HostColumn, device_to_host, host_to_device)
from spark_rapids_tpu.ops.base import (Exec, ExecContext, Schema,
    record_batch, timed)

_POOLS: dict = {}


def worker_pool(ctx: ExecContext) -> ThreadPoolExecutor:
    """Bounded pandas-UDF pool (PythonWorkerSemaphore.scala analog)."""
    from spark_rapids_tpu import config as C
    n = max(int(ctx.conf.get(C.CONCURRENT_PYTHON_WORKERS)), 1)
    pool = _POOLS.get(n)
    if pool is None:
        pool = ThreadPoolExecutor(max_workers=n,
                                  thread_name_prefix="pandas-udf")
        _POOLS[n] = pool
    return pool


# ---------------------------------------------------------------------------
# HostBatch <-> pandas
# ---------------------------------------------------------------------------

def batches_to_pandas(hbs: Sequence[HostBatch], names: Sequence[str]):
    """Concatenate host batches into one pandas DataFrame. Strings decode
    to str; nulls become None (object) or NaN (float); dates stay as
    days-since-epoch ints (the engine's physical value)."""
    import pandas as pd
    cols = {}
    for ci, name in enumerate(names):
        parts = []
        for hb in hbs:
            c = hb.columns[ci]
            if c.dtype.is_string:
                vals = [
                    (v.decode("utf-8") if isinstance(v, bytes) else v)
                    if ok else None
                    for v, ok in zip(c.data, c.validity)]
                parts.append(pd.Series(vals, dtype=object))
            elif c.validity.all():
                parts.append(pd.Series(np.asarray(c.data)))
            elif c.dtype.is_floating:
                parts.append(pd.Series(
                    np.where(c.validity, c.data, np.nan)))
            else:
                vals = [v if ok else None
                        for v, ok in zip(c.data.tolist(), c.validity)]
                parts.append(pd.Series(vals, dtype=object))
        cols[name] = pd.concat(parts, ignore_index=True) if parts \
            else pd.Series([], dtype=object)
    return pd.DataFrame(cols)


def pandas_to_batch(pdf, schema: Schema) -> HostBatch:
    """User-returned DataFrame -> HostBatch, by declared output schema
    (column NAME lookup, Spark's apply_in_pandas contract)."""
    names = tuple(n for n, _ in schema)
    cols = []
    for name, t in schema:
        if name not in pdf.columns:
            raise ValueError(
                f"pandas UDF output is missing declared column {name!r} "
                f"(has {list(pdf.columns)})")
        s = pdf[name]
        vals = []
        for v in s.tolist():
            if v is None or (isinstance(v, float) and np.isnan(v)
                             and not t.is_floating):
                vals.append(None)
            else:
                vals.append(v)
        cols.append(HostColumn.from_values(t, vals))
    return HostBatch(names, cols)


def _normalize_key(key: tuple) -> tuple:
    """Group-key tuple with every null encoding (None, float NaN)
    collapsed to None. pandas hands back ``nan`` for null keys under
    ``dropna=False``, and two NaN objects from two separate groupbys are
    neither ``==`` nor (since 3.10) same-hash — so cogrouping by raw
    keys silently pairs each side's null group with an EMPTY other side.
    Normalizing to None makes null keys from both sides collide into one
    cogrouped call (Spark's null-key grouping semantics)."""
    return tuple(None if v is None
                 or (isinstance(v, float) and v != v) else v
                 for v in key)


def _group_frames(pdf, key_names: Sequence[str]):
    """(key_tuple, group pdf) in sorted key order; NaN/None keys group
    together (dropna=False, Spark groups null keys)."""
    if not len(pdf):
        return []
    grouped = pdf.groupby(list(key_names), sort=True, dropna=False)
    return [(k if isinstance(k, tuple) else (k,),
             g.reset_index(drop=True)) for k, g in grouped]


# ---------------------------------------------------------------------------
# Execs
# ---------------------------------------------------------------------------

class _PandasIslandExec(Exec):
    """Shared download->pandas->upload plumbing."""

    out_schema: Schema

    @property
    def schema(self) -> Schema:
        return self.out_schema

    def _child_pdf(self, ctx, partition, child_idx: int = 0):
        child = self.children[child_idx]
        names = tuple(n for n, _ in child.schema)
        hbs = [device_to_host(b, names)
               for b in child.execute_device(ctx, partition)]
        return batches_to_pandas(hbs, names)

    def _child_pdf_host(self, ctx, partition, child_idx: int = 0):
        child = self.children[child_idx]
        names = tuple(n for n, _ in child.schema)
        hbs = list(child.execute_host(ctx, partition))
        return batches_to_pandas(hbs, names)

    def _child_pdf_host_all(self, ctx, child_idx: int = 0):
        """ALL child partitions as one frame: the host oracle has no
        co-partitioning exchange, so grouped flavors gather everything
        and emit from partition 0 only."""
        child = self.children[child_idx]
        names = tuple(n for n, _ in child.schema)
        hbs = []
        for p in range(child.num_partitions(ctx)):
            hbs.extend(child.execute_host(ctx, p))
        return batches_to_pandas(hbs, names)

    def _upload(self, hb: HostBatch):
        return host_to_device(hb)


class MapInPandasExec(_PandasIslandExec):
    """df.map_in_pandas(fn, schema): fn(iterator of pandas DataFrames) ->
    iterator of DataFrames (GpuMapInPandasExec analog). Streams one
    input frame per child batch."""

    def __init__(self, child: Exec, fn: Callable, out_schema: Schema):
        super().__init__(child)
        self.fn = fn
        self.out_schema = tuple(out_schema)

    def _run(self, frames):
        for out_pdf in self.fn(iter(frames)):
            yield pandas_to_batch(out_pdf, self.out_schema)

    def execute_device(self, ctx, partition):
        m = ctx.metrics_for(self)
        child = self.children[0]
        names = tuple(n for n, _ in child.schema)

        def frames():
            for b in child.execute_device(ctx, partition):
                yield batches_to_pandas([device_to_host(b, names)], names)

        with timed(m):
            for hb in self._run(frames()):
                record_batch(m, hb)
                yield self._upload(hb)

    def execute_host(self, ctx, partition):
        child = self.children[0]
        names = tuple(n for n, _ in child.schema)
        frames = (batches_to_pandas([hb], names)
                  for hb in child.execute_host(ctx, partition))
        yield from self._run(frames)


class FlatMapGroupsInPandasExec(_PandasIslandExec):
    """group_by(keys).apply_in_pandas(fn, schema): fn(group pdf) -> pdf
    (GpuFlatMapGroupsInPandasExec analog). The planner co-partitions the
    child by the grouping keys, so each partition owns whole groups; the
    bounded worker pool evaluates groups concurrently."""

    def __init__(self, child: Exec, key_names: Sequence[str],
                 fn: Callable, out_schema: Schema):
        super().__init__(child)
        self.key_names = list(key_names)
        self.fn = fn
        self.out_schema = tuple(out_schema)

    def _apply(self, ctx, pdf) -> Optional[HostBatch]:
        import pandas as pd
        groups = _group_frames(pdf, self.key_names)
        if not groups:
            return None
        pool = worker_pool(ctx)
        outs = list(pool.map(self.fn, [g for _, g in groups]))
        return pandas_to_batch(
            pd.concat(outs, ignore_index=True) if len(outs) > 1
            else outs[0], self.out_schema)

    def execute_device(self, ctx, partition):
        m = ctx.metrics_for(self)
        with timed(m):
            hb = self._apply(ctx, self._child_pdf(ctx, partition))
        if hb is not None and hb.num_rows:
            record_batch(m, hb)
            yield self._upload(hb)

    def execute_host(self, ctx, partition):
        if partition != 0:
            return
        hb = self._apply(ctx, self._child_pdf_host_all(ctx))
        if hb is not None and hb.num_rows:
            yield hb


class CoGroupedMapInPandasExec(_PandasIslandExec):
    """cogroup(l.group_by(a), r.group_by(b)).apply_in_pandas(fn, schema):
    fn(left group pdf, right group pdf) per key in the UNION of both
    sides' keys, absent side = empty frame (GpuCoGroupedMapInPandas
    analog; both children co-partitioned by key)."""

    def __init__(self, left: Exec, right: Exec,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 fn: Callable, out_schema: Schema):
        super().__init__(left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self.out_schema = tuple(out_schema)

    def num_partitions(self, ctx) -> int:
        return self.children[0].num_partitions(ctx)

    def _apply(self, ctx, lpdf, rpdf) -> Optional[HostBatch]:
        import pandas as pd
        lg = {_normalize_key(k): g
              for k, g in _group_frames(lpdf, self.left_keys)}
        rg = {_normalize_key(k): g
              for k, g in _group_frames(rpdf, self.right_keys)}
        keys = sorted(set(lg) | set(rg),
                      key=lambda k: tuple(
                          (v is None, 0 if v is None else v)
                          for v in k))
        if not keys:
            return None
        lempty = lpdf.iloc[0:0]
        rempty = rpdf.iloc[0:0]
        pool = worker_pool(ctx)
        outs = list(pool.map(
            lambda k: self.fn(lg.get(k, lempty), rg.get(k, rempty)),
            keys))
        return pandas_to_batch(pd.concat(outs, ignore_index=True)
                               if len(outs) > 1 else outs[0],
                               self.out_schema)

    def execute_device(self, ctx, partition):
        m = ctx.metrics_for(self)
        with timed(m):
            hb = self._apply(ctx, self._child_pdf(ctx, partition, 0),
                             self._child_pdf(ctx, partition, 1))
        if hb is not None and hb.num_rows:
            record_batch(m, hb)
            yield self._upload(hb)

    def execute_host(self, ctx, partition):
        if partition != 0:
            return
        hb = self._apply(ctx, self._child_pdf_host_all(ctx, 0),
                         self._child_pdf_host_all(ctx, 1))
        if hb is not None and hb.num_rows:
            yield hb


class AggregateInPandasExec(_PandasIslandExec):
    """group_by(keys).agg_in_pandas(out=(col, series_fn, dtype), ...):
    each output is series_fn(group's column as a pandas Series) -> scalar
    (GpuAggregateInPandasExec analog: pandas_udf GROUPED_AGG)."""

    def __init__(self, child: Exec, key_names: Sequence[str],
                 aggs: Sequence[Tuple[str, str, Callable, dt.DataType]]):
        super().__init__(child)
        self.key_names = list(key_names)
        self.aggs = list(aggs)
        key_types = dict(child.schema)
        self.out_schema = tuple(
            [(k, key_types[k]) for k in self.key_names]
            + [(name, t) for name, _, _, t in self.aggs])

    def _apply(self, ctx, pdf) -> Optional[HostBatch]:
        groups = _group_frames(pdf, self.key_names)
        if not groups:
            return None
        pool = worker_pool(ctx)

        def one(item):
            key, g = item
            row = list(key)
            for _, colname, fn, _t in self.aggs:
                row.append(fn(g[colname]))
            return tuple(row)

        rows = list(pool.map(one, groups))
        names = tuple(n for n, _ in self.out_schema)
        cols = []
        for ci, (_, t) in enumerate(self.out_schema):
            vals = []
            for r in rows:
                v = r[ci]
                if v is not None and isinstance(v, float) \
                        and np.isnan(v) and not t.is_floating:
                    v = None
                vals.append(v)
            cols.append(HostColumn.from_values(t, vals))
        return HostBatch(names, cols)

    def execute_device(self, ctx, partition):
        m = ctx.metrics_for(self)
        with timed(m):
            hb = self._apply(ctx, self._child_pdf(ctx, partition))
        if hb is not None and hb.num_rows:
            record_batch(m, hb)
            yield self._upload(hb)

    def execute_host(self, ctx, partition):
        if partition != 0:
            return
        hb = self._apply(ctx, self._child_pdf_host_all(ctx))
        if hb is not None and hb.num_rows:
            yield hb
