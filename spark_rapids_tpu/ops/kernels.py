"""Shared device kernels: key normalization, lexicographic sort, grouping.

These are the TPU-first replacements for the cuDF kernels the reference
reaches through JNI (Table.orderBy, Table.groupBy, hash partition): everything
is expressed as stable argsorts, segmented reductions and scatters over
fixed-capacity arrays, so XLA can fuse and tile them (no dynamic allocations,
no data-dependent shapes — SURVEY.md §7 "hard parts" #1/#3).

Key ideas:
- ``sort_key_passes`` turns any key column into a list of uint32 radix words,
  most-significant first, already adjusted for asc/desc and null ordering.
  A multi-column sort is then a sequence of stable argsorts over the reversed
  pass list (LSD radix over words).
- ``group_ids`` gives each live row a dense group index by sorting rows by a
  128-bit key fingerprint (two independent murmur3 streams + null pattern);
  equal keys become adjacent, segment boundaries fall where the fingerprint
  changes. Collision probability is ~n^2/2^64 per batch — the same class of
  trade cuDF's hash aggregation makes.
- ``segment_reduce`` wraps jax.ops.segment_* with null discipline (Spark
  semantics: aggregates skip nulls; all-null groups yield null).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn
from spark_rapids_tpu.exprs import hash as mh


# ---------------------------------------------------------------------------
# Orderable key normalization
# ---------------------------------------------------------------------------

def _orderable_u32_words(col: DeviceColumn) -> List[jnp.ndarray]:
    """Column -> list of uint32 words, most-significant first, such that
    lexicographic unsigned comparison of the word tuple == SQL ordering
    (ascending, nulls handled separately)."""
    t = col.dtype
    if t.is_string:
        # Bytes are already unsigned-lexicographic; zero padding sorts
        # shorter strings first, matching SQL byte ordering (strings with
        # embedded NUL bytes are the known approximation).
        data = col.data
        w = data.shape[1]
        words = []
        for i in range(0, w, 4):
            chunk = data[:, i:i + 4]
            if chunk.shape[1] < 4:
                pad = jnp.zeros((data.shape[0], 4 - chunk.shape[1]),
                                jnp.uint8)
                chunk = jnp.concatenate([chunk, pad], axis=1)
            word = (chunk[:, 0].astype(jnp.uint32) << 24) | \
                   (chunk[:, 1].astype(jnp.uint32) << 16) | \
                   (chunk[:, 2].astype(jnp.uint32) << 8) | \
                   chunk[:, 3].astype(jnp.uint32)
            words.append(word)
        return words
    if t.is_floating:
        if t.name == "float32":
            bits = jnp.asarray(col.data, jnp.float32).view(jnp.uint32)
            # IEEE total order: flip all bits if negative else flip sign.
            neg = (bits >> jnp.uint32(31)) == 1
            bits = jnp.where(neg, ~bits, bits | jnp.uint32(0x80000000))
            # Spark: NaN sorts greater than everything; canonical NaN bits
            # already sort above +inf after the transform.
            return [bits]
        # float64: TPU's x64 emulation has no 64-bit bitcast, so the key
        # stays in the FLOAT domain (argsort compares f64 directly):
        #   [nan tier (u32), value (f64, NaNs zeroed), -0/+0 tiebreak].
        x = jnp.asarray(col.data, jnp.float64)
        nan = jnp.isnan(x)
        nan_word = nan.astype(jnp.uint32)           # NaN sorts greatest
        val = jnp.where(nan, jnp.float64(0.0), x)
        negzero = (x == 0.0) & (1.0 / x < 0)
        zero_word = jnp.where(x == 0.0,
                              jnp.where(negzero, jnp.uint32(0),
                                        jnp.uint32(1)),
                              jnp.uint32(0))        # -0.0 before +0.0
        return [nan_word, val, zero_word]
    if t.name in ("int64", "timestamp"):
        u = col.data.astype(jnp.int64).astype(jnp.uint64) ^ \
            jnp.uint64(0x8000000000000000)
        return [(u >> jnp.uint64(32)).astype(jnp.uint32),
                (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)]
    # bool/int8/16/32/date -> one word, sign-bias flip.
    u = col.data.astype(jnp.int32).astype(jnp.uint32) ^ jnp.uint32(0x80000000)
    return [u]


def sort_key_passes(col: DeviceColumn, ascending: bool,
                    nulls_first: bool) -> List[jnp.ndarray]:
    """Radix word passes for one sort key, MSW first, including the null
    ordering word. Descending keys get bit-flipped words."""
    words = _orderable_u32_words(col)
    if not ascending:
        # u32 words flip bitwise; float-domain passes flip by negation.
        words = [jnp.negative(w) if jnp.issubdtype(w.dtype, jnp.floating)
                 else ~w for w in words]
    # Null word: 0 sorts first. nulls_first -> nulls get 0, else 1-flip.
    if nulls_first:
        null_word = jnp.where(col.validity, jnp.uint32(1), jnp.uint32(0))
    else:
        null_word = jnp.where(col.validity, jnp.uint32(0), jnp.uint32(1))
    # Zero data words for nulls so null ordering is decided by null_word.
    words = [jnp.where(col.validity, w, jnp.zeros_like(w)) for w in words]
    return [null_word] + words


def _radix_perm(passes: List[jnp.ndarray], capacity: int,
                unstable_first: bool = False) -> jnp.ndarray:
    """Stable LSD radix argsort: the ONE traced implementation every
    multi-pass sort in this engine shares (full sorts, grouping, per-group
    string min/max — and through the kernel cache, the fused paths).

    ``passes`` are per-row word arrays, most significant first; the
    returned permutation orders rows by the lexicographic pass tuple.
    ``unstable_first`` relaxes tie order on the least-significant pass
    only (spark.rapids.sql.stableSort.enabled off) — every later pass
    must stay stable for multi-key correctness.

    With ``spark.rapids.sql.native.radixSort.enabled`` live, each stable
    uint32 pass runs as the native Pallas counting-sort rank
    (ops/native.py) instead of XLA's bitonic argsort — bit-identical,
    because a stable sort permutation is unique. Float-domain passes
    (the TPU f64 key path) and the relaxed unstable first pass keep the
    jnp twin."""
    from spark_rapids_tpu.ops import native
    use_native = native.kernel_enabled("radixSort")
    perm = jnp.arange(capacity, dtype=jnp.int32)
    first = True
    for words in reversed(passes):
        keyed = jnp.take(words, perm, axis=0)
        stable = not (unstable_first and first)
        if use_native and stable and keyed.dtype == jnp.uint32:
            order = native.stable_argsort_u32(keyed)
        else:
            order = jnp.argsort(keyed, stable=stable)
        perm = jnp.take(perm, order, axis=0)
        first = False
    return perm


def lex_sort_perm(passes: List[jnp.ndarray], live: jnp.ndarray,
                  capacity: int, stable: bool = True) -> jnp.ndarray:
    """Permutation sorting rows by the MSW-first word passes; dead rows
    (padding / deselected) always sort last. ``live`` is either a
    (capacity,) bool mask (row_mask) or an int32 row-count scalar."""
    if getattr(live, "ndim", 0) == 0 or np.isscalar(live):
        live = jnp.arange(capacity, dtype=jnp.int32) < live
    # Padding pass first (most significant of all): dead rows sort last.
    pad_last = jnp.where(live, jnp.uint32(0), jnp.uint32(0xFFFFFFFF))
    return _radix_perm([pad_last] + list(passes), capacity,
                       unstable_first=not stable)


# ---------------------------------------------------------------------------
# Grouping
# ---------------------------------------------------------------------------

_SEED_A = 42
_SEED_B = 0x5EED


def key_fingerprint(cols: Sequence[DeviceColumn],
                    capacity: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two independent 32-bit fingerprints of the key tuple per row.

    Null rows must differ from any value: the null pattern is mixed into the
    second stream explicitly (murmur3 passes the seed through on null, which
    would otherwise let NULL collide with unlucky values)."""
    ha = jnp.full((capacity,), np.uint32(_SEED_A), dtype=jnp.uint32)
    hb = jnp.full((capacity,), np.uint32(_SEED_B), dtype=jnp.uint32)
    for i, c in enumerate(cols):
        # Null cells may carry arbitrary data (packed row movement does not
        # zero them — rowmove.py contract); normalize so all NULLs
        # fingerprint identically. The null flag itself is mixed below.
        if c.dtype.is_string:
            data = jnp.where(c.validity[:, None], c.data,
                             jnp.zeros_like(c.data))
            lens = jnp.where(c.validity, c.lengths, 0)
            c = DeviceColumn(c.dtype, data, c.validity, lens)
        elif c.dtype.is_floating:
            # Grouping equality: -0.0 == 0.0 and NaN == NaN (Spark inserts
            # NormalizeNaNAndZero before grouping; we fold it in here).
            data = jnp.where(c.data == 0, jnp.zeros_like(c.data), c.data)
            data = jnp.where(c.validity, data, jnp.zeros_like(data))
            c = DeviceColumn(c.dtype, data, c.validity)
        else:
            data = jnp.where(c.validity, c.data, jnp.zeros_like(c.data))
            c = DeviceColumn(c.dtype, data, c.validity)
        ha = mh.hash_column(jnp, c, c.dtype, ha)
        hb = mh.hash_column(jnp, c, c.dtype, hb)
        # Mix null flag into stream B so NULL != seed-collision value.
        nullbit = jnp.where(c.validity, jnp.uint32(0),
                            jnp.uint32(0x9E3779B9 + i))
        hb = mh._fmix(jnp, hb ^ nullbit, 4)
    return ha, hb


@dataclasses.dataclass
class Grouping:
    """Result of group_ids: rows sorted so equal keys are adjacent."""

    perm: jnp.ndarray         # (capacity,) row permutation (padding last)
    group_of_sorted: jnp.ndarray  # (capacity,) dense group id per sorted row
    num_groups: jnp.ndarray   # int32 scalar
    group_leader: jnp.ndarray  # (capacity,) original row index of each
    #                            group's first sorted row (by group id)


def group_ids(batch: DeviceBatch, key_ordinals: Sequence[int]) -> Grouping:
    """Assign dense group ids over the key columns (cuDF groupBy analog)."""
    cap = batch.capacity
    cols = [batch.columns[i] for i in key_ordinals]
    ha, hb = key_fingerprint(cols, cap)
    live = batch.row_mask()
    # Sort rows by (live desc, ha, hb): padding last.
    passes = [jnp.where(live, jnp.uint32(0), jnp.uint32(0xFFFFFFFF)), ha, hb]
    perm = _radix_perm(passes, cap)
    sa = jnp.take(ha, perm, axis=0)
    sb = jnp.take(hb, perm, axis=0)
    slive = jnp.take(live, perm, axis=0)
    prev_a = jnp.concatenate([sa[:1] ^ jnp.uint32(1), sa[:-1]])
    prev_b = jnp.concatenate([sb[:1], sb[:-1]])
    new_seg = ((sa != prev_a) | (sb != prev_b)) & slive
    # First live sorted row always starts a segment.
    first_live = jnp.argmax(slive.astype(jnp.int32))
    new_seg = new_seg | (jnp.arange(cap) == first_live) & slive
    gid = jnp.cumsum(new_seg.astype(jnp.int32)) - 1
    # Padding rows go to the last slot (their writes are masked downstream).
    gid = jnp.where(slive, gid, jnp.int32(max(cap - 1, 0)))
    num_groups = jnp.sum(new_seg.astype(jnp.int32))
    # Leader: original row index of each group's first sorted row.
    leader = jnp.zeros((cap,), jnp.int32).at[
        jnp.where(new_seg, gid, cap)].set(perm, mode="drop")
    return Grouping(perm, gid, num_groups, leader)


def _seg_sum(values: jnp.ndarray, gid: jnp.ndarray,
             capacity: int) -> jnp.ndarray:
    """segment_sum with the native sorted-scan twin behind the
    ``native.segmentReduce`` gate. The native path handles exactly the
    order-free dtypes (two's-complement ints); floats always reduce
    through jax.ops — reduction order changes rounding and bit identity
    is the contract."""
    from spark_rapids_tpu.ops import native
    if native.kernel_enabled("segmentReduce"):
        out = native.segment_sum_sorted(values, gid, capacity)
        if out is not None:
            return out
    return jax.ops.segment_sum(values, gid, num_segments=capacity)


def _seg_minmax(values: jnp.ndarray, gid: jnp.ndarray, capacity: int,
                kind: str) -> jnp.ndarray:
    """segment_min/max with the native total-order-bit-domain twin
    behind the ``native.segmentReduce`` gate."""
    from spark_rapids_tpu.ops import native
    if native.kernel_enabled("segmentReduce"):
        out = native.segment_minmax_sorted(values, gid, capacity, kind)
        if out is not None:
            return out
    red = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
    return red(values, gid, num_segments=capacity)


def segment_reduce(values: jnp.ndarray, validity: jnp.ndarray,
                   gid: jnp.ndarray, capacity: int, kind: str,
                   count_also: bool = False):
    """Segmented aggregate with Spark null discipline.

    values/validity are already permuted to sorted order; gid is
    group_of_sorted (NONDECREASING — the native segmented-scan twin
    relies on it). Returns (agg (capacity,), non_null_count (capacity,)).
    ``kind``: sum | min | max.
    """
    if kind == "sum":
        masked = jnp.where(validity, values,
                           jnp.zeros_like(values))
        agg = _seg_sum(masked, gid, capacity)
    elif kind in ("min", "max"):
        if jnp.issubdtype(values.dtype, jnp.floating):
            # Spark orders NaN greatest. Reduce in the float domain with
            # NaNs masked out (bitcast-free — TPU's x64 emulation cannot
            # bitcast f64): min ignores NaN unless the group is all-NaN;
            # max is NaN whenever any valid NaN exists.
            isnan = jnp.isnan(values)
            real = validity & ~isnan
            nanv = jnp.asarray(jnp.nan, values.dtype)
            if kind == "min":
                masked = jnp.where(real, values,
                                   jnp.asarray(jnp.inf, values.dtype))
                m = _seg_minmax(masked, gid, capacity, "min")
                has_real = _seg_sum(real.astype(jnp.int32), gid,
                                    capacity) > 0
                agg = jnp.where(has_real, m, nanv)
            else:
                masked = jnp.where(real, values,
                                   jnp.asarray(-jnp.inf, values.dtype))
                m = _seg_minmax(masked, gid, capacity, "max")
                has_nan = _seg_sum((validity & isnan).astype(jnp.int32),
                                   gid, capacity) > 0
                agg = jnp.where(has_nan, nanv, m)
        else:
            masked = jnp.where(validity, values,
                               _identity_for(values.dtype, kind))
            agg = _seg_minmax(masked, gid, capacity, kind)
    else:
        raise ValueError(kind)
    counts = _seg_sum(validity.astype(jnp.int64), gid, capacity)
    return agg, counts


def segment_minmax_string(data: jnp.ndarray, lengths: jnp.ndarray,
                          validity: jnp.ndarray, gid: jnp.ndarray,
                          capacity: int, want_max: bool):
    """Per-group lexicographic min/max of a string column.

    Inputs are in group-sorted order (groups adjacent). Strategy: one more
    stable radix sort keyed by [gid, null-loses, value words] — after it the
    first row of each gid run is the winner. Returns a (data, validity,
    lengths) buffer triple indexed by group id.
    """
    col = DeviceColumn(dt.STRING, data, validity, lengths)
    words = _orderable_u32_words(col)
    if want_max:
        words = [~w for w in words]
        # Max must also prefer longer strings on equal prefix: flip the
        # length tiebreak too (zero padding already makes shorter sort
        # first ascending; flipping words flips prefix order but not the
        # implicit length order, so add an explicit length word).
        lenword = ~lengths.astype(jnp.uint32)
    else:
        lenword = lengths.astype(jnp.uint32)
    loser = jnp.where(validity, jnp.uint32(0), jnp.uint32(0xFFFFFFFF))
    words = [jnp.where(validity, w, jnp.uint32(0)) for w in words]
    lenword = jnp.where(validity, lenword, jnp.uint32(0))
    passes = [gid.astype(jnp.uint32), loser] + words + [lenword]
    perm = _radix_perm(passes, capacity)
    sorted_gid = jnp.take(gid, perm, axis=0)
    prev = jnp.concatenate([sorted_gid[:1] ^ 1, sorted_gid[:-1]])
    new_seg = sorted_gid != prev
    new_seg = new_seg | (jnp.arange(capacity) == 0)
    winner = jnp.zeros((capacity,), jnp.int32).at[
        jnp.where(new_seg, sorted_gid, capacity)].set(perm, mode="drop")
    has_valid = jax.ops.segment_sum(validity.astype(jnp.int32), gid,
                                    num_segments=capacity) > 0
    out_data = jnp.take(data, winner, axis=0)
    out_lens = jnp.take(lengths, winner, axis=0)
    out_data = jnp.where(has_valid[:, None], out_data, 0)
    out_lens = jnp.where(has_valid, out_lens, 0)
    return out_data, has_valid, out_lens


def _identity_for(dtype, kind: str):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf if kind == "min" else -jnp.inf, dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(kind == "min", dtype)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if kind == "min" else info.min, dtype)
