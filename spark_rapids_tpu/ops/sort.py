"""Sort operator (ref: GpuSortExec.scala + SortUtils.scala).

Full sort requires the whole partition as one batch (same RequireSingleBatch
restriction the reference has in v0.3); the device kernel is an LSD radix of
stable argsorts over orderable uint32 words (ops/kernels.py), which XLA
lowers to fused bitonic sorts — the TPU replacement for cuDF Table.orderBy.
"""

from __future__ import annotations

import dataclasses
import functools
import struct
from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, bucket_capacity, concat_batches)
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.exprs.base import Expression, as_device_column, \
    as_host_column
from spark_rapids_tpu.ops.base import Exec, ExecContext, Schema, timed
from spark_rapids_tpu.ops import kernels


@dataclasses.dataclass
class SortOrder:
    """One sort key (Spark SortOrder analog). Defaults: asc, nulls first —
    Spark's ASC NULLS FIRST."""

    child: Expression
    ascending: bool = True
    nulls_first: bool = True


def coalesce_to_single_batch(batches: List[DeviceBatch]) -> DeviceBatch:
    """Concatenate a partition's batches into one (RequireSingleBatch goal,
    GpuCoalesceBatches.scala:120). Jitted so the scatter storm fuses."""
    from spark_rapids_tpu.columnar.batch import jit_concat_batches
    if len(batches) == 1:
        return batches[0]
    total_cap = sum(b.capacity for b in batches)
    return jit_concat_batches(batches, bucket_capacity(total_cap))


def sort_batch(batch: DeviceBatch, orders: Sequence[SortOrder],
               stable: bool = True) -> DeviceBatch:
    """Device kernel: fully sort one batch by the sort orders. Selected
    (live) rows sort to the front, so the output is dense (sel discharged
    by the gather)."""
    passes: List[jnp.ndarray] = []
    for o in orders:
        col = as_device_column(o.child.eval(batch), batch)
        passes.extend(kernels.sort_key_passes(col, o.ascending,
                                              o.nulls_first))
    perm = kernels.lex_sort_perm(passes, batch.row_mask(), batch.capacity,
                                 stable=stable)
    return batch.gather(perm, batch.live_count())


class SortExec(Exec):
    """Per-partition full sort (global order requires a range exchange
    upstream, as in Spark)."""

    def __init__(self, child: Exec, orders: Sequence[SortOrder]):
        super().__init__(child)
        self.orders = list(orders)
        self._jit = None

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute_device(self, ctx, partition):
        from spark_rapids_tpu import config as C
        m = ctx.metrics_for(self)
        batches = list(self.children[0].execute_device(ctx, partition))
        if not batches:
            return
        single = coalesce_to_single_batch(batches)
        stable = bool(ctx.conf.get(C.STABLE_SORT))
        if self._jit is None and all(o.child.jittable for o in self.orders):
            self._jit = jax.jit(
                lambda b: sort_batch(b, self.orders, stable=stable))
        fn = self._jit or (lambda b: sort_batch(b, self.orders,
                                                stable=stable))
        with timed(m):
            out = fn(single)
        m.add("numOutputBatches", 1)
        yield out

    def execute_host(self, ctx, partition):
        hbs = list(self.children[0].execute_host(ctx, partition))
        if not hbs:
            return
        # Concat host batches column-wise.
        names = hbs[0].names
        cols = []
        for ci, c0 in enumerate(hbs[0].columns):
            data = np.concatenate([hb.columns[ci].data for hb in hbs])
            validity = np.concatenate([hb.columns[ci].validity for hb in hbs])
            cols.append(HostColumn(c0.dtype, data, validity))
        merged = HostBatch(names, cols)
        yield sort_host_batch(merged, self.orders)


def sort_host_batch(hb: HostBatch, orders: Sequence[SortOrder]) -> HostBatch:
    """Host oracle sort with Spark semantics (NaN greatest, null ordering)."""
    n = hb.num_rows
    keys = []
    for o in orders:
        col = as_host_column(o.child.eval_host(hb), hb)
        keys.append((col, o))

    def sort_key(i: int):
        parts = []
        for col, o in keys:
            valid = bool(col.validity[i])
            null_rank = 0 if (not valid) == o.nulls_first else 1
            if not valid:
                part = (null_rank, 0)
            else:
                v = col.data[i]
                if col.dtype.is_string:
                    v = bytes(v)
                elif col.dtype.is_floating:
                    # Java Double.compare total order (Spark sort
                    # semantics): -0.0 < 0.0, every NaN greatest — via
                    # the sign-flipped raw-bits key, matching the device
                    # radix sort's float-domain word transform. All NaN
                    # bit patterns (incl. sign-bit NaN) canonicalize.
                    f = float(v)
                    if np.isnan(f):
                        v = 0x7FF8000000000000
                    else:
                        bits = struct.unpack(
                            "<q", struct.pack("<d", f))[0]
                        v = bits if bits >= 0 \
                            else bits ^ 0x7FFFFFFFFFFFFFFF
                elif col.dtype.is_boolean:
                    v = bool(v)
                else:
                    v = int(v)
                part = (null_rank, _Rev(v) if not o.ascending else v)
            parts.append(part)
        return tuple(parts)

    order = sorted(range(n), key=sort_key)
    cols = [HostColumn(c.dtype, c.data[order], c.validity[order])
            for c in hb.columns]
    return HostBatch(hb.names, cols)


@functools.total_ordering
class _Rev:
    """Reverses comparison for descending host sort keys."""

    def __init__(self, v):
        self.v = v

    def __eq__(self, other):
        return self.v == other.v

    def __lt__(self, other):
        return other.v < self.v
