"""Sort operator (ref: GpuSortExec.scala + SortUtils.scala).

Full sort requires the whole partition as one batch (same RequireSingleBatch
restriction the reference has in v0.3); the device kernel is an LSD radix of
stable argsorts over orderable uint32 words (ops/kernels.py), which XLA
lowers to fused bitonic sorts — the TPU replacement for cuDF Table.orderBy.
"""

from __future__ import annotations

import dataclasses
import functools
import struct
from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, bucket_capacity, concat_batches)
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.exprs.base import Expression, as_device_column, \
    as_host_column
from spark_rapids_tpu.ops.base import (Exec, ExecContext, Schema,
    record_batch, timed)
from spark_rapids_tpu.ops import kernels


@dataclasses.dataclass
class SortOrder:
    """One sort key (Spark SortOrder analog). Defaults: asc, nulls first —
    Spark's ASC NULLS FIRST."""

    child: Expression
    ascending: bool = True
    nulls_first: bool = True


def coalesce_to_single_batch(batches: List[DeviceBatch]) -> DeviceBatch:
    """Concatenate a partition's batches into one (RequireSingleBatch goal,
    GpuCoalesceBatches.scala:120). Jitted so the scatter storm fuses."""
    from spark_rapids_tpu.columnar.batch import jit_concat_batches
    if len(batches) == 1:
        return batches[0]
    total_cap = sum(b.capacity for b in batches)
    return jit_concat_batches(batches, bucket_capacity(total_cap))


def sort_batch(batch: DeviceBatch, orders: Sequence[SortOrder],
               stable: bool = True) -> DeviceBatch:
    """Device kernel: fully sort one batch by the sort orders. Selected
    (live) rows sort to the front, so the output is dense (sel discharged
    by the gather)."""
    passes: List[jnp.ndarray] = []
    for o in orders:
        col = as_device_column(o.child.eval(batch), batch)
        passes.extend(kernels.sort_key_passes(col, o.ascending,
                                              o.nulls_first))
    perm = kernels.lex_sort_perm(passes, batch.row_mask(), batch.capacity,
                                 stable=stable)
    return batch.gather(perm, batch.live_count())


class _SpillableListSource(Exec):
    """Leaf serving an already-buffered list of catalog-registered batches
    (the sort's out-of-core staging area)."""

    def __init__(self, schema: Schema, spillables):
        super().__init__()
        self._schema = tuple(schema)
        self._spillables = spillables

    @property
    def schema(self) -> Schema:
        return self._schema

    def num_partitions(self, ctx) -> int:
        # One partition per buffered batch: the exchange's range-bounds
        # sampler reads 64 rows from EVERY partition's first batch, so
        # this shape samples the whole staged input, not just its head.
        return len(self._spillables)

    def execute_device(self, ctx, partition):
        from spark_rapids_tpu.memory.stores import PRIORITY_SHUFFLE_OUTPUT
        sb = self._spillables[partition]
        try:
            yield sb.get()
        finally:
            # Consumers abandon this generator mid-stream (the range
            # bounds sampler breaks after one batch); the staged entry
            # must drop back to spillable either way, or the whole
            # larger-than-HBM input ends up pinned ACTIVE.
            sb.release(PRIORITY_SHUFFLE_OUTPUT)

    def execute_host(self, ctx, partition):    # pragma: no cover
        raise AssertionError("device-only staging source")


def stage_spillables(ctx, child_iter):
    """Register a batch stream as catalog spillables (the out-of-core
    staging step shared by sort/window bucketing and grace joins).
    Returns (spillables, total device bytes)."""
    from spark_rapids_tpu.memory.stores import (
        PRIORITY_SHUFFLE_OUTPUT, SpillableBatch)
    spillables = []
    total_bytes = 0
    for b in child_iter:
        total_bytes += b.device_size_bytes()
        spillables.append(SpillableBatch(ctx.catalog, b,
                                         PRIORITY_SHUFFLE_OUTPUT))
    return spillables, total_bytes


def staged_exchange(spillables, schema, partitioning):
    """An exchange over already-staged spillables: the generic bucketing
    device for out-of-core operators. Sort/window feed it a
    RangePartitioning (equal keys share a bucket, buckets stream in
    range order); grace hash joins feed it a HashPartitioning over the
    join keys so BOTH sides bucket by the same key fingerprints
    (ops/join.py). ``allow_coalesce`` stays off — bucket identity is
    load-bearing for every caller."""
    from spark_rapids_tpu.parallel.exchange import ShuffleExchangeExec
    return ShuffleExchangeExec(_SpillableListSource(schema, spillables),
                               partitioning)


def out_of_core_partition(ctx, metrics, child_iter, schema,
                          split_orders: Sequence[SortOrder], batch_fn):
    """Shared out-of-core scaffold (SortExec's sample-sort shape, also
    used by partition-chunked windows): stage the partition's batches as
    catalog spillables; small partitions run ``batch_fn`` over one
    coalesced batch, larger ones range-split by ``split_orders`` through
    the exchange into bounded spillable buckets and run ``batch_fn`` per
    bucket (equal keys always share a bucket). Yields output batches."""
    from spark_rapids_tpu.memory.oom import retry_on_oom
    from spark_rapids_tpu.parallel.partitioning import RangePartitioning
    m = metrics
    spillables, total_bytes = stage_spillables(ctx, child_iter)
    if not spillables:
        return
    bucket_budget = max(ctx.catalog.device_budget // 3, 1 << 16)
    if total_bytes <= bucket_budget or not split_orders:
        batches = [sb.get() for sb in spillables]
        single = coalesce_to_single_batch(batches)
        for sb in spillables:
            sb.close()
        with timed(m):
            out = retry_on_oom(batch_fn, single)
        record_batch(m, out)
        yield out
        return
    nb = max(2, -(-total_bytes // bucket_budget))
    m.add("outOfCoreBuckets", nb)
    ex = staged_exchange(spillables, schema,
                         RangePartitioning(list(split_orders), nb))
    try:
        for p in range(nb):
            bucket = list(ex.execute_device(ctx, p))
            if not bucket:
                continue
            with timed(m):
                out = retry_on_oom(batch_fn,
                                   coalesce_to_single_batch(bucket))
            record_batch(m, out)
            yield out
    finally:
        for sb in spillables:
            sb.close()


class SortExec(Exec):
    """Per-partition full sort (global order requires a range exchange
    upstream, as in Spark).

    OUT-OF-CORE (beyond the reference's v0.3 RequireSingleBatch,
    GpuSortExec.scala:50 — SURVEY §5.7's "thing to beat"): when the
    partition exceeds a fraction of the device budget the sort becomes a
    device sample-sort via :func:`out_of_core_partition` — bounded
    buckets sort independently and stream in range order. Peak HBM is
    one bucket + one in-flight batch; the rest rides the spill tiers."""

    def __init__(self, child: Exec, orders: Sequence[SortOrder]):
        super().__init__(child)
        self.orders = list(orders)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def _sort_fn(self, ctx):
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.ops import kernel_cache as kc
        stable = bool(ctx.conf.get(C.STABLE_SORT))
        orders = list(self.orders)
        if not all(o.child.jittable for o in orders):
            return lambda b: sort_batch(b, orders, stable=stable)
        m = ctx.metrics_for(self)
        fp = kc.fingerprint(tuple(orders))
        schema_fp = kc.schema_fingerprint(self.schema)

        def fn(b: DeviceBatch) -> DeviceBatch:
            entry = kc.lookup(
                "sort", (fp, stable, schema_fp, b.capacity),
                lambda: jax.jit(
                    lambda bb: sort_batch(bb, orders, stable=stable)), m)
            return kc.call(entry, m, b)
        return fn

    def execute_device(self, ctx, partition):
        yield from out_of_core_partition(
            ctx, ctx.metrics_for(self),
            self.children[0].execute_device(ctx, partition),
            self.schema, self.orders, self._sort_fn(ctx))

    def execute_host(self, ctx, partition):
        hbs = list(self.children[0].execute_host(ctx, partition))
        if not hbs:
            return
        from spark_rapids_tpu.columnar.host import concat_host_batches
        yield sort_host_batch(concat_host_batches(hbs), self.orders)


def host_sort_indices(hb: HostBatch,
                      orders: Sequence[SortOrder]) -> np.ndarray:
    """Stable row permutation sorting ``hb`` under Spark semantics
    (float total order via sign-flipped raw bits — every NaN canonical
    and greatest, -0.0 < 0.0 — plus per-key null ordering).

    Vectorized: each order key becomes two np.lexsort planes — the
    null-rank plane (always ascending: null placement never flips with
    the key direction, matching the row-oracle this replaced) and the
    type-aware int64 code from encode_sort_key, bit-inverted for descending
    (~x reverses int64 order with no INT64_MIN overflow). np.lexsort is
    stable, so ties keep input order exactly like the python sort."""
    from spark_rapids_tpu.columnar.host import encode_sort_key
    planes = []
    for o in orders:
        col = as_host_column(o.child.eval_host(hb), hb)
        valid = np.asarray(col.validity, np.bool_)
        null_rank = (valid if o.nulls_first else ~valid).astype(np.int8)
        code = encode_sort_key(col)
        if not o.ascending:
            code = np.where(valid, ~code, np.int64(0))
        planes.append((null_rank, code))
    # np.lexsort keys run last-to-first, so emit least-significant first.
    lex = []
    for null_rank, code in reversed(planes):
        lex.append(code)
        lex.append(null_rank)
    return np.lexsort(lex)


def sort_host_batch(hb: HostBatch, orders: Sequence[SortOrder]) -> HostBatch:
    """Host sort with Spark semantics (NaN greatest, null ordering)."""
    order = host_sort_indices(hb, orders)
    return hb.take(order)


@functools.total_ordering
class _Rev:
    """Reverses comparison for descending host sort keys."""

    def __init__(self, v):
        self.v = v

    def __eq__(self, other):
        return self.v == other.v

    def __lt__(self, other):
        return other.v < self.v
