"""Compiled host-closure cache — the numpy analog of ops/kernel_cache.

The vectorized host engine builds one numpy closure per (operator kind,
expression-tree fingerprint, input schema, bind arity). Keys use the same
structural fingerprint + bind-slot normalization as the device kernel
cache (kernel_cache.fingerprint folds BindSlotExpr down to its slot and
dtype), so a plan-cache bind-only execution re-traces nothing on host
either: the closure comes back from the cache and only the bound literal
values change.

Unlike device kernels there is no compile step to amortize — what the
cache buys is (a) one expression-tree fingerprint walk per operator
instead of per batch, (b) the shared counters (hostClosureCacheHits /
hostClosureCacheMisses) that make host-path cache behavior observable
next to the device kernel cache's, and (c) one place to hang future
host-side expression compilation.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Optional, Tuple

DEFAULT_MAX_ENTRIES = 256

_LOCK = threading.RLock()
_CACHE: "OrderedDict[tuple, Callable]" = OrderedDict()


def lookup(kind: str, key_parts: Tuple, builder: Callable[[], Callable],
           metrics=None,
           max_entries: Optional[int] = None) -> Callable:
    """Return the cached closure for ``(kind, *key_parts)``, building and
    inserting it on a miss. LRU-bounded by ``max_entries`` (conf
    ``spark.rapids.sql.host.closureCache.maxEntries``)."""
    key = (kind,) + tuple(key_parts)
    with _LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _CACHE.move_to_end(key)
            if metrics is not None:
                metrics.add("hostClosureCacheHits", 1)
            return fn
    fn = builder()
    cap = DEFAULT_MAX_ENTRIES if max_entries is None else int(max_entries)
    with _LOCK:
        if metrics is not None:
            metrics.add("hostClosureCacheMisses", 1)
        _CACHE[key] = fn
        _CACHE.move_to_end(key)
        while len(_CACHE) > max(1, cap):
            _CACHE.popitem(last=False)
    return fn


def clear() -> None:
    with _LOCK:
        _CACHE.clear()


def size() -> int:
    with _LOCK:
        return len(_CACHE)
