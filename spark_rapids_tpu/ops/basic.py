"""Basic physical operators (ref: basicPhysicalOperators.scala, limit.scala,
GpuExpandExec.scala).

Project/Filter/Union/Coalesce/Range/Limits/Expand. Per-batch device kernels
are jitted once per (expression list, batch shape) via jax.jit closure
caching; the generator layer stays in Python (orchestration only).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, DeviceColumn, bucket_capacity)
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn, \
    all_valid as host_all_valid
from spark_rapids_tpu.exprs.base import (
    Expression, as_device_column, as_host_column, eval_exprs,
    eval_exprs_host)
from spark_rapids_tpu.exprs.bindslots import (
    bound_literals, device_bind_args, has_bind_slots, host_bind_args,
    resolve_bound)
from spark_rapids_tpu.exprs.nondeterministic import (
    EvalContext, eval_context, needs_eval_context)
from spark_rapids_tpu.ops import kernel_cache as kc
from spark_rapids_tpu.ops.base import (Exec, ExecContext, Schema,
    record_batch, timed)


def _project_host_closure(exprs, names):
    """Build the compiled host closure for a projection: one numpy ufunc
    pipeline pass per batch, bound literals riding as arguments."""
    def closure(hb: HostBatch, binds) -> HostBatch:
        if binds is not None:
            with bound_literals(binds):
                return eval_exprs_host(exprs, hb, names)
        return eval_exprs_host(exprs, hb, names)
    return closure


def _filter_host_closure(condition):
    """Build the compiled host closure for a filter: fused mask-then-
    gather — evaluate the condition once, AND in validity, and gather
    every column through the matrix-preserving HostColumn.filter (string
    columns keep their dense byte-matrix layout instead of decaying to
    per-row object arrays)."""
    def closure(hb: HostBatch, binds) -> HostBatch:
        if binds is not None:
            with bound_literals(binds):
                cond = as_host_column(condition.eval_host(hb), hb)
        else:
            cond = as_host_column(condition.eval_host(hb), hb)
        keep = np.asarray(cond.data, np.bool_) \
            & np.asarray(cond.validity, np.bool_)
        return hb.filter(keep)
    return closure


def _host_closure(ctx, op, kind, exprs, builder, binds):
    """Fetch the operator's compiled host closure through the host
    closure cache (ops/host_cache.py) — same fingerprint + bind-slot
    normalization as the device kernel cache, so bind-only plan-cache
    executions hit. Non-jittable expression trees (nondeterministic
    state) skip the cache like the device path does."""
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.ops import host_cache as hc
    if not all(e.jittable for e in exprs):
        return builder()
    fp = kc.fingerprint(tuple(exprs))
    schema_fp = kc.schema_fingerprint(op.children[0].schema)
    nbinds = 0 if binds is None else len(binds)
    return hc.lookup(kind, (fp, schema_fp, nbinds), builder,
                     ctx.metrics_for(op),
                     ctx.conf.get(C.HOST_CLOSURE_CACHE_MAX_ENTRIES))


def _input_file_key(op: Exec, partition: int, host: bool = False
                    ) -> Optional[str]:
    """Cache key under which this operator's (unique) descendant file scan
    publishes the current file path. Scans scope their keys by instance so
    two scans sharing a partition can't clobber each other; if this subtree
    has zero or multiple scans there is no well-defined "current input
    file" and input_file_name() yields '' (reference behavior for
    non-scan inputs, GpuInputFileBlock.scala)."""
    scans = []

    def walk(node):
        if type(node).__name__ == "FileScanExec":
            scans.append(node)
            return
        # An exchange breaks the batch<->file association: rows in a
        # post-shuffle batch mix every map partition's files, so
        # input_file_name() above one is '' (Spark behavior).
        if "Exchange" in type(node).__name__:
            return
        for ch in getattr(node, "children", ()):
            walk(ch)

    walk(op)
    if len(scans) != 1:
        return None
    prefix = "input_file_host" if host else "input_file"
    return f"{prefix}:{id(scans[0])}:{partition}"


def _contextual_device_loop(op: Exec, exprs: Sequence[Expression],
                            kernel, ctx: ExecContext, partition: int):
    """Drive ``kernel(batch)`` over the child's batches with an EvalContext
    (partition id / row base / input file) attached around each call.

    When every expression is jittable the compiled program takes the
    partition id and row base as *traced* int scalars — one compilation
    serves all partitions; the row base is carried as a device scalar with
    no host sync. Non-jittable trees run eagerly so per-batch host values
    (input_file_name) can be read at eval time.
    """
    m = ctx.metrics_for(op)
    jittable = all(e.jittable for e in exprs)
    binds = device_bind_args(ctx) if has_bind_slots(exprs) else None
    if jittable:
        def build():
            def kfn(b, pid, base, bv=()):
                with eval_context(EvalContext(pid, base)), \
                        bound_literals(bv):
                    out = kernel(b)
                return out, base + b.num_rows.astype(jnp.int64)
            return jax.jit(kfn)
        fp = kc.fingerprint(tuple(exprs))
        schema_fp = kc.schema_fingerprint(op.children[0].schema)
        pid = jnp.asarray(partition, jnp.int32)
        base = jnp.asarray(0, jnp.int64)
        for batch in op.children[0].execute_device(ctx, partition):
            entry = kc.lookup(
                "ctx-" + type(op).__name__,
                (fp, schema_fp, batch.capacity,
                 len(binds) if binds else 0), build, m)
            with timed(m):
                out, base = kc.call(entry, m, batch, pid, base,
                                    binds or ())
            record_batch(m, out)
            yield out
    else:
        base = 0
        key = _input_file_key(op, partition)
        for batch in op.children[0].execute_device(ctx, partition):
            ec = EvalContext(partition, base,
                             ctx.cache.get(key) if key else None)
            with timed(m), eval_context(ec), \
                    bound_literals(binds or ()):
                out = kernel(batch)
            base = base + batch.num_rows.astype(jnp.int64)
            record_batch(m, out)
            yield out


def _contextual_host_loop(op: Exec, kernel, ctx: ExecContext,
                          partition: int, exprs=()):
    base = 0
    key = _input_file_key(op, partition, host=True)
    binds = host_bind_args(ctx) if has_bind_slots(exprs) else ()
    for hb in op.children[0].execute_host(ctx, partition):
        ec = EvalContext(partition, base,
                         ctx.cache.get(key) if key else None)
        with eval_context(ec), bound_literals(binds):
            out = kernel(hb)
        yield out
        base += hb.num_rows


class ProjectExec(Exec):
    """Evaluate named expressions per batch (GpuProjectExec,
    basicPhysicalOperators.scala:66)."""

    def __init__(self, child: Exec,
                 projections: Sequence[Tuple[str, Expression]]):
        super().__init__(child)
        self.names = tuple(n for n, _ in projections)
        self.exprs = [e for _, e in projections]

    @property
    def schema(self) -> Schema:
        return tuple((n, e.data_type())
                     for n, e in zip(self.names, self.exprs))

    def execute_device(self, ctx, partition):
        exprs = list(self.exprs)
        if needs_eval_context(exprs):
            yield from _contextual_device_loop(
                self, exprs, lambda b: eval_exprs(exprs, b),
                ctx, partition)
            return
        m = ctx.metrics_for(self)
        jittable = all(e.jittable for e in exprs)
        fp = kc.fingerprint(tuple(exprs)) if jittable else None
        schema_fp = kc.schema_fingerprint(self.children[0].schema)
        binds = device_bind_args(ctx) if has_bind_slots(exprs) else None
        for batch in self.children[0].execute_device(ctx, partition):
            if jittable and binds is not None:
                # Bound literals ride as traced runtime inputs: one
                # compiled kernel serves every binding of these dtypes.
                def build():
                    def kfn(b, bv):
                        with bound_literals(bv):
                            return eval_exprs(exprs, b)
                    return jax.jit(kfn)
                entry = kc.lookup(
                    "project",
                    (fp, schema_fp, batch.capacity, len(binds)),
                    build, m)
                with timed(m):
                    out = kc.call(entry, m, batch, binds)
            elif jittable:
                entry = kc.lookup(
                    "project", (fp, schema_fp, batch.capacity),
                    lambda: jax.jit(lambda b: eval_exprs(exprs, b)), m)
                with timed(m):
                    out = kc.call(entry, m, batch)
            else:
                with timed(m), bound_literals(binds or ()):
                    out = eval_exprs(exprs, batch)
            # Projection preserves row count — keep the host-known hint so
            # downstream size consumers skip their device sync.
            out.rows_hint = batch.rows_hint
            record_batch(m, out)
            yield out

    def execute_host(self, ctx, partition):
        if needs_eval_context(self.exprs):
            yield from _contextual_host_loop(
                self, lambda hb: eval_exprs_host(self.exprs, hb, self.names),
                ctx, partition, self.exprs)
            return
        binds = host_bind_args(ctx) if has_bind_slots(self.exprs) else None
        fn = _host_closure(
            ctx, self, "project", self.exprs,
            lambda: _project_host_closure(list(self.exprs),
                                          tuple(self.names)),
            binds)
        for hb in self.children[0].execute_host(ctx, partition):
            yield fn(hb, binds)


class FilterExec(Exec):
    """Row filter via SELECTION VECTOR (GpuFilterExec analog).

    Rows are never moved: the condition mask ANDs into the batch's ``sel``
    (batch.py), costing one fused elementwise kernel instead of a packed
    compaction (~100-400ms/1M rows on the target chip). Downstream
    operators read liveness through ``row_mask()``; materialization
    happens at exchanges/concats/downloads."""

    def __init__(self, child: Exec, condition: Expression):
        super().__init__(child)
        self.condition = condition

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def _kernel(self, batch: DeviceBatch) -> DeviceBatch:
        cond = as_device_column(self.condition.eval(batch), batch)
        keep = cond.data & cond.validity
        return batch.with_sel(keep)

    def _host_kernel(self, hb: HostBatch) -> HostBatch:
        return _filter_host_closure(self.condition)(hb, None)

    def execute_device(self, ctx, partition):
        condition = self.condition

        def kernel(b: DeviceBatch) -> DeviceBatch:
            cond = as_device_column(condition.eval(b), b)
            return b.with_sel(cond.data & cond.validity)

        if needs_eval_context([condition]):
            yield from _contextual_device_loop(
                self, [condition], kernel, ctx, partition)
            return
        m = ctx.metrics_for(self)
        jittable = condition.jittable
        fp = kc.fingerprint(condition) if jittable else None
        schema_fp = kc.schema_fingerprint(self.children[0].schema)
        binds = device_bind_args(ctx) \
            if has_bind_slots([condition]) else None
        for batch in self.children[0].execute_device(ctx, partition):
            if jittable and binds is not None:
                def build():
                    def kfn(b, bv):
                        with bound_literals(bv):
                            return kernel(b)
                    return jax.jit(kfn)
                entry = kc.lookup(
                    "filter",
                    (fp, schema_fp, batch.capacity, len(binds)),
                    build, m)
                with timed(m):
                    out = kc.call(entry, m, batch, binds)
            elif jittable:
                entry = kc.lookup(
                    "filter", (fp, schema_fp, batch.capacity),
                    lambda: jax.jit(kernel), m)
                with timed(m):
                    out = kc.call(entry, m, batch)
            else:
                with timed(m), bound_literals(binds or ()):
                    out = kernel(batch)
            record_batch(m, out)
            yield out

    def execute_host(self, ctx, partition):
        if needs_eval_context([self.condition]):
            yield from _contextual_host_loop(
                self, self._host_kernel, ctx, partition,
                [self.condition])
            return
        binds = host_bind_args(ctx) \
            if has_bind_slots([self.condition]) else None
        fn = _host_closure(
            ctx, self, "filter", [self.condition],
            lambda: _filter_host_closure(self.condition), binds)
        for hb in self.children[0].execute_host(ctx, partition):
            yield fn(hb, binds)


class UnionExec(Exec):
    """Concatenation of children's partitions (GpuUnionExec)."""

    def __init__(self, *children: Exec):
        super().__init__(*children)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def num_partitions(self, ctx) -> int:
        return sum(c.num_partitions(ctx) for c in self.children)

    def _locate(self, ctx, partition: int):
        for c in self.children:
            n = c.num_partitions(ctx)
            if partition < n:
                return c, partition
            partition -= n
        raise IndexError(partition)

    def execute_device(self, ctx, partition):
        child, p = self._locate(ctx, partition)
        yield from child.execute_device(ctx, p)

    def execute_host(self, ctx, partition):
        child, p = self._locate(ctx, partition)
        yield from child.execute_host(ctx, p)

    def prefetch_host(self, ctx, partition):
        # Union concatenates child partition spaces, so the prefetch must
        # translate the partition index before descending. Subtrees that
        # contain a stage boundary are skipped entirely: _locate's
        # num_partitions probe could otherwise trigger an exchange
        # materialization (AQE sizing) on a prefetch thread.
        from spark_rapids_tpu.parallel.stages import is_stage_boundary

        def boundary_free(op):
            return not is_stage_boundary(op) and \
                all(boundary_free(c) for c in op.children)

        if not all(boundary_free(c) for c in self.children):
            return
        child, p = self._locate(ctx, partition)
        child.prefetch_host(ctx, p)


class CoalescePartitionsExec(Exec):
    """Reduce partition count by concatenating streams (GpuCoalesceExec)."""

    def __init__(self, child: Exec, num_partitions: int = 1):
        super().__init__(child)
        self._n = num_partitions

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def num_partitions(self, ctx) -> int:
        return min(self._n, self.children[0].num_partitions(ctx))

    def _sources(self, ctx, partition: int) -> List[int]:
        child_n = self.children[0].num_partitions(ctx)
        mine = self.num_partitions(ctx)
        return [p for p in range(child_n) if p % mine == partition]

    def execute_device(self, ctx, partition):
        for p in self._sources(ctx, partition):
            yield from self.children[0].execute_device(ctx, p)

    def execute_host(self, ctx, partition):
        for p in self._sources(ctx, partition):
            yield from self.children[0].execute_host(ctx, p)


class RangeExec(Exec):
    """range(start, end, step) source (GpuRangeExec,
    basicPhysicalOperators.scala:190)."""

    def __init__(self, start: int, end: int, step: int = 1,
                 num_partitions: int = 1, batch_rows: int = 1 << 20,
                 name: str = "id"):
        super().__init__()
        assert step != 0
        self.start, self.end, self.step = start, end, step
        self._parts = num_partitions
        self.batch_rows = batch_rows
        self._name = name

    @property
    def schema(self) -> Schema:
        return ((self._name, dt.INT64),)

    def num_partitions(self, ctx) -> int:
        return self._parts

    def _bounds(self, partition: int) -> Tuple[int, int]:
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self._parts)
        lo = min(per * partition, total)
        hi = min(lo + per, total)
        return lo, hi

    def execute_device(self, ctx, partition):
        lo, hi = self._bounds(partition)
        cap = bucket_capacity(min(self.batch_rows, max(hi - lo, 1)))
        idx = lo
        while idx < hi:
            n = min(cap, hi - idx)
            base = self.start + idx * self.step
            data = base + jnp.arange(cap, dtype=jnp.int64) * self.step
            validity = jnp.arange(cap, dtype=jnp.int32) < n
            data = jnp.where(validity, data, 0)
            col = DeviceColumn(dt.INT64, data, validity)
            yield DeviceBatch((col,), jnp.asarray(n, jnp.int32))
            idx += n

    def execute_host(self, ctx, partition):
        lo, hi = self._bounds(partition)
        idx = lo
        while idx < hi:
            n = min(self.batch_rows, hi - idx)
            base = self.start + idx * self.step
            data = base + np.arange(n, dtype=np.int64) * self.step
            col = HostColumn(dt.INT64, data, host_all_valid(n))
            yield HostBatch((self._name,), [col])
            idx += n


class LocalLimitExec(Exec):
    """Per-partition head(n) (GpuLocalLimitExec, limit.scala)."""

    def __init__(self, child: Exec, limit: int):
        super().__init__(child)
        # A plain int, or a bindslots.BindValue slot the plan cache
        # hoisted: resolved per execution against ctx's binding vector.
        self.limit = limit

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute_device(self, ctx, partition):
        remaining = int(resolve_bound(self.limit, ctx))
        for batch in self.children[0].execute_device(ctx, partition):
            if remaining <= 0:
                break
            out = batch.head(remaining)
            # Advance the python-side budget. A host-known live count
            # (sort/shuffle outputs carry rows_hint) avoids the device
            # scalar pull the reference's limit pays per batch.
            if batch.rows_hint is not None:
                taken = min(batch.rows_hint, remaining)
                out.rows_hint = taken
            else:
                taken = int(out.live_count())
            remaining -= taken
            yield out

    def execute_host(self, ctx, partition):
        remaining = int(resolve_bound(self.limit, ctx))
        for hb in self.children[0].execute_host(ctx, partition):
            if remaining <= 0:
                break
            n = min(remaining, hb.num_rows)
            cols = [HostColumn(c.dtype, c.data[:n], c.validity[:n])
                    for c in hb.columns]
            remaining -= n
            yield HostBatch(hb.names, cols)


class GlobalLimitExec(Exec):
    """Single-partition global limit; expects a 1-partition child
    (GpuGlobalLimitExec)."""

    def __init__(self, child: Exec, limit: int):
        super().__init__(child)
        self.limit = limit

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def execute_device(self, ctx, partition):
        inner = LocalLimitExec(self.children[0], self.limit)
        yield from inner.execute_device(ctx, partition)

    def execute_host(self, ctx, partition):
        inner = LocalLimitExec(self.children[0], self.limit)
        yield from inner.execute_host(ctx, partition)


class ExpandExec(Exec):
    """GROUPING SETS expansion (GpuExpandExec.scala): each input row is
    emitted once per projection list."""

    def __init__(self, child: Exec,
                 projections: Sequence[Sequence[Expression]],
                 names: Sequence[str]):
        super().__init__(child)
        self.projections = [list(p) for p in projections]
        self.names = tuple(names)

    @property
    def schema(self) -> Schema:
        return tuple((n, e.data_type())
                     for n, e in zip(self.names, self.projections[0]))

    def execute_device(self, ctx, partition):
        for batch in self.children[0].execute_device(ctx, partition):
            for proj in self.projections:
                yield eval_exprs(proj, batch)

    def execute_host(self, ctx, partition):
        for hb in self.children[0].execute_host(ctx, partition):
            for proj in self.projections:
                yield eval_exprs_host(proj, hb, self.names)
