"""Window functions (ref: GpuWindowExec.scala:92 + GpuWindowExpression.scala
823 LoC — partition/order windows via cuDF rolling aggs, re-designed as
sorted segmented scans for TPU).

Device kernel per batch (whole partition required single-batch, like the
reference's window exec):
  1. radix-sort rows by (partition fingerprint, order keys) — reuses
     ops/kernels.py passes, so partitions become contiguous segments with
     rows in frame order;
  2. segment/peer boundary masks drive everything else:
     - row_number/rank/dense_rank from boundary cumsums,
     - lead/lag as global shifts masked at partition edges,
     - aggregates as segment reductions broadcast back, segmented running
       scans (cumsum minus segment-start), or rows-frame sliding windows
       (cumsum differences clamped to the segment);
  3. results scatter back to the original row order.

Frames supported (matching the v0.3 reference's envelope,
GpuWindowExpression.scala:100-151): whole-partition (no order), RANGE
UNBOUNDED PRECEDING..CURRENT ROW with peer (tie) semantics — Spark's
default frame — and ROWS frames with bounded preceding/following.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn, \
    all_valid as host_all_valid
from spark_rapids_tpu.exprs.base import Expression, as_device_column, \
    as_host_column
from spark_rapids_tpu.ops.base import Exec, ExecContext, Schema, timed
from spark_rapids_tpu.ops import kernels
from spark_rapids_tpu.ops.sort import SortOrder, coalesce_to_single_batch

UNBOUNDED = None


@dataclasses.dataclass
class WindowFrame:
    """Frame bounds; None = unbounded. Spark's default (RANGE
    UNBOUNDED..CURRENT with peers) is ``running=True``.

    ``range_interval=True`` makes preceding/following VALUE offsets over
    the single integer-typed order column (date days / timestamp micros)
    instead of row counts — the reference's RANGE-interval frame envelope
    (GpuWindowExpression.scala:114-151: one non-null date/time order
    column, ascending, day intervals)."""

    preceding: Optional[int] = UNBOUNDED
    following: Optional[int] = 0
    running_with_peers: bool = False
    range_interval: bool = False


@dataclasses.dataclass
class WindowSpec:
    partition_by: List[Expression]
    order_by: List[SortOrder]


class WindowFunction:
    """One window expression: fn(sorted ctx) -> (data, validity)."""

    def result_type(self) -> dt.DataType:
        raise NotImplementedError


@dataclasses.dataclass
class RowNumber(WindowFunction):
    def result_type(self):
        return dt.INT32


@dataclasses.dataclass
class Rank(WindowFunction):
    def result_type(self):
        return dt.INT32


@dataclasses.dataclass
class DenseRank(WindowFunction):
    def result_type(self):
        return dt.INT32


@dataclasses.dataclass
class Lead(WindowFunction):
    child: Expression
    offset: int = 1

    def result_type(self):
        return self.child.data_type()


@dataclasses.dataclass
class Lag(WindowFunction):
    child: Expression
    offset: int = 1

    def result_type(self):
        return self.child.data_type()


@dataclasses.dataclass
class WindowAgg(WindowFunction):
    """sum/count/min/max/avg over the window frame."""

    kind: str                   # sum | count | min | max | avg
    child: Optional[Expression]
    frame: WindowFrame = dataclasses.field(default_factory=WindowFrame)

    def result_type(self):
        if self.kind == "count":
            return dt.INT64
        if self.kind == "avg":
            return dt.FLOAT64
        t = self.child.data_type()
        if self.kind == "sum":
            return dt.FLOAT64 if t.is_floating else dt.INT64
        return t


@dataclasses.dataclass
class WindowExprSpec:
    name: str
    fn: WindowFunction
    spec: WindowSpec


# ---------------------------------------------------------------------------
# Device kernel
# ---------------------------------------------------------------------------

def _sorted_frame(batch: DeviceBatch, spec: WindowSpec):
    """Sort rows into (partition, order) frame; return sort context."""
    cap = batch.capacity
    live = batch.row_mask()
    pcols = [as_device_column(e.eval(batch), batch)
             for e in spec.partition_by]
    ha, hb = kernels.key_fingerprint(pcols, cap) if pcols else (
        jnp.zeros((cap,), jnp.uint32), jnp.zeros((cap,), jnp.uint32))
    passes = [jnp.where(live, jnp.uint32(0), jnp.uint32(0xFFFFFFFF)),
              ha, hb]
    order_word_counts = []
    for o in spec.order_by:
        col = as_device_column(o.child.eval(batch), batch)
        words = kernels.sort_key_passes(col, o.ascending, o.nulls_first)
        order_word_counts.append(len(words))
        passes.extend(words)
    perm = jnp.arange(cap, dtype=jnp.int32)
    for words in reversed(passes):
        keyed = jnp.take(words, perm, axis=0)
        order = jnp.argsort(keyed, stable=True)
        perm = jnp.take(perm, order, axis=0)
    s_live = jnp.take(live, perm, axis=0)
    s_ha = jnp.take(ha, perm, axis=0)
    s_hb = jnp.take(hb, perm, axis=0)
    # Partition boundary at sorted position i (first row of a partition).
    prev_a = jnp.concatenate([s_ha[:1] ^ jnp.uint32(1), s_ha[:-1]])
    prev_b = jnp.concatenate([s_hb[:1], s_hb[:-1]])
    new_part = ((s_ha != prev_a) | (s_hb != prev_b) |
                (jnp.arange(cap) == 0)) & s_live
    # Peer boundary: partition boundary OR any order key differs.
    new_peer = new_part
    if spec.order_by:
        off = 3
        for o, nw in zip(spec.order_by, order_word_counts):
            for wi in range(nw):
                w = passes[off + wi]
                sw = jnp.take(w, perm, axis=0)
                pw = jnp.concatenate([sw[:1], sw[:-1]])
                new_peer = new_peer | ((sw != pw) & s_live)
            off += nw
    return perm, s_live, new_part, new_peer


def _segment_starts(new_part, cap):
    idx = jnp.arange(cap, dtype=jnp.int32)
    # Start index of the segment containing each row = cummax of boundary
    # positions.
    return jax.lax.cummax(jnp.where(new_part, idx, 0))


def _run_ends(boundary_next, cap):
    """For each row, the index of the last row of its run, where
    ``boundary_next[i]`` marks i as a run's last row."""
    idx = jnp.arange(cap, dtype=jnp.int32)
    marked = jnp.where(boundary_next, idx, cap)
    rev = jnp.flip(marked)
    ends = jnp.flip(jax.lax.cummin(rev))
    return jnp.clip(ends, 0, cap - 1)


def _seg_id(new_part):
    return jnp.cumsum(new_part.astype(jnp.int32)) - 1


def compute_window(batch: DeviceBatch, exprs: Sequence[WindowExprSpec]):
    """Evaluate all window expressions; returns new columns appended to the
    original batch (original row order)."""
    cap = batch.capacity
    out_cols = list(batch.columns)
    # Group specs by identical WindowSpec object to share the sort.
    for wx in exprs:
        perm, s_live, new_part, new_peer = _sorted_frame(batch, wx.spec)
        inv = jnp.zeros((cap,), jnp.int32).at[perm].set(
            jnp.arange(cap, dtype=jnp.int32))
        seg_start = _segment_starts(new_part, cap)
        idx = jnp.arange(cap, dtype=jnp.int32)
        gid = _seg_id(new_part)
        gid = jnp.where(s_live, gid, jnp.int32(max(cap - 1, 0)))
        t = wx.fn.result_type()
        if t.is_string:
            out_cols.append(_eval_one_string(batch, wx, perm, inv, s_live,
                                             new_part, gid, idx, cap))
            continue
        data, valid = _eval_one(batch, wx, perm, s_live, new_part,
                                new_peer, seg_start, gid, idx, cap)
        # Scatter back to original order: sorted position p holds original
        # row perm[p]; result for original row r is at sorted pos inv[r].
        data_orig = jnp.take(data, inv, axis=0)
        valid_orig = jnp.take(valid, inv, axis=0) & batch.row_mask()
        data_orig = jnp.where(valid_orig, data_orig.astype(t.np_dtype),
                              jnp.zeros((), t.np_dtype))
        out_cols.append(DeviceColumn(t, data_orig, valid_orig))
    return DeviceBatch(tuple(out_cols), batch.num_rows)


def _eval_one_string(batch, wx, perm, inv, s_live, new_part, gid, idx, cap):
    """String-typed window results. The variable-width payload never flows
    through the numeric window arithmetic: each branch computes, per output
    row, the ORIGINAL row index whose string is the answer, and a single
    ``DeviceColumn.gather`` moves the (bytes, lengths) rows."""
    fn = wx.fn
    col = as_device_column(fn.child.eval(batch), batch)
    if isinstance(fn, (Lead, Lag)):
        off = fn.offset if isinstance(fn, Lead) else -fn.offset
        src = idx + off
        ok = (src >= 0) & (src < cap)
        src_c = jnp.clip(src, 0, cap - 1)
        same = jnp.take(gid, src_c, axis=0) == gid
        struct = ok & same & s_live & jnp.take(s_live, src_c, axis=0)
        src_orig = jnp.take(jnp.take(perm, src_c, axis=0), inv, axis=0)
        struct_orig = jnp.take(struct, inv, axis=0)
    elif isinstance(fn, WindowAgg) and fn.kind in ("min", "max"):
        frame = fn.frame
        if not (frame.preceding is UNBOUNDED and
                frame.following is UNBOUNDED and
                not frame.running_with_peers):
            raise NotImplementedError(
                "string min/max window: whole-partition frames only")
        # Second radix sort by (partition keys, child bytes) makes each
        # partition's winner the first live row of its segment; nulls sort
        # last, so an all-null partition's head is itself null.
        spec2 = WindowSpec(wx.spec.partition_by,
                           [SortOrder(fn.child, ascending=fn.kind == "min",
                                      nulls_first=False)])
        perm2, s_live2, new_part2, _ = _sorted_frame(batch, spec2)
        inv2 = jnp.zeros((cap,), jnp.int32).at[perm2].set(
            jnp.arange(cap, dtype=jnp.int32))
        head = _segment_starts(new_part2, cap)
        src_orig = jnp.take(jnp.take(perm2, head, axis=0), inv2, axis=0)
        struct_orig = jnp.take(s_live2, inv2, axis=0)
    else:
        raise NotImplementedError(
            "string window results for %s" % type(fn).__name__)
    return col.gather(src_orig, struct_orig & batch.row_mask())


def _eval_one(batch, wx, perm, s_live, new_part, new_peer, seg_start, gid,
              idx, cap):
    fn = wx.fn
    if isinstance(fn, RowNumber):
        return (idx - seg_start + 1), s_live
    if isinstance(fn, Rank):
        # First row index of the peer run, relative to segment start.
        peer_start = jax.lax.cummax(jnp.where(new_peer, idx, 0))
        return (peer_start - seg_start + 1), s_live
    if isinstance(fn, DenseRank):
        # Count of peer boundaries within the segment up to current row.
        pb = jnp.cumsum(new_peer.astype(jnp.int32))
        pb_at_start = jnp.take(pb, seg_start, axis=0)
        return (pb - pb_at_start + 1), s_live
    if isinstance(fn, (Lead, Lag)):
        col = as_device_column(fn.child.eval(batch), batch)
        sdata = jnp.take(col.data, perm, axis=0)
        svalid = jnp.take(col.validity, perm, axis=0) & s_live
        off = fn.offset if isinstance(fn, Lead) else -fn.offset
        src = idx + off
        ok = (src >= 0) & (src < cap)
        src_c = jnp.clip(src, 0, cap - 1)
        data = jnp.take(sdata, src_c, axis=0)
        valid = jnp.take(svalid, src_c, axis=0) & ok
        # Must stay inside the same partition.
        same = jnp.take(gid, src_c, axis=0) == gid
        valid = valid & same & s_live
        return data, valid
    if isinstance(fn, WindowAgg):
        return _eval_window_agg(batch, fn, perm, s_live, new_part,
                                new_peer, seg_start, gid, idx, cap,
                                wx.spec)
    raise NotImplementedError(type(fn).__name__)


def _seg_lower_bound(oval, lo0, hi0, target, cap, inclusive):
    """Vectorized per-row binary search within [lo0, hi0): first index j
    with oval[j] >= target (inclusive=False: > target). oval is ascending
    inside each segment; bounds confine the search to the row's segment."""
    lo, hi = lo0, hi0
    for _ in range(int(np.ceil(np.log2(max(cap, 2)))) + 1):
        mid = (lo + hi) // 2
        v = jnp.take(oval, jnp.clip(mid, 0, cap - 1), axis=0)
        go_right = (v <= target) if inclusive else (v < target)
        active = lo < hi
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


def _eval_window_agg(batch, fn: WindowAgg, perm, s_live, new_part, new_peer,
                     seg_start, gid, idx, cap, spec=None):
    if fn.child is not None:
        col = as_device_column(fn.child.eval(batch), batch)
        sdata = jnp.take(col.data, perm, axis=0)
        svalid = jnp.take(col.validity, perm, axis=0) & s_live
    else:
        sdata = jnp.ones((cap,), jnp.int64)
        svalid = s_live
    frame = fn.frame
    t = fn.result_type()

    if frame.preceding is UNBOUNDED and frame.following is UNBOUNDED and \
            not frame.running_with_peers:
        # Whole partition: segment reduce, broadcast back by gid.
        return _whole_partition(fn, sdata, svalid, gid, cap)

    if frame.range_interval:
        return _eval_range_interval(batch, fn, sdata, svalid, perm,
                                    s_live, new_part, seg_start, idx,
                                    cap, spec)

    # Running / ROWS frames via cumulative sums.
    if fn.kind in ("sum", "avg", "count"):
        acc_t = jnp.float64 if t.is_floating or fn.kind == "avg" \
            else jnp.int64
        vals = jnp.where(svalid, sdata.astype(acc_t),
                         jnp.zeros((), acc_t))
        if fn.kind == "count":
            vals = svalid.astype(jnp.int64)
        cum = jnp.cumsum(vals)
        cnt = jnp.cumsum(svalid.astype(jnp.int64))

        def upto(i):     # inclusive prefix inside segment
            c = jnp.take(cum, jnp.clip(i, 0, cap - 1), axis=0)
            n = jnp.take(cnt, jnp.clip(i, 0, cap - 1), axis=0)
            zero = i < 0
            return jnp.where(zero, 0, c), jnp.where(zero, 0, n)

        if frame.running_with_peers:
            # Spark default RANGE frame: end at the LAST peer of each row.
            last_of_run = jnp.concatenate(
                [new_peer[1:], jnp.ones((1,), jnp.bool_)])
            end = _run_ends(last_of_run, cap)
        elif frame.following is UNBOUNDED:
            # to segment end
            last_of_seg = jnp.concatenate(
                [new_part[1:], jnp.ones((1,), jnp.bool_)])
            end = _run_ends(last_of_seg, cap)
        else:
            seg_end = _run_ends(jnp.concatenate(
                [new_part[1:], jnp.ones((1,), jnp.bool_)]), cap)
            end = jnp.minimum(idx + frame.following, seg_end)
        if frame.preceding is UNBOUNDED:
            start = seg_start
        else:
            start = jnp.maximum(idx - frame.preceding, seg_start)
        c_end, n_end = upto(end)
        c_before, n_before = upto(start - 1)
        # start-1 could cross into previous segment; clamp via seg_start.
        c_start0, n_start0 = upto(seg_start - 1)
        c_before = jnp.where(start - 1 < seg_start, c_start0, c_before)
        n_before = jnp.where(start - 1 < seg_start, n_start0, n_before)
        s = c_end - c_before
        n = n_end - n_before
        if fn.kind == "count":
            return s.astype(jnp.int64), s_live
        if fn.kind == "avg":
            safe = jnp.where(n > 0, n, 1)
            return s / safe.astype(jnp.float64), s_live & (n > 0)
        return s.astype(t.np_dtype), s_live & (n > 0)

    if fn.kind in ("min", "max"):
        # Segmented running min/max via associative scan with reset flag.
        if frame.preceding is not UNBOUNDED or \
                frame.following not in (0, UNBOUNDED):
            raise NotImplementedError(
                "bounded-preceding min/max window frames")
        fill = kernels._identity_for(sdata.dtype, fn.kind)
        vals = jnp.where(svalid, sdata, fill)
        if frame.following is UNBOUNDED and not frame.running_with_peers:
            return _whole_partition(fn, sdata, svalid, gid, cap)

        def combine(a, b):
            a_flag, a_val, a_n = a
            b_flag, b_val, b_n = b
            op = jnp.minimum if fn.kind == "min" else jnp.maximum
            val = jnp.where(b_flag, b_val, op(a_val, b_val))
            n = jnp.where(b_flag, b_n, a_n + b_n)
            return a_flag | b_flag, val, n

        flags = new_part
        counts = svalid.astype(jnp.int64)
        _, scanned, ns = jax.lax.associative_scan(
            combine, (flags, vals, counts))
        if frame.running_with_peers:
            last_of_run = jnp.concatenate(
                [new_peer[1:], jnp.ones((1,), jnp.bool_)])
            end = _run_ends(last_of_run, cap)
            scanned = jnp.take(scanned, end, axis=0)
            ns = jnp.take(ns, end, axis=0)
        return scanned, s_live & (ns > 0)
    raise NotImplementedError(fn.kind)


def _eval_range_interval(batch, fn: WindowAgg, sdata, svalid, perm,
                         s_live, new_part, seg_start, idx, cap, spec):
    """RANGE BETWEEN (val - preceding) AND (val + following): frame bounds
    found by per-row segment-confined binary search over the (sorted)
    order-column values, then cumsum prefix differences — the TPU
    replacement for cuDF's range rolling windows
    (GpuWindowExpression.scala:114-151's envelope: ONE non-null integer
    date/time order column, ascending)."""
    assert spec is not None and len(spec.order_by) == 1, \
        "range-interval frames require exactly one order column"
    o = spec.order_by[0]
    assert o.ascending, "range-interval frames require ascending order"
    if fn.kind not in ("sum", "avg", "count"):
        raise NotImplementedError(
            "range-interval min/max window frames")
    ocol = as_device_column(o.child.eval(batch), batch)
    oval = jnp.take(ocol.data, perm, axis=0).astype(jnp.int64)
    seg_end = _run_ends(jnp.concatenate(
        [new_part[1:], jnp.ones((1,), jnp.bool_)]), cap)
    cur = oval
    if fn.frame.preceding is UNBOUNDED:
        start = seg_start
    else:
        # first index in segment with oval >= cur - preceding
        start = _seg_lower_bound(oval, seg_start, seg_end + 1,
                                 cur - fn.frame.preceding, cap,
                                 inclusive=False)
    if fn.frame.following is UNBOUNDED:
        end = seg_end
    else:
        # last index in segment with oval <= cur + following
        end = _seg_lower_bound(oval, seg_start, seg_end + 1,
                               cur + fn.frame.following, cap,
                               inclusive=True) - 1
    t = fn.result_type()
    acc_t = jnp.float64 if t.is_floating or fn.kind == "avg" else jnp.int64
    vals = svalid.astype(jnp.int64) if fn.kind == "count" else \
        jnp.where(svalid, sdata.astype(acc_t), jnp.zeros((), acc_t))
    cum = jnp.cumsum(vals)
    cnt = jnp.cumsum(svalid.astype(jnp.int64))

    def upto(i):
        c = jnp.take(cum, jnp.clip(i, 0, cap - 1), axis=0)
        n = jnp.take(cnt, jnp.clip(i, 0, cap - 1), axis=0)
        return jnp.where(i < 0, 0, c), jnp.where(i < 0, 0, n)

    c_end, n_end = upto(end)
    c_before, n_before = upto(start - 1)
    s = c_end - c_before
    n = n_end - n_before
    empty = end < start
    s = jnp.where(empty, 0, s)
    n = jnp.where(empty, 0, n)
    if fn.kind == "count":
        return s.astype(jnp.int64), s_live
    if fn.kind == "avg":
        safe = jnp.where(n > 0, n, 1)
        return s / safe.astype(jnp.float64), s_live & (n > 0)
    return s.astype(t.np_dtype), s_live & (n > 0)


def _whole_partition(fn: WindowAgg, sdata, svalid, gid, cap):
    t = fn.result_type()
    if fn.kind == "count":
        agg = jax.ops.segment_sum(svalid.astype(jnp.int64), gid,
                                  num_segments=cap)
        return jnp.take(agg, gid, axis=0), jnp.ones((cap,), jnp.bool_)
    if fn.kind in ("sum", "avg"):
        acc_t = jnp.float64 if fn.kind == "avg" or t.is_floating \
            else jnp.int64
        agg, counts = kernels.segment_reduce(
            sdata.astype(acc_t), svalid, gid, cap, "sum")
        n = jnp.take(counts, gid, axis=0)
        s = jnp.take(agg, gid, axis=0)
        if fn.kind == "avg":
            safe = jnp.where(n > 0, n, 1)
            return s / safe.astype(jnp.float64), n > 0
        return s.astype(t.np_dtype), n > 0
    agg, counts = kernels.segment_reduce(sdata, svalid, gid, cap, fn.kind)
    return (jnp.take(agg, gid, axis=0),
            jnp.take(counts, gid, axis=0) > 0)


# ---------------------------------------------------------------------------
# Exec
# ---------------------------------------------------------------------------

class WindowExec(Exec):
    """Appends window expression columns.

    OUT-OF-CORE (beyond GpuWindowExec v0.3's RequireSingleBatch): when a
    partitioned window's input exceeds a fraction of the device budget,
    the input range-splits by the window PARTITION KEYS into bounded
    spillable buckets — equal keys always land in one bucket, so each
    bucket's windows compute independently (the partition-chunked shape
    of SURVEY §5.7). Unpartitioned (whole-table frame) windows cannot
    chunk and keep the single-batch requirement."""

    def __init__(self, child: Exec, exprs: Sequence[WindowExprSpec]):
        super().__init__(child)
        self.exprs = list(exprs)

    @property
    def schema(self) -> Schema:
        base = list(self.children[0].schema)
        for wx in self.exprs:
            base.append((wx.name, wx.fn.result_type()))
        return tuple(base)

    def _window_fn(self, ctx):
        from spark_rapids_tpu.ops import kernel_cache as kc
        m = ctx.metrics_for(self)
        exprs = list(self.exprs)
        fp = kc.fingerprint(tuple(exprs))
        schema_fp = kc.schema_fingerprint(self.children[0].schema)

        def fn(b):
            entry = kc.lookup(
                "window", (fp, schema_fp, b.capacity),
                lambda: jax.jit(
                    lambda bb: compute_window(bb, exprs)), m)
            return kc.call(entry, m, b)
        return fn

    def execute_device(self, ctx, partition):
        from spark_rapids_tpu.ops.sort import out_of_core_partition
        # Chunking splits on the window PARTITION KEYS (equal keys share
        # a bucket); unpartitioned windows pass no orders and stay
        # single-batch.
        pcols = self.exprs[0].spec.partition_by if self.exprs else []
        orders = [SortOrder(c) for c in pcols]
        yield from out_of_core_partition(
            ctx, ctx.metrics_for(self),
            self.children[0].execute_device(ctx, partition),
            self.children[0].schema, orders, self._window_fn(ctx))

    # -- host engine ---------------------------------------------------------
    def execute_host(self, ctx, partition):
        hbs = list(self.children[0].execute_host(ctx, partition))
        if not hbs:
            return
        from spark_rapids_tpu.columnar.host import concat_host_batches
        hb = concat_host_batches(hbs)
        yield _host_window(hb, self.exprs, self.schema)


def _host_window_vectorized(hb: HostBatch, wx) -> "HostColumn":
    """One window expression evaluated with the lexsort/segment-boundary
    machinery of the vectorized host group-by: one stable lexsort over
    (partition codes, order-key codes), partition/peer boundary flags,
    then ranks as positions-in-segment, Lead/Lag as clamped shifted
    gathers, and frame aggregates as prefix-sum differences (the same
    cumsum-minus-segment-start shape the device kernels use). Results
    come back through the inverse permutation so output rows keep input
    order. Returns None for shapes the python oracle below still owns
    (min/max over bounded frames, string agg inputs, descending or
    null-bearing range frames)."""
    from spark_rapids_tpu.columnar.host import (encode_key,
                                                encode_sort_key)
    n = hb.num_rows
    fn = wx.fn
    if n == 0:
        return None
    pcols = [as_host_column(e.eval_host(hb), hb)
             for e in wx.spec.partition_by]
    ocols = [(as_host_column(o.child.eval_host(hb), hb), o)
             for o in wx.spec.order_by]
    ccol = None
    if isinstance(fn, (Lead, Lag, WindowAgg)) and \
            getattr(fn, "child", None) is not None:
        ccol = as_host_column(fn.child.eval_host(hb), hb)

    part_planes = []
    for c in pcols:
        part_planes.append((encode_key(c),
                            np.asarray(c.validity, np.int8)))
    okey_planes = []
    for c, o in ocols:
        valid = np.asarray(c.validity, np.bool_)
        null_rank = (valid if o.nulls_first else ~valid).astype(np.int8)
        code = encode_sort_key(c)
        if not o.ascending:
            code = np.where(valid, ~code, np.int64(0))
        okey_planes.append((null_rank, code))

    # Most-significant first; np.lexsort takes least-significant first.
    sig = []
    for code, val in part_planes:
        sig.append(code)
        sig.append(val)
    for null_rank, code in okey_planes:
        sig.append(null_rank)
        sig.append(code)
    if sig:
        order_idx = np.lexsort(tuple(reversed(sig)))
    else:
        order_idx = np.arange(n, dtype=np.int64)

    pos = np.arange(n, dtype=np.int64)
    seg_flags = np.zeros(n, np.bool_)
    seg_flags[0] = True
    for code, val in part_planes:
        sc, sv = code[order_idx], val[order_idx]
        seg_flags[1:] |= (sc[1:] != sc[:-1]) | (sv[1:] != sv[:-1])
    starts = np.flatnonzero(seg_flags).astype(np.int64)
    seg_len = np.diff(np.append(starts, n))
    seg_start = np.repeat(starts, seg_len)
    seg_end = np.repeat(starts + seg_len - 1, seg_len)
    r_local = pos - seg_start

    change = seg_flags.copy()
    for null_rank, code in okey_planes:
        snr, sc = null_rank[order_idx], code[order_idx]
        change[1:] |= (snr[1:] != snr[:-1]) | (sc[1:] != sc[:-1])
    rb = np.flatnonzero(change).astype(np.int64)
    run_len = np.diff(np.append(rb, n))
    peer_start = np.repeat(rb, run_len)
    peer_end = np.repeat(rb + run_len - 1, run_len)

    inv = np.empty(n, np.int64)
    inv[order_idx] = pos

    def out_numeric(t, data, validity):
        return HostColumn(t, np.where(validity, data, 0)
                          .astype(t.np_dtype),
                          np.asarray(validity, np.bool_)).take(inv)

    t = fn.result_type()
    if isinstance(fn, RowNumber):
        return out_numeric(t, r_local + 1, host_all_valid(n))
    if isinstance(fn, DenseRank):
        d = np.cumsum(change)
        dense = d - np.repeat(d[starts], seg_len) + 1
        return out_numeric(t, dense, host_all_valid(n))
    if isinstance(fn, Rank):
        return out_numeric(t, peer_start - seg_start + 1,
                           host_all_valid(n))
    if isinstance(fn, (Lead, Lag)):
        off = fn.offset if isinstance(fn, Lead) else -fn.offset
        tgt = pos + off
        inrange = (tgt >= seg_start) & (tgt <= seg_end)
        idx = np.where(inrange, order_idx[np.clip(tgt, 0, n - 1)],
                       np.int64(-1))
        return ccol.take(idx, null_on_negative=True).take(inv)
    if not isinstance(fn, WindowAgg):
        return None

    frame = fn.frame
    kind = fn.kind
    if ccol is not None and ccol.dtype.is_string and kind != "count":
        return None
    # Frame bounds as global [lo, hi] row ranges per row.
    if frame.running_with_peers:
        lo, hi = seg_start, peer_end
    elif frame.preceding is UNBOUNDED and frame.following is UNBOUNDED:
        lo, hi = seg_start, seg_end
    elif frame.range_interval:
        if not ocols:
            return None
        oc, oo = ocols[0]
        if (not oo.ascending or oc.dtype.is_string
                or not np.asarray(oc.validity, np.bool_).all()):
            return None
        ov = np.asarray(oc.data, np.float64)[order_idx]
        cur = ov                                  # current row's value
        lo = seg_start.copy()
        hi = seg_end.copy()
        for s0, sl in zip(starts.tolist(), seg_len.tolist()):
            s1 = s0 + sl
            vals_seg = ov[s0:s1]
            if frame.preceding is not UNBOUNDED:
                lo[s0:s1] = s0 + np.searchsorted(
                    vals_seg, cur[s0:s1] - frame.preceding, "left")
            if frame.following is not UNBOUNDED:
                hi[s0:s1] = s0 + np.searchsorted(
                    vals_seg, cur[s0:s1] + frame.following, "right") - 1
    else:
        lo = seg_start if frame.preceding is UNBOUNDED else \
            np.maximum(seg_start, pos - frame.preceding)
        hi = seg_end if frame.following is UNBOUNDED else \
            np.minimum(seg_end, pos + frame.following)

    empty = hi < lo
    loc = np.clip(lo, 0, n)
    hic = np.clip(hi + 1, 0, n)

    def prefix(x):
        return np.concatenate([np.zeros(1, x.dtype), np.cumsum(x)])

    if ccol is not None:
        cvalid = np.asarray(ccol.validity, np.bool_)[order_idx]
    else:
        cvalid = host_all_valid(n)
    Pc = prefix(cvalid.astype(np.int64))
    cnt = np.where(empty, 0, Pc[hic] - Pc[loc])

    if kind == "count":
        total = np.where(empty, 0, hi - lo + 1)
        data = cnt if ccol is not None else total
        return out_numeric(t, data, host_all_valid(n))

    if kind in ("sum", "avg"):
        x = np.asarray(ccol.data)[order_idx]
        if t.is_floating or kind == "avg":
            xf = np.where(cvalid, x.astype(np.float64), 0.0)
            if np.isnan(xf).any():
                # A prefix-sum difference leaks NaN into every frame
                # after the NaN (cumsum is global); the oracle sums
                # only the frame's own rows.
                return None
            P = prefix(xf)
        else:
            with np.errstate(over="ignore"):
                P = prefix(np.where(cvalid, x.astype(np.int64),
                                    np.int64(0)))
        s = np.where(empty, 0, P[hic] - P[loc])
        ok = cnt > 0
        if kind == "avg":
            data = np.where(ok, s / np.where(ok, cnt, 1), 0.0)
        else:
            data = np.where(ok, s, 0)
        return out_numeric(t, data, ok)

    # min/max: only the whole-segment frame vectorizes (a prefix trick
    # does not exist for range min); bounded frames stay on the oracle.
    if not (np.array_equal(lo, seg_start) and np.array_equal(hi, seg_end)):
        return None
    x = np.asarray(ccol.data)[order_idx]
    ok = np.add.reduceat(cvalid.astype(np.int64), starts) > 0
    if ccol.dtype.is_floating:
        f = x.astype(np.float64)
        nanm = cvalid & np.isnan(f)
        nonnan = cvalid & ~np.isnan(f)
        if kind == "max":
            m = np.maximum.reduceat(np.where(nonnan, f, -np.inf), starts)
            hasnan = np.add.reduceat(nanm.astype(np.int64), starts) > 0
            data_g = np.where(hasnan, np.nan, m)
        else:
            m = np.minimum.reduceat(np.where(nonnan, f, np.inf), starts)
            nncnt = np.add.reduceat(nonnan.astype(np.int64), starts)
            data_g = np.where(nncnt > 0, m, np.nan)
        data_g = np.where(ok, data_g, 0.0)
    else:
        xi64 = x.astype(np.int64)
        if kind == "max":
            data_g = np.maximum.reduceat(
                np.where(cvalid, xi64, np.iinfo(np.int64).min), starts)
        else:
            data_g = np.minimum.reduceat(
                np.where(cvalid, xi64, np.iinfo(np.int64).max), starts)
        data_g = np.where(ok, data_g, 0)
    data = np.repeat(data_g, seg_len)
    validity = np.repeat(ok, seg_len)
    return out_numeric(t, data, validity)


def _host_window(hb: HostBatch, exprs, schema) -> HostBatch:
    """Host window: vectorized per expression, python oracle fallback."""
    n = hb.num_rows
    out_cols = {i: [None] * n for i in range(len(exprs))}
    for xi, wx in enumerate(exprs):
        fast = _host_window_vectorized(hb, wx)
        if fast is not None:
            out_cols[xi] = fast
            continue
        pcols = [as_host_column(e.eval_host(hb), hb).to_list()
                 for e in wx.spec.partition_by]
        ocols = [(as_host_column(o.child.eval_host(hb), hb).to_list(), o)
                 for o in wx.spec.order_by]
        ccol = None
        if isinstance(wx.fn, (Lead, Lag, WindowAgg)) and \
                getattr(wx.fn, "child", None) is not None:
            ccol = as_host_column(wx.fn.child.eval_host(hb), hb).to_list()

        def canon(v):
            if isinstance(v, float):
                if np.isnan(v):
                    return "NaN"
                if v == 0:
                    return 0.0
            return v

        def order_key(i):
            parts = []
            for vals, o in ocols:
                v = vals[i]
                null_rank = 0 if (v is None) == o.nulls_first else 1
                if v is None:
                    parts.append((null_rank, 0))
                else:
                    k = v
                    if isinstance(v, float):
                        k = (1, 0.0) if np.isnan(v) else (0, v)
                    from spark_rapids_tpu.ops.sort import _Rev
                    parts.append((null_rank,
                                  k if o.ascending else _Rev(k)))
            return tuple(parts)

        groups = {}
        for i in range(n):
            key = tuple(canon(pc[i]) for pc in pcols)
            groups.setdefault(key, []).append(i)
        for key, idxs in groups.items():
            idxs = sorted(idxs, key=order_key)
            peers = []
            prev = object()
            for rank_i, i in enumerate(idxs):
                ok = order_key(i)
                if ok != prev:
                    peers.append(rank_i)
                    prev = ok
                else:
                    peers.append(peers[-1])
            ovals = ocols[0][0] if ocols else None
            out_cols[xi] = _host_eval_fn(
                wx.fn, idxs, peers, ccol, out_cols[xi], ovals)
    cols = list(hb.columns)
    for xi, wx in enumerate(exprs):
        if isinstance(out_cols[xi], HostColumn):
            cols.append(out_cols[xi])
        else:
            t = wx.fn.result_type()
            cols.append(HostColumn.from_values(t, out_cols[xi]))
    return HostBatch(tuple(n_ for n_, _ in schema), cols)


def _host_eval_fn(fn, idxs, peers, ccol, out, ovals=None):
    npart = len(idxs)
    if isinstance(fn, RowNumber):
        for r, i in enumerate(idxs):
            out[i] = r + 1
    elif isinstance(fn, Rank):
        for r, i in enumerate(idxs):
            out[i] = peers[r] + 1
    elif isinstance(fn, DenseRank):
        dense = []
        d = 0
        for r in range(npart):
            if r == 0 or peers[r] != peers[r - 1]:
                d += 1
            dense.append(d)
        for r, i in enumerate(idxs):
            out[i] = dense[r]
    elif isinstance(fn, (Lead, Lag)):
        off = fn.offset if isinstance(fn, Lead) else -fn.offset
        for r, i in enumerate(idxs):
            s = r + off
            out[i] = ccol[idxs[s]] if 0 <= s < npart else None
    elif isinstance(fn, WindowAgg):
        for r, i in enumerate(idxs):
            frame = fn.frame
            if frame.running_with_peers:
                hi = r
                while hi + 1 < npart and peers[hi + 1] == peers[r]:
                    hi += 1
                lo = 0
            elif frame.preceding is UNBOUNDED and \
                    frame.following is UNBOUNDED:
                lo, hi = 0, npart - 1
            elif frame.range_interval:
                cur = ovals[i]
                lo, hi = 0, npart - 1
                if frame.preceding is not UNBOUNDED:
                    lo = npart
                    for s in range(npart):
                        if ovals[idxs[s]] >= cur - frame.preceding:
                            lo = s
                            break
                if frame.following is not UNBOUNDED:
                    hi = -1
                    for s in range(npart - 1, -1, -1):
                        if ovals[idxs[s]] <= cur + frame.following:
                            hi = s
                            break
            else:
                lo = 0 if frame.preceding is UNBOUNDED else \
                    max(0, r - frame.preceding)
                hi = npart - 1 if frame.following is UNBOUNDED else \
                    min(npart - 1, r + frame.following)
            vals = [1 if ccol is None else ccol[idxs[s]]
                    for s in range(lo, hi + 1)]
            nn = [v for v in vals if v is not None]
            if fn.kind == "count":
                out[i] = len(nn) if ccol is not None else len(vals)
            elif not nn:
                out[i] = None
            elif fn.kind == "sum":
                out[i] = float(np.sum(np.asarray(nn, np.float64))) \
                    if fn.result_type().is_floating else int(sum(nn))
            elif fn.kind == "avg":
                out[i] = float(np.sum(np.asarray(nn, np.float64)) / len(nn))
            elif fn.kind == "min":
                non_nan = [v for v in nn if not (
                    isinstance(v, float) and np.isnan(v))]
                out[i] = min(non_nan) if non_nan else float("nan")
            elif fn.kind == "max":
                has_nan = any(isinstance(v, float) and np.isnan(v)
                              for v in nn)
                out[i] = float("nan") if has_nan else max(nn)
    return out
