"""Whole-stage kernel fusion (the WholeStageCodegen / GpuCoalesceBatches
analog for this engine).

A ``FusedStageExec`` replaces a maximal run of contiguous, row-local,
jittable device operators (Project, Filter, LocalLimit, Expand — see
plan/fusion.py for the stage-break rules) with ONE exec whose per-batch
body is the composition of the member kernels, compiled as a single XLA
program through the process-global kernel cache. A Project->Filter->Project
chain is one dispatch instead of three, and nothing materializes between
the steps — the filter's selection vector flows straight into the next
projection inside the fused program.

LocalLimit is stateful across batches (a per-partition row budget); the
fused kernel threads the remaining budgets through as TRACED int32 scalars,
so one compilation serves the whole partition stream with no host sync.
Expand is 1->K: the fused body flat-maps, so a stage containing an Expand
returns K output batches per input batch (all from the same dispatch).

The member execs keep their original child links: the host-engine path and
``explain`` fallback reporting still see the unfused chain, and disabling
``spark.rapids.sql.stageFusion.enabled`` restores the original plan shape
exactly.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceBatch
from spark_rapids_tpu.exprs.base import as_device_column, eval_exprs
from spark_rapids_tpu.exprs.bindslots import (
    bound_literals, device_bind_args, has_bind_slots, resolve_bound)
from spark_rapids_tpu.ops import kernel_cache as kc
from spark_rapids_tpu.ops.base import (Exec, ExecContext, Schema,
    record_batch, timed)


def _stage_specs(ops: Sequence[Exec]) -> List[Tuple[str, object]]:
    """Extract pure kernel descriptors from the member execs. The fused
    kernel closes over these (expression lists, limits), never over the
    exec objects — a cached kernel must not pin the plan subtree."""
    from spark_rapids_tpu.ops.basic import (
        ExpandExec, FilterExec, LocalLimitExec, ProjectExec)
    specs: List[Tuple[str, object]] = []
    nlimits = 0
    for op in ops:
        if isinstance(op, ProjectExec):
            specs.append(("project", tuple(op.exprs)))
        elif isinstance(op, FilterExec):
            specs.append(("filter", op.condition))
        elif isinstance(op, LocalLimitExec):
            specs.append(("limit", nlimits))
            nlimits += 1
        elif isinstance(op, ExpandExec):
            specs.append(("expand", tuple(tuple(p)
                                          for p in op.projections)))
        else:  # pragma: no cover - planner guards the member set
            raise TypeError(f"unfusible op {type(op).__name__}")
    return specs


def _spec_exprs(specs: Sequence[Tuple[str, object]]):
    """Every expression the fused stage evaluates (bind-slot probe)."""
    out = []
    for kind, payload in specs:
        if kind == "project":
            out.extend(payload)
        elif kind == "filter":
            out.append(payload)
        elif kind == "expand":
            out.extend(e for proj in payload for e in proj)
    return out


def _build_fused(specs: Sequence[Tuple[str, object]]):
    """Compose the member kernels into one batch->batches function.

    Signature: ``fused(batch, rems, binds) -> (outputs, rems_out)``
    where ``rems`` is a tuple of int32 scalars — one remaining-row
    budget per LocalLimit member — and ``binds`` the execution's bound
    literals (empty when the stage has no bind slots), both threaded
    through the trace as runtime inputs so one compilation serves the
    whole partition stream AND every literal binding."""

    def fused(batch: DeviceBatch, rems, binds=()):
        with bound_literals(binds):
            return _fused_body(batch, rems)

    def _fused_body(batch: DeviceBatch, rems):
        outs = [batch]
        rems = list(rems)
        for kind, payload in specs:
            if kind == "project":
                outs = [eval_exprs(payload, b) for b in outs]
            elif kind == "filter":
                nxt = []
                for b in outs:
                    cond = as_device_column(payload.eval(b), b)
                    nxt.append(b.with_sel(cond.data & cond.validity))
                outs = nxt
            elif kind == "expand":
                outs = [eval_exprs(proj, b)
                        for b in outs for proj in payload]
            else:  # limit
                i = payload
                r = rems[i]
                nxt = []
                for b in outs:
                    ob = b.head(r)
                    r = r - ob.live_count()
                    nxt.append(ob)
                rems[i] = r
                outs = nxt
        return tuple(outs), tuple(rems)

    return fused


class FusedStageExec(Exec):
    """One fused device stage. ``ops`` are the member execs in execution
    order (ops[0] innermost / applied first); ``source`` feeds the stage
    and is also ops[0]'s (original) child."""

    def __init__(self, ops: Sequence[Exec], source: Exec):
        super().__init__(source)
        self.ops = list(ops)
        self._specs = _stage_specs(self.ops)
        from spark_rapids_tpu.ops.basic import LocalLimitExec
        self._limits = [op.limit for op in self.ops
                        if isinstance(op, LocalLimitExec)]
        self._pure_project = all(k == "project" for k, _ in self._specs)
        self._fp = kc.fingerprint(tuple(self._specs))
        self._has_binds = has_bind_slots(_spec_exprs(self._specs))

    @property
    def schema(self) -> Schema:
        return self.ops[-1].schema

    @property
    def name(self) -> str:
        inner = "->".join(type(o).__name__ for o in self.ops)
        return f"FusedStageExec[{inner}]"

    def execute_device(self, ctx: ExecContext, partition: int):
        m = ctx.metrics_for(self)
        m.values.setdefault("numFusedStages", 1)
        m.values.setdefault("numFusedOps", len(self.ops))
        schema_fp = kc.schema_fingerprint(self.children[0].schema)
        rems = tuple(jnp.asarray(int(resolve_bound(n, ctx)), jnp.int32)
                     for n in self._limits)
        binds = device_bind_args(ctx) if self._has_binds else ()
        specs = self._specs
        for batch in self.children[0].execute_device(ctx, partition):
            entry = kc.lookup(
                "fused-stage",
                (self._fp, schema_fp, batch.capacity, len(binds)),
                lambda: jax.jit(_build_fused(specs)), m)
            with timed(m):
                outs, rems = kc.call(entry, m, batch, rems, binds)
            for out in outs:
                if self._pure_project:
                    # Row count unchanged by pure projection chains —
                    # keep the host-known hint so downstream size
                    # consumers skip their device sync.
                    out.rows_hint = batch.rows_hint
                record_batch(m, out)
                yield out

    def execute_host(self, ctx: ExecContext, partition: int):
        # The member chain is intact (fusion never rewires the originals'
        # links beyond the stage's source), so the host engine just runs
        # the outermost original op.
        yield from self.ops[-1].execute_host(ctx, partition)
