"""Hash joins (ref: shims/spark300 GpuHashJoin.scala:50,195,
GpuShuffledHashJoinExec, GpuBroadcastHashJoinExec,
GpuBroadcastNestedLoopJoinExec.scala, GpuCartesianProductExec.scala).

TPU-first design — no hash table with chained buckets (pointer chasing is
poison on the VPU). Instead, a sort-probe join over key fingerprints:

  build side: fingerprint build keys (two murmur3 streams, ops/kernels.py),
      sort build rows by fingerprint -> contiguous key groups, plus a
      sorted fingerprint array for searching.
  probe side: fingerprint probe keys, double binary search (searchsorted
      left/right) into the sorted build fingerprints -> per-probe match
      range [lo, hi).
  expansion: total pairs = sum(hi - lo) is reduced on device, synced once,
      and rounded up to a capacity bucket (the one host sync a join costs —
      matching cuDF's join output-size computation). The expansion kernel
      maps each output slot back to its (probe, build) pair with a
      searchsorted over the running offsets — all dense vector ops.

Join sides: inner, left/right outer, full outer, left semi, left anti, plus
cross (nested loop) joins. An optional residual condition filters pairs
post-expansion (non-equi predicates), with outer-join match bookkeeping done
after the filter, like the reference's conditional join handling.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, DeviceColumn, bucket_capacity, concat_batches)
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.exprs.base import Expression, as_device_column, \
    as_host_column
from spark_rapids_tpu.ops.base import Exec, ExecContext, Schema, timed
from spark_rapids_tpu.ops import kernels
from spark_rapids_tpu.ops.sort import coalesce_to_single_batch

JOIN_TYPES = ("inner", "left", "right", "full", "semi", "anti", "cross")


# ---------------------------------------------------------------------------
# Device join kernels
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class BuiltSide:
    """Build side prepared for probing: rows sorted by key fingerprint.

    Registered as a jax pytree so whole probe steps can be jitted with the
    built side passed as a traced argument (one compile serves every
    partition).

    ``stats`` is a small int64 device vector pulled to the host ONCE per
    build (one round trip): [max_run, int_keys_ok, kmin..., kmax...].
    It powers both the FK fast path (max_run bounds the output size with
    no per-probe-batch sync) and the dense direct-address table decision.
    ``table`` (set lazily) maps dense key offsets -> build row index — the
    TPU-first replacement for a hash-table probe: one gather instead of a
    double binary search (which costs ~190ms/1M probes on this chip)."""

    batch: DeviceBatch          # rows in fingerprint-sorted order
    fp: jnp.ndarray             # (cap,) uint64 sorted fingerprints
    matchable: jnp.ndarray      # (cap,) bool: live AND non-null keys
    row_live: jnp.ndarray       # (cap,) bool: live (incl. null-key rows)
    num_rows: jnp.ndarray       # int32
    key_ordinals: Optional[List[int]] = None  # for post-match verification
    null_safe: bool = False
    max_run: Optional[jnp.ndarray] = None     # kept for mesh path compat
    stats: Optional[jnp.ndarray] = None       # int64 stats vector
    table: Optional[jnp.ndarray] = None       # dense key -> row, or None
    table_base: Optional[Tuple[int, ...]] = None   # kmin per key (host)
    table_spans: Optional[Tuple[int, ...]] = None  # span per key (host)
    host_stats: Optional[List[int]] = None    # stats pulled once (aux)

    def stats_host(self) -> Optional[List[int]]:
        """The stats vector on the host, pulled at most ONCE per build.
        A broadcast BuiltSide is shared across every probe partition; the
        r4 q3 profile showed the per-partition ``np.asarray(stats)``
        re-reads costing ~60ms each on the tunneled link."""
        if self.host_stats is None and self.stats is not None:
            self.host_stats = [int(x) for x in np.asarray(self.stats)]
        return self.host_stats


def _builtside_flatten(bs: "BuiltSide"):
    children = (bs.batch, bs.fp, bs.matchable, bs.row_live, bs.num_rows,
                bs.max_run, bs.stats, bs.table)
    aux = (tuple(bs.key_ordinals) if bs.key_ordinals is not None else None,
           bs.null_safe, bs.table_base, bs.table_spans)
    return children, aux


def _builtside_unflatten(aux, children):
    ko, ns, tb, tsp = aux
    batch, fp, matchable, row_live, num_rows, max_run, stats, table = \
        children
    return BuiltSide(batch, fp, matchable, row_live, num_rows,
                     list(ko) if ko is not None else None, ns, max_run,
                     stats, table, tb, tsp)


jax.tree_util.register_pytree_node(
    BuiltSide, _builtside_flatten, _builtside_unflatten)


def _fingerprint64(batch: DeviceBatch, key_ordinals) -> jnp.ndarray:
    ha, hb = kernels.key_fingerprint(
        [batch.columns[i] for i in key_ordinals], batch.capacity)
    return (ha.astype(jnp.uint64) << jnp.uint64(32)) | hb.astype(jnp.uint64)


def build_side(batch: DeviceBatch, key_ordinals: Sequence[int],
               null_safe: bool = False) -> BuiltSide:
    """Sort build rows by fingerprint. Rows with null keys never match (SQL
    equi-join), but stay alive for full-outer emission."""
    from spark_rapids_tpu.columnar.rowmove import gather_rows
    fp = _fingerprint64(batch, key_ordinals)
    row_live = batch.row_mask()
    matchable = row_live
    if not null_safe:
        for i in key_ordinals:
            matchable = matchable & batch.columns[i].validity
    # Unmatchable rows sort to the end with the max fingerprint sentinel
    # (padding after null-key rows). One packed gather moves every column
    # (rowmove.py); liveness is per-sorted-row, not a prefix.
    sentinel = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    key = jnp.where(matchable, fp, sentinel)
    perm = jnp.argsort(key, stable=True)
    s_live = jnp.take(row_live, perm, axis=0)
    sorted_batch = gather_rows(batch, perm.astype(jnp.int32),
                               batch.num_rows, valid_dst=s_live)
    s_fp = jnp.take(key, perm, axis=0)
    s_match = jnp.take(matchable, perm, axis=0)
    # Longest run of equal sorted fingerprints among matchable rows (the
    # sentinel run at the end is excluded via s_match).
    cap = batch.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    starts = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                              s_fp[1:] != s_fp[:-1]])
    last_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(starts, idx, 0))
    run_pos = idx - last_start
    max_run = jnp.max(jnp.where(s_match, run_pos + 1, 0))
    # Key range stats for the dense direct-address decision: all-integral
    # keys with a small combined span get a direct table (gather probe).
    int_ok = not null_safe
    mins: List[jnp.ndarray] = []
    maxs: List[jnp.ndarray] = []
    for i in key_ordinals:
        c = batch.columns[i]
        if not (c.dtype.is_integral or c.dtype.name == "date"):
            int_ok = False
            break
        v = c.data.astype(jnp.int64)
        ok = matchable & c.validity
        mins.append(jnp.min(jnp.where(ok, v, jnp.int64(2 ** 62))))
        maxs.append(jnp.max(jnp.where(ok, v, jnp.int64(-2 ** 62))))
    if not int_ok:
        mins, maxs = [], []
    stats = jnp.stack([max_run.astype(jnp.int64),
                       jnp.asarray(1 if int_ok else 0, jnp.int64)]
                      + mins + maxs) if key_ordinals else None
    # Start the device->host copy of the stats now: the stream loop reads
    # them before the first probe batch, and overlapping the pull with
    # probe-side startup hides a full link round trip.
    if stats is not None:
        try:
            stats.copy_to_host_async()
        except AttributeError:      # tracer (jit) context: no-op
            pass
    return BuiltSide(sorted_batch, s_fp, s_match, s_live,
                     batch.num_rows, list(key_ordinals), null_safe,
                     max_run, stats)


# Dense tables beyond this many entries are not worth the HBM (64 MB int32)
_DENSE_TABLE_MAX = 1 << 24


def _maybe_build_dense(built: BuiltSide, batch: DeviceBatch,
                       key_ordinals: Sequence[int]) -> None:
    """Attach a direct-address table when the (integral) build keys are
    unique and span a small dense range — every TPC-style FK dimension
    join qualifies. Probe then costs ONE gather + compare instead of a
    sorted binary search + expansion. Idempotent: a broadcast BuiltSide is
    shared across probe partitions and must build its table once."""
    if built.stats is None or built.table is not None:
        return
    st = built.stats_host()
    max_run, int_ok = st[0], st[1]
    if not int_ok or max_run > 1:
        return
    k = len(key_ordinals)
    mins, maxs = st[2:2 + k], st[2 + k:2 + 2 * k]
    if any(mx < mn for mn, mx in zip(mins, maxs)):
        return          # no matchable rows
    spans = [mx - mn + 1 for mn, mx in zip(mins, maxs)]
    total = 1
    for s in spans:
        total *= s
        if total > _DENSE_TABLE_MAX:
            return
    size = 1
    while size < total:
        size *= 2
    from spark_rapids_tpu.ops import kernel_cache as kc

    def _builder():
        def build_table(batch_, matchable, mins_, spans_, ords):
            combined = jnp.zeros((batch_.capacity,), jnp.int64)
            for i, o in enumerate(ords):
                v = batch_.columns[o].data.astype(jnp.int64) - mins_[i]
                combined = combined * spans_[i] + v
            pos = jnp.where(matchable, combined, size)
            rows = jnp.arange(batch_.capacity, dtype=jnp.int32)
            return jnp.full((size,), -1, jnp.int32).at[pos].set(
                rows, mode="drop")
        return jax.jit(build_table, static_argnames=("ords",))

    fn = kc.lookup("join-dense-build", (size,), _builder)
    # The table indexes the fingerprint-SORTED batch (built.batch) — the
    # same rows every other join path gathers from.
    built.table = fn(built.batch, built.matchable,
                     jnp.asarray(mins, jnp.int64),
                     jnp.asarray(spans, jnp.int64), tuple(key_ordinals))
    built.table_base = tuple(mins)
    built.table_spans = tuple(spans)


def _pair_keys_equal(built: BuiltSide, b_idx: jnp.ndarray,
                     probe: DeviceBatch, p_idx: jnp.ndarray,
                     probe_ordinals: Sequence[int],
                     base: jnp.ndarray) -> jnp.ndarray:
    """Verify ACTUAL key equality for candidate (probe, build) pairs.

    Fingerprint ranges are candidates only — a 64-bit collision (or a true
    fingerprint landing on the sort sentinel) would otherwise silently join
    wrong rows. The reference's cuDF hash join compares real keys after
    hashing; this is that check, vectorized over the expanded pairs.
    Float keys follow Spark join-key semantics (NaN==NaN, -0.0==0.0);
    null-safe (<=>) joins treat NULL==NULL as a match.
    """
    from spark_rapids_tpu.columnar.batch import string_repad
    eq = base
    for bo, po in zip(built.key_ordinals, probe_ordinals):
        bc = built.batch.columns[bo]
        pc = probe.columns[po]
        bv = jnp.take(bc.validity, b_idx, axis=0, mode="clip")
        pv = jnp.take(pc.validity, p_idx, axis=0, mode="clip")
        if bc.dtype.is_string:
            w = max(bc.string_width, pc.string_width)
            bcw, pcw = string_repad(bc, w), string_repad(pc, w)
            bd = jnp.take(bcw.data, b_idx, axis=0, mode="clip")
            pd = jnp.take(pcw.data, p_idx, axis=0, mode="clip")
            bl = jnp.take(bcw.lengths, b_idx, axis=0, mode="clip")
            pl = jnp.take(pcw.lengths, p_idx, axis=0, mode="clip")
            data_eq = (bl == pl) & jnp.all(bd == pd, axis=1)
        else:
            bd = jnp.take(bc.data, b_idx, axis=0, mode="clip")
            pd = jnp.take(pc.data, p_idx, axis=0, mode="clip")
            data_eq = bd == pd
            if jnp.issubdtype(bd.dtype, jnp.floating):
                data_eq = data_eq | (jnp.isnan(bd) & jnp.isnan(pd))
        if built.null_safe:
            eq = eq & ((bv & pv & data_eq) | (~bv & ~pv))
        else:
            eq = eq & bv & pv & data_eq
    return eq


def probe_ranges(built: BuiltSide, probe: DeviceBatch,
                 key_ordinals: Sequence[int], null_safe: bool = False):
    """Per-probe-row match range [lo, hi) in the sorted build side.

    With ``spark.rapids.sql.native.joinProbe.enabled`` live, the double
    binary search runs as ONE native Pallas kernel (ops/native.py:
    branchless lower+upper bound over two u32 planes) instead of two
    jnp.searchsorted dispatches — insertion points are uniquely defined,
    so the result is bit-identical."""
    from spark_rapids_tpu.ops import native
    fp = _fingerprint64(probe, key_ordinals)
    plive = probe.row_mask()
    if not null_safe:
        for i in key_ordinals:
            plive = plive & probe.columns[i].validity
    if native.kernel_enabled("joinProbe"):
        lo, hi = native.searchsorted_u64_pair(built.fp, fp)
    else:
        lo = jnp.searchsorted(built.fp, fp, side="left").astype(jnp.int32)
        hi = jnp.searchsorted(built.fp, fp, side="right").astype(jnp.int32)
    counts = jnp.where(plive, hi - lo, 0)
    return lo, counts, plive


def expand_pairs(lo: jnp.ndarray, counts: jnp.ndarray, out_cap: int,
                 probe_cap: int):
    """Map output slots to (probe_row, build_row) pairs.

    offsets = exclusive cumsum(counts); slot s belongs to probe row
    p = upper_bound(offsets, s) - 1 and build row lo[p] + (s - offsets[p]).

    Returns (p, b, valid, num_rows, overflowed): when the true pair count
    exceeds ``out_cap`` (callers that cannot sync the exact total, e.g. the
    mesh-collective join), num_rows clamps to out_cap and ``overflowed``
    flags the truncation so callers can surface it instead of silently
    dropping pairs.
    """
    offsets = jnp.cumsum(counts) - counts          # exclusive
    total = jnp.sum(counts)
    num_rows = jnp.minimum(total, out_cap).astype(jnp.int32)
    slots = jnp.arange(out_cap, dtype=jnp.int32)
    p = (jnp.searchsorted(offsets, slots, side="right") - 1).astype(jnp.int32)
    p = jnp.clip(p, 0, probe_cap - 1)
    within = slots - jnp.take(offsets, p, axis=0)
    b = jnp.take(lo, p, axis=0) + within.astype(jnp.int32)
    valid = slots < num_rows
    return p, b, valid, num_rows, total > out_cap


def _gather_cols(batch: DeviceBatch, rows: jnp.ndarray,
                 valid: jnp.ndarray, null_out: jnp.ndarray = None):
    """Gather columns at ``rows`` (out-capacity positions); ``null_out``
    marks slots that must become NULL (outer-join no-match sides)."""
    cols = []
    for c in batch.columns:
        dst_valid = jnp.take(c.validity, rows, axis=0, mode="clip") & valid
        if null_out is not None:
            dst_valid = dst_valid & ~null_out
        cols.append(c.gather(rows, dst_valid))
    return cols


def _join_schema(left: Schema, right: Schema, join_type: str) -> Schema:
    if join_type in ("semi", "anti"):
        return left
    return tuple(left) + tuple(right)


class _JoinKernelMixin:
    """Shared device join logic over a built (single-batch) build side and a
    streamed probe side. Subclasses decide which input is which."""

    # Fast path bound: with max_run <= this, output capacity is taken as
    # probe_cap * max_run with NO per-probe-batch size sync. Beyond it the
    # padding waste outweighs the saved round trip.
    _FAST_PATH_MAX_RUN = 4

    def _join_fp(self):
        """Structural identity of this join's emit semantics: everything
        ``_emit_expanded`` reads off ``self`` (join type + condition).
        Execs with equal fingerprints share one compiled probe/emit
        program through the process-global kernel cache."""
        from spark_rapids_tpu.ops import kernel_cache as kc
        fp = getattr(self, "_join_fp_cache", None)
        if fp is None:
            fp = self._join_fp_cache = (
                type(self).__name__, self.join_type,
                kc.fingerprint(self.condition))
        return fp

    def _probe_jit_fn(self):
        """Jitted probe step from the process-global cache: fingerprint
        search + expansion + gathers fused into a single device program
        (one dispatch per probe batch instead of dozens of eager
        primitives). BuiltSide is a pytree argument, so all partitions —
        and all execs with the same join shape — share the compile."""
        from spark_rapids_tpu.ops import kernel_cache as kc

        def build():
            clone = kc.detached_clone(self)

            def step(built, pbatch, out_cap, build_is_right, probe_keys):
                lo, counts, plive = probe_ranges(built, pbatch,
                                                 list(probe_keys),
                                                 built.null_safe)
                return clone._emit_expanded(
                    built, pbatch, lo, counts, plive, out_cap,
                    build_is_right, list(probe_keys))
            return jax.jit(
                step, static_argnames=("out_cap", "build_is_right",
                                       "probe_keys"))
        return kc.lookup("join-probe", self._join_fp(), build)

    def _emit_jit_fn(self):
        """Jitted expansion for the synced (max_run > fast bound) path: the
        ranges were already computed eagerly to size the output, so this
        variant takes them as traced arguments instead of re-hashing the
        probe keys and re-searching the build fingerprints."""
        from spark_rapids_tpu.ops import kernel_cache as kc

        def build():
            clone = kc.detached_clone(self)

            def step(built, pbatch, lo, counts, plive, out_cap,
                     build_is_right, probe_keys):
                return clone._emit_expanded(
                    built, pbatch, lo, counts, plive, out_cap,
                    build_is_right, list(probe_keys))
            return jax.jit(
                step, static_argnames=("out_cap", "build_is_right",
                                       "probe_keys"))
        return kc.lookup("join-emit", self._join_fp(), build)

    def _dense_step(self, built: BuiltSide, pbatch: DeviceBatch,
                    probe_keys, build_is_right: bool):
        """Direct-address probe: ONE table gather decides every probe row's
        build match (unique integral build keys — the FK dimension join).
        Emits a selection-vector batch: no expansion, no output-size sync,
        no compaction. ~45ms per 1M-row probe batch on this chip vs ~1.2s
        through the sorted-search path."""
        from spark_rapids_tpu.columnar.rowmove import gather_rows
        jt = self.join_type
        cond = self.condition
        base, spans = built.table_base, built.table_spans
        size = built.table.shape[0]
        plive = pbatch.row_mask()
        combined = jnp.zeros((pbatch.capacity,), jnp.int64)
        inrange = plive
        for i, o in enumerate(probe_keys):
            c = pbatch.columns[o]
            v = c.data.astype(jnp.int64)
            inrange = inrange & c.validity & (v >= base[i]) & \
                (v < base[i] + spans[i])
            combined = combined * spans[i] + (v - base[i])
        idx = jnp.clip(combined, 0, size - 1)
        pos = jnp.take(built.table, idx, axis=0)
        found = inrange & (pos >= 0)
        if jt in ("semi", "anti") and cond is None:
            keep = found if jt == "semi" else ~found
            return pbatch.with_sel(keep & plive)
        bsafe = jnp.clip(pos, 0, built.batch.capacity - 1)
        build_out = gather_rows(built.batch, bsafe, pbatch.num_rows,
                                valid_dst=found)
        if build_is_right:
            cols = tuple(pbatch.columns) + tuple(build_out.columns)
        else:
            cols = tuple(build_out.columns) + tuple(pbatch.columns)
        pairs = DeviceBatch(cols, pbatch.num_rows)
        matched = found
        if cond is not None:
            c = as_device_column(cond.eval(pairs), pairs)
            matched = matched & c.data & c.validity
        if jt == "inner":
            return pairs.with_sel(matched & plive)
        if jt in ("semi", "anti"):
            keep = matched if jt == "semi" else ~matched
            return pbatch.with_sel(keep & plive)
        # left/right outer: every live probe row survives; the build side
        # shows NULLs where unmatched (gather valid_dst already nulled
        # not-found rows; a failed condition re-nulls here).
        if cond is not None:
            nulled = tuple(
                c.with_validity(c.validity & matched)
                for c in build_out.columns)
            if build_is_right:
                cols = tuple(pbatch.columns) + nulled
            else:
                cols = nulled + tuple(pbatch.columns)
            pairs = DeviceBatch(cols, pbatch.num_rows)
        return pairs.with_sel(plive)

    def _dense_jit_fn(self):
        from spark_rapids_tpu.ops import kernel_cache as kc
        return kc.lookup(
            "join-dense", self._join_fp(),
            lambda: jax.jit(kc.detached_clone(self)._dense_step,
                            static_argnames=("probe_keys",
                                             "build_is_right")))

    def _device_join_stream(self, ctx, built: BuiltSide, probe_iter,
                            probe_keys, build_is_right: bool):
        import itertools
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.columnar.batch import coalesce_iter
        jt = self.join_type
        cond = self.condition
        build_cap = built.batch.capacity
        # Full outer: build-side coverage accumulates over the whole probe
        # stream and unmatched build rows are emitted once at the end.
        covered_acc = jnp.zeros((build_cap,), jnp.bool_) \
            if jt == "full" else None
        # Coalesce the probe stream: per-batch probe work has a fixed
        # device-latency floor, so 8 scan-file batches cost 8 floors where
        # 1-2 coalesced batches cost 1-2. shrink=True compacts sparse
        # members first (an upstream selective join's output would
        # otherwise make EVERY downstream probe gather pay its full
        # capacity); the sizes pull is batched per group and skipped
        # where rows_hint is known (scans).
        probe_iter = coalesce_iter(
            probe_iter, int(ctx.conf.get(C.BATCH_SIZE_ROWS)),
            shrink=True,
            target_bytes=int(ctx.conf.get(C.BATCH_SIZE_BYTES)))
        # Dispatch the FIRST probe batch's upstream work before blocking on
        # the build stats: the async stats copy then overlaps probe-side
        # scan/decode instead of serializing ahead of it.
        first = next(iter(probe_iter), None)
        if first is not None:
            probe_iter = itertools.chain([first], probe_iter)
        else:
            probe_iter = iter(())
        # One sync per BUILD (not per probe batch): the stats pull powers
        # both the FK fast path (max_run sizes every probe batch's output
        # with no further syncs) and the dense direct-address table.
        jittable = cond is None or getattr(cond, "jittable", False)
        mr = None
        if built.stats is not None:
            mr = built.stats_host()[0]
        elif built.max_run is not None:
            mr = int(built.max_run)
        if mr is not None and jt in ("inner", "left", "right", "semi",
                                     "anti") and jittable:
            _maybe_build_dense(built, built.batch, built.key_ordinals)
        from spark_rapids_tpu.memory.oom import retry_on_oom
        if built.table is not None:
            dense = self._dense_jit_fn()
            for pbatch in probe_iter:
                yield retry_on_oom(
                    dense, built, pbatch, probe_keys=tuple(probe_keys),
                    build_is_right=build_is_right)
            return
        fast = mr is not None and 0 < mr <= self._FAST_PATH_MAX_RUN
        for pbatch in probe_iter:
            if fast:
                out_cap = bucket_capacity(max(pbatch.capacity * mr, 1))
                if jittable:
                    out, covered = retry_on_oom(
                        self._probe_jit_fn(),
                        built, pbatch, out_cap=out_cap,
                        build_is_right=build_is_right,
                        probe_keys=tuple(probe_keys))
                else:
                    lo, counts, plive = probe_ranges(
                        built, pbatch, probe_keys, built.null_safe)
                    out, covered = self._emit_expanded(
                        built, pbatch, lo, counts, plive, out_cap,
                        build_is_right, probe_keys)
            else:
                # (Semi/anti also go through expansion: candidate
                # fingerprint ranges must be key-verified before deciding
                # hit/miss.) The eagerly-computed ranges are reused by the
                # emit step — probe keys are hashed once per batch.
                lo, counts, plive = probe_ranges(built, pbatch, probe_keys,
                                                 built.null_safe)
                total = int(jnp.sum(counts))
                out_cap = bucket_capacity(max(total, 1))
                if jittable:
                    out, covered = self._emit_jit_fn()(
                        built, pbatch, lo, counts, plive, out_cap=out_cap,
                        build_is_right=build_is_right,
                        probe_keys=tuple(probe_keys))
                else:
                    out, covered = self._emit_expanded(
                        built, pbatch, lo, counts, plive, out_cap,
                        build_is_right, probe_keys)
            if covered_acc is not None and covered is not None:
                covered_acc = covered_acc | covered
            yield out
        if covered_acc is not None:
            build_unmatched = ~covered_acc & built.row_live
            # A fake empty probe batch supplies the null side's schema.
            yield self._null_extend_build(
                built, build_unmatched, self._probe_schema_batch(),
                build_is_right)

    def _probe_schema_batch(self) -> DeviceBatch:
        build_right = self.join_type != "right"
        probe_child = self.children[0] if build_right else self.children[1]
        return _empty_like(probe_child.schema)

    def _emit_expanded(self, built: BuiltSide, pbatch: DeviceBatch,
                       lo, counts, plive, out_cap: int,
                       build_is_right: bool, probe_keys=None):
        """Expand matches for one probe batch. Returns (out_batch,
        covered_build_rows_or_None)."""
        from spark_rapids_tpu.columnar.rowmove import gather_rows
        jt = self.join_type
        cond = self.condition
        probe_cap = pbatch.capacity
        p, b, valid, total, _overflow = expand_pairs(lo, counts, out_cap,
                                                     probe_cap)
        if built.key_ordinals is not None and probe_keys is not None:
            valid = _pair_keys_equal(built, b, pbatch, p, probe_keys, valid)
        probe_out = gather_rows(pbatch, p, total, valid_dst=valid)
        build_out = gather_rows(built.batch, b, total, valid_dst=valid)
        if build_is_right:
            cols = tuple(probe_out.columns) + tuple(build_out.columns)
        else:
            cols = tuple(build_out.columns) + tuple(probe_out.columns)
        pairs = DeviceBatch(cols, total)

        if cond is not None:
            c = as_device_column(cond.eval(pairs), pairs)
            cond_keep = c.data & c.validity & valid
        else:
            cond_keep = valid

        if jt in ("inner", "cross"):
            return pairs.with_sel(cond_keep), None
        if jt in ("semi", "anti"):
            hit = jax.ops.segment_max(
                cond_keep.astype(jnp.int32), p, num_segments=probe_cap) > 0
            keep = (hit if jt == "semi" else ~hit) & pbatch.row_mask()
            return pbatch.with_sel(keep), None
        # Outer joins: survivors + unmatched probe rows with NULL side.
        survivors = pairs.with_sel(cond_keep)
        probe_hit = jax.ops.segment_max(
            cond_keep.astype(jnp.int32), p, num_segments=probe_cap) > 0
        probe_unmatched = ~probe_hit & pbatch.row_mask()
        extra = self._null_extend(pbatch, probe_unmatched, built,
                                  build_is_right)
        out = concat_batches(
            [survivors, extra],
            bucket_capacity(survivors.capacity + extra.capacity))
        if jt == "full":
            build_cap = built.batch.capacity
            covered = jax.ops.segment_max(
                (cond_keep & valid).astype(jnp.int32),
                jnp.clip(b, 0, build_cap - 1), num_segments=build_cap) > 0
            return out, covered
        return out, None

    def _null_extend(self, pbatch: DeviceBatch, keep, built: BuiltSide,
                     build_is_right: bool) -> DeviceBatch:
        """Probe rows with a NULL build side (selection-vector, no move)."""
        kept = pbatch.with_sel(keep & pbatch.row_mask())
        nulls = [DeviceColumn.full_null(
            c.dtype, kept.capacity,
            c.string_width if c.dtype.is_string else 8)
            for c in built.batch.columns]
        if build_is_right:
            cols = tuple(kept.columns) + tuple(nulls)
        else:
            cols = tuple(nulls) + tuple(kept.columns)
        return DeviceBatch(cols, kept.num_rows, sel=kept.sel)

    def _null_extend_build(self, built: BuiltSide, keep, pbatch: DeviceBatch,
                           build_is_right: bool) -> DeviceBatch:
        # built.batch's live rows are NOT a prefix (fingerprint-sorted with
        # null-key rows at the end): num_rows=capacity makes row_mask read
        # the selection vector alone.
        keep = keep & built.row_live
        kept = DeviceBatch(built.batch.columns,
                           jnp.asarray(built.batch.capacity, jnp.int32),
                           sel=keep)
        nulls = [DeviceColumn.full_null(
            c.dtype, kept.capacity,
            c.string_width if c.dtype.is_string else 8)
            for c in pbatch.columns]
        if build_is_right:
            cols = tuple(nulls) + tuple(kept.columns)
        else:
            cols = tuple(kept.columns) + tuple(nulls)
        return DeviceBatch(cols, kept.num_rows, sel=kept.sel)


# ---------------------------------------------------------------------------
# Execs
# ---------------------------------------------------------------------------

class ShuffledHashJoinExec(Exec, _JoinKernelMixin):
    """Both sides co-partitioned by key (GpuShuffledHashJoinExec). The build
    side (right for left/inner/..., left for 'right' joins) is coalesced to
    a single batch per partition — RequireSingleBatch, as in the reference.
    """

    def __init__(self, left: Exec, right: Exec,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 join_type: str = "inner",
                 condition: Optional[Expression] = None):
        super().__init__(left, right)
        assert join_type in JOIN_TYPES
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type
        self.condition = condition

    @property
    def schema(self) -> Schema:
        return _join_schema(self.children[0].schema,
                            self.children[1].schema, self.join_type)

    def num_partitions(self, ctx) -> int:
        delegate = self._replan_delegate(ctx)
        if delegate is not None:
            return delegate.num_partitions(ctx)
        return self.children[0].num_partitions(ctx)

    def _replan_delegate(self, ctx) -> Optional[Exec]:
        """The broadcast delegate a runtime re-plan swapped in for this
        query (parallel/replan.py), or None. Decisions are per-context:
        the cached physical plan and the host oracle never see them.
        BroadcastHashJoinExec overrides every consulting method, so a
        delegate can never consult itself."""
        from spark_rapids_tpu.parallel import replan as RP
        return RP.demoted(ctx, self)

    def _key_ordinals(self, side: Exec, keys) -> List[int]:
        # Keys must be bound references for the kernel; project otherwise.
        from spark_rapids_tpu.exprs.base import BoundReference
        ords = []
        for k in keys:
            assert isinstance(k, BoundReference), \
                "join keys must be pre-projected BoundReferences"
            ords.append(k.ordinal)
        return ords

    def execute_device(self, ctx, partition):
        delegate = self._replan_delegate(ctx)
        if delegate is not None:
            # Runtime demotion: stream the rewritten broadcast subtree —
            # the build side serves the already-materialized exchange,
            # the probe side reads its child UNSHUFFLED.
            yield from delegate.execute_device(ctx, partition)
            return
        # 'right' join probes with the right side preserved: build LEFT.
        build_right = self.join_type != "right"
        build_child = self.children[1] if build_right else self.children[0]
        probe_child = self.children[0] if build_right else self.children[1]
        build_keys = self.right_keys if build_right else self.left_keys
        probe_keys = self.left_keys if build_right else self.right_keys
        bbatches = list(build_child.execute_device(ctx, partition))
        if not bbatches:
            if self.join_type in ("inner", "semi", "cross"):
                return
            bbatches = []
        probe_iter = probe_child.execute_device(ctx, partition)
        if not bbatches:
            # Outer/anti with empty build: every probe row is unmatched.
            for pbatch in probe_iter:
                if self.join_type == "anti":
                    yield pbatch
                elif self.join_type in ("left", "right", "full"):
                    empty = _empty_like(build_child.schema)
                    built = build_side(empty, list(range(
                        len(self._key_ordinals(build_child, build_keys)))))
                    yield self._null_extend(
                        pbatch, pbatch.row_mask(), built, build_right)
            return
        total_bytes = sum(b.device_size_bytes() for b in bbatches)
        grace_budget = self._grace_bucket_budget(ctx)
        forced = bool(ctx.cache.get(self._grace_force_key()))
        if grace_budget is not None and (forced
                                         or total_bytes > grace_budget):
            yield from self._grace_join(
                ctx, bbatches, probe_iter, build_child, probe_child,
                build_keys, probe_keys, build_right, total_bytes,
                grace_budget)
            return
        single = coalesce_to_single_batch(bbatches)
        built = build_side(single, self._key_ordinals(build_child,
                                                      build_keys))
        yield from self._device_join_stream(
            ctx, built, probe_iter,
            self._key_ordinals(probe_child, probe_keys), build_right)

    # -- out-of-core grace hash join -----------------------------------------
    def _grace_force_key(self) -> str:
        return f"grace-join-force:{id(self):x}"

    def _grace_bucket_budget(self, ctx) -> Optional[int]:
        """Per-bucket byte budget when the grace path is available for
        this join, else None. The same number is the build-side size
        past which grace engages proactively."""
        from spark_rapids_tpu import config as C
        if not bool(ctx.conf.get(C.JOIN_GRACE_ENABLED)):
            return None
        if self.join_type == "cross" or not self.left_keys:
            return None
        frac = float(ctx.conf.get(C.JOIN_GRACE_BUILD_FRACTION))
        return max(int(ctx.catalog.device_budget * frac), 1 << 16)

    def _grace_retry(self, ctx, partition):
        """The OOM-ladder rung ABOVE host fallback (ops/base.py calls
        this when the device path dies on an exhausted spill/shrink
        ladder): force the grace-partitioned path for this join and
        re-run on device. Returns the retry iterator, or None when
        grace is unavailable / already forced (then host fallback is
        next, as before)."""
        from spark_rapids_tpu import faults
        if self._grace_bucket_budget(ctx) is None:
            return None
        key = self._grace_force_key()
        if ctx.cache.get(key):
            return None                 # grace itself OOMed: demote on
        ctx.cache[key] = True
        faults.record("graceJoinEngaged")
        ctx.metrics_for(self).add("graceJoinEngaged", 1)
        return self.execute_device(ctx, partition)

    def _grace_join(self, ctx, bbatches, probe_iter, build_child,
                    probe_child, build_keys, probe_keys,
                    build_right: bool, total_bytes: int,
                    bucket_budget: int):
        """Spill-partitioned grace hash join (the Grace/hybrid-hash
        classic, TPU-shaped): BOTH sides partition by the murmur3 key
        fingerprint through the staged exchange into spillable buckets
        (equal keys land in the same bucket on both sides by
        construction), then co-partitioned bucket pairs run the normal
        build/probe kernel one at a time. Peak HBM is one bucket's
        build side + one probe batch; everything else rides the spill
        tiers. Runs build sides FAR past the device budget on-device —
        beating the reference's RequireSingleBatch build restriction
        (GpuShuffledHashJoinExec / SURVEY §5.7)."""
        from spark_rapids_tpu import config as C, faults
        from spark_rapids_tpu.memory.stores import PRIORITY_SHUFFLE_OUTPUT
        from spark_rapids_tpu.ops.sort import (stage_spillables,
                                               staged_exchange)
        from spark_rapids_tpu.parallel.partitioning import HashPartitioning
        m = ctx.metrics_for(self)
        nb = max(2, -(-total_bytes // bucket_budget))
        nb = min(nb, max(int(ctx.conf.get(C.JOIN_GRACE_MAX_PARTITIONS)),
                         2))
        m.add("graceJoinPartitions", nb)
        faults.record("graceJoinPartitions", nb)
        bords = self._key_ordinals(build_child, build_keys)
        pords = self._key_ordinals(probe_child, probe_keys)
        bspill, _ = stage_spillables(ctx, iter(bbatches))
        pspill, _ = stage_spillables(ctx, probe_iter)
        bex = staged_exchange(bspill, build_child.schema,
                              HashPartitioning(list(build_keys), nb))
        pex = staged_exchange(pspill, probe_child.schema,
                              HashPartitioning(list(probe_keys), nb))
        try:
            for p in range(nb):
                bucket = list(bex.execute_device(ctx, p))
                probe_bucket = pex.execute_device(ctx, p)
                if not bucket:
                    # Empty build bucket: mirror the empty-build-side
                    # semantics per bucket (each probe row lives in
                    # exactly one bucket, so emitting here is exact).
                    if self.join_type == "anti":
                        yield from probe_bucket
                    elif self.join_type in ("left", "right", "full"):
                        empty = _empty_like(build_child.schema)
                        built = build_side(empty,
                                           list(range(len(bords))))
                        for pbatch in probe_bucket:
                            yield self._null_extend(
                                pbatch, pbatch.row_mask(), built,
                                build_right)
                    continue
                built = build_side(coalesce_to_single_batch(bucket),
                                   bords)
                yield from self._device_join_stream(
                    ctx, built, probe_bucket, pords, build_right)
        finally:
            for sb in bspill + pspill:
                sb.close()

    # -- host oracle ---------------------------------------------------------
    def execute_host(self, ctx, partition):
        yield from _host_join(self, ctx, partition)


class BroadcastHashJoinExec(ShuffledHashJoinExec):
    """Build side pre-broadcast (wrapped in BroadcastExchangeExec); probe
    side streams its partitions (GpuBroadcastHashJoinExec)."""

    def _grace_retry(self, ctx, partition):
        # A broadcast build side is shared across every probe partition;
        # grace-partitioning it per partition would rebuild the table N
        # times. OOM here demotes straight to host fallback (the planner
        # picked broadcast because the build side was SMALL — an OOM is
        # device pressure, not build-side size).
        return None

    def num_partitions(self, ctx) -> int:
        probe = self.children[0] if self.join_type != "right" else \
            self.children[1]
        return probe.num_partitions(ctx)

    def _probe_child(self):
        return self.children[0] if self.join_type != "right" else \
            self.children[1]

    def host_prefetchable(self) -> bool:
        # Only the PROBE side streams by this node's partition numbering;
        # the build side materializes once (builtside cache) — prefetching
        # it per probe partition would re-encode the whole build table
        # N times for nothing.
        from spark_rapids_tpu.parallel.stages import is_stage_boundary
        probe = self._probe_child()
        return not is_stage_boundary(probe) and probe.host_prefetchable()

    def prefetch_host(self, ctx, partition):
        from spark_rapids_tpu.parallel.stages import is_stage_boundary
        probe = self._probe_child()
        if not is_stage_boundary(probe):
            probe.prefetch_host(ctx, partition)

    def execute_device(self, ctx, partition):
        build_right = self.join_type != "right"
        build_child = self.children[1] if build_right else self.children[0]
        probe_child = self.children[0] if build_right else self.children[1]
        build_keys = self.right_keys if build_right else self.left_keys
        probe_keys = self.left_keys if build_right else self.right_keys
        # Full outer over a broadcast build would emit build-unmatched rows
        # once per probe partition; Spark never plans that shape either.
        assert self.join_type != "full" or \
            probe_child.num_partitions(ctx) == 1, \
            "full outer join requires a shuffled (co-partitioned) plan"
        probe_iter = probe_child.execute_device(ctx, partition)
        # The BuiltSide (collection + fingerprint sort of the broadcast
        # table) is built once and shared across probe partitions.
        cache_key = f"builtside:{id(self):x}"
        built = ctx.cache.get(cache_key)
        if built is None:
            bbatches = []
            # In cluster mode the broadcast child may ADOPT its single
            # from the transport-backed broadcast artifact cache
            # (parallel/broadcast_cache.py) instead of re-collecting —
            # this loop is the consumer of that hit; only the
            # fingerprint sort below is always process-local.
            for cp in range(build_child.num_partitions(ctx)):
                bbatches.extend(build_child.execute_device(ctx, cp))
            if bbatches:
                single = coalesce_to_single_batch(bbatches)
                built = build_side(single, self._key_ordinals(
                    build_child, build_keys))
            else:
                built = "EMPTY"
            ctx.cache[cache_key] = built
            ctx.metrics_for(self).add("buildSideBuilds", 1)
        if built == "EMPTY":
            for pbatch in probe_iter:
                if self.join_type == "anti":
                    yield pbatch
                elif self.join_type in ("left", "right", "full"):
                    empty = _empty_like(build_child.schema)
                    eb = build_side(empty, [0] if build_keys else [])
                    yield self._null_extend(pbatch, pbatch.row_mask(),
                                            eb, build_right)
            return
        yield from self._device_join_stream(
            ctx, built, probe_iter,
            self._key_ordinals(probe_child, probe_keys), build_right)


class BroadcastNestedLoopJoinExec(Exec, _JoinKernelMixin):
    """Cross / conditional nested-loop join: every probe (left) row pairs
    with every build (right/broadcast) row
    (GpuBroadcastNestedLoopJoinExec.scala). Output capacity is
    probe_cap * build_cap per batch pair — keep the build side small.

    'right' preserves the build side, 'left'/'full' the usual semantics;
    right/full require a single probe partition (build-unmatched rows are
    emitted once), matching how Spark plans these only when viable."""

    def __init__(self, left: Exec, right: Exec,
                 join_type: str = "cross",
                 condition: Optional[Expression] = None):
        super().__init__(left, right)
        assert join_type in JOIN_TYPES
        self.join_type = join_type
        self.condition = condition

    @property
    def schema(self) -> Schema:
        return _join_schema(self.children[0].schema,
                            self.children[1].schema, self.join_type)

    def num_partitions(self, ctx) -> int:
        return self.children[0].num_partitions(ctx)

    def host_prefetchable(self) -> bool:
        # Probe (left) side only — the broadcast build side is pulled
        # whole per partition, not by this node's partition numbering.
        from spark_rapids_tpu.parallel.stages import is_stage_boundary
        return not is_stage_boundary(self.children[0]) and \
            self.children[0].host_prefetchable()

    def prefetch_host(self, ctx, partition):
        from spark_rapids_tpu.parallel.stages import is_stage_boundary
        if not is_stage_boundary(self.children[0]):
            self.children[0].prefetch_host(ctx, partition)

    def execute_device(self, ctx, partition):
        jt = self.join_type
        assert jt not in ("right", "full") or \
            self.num_partitions(ctx) == 1, \
            f"nested-loop {jt} join needs a single probe partition"
        bbatches = []
        for cp in range(self.children[1].num_partitions(ctx)):
            bbatches.extend(self.children[1].execute_device(ctx, cp))
        probe_iter = self.children[0].execute_device(ctx, partition)
        if not bbatches:
            # Empty build side: left/full keep probes null-extended, anti
            # keeps all probes, inner/cross/semi/right emit nothing.
            empty = _empty_like(self.children[1].schema)
            built = BuiltSide(empty, None, empty.row_mask(),
                              empty.row_mask(), empty.num_rows)
            for pbatch in probe_iter:
                if jt == "anti":
                    yield pbatch
                elif jt in ("left", "full"):
                    yield self._null_extend(pbatch, pbatch.row_mask(),
                                            built, True)
            return
        build = coalesce_to_single_batch(bbatches)
        if build.sel is not None:
            # The NLJ pairs every probe row with build positions
            # 0..num_rows-1; a selection vector (small filtered build that
            # skipped the broadcast shrink) must compact first or deleted
            # rows would join as live.
            from spark_rapids_tpu.columnar.rowmove import compact_batch
            from spark_rapids_tpu.ops import kernel_cache as kc
            build = kc.lookup("compact-batch", (),
                              lambda: jax.jit(compact_batch))(build)
        built = BuiltSide(build, None, build.row_mask(),
                          build.row_mask(), build.num_rows)
        bcap = build.capacity
        covered_acc = jnp.zeros((bcap,), jnp.bool_) \
            if jt in ("right", "full") else None
        for pbatch in probe_iter:
            pcap = pbatch.capacity
            # lo=0, count=num_build_rows for every live probe row.
            lo = jnp.zeros((pcap,), jnp.int32)
            counts = jnp.where(pbatch.row_mask(),
                               build.num_rows.astype(jnp.int32), 0)
            out_cap = bucket_capacity(
                max(int(pbatch.num_rows) * int(build.num_rows), 1))
            out, covered = self._nlj_emit(built, pbatch, lo, counts,
                                          out_cap)
            if covered_acc is not None and covered is not None:
                covered_acc = covered_acc | covered
            if out is not None:
                yield out
        if covered_acc is not None:
            build_unmatched = ~covered_acc & built.row_live
            yield self._null_extend_build(
                built, build_unmatched,
                _empty_like(self.children[0].schema), True)

    def _nlj_emit(self, built, pbatch, lo, counts, out_cap):
        """Like _emit_expanded but with nested-loop join-type semantics:
        the probe is always the LEFT side; 'right' preserves the build."""
        jt = self.join_type
        cond = self.condition
        probe_cap = pbatch.capacity
        bcap = built.batch.capacity
        p, b, valid, total, _overflow = expand_pairs(lo, counts, out_cap,
                                                     probe_cap)
        left_cols = _gather_cols(pbatch, p, valid)
        right_cols = _gather_cols(built.batch, b, valid)
        pairs = DeviceBatch(tuple(left_cols) + tuple(right_cols), total)
        if cond is not None:
            c = as_device_column(cond.eval(pairs), pairs)
            cond_keep = c.data & c.validity & valid
        else:
            cond_keep = valid
        covered = None
        if jt in ("right", "full"):
            covered = jax.ops.segment_max(
                (cond_keep & valid).astype(jnp.int32),
                jnp.clip(b, 0, bcap - 1), num_segments=bcap) > 0
        if jt in ("inner", "cross"):
            return pairs.with_sel(cond_keep), covered
        if jt in ("semi", "anti"):
            hit = jax.ops.segment_max(
                cond_keep.astype(jnp.int32), p, num_segments=probe_cap) > 0
            keep = (hit if jt == "semi" else ~hit) & pbatch.row_mask()
            return pbatch.with_sel(keep), covered
        if jt == "right":
            # Only matched pairs here; unmatched build rows come at end.
            return pairs.with_sel(cond_keep), covered
        # left / full: survivors + probe-unmatched null-extended.
        survivors = pairs.with_sel(cond_keep)
        probe_hit = jax.ops.segment_max(
            cond_keep.astype(jnp.int32), p, num_segments=probe_cap) > 0
        probe_unmatched = ~probe_hit & pbatch.row_mask()
        extra = self._null_extend(pbatch, probe_unmatched, built, True)
        return concat_batches(
            [survivors, extra],
            bucket_capacity(survivors.capacity + extra.capacity)), covered

    def execute_host(self, ctx, partition):
        yield from _host_join(self, ctx, partition, nested_loop=True)


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------

def _empty_like(schema: Schema) -> DeviceBatch:
    cols = []
    for _, t in schema:
        cols.append(DeviceColumn.full_null(t, 8))
    return DeviceBatch(tuple(cols), jnp.asarray(0, jnp.int32))


def _empty_host_batch(schema: Schema) -> HostBatch:
    cols = []
    for _, t in schema:
        if t.is_string:
            cols.append(HostColumn(t, None, np.zeros(0, np.bool_),
                                   str_matrix=np.zeros((0, 1), np.uint8),
                                   str_lengths=np.zeros(0, np.int32)))
        else:
            cols.append(HostColumn(t, np.zeros(0, t.np_dtype),
                                   np.zeros(0, np.bool_)))
    return HostBatch(tuple(n for n, _ in schema), cols)


def _host_join(op, ctx, partition, nested_loop: bool = False):
    """Vectorized host join with SQL equi-join null semantics.

    Equi-joins reduce each key tuple to one int64 code per row (shared
    code space across sides, NaN==NaN and -0.0==0.0 canonical —
    columnar/host.py encode_key_pair), sort the build side by code, and
    probe every left row with two searchsorted calls; pair expansion is
    one repeat+gather, conditions evaluate ONCE over the gathered pair
    batch, and every emission mode is an index-array gather (negative
    index = null extension). That keeps the exact emission order of the
    row loop this replaced: pairs per left row with build rows
    ascending, unmatched right rows appended at the end. Nested-loop
    joins expand the cross product in bounded chunks with the same
    vectorized condition eval."""
    from spark_rapids_tpu.columnar.host import (
        concat_host_batches, encode_key_pair, stable_code_argsort)

    def _collect(child, parts, cache_tag=None):
        # Broadcast sides span EVERY child partition; without a cache
        # each probe partition would re-execute the whole build subtree
        # (scans, upstream joins and all) — collect once per query like
        # the device path's broadcast collection.
        key = None
        if cache_tag is not None:
            key = f"bcast-host:{id(op):x}:{cache_tag}"
            hit = ctx.cache.get(key)
            if hit is not None:
                return hit
        hbs = []
        for cp in parts:
            hbs.extend(child.execute_host(ctx, cp))
        out = (concat_host_batches(hbs) if hbs
               else _empty_host_batch(child.schema))
        if key is not None:
            ctx.cache[key] = out
        return out

    # For shuffled joins the oracle joins per partition; for broadcast the
    # build side is global. Simplest correct oracle: join THIS partition's
    # probe rows against the appropriate build rows.
    lchild, rchild = op.children
    if isinstance(op, BroadcastNestedLoopJoinExec):
        lb = _collect(lchild, [partition])
        rb = _collect(rchild, range(rchild.num_partitions(ctx)), "build")
        lkeys = rkeys = None
    elif isinstance(op, BroadcastHashJoinExec):
        if op.join_type != "right":
            lb = _collect(lchild, [partition])
            rb = _collect(rchild, range(rchild.num_partitions(ctx)),
                          "build")
        else:
            lb = _collect(lchild, range(lchild.num_partitions(ctx)),
                          "build")
            rb = _collect(rchild, [partition])
        lkeys, rkeys = op.left_keys, op.right_keys
    else:
        lb = _collect(lchild, [partition])
        rb = _collect(rchild, [partition])
        lkeys, rkeys = op.left_keys, op.right_keys

    nl, nr = lb.num_rows, rb.num_rows
    lschema, rschema = lchild.schema, rchild.schema
    jt = op.join_type
    cond = op.condition

    def eval_cond(li_p, ri_p):
        if cond is None:
            return np.ones(len(li_p), np.bool_)
        if not len(li_p):
            return np.zeros(0, np.bool_)
        hb = HostBatch(
            tuple(n for n, _ in tuple(lschema) + tuple(rschema)),
            [c.take(li_p) for c in lb.columns]
            + [c.take(ri_p) for c in rb.columns])
        c = as_host_column(cond.eval_host(hb), hb)
        return np.asarray(c.data, np.bool_) & np.asarray(c.validity,
                                                         np.bool_)

    if nested_loop:
        li_parts, ri_parts = [], []
        step = max(1, (1 << 20) // max(1, nr))
        ridx = np.arange(nr, dtype=np.int64)
        for blo in range(0, nl, step):
            bhi = min(nl, blo + step)
            li_p = np.repeat(np.arange(blo, bhi, dtype=np.int64), nr)
            ri_p = np.tile(ridx, bhi - blo)
            ok = eval_cond(li_p, ri_p)
            li_parts.append(li_p[ok])
            ri_parts.append(ri_p[ok])
        li_f = (np.concatenate(li_parts) if li_parts
                else np.zeros(0, np.int64))
        ri_f = (np.concatenate(ri_parts) if ri_parts
                else np.zeros(0, np.int64))
    else:
        lval = np.ones(nl, np.bool_)
        rval = np.ones(nr, np.bool_)
        cl_parts, cr_parts = [], []
        for lk, rk in zip(lkeys, rkeys):
            a, b = lb.columns[lk.ordinal], rb.columns[rk.ordinal]
            ca, cb = encode_key_pair(a, b)
            cl_parts.append(ca)
            cr_parts.append(cb)
            lval &= np.asarray(a.validity, np.bool_)
            rval &= np.asarray(b.validity, np.bool_)
        if len(cl_parts) == 1:
            cl, cr = cl_parts[0], cr_parts[0]
        else:
            allc = np.ascontiguousarray(np.concatenate(
                [np.stack(cl_parts, 1), np.stack(cr_parts, 1)]))
            v = allc.view(np.dtype((np.void, allc.shape[1] * 8))).ravel()
            _, inv = np.unique(v, return_inverse=True)
            inv = inv.astype(np.int64)
            cl, cr = inv[:nl], inv[nl:]
        # The build-side sort order and its equal-run boundaries are
        # invariant across probe partitions: every key (re)encoding is
        # order-preserving and equality-exact over the same build rows,
        # so per-partition codes permute and segment identically. Cache
        # them per (join, build batch) — a broadcast build (one shared
        # batch) then sorts ONCE per query instead of once per probe
        # partition; only the d-sized unique-code gather is per-call.
        skey = f"hjoin-order:{id(op):x}"
        cached = ctx.cache.get(skey)
        if cached is not None and cached[0] is rb:
            rs_order, rstart, rend = cached[1], cached[2], cached[3]
        else:
            rsel = np.flatnonzero(rval)
            rs_order = rsel[stable_code_argsort(cr[rsel])]
            cr_sorted = cr[rs_order]
            if len(cr_sorted):
                rstart = np.flatnonzero(np.concatenate(
                    [np.ones(1, np.bool_),
                     cr_sorted[1:] != cr_sorted[:-1]]))
                rend = np.concatenate(
                    [rstart[1:], np.array([len(cr_sorted)], np.int64)])
            else:
                rstart = rend = np.zeros(0, np.int64)
            ctx.cache[skey] = (rb, rs_order, rstart, rend)
        # One binary search per probe row into the UNIQUE build codes,
        # not two over the full build: a probe's [lo, hi) run bounds
        # come from the run-length table of the sorted codes.
        if len(rs_order):
            uniq = cr[rs_order[rstart]]
            base = int(uniq[0])
            spread = int(uniq[-1]) - base + 1
            if spread <= max(1 << 20, 8 * len(uniq)):
                # Dense build codes (string ranks always are; int keys
                # usually): a direct [lo, hi) lookup table turns the
                # per-probe-row binary search into one O(1) gather.
                lut_lo = np.zeros(spread, np.int64)
                lut_hi = np.zeros(spread, np.int64)
                lut_lo[uniq - base] = rstart
                lut_hi[uniq - base] = rend
                idx = cl - base
                inb = (idx >= 0) & (idx < spread) & lval
                idx = np.where(inb, idx, 0)
                plo = np.where(inb, lut_lo[idx], 0)
                phi = np.where(inb, lut_hi[idx], 0)
            else:
                pos = np.minimum(np.searchsorted(uniq, cl, "left"),
                                 len(uniq) - 1)
                hit = (uniq[pos] == cl) & lval
                plo = np.where(hit, rstart[pos], 0)
                phi = np.where(hit, rend[pos], 0)
        else:
            plo = phi = np.zeros(nl, np.int64)
        if len(rstart) == len(rs_order):
            # Every build key is unique (dimension tables): each probe
            # row has 0 or 1 match, so pair expansion is a masked
            # gather — no repeat/cumsum machinery.
            mask = phi > plo
            li_p = np.flatnonzero(mask)
            ri_p = rs_order[plo[li_p]]
        else:
            cnt = (phi - plo).astype(np.int64)
            tot = int(cnt.sum())
            li_p = np.repeat(np.arange(nl, dtype=np.int64), cnt)
            offs = np.arange(tot, dtype=np.int64) \
                - np.repeat(np.cumsum(cnt) - cnt, cnt)
            ri_p = rs_order[np.repeat(plo, cnt) + offs]
        ok = eval_cond(li_p, ri_p)
        li_f, ri_f = li_p[ok], ri_p[ok]

    names = tuple(n for n, _ in op.schema)
    lmatch = np.bincount(li_f, minlength=nl)
    if jt in ("semi", "anti"):
        keep = lmatch > 0 if jt == "semi" else lmatch == 0
        yield HostBatch(names, [c.filter(keep) for c in lb.columns])
        return
    if jt in ("left", "full"):
        unm = np.flatnonzero(lmatch == 0)
        li_all = np.concatenate([li_f, unm])
        ri_all = np.concatenate([ri_f, np.full(len(unm), -1, np.int64)])
        order = np.argsort(li_all, kind="stable")
        li_all, ri_all = li_all[order], ri_all[order]
    else:                                    # inner / cross / right pairs
        li_all, ri_all = li_f, ri_f
    if jt in ("right", "full"):
        rmatched = np.zeros(nr, np.bool_)
        rmatched[ri_f] = True
        runm = np.flatnonzero(~rmatched)
        li_all = np.concatenate([li_all,
                                 np.full(len(runm), -1, np.int64)])
        ri_all = np.concatenate([ri_all, runm])
    cols = [c.take(li_all, null_on_negative=True) for c in lb.columns] \
        + [c.take(ri_all, null_on_negative=True) for c in rb.columns]
    yield HostBatch(names, cols)


def _rows_to_hb(rows, schema) -> HostBatch:
    names = tuple(n for n, _ in schema)
    cols = []
    for ci, (_, t) in enumerate(schema):
        cols.append(HostColumn.from_values(t, [r[ci] for r in rows]))
    return HostBatch(names, cols)
