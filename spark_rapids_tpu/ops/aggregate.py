"""Hash aggregate (ref: aggregate.scala:305 — the 4-stage pipeline
documented at aggregate.scala:397-425, re-designed for TPU).

Device algorithm per partition (mirrors the reference's iterative loop at
aggregate.scala:427-480):

  for each input batch:
      project grouping keys + aggregate inputs
      group_ids (fingerprint sort) + segmented update aggregation
      -> partial buffer batch [keys..., buffers...]
      concat with the running partial; when the concat grows past the
      merge threshold, re-merge (group again with merge aggregates)
  final merge once at end; in final/complete mode run the result
  projection (finalize avg, rename columns)

All kernels are fixed-capacity jnp programs; the number of groups is a
device scalar so data-dependent group counts never recompile. Buffers are
(data, validity, lengths-or-None) triples so string aggregates (min/max/
first/last over strings) flow through the same machinery.

Aggregate functions (ref: AggregateFunctions.scala as CudfAggregate
update/merge pairs): Count, Sum, Min, Max, Average, First, Last. Each also
carries a host-side update/merge/finalize so the host oracle engine runs
real partial/final plans, not just single-stage ones.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, DeviceColumn, bucket_capacity, concat_batches)
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.exprs.base import (
    Expression, as_device_column, as_host_column)
from spark_rapids_tpu.ops.base import Exec, ExecContext, Schema, timed
from spark_rapids_tpu.ops import kernels


@dataclasses.dataclass
class SortedCol:
    """One column's arrays permuted to group-sorted order."""

    data: jnp.ndarray
    validity: jnp.ndarray
    lengths: Optional[jnp.ndarray] = None   # strings only


Buf = Tuple[jnp.ndarray, jnp.ndarray, Optional[jnp.ndarray]]


# ---------------------------------------------------------------------------
# Aggregate function descriptors
# ---------------------------------------------------------------------------

class AggFunction:
    """One aggregate: an input expression plus update/merge/finalize logic
    over segmented reductions. ``buffer_types`` is the partial-buffer schema
    this function contributes."""

    def __init__(self, child: Optional[Expression]):
        self.child = child

    @property
    def buffer_types(self) -> Tuple[dt.DataType, ...]:
        raise NotImplementedError

    @property
    def result_type(self) -> dt.DataType:
        raise NotImplementedError

    # -- device ---------------------------------------------------------
    def update(self, col: SortedCol, gid, capacity,
               row_index) -> List[Buf]:
        raise NotImplementedError

    def merge(self, bufs: List[SortedCol], gid, capacity) -> List[Buf]:
        raise NotImplementedError

    def finalize(self, bufs: List[SortedCol]) -> Buf:
        raise NotImplementedError

    # -- host oracle ----------------------------------------------------
    def host_update(self, values: list) -> tuple:
        """Group's python values (None=null) -> buffer value tuple."""
        raise NotImplementedError

    def host_merge(self, buf_tuples: List[tuple]) -> tuple:
        raise NotImplementedError

    def host_finalize(self, buf: tuple):
        raise NotImplementedError

    def host_agg(self, values: list):
        return self.host_finalize(self.host_merge([self.host_update(values)]))


class Count(AggFunction):
    """count(x): non-null count; see CountStar for count(*)."""

    @property
    def buffer_types(self):
        return (dt.INT64,)

    @property
    def result_type(self):
        return dt.INT64

    def update(self, col, gid, capacity, row_index):
        cnt = jax.ops.segment_sum(col.validity.astype(jnp.int64), gid,
                                  num_segments=capacity)
        return [(cnt, jnp.ones((capacity,), jnp.bool_), None)]

    def merge(self, bufs, gid, capacity):
        b, = bufs
        s = jax.ops.segment_sum(jnp.where(b.validity, b.data, 0), gid,
                                num_segments=capacity)
        return [(s, jnp.ones((capacity,), jnp.bool_), None)]

    def finalize(self, bufs):
        b, = bufs
        return b.data, b.validity, None

    def host_update(self, values):
        return (sum(1 for v in values if v is not None),)

    def host_merge(self, buf_tuples):
        return (sum(b[0] for b in buf_tuples if b[0] is not None),)

    def host_finalize(self, buf):
        return buf[0]


class CountStar(Count):
    def host_update(self, values):
        return (len(values),)


def _sum_result_type(t: dt.DataType) -> dt.DataType:
    return dt.FLOAT64 if t.is_floating else dt.INT64


class Sum(AggFunction):
    @property
    def buffer_types(self):
        return (_sum_result_type(self.child.data_type()),)

    @property
    def result_type(self):
        return _sum_result_type(self.child.data_type())

    def update(self, col, gid, capacity, row_index):
        t = self.result_type.np_dtype
        agg, counts = kernels.segment_reduce(
            col.data.astype(t), col.validity, gid, capacity, "sum")
        return [(agg, counts > 0, None)]

    def merge(self, bufs, gid, capacity):
        b, = bufs
        agg, counts = kernels.segment_reduce(b.data, b.validity, gid,
                                             capacity, "sum")
        return [(agg, counts > 0, None)]

    def finalize(self, bufs):
        b, = bufs
        return b.data, b.validity, None

    def host_update(self, values):
        vs = [v for v in values if v is not None]
        if not vs:
            return (None,)
        if self.result_type.is_floating:
            return (float(np.sum(np.asarray(vs, np.float64))),)
        acc = np.int64(0)
        with np.errstate(over="ignore"):
            for v in vs:
                acc = np.int64(acc + np.int64(v))   # JVM wrap
        return (int(acc),)

    def host_merge(self, buf_tuples):
        return self.host_update([b[0] for b in buf_tuples])

    def host_finalize(self, buf):
        return buf[0]


class Min(AggFunction):
    kind = "min"

    @property
    def buffer_types(self):
        return (self.child.data_type(),)

    @property
    def result_type(self):
        return self.child.data_type()

    def update(self, col, gid, capacity, row_index):
        if col.lengths is not None:
            return [kernels.segment_minmax_string(
                col.data, col.lengths, col.validity, gid, capacity,
                want_max=self.kind == "max")]
        agg, counts = kernels.segment_reduce(col.data, col.validity, gid,
                                             capacity, self.kind)
        return [(agg, counts > 0, None)]

    def merge(self, bufs, gid, capacity):
        return self.update(bufs[0], gid, capacity, None)

    def finalize(self, bufs):
        b, = bufs
        return b.data, b.validity, b.lengths

    def host_update(self, values):
        vs = [v for v in values if v is not None]
        if not vs:
            return (None,)
        t = self.child.data_type()
        if t.is_floating:
            non_nan = [v for v in vs if not np.isnan(v)]
            if self.kind == "min":
                return (min(non_nan) if non_nan else float("nan"),)
            return (float("nan") if len(non_nan) < len(vs)
                    else max(vs),)
        return (min(vs) if self.kind == "min" else max(vs),)

    def host_merge(self, buf_tuples):
        return self.host_update([b[0] for b in buf_tuples])

    def host_finalize(self, buf):
        return buf[0]


class Max(Min):
    kind = "max"


class Average(AggFunction):
    """avg: partial buffer = (sum double, count long); result double."""

    @property
    def buffer_types(self):
        return (dt.FLOAT64, dt.INT64)

    @property
    def result_type(self):
        return dt.FLOAT64

    def update(self, col, gid, capacity, row_index):
        s, counts = kernels.segment_reduce(
            col.data.astype(jnp.float64), col.validity, gid, capacity, "sum")
        return [(s, counts > 0, None),
                (counts, jnp.ones((capacity,), jnp.bool_), None)]

    def merge(self, bufs, gid, capacity):
        sb, cb = bufs
        s, _ = kernels.segment_reduce(sb.data, sb.validity, gid, capacity,
                                      "sum")
        c = jax.ops.segment_sum(jnp.where(cb.validity, cb.data, 0), gid,
                                num_segments=capacity)
        return [(s, c > 0, None),
                (c, jnp.ones((capacity,), jnp.bool_), None)]

    def finalize(self, bufs):
        sb, cb = bufs
        safe = jnp.where(cb.data > 0, cb.data, 1)
        return sb.data / safe.astype(jnp.float64), cb.data > 0, None

    def host_update(self, values):
        vs = [v for v in values if v is not None]
        if not vs:
            return (None, 0)
        return (float(np.sum(np.asarray(vs, np.float64))), len(vs))

    def host_merge(self, buf_tuples):
        s = [b[0] for b in buf_tuples if b[0] is not None]
        c = sum(b[1] for b in buf_tuples)
        return (float(np.sum(s)) if s else None, c)

    def host_finalize(self, buf):
        s, c = buf
        return None if c == 0 else s / c


class First(AggFunction):
    """first(x[, ignoreNulls]) — order = arrival order within the partition
    stream, same determinism caveat as the reference's GpuFirst."""

    pick = "min"

    def __init__(self, child, ignore_nulls: bool = True):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    @property
    def buffer_types(self):
        return (self.child.data_type(), dt.INT64)

    @property
    def result_type(self):
        return self.child.data_type()

    def _gather(self, col: SortedCol, pos, ok):
        safe = jnp.clip(pos, 0, pos.shape[0] - 1).astype(jnp.int32)
        val = jnp.take(col.data, safe, axis=0)
        vval = jnp.take(col.validity, safe, axis=0) & ok
        if col.lengths is not None:
            lens = jnp.where(vval, jnp.take(col.lengths, safe, axis=0), 0)
            val = jnp.where(vval[:, None], val, 0)
            return val, vval, lens
        val = jnp.where(vval, val, jnp.zeros_like(val))
        return val, vval, None

    def update(self, col, gid, capacity, row_index):
        # Pick by GLOBAL arrival index (monotone across the batch stream, so
        # first/last stays correct through concat+merge), but gather the
        # value by sorted position: the stable fingerprint sort preserves
        # arrival order within a group, so min/max global index coincides
        # with min/max sorted position.
        pos = jnp.arange(capacity, dtype=jnp.int64)
        gidx = pos if row_index is None else row_index.astype(jnp.int64)
        eligible = col.validity if self.ignore_nulls else \
            jnp.ones_like(col.validity)
        bad_pos = jnp.int64(capacity if self.pick == "min" else -1)
        bad_idx = jnp.int64(2 ** 62 if self.pick == "min" else -1)
        red = jax.ops.segment_min if self.pick == "min" else \
            jax.ops.segment_max
        picked_pos = red(jnp.where(eligible, pos, bad_pos), gid,
                         num_segments=capacity)
        picked_idx = red(jnp.where(eligible, gidx, bad_idx), gid,
                         num_segments=capacity)
        ok = (picked_pos < capacity) & (picked_pos >= 0)
        val, vval, lens = self._gather(col, picked_pos, ok)
        return [(val, vval, lens),
                (jnp.where(ok, picked_idx, bad_idx), ok, None)]

    def merge(self, bufs, gid, capacity):
        vcol, icol = bufs
        bad = jnp.int64(2 ** 62 if self.pick == "min" else -1)
        keyed = jnp.where(icol.validity, icol.data, bad)
        red = jax.ops.segment_min if self.pick == "min" else \
            jax.ops.segment_max
        picked_val = red(keyed, gid, num_segments=capacity)
        # Winner = the row holding the reduced index; tie-break by min row.
        row = jnp.arange(capacity, dtype=jnp.int64)
        winner = keyed == jnp.take(picked_val, gid, axis=0)
        wrow = jnp.where(winner & icol.validity, row, capacity)
        first_row = jax.ops.segment_min(wrow, gid, num_segments=capacity)
        ok = first_row < capacity
        val, vval, lens = self._gather(vcol, first_row, ok)
        iv = jnp.take(icol.data, jnp.clip(first_row, 0, capacity - 1)
                      .astype(jnp.int32), axis=0)
        return [(val, vval, lens), (jnp.where(ok, iv, bad), ok, None)]

    def finalize(self, bufs):
        vcol, _ = bufs
        return vcol.data, vcol.validity, vcol.lengths

    def host_update(self, values):
        seq = [(i, v) for i, v in enumerate(values)
               if not (self.ignore_nulls and v is None)]
        if not seq:
            return (None, None)
        i, v = seq[0] if self.pick == "min" else seq[-1]
        return (v, i)

    def host_merge(self, buf_tuples):
        cands = [b for b in buf_tuples if b[1] is not None]
        if not cands:
            return (None, None)
        pickf = min if self.pick == "min" else max
        return pickf(cands, key=lambda b: b[1])

    def host_finalize(self, buf):
        return buf[0]


class Last(First):
    pick = "max"


@dataclasses.dataclass
class AggSpec:
    """A named aggregate in the output (result column). ``distinct`` is
    consumed by mixed_final mode: the fn runs UPDATE over the deduped
    distinct input instead of MERGE over partial buffers."""

    name: str
    fn: AggFunction
    distinct: bool = False


# ---------------------------------------------------------------------------
# The exec
# ---------------------------------------------------------------------------

class HashAggregateExec(Exec):
    """Groupby aggregate. ``mode``:
    - 'partial': emits [keys..., buffers...] for a downstream exchange
    - 'final': consumes partial buffers, emits finalized results
    - 'complete': update+merge+finalize in one node (single-stage plans)
    """

    def __init__(self, child: Exec,
                 group_by: Sequence[Tuple[str, Expression]],
                 aggregates: Sequence[AggSpec],
                 mode: str = "complete"):
        super().__init__(child)
        # 'merge' = final minus the result projection (emits buffers);
        # 'mixed_final' = the distinct combo stage: input layout is
        # [keys..., distinct_x, nd buffers...]; distinct specs UPDATE over
        # x, the rest MERGE their buffers (aggregate.scala:305 distinct
        # partial-merge mode combos).
        assert mode in ("partial", "final", "complete", "merge",
                        "mixed_final")
        self.group_names = tuple(n for n, _ in group_by)
        self.group_exprs = [e for _, e in group_by]
        self.aggs = list(aggregates)
        self.mode = mode

    # -- schemas -------------------------------------------------------------
    @property
    def buffer_schema(self) -> Schema:
        cols: List[Tuple[str, dt.DataType]] = []
        for n, e in zip(self.group_names, self.group_exprs):
            cols.append((n, e.data_type()))
        for spec in self.aggs:
            for bi, bt in enumerate(spec.fn.buffer_types):
                cols.append((f"{spec.name}#buf{bi}", bt))
        return tuple(cols)

    @property
    def schema(self) -> Schema:
        if self.mode in ("partial", "merge"):
            return self.buffer_schema
        cols = [(n, e.data_type())
                for n, e in zip(self.group_names, self.group_exprs)]
        cols += [(s.name, s.fn.result_type) for s in self.aggs]
        return tuple(cols)

    @property
    def _nkeys(self) -> int:
        return len(self.group_exprs)

    # -- device path ---------------------------------------------------------
    def _project_inputs(self, batch: DeviceBatch) -> Tuple[DeviceBatch, list]:
        """[keys..., agg inputs...] working batch + per-agg input ordinal."""
        cols = [as_device_column(e.eval(batch), batch)
                for e in self.group_exprs]
        ords = []
        for spec in self.aggs:
            if spec.fn.child is None:   # count(*)
                ords.append(None)
            else:
                cols.append(as_device_column(spec.fn.child.eval(batch),
                                             batch))
                ords.append(len(cols) - 1)
        return DeviceBatch(tuple(cols), batch.num_rows), ords

    @staticmethod
    def _sorted_col(col: DeviceColumn, perm, slive) -> SortedCol:
        data = jnp.take(col.data, perm, axis=0)
        validity = jnp.take(col.validity, perm, axis=0) & slive
        lens = None
        if col.dtype.is_string:
            lens = jnp.where(validity, jnp.take(col.lengths, perm, axis=0),
                             0)
        return SortedCol(data, validity, lens)

    @staticmethod
    def _buf_column(buf: Buf, bt: dt.DataType, gmask) -> DeviceColumn:
        data, valid, lens = buf
        valid = valid & gmask
        if bt.is_string:
            data = jnp.where(valid[:, None], data.astype(jnp.uint8), 0)
            lens = jnp.where(valid, lens, 0)
            return DeviceColumn(bt, data, valid, lens)
        data = jnp.where(valid, data.astype(bt.np_dtype),
                         jnp.zeros((), bt.np_dtype))
        return DeviceColumn(bt, data, valid)

    def _update_batch(self, batch: DeviceBatch,
                      offset: jnp.ndarray) -> DeviceBatch:
        """One input batch -> partial buffer batch. ``offset`` is the global
        arrival index of this batch's row 0 (orders First/Last across the
        stream)."""
        work, ords = self._project_inputs(batch)
        cap = work.capacity
        g = kernels.group_ids(work, range(self._nkeys))
        slive = jnp.take(batch.row_mask(), g.perm, axis=0)
        row_index = offset.astype(jnp.int64) + g.perm.astype(jnp.int64)
        out_cols: List[DeviceColumn] = []
        gmask = jnp.arange(cap, dtype=jnp.int32) < g.num_groups
        for ki in range(self._nkeys):
            out_cols.append(work.columns[ki].gather(g.group_leader, gmask))
        for spec, ord_ in zip(self.aggs, ords):
            if ord_ is None:
                col = SortedCol(jnp.zeros((cap,), jnp.int64), slive)
            else:
                col = self._sorted_col(work.columns[ord_], g.perm, slive)
            bufs = spec.fn.update(col, g.group_of_sorted, cap, row_index)
            for buf, bt in zip(bufs, spec.fn.buffer_types):
                out_cols.append(self._buf_column(buf, bt, gmask))
        return DeviceBatch(tuple(out_cols), g.num_groups)

    def _merge_batch(self, batch: DeviceBatch) -> DeviceBatch:
        """Merge a buffer batch (re-group by keys, merge buffers)."""
        cap = batch.capacity
        g = kernels.group_ids(batch, range(self._nkeys))
        slive = jnp.take(batch.row_mask(), g.perm, axis=0)
        gmask = jnp.arange(cap, dtype=jnp.int32) < g.num_groups
        out_cols: List[DeviceColumn] = []
        for ki in range(self._nkeys):
            out_cols.append(batch.columns[ki].gather(g.group_leader, gmask))
        ci = self._nkeys
        for spec in self.aggs:
            nbuf = len(spec.fn.buffer_types)
            bufs = [self._sorted_col(batch.columns[ci + b], g.perm, slive)
                    for b in range(nbuf)]
            merged = spec.fn.merge(bufs, g.group_of_sorted, cap)
            for buf, bt in zip(merged, spec.fn.buffer_types):
                out_cols.append(self._buf_column(buf, bt, gmask))
            ci += nbuf
        return DeviceBatch(tuple(out_cols), g.num_groups)

    def _mixed_batch(self, batch: DeviceBatch) -> DeviceBatch:
        """Distinct combo stage: input [keys..., x, nd buffers...] with
        (keys, x) already unique; group by keys only; distinct specs
        update over x, others merge buffers. Output is the standard
        buffer layout [keys..., all buffers...]."""
        cap = batch.capacity
        g = kernels.group_ids(batch, range(self._nkeys))
        slive = jnp.take(batch.row_mask(), g.perm, axis=0)
        gmask = jnp.arange(cap, dtype=jnp.int32) < g.num_groups
        out_cols: List[DeviceColumn] = []
        for ki in range(self._nkeys):
            out_cols.append(batch.columns[ki].gather(g.group_leader, gmask))
        x_ord = self._nkeys
        ci = self._nkeys + 1            # nd buffers follow the x column
        row_index = g.perm.astype(jnp.int64)
        for spec in self.aggs:
            if spec.distinct:
                col = self._sorted_col(batch.columns[x_ord], g.perm, slive)
                bufs = spec.fn.update(col, g.group_of_sorted, cap,
                                      row_index)
            else:
                nbuf = len(spec.fn.buffer_types)
                ins = [self._sorted_col(batch.columns[ci + b], g.perm,
                                        slive) for b in range(nbuf)]
                bufs = spec.fn.merge(ins, g.group_of_sorted, cap)
                ci += nbuf
            for buf, bt in zip(bufs, spec.fn.buffer_types):
                out_cols.append(self._buf_column(buf, bt, gmask))
        return DeviceBatch(tuple(out_cols), g.num_groups)

    def _finalize_batch(self, batch: DeviceBatch) -> DeviceBatch:
        out_cols = list(batch.columns[:self._nkeys])
        ci = self._nkeys
        gmask = batch.row_mask()
        for spec in self.aggs:
            nbuf = len(spec.fn.buffer_types)
            bufs = [SortedCol(batch.columns[ci + b].data,
                              batch.columns[ci + b].validity,
                              batch.columns[ci + b].lengths)
                    for b in range(nbuf)]
            data, valid, lens = spec.fn.finalize(bufs)
            out_cols.append(self._buf_column((data, valid, lens),
                                             spec.fn.result_type, gmask))
            ci += nbuf
        return DeviceBatch(tuple(out_cols), batch.num_rows)

    def _jits(self):
        """One jit wrapper per exec instance — jax caches compiled programs
        on the wrapper, so partitions and repeated collects reuse them."""
        if not hasattr(self, "_jit_fns"):
            self._jit_fns = (jax.jit(self._update_batch),
                             jax.jit(self._merge_batch),
                             jax.jit(self._finalize_batch),
                             jax.jit(self._mixed_batch))
        return self._jit_fns

    def execute_device(self, ctx, partition):
        m = ctx.metrics_for(self)
        update, merge, finalize, mixed = self._jits()

        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.columnar.batch import (
            jit_concat_batches, shrink_to_capacity)
        acc: Optional[DeviceBatch] = None
        saw_input = False
        offset = 0
        # Shrinking the accumulator to its true group-count bucket needs a
        # device->host sync of the group count — on a remote/tunneled chip
        # that is a full network round trip, so do it only when the
        # accumulator's capacity has grown past a threshold (and once at
        # the end) instead of per input batch. High-cardinality groupbys
        # degrade gracefully: the threshold trips every batch and behavior
        # matches the reference's per-batch re-merge (aggregate.scala:427).
        shrink_at = 2 * int(ctx.conf.get(C.BATCH_SIZE_ROWS))
        for batch in self.children[0].execute_device(ctx, partition):
            saw_input = True
            with timed(m):
                # 'final'/'merge' consume buffer batches: first pass is a
                # merge; 'mixed_final' runs the distinct combo kernel.
                if self.mode in ("final", "merge"):
                    partial = merge(batch)
                elif self.mode == "mixed_final":
                    partial = mixed(batch)
                else:
                    partial = update(batch, jnp.asarray(offset, jnp.int64))
                offset += batch.capacity
                if acc is None:
                    acc = partial
                else:
                    cap = bucket_capacity(acc.capacity + partial.capacity)
                    acc = merge(jit_concat_batches([acc, partial], cap))
                if acc.capacity > shrink_at:
                    k = max(int(acc.num_rows), 1)
                    acc = shrink_to_capacity(acc, bucket_capacity(k))
        if not saw_input or acc is None:
            if self._nkeys == 0 and self.mode in ("final", "complete",
                                                  "mixed_final"):
                yield self._empty_result()
            return
        with timed(m):
            if self.mode in ("final", "complete", "mixed_final"):
                acc = finalize(acc)
            # No per-partition shrink sync here: the group-count read is a
            # device->host round trip, so whoever needs live-scale batches
            # does it batched — exchanges shrink all child partitions with
            # one sizes pull (two-phase exchange, SURVEY §7) and collect's
            # download_batches shrinks before fetching. Downstream device
            # ops just run at input capacity (compute is cheap; the link
            # is not).
        m.add("numOutputBatches", 1)
        yield acc

    def _empty_result(self) -> DeviceBatch:
        cap = 8
        cols = []
        for spec in self.aggs:
            t = spec.fn.result_type
            if isinstance(spec.fn, (Count, CountStar)):
                data = jnp.zeros((cap,), t.np_dtype)
                valid = jnp.arange(cap) < 1
            else:
                data = jnp.zeros((cap,), t.np_dtype)
                valid = jnp.zeros((cap,), jnp.bool_)
            if t.is_string:
                cols.append(DeviceColumn(t, jnp.zeros((cap, 8), jnp.uint8),
                                         valid, jnp.zeros((cap,), jnp.int32)))
            else:
                cols.append(DeviceColumn(t, data, valid))
        return DeviceBatch(tuple(cols), jnp.asarray(1, jnp.int32))

    # -- host oracle ---------------------------------------------------------
    def _host_groups(self, hbs, key_evaluator, input_lists):
        """Shared host grouping: returns (order, key_values, groups) where
        groups[key][ai] is the list of python values for aggregate ai."""
        groups = {}
        key_values = {}
        order = []
        for hb, keycols, inlists in zip(hbs, key_evaluator, input_lists):
            for i in range(hb.num_rows):
                triples = [self._host_key(kc, i) for kc in keycols]
                # Canonical key only — raw floats break NaN equality.
                key = tuple((t[0], t[1]) for t in triples)
                if key not in groups:
                    groups[key] = [[] for _ in self.aggs]
                    key_values[key] = [t[2] if t[0] else None
                                       for t in triples]
                    order.append(key)
                for ai, vals in enumerate(inlists):
                    groups[key][ai].append(vals[i] if vals is not None
                                           else 1)
        return order, key_values, groups

    def execute_host(self, ctx, partition):
        hbs = list(self.children[0].execute_host(ctx, partition))
        if self.mode in ("final", "merge"):
            yield from self._execute_host_final(
                hbs, do_finalize=self.mode == "final")
            return
        if self.mode == "mixed_final":
            yield from self._execute_host_mixed(hbs)
            return
        key_evaluator = []
        input_lists = []
        for hb in hbs:
            key_evaluator.append([as_host_column(e.eval_host(hb), hb)
                                  for e in self.group_exprs])
            inlists = []
            for spec in self.aggs:
                if spec.fn.child is None:
                    inlists.append(None)
                else:
                    inlists.append(as_host_column(
                        spec.fn.child.eval_host(hb), hb).to_list())
            input_lists.append(inlists)
        order, key_values, groups = self._host_groups(hbs, key_evaluator,
                                                      input_lists)
        rows: List[tuple] = []
        for key in order:
            vals = list(key_values[key])
            for ai, spec in enumerate(self.aggs):
                if self.mode == "partial":
                    vals.extend(spec.fn.host_update(groups[key][ai]))
                else:
                    vals.append(spec.fn.host_agg(groups[key][ai]))
            rows.append(tuple(vals))
        if not rows and self._nkeys == 0:
            vals = []
            for spec in self.aggs:
                if self.mode == "partial":
                    vals.extend(spec.fn.host_update([]))
                else:
                    vals.append(spec.fn.host_agg([]))
            rows = [tuple(vals)]
        yield _rows_to_host_batch(rows, self.schema)

    def _execute_host_final(self, hbs, do_finalize: bool = True):
        """Host final/merge mode: group buffer rows by key, merge buffer
        tuples; 'merge' emits the merged buffers unfinalized."""
        key_evaluator = []
        buf_lists = []
        for hb in hbs:
            key_evaluator.append(list(hb.columns[:self._nkeys]))
            # One pseudo-input per aggregate: the tuple of its buffer values.
            ci = self._nkeys
            per_agg = []
            for spec in self.aggs:
                nbuf = len(spec.fn.buffer_types)
                cols = [hb.columns[ci + b].to_list() for b in range(nbuf)]
                per_agg.append(list(zip(*cols)) if cols else [])
                ci += nbuf
            buf_lists.append(per_agg)
        order, key_values, groups = self._host_groups(hbs, key_evaluator,
                                                      buf_lists)
        rows = []
        for key in order:
            vals = list(key_values[key])
            for ai, spec in enumerate(self.aggs):
                merged = spec.fn.host_merge(groups[key][ai])
                if do_finalize:
                    vals.append(spec.fn.host_finalize(merged))
                else:
                    vals.extend(merged)
            rows.append(tuple(vals))
        yield _rows_to_host_batch(rows, self.schema)

    def _execute_host_mixed(self, hbs):
        """Host mixed_final: input rows are unique by (keys, x); distinct
        specs aggregate the x values, others merge their buffers."""
        key_evaluator = []
        input_lists = []
        x_ord = self._nkeys
        for hb in hbs:
            key_evaluator.append(list(hb.columns[:self._nkeys]))
            xvals = hb.columns[x_ord].to_list()
            ci = self._nkeys + 1
            per_agg = []
            for spec in self.aggs:
                if spec.distinct:
                    per_agg.append(xvals)
                else:
                    nbuf = len(spec.fn.buffer_types)
                    cols = [hb.columns[ci + b].to_list()
                            for b in range(nbuf)]
                    per_agg.append(list(zip(*cols)) if cols else [])
                    ci += nbuf
            input_lists.append(per_agg)
        order, key_values, groups = self._host_groups(hbs, key_evaluator,
                                                      input_lists)
        rows = []
        for key in order:
            vals = list(key_values[key])
            for ai, spec in enumerate(self.aggs):
                if spec.distinct:
                    vals.append(spec.fn.host_agg(groups[key][ai]))
                else:
                    merged = spec.fn.host_merge(groups[key][ai])
                    vals.append(spec.fn.host_finalize(merged))
            rows.append(tuple(vals))
        if not rows and self._nkeys == 0:
            vals = []
            for spec in self.aggs:
                if spec.distinct:
                    vals.append(spec.fn.host_agg([]))
                else:
                    vals.append(spec.fn.host_finalize(
                        spec.fn.host_merge([])))
            rows = [tuple(vals)]
        yield _rows_to_host_batch(rows, self.schema)

    @staticmethod
    def _host_key(col: HostColumn, i: int):
        """(valid, canonical-group-key, output-value) triple for one key."""
        if not col.validity[i]:
            return (False, None, None)
        v = col.data[i]
        if col.dtype.is_string:
            s = bytes(v).decode("utf-8", "replace")
            return (True, s, s)
        if col.dtype.is_floating:
            f = float(v)
            if np.isnan(f):
                return (True, "NaN", f)   # NaN == NaN for grouping
            if f == 0.0:
                return (True, 0.0, 0.0)   # -0.0 == 0.0 for grouping
            return (True, f, f)
        if col.dtype.is_boolean:
            return (True, bool(v), bool(v))
        return (True, int(v), int(v))


def _rows_to_host_batch(rows: List[tuple], schema: Schema) -> HostBatch:
    names = tuple(n for n, _ in schema)
    cols = []
    for ci, (_, t) in enumerate(schema):
        vals = [r[ci] for r in rows]
        cols.append(HostColumn.from_values(t, vals))
    return HostBatch(names, cols)
