"""Hash aggregate (ref: aggregate.scala:305 — the 4-stage pipeline
documented at aggregate.scala:397-425, re-designed for TPU).

Device algorithm per partition (mirrors the reference's iterative loop at
aggregate.scala:427-480):

  for each input batch:
      project grouping keys + aggregate inputs
      group_ids (fingerprint sort) + segmented update aggregation
      -> partial buffer batch [keys..., buffers...]
      concat with the running partial; when the concat grows past the
      merge threshold, re-merge (group again with merge aggregates)
  final merge once at end; in final/complete mode run the result
  projection (finalize avg, rename columns)

All kernels are fixed-capacity jnp programs; the number of groups is a
device scalar so data-dependent group counts never recompile. Buffers are
(data, validity, lengths-or-None) triples so string aggregates (min/max/
first/last over strings) flow through the same machinery.

Aggregate functions (ref: AggregateFunctions.scala as CudfAggregate
update/merge pairs): Count, Sum, Min, Max, Average, First, Last. Each also
carries a host-side update/merge/finalize so the host oracle engine runs
real partial/final plans, not just single-stage ones.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import (
    DeviceBatch, DeviceColumn, bucket_capacity, concat_batches)
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
from spark_rapids_tpu.exprs.base import (
    Expression, as_device_column, as_host_column)
from spark_rapids_tpu.ops.base import (Exec, ExecContext, Schema,
    record_batch, timed)
from spark_rapids_tpu.ops import kernels


@dataclasses.dataclass
class SortedCol:
    """One column's arrays permuted to group-sorted order."""

    data: jnp.ndarray
    validity: jnp.ndarray
    lengths: Optional[jnp.ndarray] = None   # strings only


Buf = Tuple[jnp.ndarray, jnp.ndarray, Optional[jnp.ndarray]]


# ---------------------------------------------------------------------------
# Aggregate function descriptors
# ---------------------------------------------------------------------------

class AggFunction:
    """One aggregate: an input expression plus update/merge/finalize logic
    over segmented reductions. ``buffer_types`` is the partial-buffer schema
    this function contributes."""

    def __init__(self, child: Optional[Expression]):
        self.child = child

    @property
    def buffer_types(self) -> Tuple[dt.DataType, ...]:
        raise NotImplementedError

    @property
    def result_type(self) -> dt.DataType:
        raise NotImplementedError

    # -- device ---------------------------------------------------------
    def update(self, col: SortedCol, gid, capacity,
               row_index) -> List[Buf]:
        raise NotImplementedError

    def merge(self, bufs: List[SortedCol], gid, capacity) -> List[Buf]:
        raise NotImplementedError

    def finalize(self, bufs: List[SortedCol]) -> Buf:
        raise NotImplementedError

    # -- fast segmented-sum plan (cumsum path) ---------------------------
    # Sum-decomposable aggregates (Sum/Count/Average) expose their work as
    # masked value streams; HashAggregateExec stacks every stream of the
    # whole spec list into per-dtype 2D arrays and computes ALL group sums
    # with ONE cumsum + boundary-diff per dtype (f64 scatter-adds cost
    # ~147ms/1M on this chip; a (1M, k) cumsum costs ~48ms TOTAL —
    # scripts/microbench.py). None = not sum-decomposable (min/max/first/
    # last keep the per-fn segment path).
    # ``has_nans`` mirrors spark.rapids.sql.hasNans: when the user asserts
    # float data is finite, the out-of-band NaN/inf occurrence streams (3
    # extra i32 cumsum columns per f64 sum) are skipped entirely.
    def sum_terms_update(self, col: SortedCol,
                         has_nans: bool = True) -> Optional[List[Tuple]]:
        return None

    def sum_terms_merge(self, bufs: List[SortedCol],
                        has_nans: bool = True) -> Optional[List[Tuple]]:
        return None

    def bufs_from_sums(self, sums: List, capacity: int,
                       has_nans: bool = True) -> List[Buf]:
        raise NotImplementedError

    # -- global (zero-key) fast path -------------------------------------
    # Whole-batch masked reductions — no sort, no segments. Returns one
    # value per buffer as (scalar_data, scalar_valid, lengths_or_None).
    def update_global(self, col: SortedCol, row_index=None,
                      live=None) -> Optional[List[Tuple]]:
        return None

    def merge_global(self, bufs: List[SortedCol]) -> Optional[List[Tuple]]:
        return None

    # -- partial-skip passthrough ----------------------------------------
    # Each input ROW becomes its own single-element group buffer — a pure
    # elementwise projection into the buffer layout, used when the partial
    # stage's measured reduction ratio is poor (the reference's later
    # skipAggPassReductionRatio idea): grouping then happens once, after
    # the exchange, instead of twice. None = unsupported.
    def update_row(self, col: SortedCol, row_index) -> Optional[List[Buf]]:
        return None

    # -- host oracle ----------------------------------------------------
    def host_update(self, values: list) -> tuple:
        """Group's python values (None=null) -> buffer value tuple."""
        raise NotImplementedError

    def host_merge(self, buf_tuples: List[tuple]) -> tuple:
        raise NotImplementedError

    def host_finalize(self, buf: tuple):
        raise NotImplementedError

    def host_agg(self, values: list):
        return self.host_finalize(self.host_merge([self.host_update(values)]))


class Count(AggFunction):
    """count(x): non-null count; see CountStar for count(*)."""

    @property
    def buffer_types(self):
        return (dt.INT64,)

    @property
    def result_type(self):
        return dt.INT64

    def update(self, col, gid, capacity, row_index):
        cnt = jax.ops.segment_sum(col.validity.astype(jnp.int64), gid,
                                  num_segments=capacity)
        return [(cnt, jnp.ones((capacity,), jnp.bool_), None)]

    def merge(self, bufs, gid, capacity):
        b, = bufs
        s = jax.ops.segment_sum(jnp.where(b.validity, b.data, 0), gid,
                                num_segments=capacity)
        return [(s, jnp.ones((capacity,), jnp.bool_), None)]

    def finalize(self, bufs):
        b, = bufs
        return b.data, b.validity, None

    # -- fast paths ------------------------------------------------------
    def sum_terms_update(self, col, has_nans=True):
        return [("i32", col.validity.astype(jnp.int32))]

    def sum_terms_merge(self, bufs, has_nans=True):
        b, = bufs
        return [("i64", jnp.where(b.validity, b.data, 0))]

    def bufs_from_sums(self, sums, capacity, has_nans=True):
        s, = sums
        return [(s.astype(jnp.int64), jnp.ones((capacity,), jnp.bool_),
                 None)]

    def update_global(self, col, row_index=None, live=None):
        return [(jnp.sum(col.validity.astype(jnp.int64)), True, None)]

    def update_row(self, col, row_index):
        ones = jnp.ones_like(col.validity)
        return [(col.validity.astype(jnp.int64), ones, None)]

    def merge_global(self, bufs):
        b, = bufs
        return [(jnp.sum(jnp.where(b.validity, b.data, 0)), True, None)]

    def host_update(self, values):
        return (sum(1 for v in values if v is not None),)

    def host_merge(self, buf_tuples):
        return (sum(b[0] for b in buf_tuples if b[0] is not None),)

    def host_finalize(self, buf):
        return buf[0]


class CountStar(Count):
    def update_row(self, col, row_index):
        ones = jnp.ones_like(col.validity)
        return [(jnp.ones(col.validity.shape, jnp.int64), ones, None)]

    def host_update(self, values):
        return (len(values),)


def _sum_result_type(t: dt.DataType) -> dt.DataType:
    return dt.FLOAT64 if t.is_floating else dt.INT64


def _reapply_nonfinite(s, nan_cnt, pinf_cnt, ninf_cnt):
    """Reconstruct IEEE sum semantics from a finite-only sum plus per-group
    NaN/±inf occurrence counts (cumsum path carries non-finites out of
    band)."""
    bad = (nan_cnt > 0) | ((pinf_cnt > 0) & (ninf_cnt > 0))
    s = jnp.where(pinf_cnt > 0, jnp.inf, s)
    s = jnp.where(ninf_cnt > 0, -jnp.inf, s)
    return jnp.where(bad, jnp.nan, s)


class Sum(AggFunction):
    @property
    def buffer_types(self):
        return (_sum_result_type(self.child.data_type()),)

    @property
    def result_type(self):
        return _sum_result_type(self.child.data_type())

    def update(self, col, gid, capacity, row_index):
        t = self.result_type.np_dtype
        agg, counts = kernels.segment_reduce(
            col.data.astype(t), col.validity, gid, capacity, "sum")
        return [(agg, counts > 0, None)]

    def merge(self, bufs, gid, capacity):
        b, = bufs
        agg, counts = kernels.segment_reduce(b.data, b.validity, gid,
                                             capacity, "sum")
        return [(agg, counts > 0, None)]

    def finalize(self, bufs):
        b, = bufs
        return b.data, b.validity, None

    # -- fast paths ------------------------------------------------------
    @property
    def _cls(self) -> str:
        return "f64" if self.result_type.is_floating else "i64"

    def _terms(self, data, validity, has_nans):
        """Masked value stream + count; float streams also carry NaN/inf
        occurrence counts (unless hasNans=false asserts finiteness) — the
        cumsum prefix-diff would otherwise let one group's NaN poison
        every later group's sum."""
        t = self.result_type.np_dtype
        v = jnp.where(validity, data.astype(t), jnp.zeros((), t))
        if self._cls != "f64":
            return [("i64", v), ("i32", validity.astype(jnp.int32))]
        if not has_nans:
            return [("f64", v), ("i32", validity.astype(jnp.int32))]
        finite = jnp.isfinite(v)
        clean = jnp.where(finite, v, 0.0)
        return [("f64", clean), ("i32", validity.astype(jnp.int32)),
                ("i32", (validity & jnp.isnan(v)).astype(jnp.int32)),
                ("i32", (v == jnp.inf).astype(jnp.int32)),
                ("i32", (v == -jnp.inf).astype(jnp.int32))]

    def sum_terms_update(self, col, has_nans=True):
        return self._terms(col.data, col.validity, has_nans)

    def sum_terms_merge(self, bufs, has_nans=True):
        b, = bufs
        return self._terms(b.data, b.validity, has_nans)

    def bufs_from_sums(self, sums, capacity, has_nans=True):
        if self._cls != "f64" or not has_nans:
            s, c = sums
            return [(s, c > 0, None)]
        s, c, nan, pinf, ninf = sums
        s = _reapply_nonfinite(s, nan, pinf, ninf)
        return [(s, c > 0, None)]

    def update_global(self, col, row_index=None, live=None):
        t = self.result_type.np_dtype
        v = jnp.where(col.validity, col.data.astype(t), jnp.zeros((), t))
        return [(jnp.sum(v), jnp.sum(col.validity.astype(jnp.int32)) > 0,
                 None)]

    def update_row(self, col, row_index):
        t = self.result_type.np_dtype
        return [(col.data.astype(t), col.validity, None)]

    def merge_global(self, bufs):
        b, = bufs
        t = self.result_type.np_dtype
        v = jnp.where(b.validity, b.data.astype(t), jnp.zeros((), t))
        return [(jnp.sum(v), jnp.sum(b.validity.astype(jnp.int32)) > 0,
                 None)]

    def host_update(self, values):
        vs = [v for v in values if v is not None]
        if not vs:
            return (None,)
        if self.result_type.is_floating:
            return (float(np.sum(np.asarray(vs, np.float64))),)
        acc = np.int64(0)
        with np.errstate(over="ignore"):
            for v in vs:
                acc = np.int64(acc + np.int64(v))   # JVM wrap
        return (int(acc),)

    def host_merge(self, buf_tuples):
        return self.host_update([b[0] for b in buf_tuples])

    def host_finalize(self, buf):
        return buf[0]


class Min(AggFunction):
    kind = "min"

    @property
    def buffer_types(self):
        return (self.child.data_type(),)

    @property
    def result_type(self):
        return self.child.data_type()

    def update(self, col, gid, capacity, row_index):
        if col.lengths is not None:
            return [kernels.segment_minmax_string(
                col.data, col.lengths, col.validity, gid, capacity,
                want_max=self.kind == "max")]
        agg, counts = kernels.segment_reduce(col.data, col.validity, gid,
                                             capacity, self.kind)
        return [(agg, counts > 0, None)]

    def merge(self, bufs, gid, capacity):
        return self.update(bufs[0], gid, capacity, None)

    def finalize(self, bufs):
        b, = bufs
        return b.data, b.validity, b.lengths

    def _global(self, col):
        if col.lengths is not None:
            return None       # string min/max: sorted path
        v, val = col.data, col.validity
        if jnp.issubdtype(v.dtype, jnp.floating):
            isnan = jnp.isnan(v)
            real = val & ~isnan
            nanv = jnp.asarray(jnp.nan, v.dtype)
            if self.kind == "min":
                m = jnp.min(jnp.where(real, v,
                                      jnp.asarray(jnp.inf, v.dtype)))
                m = jnp.where(jnp.sum(real.astype(jnp.int32)) > 0, m, nanv)
            else:
                m = jnp.max(jnp.where(real, v,
                                      jnp.asarray(-jnp.inf, v.dtype)))
                m = jnp.where(jnp.sum((val & isnan).astype(jnp.int32)) > 0,
                              nanv, m)
        else:
            ident = kernels._identity_for(v.dtype, self.kind)
            masked = jnp.where(val, v, ident)
            m = jnp.min(masked) if self.kind == "min" else jnp.max(masked)
        ok = jnp.sum(val.astype(jnp.int32)) > 0
        return [(m, ok, None)]

    def update_global(self, col, row_index=None, live=None):
        return self._global(col)

    def update_row(self, col, row_index):
        return [(col.data, col.validity, col.lengths)]

    def merge_global(self, bufs):
        return self._global(bufs[0])

    def host_update(self, values):
        vs = [v for v in values if v is not None]
        if not vs:
            return (None,)
        t = self.child.data_type()
        if t.is_floating:
            non_nan = [v for v in vs if not np.isnan(v)]
            if self.kind == "min":
                return (min(non_nan) if non_nan else float("nan"),)
            return (float("nan") if len(non_nan) < len(vs)
                    else max(vs),)
        return (min(vs) if self.kind == "min" else max(vs),)

    def host_merge(self, buf_tuples):
        return self.host_update([b[0] for b in buf_tuples])

    def host_finalize(self, buf):
        return buf[0]


class Max(Min):
    kind = "max"


class Average(AggFunction):
    """avg: partial buffer = (sum double, count long); result double."""

    @property
    def buffer_types(self):
        return (dt.FLOAT64, dt.INT64)

    @property
    def result_type(self):
        return dt.FLOAT64

    def update(self, col, gid, capacity, row_index):
        s, counts = kernels.segment_reduce(
            col.data.astype(jnp.float64), col.validity, gid, capacity, "sum")
        return [(s, counts > 0, None),
                (counts, jnp.ones((capacity,), jnp.bool_), None)]

    def merge(self, bufs, gid, capacity):
        sb, cb = bufs
        s, _ = kernels.segment_reduce(sb.data, sb.validity, gid, capacity,
                                      "sum")
        c = jax.ops.segment_sum(jnp.where(cb.validity, cb.data, 0), gid,
                                num_segments=capacity)
        return [(s, c > 0, None),
                (c, jnp.ones((capacity,), jnp.bool_), None)]

    def finalize(self, bufs):
        sb, cb = bufs
        safe = jnp.where(cb.data > 0, cb.data, 1)
        return sb.data / safe.astype(jnp.float64), cb.data > 0, None

    # -- fast paths ------------------------------------------------------
    @staticmethod
    def _f64_terms(v, has_nans):
        if not has_nans:
            return [("f64", v)]
        finite = jnp.isfinite(v)
        return [("f64", jnp.where(finite, v, 0.0)),
                ("i32", jnp.isnan(v).astype(jnp.int32)),
                ("i32", (v == jnp.inf).astype(jnp.int32)),
                ("i32", (v == -jnp.inf).astype(jnp.int32))]

    def sum_terms_update(self, col, has_nans=True):
        masked = jnp.where(col.validity, col.data.astype(jnp.float64), 0.0)
        return self._f64_terms(masked, has_nans) + \
            [("i32", col.validity.astype(jnp.int32))]

    def sum_terms_merge(self, bufs, has_nans=True):
        sb, cb = bufs
        return self._f64_terms(jnp.where(sb.validity, sb.data, 0.0),
                               has_nans) + \
            [("i64", jnp.where(cb.validity, cb.data, 0))]

    def bufs_from_sums(self, sums, capacity, has_nans=True):
        if has_nans:
            s, nan, pinf, ninf, c = sums
            s = _reapply_nonfinite(s, nan, pinf, ninf)
        else:
            s, c = sums
        c = c.astype(jnp.int64)
        return [(s, c > 0, None),
                (c, jnp.ones((capacity,), jnp.bool_), None)]

    def update_global(self, col, row_index=None, live=None):
        s = jnp.sum(jnp.where(col.validity, col.data.astype(jnp.float64),
                              0.0))
        c = jnp.sum(col.validity.astype(jnp.int64))
        return [(s, c > 0, None), (c, True, None)]

    def update_row(self, col, row_index):
        ones = jnp.ones_like(col.validity)
        return [(col.data.astype(jnp.float64), col.validity, None),
                (col.validity.astype(jnp.int64), ones, None)]

    def merge_global(self, bufs):
        sb, cb = bufs
        s = jnp.sum(jnp.where(sb.validity, sb.data, 0.0))
        c = jnp.sum(jnp.where(cb.validity, cb.data, 0))
        return [(s, c > 0, None), (c, True, None)]

    def host_update(self, values):
        vs = [v for v in values if v is not None]
        if not vs:
            return (None, 0)
        return (float(np.sum(np.asarray(vs, np.float64))), len(vs))

    def host_merge(self, buf_tuples):
        s = [b[0] for b in buf_tuples if b[0] is not None]
        c = sum(b[1] for b in buf_tuples)
        return (float(np.sum(s)) if s else None, c)

    def host_finalize(self, buf):
        s, c = buf
        return None if c == 0 else s / c


class First(AggFunction):
    """first(x[, ignoreNulls]) — order = arrival order within the partition
    stream, same determinism caveat as the reference's GpuFirst."""

    pick = "min"

    def __init__(self, child, ignore_nulls: bool = True):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    @property
    def buffer_types(self):
        return (self.child.data_type(), dt.INT64)

    @property
    def result_type(self):
        return self.child.data_type()

    def _gather(self, col: SortedCol, pos, ok):
        safe = jnp.clip(pos, 0, pos.shape[0] - 1).astype(jnp.int32)
        val = jnp.take(col.data, safe, axis=0)
        vval = jnp.take(col.validity, safe, axis=0) & ok
        if col.lengths is not None:
            lens = jnp.where(vval, jnp.take(col.lengths, safe, axis=0), 0)
            val = jnp.where(vval[:, None], val, 0)
            return val, vval, lens
        val = jnp.where(vval, val, jnp.zeros_like(val))
        return val, vval, None

    def update(self, col, gid, capacity, row_index):
        # Pick by GLOBAL arrival index (monotone across the batch stream, so
        # first/last stays correct through concat+merge), but gather the
        # value by sorted position: the stable fingerprint sort preserves
        # arrival order within a group, so min/max global index coincides
        # with min/max sorted position.
        pos = jnp.arange(capacity, dtype=jnp.int64)
        gidx = pos if row_index is None else row_index.astype(jnp.int64)
        eligible = col.validity if self.ignore_nulls else \
            jnp.ones_like(col.validity)
        bad_pos = jnp.int64(capacity if self.pick == "min" else -1)
        bad_idx = jnp.int64(2 ** 62 if self.pick == "min" else -1)
        red = jax.ops.segment_min if self.pick == "min" else \
            jax.ops.segment_max
        picked_pos = red(jnp.where(eligible, pos, bad_pos), gid,
                         num_segments=capacity)
        picked_idx = red(jnp.where(eligible, gidx, bad_idx), gid,
                         num_segments=capacity)
        ok = (picked_pos < capacity) & (picked_pos >= 0)
        val, vval, lens = self._gather(col, picked_pos, ok)
        return [(val, vval, lens),
                (jnp.where(ok, picked_idx, bad_idx), ok, None)]

    def merge(self, bufs, gid, capacity):
        vcol, icol = bufs
        bad = jnp.int64(2 ** 62 if self.pick == "min" else -1)
        keyed = jnp.where(icol.validity, icol.data, bad)
        red = jax.ops.segment_min if self.pick == "min" else \
            jax.ops.segment_max
        picked_val = red(keyed, gid, num_segments=capacity)
        # Winner = the row holding the reduced index; tie-break by min row.
        row = jnp.arange(capacity, dtype=jnp.int64)
        winner = keyed == jnp.take(picked_val, gid, axis=0)
        wrow = jnp.where(winner & icol.validity, row, capacity)
        first_row = jax.ops.segment_min(wrow, gid, num_segments=capacity)
        ok = first_row < capacity
        val, vval, lens = self._gather(vcol, first_row, ok)
        iv = jnp.take(icol.data, jnp.clip(first_row, 0, capacity - 1)
                      .astype(jnp.int32), axis=0)
        return [(val, vval, lens), (jnp.where(ok, iv, bad), ok, None)]

    def finalize(self, bufs):
        vcol, _ = bufs
        return vcol.data, vcol.validity, vcol.lengths

    def update_row(self, col, row_index):
        eligible = col.validity if self.ignore_nulls else \
            jnp.ones_like(col.validity)
        bad = jnp.int64(2 ** 62 if self.pick == "min" else -1)
        idx = jnp.where(eligible, row_index.astype(jnp.int64), bad)
        return [(col.data, col.validity, col.lengths),
                (idx, eligible, None)]

    def update_global(self, col, row_index=None, live=None):
        cap = col.validity.shape[0]
        pos = jnp.arange(cap, dtype=jnp.int64)
        # With ignore_nulls=False a NULL row still wins, but dead rows
        # (padding / sel-deselected) never do.
        eligible = col.validity if self.ignore_nulls else \
            (live if live is not None else jnp.ones_like(col.validity))
        if self.pick == "min":
            picked = jnp.min(jnp.where(eligible, pos, cap))
            ok = picked < cap
        else:
            picked = jnp.max(jnp.where(eligible, pos, -1))
            ok = picked >= 0
        safe = jnp.clip(picked, 0, cap - 1).astype(jnp.int32)
        val = jnp.take(col.data, safe, axis=0)
        gidx = jnp.take(row_index, safe, axis=0) \
            if row_index is not None else picked
        bad = jnp.int64(2 ** 62 if self.pick == "min" else -1)
        length = jnp.take(col.lengths, safe, axis=0) \
            if col.lengths is not None else None
        vval = ok & jnp.take(col.validity, safe, axis=0)
        return [(val, vval, length), (jnp.where(ok, gidx, bad), ok, None)]

    def merge_global(self, bufs):
        vcol, icol = bufs
        cap = icol.validity.shape[0]
        bad = jnp.int64(2 ** 62 if self.pick == "min" else -1)
        keyed = jnp.where(icol.validity, icol.data, bad)
        best = jnp.min(keyed) if self.pick == "min" else jnp.max(keyed)
        row = jnp.min(jnp.where(icol.validity & (keyed == best),
                                jnp.arange(cap, dtype=jnp.int64), cap))
        ok = (row < cap) & (best != bad)
        safe = jnp.clip(row, 0, cap - 1).astype(jnp.int32)
        val = jnp.take(vcol.data, safe, axis=0)
        length = jnp.take(vcol.lengths, safe, axis=0) \
            if vcol.lengths is not None else None
        iv = jnp.take(icol.data, safe, axis=0)
        return [(val, ok & jnp.take(vcol.validity, safe, axis=0), length),
                (jnp.where(ok, iv, bad), ok, None)]

    def host_update(self, values):
        seq = [(i, v) for i, v in enumerate(values)
               if not (self.ignore_nulls and v is None)]
        if not seq:
            return (None, None)
        i, v = seq[0] if self.pick == "min" else seq[-1]
        return (v, i)

    def host_merge(self, buf_tuples):
        cands = [b for b in buf_tuples if b[1] is not None]
        if not cands:
            return (None, None)
        pickf = min if self.pick == "min" else max
        return pickf(cands, key=lambda b: b[1])

    def host_finalize(self, buf):
        return buf[0]


class Last(First):
    pick = "max"


@dataclasses.dataclass
class AggSpec:
    """A named aggregate in the output (result column). ``distinct`` is
    consumed by mixed_final mode: the fn runs UPDATE over the deduped
    distinct input instead of MERGE over partial buffers."""

    name: str
    fn: AggFunction
    distinct: bool = False


# ---------------------------------------------------------------------------
# The exec
# ---------------------------------------------------------------------------

class HashAggregateExec(Exec):
    """Groupby aggregate. ``mode``:
    - 'partial': emits [keys..., buffers...] for a downstream exchange
    - 'final': consumes partial buffers, emits finalized results
    - 'complete': update+merge+finalize in one node (single-stage plans)
    """

    def __init__(self, child: Exec,
                 group_by: Sequence[Tuple[str, Expression]],
                 aggregates: Sequence[AggSpec],
                 mode: str = "complete"):
        super().__init__(child)
        # 'merge' = final minus the result projection (emits buffers);
        # 'mixed_final' = the distinct combo stage: input layout is
        # [keys..., distinct_x, nd buffers...]; distinct specs UPDATE over
        # x, the rest MERGE their buffers (aggregate.scala:305 distinct
        # partial-merge mode combos).
        assert mode in ("partial", "final", "complete", "merge",
                        "mixed_final")
        self.group_names = tuple(n for n, _ in group_by)
        self.group_exprs = [e for _, e in group_by]
        self.aggs = list(aggregates)
        self.mode = mode

    # -- schemas -------------------------------------------------------------
    @property
    def buffer_schema(self) -> Schema:
        cols: List[Tuple[str, dt.DataType]] = []
        for n, e in zip(self.group_names, self.group_exprs):
            cols.append((n, e.data_type()))
        for spec in self.aggs:
            for bi, bt in enumerate(spec.fn.buffer_types):
                cols.append((f"{spec.name}#buf{bi}", bt))
        return tuple(cols)

    @property
    def schema(self) -> Schema:
        if self.mode in ("partial", "merge"):
            return self.buffer_schema
        cols = [(n, e.data_type())
                for n, e in zip(self.group_names, self.group_exprs)]
        cols += [(s.name, s.fn.result_type) for s in self.aggs]
        return tuple(cols)

    @property
    def _nkeys(self) -> int:
        return len(self.group_exprs)

    # -- device path ---------------------------------------------------------
    def _project_inputs(self, batch: DeviceBatch) -> Tuple[DeviceBatch, list]:
        """[keys..., agg inputs...] working batch + per-agg input ordinal."""
        cols = [as_device_column(e.eval(batch), batch)
                for e in self.group_exprs]
        ords = []
        for spec in self.aggs:
            if spec.fn.child is None:   # count(*)
                ords.append(None)
            else:
                cols.append(as_device_column(spec.fn.child.eval(batch),
                                             batch))
                ords.append(len(cols) - 1)
        from spark_rapids_tpu.exprs.base import project_batch
        return project_batch(cols, batch), ords

    @staticmethod
    def _sorted_col(col: DeviceColumn, perm, slive) -> SortedCol:
        data = jnp.take(col.data, perm, axis=0)
        validity = jnp.take(col.validity, perm, axis=0) & slive
        lens = None
        if col.dtype.is_string:
            lens = jnp.where(validity, jnp.take(col.lengths, perm, axis=0),
                             0)
        return SortedCol(data, validity, lens)

    @staticmethod
    def _buf_column(buf: Buf, bt: dt.DataType, gmask) -> DeviceColumn:
        data, valid, lens = buf
        valid = valid & gmask
        if bt.is_string:
            data = jnp.where(valid[:, None], data.astype(jnp.uint8), 0)
            lens = jnp.where(valid, lens, 0)
            return DeviceColumn(bt, data, valid, lens)
        data = jnp.where(valid, data.astype(bt.np_dtype),
                         jnp.zeros((), bt.np_dtype))
        return DeviceColumn(bt, data, valid)

    # -- sorted-path machinery ----------------------------------------------
    def _group_sorted(self, work: DeviceBatch):
        """Group + ONE packed gather of the whole batch to group-sorted
        order (rowmove.py): per-column takes cost ~40-60ms each at 1M rows
        on this chip; the packed 2D form moves every column at once."""
        from spark_rapids_tpu.columnar.rowmove import gather_rows
        g = kernels.group_ids(work, range(self._nkeys))
        live = work.live_count()
        sorted_b = gather_rows(work, g.perm, live)
        slive = jnp.arange(work.capacity, dtype=jnp.int32) < live
        return g, sorted_b, slive

    @staticmethod
    def _segment_sums(stacks, gid, slive, capacity):
        """ALL group sums with one cumsum + boundary shift-diff per dtype
        class. Values arrive pre-masked (dead/null rows contribute 0).
        Groups are contiguous ascending runs of ``gid`` in sorted order, so
        group g's sum = prefix(end_g) - prefix(end_{g-1})."""
        idx = jnp.arange(capacity, dtype=jnp.int32)
        nxt_gid = jnp.concatenate([gid[1:], gid[-1:]])
        nxt_live = jnp.concatenate([slive[1:], jnp.zeros((1,), jnp.bool_)])
        last = slive & ((idx == capacity - 1) | (nxt_gid != gid)
                        | ~nxt_live)
        ends = jnp.zeros((capacity,), jnp.int32).at[
            jnp.where(last, gid, capacity)].set(idx, mode="drop")
        out = {}
        for cls, arrs in stacks.items():
            M = jnp.stack(arrs, axis=1)
            S = jnp.cumsum(M, axis=0)
            Se = jnp.take(S, ends, axis=0)
            out[cls] = jnp.concatenate([Se[:1], Se[1:] - Se[:-1]], axis=0)
        return out

    def _run_specs(self, spec_inputs, gid, slive, capacity, row_index,
                   has_nans: bool = True):
        """Shared spec-evaluation core: ``spec_inputs`` yields per spec
        ("update", SortedCol) or ("merge", [SortedCol...]). Sum-decomposable
        specs ride the stacked-cumsum path; the rest use their segment
        kernels. Returns the flat buffer list (per spec, per buffer)."""
        stacks: dict = {}
        plans = []          # per spec: ("sum", [(cls, pos)...]) | ("raw", bufs)
        for spec, (kind, arg) in zip(self.aggs, spec_inputs):
            terms = spec.fn.sum_terms_update(arg, has_nans) \
                if kind == "update" \
                else spec.fn.sum_terms_merge(arg, has_nans)
            if terms is not None:
                slots = []
                for cls, values in terms:
                    stacks.setdefault(cls, []).append(values)
                    slots.append((cls, len(stacks[cls]) - 1))
                plans.append(("sum", slots))
            elif kind == "update":
                plans.append(("raw", spec.fn.update(arg, gid, capacity,
                                                    row_index)))
            else:
                plans.append(("raw", spec.fn.merge(arg, gid, capacity)))
        sums = self._segment_sums(stacks, gid, slive, capacity) \
            if stacks else {}
        out = []
        for spec, plan in zip(self.aggs, plans):
            if plan[0] == "sum":
                vals = [sums[cls][:, pos] for cls, pos in plan[1]]
                out.append(spec.fn.bufs_from_sums(vals, capacity,
                                                  has_nans))
            else:
                out.append(plan[1])
        return out

    def _assemble(self, work: DeviceBatch, g, all_bufs) -> DeviceBatch:
        """Key columns at group leaders (one small packed gather) + buffer
        columns -> the output buffer batch."""
        from spark_rapids_tpu.columnar.rowmove import gather_rows
        cap = work.capacity
        gmask = jnp.arange(cap, dtype=jnp.int32) < g.num_groups
        out_cols: List[DeviceColumn] = []
        if self._nkeys:
            keys = gather_rows(work.select(range(self._nkeys)),
                               g.group_leader, g.num_groups)
            out_cols.extend(keys.columns)
        for spec, bufs in zip(self.aggs, all_bufs):
            for buf, bt in zip(bufs, spec.fn.buffer_types):
                out_cols.append(self._buf_column(buf, bt, gmask))
        return DeviceBatch(tuple(out_cols), g.num_groups)

    def _sorted_view(self, sorted_b: DeviceBatch, ord_: int) -> SortedCol:
        c = sorted_b.columns[ord_]
        return SortedCol(c.data, c.validity, c.lengths)

    def _update_batch(self, batch: DeviceBatch,
                      offset: jnp.ndarray) -> DeviceBatch:
        """One input batch -> partial buffer batch. ``offset`` is the global
        arrival index of this batch's row 0 (orders First/Last across the
        stream)."""
        work, ords = self._project_inputs(batch)
        if self._global_ok:
            return self._global_stage(work, ords, offset, update=True)
        cap = work.capacity
        g, sorted_b, slive = self._group_sorted(work)
        row_index = offset.astype(jnp.int64) + g.perm.astype(jnp.int64)
        inputs = []
        for spec, ord_ in zip(self.aggs, ords):
            if ord_ is None:
                inputs.append(("update",
                               SortedCol(jnp.zeros((cap,), jnp.int64),
                                         slive)))
            else:
                inputs.append(("update", self._sorted_view(sorted_b, ord_)))
        bufs = self._run_specs(inputs, g.group_of_sorted, slive, cap,
                               row_index, self._has_nans)
        return self._assemble(work, g, bufs)

    def _merge_batch(self, batch: DeviceBatch) -> DeviceBatch:
        """Merge a buffer batch (re-group by keys, merge buffers)."""
        if self._global_ok:
            return self._global_stage(batch, None, None, update=False)
        cap = batch.capacity
        g, sorted_b, slive = self._group_sorted(batch)
        ci = self._nkeys
        inputs = []
        for spec in self.aggs:
            nbuf = len(spec.fn.buffer_types)
            inputs.append(("merge",
                           [self._sorted_view(sorted_b, ci + b)
                            for b in range(nbuf)]))
            ci += nbuf
        bufs = self._run_specs(inputs, g.group_of_sorted, slive, cap, None,
                               self._has_nans)
        return self._assemble(batch, g, bufs)

    def _mixed_batch(self, batch: DeviceBatch) -> DeviceBatch:
        """Distinct combo stage: input [keys..., x, nd buffers...] with
        (keys, x) already unique; group by keys only; distinct specs
        update over x, others merge buffers. Output is the standard
        buffer layout [keys..., all buffers...]."""
        cap = batch.capacity
        g, sorted_b, slive = self._group_sorted(batch)
        x_ord = self._nkeys
        ci = self._nkeys + 1            # nd buffers follow the x column
        row_index = g.perm.astype(jnp.int64)
        inputs = []
        for spec in self.aggs:
            if spec.distinct:
                inputs.append(("update", self._sorted_view(sorted_b,
                                                           x_ord)))
            else:
                nbuf = len(spec.fn.buffer_types)
                inputs.append(("merge",
                               [self._sorted_view(sorted_b, ci + b)
                                for b in range(nbuf)]))
                ci += nbuf
        bufs = self._run_specs(inputs, g.group_of_sorted, slive, cap,
                               row_index)
        return self._assemble(batch, g, bufs)

    # -- zero-key fast path ---------------------------------------------------
    @property
    def _global_ok(self) -> bool:
        """Zero grouping keys and every fn supports whole-batch masked
        reductions (no sort, no segment scatters — a 1M-row f64 masked sum
        costs ~46ms vs ~700ms through the sorted path on this chip)."""
        if self._nkeys != 0 or self.mode == "mixed_final":
            return False
        for spec in self.aggs:
            fn = spec.fn
            if isinstance(fn, Min) and fn.child.data_type().is_string:
                return False
        return True

    def _global_stage(self, work: DeviceBatch, ords, offset,
                      update: bool) -> DeviceBatch:
        live = work.row_mask()
        all_bufs = []
        if update:
            cap = work.capacity
            row_index = offset.astype(jnp.int64) + \
                jnp.arange(cap, dtype=jnp.int64)
            for spec, ord_ in zip(self.aggs, ords):
                if ord_ is None:
                    col = SortedCol(jnp.zeros((cap,), jnp.int64), live)
                else:
                    c = work.columns[ord_]
                    col = SortedCol(c.data, c.validity & live, c.lengths)
                all_bufs.append(spec.fn.update_global(col, row_index,
                                                      live=live))
        else:
            ci = self._nkeys
            for spec in self.aggs:
                nbuf = len(spec.fn.buffer_types)
                bufs = []
                for b in range(nbuf):
                    c = work.columns[ci + b]
                    bufs.append(SortedCol(c.data, c.validity & live,
                                          c.lengths))
                ci += nbuf
                all_bufs.append(spec.fn.merge_global(bufs))
        return self._global_assemble(all_bufs)

    def _global_assemble(self, all_bufs) -> DeviceBatch:
        cap = 8
        first = jnp.arange(cap, dtype=jnp.int32) < 1
        out_cols: List[DeviceColumn] = []
        for spec, bufs in zip(self.aggs, all_bufs):
            for (val, ok, length), bt in zip(bufs, spec.fn.buffer_types):
                valid = first & jnp.asarray(ok, jnp.bool_)
                if bt.is_string:
                    w = val.shape[-1]
                    data = jnp.zeros((cap, w), jnp.uint8).at[0].set(
                        val.astype(jnp.uint8))
                    lens = jnp.zeros((cap,), jnp.int32).at[0].set(
                        jnp.asarray(length, jnp.int32))
                    out_cols.append(self._buf_column((data, valid, lens),
                                                     bt, first))
                else:
                    data = jnp.zeros((cap,), bt.np_dtype).at[0].set(
                        jnp.asarray(val).astype(bt.np_dtype))
                    out_cols.append(self._buf_column((data, valid, None),
                                                     bt, first))
        return DeviceBatch(tuple(out_cols), jnp.asarray(1, jnp.int32))

    def _finalize_batch(self, batch: DeviceBatch) -> DeviceBatch:
        out_cols = list(batch.columns[:self._nkeys])
        ci = self._nkeys
        gmask = batch.row_mask()
        for spec in self.aggs:
            nbuf = len(spec.fn.buffer_types)
            bufs = [SortedCol(batch.columns[ci + b].data,
                              batch.columns[ci + b].validity,
                              batch.columns[ci + b].lengths)
                    for b in range(nbuf)]
            data, valid, lens = spec.fn.finalize(bufs)
            out_cols.append(self._buf_column((data, valid, lens),
                                             spec.fn.result_type, gmask))
            ci += nbuf
        return DeviceBatch(tuple(out_cols), batch.num_rows)

    def _passthrough_batch(self, batch: DeviceBatch,
                           offset: jnp.ndarray) -> DeviceBatch:
        """Partial-skip path: project each ROW into the buffer layout with
        no grouping at all (pure elementwise — the measured reduction ratio
        said grouping here would not pay for itself)."""
        work, ords = self._project_inputs(batch)
        cap = work.capacity
        live = work.row_mask()
        row_index = offset.astype(jnp.int64) + \
            jnp.arange(cap, dtype=jnp.int64)
        out_cols = list(work.columns[:self._nkeys])
        for spec, ord_ in zip(self.aggs, ords):
            if ord_ is None:
                col = SortedCol(jnp.zeros((cap,), jnp.int64), live)
            else:
                c = work.columns[ord_]
                col = SortedCol(c.data, c.validity & live, c.lengths)
            bufs = spec.fn.update_row(col, row_index)
            for buf, bt in zip(bufs, spec.fn.buffer_types):
                out_cols.append(self._buf_column(buf, bt, live))
        return DeviceBatch(tuple(out_cols), work.num_rows, sel=work.sel)

    @property
    def _rowskip_capable(self) -> bool:
        return self._nkeys > 0 and all(
            type(s.fn).update_row is not AggFunction.update_row
            for s in self.aggs)

    _has_nans = True    # set from conf before the jits are built

    def _jits(self):
        """Aggregation-stage kernels from the PROCESS-GLOBAL kernel cache,
        keyed by the structural identity of the aggregation (mode, group
        expressions, agg specs, hasNans term layout): a fresh query — a
        new bench iteration, a re-planned DataFrame — reuses the compiled
        update/merge/finalize programs instead of re-tracing them per
        exec instance. The jitted bound methods belong to a child-severed
        clone so a cache entry never pins the plan subtree."""
        from spark_rapids_tpu.ops import kernel_cache as kc
        key = ("agg-fns", type(self).__name__, self.mode, self._has_nans,
               kc.fingerprint(tuple(self.group_names)),
               kc.fingerprint(tuple(self.group_exprs)),
               kc.fingerprint(tuple(self.aggs)))

        def build():
            clone = kc.detached_clone(self)
            clone._has_nans = self._has_nans
            return (jax.jit(clone._update_batch),
                    jax.jit(clone._merge_batch),
                    jax.jit(clone._finalize_batch),
                    jax.jit(clone._mixed_batch),
                    jax.jit(clone._passthrough_batch))

        fns, _ = kc.cache().get(key, build)
        return fns

    # Max batches concatenated per merge step: bounds the transient HBM of
    # a consolidation to CHUNK x batch-capacity (a 70-wide concat of
    # high-cardinality partials OOMed the chip on TPC-DS q67's rollup).
    _CONSOLIDATE_CHUNK = 12

    def _consolidate(self, ctx, m, pending: List[DeviceBatch],
                     final_stage: bool = False) -> DeviceBatch:
        """Chunked tree of shrink + concat + merge over the pending list.

        Each level does ONE batched sizes pull for its hint-less batches
        (a sync is a full network round trip on a tunneled chip; exchange
        pieces carry ``rows_hint`` so the final stage's first level
        usually needs no sync), concats chunks of at most
        ``_CONSOLIDATE_CHUNK`` members, and runs the grouping stage on
        each chunk — grouping shrinks the data level by level, so peak
        HBM stays bounded regardless of how many partials a partition
        accumulated. mixed_final's distinct-update kernel is chunk-safe:
        its distinct inputs are globally unique rows, so chunk updates
        followed by plain merges count each value exactly once."""
        from spark_rapids_tpu.columnar.batch import (
            jit_concat_batches, shrink_all)
        _, merge, finalize, mixed, _pt = self._jits()
        first_stage = {"final": merge, "merge": merge,
                       "mixed_final": mixed}.get(self.mode)
        level = 0
        batches = pending
        while True:
            with timed(m, "sizesPullTime"):
                batches, _ = shrink_all(batches)
            if len(batches) == 1:
                single = batches[0]
                if level == 0 and first_stage is not None:
                    single = first_stage(single)
                break
            stage = first_stage if (level == 0 and
                                    first_stage is not None) else merge
            nxt = []
            for i in range(0, len(batches), self._CONSOLIDATE_CHUNK):
                grp = batches[i:i + self._CONSOLIDATE_CHUNK]
                if len(grp) == 1:
                    # Level >= 1 singletons are already merge outputs.
                    nxt.append(stage(grp[0]) if level == 0 else grp[0])
                    continue
                cap = bucket_capacity(sum(b.capacity for b in grp))
                nxt.append(stage(jit_concat_batches(grp, cap)))
            batches = nxt
            level += 1
            if len(batches) == 1:
                single = batches[0]
                break
        if final_stage and self.mode in ("final", "complete",
                                         "mixed_final"):
            single = finalize(single)
        return single

    def execute_device(self, ctx, partition):
        import jax as _jax
        from spark_rapids_tpu import config as _C
        m = ctx.metrics_for(self)
        self._has_nans = bool(ctx.conf.get(_C.HAS_NANS))
        update, merge, finalize, mixed, passthrough = self._jits()

        from spark_rapids_tpu import config as C
        pending: List[DeviceBatch] = []
        pending_cap = 0
        saw_input = False
        offset = 0
        update_stage = self.mode in ("partial", "complete")
        # Adaptive partial-skip (skipAggPassReductionRatio): measure the
        # FIRST partial batch's reduction; if grouping barely reduced it,
        # later batches project rows straight into the buffer layout and
        # the post-exchange stage does all grouping once. One decision per
        # query (cached in ctx), one small device sync to make it.
        skip_key = f"aggskip:{id(self):x}"
        skip_ratio = float(ctx.conf.get(C.AGG_SKIP_PARTIAL_RATIO))
        can_skip = (self.mode == "partial" and skip_ratio < 1.0
                    and getattr(self, "allow_partial_skip", True)
                    and self._rowskip_capable)
        # Memory guard: when buffered partials exceed this many rows of
        # capacity, consolidate early (mirrors the reference's iterative
        # re-merge loop, aggregate.scala:427 — but amortized, not
        # per-batch). Deliberately NOT tied to batchSizeRows: that knob
        # tunes coalescing, this one bounds buffered-state high water.
        consolidate_at = max(8 << 20,
                             2 * int(ctx.conf.get(C.BATCH_SIZE_ROWS)))
        child_iter = self.children[0].execute_device(ctx, partition)
        if update_stage and not self._global_ok:
            # Coalesce the input stream: one sort-based update kernel over
            # a 4M-row batch beats 8 over 512k (fixed per-dispatch floor),
            # and sparse join outputs compact before the capacity-scaled
            # sort. Zero-key aggregates skip this: their masked reductions
            # don't sort, so the concat gather would be pure overhead.
            from spark_rapids_tpu.columnar.batch import coalesce_iter
            from spark_rapids_tpu.memory.oom import effective_batch_target
            child_iter = coalesce_iter(
                child_iter,
                effective_batch_target(
                    int(ctx.conf.get(C.BATCH_SIZE_ROWS))),
                shrink=True,
                target_bytes=int(ctx.conf.get(C.BATCH_SIZE_BYTES)))
        for batch in child_iter:
            saw_input = True
            if update_stage:
                from spark_rapids_tpu.memory.oom import retry_on_oom
                skipping = can_skip and ctx.cache.get(skip_key, False)
                with timed(m):
                    if skipping:
                        partial = retry_on_oom(
                            passthrough,
                            batch, jnp.asarray(offset, jnp.int64))
                    else:
                        partial = retry_on_oom(
                            update, batch, jnp.asarray(offset, jnp.int64))
                if can_skip and skip_key not in ctx.cache:
                    groups, live = _jax.device_get(
                        [partial.num_rows, batch.live_count()])
                    ctx.cache[skip_key] = \
                        int(groups) >= skip_ratio * max(int(live), 1)
                offset += batch.capacity
                if self.mode == "partial":
                    # Partial stage feeds an exchange, which batches its
                    # own sizes pull across every partition — emit the
                    # per-batch partial as-is, no sync here.
                    record_batch(m, partial)
                    yield partial
                    continue
                pending.append(partial)
                pending_cap += partial.capacity
            else:
                # final/merge/mixed_final: defer ALL grouping to one
                # consolidated pass over the partition's batches.
                pending.append(batch)
                pending_cap += batch.capacity
            # mixed_final's kernel is NOT idempotent (it reads a raw x
            # column that its own output no longer has) — never consolidate
            # it mid-stream, only once at the end.
            if pending_cap > consolidate_at and len(pending) > 1 \
                    and self.mode != "mixed_final":
                with timed(m):
                    merged = self._consolidate(ctx, m, pending)
                pending = [merged]
                pending_cap = merged.capacity
        if self.mode == "partial":
            return
        if not saw_input:
            if self._nkeys == 0 and self.mode in ("final", "complete",
                                                  "mixed_final"):
                yield self._empty_result()
            return
        with timed(m):
            acc = self._consolidate(ctx, m, pending, final_stage=True)
        record_batch(m, acc)
        yield acc

    def _empty_result(self) -> DeviceBatch:
        cap = 8
        cols = []
        for spec in self.aggs:
            t = spec.fn.result_type
            if isinstance(spec.fn, (Count, CountStar)):
                data = jnp.zeros((cap,), t.np_dtype)
                valid = jnp.arange(cap) < 1
            else:
                data = jnp.zeros((cap,), t.np_dtype)
                valid = jnp.zeros((cap,), jnp.bool_)
            if t.is_string:
                cols.append(DeviceColumn(t, jnp.zeros((cap, 8), jnp.uint8),
                                         valid, jnp.zeros((cap,), jnp.int32)))
            else:
                cols.append(DeviceColumn(t, data, valid))
        return DeviceBatch(tuple(cols), jnp.asarray(1, jnp.int32))

    # -- host oracle ---------------------------------------------------------
    def _host_groups(self, hbs, key_evaluator, input_lists):
        """Shared host grouping: returns (order, key_values, groups) where
        groups[key][ai] is the list of python values for aggregate ai.

        Primitive (non-string) keys take a vectorized path — one stable
        lexsort over canonicalized key arrays instead of a per-row python
        dict walk. Host placement (plan/cost.py) made the host engine a
        first-class executor, so grouping millions of rows here must run
        at numpy speed, not interpreter speed (~50x). Semantics are
        identical: first-seen group order, within-group row order (First/
        Last), NaN==NaN and -0.0==0.0 canonical grouping, null keys group
        together."""
        fast = self._host_groups_vectorized(hbs, key_evaluator,
                                            input_lists)
        if fast is not None:
            return fast
        groups = {}
        key_values = {}
        order = []
        for hb, keycols, inlists in zip(hbs, key_evaluator, input_lists):
            for i in range(hb.num_rows):
                triples = [self._host_key(kc, i) for kc in keycols]
                # Canonical key only — raw floats break NaN equality.
                key = tuple((t[0], t[1]) for t in triples)
                if key not in groups:
                    groups[key] = [[] for _ in self.aggs]
                    key_values[key] = [t[2] if t[0] else None
                                       for t in triples]
                    order.append(key)
                for ai, vals in enumerate(inlists):
                    groups[key][ai].append(vals[i] if vals is not None
                                           else 1)
        return order, key_values, groups

    def _host_groups_vectorized(self, hbs, key_evaluator, input_lists):
        """The numpy fast path of :meth:`_host_groups`, or None when the
        shape doesn't qualify (string keys keep the exact python-loop
        canonicalization)."""
        nrows = [hb.num_rows for hb in hbs]
        total = sum(nrows)
        if total == 0:
            return [], {}, {}
        keycols0 = key_evaluator[0] if key_evaluator else []
        if any(kc.dtype.is_string for kc in keycols0):
            return None
        nkeys = len(keycols0)
        nags = len(self.aggs)

        def group_lists(idx_groups):
            out_per_agg = []
            for ai in range(nags):
                parts = [il[ai] for il in input_lists]
                if any(p is None for p in parts):
                    out_per_agg.append([[1] * len(idx)
                                        for idx in idx_groups])
                    continue
                merged = parts[0] if len(parts) == 1 else \
                    [v for p in parts for v in p]
                arr = np.empty(len(merged), dtype=object)
                try:
                    arr[:] = merged          # scalars: one C-level copy
                    ok = True
                except (ValueError, TypeError):
                    ok = False               # tuple rows (merge buffers)
                if ok:
                    out_per_agg.append([arr[idx].tolist()
                                        for idx in idx_groups])
                else:
                    out_per_agg.append([[merged[i] for i in idx.tolist()]
                                        for idx in idx_groups])
            return out_per_agg

        if nkeys == 0:
            idx_all = np.arange(total, dtype=np.int64)
            per_agg = group_lists([idx_all])
            key = ()
            return [key], {key: []}, {key: [per_agg[ai][0]
                                            for ai in range(nags)]}

        # Canonicalize each key column across batches: an exact-equality
        # uint64/int64 view where NaNs share one bit pattern, -0.0 == 0.0
        # and invalid rows compare equal regardless of payload.
        views = []
        valids = []
        raws = []
        for ki in range(nkeys):
            cols = [ke[ki] for ke in key_evaluator]
            data = np.concatenate([np.asarray(c.data) for c in cols]) \
                if len(cols) > 1 else np.asarray(cols[0].data)
            valid = np.concatenate([np.asarray(c.validity)
                                    for c in cols]) \
                if len(cols) > 1 else np.asarray(cols[0].validity)
            dtype = cols[0].dtype
            if dtype.is_floating:
                d = data.astype(np.float64) + 0.0     # -0.0 -> +0.0
                nanmask = np.isnan(d)
                if nanmask.any():
                    d = d.copy()
                    d[nanmask] = np.nan               # canonical NaN bits
                view = d.view(np.uint64).astype(np.int64, copy=False)
            elif dtype.is_boolean:
                view = data.astype(np.int64)
            else:
                view = data.astype(np.int64, copy=False)
            view = np.where(valid, view, np.int64(0))
            views.append(view)
            valids.append(valid.astype(np.int8))
            raws.append((dtype, data, valid))
        order_idx = np.lexsort(tuple(
            a for ki in range(nkeys - 1, -1, -1)
            for a in (views[ki], valids[ki])))
        new_flags = np.zeros(total, dtype=bool)
        new_flags[0] = True
        for ki in range(nkeys):
            sv = views[ki][order_idx]
            sa = valids[ki][order_idx]
            new_flags[1:] |= (sv[1:] != sv[:-1]) | (sa[1:] != sa[:-1])
        starts = np.flatnonzero(new_flags)
        ends = np.append(starts[1:], total)
        # First-seen emission order: lexsort is stable, so order_idx at a
        # group's start IS its first original row.
        emit = np.argsort(order_idx[starts], kind="stable")
        # Within a group, order_idx is already ascending (stable sort
        # keeps equal keys in original row order — First/Last depend on
        # it).
        idx_groups = [order_idx[starts[g]:ends[g]] for g in emit]
        per_agg = group_lists(idx_groups)
        order = []
        key_values = {}
        groups = {}
        for gi, g in enumerate(emit):
            rep = int(order_idx[starts[g]])
            key = []
            vals = []
            for ki in range(nkeys):
                v_ok = bool(valids[ki][rep])
                key.append((v_ok, int(views[ki][rep])))
                if not v_ok:
                    vals.append(None)
                    continue
                dtype, data, _ = raws[ki]
                if dtype.is_floating:
                    f = float(data[rep])
                    vals.append(0.0 if f == 0.0 else f)
                elif dtype.is_boolean:
                    vals.append(bool(data[rep]))
                else:
                    vals.append(int(data[rep]))
            key = tuple(key)
            order.append(key)
            key_values[key] = vals
            groups[key] = [per_agg[ai][gi] for ai in range(nags)]
        return order, key_values, groups

    # -- vectorized host engine ---------------------------------------------
    def _host_segments(self, key_pieces, total):
        """Group segmentation over per-batch key column pieces: one stable
        lexsort over (encode_key_concat, validity) planes per key. Returns
        ``(order_idx, starts, ends, emit, rep_idx, key_enc)`` where
        starts/ends are ascending (reduceat currency), ``emit`` permutes
        sorted-group order into first-seen emission order, ``rep_idx``
        is each group's first original row in emission order, and
        ``key_enc`` is the per-key ``(codes, space)`` list — the caller
        stamps these onto the concatenated key columns so the encoding
        survives into this aggregate's OUTPUT and the next consumer
        (shuffle -> final agg) merges dictionaries instead of
        re-ranking rows.

        Keys arrive as the UNCONCATENATED per-batch pieces so encoding
        can dedupe repeated column instances (grouping-set expansion)
        instead of re-ranking the materialized concat."""
        from spark_rapids_tpu.columnar.host import encode_key_concat
        nkeys = len(key_pieces)
        if nkeys == 0:
            order_idx = np.arange(total, dtype=np.int64)
            one = np.zeros(1, np.int64)
            return (order_idx, one, np.asarray([total], np.int64), one,
                    one.copy(), [])
        codes, valids, spaces = [], [], []
        for pieces in key_pieces:
            c, v, space = encode_key_concat(pieces)
            codes.append(c)
            valids.append(v.view(np.int8))
            spaces.append(space)
        # Pack (valid, code) pairs into as few int64 planes as their
        # value ranges allow: a 9-key rollup that would lexsort and
        # diff-scan 18 planes usually fits in one packed word (string
        # codes are dense ranks, int keys span small ranges). Packing is
        # injective per key, so segment contiguity and the stable
        # within-group order are exactly those of the unpacked sort —
        # only the (irrelevant, emit-normalized) group order changes.
        planes: list = []
        acc = None
        acc_range = 1
        _cap = 1 << 62
        for ki in range(nkeys):
            c, v = codes[ki], valids[ki].astype(np.int64)
            cmin = int(c.min())
            crange = int(c.max()) - cmin + 1
            r = 2 * crange
            if r > _cap:
                if acc is not None:
                    planes.append(acc)
                    acc, acc_range = None, 1
                planes.append(v)        # valid outranks code (null group)
                planes.append(c)
                continue
            local = v * crange + (c - cmin)
            if acc is None:
                acc, acc_range = local, r
            elif acc_range * r <= _cap:
                acc = acc * r + local
                acc_range *= r
            else:
                planes.append(acc)
                acc, acc_range = local, r
        if acc is not None:
            planes.append(acc)
        from spark_rapids_tpu.columnar.host import stable_code_argsort
        order_idx = stable_code_argsort(planes[0]) if len(planes) == 1 \
            else np.lexsort(tuple(planes[::-1]))
        new_flags = np.zeros(total, dtype=bool)
        new_flags[0] = True
        for p in planes:
            sp = p[order_idx]
            new_flags[1:] |= sp[1:] != sp[:-1]
        starts = np.flatnonzero(new_flags).astype(np.int64)
        ends = np.append(starts[1:], total)
        emit = np.argsort(order_idx[starts], kind="stable").astype(np.int64)
        rep_idx = order_idx[starts][emit]
        return (order_idx, starts, ends, emit, rep_idx,
                list(zip(codes, spaces)))

    def _host_exec_vectorized(self, hbs):
        """One vectorized pass covering every host aggregation mode
        (update/complete over inputs, merge/final over buffers,
        mixed_final), or None when the shape doesn't qualify (empty
        input, string min/max, an agg without a segment kernel) — the
        per-row python grouping below stays as the oracle fallback."""
        from spark_rapids_tpu.columnar.host import concat_host_batches
        total = sum(hb.num_rows for hb in hbs)
        if total == 0:
            return None
        for spec in self.aggs:
            fn = spec.fn
            if isinstance(fn, (Count, Average, Sum, First)):
                continue
            if isinstance(fn, Min):
                if fn.child.data_type().is_string:
                    return None
                continue
            return None

        def concat_col(cols):
            if len(cols) == 1:
                return cols[0]
            return concat_host_batches(
                [HostBatch(("c",), [c]) for c in cols]).columns[0]

        mode = self.mode
        agg_inputs = []
        if mode in ("partial", "complete"):
            kind = "update" if mode == "partial" else "agg"
            keysrc = [[as_host_column(e.eval_host(hb), hb)
                       for e in self.group_exprs] for hb in hbs]
            for spec in self.aggs:
                if spec.fn.child is None:
                    agg_inputs.append((kind, [None]))
                else:
                    agg_inputs.append((kind, [concat_col(
                        [as_host_column(spec.fn.child.eval_host(hb), hb)
                         for hb in hbs])]))
        elif mode in ("final", "merge"):
            kind = "final" if mode == "final" else "merge"
            keysrc = [list(hb.columns[:self._nkeys]) for hb in hbs]
            ci = self._nkeys
            for spec in self.aggs:
                nbuf = len(spec.fn.buffer_types)
                agg_inputs.append((kind, [
                    concat_col([hb.columns[ci + b] for hb in hbs])
                    for b in range(nbuf)]))
                ci += nbuf
        else:                                   # mixed_final
            keysrc = [list(hb.columns[:self._nkeys]) for hb in hbs]
            xcol = concat_col([hb.columns[self._nkeys] for hb in hbs])
            ci = self._nkeys + 1
            for spec in self.aggs:
                if spec.distinct:
                    agg_inputs.append(("agg", [xcol]))
                else:
                    nbuf = len(spec.fn.buffer_types)
                    agg_inputs.append(("final", [
                        concat_col([hb.columns[ci + b] for hb in hbs])
                        for b in range(nbuf)]))
                    ci += nbuf
        key_cols = [concat_col([ks[ki] for ks in keysrc])
                    for ki in range(self._nkeys)]
        (order_idx, starts, ends, emit, rep_idx,
         key_enc) = self._host_segments(
            [[ks[ki] for ks in keysrc] for ki in range(self._nkeys)],
            total)
        for kc, (codes, space) in zip(key_cols, key_enc):
            # The concat rows ARE the rows these codes were computed
            # for; stamping lets take(rep_idx) below propagate them.
            if kc._key_codes is None:
                kc._key_codes = codes
                kc._key_uniq = space
        out_cols = []
        for kc in key_cols:
            oc = kc.take(rep_idx)
            if oc.dtype.is_floating:
                # Canonical zero on output: -0.0 group reps emit as 0.0
                # (grouping already treats them equal).
                oc = HostColumn(oc.dtype,
                                oc.data + oc.dtype.np_dtype.type(0),
                                oc.validity)
            out_cols.append(oc)
        for (kind, cols), spec in zip(agg_inputs, self.aggs):
            res = _host_seg_agg(spec.fn, kind, cols, order_idx, starts,
                                ends, total)
            if res is None:
                return None
            out_cols.extend(rc.take(emit) for rc in res)
        return HostBatch(tuple(n for n, _ in self.schema), out_cols)

    def execute_host(self, ctx, partition):
        hbs = list(self.children[0].execute_host(ctx, partition))
        fast = self._host_exec_vectorized(hbs)
        if fast is not None:
            yield fast
            return
        if self.mode in ("final", "merge"):
            yield from self._execute_host_final(
                hbs, do_finalize=self.mode == "final")
            return
        if self.mode == "mixed_final":
            yield from self._execute_host_mixed(hbs)
            return
        key_evaluator = []
        input_lists = []
        for hb in hbs:
            key_evaluator.append([as_host_column(e.eval_host(hb), hb)
                                  for e in self.group_exprs])
            inlists = []
            for spec in self.aggs:
                if spec.fn.child is None:
                    inlists.append(None)
                else:
                    inlists.append(as_host_column(
                        spec.fn.child.eval_host(hb), hb).to_list())
            input_lists.append(inlists)
        order, key_values, groups = self._host_groups(hbs, key_evaluator,
                                                      input_lists)
        rows: List[tuple] = []
        for key in order:
            vals = list(key_values[key])
            for ai, spec in enumerate(self.aggs):
                if self.mode == "partial":
                    vals.extend(spec.fn.host_update(groups[key][ai]))
                else:
                    vals.append(spec.fn.host_agg(groups[key][ai]))
            rows.append(tuple(vals))
        if not rows and self._nkeys == 0:
            vals = []
            for spec in self.aggs:
                if self.mode == "partial":
                    vals.extend(spec.fn.host_update([]))
                else:
                    vals.append(spec.fn.host_agg([]))
            rows = [tuple(vals)]
        yield _rows_to_host_batch(rows, self.schema)

    def _execute_host_final(self, hbs, do_finalize: bool = True):
        """Host final/merge mode: group buffer rows by key, merge buffer
        tuples; 'merge' emits the merged buffers unfinalized."""
        key_evaluator = []
        buf_lists = []
        for hb in hbs:
            key_evaluator.append(list(hb.columns[:self._nkeys]))
            # One pseudo-input per aggregate: the tuple of its buffer values.
            ci = self._nkeys
            per_agg = []
            for spec in self.aggs:
                nbuf = len(spec.fn.buffer_types)
                cols = [hb.columns[ci + b].to_list() for b in range(nbuf)]
                per_agg.append(list(zip(*cols)) if cols else [])
                ci += nbuf
            buf_lists.append(per_agg)
        order, key_values, groups = self._host_groups(hbs, key_evaluator,
                                                      buf_lists)
        rows = []
        for key in order:
            vals = list(key_values[key])
            for ai, spec in enumerate(self.aggs):
                merged = spec.fn.host_merge(groups[key][ai])
                if do_finalize:
                    vals.append(spec.fn.host_finalize(merged))
                else:
                    vals.extend(merged)
            rows.append(tuple(vals))
        yield _rows_to_host_batch(rows, self.schema)

    def _execute_host_mixed(self, hbs):
        """Host mixed_final: input rows are unique by (keys, x); distinct
        specs aggregate the x values, others merge their buffers."""
        key_evaluator = []
        input_lists = []
        x_ord = self._nkeys
        for hb in hbs:
            key_evaluator.append(list(hb.columns[:self._nkeys]))
            xvals = hb.columns[x_ord].to_list()
            ci = self._nkeys + 1
            per_agg = []
            for spec in self.aggs:
                if spec.distinct:
                    per_agg.append(xvals)
                else:
                    nbuf = len(spec.fn.buffer_types)
                    cols = [hb.columns[ci + b].to_list()
                            for b in range(nbuf)]
                    per_agg.append(list(zip(*cols)) if cols else [])
                    ci += nbuf
            input_lists.append(per_agg)
        order, key_values, groups = self._host_groups(hbs, key_evaluator,
                                                      input_lists)
        rows = []
        for key in order:
            vals = list(key_values[key])
            for ai, spec in enumerate(self.aggs):
                if spec.distinct:
                    vals.append(spec.fn.host_agg(groups[key][ai]))
                else:
                    merged = spec.fn.host_merge(groups[key][ai])
                    vals.append(spec.fn.host_finalize(merged))
            rows.append(tuple(vals))
        if not rows and self._nkeys == 0:
            vals = []
            for spec in self.aggs:
                if spec.distinct:
                    vals.append(spec.fn.host_agg([]))
                else:
                    vals.append(spec.fn.host_finalize(
                        spec.fn.host_merge([])))
            rows = [tuple(vals)]
        yield _rows_to_host_batch(rows, self.schema)

    @staticmethod
    def _host_key(col: HostColumn, i: int):
        """(valid, canonical-group-key, output-value) triple for one key."""
        if not col.validity[i]:
            return (False, None, None)
        v = col.data[i]
        if col.dtype.is_string:
            s = bytes(v).decode("utf-8", "replace")
            return (True, s, s)
        if col.dtype.is_floating:
            f = float(v)
            if np.isnan(f):
                return (True, "NaN", f)   # NaN == NaN for grouping
            if f == 0.0:
                return (True, 0.0, 0.0)   # -0.0 == 0.0 for grouping
            return (True, f, f)
        if col.dtype.is_boolean:
            return (True, bool(v), bool(v))
        return (True, int(v), int(v))


def _rows_to_host_batch(rows: List[tuple], schema: Schema) -> HostBatch:
    names = tuple(n for n, _ in schema)
    cols = []
    for ci, (_, t) in enumerate(schema):
        vals = [r[ci] for r in rows]
        cols.append(HostColumn.from_values(t, vals))
    return HostBatch(names, cols)


def _host_seg_agg(fn: AggFunction, kind: str, cols, order_idx, starts,
                  ends, total) -> Optional[List[HostColumn]]:
    """Vectorized per-group evaluation of one aggregate over sorted
    segments — the numpy mirror of the fn's host_update/host_agg/
    host_merge/host_finalize contract, one reduceat per group set
    instead of one python call per group.

    ``kind``: 'agg' (complete result), 'update' (partial buffers),
    'merge' (merged buffers, unfinalized), 'final' (merge + finalize).
    ``cols`` holds the concatenated input column ('agg'/'update'; None
    for count(*)) or the buffer columns ('merge'/'final'). Results come
    back in SORTED-group order (the caller permutes by its emission
    order). None = no segment kernel for this fn/dtype (caller falls
    back to the python path)."""
    ngroups = len(starts)

    def v_of(c):
        return np.asarray(c.validity, np.bool_)[order_idx]

    def d_of(c):
        return np.asarray(c.data)[order_idx]

    def cnt_of(v):
        return np.add.reduceat(v.astype(np.int64), starts)

    def masked_sum(c, out_float):
        v = v_of(c)
        if out_float:
            return np.add.reduceat(
                np.where(v, d_of(c).astype(np.float64), 0.0), starts), v
        with np.errstate(over="ignore"):
            s = np.add.reduceat(
                np.where(v, d_of(c).astype(np.int64), np.int64(0)), starts)
        return s, v

    if isinstance(fn, CountStar) and kind in ("agg", "update"):
        return [HostColumn(dt.INT64, (ends - starts).astype(np.int64),
                           np.ones(ngroups, np.bool_))]
    if isinstance(fn, Count):           # Count + CountStar merge/final
        if kind in ("agg", "update"):
            data = cnt_of(v_of(cols[0]))
        else:
            data, _ = masked_sum(cols[0], out_float=False)
        return [HostColumn(dt.INT64, data, np.ones(ngroups, np.bool_))]

    if isinstance(fn, Sum):
        t = fn.result_type
        s, v = masked_sum(cols[0], out_float=t.is_floating)
        ok = cnt_of(v) > 0
        data = np.where(ok, s, 0).astype(t.np_dtype)
        return [HostColumn(t, data, ok)]

    if isinstance(fn, Average):
        if kind in ("agg", "update"):
            s, v = masked_sum(cols[0], out_float=True)
            n = cnt_of(v)
            sv = n > 0
        else:
            s, v0 = masked_sum(cols[0], out_float=True)
            n, _ = masked_sum(cols[1], out_float=False)
            sv = cnt_of(v0) > 0
        if kind in ("agg", "final"):
            ok = n > 0
            data = np.where(ok, s / np.where(ok, n, 1), 0.0)
            return [HostColumn(dt.FLOAT64, data, ok)]
        return [HostColumn(dt.FLOAT64, np.where(sv, s, 0.0), sv),
                HostColumn(dt.INT64, n, np.ones(ngroups, np.bool_))]

    if isinstance(fn, Min):             # Min + Max, numeric only
        c = cols[0]
        t = c.dtype
        if t.is_string:
            return None
        v = v_of(c)
        ok = cnt_of(v) > 0
        is_max = fn.kind == "max"
        if t.is_floating:
            f = d_of(c).astype(np.float64)
            nanm = v & np.isnan(f)
            nonnan = v & ~np.isnan(f)
            if is_max:
                # Spark max: NaN is greatest — any NaN wins the group.
                m = np.maximum.reduceat(np.where(nonnan, f, -np.inf),
                                        starts)
                data = np.where(cnt_of(nanm) > 0, np.nan, m)
            else:
                # Spark min: NaN only when the group is all-NaN.
                m = np.minimum.reduceat(np.where(nonnan, f, np.inf),
                                        starts)
                data = np.where(cnt_of(nonnan) > 0, m, np.nan)
            data = np.where(ok, data, 0.0).astype(t.np_dtype)
        else:
            x = d_of(c).astype(np.int64)
            if is_max:
                m = np.maximum.reduceat(
                    np.where(v, x, np.iinfo(np.int64).min), starts)
            else:
                m = np.minimum.reduceat(
                    np.where(v, x, np.iinfo(np.int64).max), starts)
            data = np.where(ok, m, 0).astype(t.np_dtype)
        return [HostColumn(t, data, ok)]

    if isinstance(fn, First):           # First + Last
        last = fn.pick == "max"
        pos = np.arange(total, dtype=np.int64)
        if kind in ("agg", "update"):
            c = cols[0]
            v = v_of(c)
            if fn.ignore_nulls:
                if last:
                    p = np.maximum.reduceat(np.where(v, pos, np.int64(-1)),
                                            starts)
                    ok = p >= 0
                else:
                    big = np.int64(total)
                    p = np.minimum.reduceat(np.where(v, pos, big), starts)
                    ok = p < big
            else:
                p = (ends - 1 if last else starts).astype(np.int64)
                ok = np.ones(ngroups, np.bool_)
            safe = np.where(ok, p, 0)
            idx = np.where(ok, order_idx[safe], np.int64(-1))
            vcol = c.take(idx, null_on_negative=True)
            if kind == "agg":
                return [vcol]
            return [vcol, HostColumn(dt.INT64, np.where(ok, safe - starts, 0),
                                     ok)]
        # merge/final over (value, within-group-index) buffers: pick the
        # min (First) / max (Last) index, first-wins on ties like the
        # stable python min/max — encoded as index*T + tiebreak so one
        # reduceat does argmin with stability.
        vb, ib = cols
        iv = v_of(ib)
        ix = d_of(ib).astype(np.int64)
        localpos = pos - np.repeat(starts, ends - starts)
        T = np.int64(total + 1)
        if last:
            enc = np.where(iv, ix * T + (T - 1 - localpos), np.int64(-1))
            best = np.maximum.reduceat(enc, starts)
            ok = best >= 0
        else:
            imax = np.iinfo(np.int64).max
            enc = np.where(iv, ix * T + localpos, imax)
            best = np.minimum.reduceat(enc, starts)
            ok = best < imax
        safe = np.where(ok, best, 0)
        lp = (T - 1) - (safe % T) if last else safe % T
        p = starts + lp
        idx = np.where(ok, order_idx[np.where(ok, p, 0)], np.int64(-1))
        vcol = vb.take(idx, null_on_negative=True)
        if kind == "final":
            return [vcol]
        return [vcol, HostColumn(dt.INT64, np.where(ok, safe // T, 0), ok)]

    return None
