"""Process-global compiled-kernel cache (the WholeStageCodegen serving
story's other half).

Every device operator used to hold its own ``self._jit = jax.jit(...)``
closure: a fresh query — every bench iteration, every partition-worth of a
TPC-H suite run, every new ``Planner`` — built NEW closures and re-traced
kernels the previous instance had already compiled (jax keys its program
cache on the closure object, not the computation). This module replaces
those scattered per-instance closures with one process-global LRU keyed by
*structural* identity: (expression-tree fingerprint, input schema,
capacity bucket). Two exec instances with equal fingerprints share one
jitted callable, so repeated execution pays compile cost exactly once per
process.

Design notes:
- Keys are plain hashable tuples built by :func:`fingerprint`, a generic
  structural walk (type names + scalar attrs + recursion into nested
  objects/arrays). Floats go through ``repr`` so NaN keys stay equal to
  themselves; callables hash by qualname + bytecode; arrays by content
  digest (range-partition bounds are data — equal bounds, equal kernel).
- Entries wrap the jitted callable in :class:`CompiledKernel`, which times
  the FIRST invocation (tracing + XLA compile happen there, synchronously)
  so operators can surface a ``compileTime`` metric.
- The cache is bounded by ``spark.rapids.sql.kernelCache.maxEntries``
  (LRU); hits/misses are counted globally and surfaced per-op through
  ``Metrics`` as ``kernelCacheHits`` / ``kernelCacheMisses``.

This module deliberately imports nothing from the ops/exprs/columnar
layers (they all import it), only stdlib + numpy — with one lazy
exception: ``lookup`` folds the native-kernel fingerprint
(ops/native.py, which itself imports only config + jax) into every key
so toggling a ``spark.rapids.sql.native.*`` gate can never serve a
program traced under the other setting.
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

DEFAULT_MAX_ENTRIES = 1024


# ---------------------------------------------------------------------------
# Structural fingerprints
# ---------------------------------------------------------------------------

def fingerprint(obj: Any) -> Any:
    """Hashable structural fingerprint of ``obj``.

    Stable across instances and across processes-of-the-same-code for the
    object graphs that describe kernels: expression trees, sort orders,
    agg specs, window specs, partitionings (including sampled range
    bounds), schemas. Two objects with equal fingerprints must denote the
    same traced computation — the cache correctness contract."""
    return _fp(obj, 0)


_MAX_DEPTH = 32


def _fp(v: Any, depth: int) -> Any:
    if depth > _MAX_DEPTH:
        raise ValueError("fingerprint recursion too deep (cyclic kernel "
                         "descriptor?)")
    if v is None or isinstance(v, (bool, int, str, bytes)):
        return v
    if isinstance(v, float):
        # repr: NaN != NaN would make any NaN-bearing key unfindable.
        return ("f", repr(v))
    if isinstance(v, np.dtype):
        return ("npdt", v.str)
    if isinstance(v, np.generic):
        return ("npv", v.dtype.str, repr(v.item()))
    if type(v).__name__ == "BindSlotExpr":
        # Bound literals (exprs/bindslots.py) are VALUE-FREE by
        # construction: the key carries (slot, dtype) only, so two
        # bindings of the same dtype share ONE compiled kernel — the
        # binding arrives as a traced runtime input, never a trace
        # constant. Plain Literal nodes keep their value in the key
        # (the generic walk below), which stays correct: an unhoisted
        # literal IS a trace constant. Duck-typed on the class name so
        # this module keeps its no-engine-imports rule.
        return ("bindslot", v.slot, v.dtype.name)
    if isinstance(v, (list, tuple)):
        return tuple(_fp(x, depth + 1) for x in v)
    if isinstance(v, (set, frozenset)):
        return ("set",) + tuple(sorted(repr(_fp(x, depth + 1)) for x in v))
    if isinstance(v, dict):
        return ("dict",) + tuple(
            (_fp(k, depth + 1), _fp(x, depth + 1))
            for k, x in sorted(v.items(), key=lambda kv: repr(kv[0])))
    if isinstance(v, np.ndarray):
        if v.dtype == object:
            # Object arrays (host string columns): content, not pointers.
            return ("ndo", v.shape) + tuple(
                _fp(x, depth + 1) for x in v.ravel().tolist())
        return ("nd", v.dtype.str, v.shape,
                hashlib.sha1(np.ascontiguousarray(v).tobytes())
                .hexdigest())
    if hasattr(v, "__array__"):
        # Device arrays (range bounds that stayed on device, scalars).
        a = np.asarray(v)
        return _fp(a, depth + 1)
    if callable(v) and not hasattr(v, "__dict__"):
        code = getattr(v, "__code__", None)
        return ("fn", getattr(v, "__qualname__", type(v).__name__),
                hashlib.sha1(code.co_code).hexdigest() if code else "")
    # Generic object: type identity + instance attrs. Covers Expression
    # trees (children live in __dict__), SortOrder, AggSpec/AggFunction,
    # WindowExprSpec/WindowSpec/WindowFrame, Partitioning, HostBatch/
    # HostColumn (range bounds), DataType.
    d = getattr(v, "__dict__", None)
    if d is not None:
        code = getattr(v, "__code__", None)
        parts: List[Any] = [
            "obj", type(v).__module__, type(v).__qualname__]
        if code is not None:  # a function that also has attributes
            parts.append(hashlib.sha1(code.co_code).hexdigest())
        attrs = tuple((k, _fp(x, depth + 1))
                      for k, x in sorted(d.items())
                      if not k.startswith("_jit")
                      and not k.startswith("_phys"))
        return tuple(parts) + attrs
    # Opaque leaf with no state we can see: fall back to the type name
    # only if its repr carries no identity (addresses would poison keys).
    r = repr(v)
    if "0x" in r:
        r = type(v).__qualname__
    return ("opaque", type(v).__module__, type(v).__qualname__, r)


def schema_fingerprint(schema) -> Tuple:
    """Fingerprint of an exec output schema ((name, DataType), ...)."""
    return tuple((n, t.name) for n, t in schema)


# ---------------------------------------------------------------------------
# Cache
# ---------------------------------------------------------------------------

class CompiledKernel:
    """A cached callable that records its first-call wall time.

    jax traces + compiles synchronously inside the first invocation of a
    jitted function, so ``compile_ns`` after the first call is a
    compile-inclusive measure — exactly the number ops report as their
    ``compileTime`` metric."""

    __slots__ = ("fn", "compile_ns", "compiled", "_lock")

    def __init__(self, fn: Callable):
        self.fn = fn
        self.compile_ns = 0
        self.compiled = False
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        if not self.compiled:
            # Double-checked: two threads racing the first call (shared
            # kernel across concurrent pipelined queries) must record
            # compile time exactly once; the loser falls through to a
            # plain (already-compiled) dispatch.
            with self._lock:
                if not self.compiled:
                    t0 = time.perf_counter_ns()
                    out = self.fn(*args, **kwargs)
                    self.compile_ns = time.perf_counter_ns() - t0
                    self.compiled = True
                    return out
        return self.fn(*args, **kwargs)


class KernelCache:
    """Bounded LRU of compiled kernels keyed by structural fingerprints."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self._entries: "collections.OrderedDict[Any, Any]" = \
            collections.OrderedDict()
        # key -> query id that paid the compile (owner tag; None when
        # compiled outside a managed query). The cache itself stays
        # process-global — sharing compiled kernels across queries is
        # the point — but reservations are attributable.
        self._owners: Dict[Any, Any] = {}
        self._lock = threading.RLock()
        self.max_entries = max_entries
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def configure(self, max_entries: int):
        with self._lock:
            self.max_entries = max(int(max_entries), 1)
            self._evict()

    def get(self, key: Any, builder: Callable[[], Any]) -> Tuple[Any, bool]:
        """Return (value, hit). ``builder`` runs on miss; its result is
        stored verbatim (usually a :class:`CompiledKernel` or a tuple of
        them)."""
        with self._lock:
            try:
                entry = self._entries[key]
            except KeyError:
                pass
            except TypeError:
                raise TypeError(f"unhashable kernel-cache key: {key!r}")
            else:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry, True
            self.misses += 1
            entry = builder()
            self._entries[key] = entry
            from spark_rapids_tpu import faults
            self._owners[key] = faults.current_query_id()
            self._evict()
            return entry, False

    def _evict(self):
        while len(self._entries) > self.max_entries:
            key, _ = self._entries.popitem(last=False)
            self._owners.pop(key, None)
            self.evictions += 1

    def owners(self) -> Dict[Any, Any]:
        """key -> owning query id (None = unmanaged compile)."""
        with self._lock:
            return dict(self._owners)

    def evict_owned(self, owner_ids, keep: int) -> int:
        """Per-tenant compile-budget enforcement (parallel/qos/): drop
        the OLDEST entries whose owner tag is in ``owner_ids`` until at
        most ``keep`` remain; returns how many were evicted. Evicted
        kernels recompile transparently on next use — a quota, not a
        correctness event."""
        owner_ids = set(owner_ids)
        with self._lock:
            owned = [k for k in self._entries
                     if self._owners.get(k) in owner_ids]
            drop = len(owned) - max(int(keep), 0)
            n = 0
            for k in owned:
                if n >= drop:
                    break
                self._entries.pop(k, None)
                self._owners.pop(k, None)
                self.evictions += 1
                n += 1
            return n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = {"hits": self.hits, "misses": self.misses,
                   "evictions": self.evictions,
                   "entries": len(self._entries)}
        p = persistent_stats()
        if p["dir"]:
            out["persistentCacheDir"] = p["dir"]
            out["persistentCacheHits"] = p["hits"]
            out["persistentCacheMisses"] = p["misses"]
        return out

    def reset_stats(self):
        with self._lock:
            self.hits = self.misses = self.evictions = 0

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._owners.clear()
            self.reset_stats()

    def keys(self) -> List[Any]:
        with self._lock:
            return list(self._entries.keys())


_CACHE = KernelCache()


def cache() -> KernelCache:
    """The process-global kernel cache."""
    return _CACHE


def lookup(kind: str, key_parts: Tuple, builder: Callable[[], Callable],
           metrics=None) -> CompiledKernel:
    """Fetch-or-build the kernel for ``(kind, *key_parts)``, wrapping the
    built callable in :class:`CompiledKernel`. When ``metrics`` is given,
    counts ``kernelCacheHits``/``kernelCacheMisses`` on it.

    The native-kernel fingerprint (ops/native.py) is folded into every
    key: a kernel traced while a native Pallas gate was live embeds
    different lowering than its jax.numpy twin, so toggling
    ``spark.rapids.sql.native.*`` must miss rather than serve the stale
    program."""
    from spark_rapids_tpu.ops import native
    entry, hit = _CACHE.get((kind,) + tuple(key_parts)
                            + (native.fingerprint(),),
                            lambda: CompiledKernel(builder()))
    if metrics is not None:
        metrics.add("kernelCacheHits" if hit else "kernelCacheMisses", 1)
    return entry


def call(entry: CompiledKernel, metrics, *args, **kwargs):
    """Invoke a cached kernel; if this call compiled it, surface the
    compile-inclusive first-call time as the op's ``compileTime``.

    Every cached-kernel dispatch is a pure batch->batch computation, so
    the whole funnel runs under the OOM escalation ladder
    (memory/oom.py) and carries the ``kernel`` fault-injection site —
    one hardened choke point instead of per-call-site wrappers."""
    from spark_rapids_tpu import faults
    from spark_rapids_tpu.memory.oom import retry_on_oom

    fresh = not entry.compiled

    def dispatch():
        faults.fault_point("kernel")
        return entry(*args, **kwargs)

    out = retry_on_oom(dispatch)
    if fresh and metrics is not None:
        metrics.add("compileTime", entry.compile_ns)
    return out


# ---------------------------------------------------------------------------
# Persistent (on-disk) compilation cache
# ---------------------------------------------------------------------------
#
# The in-memory LRU above survives re-planning but not process restarts:
# a fresh server pays first_run_s (trace + XLA compile) for every kernel
# again. ``spark.rapids.sql.kernelCache.persistentDir`` points JAX's
# persistent compilation cache at a directory so compiled executables
# serialize to disk and a restarted process deserializes (~ms) instead of
# recompiling (~s). Hits/misses are counted via jax's monitoring events
# and surface through :meth:`KernelCache.stats` as persistentCacheHits /
# persistentCacheMisses (bench.py's kernel_cache JSON block).

_PERSISTENT_LOCK = threading.Lock()
_PERSISTENT = {"dir": None, "hits": 0, "misses": 0, "listener": False}


def _on_cache_event(event: str, **kwargs) -> None:
    if event.endswith("/cache_hits"):
        with _PERSISTENT_LOCK:
            _PERSISTENT["hits"] += 1
    elif event.endswith("/cache_misses"):
        with _PERSISTENT_LOCK:
            _PERSISTENT["misses"] += 1


def configure_persistent(path: Optional[str]) -> bool:
    """Enable JAX's persistent compilation cache at ``path`` (idempotent;
    empty/None disables nothing — the cache cannot be torn down once jax
    has initialized it, so the first non-empty dir of the process wins).
    Returns True when the cache is active at ``path``."""
    path = (path or "").strip()
    if not path:
        return False
    with _PERSISTENT_LOCK:
        if _PERSISTENT["dir"] == path:
            return True
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        # The engine's kernels compile in ms on warm backends; without
        # these floors jax would skip persisting exactly the cheap
        # kernels whose aggregate retrace cost dominates first_run_s.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        try:
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:   # older jax: flag absent, default persists all
            pass
        try:
            # jax latches "is the cache usable" on the FIRST compile of
            # the process; a kernel compiled before this conf arrived
            # would leave that latch stuck at disabled. Reset it so the
            # newly-configured dir takes effect mid-process.
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:   # pragma: no cover - jax-version dependent
            pass
        with _PERSISTENT_LOCK:
            if not _PERSISTENT["listener"]:
                from jax._src import monitoring
                monitoring.register_event_listener(_on_cache_event)
                _PERSISTENT["listener"] = True
            _PERSISTENT["dir"] = path
        return True
    except Exception as e:      # pragma: no cover - jax-version dependent
        import logging
        logging.getLogger("spark_rapids_tpu").warning(
            "persistent kernel cache unavailable at %r: %s", path, e)
        return False


def persistent_stats() -> Dict[str, Any]:
    with _PERSISTENT_LOCK:
        return {"dir": _PERSISTENT["dir"], "hits": _PERSISTENT["hits"],
                "misses": _PERSISTENT["misses"]}


def detached_clone(op):
    """Shallow clone of an exec with its child links severed — jitting a
    BOUND METHOD for the global cache must not pin the exec's whole
    subtree (and through it the source data) in memory for the cache
    entry's lifetime. The kernels only read the op's own spec attributes
    (exprs/aggs/mode/...), never its children."""
    import copy
    clone = copy.copy(op)
    clone.children = ()
    return clone
