"""Typed, self-documenting configuration registry.

The analog of the reference's RapidsConf.scala (1049 LoC builder DSL producing
typed ConfEntry objects, a registry, and generated docs/configs.md). Same
design: ``conf("spark.rapids...").doc(...).boolean(default)`` builders append
to a module-level registry; ``TpuConf`` resolves values from a plain dict (the
stand-in for Spark SQL conf); ``generate_docs()`` renders the markdown table.

Per-operator kill-switch keys (``spark.rapids.sql.exec.*`` /
``spark.rapids.sql.expression.*``) are registered dynamically by the
plan-rewrite rules (plan/overrides.py), mirroring RapidsMeta's ``confKey``.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class ConfEntry:
    key: str
    doc: str
    value_type: str            # "boolean" | "integer" | "long" | "double" | "string"
    default: Any
    converter: Callable[[str], Any]
    internal: bool = False

    def get(self, conf: "TpuConf") -> Any:
        raw = conf.raw.get(self.key)
        if raw is None:
            return self.default
        if isinstance(raw, str):
            return self.converter(raw)
        # Coerce non-string values to the declared type so typed accessors
        # never leak e.g. int 0 where a bool is expected.
        if self.value_type == "boolean":
            if not isinstance(raw, bool):
                raise ValueError(
                    f"{self.key} expects a boolean, got {raw!r}")
            return raw
        if self.value_type in ("integer", "long"):
            return int(raw)
        if self.value_type == "double":
            return float(raw)
        return raw


_REGISTRY: Dict[str, ConfEntry] = {}
_REGISTRY_LOCK = threading.Lock()


def _parse_bool(s: str) -> bool:
    v = s.strip().lower()
    if v in ("true", "1", "yes"):
        return True
    if v in ("false", "0", "no"):
        return False
    raise ValueError(f"not a boolean config value: {s!r}")


class _Builder:
    def __init__(self, key: str):
        self._key = key
        self._doc = ""
        self._internal = False

    def doc(self, text: str) -> "_Builder":
        self._doc = text
        return self

    def internal(self) -> "_Builder":
        self._internal = True
        return self

    def _register(self, value_type, default, converter) -> ConfEntry:
        entry = ConfEntry(self._key, self._doc, value_type, default, converter,
                          self._internal)
        with _REGISTRY_LOCK:
            if self._key in _REGISTRY:
                return _REGISTRY[self._key]   # idempotent re-registration
            _REGISTRY[self._key] = entry
        return entry

    def boolean(self, default: bool) -> ConfEntry:
        return self._register("boolean", default, _parse_bool)

    def integer(self, default: int) -> ConfEntry:
        return self._register("integer", default, int)

    def long(self, default: int) -> ConfEntry:
        return self._register("long", default, int)

    def double(self, default: float) -> ConfEntry:
        return self._register("double", default, float)

    def string(self, default: Optional[str]) -> ConfEntry:
        return self._register("string", default, str)


def conf(key: str) -> _Builder:
    return _Builder(key)


def registered_entries() -> List[ConfEntry]:
    with _REGISTRY_LOCK:
        return sorted(_REGISTRY.values(), key=lambda e: e.key)


# ---------------------------------------------------------------------------
# Core entries (ref: RapidsConf.scala:282-751; keys kept compatible where the
# concept carries over, with TPU-specific replacements where it does not).
# ---------------------------------------------------------------------------

SQL_ENABLED = conf("spark.rapids.sql.enabled").doc(
    "Enable or disable running SQL operators on the TPU.").boolean(True)

DEVICE = conf("spark.rapids.device").doc(
    "Accelerator backend to target: 'tpu' (jax default backend) or 'cpu' "
    "(host fallback everywhere; useful for debugging).").string("tpu")

EXPLAIN = conf("spark.rapids.sql.explain").doc(
    "Explain why parts of a query were or were not placed on the TPU: "
    "NONE, ALL, or NOT_ON_GPU (only print replacement failures).").string("NONE")

BATCH_SIZE_BYTES = conf("spark.rapids.sql.batchSizeBytes").doc(
    "Target size in bytes for coalesced TPU batches. Larger batches amortize "
    "kernel launch/compile overhead; bounded by HBM.").long(512 * 1024 * 1024)

BATCH_SIZE_ROWS = conf("spark.rapids.sql.batchSizeRows").doc(
    "Target row capacity bucket for coalesced TPU batches (power of two). "
    "TPU addition: row capacity, not just bytes, is what bounds XLA "
    "recompilation. Default favors few large batches: per-batch device "
    "work has a fixed latency floor on a tunneled chip.").long(4 << 20)

AUTO_BROADCAST_THRESHOLD = conf(
    "spark.rapids.sql.autoBroadcastJoinThreshold").doc(
    "Joins with strategy 'auto' broadcast the build side when its "
    "estimated size (parquet footer stats propagated through the plan) "
    "is at most this many bytes, else hash-shuffle both sides — the "
    "stats-driven half of AQE-lite (ref GpuCustomShuffleReaderExec / "
    "Spark autoBroadcastJoinThreshold semantics: -1 disables "
    "auto-broadcast entirely).").long(64 * 1024 * 1024)

AQE_COALESCE_PARTITIONS = conf(
    "spark.rapids.sql.aqe.coalescePartitions.enabled").doc(
    "After a shuffle materializes, merge undersized reduce partitions "
    "using their now-exact row counts (GpuCustomShuffleReaderExec.scala:"
    "132 coalesced-partition reader analog).").boolean(True)

AQE_COALESCE_TARGET_ROWS = conf(
    "spark.rapids.sql.aqe.coalescePartitions.targetRows").doc(
    "Row target per post-shuffle partition when coalescing.").long(1 << 20)

AQE_COALESCE_TARGET_BYTES = conf(
    "spark.rapids.sql.aqe.coalescePartitions.targetBytes").doc(
    "Byte target per post-shuffle partition when coalescing, from the "
    "OBSERVED shard bytes the transport session recorded at "
    "materialization (the exact-size half of "
    "GpuCustomShuffleReaderExec's coalesced reader). Partitions merge "
    "while both the row and the byte target hold.").long(64 * 1024 * 1024)

AQE_REPLAN = conf("spark.rapids.sql.aqe.replan.enabled").doc(
    "Runtime adaptive re-planning (parallel/replan.py): before stage "
    "prematerialization, materialize each shuffled hash join's "
    "build-side exchange, read the OBSERVED partition byte sizes from "
    "its transport session, and when the build side fits "
    "autoBroadcastJoinThreshold demote the join to a broadcast hash "
    "join — the probe side then skips its shuffle entirely and the "
    "fusion pass re-runs over the rewritten subtree. Extends the "
    "stats-only AQE-lite into true mid-query re-planning "
    "(GpuCustomShuffleReaderExec.scala:132 analog driven by the stage "
    "DAG). Off keeps the statically planned joins.").boolean(True)

COST_ENABLED = conf("spark.rapids.sql.cost.enabled").doc(
    "Cost-based host/device placement (plan/cost.py): estimate every "
    "logical subtree's device time (compile-amortized sync floor + "
    "bytes over the device pipeline) and host time (bytes over the "
    "host engine) from parquet/ORC footer stats, and place whole "
    "maximal subtrees on the host engine when the host estimate wins — "
    "small inputs cannot amortize the ~70-100ms per-dispatch sync "
    "floor of a tunneled chip (the reference's own 'worthwhile >=30s' "
    "economics, docs/FAQ.md:82-84). The SRT_COST env (0/1) overrides "
    "the default for a whole process. Placement is skipped in test "
    "mode, under an armed fault schedule, and on non-inprocess shuffle "
    "transports (chaos/mesh paths pin the device plan).").boolean(True)

COST_SYNC_FLOOR_MS = conf("spark.rapids.sql.cost.deviceSyncFloorMs").doc(
    "Calibrated cost of ONE device host-sync round trip (the sizes "
    "pull / result fetch floor a tunneled chip pays per dispatch "
    "funnel; the r4 q3 profile measured ~70-100ms). Every "
    "sync-bearing node (exchange, join build, aggregate shrink, sort "
    "sample, collect download) charges multiples of this.").double(80.0)

COST_DEVICE_GBPS = conf("spark.rapids.sql.cost.deviceThroughputGBps").doc(
    "Calibrated steady-state device pipeline throughput (decode + "
    "upload + kernels with the scan cache warm) used for the "
    "bytes-proportional term of the device estimate.").double(2.0)

COST_ASSUME_TUNNEL = conf("spark.rapids.sql.cost.assumeTunnel").doc(
    "Test/bench hook: charge the device sync floor even when the "
    "backend is CPU-only (where effective_sync_floor_ms otherwise "
    "zeroes it — no tunnel, no per-dispatch sync cost), so placement "
    "scenarios calibrated for real hardware can be exercised "
    "locally.").internal().boolean(False)

COST_HOST_GBPS = conf("spark.rapids.sql.cost.hostThroughputGBps").doc(
    "Calibrated host (numpy) engine throughput per operator pass used "
    "for the bytes-proportional term of the host estimate.").double(0.6)

COST_MAX_HOST_BYTES = conf("spark.rapids.sql.cost.maxHostBytes").doc(
    "Safety ceiling: a subtree whose estimated input exceeds this many "
    "bytes is never host-placed regardless of the model (the host "
    "engine is single-process numpy; past this size the device always "
    "wins once syncs amortize).").long(256 * 1024 * 1024)

COST_EXPLAIN = conf("spark.rapids.sql.cost.explain").doc(
    "Render per-node cost estimates (rows/bytes, device-ms vs host-ms, "
    "sync counts) and the chosen placement in DataFrame.explain() "
    "output.").boolean(False)

AGG_SKIP_PARTIAL_RATIO = conf(
    "spark.rapids.sql.agg.skipAggPassReductionRatio").doc(
    "When the first partial-aggregation batch reduces its input by less "
    "than this ratio (groups/rows above the threshold), remaining batches "
    "skip pre-shuffle grouping and project rows straight into the buffer "
    "layout; all grouping then happens once, after the exchange. 1.0 "
    "disables skipping.").double(0.85)

CONCURRENT_TPU_TASKS = conf("spark.rapids.sql.concurrentTpuTasks").doc(
    "Number of tasks that may issue work to one TPU chip concurrently "
    "(ref: spark.rapids.sql.concurrentGpuTasks / GpuSemaphore).").integer(2)

INCOMPATIBLE_OPS = conf("spark.rapids.sql.incompatibleOps.enabled").doc(
    "Enable operators that produce results that differ from Spark CPU in "
    "corner cases (float aggregation order, locale-sensitive strings...)."
).boolean(False)

HAS_NANS = conf("spark.rapids.sql.hasNans").doc(
    "Assume floating point data may contain NaN/Infinity. When true (the "
    "safe default), sum/avg aggregation carries out-of-band non-finite "
    "occurrence streams through the cumsum fast path; setting it false "
    "(the reference's common benchmark setting) drops that work entirely."
).boolean(True)

VARIABLE_FLOAT_AGG = conf("spark.rapids.sql.variableFloatAgg.enabled").doc(
    "Allow float/double aggregations whose result can vary with evaluation "
    "order (parallel tree reductions on TPU).").boolean(False)

CAST_FLOAT_TO_STRING = conf(
    "spark.rapids.sql.castFloatToString.enabled").doc(
    "Allow float->string casts that may format differently from Spark."
).boolean(False)

CAST_STRING_TO_FLOAT = conf(
    "spark.rapids.sql.castStringToFloat.enabled").doc(
    "Allow string->float casts that may differ in corner cases."
).boolean(False)

IMPROVED_FLOAT_OPS = conf("spark.rapids.sql.improvedFloatOps.enabled").doc(
    "Use TPU-fused float paths that can round differently from the JVM."
).boolean(False)

TEST_ENABLED = conf("spark.rapids.sql.test.enabled").doc(
    "Test mode: fail any query that executes a non-allowlisted operator on "
    "the host (ref: GpuTransitionOverrides.assertIsOnTheGpu).").boolean(False)

TEST_ALLOWED_NONTPU = conf("spark.rapids.sql.test.allowedNonTpu").doc(
    "Comma-separated exec class names tolerated on host in test mode."
).string("")

MAX_READER_BATCH_SIZE_ROWS = conf(
    "spark.rapids.sql.reader.batchSizeRows").doc(
    "Soft cap on rows per batch produced by file readers.").long(1 << 20)

MAX_READER_BATCH_SIZE_BYTES = conf(
    "spark.rapids.sql.reader.batchSizeBytes").doc(
    "Soft cap on bytes per batch produced by file readers."
).long(512 * 1024 * 1024)

PARQUET_READER_TYPE = conf("spark.rapids.sql.format.parquet.reader.type").doc(
    "Parquet reader strategy: PERFILE, COALESCING, MULTITHREADED, or AUTO "
    "(ref: GpuParquetScan.scala reader selection).").string("AUTO")

PARQUET_MULTITHREADED_READ_NUM_THREADS = conf(
    "spark.rapids.sql.format.parquet.multiThreadedRead.numThreads").doc(
    "Host threads used to read parquet row groups in parallel.").integer(20)

ENABLE_PARQUET = conf("spark.rapids.sql.format.parquet.enabled").doc(
    "Enable parquet scan/write on TPU path.").boolean(True)

ENABLE_CSV = conf("spark.rapids.sql.format.csv.enabled").doc(
    "Enable CSV scan on TPU path.").boolean(True)

ENABLE_ORC = conf("spark.rapids.sql.format.orc.enabled").doc(
    "Enable ORC scan/write on TPU path.").boolean(True)

ENABLE_PARQUET_READ = conf(
    "spark.rapids.sql.format.parquet.read.enabled").doc(
    "Enable parquet reads on the TPU path (scan falls back to the host "
    "engine when off; finer grain than format.parquet.enabled)."
).boolean(True)

ENABLE_PARQUET_WRITE = conf(
    "spark.rapids.sql.format.parquet.write.enabled").doc(
    "Enable the device plan feeding parquet writes (off = the write job "
    "runs through the host fallback engine).").boolean(True)

ENABLE_ORC_READ = conf("spark.rapids.sql.format.orc.read.enabled").doc(
    "Enable ORC reads on the TPU path.").boolean(True)

ENABLE_ORC_WRITE = conf("spark.rapids.sql.format.orc.write.enabled").doc(
    "Enable the device plan feeding ORC writes.").boolean(True)

ENABLE_CSV_READ = conf("spark.rapids.sql.format.csv.read.enabled").doc(
    "Enable CSV reads on the TPU path.").boolean(True)

ORC_READER_TYPE = conf("spark.rapids.sql.format.orc.reader.type").doc(
    "ORC reader strategy: PERFILE, COALESCING, MULTITHREADED, or AUTO "
    "(GpuOrcScan multi-file reader selection analog).").string("AUTO")

CSV_READER_TYPE = conf("spark.rapids.sql.format.csv.reader.type").doc(
    "CSV reader strategy: PERFILE, COALESCING, MULTITHREADED, or AUTO."
).string("AUTO")

REPLACE_SORT_MERGE_JOIN = conf(
    "spark.rapids.sql.replaceSortMergeJoin.enabled").doc(
    "Replace sort-merge joins with TPU hash joins, dropping the sorts "
    "(ref: GpuSortMergeJoinExec meta).").boolean(True)

STABLE_SORT = conf("spark.rapids.sql.stableSort.enabled").doc(
    "Use stable sorting (matches Spark's sort for ties at a small cost)."
).boolean(True)

SHUFFLE_COMPRESSION_CODEC = conf(
    "spark.rapids.shuffle.compression.codec").doc(
    "Codec for spilled shuffle/buffer blobs: lz4 (native LZ4 block "
    "format, memory/compression.py + native/compress.cpp), copy "
    "(framing only, testing), or none. The reference compresses with "
    "nvcomp LZ4 on-GPU; the TPU path keeps live data in HBM, so the "
    "codec applies on the host at the disk-spill boundary.").string("lz4")

SCAN_CACHE_BYTES = conf(
    "spark.rapids.sql.format.scanCache.maxBytes").doc(
    "Device (HBM) budget for the transparent scan-unit cache: decoded "
    "batches of recently scanned parquet/orc/csv units stay resident and "
    "are served without re-decoding or re-crossing the host->device link "
    "(the TPU analog of serving Spark's columnar InMemoryTableScan from "
    "the device store, GpuTransitionOverrides.scala:339; same role as a "
    "transparent read cache in front of cold storage). 0 disables."
).long(4 * 1024 * 1024 * 1024)

WIRE_CODEC = conf("spark.rapids.sql.wire.codec").doc(
    "Host->device wire codec (columnar/wire.py): 'v2' (default — "
    "dictionary, narrow-int, RLE, delta and frame-of-reference "
    "encodings chosen per column by smallest wire size from one host "
    "stats pass), 'v1' (dictionary + narrow-int only, the pre-fast-path "
    "behavior), or 'plain' (logical dtypes ship untransformed — the "
    "transport-transparency baseline; every codec is lossless, so all "
    "three produce bit-identical query results). The SRT_WIRE_CODEC "
    "env seeds the process default; the conf key overrides it. "
    "Process-global, like the kernel cache.").string("v2")

WIRE_MIN_UPLOAD_BYTES = conf("spark.rapids.sql.wire.minUploadBytes").doc(
    "Upload transfer coalescing threshold: consecutive encoded scan "
    "batches whose packed staging buffers are each below this many "
    "bytes share ONE device_put transfer (each member still decodes "
    "through its own cached kernel off an on-device slice, so results "
    "are bit-identical — only the transfer count changes). Every "
    "transfer on a tunneled link costs a fixed ~100ms floor, so many "
    "tiny row groups used to pay it N times. 0 disables grouping."
).long(1 << 20)

JOIN_GRACE_ENABLED = conf("spark.rapids.sql.join.grace.enabled").doc(
    "Out-of-core grace hash joins (ops/join.py): when a shuffled hash "
    "join's build side exceeds join.grace.buildFraction of the device "
    "budget, partition BOTH sides by key fingerprint (the same "
    "murmur3 hash partitioning the exchange uses) into spillable "
    "buckets and join the co-partitioned bucket pairs — so a build "
    "side far past the device budget still runs ON DEVICE instead of "
    "OOM-laddering to the host engine. Also registered as the OOM "
    "escalation rung directly ABOVE host fallback: a hash join whose "
    "single-batch build exhausts the spill/shrink ladder retries "
    "grace-partitioned before degrading to host. This beats the "
    "reference's RequireSingleBatch build-side restriction "
    "(GpuShuffledHashJoinExec).").boolean(True)

JOIN_GRACE_BUILD_FRACTION = conf(
    "spark.rapids.sql.join.grace.buildFraction").doc(
    "Fraction of the device budget a hash-join build side may occupy "
    "as a single coalesced batch before the grace path engages; it is "
    "also the per-bucket byte budget the grace partitioner targets."
).double(0.5)

JOIN_GRACE_MAX_PARTITIONS = conf(
    "spark.rapids.sql.join.grace.maxPartitions").doc(
    "Upper bound on grace-join fingerprint buckets per partition "
    "(graceJoinPartitions counts the buckets actually used)."
).integer(64)

SHUFFLE_PARTITIONS = conf("spark.rapids.sql.shuffle.partitions").doc(
    "Number of shuffle output partitions for exchanges (analog of "
    "spark.sql.shuffle.partitions).").integer(8)

HBM_POOL_FRACTION = conf("spark.rapids.memory.tpu.allocFraction").doc(
    "Fraction of visible HBM the engine budgets for batch storage; the "
    "watermark evictor starts spilling above it (ref: RMM pool + "
    "DeviceMemoryEventHandler). A real allocation failure past the "
    "watermark spills-and-retries at the dispatch site (memory/oom.py), "
    "so the budget can run close to full.").double(0.9)

CONCURRENT_PYTHON_WORKERS = conf(
    "spark.rapids.python.concurrentPythonWorkers").doc(
    "Max pandas-UDF group functions evaluated concurrently "
    "(PythonWorkerSemaphore analog; 0 or 1 = serial).").integer(4)

MEMORY_DEBUG = conf("spark.rapids.memory.tpu.debug").doc(
    "Log every catalog buffer add/acquire/spill/remove with sizes, record "
    "creation stacks, and emit a leak report (unfreed buffers + where "
    "they were allocated) when the query context closes (ref: "
    "spark.rapids.memory.gpu.debug, RapidsConf.scala:288 + cuDF "
    "MemoryCleaner leak callstacks).").boolean(False)

MAX_ALLOC_FRACTION = conf(
    "spark.rapids.memory.tpu.maxAllocFraction").doc(
    "Hard ceiling on the fraction of visible HBM the batch-storage "
    "budget may claim, regardless of allocFraction (RapidsConf's "
    "maxAllocFraction).").double(0.95)

RESERVE_BYTES = conf("spark.rapids.memory.tpu.reserve").doc(
    "HBM bytes held back from the batch-storage budget for compute "
    "transients and the XLA runtime (spark.rapids.memory.gpu.reserve "
    "analog).").long(512 * 1024 * 1024)

METRICS_LEVEL = conf("spark.rapids.sql.metrics.level").doc(
    "Operator metric verbosity reported by DataFrame.metrics(): "
    "ESSENTIAL (rows/time), MODERATE (+batches/shuffle), or DEBUG "
    "(everything the execs record). Audit groups registered in "
    "ops/base.py (Recovery/Pipeline/Scheduler/Transport/Cost @query) "
    "are never filtered.").string("DEBUG")

TRACE_ENABLED = conf("spark.rapids.sql.trace.enabled").doc(
    "Query flight recorder (spark_rapids_tpu/monitoring/): record "
    "structured trace spans (scheduler queue, host prefetch, wire "
    "pack/upload, per-operator device dispatch, shuffle write/fetch, "
    "stage materialization) and instant events (fault injected, OOM "
    "rung, stage recompute, join demotion, watchdog kill, "
    "cancellation, cross-query eviction) into a bounded per-query "
    "ring buffer. Consumed by DataFrame.trace_export (Chrome/Perfetto "
    "JSON), DataFrame.explain_analyze, monitoring.snapshot() and "
    "bench.py's trace block. Off = a no-op recorder with near-zero "
    "per-call overhead (the NVTX-always-on analog, "
    "NvtxWithMetrics.scala:21-44). The SRT_TRACE env (0/1) overrides "
    "the default for a whole process.").boolean(False)

TRACE_MAX_EVENTS = conf("spark.rapids.sql.trace.maxEvents").doc(
    "Per-query ring-buffer bound for the flight recorder: once a "
    "query's ring is full the oldest events drop (droppedEvents in "
    "monitoring.snapshot() counts them), so tracing can stay on under "
    "sustained load without unbounded memory.").integer(65536)

TRACE_LEVEL = conf("spark.rapids.sql.trace.level").doc(
    "Flight-recorder verbosity: 'query' (query/stage lifecycle spans + "
    "every instant event), 'operator' (+ per-partition, per-operator, "
    "upload, shuffle spans), or 'kernel' (+ per-batch wire encode/pack "
    "and host-sync attribution spans).").string("operator")

HOST_SPILL_STORAGE_SIZE = conf("spark.rapids.memory.host.spillStorageSize").doc(
    "Bytes of host RAM for spilled device batches before going to disk."
).long(1024 * 1024 * 1024)

SPILL_DIR = conf("spark.rapids.memory.spill.dir").doc(
    "Directory for the disk spill tier.").string("/tmp/spark_rapids_tpu_spill")

UDF_COMPILER_ENABLED = conf("spark.rapids.sql.udfCompiler.enabled").doc(
    "Trace python UDFs with JAX into columnar expressions when possible "
    "(the TPU-native analog of the bytecode->Catalyst udf-compiler)."
).boolean(True)

METRICS_ENABLED = conf("spark.rapids.sql.metrics.enabled").doc(
    "Live telemetry plane (spark_rapids_tpu/monitoring/telemetry.py): "
    "a process-global typed metric registry — monotonic counters, "
    "gauges, sliding-window log-bucket histograms (p50/p95/p99) with "
    "labeled series (tenant/class/kind/tier/worker) — continuously "
    "scrapeable while queries run, bridged from every existing counter "
    "funnel (scheduler/QoS, plan+kernel caches, recovery ladder, "
    "transport, pipeline, spill watermark). Consumed by "
    "telemetry.snapshot()/render_text(), the OpenMetrics exporter "
    "(metrics.port) and bench.py's telemetry block. Off = a no-op "
    "registry whose per-call cost is one global load (the same "
    "discipline as trace.enabled; scripts/microbench.py bounds it). "
    "The SRT_METRICS env (0/1) overrides the default for a whole "
    "process.").boolean(False)

METRICS_PORT = conf("spark.rapids.sql.metrics.port").doc(
    "OpenMetrics/Prometheus exporter port (monitoring/exporter.py): "
    "with metrics.enabled, serve the text exposition on "
    "127.0.0.1:<port>/metrics from a daemon thread. 0 (default) = no "
    "socket — the registry stays readable in-process via "
    "telemetry.snapshot()/render_text().").integer(0)

EVENT_LOG_DIR = conf("spark.rapids.sql.eventLog.dir").doc(
    "Persistent per-query event log (monitoring/history.py): append "
    "one JSONL record per query at teardown — plan fingerprint, bind "
    "slots, per-node observed rows/bytes, span-category breakdown, "
    "recovery/QoS instants, final metrics — under this directory "
    "(one events-<pid>.jsonl per process). scripts/history.py "
    "reconstructs explain_analyze-style reports and a fleet summary "
    "from the log alone, after the process has exited (the history "
    "server analog). Empty (default) = off. The SRT_EVENT_LOG env "
    "overrides the default for a whole process.").string("")

MESH_ENABLED = conf("spark.rapids.sql.mesh.enabled").doc(
    "Lower hash shuffles to collective all_to_all exchanges over the "
    "jax.sharding.Mesh of all visible devices (ICI shuffle; ref: "
    "SURVEY.md §2.6 TPU mapping). Off = single-process materialized "
    "exchange.").boolean(False)

STAGE_FUSION_ENABLED = conf("spark.rapids.sql.stageFusion.enabled").doc(
    "Collapse maximal runs of contiguous row-local jittable device "
    "operators (Project, Filter, LocalLimit, Expand) into one fused "
    "kernel per stage — one XLA dispatch instead of one per operator, "
    "with no materialized batch between them (the WholeStageCodegen / "
    "GpuCoalesceBatches analog for this engine). A stage breaks at "
    "exchanges, aggregates, sorts, joins, host-roundtrip expressions "
    "and task-context expressions (rand, input_file_name...). Off "
    "restores the one-Exec-one-kernel plan shape.").boolean(True)

KERNEL_CACHE_MAX_ENTRIES = conf(
    "spark.rapids.sql.kernelCache.maxEntries").doc(
    "LRU bound on the process-global compiled-kernel cache keyed by "
    "(expression fingerprint, input schema, capacity bucket). Repeated "
    "queries — bench iterations, suite partitions, serving traffic — "
    "reuse compiled programs across planner/exec instances instead of "
    "re-tracing them; the bound caps host memory AND mmap regions held "
    "by cached executables. The latter is the binding constraint: a "
    "live XLA CPU executable for a real query kernel holds ~80 memory "
    "maps, and Linux caps a process at vm.max_map_count (65530 by "
    "default) — cross it and the next compile SIGSEGVs inside XLA. 512 "
    "keeps a fully-fat cache near ~40k maps; raise it only with a "
    "raised map ceiling.").integer(512)

HOST_CLOSURE_CACHE_MAX_ENTRIES = conf(
    "spark.rapids.sql.host.closureCache.maxEntries").doc(
    "LRU bound on the host engine's compiled-closure cache "
    "(ops/host_cache.py) — the numpy analog of the device kernel "
    "cache, keyed by the same structural expression fingerprint + "
    "bind-slot normalization so plan-cache bind-only executions walk "
    "no expression tree on host either. Entries are plain python "
    "closures (no XLA executables), so the bound only caps fingerprint "
    "bookkeeping memory.").integer(256)

DEVICE_BUDGET_BYTES = conf("spark.rapids.memory.tpu.budgetBytes").doc(
    "Explicit HBM budget for the buffer catalog in bytes; 0 derives it "
    "from allocFraction of the visible device memory (ref: RMM pool "
    "sizing, GpuDeviceManager.scala:159-230).").long(0)

TEST_FAULTS = conf("spark.rapids.sql.test.faults").doc(
    "Deterministic fault-injection schedule for chaos testing: "
    "comma-separated kind@site[:arg] entries (kinds oom/transient/"
    "corrupt; arg = fire-count or probability), e.g. "
    "'oom@upload:0.05,transient@exchange.flush:2,corrupt@wire:1'. "
    "Empty disarms. The SRT_FAULTS env var seeds the process-global "
    "schedule when this key is unset. See docs/robustness.md and "
    "spark_rapids_tpu/faults.py.").string("")

TEST_FAULTS_SEED = conf("spark.rapids.sql.test.faults.seed").doc(
    "Seed for the per-site fault-injection PRNGs and retry-backoff "
    "jitter: the same schedule + seed reproduces the same failures AND "
    "the same recovery timing (SRT_FAULTS_SEED env analog).").long(0)

RETRY_TRANSIENT_MAX = conf(
    "spark.rapids.sql.retry.transientMaxRetries").doc(
    "Per-query retry budget for transient backend/tunnel failures "
    "(UNAVAILABLE, DEADLINE_EXCEEDED, connection resets): the whole "
    "query re-runs on a fresh context up to this many times, with "
    "exponential backoff between attempts. 0 disables the retry."
).integer(2)

RETRY_BACKOFF_MS = conf("spark.rapids.sql.retry.backoffMs").doc(
    "Base backoff before transient-retry attempt i: "
    "min(backoffMs * 2^i, maxBackoffMs) scaled by deterministic jitter "
    "in [0.5, 1.0) seeded from spark.rapids.sql.test.faults.seed."
).long(50)

RETRY_MAX_BACKOFF_MS = conf("spark.rapids.sql.retry.maxBackoffMs").doc(
    "Ceiling on the exponential transient-retry backoff.").long(2000)

OOM_HOST_FALLBACK = conf("spark.rapids.sql.oom.hostFallback.enabled").doc(
    "Final OOM escalation rung: when a device operator exhausts the "
    "spill-some -> spill-all -> shrink ladder before producing its "
    "first batch, re-run that operator subtree on the host engine and "
    "upload the results (the reference's CPU-fallback-always-available "
    "guarantee applied at the dispatch funnel).").boolean(True)

WATCHDOG_ENABLED = conf("spark.rapids.sql.watchdog.enabled").doc(
    "Execution watchdog: run each partition's device execution under a "
    "deadline (taskTimeoutMs) with bounded re-dispatch (maxAttempts) — "
    "the speculative-re-execution analog of Spark's task-level "
    "straggler handling, with deterministic first-winner semantics so "
    "chaos runs stay bit-identical. Off by default: the per-partition "
    "worker thread is pure overhead on a healthy single-tenant chip."
).boolean(False)

WATCHDOG_TASK_TIMEOUT_MS = conf(
    "spark.rapids.sql.watchdog.taskTimeoutMs").doc(
    "Deadline per watchdog partition attempt. An attempt still running "
    "at the deadline is killed (cooperative cancel; a wedged device "
    "call is abandoned to its daemon thread) and re-dispatched."
).long(600000)

WATCHDOG_MAX_ATTEMPTS = conf("spark.rapids.sql.watchdog.maxAttempts").doc(
    "Total watchdog attempts per partition (first dispatch + "
    "re-dispatches). Exhausting them raises DEADLINE_EXCEEDED, handing "
    "recovery to the transient whole-query retry rung.").integer(2)

STAGE_RECOVERY_ENABLED = conf(
    "spark.rapids.sql.recovery.stageRecompute.enabled").doc(
    "Lineage-scoped recovery (parallel/stages.py): split the physical "
    "plan into a stage DAG at exchange boundaries and, when a durable "
    "stage output is lost or fails its checksum, invalidate and "
    "recompute ONLY that stage on the same query context — sibling "
    "stages serve their still-materialized outputs. Off = every "
    "recoverable failure falls back to the whole-query retry."
).boolean(True)

RECOVERY_MAX_STAGE_RECOMPUTES = conf(
    "spark.rapids.sql.recovery.maxStageRecomputes").doc(
    "Per-query budget of lineage-scoped stage recomputes before "
    "recovery demotes to the whole-query retry (a stage that keeps "
    "losing its output is a sick backend, not a transient blip)."
).integer(4)

PIPELINE_ENABLED = conf("spark.rapids.sql.pipeline.enabled").doc(
    "Pipelined partition execution (parallel/pipeline.py): a host thread "
    "pool runs the separable host half of each partition (scan-unit "
    "decode, filter-stat pruning, wire encode) prefetchPartitions ahead "
    "while the consumer dispatches device work in strict partition order "
    "under the TPU semaphore — upload of partition p+1 overlaps compute "
    "of p (the MULTITHREADED-reader overlap, GpuParquetScan.scala:1144, "
    "applied at every partition-loop dispatch funnel). Independent "
    "stages of the plan DAG additionally materialize their exchange "
    "outputs concurrently. Off (or SRT_PIPELINE=0) restores the serial "
    "per-partition dispatch exactly.").boolean(True)

PIPELINE_PREFETCH_PARTITIONS = conf(
    "spark.rapids.sql.pipeline.prefetchPartitions").doc(
    "How many partitions ahead of the ordered consumer the host half may "
    "run. 1 keeps exactly one partition in flight beyond the one being "
    "consumed; larger values smooth uneven partition decode times at the "
    "cost of host memory for the buffered encodes.").integer(2)

PIPELINE_HOST_THREADS = conf("spark.rapids.sql.pipeline.hostThreads").doc(
    "Host threads shared by the pipeline's partition prefetchers "
    "(decode + wire encode are pure CPU work; the reference's "
    "multiThreadedRead.numThreads plays the same role inside one scan)."
).integer(4)

PIPELINE_MAX_CONCURRENT_STAGES = conf(
    "spark.rapids.sql.pipeline.maxConcurrentStages").doc(
    "Upper bound on plan stages (parallel/stages.py DAG nodes) whose "
    "exchange outputs materialize concurrently — e.g. the build and "
    "probe side scans of a join. Device dispatch stays bounded by the "
    "query's TPU semaphore permit; this caps only the thread fan-out. "
    "1 disables concurrent stage materialization.").integer(2)

KERNEL_CACHE_PERSISTENT_DIR = conf(
    "spark.rapids.sql.kernelCache.persistentDir").doc(
    "Directory for JAX's persistent compilation cache: compiled XLA "
    "executables serialize here and survive process restarts, so a "
    "fresh process pays deserialization (~ms) instead of recompilation "
    "(~s) for every kernel it has ever compiled (the first_run_s tax). "
    "Hits surface as persistentCacheHits in the kernel-cache counters. "
    "Empty disables.").string("")

MESH_DEGRADE_ENABLED = conf("spark.rapids.sql.mesh.degrade.enabled").doc(
    "Graceful mesh degrade: when a mesh collective exchange fails, "
    "demote this query's exchanges to the single-process "
    "ShuffleExchangeExec path (counter meshDegrades) instead of killing "
    "the query. Off = collective failures propagate.").boolean(True)

SCHEDULER_MAX_CONCURRENT = conf(
    "spark.rapids.sql.scheduler.maxConcurrentQueries").doc(
    "Multi-query admission control (parallel/scheduler.py): at most this "
    "many collect()s execute at once; excess queries wait in the bounded "
    "run queue. 1 = strictly serial queries (byte-identical to the "
    "pre-scheduler engine); the SRT_SCHEDULER_MAX_CONCURRENT env "
    "overrides for a whole process.").integer(2)

SCHEDULER_QUEUE_DEPTH = conf("spark.rapids.sql.scheduler.queueDepth").doc(
    "Admission run-queue bound: queries beyond maxConcurrentQueries "
    "wait here, FIFO. A query arriving with the queue full is SHED with "
    "QueryRejectedError instead of letting unbounded concurrency OOM "
    "the device.").integer(16)

SCHEDULER_ADMISSION_TIMEOUT_MS = conf(
    "spark.rapids.sql.scheduler.admissionTimeoutMs").doc(
    "How long a queued query waits for a run slot before it is shed "
    "with QueryRejectedError (queuedMs reports the wait of admitted "
    "queries).").integer(60000)

SCHEDULER_QUERY_MEMORY_FRACTION = conf(
    "spark.rapids.sql.scheduler.queryMemoryFraction").doc(
    "Fair-share fraction of the device budget each admitted query's "
    "buffer catalog is charged against. 0 = auto "
    "(1/maxConcurrentQueries when queries can overlap, else the full "
    "budget); 1.0 = every query sees the full budget and isolation "
    "relies on admission + cross-query eviction.").double(1.0)

QOS_ENABLED = conf("spark.rapids.sql.scheduler.qos.enabled").doc(
    "Serving QoS subsystem (parallel/qos/): replaces the FIFO run queue "
    "with weighted fair queueing across priority classes, "
    "shortest-job-first ordering by the plan/cost.py estimate, "
    "per-tenant quotas, and deadline-aware admission. Default off: the "
    "scheduler is byte-for-byte the FIFO QueryManager. The SRT_QOS env "
    "enables it for a whole process (the CI matrix hook); the conf key "
    "wins when set.").boolean(False)

QOS_PRIORITY_CLASS = conf(
    "spark.rapids.sql.scheduler.qos.priorityClass").doc(
    "This session's default priority class: 'interactive', 'batch', or "
    "'background'. The priority= kwarg of DataFrame.collect/submit "
    "overrides per call. Ignored (recorded only) when qos.enabled is "
    "false.").string("batch")

QOS_WEIGHTS = conf("spark.rapids.sql.scheduler.qos.weights").doc(
    "WFQ weight vector 'interactive,batch,background' — run slots are "
    "granted proportionally to these weights over any window (stride "
    "scheduling; parallel/qos/policy.py). All weights must be > 0."
).string("8,3,1")

QOS_STARVATION_BOUND = conf(
    "spark.rapids.sql.scheduler.qos.starvationBound").doc(
    "Hard starvation bound: the max times a non-empty class may be "
    "bypassed for a run slot before its head query runs NEXT regardless "
    "of weights (counter starvationBoundEngagements).").integer(8)

QOS_TENANT = conf("spark.rapids.sql.scheduler.qos.tenant").doc(
    "Tenant identity for this session's queries (per-tenant quotas, "
    "plan-cache stats, chaos isolation). The tenant= kwarg of "
    "DataFrame.collect/submit overrides per call. Empty = 'default'."
).string("")

QOS_TENANT_MAX_IN_FLIGHT = conf(
    "spark.rapids.sql.scheduler.qos.tenantMaxInFlight").doc(
    "Per-tenant cap on in-flight (running + queued) queries; an "
    "over-cap tenant is rejected at admission with a typed "
    "QueryRejectedError (kind 'tenant-quota') carrying a retry-after "
    "hint. 0 = unlimited.").integer(0)

QOS_TENANT_MAX_CATALOG_BYTES = conf(
    "spark.rapids.sql.scheduler.qos.tenantMaxCatalogBytes").doc(
    "Per-tenant cap on owner-tagged catalog bytes "
    "(BufferCatalog.owned_bytes summed over the tenant's active "
    "queries) checked at admission. 0 = unlimited.").long(0)

QOS_TENANT_MAX_KERNEL_ENTRIES = conf(
    "spark.rapids.sql.scheduler.qos.tenantMaxKernelCacheEntries").doc(
    "Per-tenant compile budget: kernel-cache entries owned by the "
    "tenant's query ids (KernelCache.owners). Over the cap the "
    "tenant's OLDEST entries are evicted at its next admission "
    "(counter quotaEvictions) — never a rejection. 0 = unlimited."
).integer(0)

QOS_DEADLINE_ADMISSION = conf(
    "spark.rapids.sql.scheduler.qos.deadlineAdmission.enabled").doc(
    "Deadline-aware admission (qos.enabled only): a query whose "
    "plan/cost.py estimate cannot meet its collect(timeout_ms=...) "
    "deadline is rejected at admit time (kind 'deadline-unmeetable') "
    "instead of burning device time and dying to the kill timer. "
    "Un-priced queries always pass; the in-flight timer remains the "
    "backstop.").boolean(True)

QOS_DEADLINE_SLACK = conf(
    "spark.rapids.sql.scheduler.qos.deadlineSlack").doc(
    "Multiplier applied to the cost estimate before the deadline "
    "admission test (>1.0 rejects earlier — estimates are optimistic "
    "about queueing; <1.0 admits optimistically).").double(1.0)

PREEMPTION_ENABLED = conf(
    "spark.rapids.sql.scheduler.preemption.enabled").doc(
    "Class-aware device preemption (the overload survival plane): when "
    "a higher-priority query queues for the TPU semaphore behind a "
    "running lower-class query, the victim is asked to suspend at its "
    "next partition boundary — it spills its live catalog buffers "
    "through the existing memory ladder, releases the device permit, "
    "and resumes through the stage DAG after the preemptor drains "
    "(durable stage outputs are kept, so only unfinished work re-runs; "
    "results stay byte-identical for victim and preemptor). Default "
    "off: the device gate is the flat class-blind semaphore, "
    "byte-for-byte today's behavior. Counters preemptions/preemptedMs/"
    "resumedStages.").boolean(False)

PREEMPTION_MAX_PER_QUERY = conf(
    "spark.rapids.sql.scheduler.preemption.maxPerQuery").doc(
    "Upper bound on how many times one query may be preempted; past it "
    "the query ignores further preemption requests and runs to "
    "completion (livelock guard for a sustained interactive storm)."
).integer(4)

PREEMPTION_SPILL_ENABLED = conf(
    "spark.rapids.sql.scheduler.preemption.spill.enabled").doc(
    "Whether a preempted query spills its spillable device buffers to "
    "host while suspended (frees HBM for the preemptor). Off = suspend "
    "only releases the device permit and keeps buffers resident."
).boolean(True)

PRESSURE_ENABLED = conf(
    "spark.rapids.sql.scheduler.pressure.enabled").doc(
    "Memory-pressure shedding: each collect publishes a pressure score "
    "derived from its catalog's device/host/disk watermarks "
    "(srt_pressure_score; workers piggyback it on CBEAT heartbeats), "
    "the cluster coordinator demotes pressured workers below the "
    "steal-delay placement preference so they shed new stages instead "
    "of spilling, and sustained device pressure flips admission into "
    "brownout mode. Default off: no score is consulted anywhere."
).boolean(False)

PRESSURE_SHED_SCORE = conf(
    "spark.rapids.sql.scheduler.pressure.shedScore").doc(
    "Pressure score at or above which the coordinator demotes a worker "
    "in CPOLL placement (it loses steal-delay reservations and only "
    "receives a stage when every unpressured worker is busy or the "
    "reservation window expired). Scores are in [0, ~1.35]; the device "
    "fraction dominates.").double(0.75)

PRESSURE_BROWNOUT_SCORE = conf(
    "spark.rapids.sql.scheduler.pressure.brownout.enterScore").doc(
    "Device-pressure score at or above which (sustained for "
    "brownout.sustainMs) admission enters brownout: background-class "
    "queries are rejected with kind 'brownout' and a retry-after hint "
    "while interactive/batch admit normally — load is shed BEFORE the "
    "OOM ladders engage.").double(0.9)

PRESSURE_BROWNOUT_EXIT_SCORE = conf(
    "spark.rapids.sql.scheduler.pressure.brownout.exitScore").doc(
    "Pressure score below which brownout mode exits (hysteresis: must "
    "be below brownout.enterScore or brownout flaps).").double(0.7)

PRESSURE_BROWNOUT_SUSTAIN_MS = conf(
    "spark.rapids.sql.scheduler.pressure.brownout.sustainMs").doc(
    "How long the pressure score must stay at or above "
    "brownout.enterScore before admission browns out — one transient "
    "spike (a single large partition) must not shed a whole class."
).integer(200)

CLIENT_RETRY_MAX_ATTEMPTS = conf(
    "spark.rapids.sql.client.retry.maxAttempts").doc(
    "Default attempt budget for DataFrame.collect_with_retry: total "
    "admission attempts before the last QueryRejectedError propagates. "
    "Each retry honors the rejection's retry_after_ms hint with capped "
    "deterministic-jitter backoff (counter clientRetries / "
    "srt_client_retries_total).").integer(5)

CLIENT_RETRY_MAX_BACKOFF_MS = conf(
    "spark.rapids.sql.client.retry.maxBackoffMs").doc(
    "Cap on one collect_with_retry backoff sleep, applied after the "
    "retry_after_ms hint and the deterministic jitter (a rejection "
    "storm must converge, not sleep unboundedly).").integer(10000)

TEST_FAULTS_QUERY_TAG = conf(
    "spark.rapids.sql.test.faults.queryTag").doc(
    "Explicit fault tag for query-scoped chaos (kind@site/query=N "
    "entries fire only on the query whose tag is N). -1 = untagged: "
    "the scheduler admission ordinal is the tag.").integer(-1)

SHUFFLE_TRANSPORT = conf("spark.rapids.sql.shuffle.transport").doc(
    "Shuffle transport SPI selection (parallel/transport/): 'inprocess' "
    "(catalog-backed single-process exchange — today's default), 'mesh' "
    "(ICI collective all_to_all over the device mesh; implies what "
    "spark.rapids.sql.mesh.enabled used to select), or 'hostfile' "
    "(shards spooled to a shared directory with a manifest/socket "
    "rendezvous so independent worker processes can map-write and "
    "reduce-fetch each other's shards — the DCN multi-slice stand-in). "
    "Empty = inprocess unless SRT_SHUFFLE_TRANSPORT or the legacy "
    "mesh.enabled key says otherwise. The reference's analog is the "
    "RapidsShuffleInternalManager serializer fallback with the UCX "
    "plugin behind it (GpuColumnarBatchSerializer.scala:38).").string("")

SHUFFLE_TRANSPORT_HOSTFILE_DIR = conf(
    "spark.rapids.sql.shuffle.transport.hostfile.dir").doc(
    "Spool directory for the hostfile shuffle transport. All "
    "cooperating worker processes must see the same path (a shared "
    "filesystem is the stand-in for the DCN fabric). Empty = a "
    "per-process directory under the system temp dir — correct for "
    "single-process use, useless for cross-process rendezvous."
).string("")

SHUFFLE_TRANSPORT_HOSTFILE_WORKER_ID = conf(
    "spark.rapids.sql.shuffle.transport.hostfile.workerId").doc(
    "This process's worker identity in the hostfile spool (manifest "
    "name + shard subdirectory). Empty = 'w<pid>'.").string("")

SHUFFLE_TRANSPORT_HOSTFILE_EXPECTED_WORKERS = conf(
    "spark.rapids.sql.shuffle.transport.hostfile.expectedWorkers").doc(
    "How many worker manifests a reduce-side fetch waits for before "
    "serving shards (the membership half of the rendezvous). 1 = "
    "single-process (fetch only this worker's shards).").integer(1)

SHUFFLE_TRANSPORT_HOSTFILE_RENDEZVOUS = conf(
    "spark.rapids.sql.shuffle.transport.hostfile.rendezvous").doc(
    "Optional 'host:port' of the socket rendezvous "
    "(parallel/transport/rendezvous.py): committing workers announce "
    "their manifest over TCP and fetchers block on the commit barrier "
    "instead of polling the spool directory. Empty = manifest-file "
    "polling only.").string("")

SHUFFLE_TRANSPORT_HOSTFILE_FETCH_TIMEOUT_MS = conf(
    "spark.rapids.sql.shuffle.transport.hostfile.fetchTimeoutMs").doc(
    "How long a reduce-side fetch waits for the expected worker "
    "manifests before failing with a lost-shard error (which flows "
    "into the recovery ladder).").integer(30000)

SHUFFLE_TRANSPORT_HOSTFILE_EXCLUSIVE_MANIFEST = conf(
    "spark.rapids.sql.shuffle.transport.hostfile.exclusiveManifest").doc(
    "Single-writer manifest mode: the committing session publishes ONE "
    "tag-scoped 'exchange.manifest.json' (atomic rename) instead of a "
    "per-worker manifest, so a stage recompute on a DIFFERENT worker "
    "atomically REPLACES the dead worker's manifest — a late fetcher "
    "sees the old complete shard set or the new complete shard set, "
    "never a mix. The cluster runtime (parallel/cluster/) opens every "
    "stage-output session in this mode; expectedWorkers is forced to 1 "
    "(one committed manifest IS the stage output).").boolean(False)

SHUFFLE_TRANSPORT_HOSTFILE_RV_CONNECT_TIMEOUT_MS = conf(
    "spark.rapids.sql.shuffle.transport.hostfile.rendezvous."
    "connectTimeoutMs").doc(
    "Socket connect/read timeout for one rendezvous round trip "
    "(parallel/transport/rendezvous.py). A dead rendezvous peer fails "
    "the round trip within this bound instead of hanging the fetch "
    "indefinitely.").integer(5000)

SHUFFLE_TRANSPORT_HOSTFILE_RV_RETRIES = conf(
    "spark.rapids.sql.shuffle.transport.hostfile.rendezvous."
    "retries").doc(
    "Bounded retry count for one rendezvous round trip, with "
    "deterministic exponential backoff between attempts "
    "(rendezvous.backoffMs * 2^attempt, capped at 2s). Exhausted "
    "retries raise RendezvousUnavailableError — typed 'UNAVAILABLE:' "
    "so it maps onto the transient rung of the recovery ladder; the "
    "hostfile transport additionally DEGRADES to manifest-file polling "
    "instead of failing the fetch.").integer(3)

SHUFFLE_TRANSPORT_HOSTFILE_RV_BACKOFF_MS = conf(
    "spark.rapids.sql.shuffle.transport.hostfile.rendezvous."
    "backoffMs").doc(
    "Base backoff between rendezvous round-trip retries; attempt i "
    "sleeps backoffMs * 2^i (deterministic, capped at 2s).").integer(50)

SHUFFLE_TRANSPORT_OBJECTSTORE_ENDPOINT = conf(
    "spark.rapids.sql.shuffle.transport.objectstore.endpoint").doc(
    "Base URL of the object-store backend for the objectstore shuffle "
    "transport (parallel/transport/objectstore.py), e.g. "
    "'http://127.0.0.1:9000'. Empty = SRT_OBJECTSTORE_ENDPOINT, else an "
    "in-process localhost stub server is started once per process "
    "(single-machine stand-in for S3/GCS; the cluster coordinator pins "
    "the resolved endpoint into dispatched worker confs so every "
    "process shares one store).").string("")

SHUFFLE_TRANSPORT_OBJECTSTORE_PREFIX = conf(
    "spark.rapids.sql.shuffle.transport.objectstore.prefix").doc(
    "Key-namespace prefix prepended to every object this session "
    "reads or writes ('<prefix>/<tag>/<worker>/pNNNNN-SSSS.shard'). "
    "The cluster runtime sets '<cluster-ns>/q<qid>' per query so "
    "concurrent queries and clusters can share one store. Empty = "
    "keys rooted at the tag.").string("")

SHUFFLE_TRANSPORT_OBJECTSTORE_WORKER_ID = conf(
    "spark.rapids.sql.shuffle.transport.objectstore.workerId").doc(
    "This process's worker identity in the object store (manifest "
    "name + shard key segment). Empty = 'w<pid>'.").string("")

SHUFFLE_TRANSPORT_OBJECTSTORE_EXPECTED_WORKERS = conf(
    "spark.rapids.sql.shuffle.transport.objectstore.expectedWorkers"
).doc(
    "How many worker manifests a reduce-side fetch waits for before "
    "serving shards (same membership contract as "
    "hostfile.expectedWorkers). 1 = single-process.").integer(1)

SHUFFLE_TRANSPORT_OBJECTSTORE_EXCLUSIVE_MANIFEST = conf(
    "spark.rapids.sql.shuffle.transport.objectstore.exclusiveManifest"
).doc(
    "Single-writer manifest mode: commit publishes ONE tag-scoped "
    "'exchange.manifest.json' object (a whole-object PUT is the atomic "
    "publication barrier — readers see the old manifest or the new "
    "one, never a torn mix), mirroring "
    "hostfile.exclusiveManifest for the cluster runtime.").boolean(
    False)

SHUFFLE_TRANSPORT_OBJECTSTORE_FETCH_TIMEOUT_MS = conf(
    "spark.rapids.sql.shuffle.transport.objectstore.fetchTimeoutMs"
).doc(
    "How long a reduce-side fetch polls for the expected worker "
    "manifests before failing with a lost-shard error (which flows "
    "into the recovery ladder).").integer(30000)

SHUFFLE_TRANSPORT_OBJECTSTORE_RETRIES = conf(
    "spark.rapids.sql.shuffle.transport.objectstore.retries").doc(
    "Bounded retry count for one backend request (put/get/list/"
    "delete) on TRANSIENT errors — 5xx responses, refused/reset "
    "connections, socket timeouts. Attempt i sleeps backoffMs * "
    "2^(i-1) (capped at 2s) plus a deterministic jitter derived from "
    "the object key, so a fleet of fetchers retrying the same outage "
    "does not stampede in lockstep. Exhausted retries raise a typed "
    "'UNAVAILABLE:' error onto the transient rung of the recovery "
    "ladder. A 404 on a manifest-listed shard is NOT retried — that "
    "is shard loss and goes to stage recompute instead.").integer(4)

SHUFFLE_TRANSPORT_OBJECTSTORE_BACKOFF_MS = conf(
    "spark.rapids.sql.shuffle.transport.objectstore.backoffMs").doc(
    "Base backoff between backend-request retries (see "
    "objectstore.retries for the schedule).").integer(25)

SHUFFLE_TRANSPORT_OBJECTSTORE_TIMEOUT_MS = conf(
    "spark.rapids.sql.shuffle.transport.objectstore.timeoutMs").doc(
    "Socket connect/read timeout for one HTTP request to the object "
    "store backend.").integer(5000)

CLUSTER_ENABLED = conf("spark.rapids.sql.cluster.enabled").doc(
    "Distributed worker runtime (parallel/cluster/): the driver "
    "partitions each query's stage DAG into stage tasks and dispatches "
    "them to registered worker processes, which publish stage outputs "
    "as owner-tagged shards through the hostfile shuffle transport. "
    "false (the default) leaves every existing single-process code "
    "path byte-for-byte unchanged. Queries ineligible for dispatch "
    "(host-fallback nodes, mesh transport, no dispatchable stage, "
    "caller-provided context) stand down to local execution even when "
    "enabled.").boolean(False)

CLUSTER_COORDINATOR = conf("spark.rapids.sql.cluster.coordinator").doc(
    "host:port the driver-side coordinator binds its control-plane "
    "socket on (the rendezvous protocol extended with stage-task "
    "verbs). Workers register against this address. Empty = "
    "127.0.0.1 with an OS-assigned port (tests; read the bound "
    "address off the coordinator object).").string("")

CLUSTER_DIR = conf("spark.rapids.sql.cluster.dir").doc(
    "Shared spool directory for cluster stage outputs (the hostfile "
    "transport's DCN stand-in). All workers and the driver must see "
    "the same path. Empty = a per-process directory under the system "
    "temp dir — single-machine clusters only.").string("")

CLUSTER_MIN_WORKERS = conf("spark.rapids.sql.cluster.minWorkers").doc(
    "Dispatch gate: stage tasks are held until this many workers have "
    "registered (elastic membership — a worker joining later picks up "
    "queued tasks immediately).").integer(1)

CLUSTER_HEARTBEAT_TIMEOUT_MS = conf(
    "spark.rapids.sql.cluster.heartbeatTimeoutMs").doc(
    "A worker whose last heartbeat (or any control-plane traffic) is "
    "older than this is declared dead: its RUNNING stage task is "
    "requeued onto a survivor (one stage recompute — the partial spool "
    "is cleared first), and its membership is dropped.").integer(10000)

CLUSTER_POLL_MS = conf("spark.rapids.sql.cluster.pollMs").doc(
    "Worker task-poll interval and the driver's dispatch-loop tick. "
    "Workers heartbeat at a third of heartbeatTimeoutMs independently "
    "of this.").integer(25)

CLUSTER_DISPATCH_TIMEOUT_MS = conf(
    "spark.rapids.sql.cluster.dispatchTimeoutMs").doc(
    "How long the driver waits for the full stage-task set of one "
    "query (including requeues after worker death) before failing the "
    "dispatch with a typed UNAVAILABLE error that flows into the "
    "recovery ladder.").integer(300000)

CLUSTER_MAX_TASK_RETRIES = conf(
    "spark.rapids.sql.cluster.maxTaskRetries").doc(
    "Per-stage-task requeue budget (worker deaths + reported stage "
    "failures). A task exhausting it fails the query dispatch instead "
    "of requeueing forever.").integer(3)

CLUSTER_STEAL_DELAY_MS = conf(
    "spark.rapids.sql.cluster.stealDelayMs").doc(
    "Delay scheduling: how long a ready stage task is reserved for its "
    "preferred worker (most input-shard bytes, then rendezvous-hash "
    "owner) before any polling worker may steal it. Keeps repeat-query "
    "placement deterministic — a momentarily busy worker keeps its "
    "stages instead of paying a fresh kernel trace on whichever "
    "process grabbed them first. 0 disables the reservation.").integer(
    200)

CLUSTER_COORDINATOR_REMOTE = conf(
    "spark.rapids.sql.cluster.coordinator.remote").doc(
    "Treat cluster.coordinator as an ALREADY-RUNNING standalone "
    "coordinator process (python -m "
    "spark_rapids_tpu.parallel.cluster.coordinator) instead of "
    "hosting one in the driver. The driver submits stage plans over "
    "the control socket and polls for completion, riding out "
    "coordinator outages up to dispatchTimeoutMs — combined with the "
    "journal this is what makes a coordinator SIGKILL + restart "
    "mid-query survivable. Requires cluster.dir to be a path shared "
    "with the coordinator and workers.").boolean(False)

CLUSTER_JOURNAL_ENABLED = conf(
    "spark.rapids.sql.cluster.journal.enabled").doc(
    "Write-ahead journal for coordinator failover: registration and "
    "per-query stage state (submit/dispatch/done/requeue, with stage "
    "generations) are appended as torn-line-tolerant JSONL under "
    "<cluster.dir>/journal/ — the same event-log machinery as "
    "monitoring/history.py. A restarted coordinator replays the "
    "journal, re-adopts stage outputs whose transport manifests are "
    "still committed, and requeues only the tasks that were in "
    "flight, bounding a coordinator crash at ≤1 recompute per "
    "affected stage.").boolean(True)

CLUSTER_JOURNAL_FSYNC = conf(
    "spark.rapids.sql.cluster.journal.fsync").doc(
    "fsync the journal after every append. Off by default: the "
    "failover contract tolerates a torn tail (an unflushed 'done' "
    "record costs at most the one recompute the crash already "
    "budgeted), so the default buys dispatch latency instead of "
    "durability theater.").boolean(False)

CLUSTER_AUTOSCALE_ENABLED = conf(
    "spark.rapids.sql.cluster.autoscale.enabled").doc(
    "SLO-driven autoscaling of the worker pool "
    "(parallel/cluster/autoscaler.py): the autoscaler loop watches "
    "admission queueing (srt_admission_queued_ms), run-queue depth and "
    "fleet pressure (srt_pressure_score) against targetQueuedMs and "
    "spawns or cleanly drains workers through the supervisor, within "
    "[minWorkers, maxWorkers] and subject to cooldownMs hysteresis. "
    "Default off: the pool size is whatever was launched and no "
    "scaling decision is ever taken.").boolean(False)

CLUSTER_AUTOSCALE_MIN_WORKERS = conf(
    "spark.rapids.sql.cluster.autoscale.minWorkers").doc(
    "Autoscaler floor: the pool never drains below this many "
    "supervised workers, regardless of how idle the fleet is."
).integer(1)

CLUSTER_AUTOSCALE_MAX_WORKERS = conf(
    "spark.rapids.sql.cluster.autoscale.maxWorkers").doc(
    "Autoscaler ceiling: scale-up stops here. When the fleet is at "
    "the ceiling AND pressure stays sustained, brownout admission "
    "shedding engages (scale-up is tried FIRST — see "
    "scheduler.pressure.brownout.*).").integer(4)

CLUSTER_AUTOSCALE_TARGET_QUEUED_MS = conf(
    "spark.rapids.sql.cluster.autoscale.targetQueuedMs").doc(
    "Per-class admission-wait SLO the scale-up rule defends: when the "
    "observed queued-ms signal (worst class) exceeds this target, or "
    "the run queue is non-empty with every worker busy, the "
    "autoscaler requests scaleUpStep more workers.").integer(500)

CLUSTER_AUTOSCALE_SCALE_UP_STEP = conf(
    "spark.rapids.sql.cluster.autoscale.scaleUpStep").doc(
    "How many workers one scale-up decision adds (bounded by "
    "maxWorkers). Scale-down always retires exactly one worker per "
    "decision — draining is deliberately slower than spawning."
).integer(1)

CLUSTER_AUTOSCALE_SCALE_DOWN_IDLE_S = conf(
    "spark.rapids.sql.cluster.autoscale.scaleDownIdleS").doc(
    "How long the load signals must stay below target (no queueing, "
    "spare workers idle) before one worker is drained. Drains use "
    "CDRAIN: the coordinator stops dispatching to the worker, waits "
    "for its in-flight stages to commit their manifests, then "
    "retires it — scale-down never costs a stage recompute."
).integer(10)

CLUSTER_AUTOSCALE_COOLDOWN_MS = conf(
    "spark.rapids.sql.cluster.autoscale.cooldownMs").doc(
    "Minimum wall time between two autoscaling decisions (either "
    "direction). With the scaleDownIdleS dwell this is the "
    "hysteresis that makes the loop converge instead of flapping "
    "around the target.").integer(5000)

CLUSTER_SUPERVISOR_POLL_MS = conf(
    "spark.rapids.sql.cluster.supervisor.pollMs").doc(
    "Supervisor control-loop tick (parallel/cluster/supervisor.py): "
    "how often worker processes are reaped, restart backoffs "
    "re-evaluated, and straggler statistics pulled from the "
    "coordinator.").integer(250)

CLUSTER_SUPERVISOR_BACKOFF_BASE_MS = conf(
    "spark.rapids.sql.cluster.supervisor.restartBackoffBaseMs").doc(
    "First restart delay after a supervised worker dies; each "
    "consecutive death doubles it (deterministic exponential "
    "schedule) up to restartBackoffCapMs. A worker that completes a "
    "task resets its schedule.").integer(250)

CLUSTER_SUPERVISOR_BACKOFF_CAP_MS = conf(
    "spark.rapids.sql.cluster.supervisor.restartBackoffCapMs").doc(
    "Upper bound on the exponential restart backoff.").integer(10000)

CLUSTER_SUPERVISOR_CRASH_LOOP_WINDOW_MS = conf(
    "spark.rapids.sql.cluster.supervisor.crashLoopWindowMs").doc(
    "Crash-loop detection window: a worker that dies "
    "crashLoopThreshold times within this window is QUARANTINED — "
    "held out of the pool with a typed reason "
    "(srt_quarantined_workers gauge + worker-quarantined event-log "
    "instant) instead of being respawned forever.").integer(30000)

CLUSTER_SUPERVISOR_CRASH_LOOP_THRESHOLD = conf(
    "spark.rapids.sql.cluster.supervisor.crashLoopThreshold").doc(
    "Deaths within crashLoopWindowMs that quarantine a worker."
).integer(3)

CLUSTER_SUPERVISOR_STRAGGLER_FACTOR = conf(
    "spark.rapids.sql.cluster.supervisor.stragglerFactor").doc(
    "Straggler demotion threshold: a worker whose median CBEAT "
    "heartbeat interval or per-stage wall exceeds this multiple of "
    "the fleet median is demoted below steal-delay placement "
    "preference (CDEMO — the same pressure-shed tier as "
    "scheduler.pressure.shedScore), and promoted back once it "
    "recovers under factor*0.5.").double(3.0)

CLUSTER_SUPERVISOR_STRAGGLER_MIN_SAMPLES = conf(
    "spark.rapids.sql.cluster.supervisor.stragglerMinSamples").doc(
    "Minimum per-worker samples (heartbeat intervals or stage walls) "
    "before the straggler detector may judge it — outlier math on "
    "two points demotes noise.").integer(5)

CLUSTER_SUPERVISOR_DRAIN_TIMEOUT_MS = conf(
    "spark.rapids.sql.cluster.supervisor.drainTimeoutMs").doc(
    "How long a drain (CDRAIN) may wait for the worker's in-flight "
    "stages to commit before the supervisor escalates to terminating "
    "the process anyway (the heartbeat sweep then requeues whatever "
    "was left RUNNING).").integer(30000)

BROADCAST_CACHE_ENABLED = conf(
    "spark.rapids.sql.broadcast.cache.enabled").doc(
    "Cluster-wide broadcast artifact cache: the first process to "
    "build a broadcast build-side publishes the built batch through "
    "the shuffle transport (keyed by plan fingerprint + upstream "
    "stage generations, same CRC-framed blob + "
    "manifest-as-publication-barrier + refetch-once contract as "
    "stage outputs), and every other worker fetches it instead of "
    "re-collecting and re-building the same table. Only active when "
    "a query runs under the cluster runtime; any cache miss or "
    "corruption falls back to the local build, never to a query "
    "error.").boolean(True)

BROADCAST_CACHE_FETCH_TIMEOUT_MS = conf(
    "spark.rapids.sql.broadcast.cache.fetchTimeoutMs").doc(
    "How long a broadcast-cache probe waits for a published manifest "
    "before declaring a miss and building locally. Deliberately "
    "short — the cache is an optimization, and the local build is "
    "always correct.").integer(50)

NATIVE_ENABLED = conf("spark.rapids.sql.native.enabled").doc(
    "Native Pallas kernel layer (ops/native.py): re-implement the "
    "profiled top device-time sinks — the LSD radix sort's per-digit "
    "passes, the hash-join probe's double binary search, wire v2's RLE "
    "decode, and the sorted-segment groupby reductions — as TPU-native "
    "Pallas (Mosaic) kernels instead of jax.numpy compositions, the "
    "analog of the reference routing every kernel through libcudf "
    "(PAPER.md L0). Every native kernel is bit-identical to its "
    "jax.numpy twin (the parity suite pins this) and individually "
    "gateable via the spark.rapids.sql.native.<kernel>.enabled keys; "
    "false restores today's jax.numpy code paths byte-for-byte. "
    "Kernels engage only on a real TPU backend — CPU runs no-op to the "
    "fallback (SRT_NATIVE_INTERPRET=1 forces the Pallas interpreter for "
    "the CPU parity suite). The SRT_NATIVE env (0/1) overrides the "
    "default for a whole process.").boolean(True)

NATIVE_RADIX_SORT = conf("spark.rapids.sql.native.radixSort.enabled").doc(
    "Per-kernel gate: native LSD radix rank for the stable u32 sort "
    "passes every multi-pass sort shares (ops/kernels.py _radix_perm) — "
    "an 8-bit counting-sort rank (block histogram + scanned bases + "
    "stable within-block prefix) replacing XLA's O(n log^2 n) bitonic "
    "argsort per pass. Stable by construction, so the permutation is "
    "bit-identical.").boolean(True)

NATIVE_JOIN_PROBE = conf("spark.rapids.sql.native.joinProbe.enabled").doc(
    "Per-kernel gate: native hash-join probe (ops/join.py "
    "probe_ranges) — one fused branchless lower/upper binary search "
    "over the sorted build fingerprints (uint64 as two u32 planes, "
    "lexicographic compare) instead of two jnp.searchsorted "
    "dispatches.").boolean(True)

NATIVE_RLE_DECODE = conf("spark.rapids.sql.native.rleDecode.enabled").doc(
    "Per-kernel gate: native wire-v2 RLE decode (columnar/wire.py) — "
    "one interval-membership select over the run table instead of the "
    "searchsorted+gather chain, engaged when the run table fits "
    "native.rleDecode.maxRuns. Values move as bit patterns (int "
    "planes), so the decode stays bit-exact including -0.0/NaN float "
    "payloads.").boolean(True)

NATIVE_RLE_MAX_RUNS = conf("spark.rapids.sql.native.rleDecode.maxRuns").doc(
    "Run-table bound for the native RLE decode: a column whose run "
    "capacity exceeds this falls back to the jax.numpy "
    "searchsorted+gather decode (the interval select is O(rows x "
    "runs)).").integer(4096)

NATIVE_SEGMENT_REDUCE = conf(
    "spark.rapids.sql.native.segmentReduce.enabled").doc(
    "Per-kernel gate: native sorted-segment reduction (ops/kernels.py "
    "segment_reduce) — a single-sweep segmented scan (Hillis-Steele "
    "within blocks, a sequential-grid carry across them) replacing the "
    "scatter-based jax.ops.segment_* for group-sorted ids. Engages for "
    "integer/count sums (exact two's-complement, carried as u32 "
    "planes) and min/max in the total-order bit domain (so -0.0 < 0.0 "
    "and identities match the twin exactly); float SUMS stay on the "
    "jax.numpy twin — reduction order changes float rounding, and "
    "bit-identity is the contract.").boolean(True)

COST_CALIBRATION = conf("spark.rapids.sql.cost.calibration.enabled").doc(
    "Cost-model self-calibration (plan/cost.py): feed flight-recorder "
    "span timings (sync-category span means -> deviceSyncFloorMs, "
    "upload span bytes/wall -> deviceThroughputGBps) and the "
    "Cost@query estimateErrorPct back into the placement model as "
    "EWMA-updated effective constants, clamped to [1/4x, 4x] of the "
    "configured values — so placement tracks the machine it runs on "
    "instead of hand constants. An explicitly-set cost.* key always "
    "wins over the calibrated value. The SRT_COST_CALIBRATION env "
    "(0/1) overrides the default.").boolean(True)

COST_CALIBRATION_ALPHA = conf(
    "spark.rapids.sql.cost.calibration.alpha").doc(
    "EWMA weight of one query's observation when calibrating "
    "cost.{deviceSyncFloorMs,deviceThroughputGBps}.").double(0.2)

PLAN_CACHE_ENABLED = conf("spark.rapids.sql.planCache.enabled").doc(
    "Parameterized plan cache (plan/plan_cache.py): keep fully "
    "planned/fused/cost-placed physical plan templates in a "
    "process-global LRU keyed by the logical plan's structural "
    "fingerprint (literal VALUES hoisted into bind slots) + input "
    "schemas + the conf snapshot. A repeat execution with the same "
    "shape and new literals (filter constants, date ranges, limits) "
    "skips analysis/planning/fusion/cost placement entirely and binds "
    "its literals as runtime scalar kernel inputs, so compiled "
    "executables are shared across bindings too. Armed fault schedules "
    "bypass the cache; any conf change misses it. The SRT_PLAN_CACHE "
    "env (0/1) overrides the default for a whole process.").boolean(True)

PLAN_CACHE_MAX_ENTRIES = conf("spark.rapids.sql.planCache.maxEntries").doc(
    "LRU bound on the parameterized plan cache. Each entry pins one "
    "physical plan template (exec tree + tagged meta — no compiled "
    "kernels; those live in the kernel cache) plus, for in-memory "
    "sources, the source batches its key identifies.").integer(256)


class TpuConf:
    """Resolved view over a raw key->value dict (Spark SQL conf stand-in)."""

    def __init__(self, raw: Optional[Dict[str, Any]] = None):
        self.raw = dict(raw or {})
        self._version = 0

    @property
    def version(self) -> int:
        """Bumped on every set(); planners cache against it."""
        return self._version

    def get(self, entry: ConfEntry) -> Any:
        return entry.get(self)

    def get_key(self, key: str, default: Any = None) -> Any:
        entry = _REGISTRY.get(key)
        if entry is not None:
            return entry.get(self)
        return self.raw.get(key, default)

    def set(self, key: str, value: Any) -> "TpuConf":
        self.raw[key] = value
        self._version += 1
        return self

    def is_op_enabled(self, conf_key: str) -> bool:
        """Per-rule kill switch lookup; default True (ref: RapidsMeta confKey)."""
        raw = self.raw.get(conf_key)
        if raw is None:
            return True
        return raw if isinstance(raw, bool) else _parse_bool(str(raw))

    # Convenience accessors used widely.
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str:
        return str(self.get(EXPLAIN)).upper()

    @property
    def batch_size_rows(self) -> int:
        return self.get(BATCH_SIZE_ROWS)

    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES)

    @property
    def incompatible_ops(self) -> bool:
        return self.get(INCOMPATIBLE_OPS)

    @property
    def test_enabled(self) -> bool:
        return self.get(TEST_ENABLED)


def generate_docs() -> str:
    """Render configs.md, same shape as the reference's generated docs."""
    lines = [
        "# spark-rapids-tpu Configuration",
        "",
        "Generated from spark_rapids_tpu.config — do not edit by hand.",
        "",
        "| Name | Description | Default |",
        "|---|---|---|",
    ]
    for e in registered_entries():
        if e.internal:
            continue
        default = "None" if e.default is None else str(e.default)
        lines.append(f"| {e.key} | {e.doc} | {default} |")
    lines += [
        "",
        "## Stage fusion",
        "",
        "With `spark.rapids.sql.stageFusion.enabled` (default true) the",
        "planner collapses maximal runs of contiguous, row-local, jittable",
        "device operators into a single `FusedStageExec` whose body is one",
        "composed batch->batch function compiled as ONE kernel — a",
        "Project->Filter->Project chain costs one XLA dispatch instead of",
        "three, with no materialized batch between the steps.",
        "",
        "What fuses: `ProjectExec`, `FilterExec`, `LocalLimitExec`,",
        "`ExpandExec` — operators whose device kernel is a pure",
        "batch-in/batch-out function.",
        "",
        "What breaks a stage: exchanges (shuffle/broadcast), aggregates,",
        "sorts, joins, windows, generate, scans, engine transitions",
        "(host<->device bridges), host-roundtrip expressions (regexp,",
        "pad/replace, python UDF fallbacks), and task-context expressions",
        "(`rand`, `spark_partition_id`, `monotonically_increasing_id`,",
        "`input_file_name`), which need the per-batch EvalContext the",
        "unfused operator threads.",
        "",
        "Fused kernels (and every other operator kernel) are compiled",
        "through the process-global kernel cache bounded by",
        "`spark.rapids.sql.kernelCache.maxEntries`, so re-running a query",
        "— every bench iteration, every serving request — re-traces",
        "nothing. Cache behavior is observable per operator via the",
        "`kernelCacheHits`/`kernelCacheMisses`/`compileTime` metrics and",
        "fused stages are rendered in `explain`/`pretty_tree` output with",
        "their member operator names.",
        "",
        "## Pipelined execution",
        "",
        "With `spark.rapids.sql.pipeline.enabled` (default true) every",
        "partition-loop dispatch funnel (driver collect, exchange",
        "map-side materialization, broadcast collection) runs through a",
        "bounded producer/consumer pipeline: a host thread pool",
        "(`pipeline.hostThreads`) executes the separable host half of",
        "each partition — scan-unit decode, filter-stat pruning, wire",
        "encode — up to `pipeline.prefetchPartitions` ahead, while a",
        "single ordered consumer performs all device dispatch under the",
        "TPU semaphore. Upload of partition p+1 overlaps compute of p;",
        "results are deterministically ordered and bit-identical to the",
        "serial path. Independent plan stages (e.g. the two exchange",
        "inputs of a shuffled join) additionally materialize",
        "concurrently, bounded by `pipeline.maxConcurrentStages`.",
        "`SRT_PIPELINE=0` (or the conf) restores the serial dispatch",
        "exactly. Overlap is observable via the `Pipeline@query` metrics",
        "entry and bench.py's `pipeline` JSON block (`hostPrefetchMs`,",
        "`consumerWaitMs`, `pipelineStalls`, `concurrentStages`,",
        "`overlapRatio`). See docs/performance.md for the overlap model",
        "and the interaction with the watchdog/recovery demotion ladder.",
        "",
        "## Ingest fast path: wire codec v2 & coalesced uploads",
        "",
        "`spark.rapids.sql.wire.codec` (default `v2`) selects the",
        "host->device wire codec (columnar/wire.py): per column, one",
        "cheap host stats pass picks the smallest LOSSLESS encoding",
        "among narrow-int / dictionary (v1's set), run-length (sorted or",
        "low-run-count columns), delta (monotone/smooth integers: int64",
        "base + narrow deltas, decoded by an exact jitted cumsum) and",
        "frame-of-reference (clustered ids: base + narrow unsigned",
        "offsets). Decodes are gathers, bitcasts and exact integer",
        "arithmetic only — never emulated-f64 math — so every mode is",
        "transport-transparent: `plain`, `v1` and `v2` produce",
        "bit-identical query results (the dual-engine parity suite and",
        "the SRT_WIRE_CODEC=plain CI matrix entry pin this).",
        "",
        "All of a batch's wire arrays pack into ONE contiguous",
        "8-byte-aligned staging buffer with a static offset table, so an",
        "upload is a single device_put transfer plus one jitted",
        "unpack-and-decode program; consecutive encoded batches below",
        "`spark.rapids.sql.wire.minUploadBytes` share a transfer. The",
        "pack half runs on pipeline prefetch threads, so the ordered",
        "consumer only dispatches. bench.py's `wire` JSON block reports",
        "raw vs encoded bytes, per-codec column counts, transfer counts",
        "and the staging hit rate. See docs/performance.md.",
        "",
        "## Out-of-core grace hash joins",
        "",
        "`spark.rapids.sql.join.grace.enabled` (default true): a",
        "shuffled hash join whose build side exceeds",
        "`join.grace.buildFraction` of the device budget partitions",
        "BOTH sides by key fingerprint (the exchange's murmur3 hash",
        "partitioning) into spillable buckets and joins co-partitioned",
        "bucket pairs — peak HBM is one bucket's build side plus one",
        "probe batch, so a build side 2x+ the device budget runs",
        "ON-DEVICE instead of OOM-laddering to the host engine (beating",
        "the reference's RequireSingleBatch build restriction). Grace is",
        "also the OOM escalation rung directly ABOVE host fallback: a",
        "join whose single-batch build exhausts the spill/shrink ladder",
        "retries grace-partitioned first (`graceJoinEngaged`), and only",
        "a grace OOM demotes to host. `graceJoinPartitions` counts the",
        "buckets used, in per-operator metrics and the recovery block.",
        "",
        "## Robustness: fault injection & the recovery ladder",
        "",
        "Device OOMs at any dispatch funnel (upload, concat, cached",
        "kernel, download) walk a bounded escalation ladder instead of",
        "failing: spill-some -> spill-all -> shrink the batch target ->",
        "the operator's on-device degraded mode (a hash join retries",
        "grace-partitioned, `spark.rapids.sql.join.grace.enabled`) ->",
        "degrade the operator subtree to the host engine",
        "(`spark.rapids.sql.oom.hostFallback.enabled`). Execution-side",
        "failures demote through partition-scoped, then stage-scoped,",
        "then query-scoped recovery: the execution watchdog",
        "(`spark.rapids.sql.watchdog.*`) kills and re-dispatches a",
        "stalled partition; lineage-scoped stage recovery",
        "(`spark.rapids.sql.recovery.stageRecompute.enabled`) recomputes",
        "only the stage whose durable exchange output was lost or failed",
        "its checksum; transient backend/tunnel errors retry first on",
        "the same context (materialized stages are reused) and only then",
        "re-run the whole query on a fresh context with exponential",
        "backoff, bounded by `spark.rapids.sql.retry.transientMaxRetries`.",
        "A failed mesh collective demotes that query's exchanges to the",
        "single-process shuffle path",
        "(`spark.rapids.sql.mesh.degrade.enabled`). Spilled frames",
        "carry a CRC32 checksum verified at deserialize, so corruption",
        "is detected (and re-read once) instead of decoding into wrong",
        "rows. The whole machinery is continuously exercised by",
        "deterministic fault injection (`spark.rapids.sql.test.faults` /",
        "`SRT_FAULTS`) — see docs/robustness.md, tests/test_chaos.py and",
        "tests/test_stage_recovery.py. Recovery counters",
        "(retriesAttempted, spillEscalations, hostFallbacks,",
        "faultsInjected, corruptionsDetected, stageRecomputes,",
        "partitionRetries, watchdogKills, meshDegrades,",
        "meshCollectiveSkipped, crossQueryEvictions) surface",
        "through `DataFrame.metrics()` and bench.py's JSON report.",
        "",
        "## Shuffle transport SPI",
        "",
        "`spark.rapids.sql.shuffle.transport` selects where shuffle",
        "shards live (parallel/transport/, docs/shuffle.md):",
        "",
        "- `inprocess` (default) — the BufferCatalog-backed",
        "  single-process exchange: shards are spillable catalog",
        "  handles under the memory ladder.",
        "- `mesh` — hash shuffles lower to `jax.lax.all_to_all`",
        "  collectives over the device mesh (the ICI path; the legacy",
        "  `spark.rapids.sql.mesh.enabled` key still selects it).",
        "  Logical partition counts that differ from the mesh size FOLD",
        "  onto devices (`meshPartitionFolds`) instead of degrading.",
        "- `hostfile` — shards spool to a shared directory as",
        "  CRC-framed blobs with a manifest/socket rendezvous",
        "  (`shuffle.transport.hostfile.*` keys), so N independent",
        "  worker processes can map-write and reduce-fetch each",
        "  other's shards — the DCN multi-slice stand-in.",
        "",
        "All transports share the recovery contract: a lost or",
        "persistently-corrupt shard raises owner-tagged and costs ONE",
        "lineage-scoped stage recompute; a transiently-corrupt fetch",
        "refetches once (`remoteShardRefetches`). The",
        "`SRT_SHUFFLE_TRANSPORT` env overrides the default for a whole",
        "process (the CI matrix hook), and `Transport@query` metrics +",
        "bench.py's `transport` JSON block carry",
        "`transportBytesWritten/Fetched` and the recovery counters.",
        "",
        "## Multi-query admission, isolation & cancellation",
        "",
        "Concurrent `collect()`s from multiple threads run through the",
        "process-wide QueryManager (parallel/scheduler.py): at most",
        "`spark.rapids.sql.scheduler.maxConcurrentQueries` queries",
        "execute at once, excess queries wait FIFO in a run queue of",
        "`scheduler.queueDepth`, and a query arriving with the queue",
        "full — or waiting past `scheduler.admissionTimeoutMs` — is",
        "SHED with `QueryRejectedError` instead of oversubscribing the",
        "device. Each admitted query gets an owner id that tags every",
        "catalog buffer and kernel-cache reservation it creates, a",
        "fair-share device budget (`scheduler.queryMemoryFraction`),",
        "and a cooperative cancellation token:",
        "`DataFrame.collect(timeout_ms=...)` arms a deadline and",
        "`DataFrame.submit().cancel()` stops a query mid-flight — both",
        "unwind with `QueryCancelledError` at the next dispatch",
        "checkpoint, releasing the TPU semaphore and every owned buffer",
        "(the catalog leak report proves teardown freed everything).",
        "The OOM ladder spills the offending query's own buffers",
        "through two rungs before evicting neighbors",
        "(`crossQueryEvictions`), and query-scoped fault arming",
        "(`kind@site/query=N` with",
        "`spark.rapids.sql.test.faults.queryTag`) lets chaos tests",
        "prove a fault injected into one query is invisible to its",
        "neighbors. `SRT_SCHEDULER_MAX_CONCURRENT=1` degenerates to",
        "strictly serial queries, byte-identical to the pre-scheduler",
        "engine. See docs/robustness.md and tests/test_scheduler.py.",
        "",
        "## Serving QoS: priority classes, fair queueing, tenant quotas",
        "",
        "With `spark.rapids.sql.scheduler.qos.enabled` (default FALSE;",
        "`SRT_QOS=1` enables for a whole process) the QueryManager's",
        "FIFO run queue is replaced by the cost-aware QoS scheduler",
        "(parallel/qos/): queries carry a priority class —",
        "`interactive` / `batch` / `background`, from",
        "`scheduler.qos.priorityClass` or the `priority=` kwarg of",
        "`DataFrame.collect/submit` — and run slots are granted by",
        "weighted fair queueing over `scheduler.qos.weights` with a",
        "HARD starvation bound (`scheduler.qos.starvationBound`: after",
        "that many bypasses a starved class's head runs next,",
        "counter `starvationBoundEngagements`). Within a class,",
        "queries drain shortest-job-first by the plan/cost.py estimate",
        "(plan-cache hits reuse the template's CostReport, so ordering",
        "is free for repeat shapes). Tenants",
        "(`scheduler.qos.tenant` / the `tenant=` kwarg) get",
        "admission-time quotas: in-flight query caps",
        "(`tenantMaxInFlight`), owner-tagged catalog bytes",
        "(`tenantMaxCatalogBytes`), and a kernel-cache compile budget",
        "(`tenantMaxKernelCacheEntries`, enforced by evicting the",
        "tenant's oldest entries — `quotaEvictions`). A deadline armed",
        "via `collect(timeout_ms=...)` is additionally tested against",
        "the cost estimate AT ADMISSION",
        "(`qos.deadlineAdmission.enabled`): an unmeetable deadline",
        "rejects immediately instead of burning device time. Every",
        "rejection is a structured `QueryRejectedError` carrying",
        "`kind` (`queue-full` / `admission-timeout` / `tenant-quota` /",
        "`deadline-unmeetable`), a `queue_depth` snapshot, and a",
        "`retry_after_ms` hint derived from observed service times.",
        "Disabled, the scheduler is byte-for-byte the FIFO",
        "QueryManager (the `qos-on` tier-1 matrix entry proves the",
        "whole suite passes identically either way). See",
        "docs/serving.md and tests/test_qos.py for the model and the",
        "1000-query x 4-tenant soak contract.",
        "",
        "## Cost-based placement & adaptive re-planning",
        "",
        "With `spark.rapids.sql.cost.enabled` (default true) the planner",
        "estimates every logical subtree's device time (per-dispatch sync",
        "floor x sync count + bytes over the device pipeline) and host",
        "time (bytes over the host engine) from parquet/ORC footer stats",
        "and places whole maximal subtrees on the HOST engine when the",
        "host estimate strictly wins — small inputs cannot amortize the",
        "~70-100ms round-trip floor of a tunneled chip. Calibration",
        "constants (`cost.deviceSyncFloorMs`, `cost.deviceThroughputGBps`,",
        "`cost.hostThroughputGBps`, `cost.maxHostBytes`) are",
        "conf-overridable; `cost.explain` renders per-node estimates;",
        "`SRT_COST=0` restores the legacy all-device planner for a whole",
        "process. Placement stands down in test mode, under an armed",
        "fault schedule, on non-inprocess transports, and for plans",
        "without a file scan.",
        "",
        "At runtime, `spark.rapids.sql.aqe.replan.enabled` (default true)",
        "re-plans mid-query from OBSERVED shuffle sizes: each shuffled",
        "hash join's build-side exchange materializes first, its",
        "transport session records exact per-partition bytes, and a build",
        "side within `autoBroadcastJoinThreshold` demotes the join to a",
        "broadcast hash join — the probe side never shuffles, the fusion",
        "pass re-runs over the rewritten subtree, and lineage recovery",
        "still covers the re-planned stages. Post-shuffle coalescing",
        "merges partitions while BOTH `aqe.coalescePartitions.targetRows`",
        "and `aqe.coalescePartitions.targetBytes` hold. Decisions and",
        "estimate-vs-actual error surface in the `Cost@query` metrics",
        "entry and bench.py's `cost` JSON block. See docs/performance.md.",
        "",
        "## Parameterized plan cache",
        "",
        "With `spark.rapids.sql.planCache.enabled` (default true;",
        "`SRT_PLAN_CACHE=0` disables for a whole process) every",
        "`collect()` first rewrites its logical plan's bindable literal",
        "leaves (numeric/bool/date operands of comparisons and",
        "arithmetic in filters and projections, plus `limit(n)` values)",
        "into positional BIND SLOTS, then looks the parameterized shape",
        "up in a process-global LRU keyed by (structural plan",
        "fingerprint, input schemas, conf snapshot). A hit skips",
        "analysis, planning, capability tagging, fusion and cost",
        "placement entirely — the cached physical template executes with",
        "this call's literals bound as runtime scalar kernel inputs, so",
        "kernel-cache fingerprints (and compiled XLA executables) are",
        "shared across bindings and a re-parameterized query re-traces",
        "nothing. Per-query state (ExecContext, owner tags, AQE replan",
        "decisions, trace rings) stays per-execution. Invalidation is",
        "conservative: ANY conf change, schema change, or armed fault",
        "schedule misses or bypasses the cache. `explain()` annotates",
        "provenance (`[plan-cache hit, bind-only]`), `DataFrame.prepare()`",
        "returns the bound template as an explicit prepared-statement",
        "handle, and `scripts/warmup.py` replays a shape manifest so a",
        "fresh process serves its first query without the cold-compile",
        "cliff. Counters (planCacheHits/Misses/bindOnlyExecutions) land",
        "in bench.py's `plan_cache` block and per-tenant on the",
        "`Scheduler@query` metrics entry. See docs/performance.md.",
        "",
        "## Query flight recorder",
        "",
        "With `spark.rapids.sql.trace.enabled` (or `SRT_TRACE=1`) every",
        "execution funnel records structured spans — scheduler admission",
        "queue, TPU-semaphore acquire, host prefetch, wire pack, upload,",
        "per-operator device dispatch, shuffle materialize/serve, stage",
        "prematerialization, result download — and instant events (fault",
        "injected, OOM rung, stage recompute, join demotion, watchdog",
        "kill, cancellation, cross-query eviction) into a bounded",
        "per-query ring buffer (`trace.maxEvents`; `trace.level` picks",
        "query < operator < kernel verbosity). Consumers:",
        "`DataFrame.trace_export(path)` writes Chrome trace-event JSON",
        "(Perfetto / chrome://tracing, one track per query and worker",
        "thread), `DataFrame.explain_analyze()` renders the plan tree",
        "with observed rows/bytes/wall next to the cost model's",
        "estimates, `monitoring.snapshot()` aggregates the span-category",
        "breakdown bench.py publishes as its `trace` JSON block.",
        "Disabled, the recorder is a shared no-op costing nanoseconds",
        "per call site — results and metrics are byte-identical either",
        "way. See docs/observability.md.",
        "",
        "## Live telemetry & history",
        "",
        "With `spark.rapids.sql.metrics.enabled` (default false;",
        "`SRT_METRICS=1` env override) every process keeps a typed",
        "metric registry — counters, gauges and sliding-window",
        "histograms with p50/p95/p99 — fed from the existing",
        "scheduler/memory/cache/shuffle counter funnels plus per-query",
        "labeled series (status, QoS class, tenant, rejection kind).",
        "`spark.rapids.sql.metrics.port` (default 0 = off) additionally",
        "serves the registry in OpenMetrics text format on a",
        "localhost-only HTTP endpoint (`/metrics`, `/healthz`) for",
        "Prometheus-style scraping; `telemetry.snapshot()` and",
        "`telemetry.render_text()` expose the same view in-process",
        "with zero dependencies. In cluster mode workers piggyback",
        "metric deltas on their heartbeats, so the coordinator process",
        "scrapes a fleet view with per-worker labels.",
        "",
        "`spark.rapids.sql.eventLog.dir` (default empty = off;",
        "`SRT_EVENT_LOG` env override) appends one JSONL record per",
        "query at teardown — status, class, tenant, plan fingerprint,",
        "per-node observed rows/bytes/wall, span-category breakdown and",
        "recovery instants. `scripts/history.py` reconstructs",
        "explain_analyze-style reports and a fleet summary from the log",
        "alone after every process has exited. Both gates are",
        "exposition-only: disabled (the default) the hot paths reduce",
        "to a single global load, and results are byte-identical either",
        "way. See docs/observability.md.",
        "",
        "## Native Pallas kernels",
        "",
        "With `spark.rapids.sql.native.enabled` (default true) the hot",
        "device loops the flight recorder profiles as the top",
        "device-time sinks run as TPU-native Pallas (Mosaic) kernels",
        "instead of jax.numpy compositions — the analog of the",
        "reference routing every kernel through libcudf:",
        "",
        "- `native.radixSort.enabled` — stable u32 radix rank for every",
        "  LSD sort pass (`ops/kernels.py _radix_perm`): block",
        "  histograms + scanned digit bases + a stable within-block",
        "  prefix, 4 counting passes per word instead of an XLA",
        "  bitonic argsort.",
        "- `native.joinProbe.enabled` — the hash-join probe's double",
        "  binary search (`ops/join.py probe_ranges`) fused into one",
        "  branchless lower/upper search over two u32 planes.",
        "- `native.rleDecode.enabled` — wire v2's RLE decode as an",
        "  interval-membership select over the run table (bounded by",
        "  `native.rleDecode.maxRuns`), bit patterns only.",
        "- `native.segmentReduce.enabled` — sorted-segment groupby",
        "  reductions as a single-sweep segmented scan (integer/count",
        "  sums exactly in u32 carry planes; min/max in the total-order",
        "  bit domain; float sums stay on the twin because reduction",
        "  order changes float rounding).",
        "",
        "Every native kernel keeps its jax.numpy twin as a per-op",
        "kill-switch fallback and is BIT-IDENTICAL to it (the",
        "tests/test_native.py parity suite pins the whole dtype ladder",
        "including -0.0/NaN); `native.enabled=false` (or `SRT_NATIVE=0`)",
        "restores today's code paths byte-for-byte. Kernels engage only",
        "on a real TPU backend — CPU runs no-op to the fallback, and",
        "`SRT_NATIVE_INTERPRET=1` forces the Pallas interpreter so the",
        "CPU CI can prove parity. `scripts/microbench.py` compares each",
        "native kernel against its twin (the >=2x-on-TPU claim);",
        "bench.py's `native` JSON block reports the enabled set and",
        "trace counts. See docs/performance.md.",
        "",
        "## Dynamic per-rule kill switches",
        "",
        "Beyond the registered keys, every planner rule accepts a boolean",
        "kill switch (RapidsMeta confKey analog, default true):",
        "",
        "- `spark.rapids.sql.exec.<ExecName>` — disable one physical",
        "  operator (e.g. `spark.rapids.sql.exec.LogicalJoin`); the plan",
        "  falls back to the host engine there with an explain reason.",
        "- `spark.rapids.sql.expression.<kind>` — disable one expression",
        "  kind (e.g. `spark.rapids.sql.expression.upper`); the enclosing",
        "  operator falls back with a reason naming the expression.",
    ]
    return "\n".join(lines) + "\n"
