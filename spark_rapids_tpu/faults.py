"""Deterministic fault-injection registry (the chaos half of the
robustness story).

The reference's headline guarantee is that nothing failing ever corrupts
a query: RMM's alloc-failure callback spills and retries
(DeviceMemoryEventHandler.scala:42-69) and CPU fallback is always
available. This engine has the same machinery (memory/oom.py ladder,
planner transient retry, host degradation) — but recovery code that is
never exercised is recovery code that cannot be trusted. This module
makes every dispatch funnel *injectable* so tests/test_chaos.py can run
real queries under seeded fault schedules and assert bit-identical
results.

Spec grammar (``spark.rapids.sql.test.faults`` config or ``SRT_FAULTS``
env)::

    kind@site[/query=N][:arg][,kind@site[/query=N][:arg]...]

- ``kind``: ``oom`` (raises a synthetic RESOURCE_EXHAUSTED, recovered by
  the OOM escalation ladder), ``transient`` (raises a synthetic
  UNAVAILABLE, recovered by the planner's retry ladder), ``corrupt``
  (flips one byte of a serialized frame at a corruption site; detected
  by the CRC32 frame checksum and re-read), ``lostoutput`` (simulates a
  lost durable stage output at an exchange site; recovered by the
  lineage-scoped stage recompute, parallel/stages.py), ``stall``
  (hangs the dispatch until the execution watchdog kills and
  re-dispatches the partition, ops/base.py), or ``workerdeath``
  (SIGKILLs the cluster worker process at the ``cluster.stage`` site,
  parallel/cluster/worker.py — the coordinator's heartbeat monitor
  detects the death and requeues the stage task on a survivor: one
  stage recompute, never a dead query), ``slowput`` (injects latency
  into a shuffle-transport shard write at the ``transport`` site —
  exercises slow-writer overlap, never an error), or ``unavailable``
  (one backend request at the ``objectstore`` site fails with a
  synthetic 5xx/UNAVAILABLE; absorbed by the transport's bounded
  retry with exponential backoff + deterministic jitter, counter
  ``objectstoreRetries``).
- ``site``: a named injection point woven into the dispatch funnels:
  ``upload`` (wire codec device_put), ``download`` (result device_get),
  ``concat`` (batch coalescing), ``kernel`` (cached-kernel dispatch),
  ``scan`` (host-side scan-unit decode — fires on prefetch/reader
  threads and is re-raised at the ordered consumption point under the
  pipelined executor), ``exchange.flush`` / ``exchange.serve`` (shuffle
  map/reduce sides), ``mesh.exchange`` (collective shuffle),
  ``transport`` / ``transport.write`` (shuffle-transport SPI fetch and
  write funnels, parallel/transport/ — ``lostshard`` deletes the shard
  at rest and raises owner-tagged, so recovery MUST recompute the
  owning stage; ``corrupt`` flips a byte of the fetched frame, detected
  by the CRC and refetched once, counter ``remoteShardRefetches``;
  ``slowput`` delays the shard write), ``objectstore`` (one HTTP
  request to the object-store backend — ``unavailable`` only),
  ``spill.write`` / ``spill.read`` (disk tier I/O), ``wire``
  (serialized spill frames — corrupt only), ``cluster.stage``
  (cluster worker stage-task execution — workerdeath only).
- ``arg``: an integer N fires on the first N hits of the site (default
  1); a float p in (0, 1) fires per-hit with probability p from a
  deterministic per-site PRNG seeded by
  ``spark.rapids.sql.test.faults.seed`` / ``SRT_FAULTS_SEED``.
- ``/query=N``: query-scoped arming — the entry fires only on hits made
  by the query whose fault tag is ``N`` (the explicit
  ``spark.rapids.sql.test.faults.queryTag`` conf, falling back to the
  scheduler admission ordinal). Cross-query chaos tests inject a fault
  into query A and assert query B's results and counters are
  bit-identical to a solo run (parallel/scheduler.py, ISSUE 5).

This module also carries the per-thread QUERY TOKEN — the cooperative
cancellation/deadline handle the QueryManager (parallel/scheduler.py)
issues at admission. Every dispatch funnel already calls
:func:`fault_point`, so the same funnels double as cancellation
checkpoints: a cancelled or deadline-expired query unwinds with
:class:`QueryCancelledError` at its next dispatch, releasing the TPU
semaphore and every owned buffer on the way out. The token lives here
(not in the scheduler) because deep dispatch code may import faults but
must not import the scheduler.

The registry is process-global and ARMED only while a non-empty spec is
configured; a disarmed ``fault_point`` is two attribute loads (the
cancellation checkpoint + the injector), so production dispatch pays
almost nothing. Every injection/recovery event bumps
the process-global counters (``faultsInjected``, ``retriesAttempted``,
``spillEscalations``, ``hostFallbacks``, ``corruptionsDetected``) and,
when a query is running, the per-query ``Recovery`` Metrics sink —
surfaced through ``DataFrame.metrics()`` and ``bench.py``'s JSON.

Deliberately imports nothing beyond stdlib: oom/stores/wire/ops all
import this module from deep dispatch code.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple


class InjectedOomError(RuntimeError):
    """Synthetic device allocation failure. The message carries the
    backend's RESOURCE_EXHAUSTED marker so ``is_oom_error`` routes it
    into the spill/retry ladder exactly like the real thing."""

    def __init__(self, site: str):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected fault at {site!r} "
            f"(spark.rapids.sql.test.faults)")
        self.site = site


class InjectedTransientError(RuntimeError):
    """Synthetic backend/tunnel failure. Carries the UNAVAILABLE marker
    so ``is_transient_error`` routes it into the whole-query retry."""

    def __init__(self, site: str):
        super().__init__(
            f"UNAVAILABLE: injected transient fault at {site!r} "
            f"(spark.rapids.sql.test.faults)")
        self.site = site


class InjectedLostOutputError(RuntimeError):
    """Synthetic loss of a durable stage output (a shuffle/broadcast
    materialization that vanished or failed its checksum). Carries the
    UNAVAILABLE marker so, when lineage-scoped recovery is disabled or
    cannot attribute the loss, the whole-query retry still recovers it.
    ``fault_owner`` (``id()`` of the owning exchange exec, set by the
    injection site) lets parallel/stages.py invalidate and recompute
    just the owning stage instead."""

    def __init__(self, site: str):
        super().__init__(
            f"UNAVAILABLE: injected lost stage output at {site!r} "
            f"(spark.rapids.sql.test.faults)")
        self.site = site
        self.fault_owner: Optional[int] = None


class InjectedStallError(RuntimeError):
    """Raised when an injected stall is cancelled by the execution
    watchdog (the killed attempt's thread unwinds on it) or when its
    safety timeout expires with no watchdog armed. The message carries
    the DEADLINE_EXCEEDED marker so an escaped stall routes into the
    transient retry instead of failing the query."""

    def __init__(self, site: str):
        super().__init__(
            f"DEADLINE_EXCEEDED: injected stall at {site!r} "
            f"(spark.rapids.sql.test.faults)")
        self.site = site


class QueryCancelledError(RuntimeError):
    """The query was cancelled (explicit ``cancel()``) or its deadline
    expired (``collect(timeout_ms=...)``). The message deliberately
    carries NO transient/OOM marker: a cancelled query must unwind
    through every retry ladder — not be lovingly retried by one."""

    def __init__(self, query_id: int, reason: str):
        super().__init__(
            f"CANCELLED: query {query_id} {reason} "
            "(spark.rapids.sql.scheduler.*)")
        self.query_id = query_id
        self.reason = reason


class QueryPreemptedError(RuntimeError):
    """Control-flow only: the query was asked to yield the device to a
    higher-priority class and unwound at a partition boundary. The
    planner's ladder catches it, spills the query's catalog, waits for
    the preemptor to drain, and resumes on the SAME context — durable
    stage outputs make the suspension invisible in the results. Like
    cancellation, the message carries NO transient/OOM marker: no other
    retry rung may consume a preemption."""

    def __init__(self, query_id: int, preemptor: Optional[str] = None):
        super().__init__(
            f"PREEMPTED: query {query_id} yielded the device to a "
            f"{preemptor or 'higher-priority'} query "
            "(spark.rapids.sql.scheduler.preemption.*)")
        self.query_id = query_id
        self.preemptor = preemptor


class QueryToken:
    """Per-query cooperative cancellation/deadline handle, issued by the
    QueryManager at admission and registered thread-locally on every
    thread that works for the query (the collect thread itself, watchdog
    attempt workers, pipeline prefetchers, concurrent stage threads).

    ``cancel`` is a plain Event so blocking waits (semaphore admission,
    pipeline ``_take``, injected stalls) can wake on it; ``reason`` is
    set before the event so the unwinding error names why. The deadline
    is enforced by the scheduler's timer arm (it sets the same event),
    so checkpoints only ever test one flag.

    ``preempt`` is the overload survival plane's second, gentler signal
    (scheduler.preemption.enabled): set by the class-ranked device gate
    when a higher-priority query is queued behind this one. Unlike
    cancel it is only honored at partition boundaries
    (:func:`check_preempted`) and the query RESUMES afterwards — it
    never changes results, only when the device is held."""

    __slots__ = ("query_id", "fault_tag", "cancel", "reason", "tenant",
                 "qos_class", "preempt", "preemptor_class",
                 "preempt_enabled")

    def __init__(self, query_id: int, fault_tag: Optional[int] = None,
                 tenant: Optional[str] = None,
                 qos_class: Optional[str] = None):
        self.query_id = query_id
        # The tag query-scoped fault entries (kind@site/query=N) match.
        self.fault_tag = fault_tag if fault_tag is not None else query_id
        self.cancel = threading.Event()
        self.reason = "cancelled"
        # Serving-tier identity (parallel/qos/): owner attribution for
        # per-tenant quotas and plan-cache stats. None = untagged.
        self.tenant = tenant
        # Priority class (parallel/qos/) — the class-ranked device gate
        # orders acquisition and picks preemption victims by it. None =
        # FIFO admission (ranks as the default class).
        self.qos_class = qos_class
        self.preempt = threading.Event()
        self.preemptor_class: Optional[str] = None
        # Cleared by the planner once preemption.maxPerQuery is spent:
        # further requests are ignored and the query runs to completion.
        self.preempt_enabled = True

    def request_cancel(self, reason: str = "cancelled") -> None:
        self.reason = reason
        self.cancel.set()

    def cancelled(self) -> bool:
        return self.cancel.is_set()

    def error(self) -> QueryCancelledError:
        return QueryCancelledError(self.query_id, self.reason)

    def request_preempt(self, preemptor_class: Optional[str] = None) -> None:
        """Ask this query to yield the device at its next partition
        boundary (the class-ranked gate calls this; honoring it is
        cooperative and bounded by preemption.maxPerQuery)."""
        self.preemptor_class = preemptor_class
        self.preempt.set()

    def preempt_requested(self) -> bool:
        return self.preempt_enabled and self.preempt.is_set()

    def clear_preempt(self) -> None:
        self.preempt.clear()
        self.preemptor_class = None


def set_query_token(token: Optional[QueryToken]) -> None:
    """Register the active query's token for the calling thread. Helper
    threads (watchdog attempts, prefetch pool, stage pool) propagate it
    exactly like the recovery sink — thread-locals don't inherit."""
    _TL.query = token


def get_query_token() -> Optional[QueryToken]:
    return getattr(_TL, "query", None)


def check_cancelled() -> None:
    """Cancellation checkpoint: raise :class:`QueryCancelledError` when
    the calling thread's query was cancelled or deadlined. A single
    thread-local load + event test when a token is registered; a single
    attribute load when not — cheap enough for every dispatch funnel
    (:func:`fault_point` calls it first)."""
    tok = getattr(_TL, "query", None)
    if tok is not None and tok.cancel.is_set():
        raise tok.error()


def check_preempted() -> None:
    """Partition-boundary preemption checkpoint: raise
    :class:`QueryPreemptedError` when the class-ranked device gate asked
    the calling thread's query to yield. Separate from
    :func:`check_cancelled` on purpose — preemption is only honored
    where suspending is safe (between partitions, where every live
    intermediate is catalog-registered data at rest), never inside the
    deep dispatch funnels. One thread-local load + two attribute tests
    when a token is registered; a no-op whenever preemption is off
    (the gate never sets the event)."""
    tok = getattr(_TL, "query", None)
    if tok is not None and tok.preempt_enabled and tok.preempt.is_set():
        raise QueryPreemptedError(tok.query_id, tok.preemptor_class)


def current_query_id() -> Optional[int]:
    """The calling thread's query id (owner tag for catalog buffers and
    kernel-cache reservations), or None outside a managed query."""
    tok = getattr(_TL, "query", None)
    return None if tok is None else tok.query_id


class FaultSpec:
    """One parsed ``kind@site[/query=N]:arg`` entry."""

    __slots__ = ("kind", "site", "count", "probability", "fired", "query")

    def __init__(self, kind: str, site: str, count: Optional[int],
                 probability: Optional[float],
                 query: Optional[int] = None):
        self.kind = kind
        self.site = site
        self.count = count              # fire on the first N hits
        self.probability = probability  # or per-hit Bernoulli(p)
        self.query = query              # only for this query tag (None=any)
        self.fired = 0

    def __repr__(self):  # pragma: no cover - debug
        arg = self.probability if self.count is None else self.count
        q = "" if self.query is None else f"/query={self.query}"
        return f"FaultSpec({self.kind}@{self.site}{q}:{arg})"


_KINDS = ("oom", "transient", "corrupt", "lostoutput", "stall",
          "lostshard", "workerdeath", "slowput", "unavailable")


class FaultParseError(ValueError):
    pass


def parse_spec(spec: str) -> List[FaultSpec]:
    out: List[FaultSpec] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "@" not in entry:
            raise FaultParseError(
                f"bad fault entry {entry!r}: expected kind@site[:arg]")
        kind, rest = entry.split("@", 1)
        kind = kind.strip().lower()
        if kind not in _KINDS:
            raise FaultParseError(
                f"unknown fault kind {kind!r} (want one of {_KINDS})")
        if ":" in rest:
            site, arg = rest.rsplit(":", 1)
        else:
            site, arg = rest, "1"
        site = site.strip()
        query: Optional[int] = None
        if "/" in site:
            site, qpart = site.split("/", 1)
            site = site.strip()
            qpart = qpart.strip()
            if not qpart.startswith("query="):
                raise FaultParseError(
                    f"bad fault entry {entry!r}: expected /query=N")
            try:
                query = int(qpart[len("query="):])
            except ValueError:
                raise FaultParseError(
                    f"bad fault entry {entry!r}: query tag must be an int")
        if not site:
            raise FaultParseError(f"bad fault entry {entry!r}: empty site")
        arg = arg.strip()
        try:
            if "." in arg:
                p = float(arg)
                if not 0.0 < p <= 1.0:
                    raise FaultParseError(
                        f"fault probability out of (0, 1]: {entry!r}")
                out.append(FaultSpec(kind, site, None, p, query))
            else:
                n = int(arg)
                if n < 1:
                    raise FaultParseError(
                        f"fault count must be >= 1: {entry!r}")
                out.append(FaultSpec(kind, site, n, None, query))
        except ValueError as e:
            if isinstance(e, FaultParseError):
                raise
            raise FaultParseError(f"bad fault arg in {entry!r}") from e
    return out


class FaultInjector:
    """Armed schedule: per-site hit counters + deterministic PRNGs."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self.entries = parse_spec(spec)
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._rngs: Dict[str, random.Random] = {}

    def _rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            # Seeded per (seed, site): the roll sequence at a site is a
            # pure function of the schedule, never of thread timing at
            # OTHER sites.
            rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
        return rng

    def should_fire(self, site: str, kinds,
                    query: Optional[int] = None) -> Optional[FaultSpec]:
        """One hit of ``site``; returns the spec entry that fires (first
        match wins) or None. Thread-safe and deterministic for count
        faults; probability faults are deterministic given a
        deterministic hit order. ``query`` is the hitting query's fault
        tag — query-scoped entries fire only on matching hits, so chaos
        in query A is invisible to query B."""
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            for e in self.entries:
                if e.site != site or e.kind not in kinds:
                    continue
                if e.query is not None and e.query != query:
                    continue
                if e.count is not None:
                    if e.fired < e.count:
                        e.fired += 1
                        return e
                elif self._rng(site).random() < e.probability:
                    e.fired += 1
                    return e
        return None


_LOCK = threading.Lock()
_INJECTOR: Optional[FaultInjector] = None
_COUNTERS: Dict[str, float] = {}
_TL = threading.local()


def _env_injector() -> Optional[FaultInjector]:
    spec = os.environ.get("SRT_FAULTS", "").strip()
    if not spec:
        return None
    return FaultInjector(spec, int(os.environ.get("SRT_FAULTS_SEED", "0")))


with _LOCK:
    _INJECTOR = _env_injector()


def configure(spec: str, seed: int = 0) -> Optional[FaultInjector]:
    """(Re-)arm the process-global schedule; empty spec disarms. Count
    faults reset to unfired — callers arm once per query so a retried
    attempt sees the REMAINING schedule, not a fresh one."""
    global _INJECTOR
    with _LOCK:
        _INJECTOR = FaultInjector(spec, seed) if spec.strip() else None
        return _INJECTOR


def maybe_configure(conf) -> None:
    """Arm from ``spark.rapids.sql.test.faults`` when the query's conf
    sets it explicitly (the config wins over SRT_FAULTS); called once
    per query by PhysicalPlan.collect, BEFORE the attempt loop, so
    transient retries run against the remaining schedule.

    Idempotent against the ARMED schedule: a second collect() with the
    same (spec, seed) keeps the current injector — and therefore its
    consumed count-fault state — instead of re-arming a fresh one. A
    repeated collect after a fault-recovered run must not re-fire
    already-consumed faults; tests that want a fresh schedule call
    :func:`configure` directly."""
    from spark_rapids_tpu import config as C
    if C.TEST_FAULTS.key in conf.raw:
        spec = str(conf.get(C.TEST_FAULTS))
        seed = int(conf.get(C.TEST_FAULTS_SEED))
        with _LOCK:
            cur = _INJECTOR
            if cur is not None and cur.spec == spec and cur.seed == seed:
                return
        configure(spec, seed)


def injector() -> Optional[FaultInjector]:
    return _INJECTOR


def snapshot() -> Tuple[Optional[FaultInjector], Dict[str, float]]:
    """Capture the process-global fault state (armed injector + recovery
    counters) so a test harness can restore it afterwards — chaos tests
    must never bleed armed schedules or counter state into later tests
    (tests/conftest.py's autouse fixture)."""
    with _LOCK:
        return _INJECTOR, dict(_COUNTERS)


def restore(state: Tuple[Optional[FaultInjector], Dict[str, float]]) -> None:
    """Restore a :func:`snapshot` (the exact injector object, with its
    consumed-fault state, and the counter values as of the snapshot)."""
    global _INJECTOR
    inj, counters = state
    with _LOCK:
        _INJECTOR = inj
        _COUNTERS.clear()
        _COUNTERS.update(counters)


def set_recovery_sink(metrics) -> None:
    """Per-query Metrics object that mirrors the process-global recovery
    counters (set around a collect by ops/base.py)."""
    _TL.sink = metrics


def get_recovery_sink():
    """The calling thread's recovery sink (ops/base.py's watchdog hands
    it to partition worker threads — thread-locals don't inherit)."""
    return getattr(_TL, "sink", None)


def set_cancel_event(event) -> None:
    """Register the watchdog's cancel event for the calling (partition
    worker) thread: an injected ``stall`` waits on it and unwinds with
    :class:`InjectedStallError` the moment the watchdog kills the
    attempt, so the abandoned thread exits instead of lingering."""
    _TL.cancel = event


def get_cancel_event():
    """The calling thread's registered cancel event (None outside a
    watchdog attempt). Pool fan-outs that dispatch work on helper
    threads (scan reader pool, pipeline prefetchers) propagate it so a
    stall on a helper thread still unwinds when the watchdog kills the
    consuming attempt."""
    return getattr(_TL, "cancel", None)


def record(name: str, amount: float = 1) -> None:
    """Bump a recovery counter: process-global (bench.py JSON) and the
    active query's Recovery metrics (DataFrame.metrics())."""
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + amount
    sink = getattr(_TL, "sink", None)
    if sink is not None:
        sink.add(name, amount)


def counters() -> Dict[str, float]:
    with _LOCK:
        return dict(_COUNTERS)


def reset_counters() -> None:
    with _LOCK:
        _COUNTERS.clear()


# Safety net for a stall with no watchdog armed: wait at most this long
# before unwinding as DEADLINE_EXCEEDED (-> transient retry).
STALL_TIMEOUT_S = float(os.environ.get("SRT_STALL_TIMEOUT_S", "30"))


def _current_fault_tag() -> Optional[int]:
    """The calling thread's query fault tag (for kind@site/query=N
    matching), or None outside a managed query — query-scoped entries
    then never fire."""
    tok = getattr(_TL, "query", None)
    return None if tok is None else tok.fault_tag


def _stall(site: str) -> None:
    """Injected stall: hang this dispatch like a wedged device call.
    With a watchdog armed (worker thread registered a cancel event) the
    wait ends the instant the watchdog kills the attempt; a registered
    query token likewise ends it on cancel/deadline; without either, the
    bounded safety timeout expires. Either way the dispatch unwinds —
    with :class:`QueryCancelledError` on a query cancel, else
    :class:`InjectedStallError` — a stall never 'completes'."""
    cancel = getattr(_TL, "cancel", None)
    tok = getattr(_TL, "query", None)
    deadline = time.monotonic() + STALL_TIMEOUT_S
    while time.monotonic() < deadline:
        if cancel is not None and cancel.is_set():
            break
        if tok is not None:
            if tok.cancel.wait(0.02):
                raise tok.error()
        elif cancel is not None:
            cancel.wait(0.05)
        else:
            time.sleep(0.05)
    raise InjectedStallError(site)


def check_fault(site: str, kinds) -> Optional[FaultSpec]:
    """One hit of ``site`` against the armed schedule, restricted to
    ``kinds``: returns the firing entry (recording the injection
    counters) or None. The raw half of :func:`fault_point` for callers
    that must act on the fired kind themselves — the shuffle-transport
    fetch funnel uses it to delete the shard at rest before raising a
    ``lostshard``, so recovery provably rewrites data instead of
    re-reading it."""
    inj = _INJECTOR
    if inj is None:
        return None
    e = inj.should_fire(site, kinds, _current_fault_tag())
    if e is None:
        return None
    record("faultsInjected")
    record(f"faultsInjected.{e.kind}@{site}")
    # Flight-recorder instant (lazy import: this module stays
    # stdlib-only at load; monitoring is itself stdlib-only).
    from spark_rapids_tpu import monitoring
    monitoring.instant("fault-injected", "recovery",
                       args={"kind": e.kind, "site": site})
    return e


def fault_point(site: str, owner: Optional[int] = None) -> None:
    """Named injection site AND cancellation checkpoint. Checks the
    calling thread's query token first (a cancelled/deadlined query
    unwinds here with :class:`QueryCancelledError`); beyond that it is a
    no-op unless a schedule is armed — raising the synthetic error when
    an ``oom``/``transient``/``lostoutput`` entry fires, or hanging
    (then unwinding) on a ``stall``. ``owner`` tags a lostoutput with
    the owning exchange exec's id so lineage recovery can invalidate
    exactly that stage's output."""
    check_cancelled()
    e = check_fault(site, ("oom", "transient", "lostoutput", "stall"))
    if e is None:
        return
    if e.kind == "oom":
        raise InjectedOomError(site)
    if e.kind == "transient":
        raise InjectedTransientError(site)
    if e.kind == "lostoutput":
        err = InjectedLostOutputError(site)
        err.fault_owner = owner
        raise err
    _stall(site)


def corrupt_blob(site: str, blob: bytes) -> bytes:
    """Corruption site: returns ``blob`` with one byte flipped when a
    ``corrupt`` entry fires (deterministic offset from the site PRNG),
    else the blob unchanged. Used on READ paths so the underlying data
    survives — detection + one re-read recovers; real (persistent)
    corruption still fails loudly at the checksum."""
    inj = _INJECTOR
    if inj is None or not blob:
        return blob
    e = inj.should_fire(site, ("corrupt",), _current_fault_tag())
    if e is None:
        return blob
    record("faultsInjected")
    record(f"faultsInjected.corrupt@{site}")
    from spark_rapids_tpu import monitoring
    monitoring.instant("fault-injected", "recovery",
                       args={"kind": "corrupt", "site": site})
    off = inj._rng(site).randrange(len(blob))
    out = bytearray(blob)
    out[off] ^= 0xFF
    return bytes(out)
