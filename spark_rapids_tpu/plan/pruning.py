"""Column pruning: push required-column sets down to file scans.

The reference gets this for free from Spark (FileSourceScanExec's output
attributes are pruned by Catalyst before GpuOverrides sees the plan, and
GpuParquetScan reads only the requested schema — GpuParquetScan.scala:84
``readDataSchema``). Standalone, this engine owns the frontend, so the
planner runs this rewrite before tag/convert: walk the logical tree
computing which column names each subtree must produce, and replace
``FileScan`` leaves with copies whose ``source_schema`` keeps only the
required fields (file order preserved). The scan layer then asks pyarrow
for just those columns, skipping the host decode of everything else.

Only scans narrow; Project/Aggregate/Join output widths are left alone so
resolution-by-name above them is unaffected.
"""

from __future__ import annotations

from typing import Optional, Set

from spark_rapids_tpu.plan import logical as L
from spark_rapids_tpu.plan.logical import Column, LogicalPlan


def refs_of(c: Column, out: Set[str]) -> Set[str]:
    """Collect column names referenced by an untyped Column AST."""
    node = c.node
    if node[0] == "ref":
        out.add(node[1])
        return out
    for x in node[1:]:
        if isinstance(x, Column):
            refs_of(x, out)
        elif isinstance(x, tuple):
            for y in x:
                if isinstance(y, Column):
                    refs_of(y, out)
                elif isinstance(y, tuple):
                    for z in y:
                        if isinstance(z, Column):
                            refs_of(z, out)
    return out


def prune_columns(plan: LogicalPlan) -> LogicalPlan:
    """Entry point: rewrite ``plan`` with column-pruned file scans."""
    return _prune(plan, None)


# ---------------------------------------------------------------------------
# Filter pushdown: attach simple conjuncts to file scans for row-group
# stats skipping (GpuParquetScan.scala predicate pushdown / OrcFilters
# analog). The filter node itself stays in the plan — pushed predicates
# only *skip* row groups whose min/max stats prove no row can match.
# ---------------------------------------------------------------------------

_PUSH_OPS = {"eq": "eq", "lt": "lt", "le": "le", "gt": "gt", "ge": "ge"}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def _conjuncts(c: Column, out: list):
    if c.node[0] == "and":
        _conjuncts(c.node[1], out)
        _conjuncts(c.node[2], out)
    else:
        out.append(c)
    return out


def _as_predicate(c: Column):
    """(name, op, value) for a supported conjunct, else None. A
    plan-cache bind slot pushes as a ``BindValue`` marker the scan
    resolves against the EXECUTION's binding vector — row-group
    stats skipping must see this call's literal, never the one the
    template was first planned with."""
    from spark_rapids_tpu.exprs.bindslots import BindValue
    node = c.node
    kind = node[0]
    if kind == "isnotnull" and node[1].node[0] == "ref":
        return (node[1].node[1], "isnotnull", None)
    if kind in _PUSH_OPS:
        l, r = node[1], node[2]
        if l.node[0] == "ref" and r.node[0] == "lit":
            return (l.node[1], kind, r.node[1])
        if l.node[0] == "lit" and r.node[0] == "ref":
            return (r.node[1], _FLIP[kind], l.node[1])
        if l.node[0] == "ref" and r.node[0] == "bindslot":
            return (l.node[1], kind, BindValue(r.node[1]))
        if l.node[0] == "bindslot" and r.node[0] == "ref":
            return (r.node[1], _FLIP[kind], BindValue(l.node[1]))
    return None


def pushdown_filters(plan: LogicalPlan) -> LogicalPlan:
    """Entry point: copy filter conjuncts onto scans they sit above."""
    if isinstance(plan, L.LogicalFilter) and \
            isinstance(plan.child, L.FileScan):
        preds = []
        for cj in _conjuncts(plan.condition, []):
            p = _as_predicate(cj)
            if p is not None:
                preds.append(p)
        if preds:
            scan = plan.child
            new_scan = L.FileScan(scan.fmt, scan.paths, scan.source_schema,
                                  scan.options,
                                  tuple(scan.predicates) + tuple(preds))
            return L.LogicalFilter(new_scan, plan.condition)
        return plan
    rebuilt = [pushdown_filters(c) for c in plan.children]
    if all(a is b for a, b in zip(rebuilt, plan.children)):
        return plan
    return _with_children(plan, rebuilt)


def estimate_bytes(plan: LogicalPlan) -> Optional[int]:
    """Size-in-bytes estimate for join-strategy planning (the
    SizeInBytesOnlyStatsPlanVisitor analog feeding
    autoBroadcastJoinThreshold). FileScans read EXACT uncompressed sizes
    of the pruned columns from parquet footers (cached); other nodes
    propagate conservatively (filters/aggregates keep their child's
    size, matching Spark's non-CBO stats). None = unknown (never
    broadcast on unknown)."""
    if isinstance(plan, L.FileScan):
        if plan.fmt == "parquet":
            from spark_rapids_tpu.io.scan import _parquet_metadata
            names = {n for n, _ in plan.source_schema}
            total = 0
            try:
                for path in plan.paths:
                    md = _parquet_metadata(path)
                    for rg in range(md.num_row_groups):
                        g = md.row_group(rg)
                        for ci in range(g.num_columns):
                            c = g.column(ci)
                            if c.path_in_schema.split(".")[0] in names:
                                total += c.total_uncompressed_size
            except OSError:
                return None
            return total
        if plan.fmt in ("orc", "csv"):
            # ORC footers don't expose per-column uncompressed sizes the
            # way parquet row groups do; approximate from file sizes
            # (x3 for ORC's typical compression, x1 for text CSV).
            # Coarse, but enough to steer placement (plan/cost.py) the
            # same way the parquet path does.
            import os as _os
            try:
                raw = sum(_os.path.getsize(p) for p in plan.paths)
            except OSError:
                return None
            factor = 3 if plan.fmt == "orc" else 1
            return raw * factor
        return None
    if isinstance(plan, L.InMemoryScan):
        total = 0
        for part in plan.partitions:
            for hb in part:
                for c in hb.columns:
                    if c.dtype.is_string:
                        if c.str_lengths is not None:
                            total += int(c.str_lengths.sum()) + \
                                4 * c.num_rows
                        else:
                            total += sum(
                                len(b) if b is not None else 0
                                for b in c.data) + 4 * c.num_rows
                    else:
                        total += c.num_rows * max(c.dtype.itemsize, 8)
        return total
    if isinstance(plan, L.LogicalRange):
        rows = max(0, -(-(plan.end - plan.start) // plan.step)) \
            if plan.step else 0
        return 8 * rows
    if isinstance(plan, (L.LogicalFilter, L.LogicalSort, L.LogicalLimit,
                         L.LogicalRepartition, L.LogicalAggregate,
                         L.LogicalProject, L.LogicalWindow)):
        return estimate_bytes(plan.child)
    if isinstance(plan, (L.LogicalUnion, L.LogicalJoin)):
        sizes = [estimate_bytes(c) for c in plan.children]
        if any(s is None for s in sizes):
            return None
        return sum(sizes)
    return None


def _with_children(plan: LogicalPlan, kids) -> LogicalPlan:
    """Shallow-copy a logical node with new children."""
    import copy
    cp = copy.copy(plan)
    cp.children = tuple(kids)
    return cp


def _prune(plan: LogicalPlan, required: Optional[Set[str]]) -> LogicalPlan:
    # required == None means "every column of this subtree's schema".
    if isinstance(plan, L.FileScan):
        if required is None:
            return plan
        kept = tuple(f for f in plan.source_schema if f[0] in required)
        if not kept or len(kept) == len(plan.source_schema):
            return plan
        return L.FileScan(plan.fmt, plan.paths, kept, plan.options,
                          plan.predicates)
    if isinstance(plan, (L.InMemoryScan, L.LogicalRange)):
        return plan
    if isinstance(plan, L.LogicalFilter):
        child_req = None if required is None else \
            refs_of(plan.condition, set(required))
        return L.LogicalFilter(_prune(plan.child, child_req),
                               plan.condition)
    if isinstance(plan, L.LogicalProject):
        # Drop projections nothing above references (a with_column chain
        # passes every source column through; keeping them would defeat
        # scan pruning below), then require only what the kept ones read.
        projections = plan.projections
        if required is not None:
            kept = [(n, c) for n, c in projections if n in required]
            if kept:
                projections = kept
        child_req: Set[str] = set()
        for _, c in projections:
            refs_of(c, child_req)
        return L.LogicalProject(_prune(plan.child, child_req),
                                projections)
    if isinstance(plan, L.LogicalAggregate):
        child_req = set()
        for _, c in plan.group_by:
            refs_of(c, child_req)
        for _, c in plan.aggregates:
            refs_of(c, child_req)
        return L.LogicalAggregate(_prune(plan.child, child_req),
                                  plan.group_by, plan.aggregates,
                                  grouping=plan.grouping)
    if isinstance(plan, L.LogicalWindow):
        child_req = None
        if required is not None:
            child_req = set(required) - {n for n, _ in plan.exprs}
            for c in plan.window.partition_cols:
                refs_of(c, child_req)
            for o in plan.window.order_cols:
                inner = o.node[1] if o.node[0] == "sortorder" else o
                refs_of(inner, child_req)
            for _, fn_col in plan.exprs:
                node = fn_col.node
                if len(node) > 2 and isinstance(node[2], L.Column):
                    refs_of(node[2], child_req)
        return L.LogicalWindow(_prune(plan.child, child_req),
                               plan.exprs, plan.window)
    if isinstance(plan, L.LogicalGenerate):
        child_req = None
        if required is not None:
            child_req = set(required) - {plan.out_name,
                                         f"{plan.out_name}__pos"}
            for c in plan.elements:
                refs_of(c, child_req)
        return L.LogicalGenerate(_prune(plan.child, child_req),
                                 plan.out_name, plan.elements,
                                 plan.position, plan.outer)
    if isinstance(plan, L.LogicalSort):
        child_req = None
        if required is not None:
            child_req = set(required)
            for o in plan.orders:
                inner = o.node[1] if o.node[0] == "sortorder" else o
                refs_of(inner, child_req)
        return L.LogicalSort(_prune(plan.child, child_req), plan.orders)
    if isinstance(plan, L.LogicalLimit):
        return L.LogicalLimit(_prune(plan.child, required), plan.n)
    if isinstance(plan, L.LogicalRepartition):
        child_req = None
        if required is not None:
            child_req = set(required)
            for k in (plan.keys or []):
                refs_of(k, child_req)
        return L.LogicalRepartition(_prune(plan.child, child_req),
                                    plan.num_partitions, plan.keys)
    if isinstance(plan, L.LogicalUnion):
        # Union children flow positionally: pruning them independently
        # could leave siblings with mismatched schemas. Keep full width.
        return L.LogicalUnion(*[_prune(c, None)
                                for c in plan.children])
    if isinstance(plan, L.LogicalJoin):
        left, right = plan.children
        if required is None:
            lreq = rreq = None
        else:
            needed = set(required)
            for k in plan.left_keys + plan.right_keys:
                refs_of(k, needed)
            if plan.condition is not None:
                refs_of(plan.condition, needed)
            lnames = {n for n, _ in left.schema}
            rnames = {n for n, _ in right.schema}
            lreq = needed & lnames
            rreq = needed & rnames
        return L.LogicalJoin(_prune(left, lreq), _prune(right, rreq),
                             plan.left_keys, plan.right_keys,
                             plan.join_type, plan.condition,
                             plan.strategy)
    return plan
