"""Stage-fusion pass over the physical plan (planner side of
ops/fused.py — the GpuTransitionOverrides-style post-conversion rewrite).

Walks the converted Exec tree tracking which engine each region runs on
(host<->device bridges flip it) and collapses every maximal run of
contiguous fusible DEVICE operators into one :class:`FusedStageExec`.

Fusible: Project / Filter / LocalLimit / Expand whose expressions are all
jittable (no host-roundtrip islands: regexp, python-UDF fallbacks) and
need no EvalContext (no rand / spark_partition_id /
monotonically_increasing_id / input_file_name — those rely on the
per-batch context the unfused operator threads). Everything else —
exchanges, aggregates, sorts, joins, windows, generate, scans, bridges —
breaks the stage.

The pass rewires only stage boundaries: member execs keep their original
child links so the host path, fallback reports and the fusion-off plan
shape stay exactly as converted.
"""

from __future__ import annotations

from typing import List, Tuple

from spark_rapids_tpu.exprs.nondeterministic import needs_eval_context
from spark_rapids_tpu.ops.base import (
    DeviceToHostExec, Exec, HostToDeviceExec)
from spark_rapids_tpu.ops.basic import (
    ExpandExec, FilterExec, LocalLimitExec, ProjectExec)
from spark_rapids_tpu.ops.fused import FusedStageExec


def _member_exprs(op: Exec):
    if isinstance(op, ProjectExec):
        return list(op.exprs)
    if isinstance(op, FilterExec):
        return [op.condition]
    if isinstance(op, LocalLimitExec):
        return []
    if isinstance(op, ExpandExec):
        return [e for proj in op.projections for e in proj]
    return None


def fusible(op: Exec) -> bool:
    """True when ``op`` can join a fused device stage."""
    exprs = _member_exprs(op)
    if exprs is None or len(op.children) != 1:
        return False
    return all(e.jittable for e in exprs) and not needs_eval_context(exprs)


def fuse_stages(root: Exec, root_on_device: bool) -> Tuple[Exec, int]:
    """Rewrite ``root`` in place, returning (new root, stages fused)."""
    fused_count = [0]

    def rec(op: Exec, device: bool) -> Exec:
        if isinstance(op, DeviceToHostExec):
            child_device = [True]
        elif isinstance(op, HostToDeviceExec):
            child_device = [False]
        else:
            child_device = [device] * len(op.children)
        if device and fusible(op):
            run: List[Exec] = [op]          # outermost first
            while fusible(run[-1].children[0]):
                run.append(run[-1].children[0])
            if len(run) >= 2:
                below = rec(run[-1].children[0], device)
                run[-1].children = (below,)
                fused_count[0] += 1
                return FusedStageExec(list(reversed(run)), below)
        op.children = tuple(rec(c, d)
                            for c, d in zip(op.children, child_device))
        return op

    return rec(root, root_on_device), fused_count[0]


def collect_fused(root: Exec) -> List[FusedStageExec]:
    """All fused stages in the plan, outermost first (for explain)."""
    out: List[FusedStageExec] = []

    def rec(op: Exec):
        if isinstance(op, FusedStageExec):
            out.append(op)
        for c in op.children:
            rec(c)

    rec(root)
    return out
