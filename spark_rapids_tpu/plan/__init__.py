"""Plan layer: logical plans, tag->convert rewrite, transitions
(SURVEY.md §1 L6)."""

from spark_rapids_tpu.plan.logical import (     # noqa: F401
    Column, LogicalPlan, col, lit_col, resolve)
from spark_rapids_tpu.plan.planner import Planner, PhysicalPlan  # noqa: F401
